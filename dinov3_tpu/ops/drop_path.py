"""Stochastic depth, two TPU-static flavors.

The reference implements drop-path by *batch subsetting* — it computes the
residual branch on a random ``floor(B*(1-rate))``-row subset and
scatter-adds the scaled result back (dinov3_jax/layers/block.py:94-117), so
dropped samples skip the branch compute entirely. That is the semantic the
published throughput anchors were measured with: at ``drop_path_rate=0.3``
it skips ~31% of every student block's FLOPs.

On TPU the subset size must be static for XLA; it is — ``B`` and ``rate``
are trace-time constants — so ``subset_residual`` keeps the reference's
compute-skipping semantics with fully static shapes (sorted gather →
branch on [keep, ...] → scatter-add). The per-sample Bernoulli mask
(``DropPath``) is kept as the ``drop_path_mode="mask"`` fallback: same
expectation, no gather/scatter, but full branch compute.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp


def subset_keep_count(batch: int, rate: float) -> int:
    """floor(B * (1 - rate)), at least 1 (reference block.py:88-91)."""
    return max(1, int(batch * (1.0 - rate)))


def subset_residual(
    x: jnp.ndarray,
    branch: Callable[[jnp.ndarray], jnp.ndarray],
    rng: jax.Array,
    rate: float,
    groups: int = 1,
) -> jnp.ndarray:
    """x + drop-path(branch) with the reference's batch-subset semantics.

    Computes ``branch`` on a random ``keep``-row subset of ``x`` (static
    shape) and scatter-adds ``B/keep``-scaled results back, leaving the
    other rows' residuals dropped. Indices are sorted so the gather and
    scatter are monotone row selections, the cheapest form on TPU.

    ``groups > 1`` stratifies the sampling: the batch is treated as
    ``groups`` contiguous row spans and ``floor((B/groups)*(1-rate))``
    rows are drawn *within each span*. With groups = the data-shard count
    this matches the torch reference's per-rank subsetting (each FSDP
    rank permuted its local batch) and keeps every sampled index inside
    its span — equal work per shard, and the gather never has to reach
    into another span except through XLA's own partitioning choices.
    """
    B = x.shape[0]
    if groups < 1 or B % groups:
        raise ValueError(f"groups={groups} must divide batch {B}")
    Bg = B // groups
    keep_g = subset_keep_count(Bg, rate)
    if keep_g >= Bg:
        return x + branch(x).astype(x.dtype)
    if groups == 1:
        idx = jnp.sort(jax.random.permutation(rng, B)[:keep_g])
    else:
        perms = jax.vmap(
            lambda k: jax.random.permutation(k, Bg)[:keep_g]
        )(jax.random.split(rng, groups))
        offs = (jnp.arange(groups, dtype=perms.dtype) * Bg)[:, None]
        # sorted within each span; spans are in ascending offset order,
        # so the flattened index vector is globally sorted
        idx = jnp.sort(perms, axis=1).reshape(-1) + offs.reshape(-1).repeat(keep_g)
    xs = jnp.take(x, idx, axis=0, unique_indices=True,
                  indices_are_sorted=True)
    res = branch(xs) * (Bg / keep_g)
    return x.at[idx].add(res.astype(x.dtype), indices_are_sorted=True,
                         unique_indices=True, mode="promise_in_bounds")


class DropPath(nn.Module):
    """Per-sample Bernoulli residual mask (``drop_path_mode="mask"``):
    same expectation as the subset form, static shapes, but the branch is
    computed for every sample and masked after the fact."""

    rate: float = 0.0

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        if deterministic or self.rate == 0.0:
            return x
        keep = 1.0 - self.rate
        rng = self.make_rng("drop_path")
        shape = (x.shape[0],) + (1,) * (x.ndim - 1)
        mask = jax.random.bernoulli(rng, keep, shape)
        return jnp.where(mask, x / keep, jnp.zeros_like(x)).astype(x.dtype)
