"""Low-precision training arms: fp8/int8 block matmuls on the ZeRO-3
stream (ROADMAP item 3; the training-side extension of the PR-12 serving
quantization discipline).

One switch — ``train.low_precision.arm`` (bf16 | fp8 | int8), bf16
default = today's bitwise-unchanged path — quantizes exactly the
``stream_castable_path`` attn/mlp matmul KERNELS (``lowp_kernel_path``,
the same leaf rule the int8 serving engine uses) for the block matmuls:

- **Per-tensor delayed scaling.** Each castable kernel carries an amax
  history ring in the train state (``TrainState.lowp``: f32 [H] per
  kernel, [L, H] under the block scan); the step's weight scale is
  ``scale_margin * max(history) / qmax`` — one step behind the masters,
  so the scale is a compile-time-free constant of the forward and no
  amax sync sits on the critical matmul path (the FP8-LM / Transformer
  Engine recipe). Histories advance AFTER the optimizer update from the
  new masters under the ``lowp_amax`` named scope (the amax over a
  zero3-sharded master is a tiny all-reduce-max the census attributes).
  Activations use current per-tensor scaling (one amax per tensor,
  stop-gradient), matching ``fp8_dot_general``'s convention.
- **The cast rides the bf16-before-gather hook.** Under the zero3
  stream (``ops/block.py _zero3_stream_trans_in``) the castable KERNEL
  leaves skip the bf16 gather; ``lowp_matmul`` quantizes the sharded
  bf16 view shard-locally and gathers the 1-byte codes under the SAME
  ``zero3_stream`` named scope — identical collective counts, ~2x fewer
  streamed bytes (COST_LP_r21.json). Biases/norms/gammas keep the plain
  bf16/f32 stream; masters, Adam moments, and the EMA teacher's
  STORAGE are untouched (the teacher's forward runs the same quantized
  matmuls — its fp32 EMA state never sees a quantizer).
- **Real quantized dots.** ``jax.lax.dot_general`` on the quantized
  operands with ``preferred_element_type`` (int32 accum for int8, f32
  for fp8), dequantized by ``s_x * s_w`` in a ``lowp_dequant`` named
  scope the PR-13 anatomy ledger attributes. The backward is a
  module-level ``jax.custom_vjp`` (the ``_softmax_lowp`` idiom —
  defined ONCE, config static, or flax re-wraps per call and nn.scan
  trips the tracer leak): straight-through wrt the quantization, dx
  from the RE-GATHERED dequantized codes (the backward never gathers
  fp32/bf16 masters — the FSDP gather-twice discipline at 1-byte
  rates), full dw back to the masters.

Scales reach the modules as a read-only ``"lowp"`` flax variable
collection mirroring the module tree (``module.apply({"params": p,
"lowp": scales}, ...)``), sliced per layer by ``nn.scan`` via
``variable_axes={"lowp": 0}``; a module only engages its lowp path when
``lowp_arm != "bf16"`` AND the scale variable exists, so init, eval,
and the gram teacher (never handed a collection) stay on the bf16 path
with zero signature changes.

CPU-harness honesty (docs/PERFORMANCE.md): XLA:CPU emulates the fp8/int8
dots by upconversion, so the CPU tier pins numerics and the streamed
collective-bytes census; the speed claim is banked by the phQ on-chip
A/B (scripts/r6_queue.sh).
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import jax.tree_util as jtu

LOWP_ARMS = ("bf16", "fp8", "int8")


class QSpec(NamedTuple):
    """One quantized arm: storage dtype, symmetric max code, accumulator
    dtype for ``preferred_element_type``."""

    qdtype: Any
    qmax: float
    acc_dtype: Any


_QSPECS = {
    # float8_e4m3 finite max (ops/common.py _F8_MAX); fp8 dots accumulate f32
    "fp8": QSpec(jnp.float8_e4m3fn, 448.0, jnp.float32),
    # symmetric int8 ([-127, 127], -128 unused — serve/quant.py convention);
    # int8 dots accumulate exactly in int32
    "int8": QSpec(jnp.int8, 127.0, jnp.int32),
}


def qspec(arm: str) -> QSpec:
    if arm not in _QSPECS:
        raise ValueError(
            f"unknown low-precision arm {arm!r}; expected one of {LOWP_ARMS}"
        )
    return _QSPECS[arm]


# ---------------------------------------------------------------------
# scale math — ONE implementation shared with the int8 serving engine
# (serve/quant.py quantize_leaf delegates here with xp=numpy, so the
# training and serving quantizers can never drift apart numerically)
# ---------------------------------------------------------------------

def symmetric_scale(amax, qmax, xp=jnp):
    """``amax / qmax`` with zero-amax channels pinned to scale 1.0 (the
    divide stays exact and dequant returns exact zeros — serve/quant.py
    convention). Works on numpy (host serving quantizer) and jnp
    (traced training quantizer) alike."""
    return xp.where(
        amax > 0, amax / xp.float32(qmax), xp.float32(1.0)
    ).astype(xp.float32)


def symmetric_quantize(w, scale, qmax, qdtype, xp=jnp):
    """Symmetric quantization of ``w`` by a precomputed ``scale``:
    integer arms round half-to-even (``rint``, the serving convention)
    and clip to [-qmax, qmax]; float arms (fp8) clip to the finite range
    and let the dtype cast do the rounding."""
    w32 = w.astype(xp.float32) / scale
    if xp.issubdtype(xp.dtype(qdtype), xp.integer):
        w32 = xp.rint(w32)
    return xp.clip(w32, -qmax, qmax).astype(qdtype)


def scale_from_history(hist, qmax: float, margin: float):
    """Delayed-scaling weight scale from one amax history ring:
    ``margin * max(history) / qmax`` over the ring axis (last), zero-safe
    (an all-zero history — a dead kernel — scales by 1.0)."""
    amax = jnp.max(hist.astype(jnp.float32), axis=-1)
    return symmetric_scale(jnp.float32(margin) * amax, qmax)


def current_scale(x, qmax: float):
    """Current (per-tensor, stop-gradient) activation scale — the
    ``fp8_dot_general`` convention (ops/common.py): amax floored at
    1e-12 so a zero tensor quantizes to zeros with a finite scale."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)))
    return jax.lax.stop_gradient(
        jnp.maximum(amax, 1e-12) / jnp.float32(qmax))


# ---------------------------------------------------------------------
# the quantized-kernel leaf rule (shared with serve/quant.py)
# ---------------------------------------------------------------------

def lowp_kernel_path(path) -> bool:
    """Whether the param leaf at ``path`` runs the low-precision matmul:
    an attn/mlp matmul KERNEL by the stream-castable rule (ops/block.py
    ``stream_castable_path``) narrowed to ``*kernel`` leaves — exactly
    the set the int8 serving engine quantizes (serve/quant.py
    ``quantizable_path`` delegates here). Biases stay on the bf16
    stream; norm scales, layerscale gammas, and the MoE router were
    never castable at all."""
    from dinov3_tpu.ops.block import stream_castable_path

    if not path or not stream_castable_path(path):
        return False
    last = str(getattr(path[-1], "key", getattr(path[-1], "idx", path[-1])))
    return "kernel" in last


def _path_keys(path) -> list[str]:
    return [str(getattr(k, "key", getattr(k, "idx", k))) for k in path]


def lowp_scale_site(path) -> tuple[tuple[str, ...], str]:
    """Where a kernel's scale lives in the ``"lowp"`` collection: flax
    ``nn.Dense`` kernels (params path ``(..., "fc1", "kernel")``) fold
    into their parent module as ``fc1_kernel`` — the Dense submodule
    cannot read sibling collections, so the owning FFN module reads the
    scale and passes a closure; attention kernels (``qkv_kernel`` /
    ``proj_kernel``) are direct params of the attn module and keep
    their name in place."""
    keys = _path_keys(path)
    if keys[-1] == "kernel":
        return tuple(keys[:-2]), f"{keys[-2]}_kernel"
    return tuple(keys[:-1]), keys[-1]


# ---------------------------------------------------------------------
# delayed-scaling state: amax history rings in TrainState.lowp
# ---------------------------------------------------------------------

def lowp_amax_tree(backbone_params) -> dict:
    """Per-kernel amax of a backbone param tree, placed at each
    kernel's ``lowp_scale_site`` — the collection-shaped tree every
    history/scale helper below maps over. Scanned stacks (any exact
    ``blocks`` path component — ``blocks_i`` is the unrolled arm)
    reduce over the non-layer axes to [L]; unrolled kernels reduce to a
    scalar. The amax of a zero3-SHARDED master is a cross-shard max
    (one tiny all-reduce, ``lowp_amax`` scope at the call sites)."""
    out: dict = {}
    for path, leaf in jtu.tree_flatten_with_path(backbone_params)[0]:
        if not hasattr(leaf, "dtype") or not lowp_kernel_path(path):
            continue
        keys = _path_keys(path)
        axes = tuple(range(1, leaf.ndim)) if "blocks" in keys else None
        amax = jnp.max(jnp.abs(leaf.astype(jnp.float32)), axis=axes)
        parent, name = lowp_scale_site(path)
        node = out
        for k in parent:
            node = node.setdefault(k, {})
        node[name] = amax
    return out


def lowp_history_init(backbone_params, history_len: int) -> dict:
    """Fresh amax history rings, every slot filled with the CURRENT
    masters' amax (not zeros: a zero history would scale the first
    ``history_len`` steps by 1.0 — wildly wrong for ~0.02-std kernels
    — and delayed scaling would start from a divergence)."""
    amax = lowp_amax_tree(backbone_params)
    return jax.tree.map(
        lambda a: jnp.broadcast_to(
            a[..., None], a.shape + (int(history_len),)
        ).astype(jnp.float32),
        amax,
    )


def lowp_history_step(hist_tree, backbone_params):
    """Advance every history ring one step: drop the oldest amax, append
    the NEW masters' (post-update) amax. Runs after the optimizer /
    EMA update under the ``lowp_amax`` named scope (train/fused_update
    ``lowp_state_step``) so next step's scales see this step's
    weights."""
    with jax.named_scope("lowp_amax"):
        new = lowp_amax_tree(backbone_params)
        return jax.tree.map(
            lambda h, a: jnp.concatenate(
                [h[..., 1:], a[..., None].astype(jnp.float32)], axis=-1),
            hist_tree, new,
        )


def lowp_scales(hist_tree, arm: str, margin: float):
    """History rings -> the ``"lowp"`` variable collection of per-kernel
    delayed scales ([L] per scanned kernel, scalar unrolled)."""
    spec = qspec(arm)
    return jax.tree.map(
        lambda h: scale_from_history(h, spec.qmax, margin), hist_tree)


# ---------------------------------------------------------------------
# the quantized matmul (module-level custom_vjp; arm static)
# ---------------------------------------------------------------------

def _gather_codes(q, like=None):
    """Materialize (replicate) quantized codes for the dot under the
    ``zero3_stream`` scope — the SAME scope (and so the same census
    attribution and identical collective count) as the bf16 stream this
    replaces, at 1-byte rates. ``like`` pins the codes to the sharded
    master's placement first (the shard_alike discipline of
    ``_zero3_stream_trans_in``: without it the replicated constraint
    back-propagates through the elementwise quantizer and the
    partitioner gathers the WIDE operand). No-op without a mesh."""
    from dinov3_tpu.parallel.context import get_current_mesh
    from dinov3_tpu.parallel.sharding import constrain_replicated

    mesh = get_current_mesh()
    with jax.named_scope("zero3_stream"):
        if mesh is not None and like is not None:
            from jax.experimental.shard_alike import shard_alike

            q, _ = shard_alike(q, like)
        return constrain_replicated(q, mesh) if mesh is not None else q


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def lowp_matmul(arm: str, x, w, scale):
    """``x @ w`` through the quantized arm: w by its delayed per-tensor
    ``scale`` (quantized SHARD-LOCAL, codes gathered under
    ``zero3_stream``), x by current scaling, ``lax.dot_general`` on the
    codes with the arm's accumulator ``preferred_element_type``, dequant
    epilogue under ``lowp_dequant``. x: [..., K] (compute dtype),
    w: [K, N] (the bf16 stream view of the master), scale: f32 scalar."""
    out, _ = _lowp_matmul_fwd(arm, x, w, scale)
    return out


def _lowp_matmul_fwd(arm, x, w, scale):
    spec = qspec(arm)
    scale = jax.lax.stop_gradient(scale.astype(jnp.float32))
    q_w = symmetric_quantize(w, scale, spec.qmax, spec.qdtype)
    q_w_rep = _gather_codes(q_w, like=w)
    with jax.named_scope("lowp_amax"):
        s_x = current_scale(x, spec.qmax)
    q_x = symmetric_quantize(x, s_x, spec.qmax, spec.qdtype)
    acc = jax.lax.dot_general(
        q_x, q_w_rep, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=spec.acc_dtype,
    )
    with jax.named_scope("lowp_dequant"):
        out = (acc.astype(jnp.float32) * (s_x * scale)).astype(x.dtype)
    return out, (q_x, s_x, q_w, scale)


def _lowp_matmul_bwd(arm, res, g):
    """Straight-through backward on the DEQUANTIZED codes: dx re-gathers
    the saved 1-byte weight codes (never the wide masters) under the
    same ``zero3_stream`` scope; dw contracts the quantized-activation
    view with the cotangent — the STE wrt both quantizers (scales carry
    stop_gradient, zero cotangent)."""
    q_x, s_x, q_w, scale = res
    q_w_rep = _gather_codes(q_w)
    w_hat = (q_w_rep.astype(jnp.float32) * scale).astype(g.dtype)
    x_hat = (q_x.astype(jnp.float32) * s_x).astype(g.dtype)
    dx = jax.lax.dot_general(
        g, w_hat, (((g.ndim - 1,), (1,)), ((), ())))
    batch = tuple(range(g.ndim - 1))
    dw = jax.lax.dot_general(x_hat, g, ((batch, batch), ((), ())))
    return dx, dw, jnp.zeros_like(scale)


lowp_matmul.defvjp(_lowp_matmul_fwd, _lowp_matmul_bwd)


def make_lowp_dot_general(scale, arm: str):
    """Drop-in ``dot_general`` for ``nn.Dense`` routing through
    ``lowp_matmul`` (the ``_dense_kwargs`` hook, ops/ffn.py). Dense
    always contracts its input's last dim with kernel dim 0 — anything
    else is a wiring bug this raises on."""

    def dg(lhs, rhs, dimension_numbers, precision=None,
           preferred_element_type=None):
        expected = (((lhs.ndim - 1,), (0,)), ((), ()))
        if dimension_numbers != expected:
            raise NotImplementedError(
                f"lowp dot_general only supports the Dense contraction "
                f"{expected}, got {dimension_numbers}")
        return lowp_matmul(arm, lhs, rhs, scale)

    return dg


# ---------------------------------------------------------------------
# drift probe (warn_lowp_divergence, configs/config.py)
# ---------------------------------------------------------------------

def lowp_drift_probe(backbone_params, hist_tree, arm: str, margin: float,
                     seed: int = 0) -> dict:
    """Device-side per-kernel drift of the lowp matmul vs its bf16
    shadow on a SAMPLED layer (layer 0 of each scanned stack; every
    unrolled ``blocks_0`` kernel): relative Frobenius error of
    ``lowp_matmul(x, w)`` against ``x @ w`` in bf16 on a fixed normal
    probe batch. Returns ``{"<site>": drift}`` plus ``"max"`` — the
    number ``warn_lowp_divergence`` gates on at setup build and bench
    embeds per record."""
    scales = lowp_scales(hist_tree, arm, margin)
    drifts: dict = {}
    for path, leaf in jtu.tree_flatten_with_path(backbone_params)[0]:
        if not hasattr(leaf, "dtype") or not lowp_kernel_path(path):
            continue
        keys = _path_keys(path)
        if any(k.startswith("blocks_") and k != "blocks_0" for k in keys):
            continue  # sampled layer: the unrolled arm probes block 0
        parent, name = lowp_scale_site(path)
        node = scales
        for k in parent:
            node = node[k]
        s = node[name]
        w = leaf
        if "blocks" in keys:  # scanned [L, K, N]: probe layer 0
            w, s = w[0], s[0]
        w = w.astype(jnp.bfloat16)
        x = jax.random.normal(
            jax.random.key(seed), (8, w.shape[0]), jnp.bfloat16)
        ref = (x @ w).astype(jnp.float32)
        got = lowp_matmul(arm, x, w, s).astype(jnp.float32)
        denom = jnp.maximum(jnp.linalg.norm(ref), 1e-12)
        site = "/".join(parent + (name,))
        drifts[site] = float(jnp.linalg.norm(got - ref) / denom)
    drifts["max"] = max(
        [v for k, v in drifts.items() if k != "max"], default=0.0)
    return drifts
