"""2-D rotary position embeddings for ViT patch grids, as pure functions.

Math parity with the reference module (dinov3_jax/layers/rope_position_encoding.py):
- period spectrum from ``base ** (2j / (D_head/2))`` for j in [0, D_head/4)
  or geometric between ``min_period`` and ``max_period``;
- patch-center coordinates normalized to [-1, 1] per the ``min|max|separate``
  mode (the reference's "min" mode used max(H, W) — a bug we fix, SURVEY.md
  §2.9.8);
- optional train-time coordinate augmentation: global shift, per-axis
  log-uniform jitter, isotropic log-uniform rescale;
- output ``(sin, cos)`` of shape [H*W, D_head] consumed by ``rope_apply``
  with rotate-half pairing.

Pure functions (not a Flax module): the tables depend only on static config
+ (H, W) + an rng, so the ViT computes one table per crop resolution per
step and passes it to all blocks — no per-block recompute as in the
reference (dinov3_jax/models/vision_transformer.py:212-217).
"""

from __future__ import annotations

import math
from typing import Literal

import jax
import jax.numpy as jnp


def rope_periods(
    head_dim: int,
    base: float | None = 100.0,
    min_period: float | None = None,
    max_period: float | None = None,
    dtype=jnp.float32,
) -> jnp.ndarray:
    """[head_dim // 4] period spectrum."""
    if head_dim % 4 != 0:
        raise ValueError(f"head_dim must be divisible by 4, got {head_dim}")
    both = min_period is not None and max_period is not None
    if (base is None) == (not both):
        raise ValueError("provide either `base` or `min_period`+`max_period`")
    n = head_dim // 4
    if base is not None:
        return jnp.asarray(base, dtype) ** (
            2.0 * jnp.arange(n, dtype=dtype) / (head_dim / 2.0)
        )
    ratio = max_period / min_period
    exponents = jnp.linspace(0.0, 1.0, n, dtype=dtype)
    return (ratio**exponents) * (max_period / ratio)


def patch_coords(
    H: int,
    W: int,
    normalize: Literal["min", "max", "separate"] = "separate",
    dtype=jnp.float32,
) -> jnp.ndarray:
    """[H*W, 2] patch-center coordinates in [-1, 1] (row-major, ij order)."""
    if normalize == "max":
        denom_h = denom_w = max(H, W)
    elif normalize == "min":
        denom_h = denom_w = min(H, W)
    elif normalize == "separate":
        denom_h, denom_w = H, W
    else:
        raise ValueError(f"unknown normalize mode {normalize!r}")
    ch = (jnp.arange(H, dtype=dtype) + 0.5) / denom_h
    cw = (jnp.arange(W, dtype=dtype) + 0.5) / denom_w
    coords = jnp.stack(jnp.meshgrid(ch, cw, indexing="ij"), axis=-1).reshape(-1, 2)
    return 2.0 * coords - 1.0


def augment_coords(
    coords: jnp.ndarray,
    rng: jax.Array,
    shift: float | None = None,
    jitter: float | None = None,
    rescale: float | None = None,
) -> jnp.ndarray:
    """Train-time coordinate augmentation (jittable; factors of 1 when off)."""
    rng_shift, rng_jitter, rng_rescale = jax.random.split(rng, 3)
    d = coords.dtype
    if shift is not None:
        coords = coords + jax.random.uniform(
            rng_shift, (2,), minval=-shift, maxval=shift, dtype=d
        )
    if jitter is not None:
        j = math.log(jitter)
        coords = coords * jnp.exp(
            jax.random.uniform(rng_jitter, (2,), minval=-j, maxval=j, dtype=d)
        )
    if rescale is not None:
        r = math.log(rescale)
        coords = coords * jnp.exp(
            jax.random.uniform(rng_rescale, (1,), minval=-r, maxval=r, dtype=d)
        )
    return coords


def rope_aug_values(
    u: jnp.ndarray,
    shift: float | None = None,
    jitter: float | None = None,
    rescale: float | None = None,
) -> dict:
    """[5] uniforms in [0, 1) -> the concrete augmentation factors.

    Same marginal distributions as ``augment_coords``'s three separate
    draws (shift ~ U[-s, s] per axis; jitter/rescale ~ log-uniform over
    [1/j, j]), derived from ONE fused uniform draw so the step-wide RNG
    plan (rng/plan.py) spends a single threefry op per forward pass on
    coordinate augmentation instead of a split + three draws.
    """
    out = {}
    if shift is not None:
        out["shift"] = (2.0 * u[0:2] - 1.0) * shift
    if jitter is not None:
        out["jitter"] = jnp.exp((2.0 * u[2:4] - 1.0) * math.log(jitter))
    if rescale is not None:
        out["rescale"] = jnp.exp((2.0 * u[4:5] - 1.0) * math.log(rescale))
    return out


def augment_coords_planned(coords: jnp.ndarray, aug: dict) -> jnp.ndarray:
    """Apply precomputed augmentation factors (``rope_aug_values``)."""
    d = coords.dtype
    if "shift" in aug:
        coords = coords + aug["shift"].astype(d)
    if "jitter" in aug:
        coords = coords * aug["jitter"].astype(d)
    if "rescale" in aug:
        coords = coords * aug["rescale"].astype(d)
    return coords


def rope_sincos(
    H: int,
    W: int,
    periods: jnp.ndarray,
    normalize: Literal["min", "max", "separate"] = "separate",
    rng: jax.Array | None = None,
    shift: float | None = None,
    jitter: float | None = None,
    rescale: float | None = None,
    dtype=jnp.float32,
    aug: dict | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(sin, cos), each [H*W, 4*len(periods)] == [H*W, head_dim].

    Coordinate augmentation comes from EITHER ``rng`` (legacy in-place
    draws) OR ``aug`` (precomputed factors from the step-wide RNG plan);
    passing both is a wiring error.
    """
    if rng is not None and aug is not None:
        raise ValueError("pass either rng or aug (plan), not both")
    coords = patch_coords(H, W, normalize, dtype=jnp.float32)
    if aug is not None:
        coords = augment_coords_planned(coords, aug)
    elif rng is not None and (shift or jitter or rescale):
        coords = augment_coords(coords, rng, shift, jitter, rescale)
    # [HW, 2, 1] / [P] -> [HW, 2, P] -> [HW, 2P] -> duplicated rotate-half halves
    angles = 2.0 * math.pi * coords[:, :, None] / periods[None, None, :]
    angles = angles.reshape(angles.shape[0], -1)
    angles = jnp.concatenate([angles, angles], axis=-1)
    return jnp.sin(angles).astype(dtype), jnp.cos(angles).astype(dtype)


def rope_rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def rope_apply(x: jnp.ndarray, sin: jnp.ndarray, cos: jnp.ndarray) -> jnp.ndarray:
    """Rotate the trailing head_dim of x ([..., N, head_dim]) by the table."""
    return x * cos + rope_rotate_half(x) * sin


def rope_with_identity_prefix(
    sin: jnp.ndarray, cos: jnp.ndarray, n_prefix: int
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Prepend identity rotations (sin=0, cos=1) for prefix tokens.

    Lets the per-block apply be one full-sequence fma with no token-axis
    slice/concat: CLS + storage tokens rotate by the identity instead of
    being carved out and re-concatenated in every block (the fusion-breaking
    pattern the reference had, dinov3_jax/layers/attention.py:77-87)."""
    if n_prefix == 0:
        return sin, cos
    pad_sin = jnp.zeros((n_prefix, sin.shape[-1]), sin.dtype)
    pad_cos = jnp.ones((n_prefix, cos.shape[-1]), cos.dtype)
    return (jnp.concatenate([pad_sin, sin], axis=0),
            jnp.concatenate([pad_cos, cos], axis=0))


def rope_apply_full(
    q: jnp.ndarray,
    k: jnp.ndarray,
    sin: jnp.ndarray,
    cos: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Rotate q/k ([B, N, heads, head_dim]) by a full-length table
    ([N, head_dim] shared by every row, or [B, N, head_dim] per-row —
    the crop-packed batch, where global and packed rows carry different
    coordinate grids; identity rows for prefix/pad tokens either way).

    Half-pair formulation (out1 = x1*c - x2*s; out2 = x2*c + x1*s) — the
    same math as ``rope_apply``'s rotate-half but with no negation pass,
    computed in the table's dtype (fp32 tables upcast q/k transiently;
    bf16 tables keep the whole chain in bf16)."""
    compute = jnp.promote_types(q.dtype, sin.dtype)
    half = sin.shape[-1] // 2
    # tables duplicate their halves ([ang, ang]); one half suffices
    if sin.ndim == 3:
        s = sin[:, :, None, :half].astype(compute)
        c = cos[:, :, None, :half].astype(compute)
    else:
        s = sin[None, :, None, :half].astype(compute)
        c = cos[None, :, None, :half].astype(compute)

    def rot(t):
        x = t.astype(compute)
        x1, x2 = x[..., :half], x[..., half:]
        out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
        return out.astype(t.dtype)

    return rot(q), rot(k)


def rope_packed_rows(
    global_table: tuple[jnp.ndarray, jnp.ndarray],
    local_table: tuple[jnp.ndarray, jnp.ndarray],
    layout,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-row RoPE tables for a crop-packed batch: ([R, N_g, d], x2).

    ``global_table``/``local_table`` are full-length (sin, cos) tables
    with their identity prefix rows already prepended
    (``rope_with_identity_prefix``), [N_g, d] and [N_l, d]. The packed
    rows tile the LOCAL table k times — each packed segment keeps its
    own local patch grid (its own CLS identity row included) — and pad
    the row tail with identity rotations; pad rotations are irrelevant
    (pad tokens are segment-masked) but identity keeps them inert.
    ``layout``: ops/packing.PackedLayout; row order follows its
    shard-grouped convention (packing.assemble_packed_batch).
    """
    sin_g, cos_g = global_table
    sin_l, cos_l = local_table
    d = sin_g.shape[-1]
    pad = layout.pad_tokens_per_row
    sin_p = jnp.concatenate(
        [jnp.tile(sin_l, (layout.k, 1)),
         jnp.zeros((pad, d), sin_l.dtype)], axis=0)
    cos_p = jnp.concatenate(
        [jnp.tile(cos_l, (layout.k, 1)),
         jnp.ones((pad, d), cos_l.dtype)], axis=0)
    g, R = layout.groups, layout.rows_total
    rows_g = jnp.broadcast_to(
        sin_g[None], (layout.n_global_rows,) + sin_g.shape)
    rows_gc = jnp.broadcast_to(
        cos_g[None], (layout.n_global_rows,) + cos_g.shape)
    rows_p = jnp.broadcast_to(
        sin_p[None], (layout.n_packed_rows,) + sin_p.shape)
    rows_pc = jnp.broadcast_to(
        cos_p[None], (layout.n_packed_rows,) + cos_p.shape)
    if g <= 1:
        return (jnp.concatenate([rows_g, rows_p], axis=0),
                jnp.concatenate([rows_gc, rows_pc], axis=0))
    gb = layout.n_global_rows // g
    pb = layout.n_packed_rows // g
    tail = sin_g.shape

    def grouped(a, b):
        mixed = jnp.concatenate(
            [a.reshape((g, gb) + tail), b.reshape((g, pb) + tail)], axis=1)
        return mixed.reshape((R,) + tail)

    return grouped(rows_g, rows_p), grouped(rows_gc, rows_pc)


def rope_apply_with_prefix(
    q: jnp.ndarray,
    k: jnp.ndarray,
    sin: jnp.ndarray,
    cos: jnp.ndarray,
    dtype=None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Apply RoPE to the trailing-patch part of q/k, skipping prefix tokens.

    q, k: [B, N, heads, head_dim]; sin/cos: [P, head_dim] with P <= N.
    The first N - P tokens (CLS + storage/register tokens) pass through
    unrotated (reference: dinov3_jax/layers/attention.py:77-87).
    """
    n_prefix = q.shape[-3] - sin.shape[-2]
    if n_prefix < 0:
        raise ValueError(
            f"rope table covers {sin.shape[-2]} tokens but sequence has {q.shape[-3]}"
        )
    compute = dtype or q.dtype
    sin = sin[:, None, :].astype(compute)  # [P, 1, head_dim] broadcasting over heads
    cos = cos[:, None, :].astype(compute)

    def rot(t):
        patch = rope_apply(t[..., n_prefix:, :, :].astype(compute), sin, cos)
        return jnp.concatenate(
            [t[..., :n_prefix, :, :], patch.astype(t.dtype)], axis=-3
        )

    return rot(q), rot(k)
