"""DINO/iBOT projection head.

n-layer GELU MLP -> bottleneck -> L2-normalize -> prototype Dense (no bias),
optionally weight-normalized (reference: dinov3_jax/layers/dino_head.py;
weight-norm semantics from Meta's DINOv3 ``weight_norm(last_layer)`` with
unit-norm rows when ``norm_last_layer``).

The prototype matrix is [bottleneck, K] with K up to 262144
(dinov3_vit7b16 recipes) — it is annotated with the "vocab" logical axis so
the tensor axis shards the prototypes; softmax/sinkhorn downstream handle
sharded logits as plain global-array math under GSPMD (SURVEY.md §7.3).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from dinov3_tpu.ops.common import l2_normalize, part, trunc_normal_init


class DINOHead(nn.Module):
    out_dim: int
    hidden_dim: int = 2048
    bottleneck_dim: int = 256
    nlayers: int = 3
    mlp_bias: bool = True
    norm_last_layer: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    reduce_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, skip_last_layer: bool = False,
                 only_last_layer: bool = False) -> jnp.ndarray:
        dense = lambda feats, name, names: nn.Dense(  # noqa: E731
            feats, use_bias=self.mlp_bias, dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=part(trunc_normal_init(), names),
            bias_init=part(nn.initializers.zeros, (names[-1],)),
            name=name,
        )
        if not only_last_layer:
            n = max(1, self.nlayers)
            if n == 1:
                x = dense(self.bottleneck_dim, "mlp_0", ("embed", "mlp"))(x)
            else:
                x = dense(self.hidden_dim, "mlp_0", ("embed", "mlp"))(x)
                x = nn.gelu(x)
                # middle layers are row-parallel (input dim carries the
                # tensor shard; flax forbids a logical name twice per param)
                for i in range(1, n - 1):
                    x = dense(self.hidden_dim, f"mlp_{i}", ("mlp", None))(x)
                    x = nn.gelu(x)
                x = dense(self.bottleneck_dim, f"mlp_{n-1}", ("mlp", None))(x)
            # L2 normalize in fp32 (reference dino_head.py:80-82), with the
            # zero-safe gradient form (ops/common.py l2_normalize)
            x = l2_normalize(x.astype(self.reduce_dtype)).astype(self.dtype)
        if skip_last_layer:
            return x
        prototypes = self.param(
            "prototypes", part(trunc_normal_init(), (None, "vocab")),
            (self.bottleneck_dim, self.out_dim), self.param_dtype,
        )
        w = prototypes.astype(self.reduce_dtype)
        if self.norm_last_layer:
            w = l2_normalize(w, axis=0)
        return (x.astype(self.reduce_dtype) @ w).astype(self.reduce_dtype)
