"""Crop packing: k local-crop token sequences per global-length row.

The two-pass student forward runs the backbone once on [2B, N_g] global
rows and once on [n_l*B, N_l] local rows — the ViT-L weight stack
streams from HBM twice per forward (and twice again per backward), and
the 37-token local rows tile terribly on the 128-lane axis (the same
padding-cliff class as the B=10 sublane guardrail,
configs/config.py sublane_padding_waste). GSPMD (arXiv:2105.04663)
quantifies the general point: once the matmuls sit at the roofline,
padding waste and per-op overhead are what remain.

This module holds the pure layout math and token assembly for the
crop-packed single-pass engine (``model.crop_packing``,
train/ssl_meta_arch.py): pack ``k = N_g // N_l`` local sequences into
each global-length row, concatenate with the global rows, and run ONE
backbone apply — one block scan, ~44 well-tiled rows instead of 120 at
ViT-L B=12 — under segment-masked (block-diagonal) attention so packed
crops never attend across segments (ops/attention.py seg argument,
ops/flash_attention.py seg kernels) and per-segment RoPE tables
(ops/rope.py rope_packed_rows).

Row order is *data-shard grouped* when a mesh with a >1-way data axis
is current (``groups`` below): the packed batch is laid out as
[shard0's globals, shard0's packed rows, shard1's globals, ...], so the
even GSPMD sharding of the concatenated row axis coincides with a
shard-local concatenation — no cross-shard row movement at the pack
boundary (parallel/sharding.py ``constrain_packed_rows`` pins the
axis). With ``groups=1`` (no mesh, CPU tests) the order degenerates to
the plain [globals..., packed...] concatenation.

Pad tokens (the row tail beyond ``k*N_l`` and the missing segments of
the ragged last row) carry segment id -1: they attend only among
themselves (never an empty softmax row, so no NaN can leak into the
backward) and no valid token attends to them; their outputs are
dropped at extraction.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class PackedLayout:
    """Static shape plan for one crop-packed student batch."""

    n_global_rows: int   # 2B global-crop rows
    n_local: int         # n_l * B local-crop sequences
    seq_global: int      # N_g = n_prefix + T_g
    seq_local: int       # N_l = n_prefix + T_l
    n_prefix: int        # 1 + n_storage_tokens (CLS + registers)
    groups: int = 1      # data-shard row grouping (see module doc)

    @property
    def k(self) -> int:
        """Local sequences packed per global-length row."""
        return self.seq_global // self.seq_local

    @property
    def n_packed_rows(self) -> int:
        """P = ceil(n_local / k)."""
        return -(-self.n_local // self.k)

    @property
    def rows_total(self) -> int:
        return self.n_global_rows + self.n_packed_rows

    @property
    def pad_segments(self) -> int:
        """Empty segment slots in the ragged last packed row."""
        return self.n_packed_rows * self.k - self.n_local

    @property
    def pad_tokens_per_row(self) -> int:
        """Row-tail tokens beyond the k packed segments."""
        return self.seq_global - self.k * self.seq_local

    @property
    def pad_waste(self) -> float:
        """Fraction of packed-row tokens that are padding (tail pads +
        the ragged row's empty segments)."""
        computed = self.n_packed_rows * self.seq_global
        useful = self.n_local * self.seq_local
        return (computed - useful) / computed


def make_packed_layout(n_global_rows: int, n_local: int, seq_global: int,
                       seq_local: int, n_prefix: int,
                       groups: int = 1) -> PackedLayout:
    if seq_local > seq_global:
        raise ValueError(
            f"local sequence ({seq_local}) longer than global "
            f"({seq_global}); nothing to pack")
    layout = PackedLayout(
        n_global_rows=n_global_rows, n_local=n_local,
        seq_global=seq_global, seq_local=seq_local, n_prefix=n_prefix,
        groups=max(1, int(groups)),
    )
    if layout.groups > 1 and (
            n_global_rows % layout.groups or
            layout.n_packed_rows % layout.groups):
        # indivisible row counts: fall back to the ungrouped order (the
        # sharding constraint then no-ops; GSPMD still partitions what
        # it can)
        layout = dataclasses.replace(layout, groups=1)
    return layout


def seq_len_from_crop(crop_size, patch_size: int, n_prefix: int) -> int:
    s = crop_size
    if isinstance(s, (list, tuple)):
        s = int(s[0])
    return n_prefix + (int(s) // int(patch_size)) ** 2


def layout_from_cfg(cfg, per_chip_batch: int,
                    groups: int = 1) -> PackedLayout | None:
    """Config-level layout (the guardrail / cost-script view), or None
    when the config has no packable ViT crop geometry (convnext)."""
    s = cfg.student
    if str(s.arch).startswith("convnext"):
        return None
    n_prefix = 1 + int(s.get("n_storage_tokens", 0) or 0)
    seq_g = seq_len_from_crop(cfg.crops.global_crops_size, s.patch_size,
                              n_prefix)
    seq_l = seq_len_from_crop(cfg.crops.local_crops_size, s.patch_size,
                              n_prefix)
    if seq_l > seq_g:
        return None
    B = int(per_chip_batch)
    return make_packed_layout(
        n_global_rows=2 * B,
        n_local=int(cfg.crops.local_crops_number) * B,
        seq_global=seq_g, seq_local=seq_l, n_prefix=n_prefix,
        groups=groups,
    )


# ---------------- token assembly ----------------


def pack_local_rows(l_tokens, layout: PackedLayout):
    """[n_local, N_l, D] -> [P, N_g, D]: k sequences per row, zero pad.

    Zero pad tokens are safe through the per-token ops (LayerNorm of a
    zero vector is the bias; MLP is pointwise) and are attention-masked
    by their -1 segment id; their outputs are dropped at extraction.
    """
    import jax.numpy as jnp

    P, k, N_l = layout.n_packed_rows, layout.k, layout.seq_local
    x = l_tokens
    if layout.pad_segments:
        x = jnp.pad(x, ((0, layout.pad_segments), (0, 0), (0, 0)))
    x = x.reshape(P, k * N_l, x.shape[-1])
    if layout.pad_tokens_per_row:
        x = jnp.pad(x, ((0, 0), (0, layout.pad_tokens_per_row), (0, 0)))
    return x


def assemble_packed_batch(g_tokens, packed_rows, layout: PackedLayout):
    """Concatenate global and packed rows in the shard-grouped order."""
    import jax.numpy as jnp

    g = layout.groups
    if g <= 1:
        return jnp.concatenate([g_tokens, packed_rows], axis=0)
    gb = layout.n_global_rows // g
    pb = layout.n_packed_rows // g
    tail = g_tokens.shape[1:]
    mixed = jnp.concatenate([
        g_tokens.reshape((g, gb) + tail),
        packed_rows.reshape((g, pb) + tail),
    ], axis=1)
    return mixed.reshape((layout.rows_total,) + tail)


def split_packed_output(out, layout: PackedLayout):
    """Inverse of ``assemble_packed_batch``: ([2B, N, D], [P, N, D])."""
    g = layout.groups
    tail = out.shape[1:]
    if g <= 1:
        return (out[: layout.n_global_rows],
                out[layout.n_global_rows:])
    gb = layout.n_global_rows // g
    pb = layout.n_packed_rows // g
    mixed = out.reshape((g, gb + pb) + tail)
    return (mixed[:, :gb].reshape((layout.n_global_rows,) + tail),
            mixed[:, gb:].reshape((layout.n_packed_rows,) + tail))


def interleave_rows(plain_rows: np.ndarray, layout: PackedLayout) -> np.ndarray:
    """Host-side reorder of a per-row [R, ...] array from the plain
    [globals..., packed...] order into the shard-grouped order."""
    g = layout.groups
    if g <= 1:
        return plain_rows
    gb = layout.n_global_rows // g
    pb = layout.n_packed_rows // g
    perm = np.concatenate([
        np.concatenate([
            np.arange(s * gb, (s + 1) * gb),
            layout.n_global_rows + np.arange(s * pb, (s + 1) * pb),
        ]) for s in range(g)
    ])
    return plain_rows[perm]


def packed_segment_ids(layout: PackedLayout) -> np.ndarray:
    """[R, N_g] int32 segment ids (host constant).

    Global rows are one segment (0). Packed row p, token t: segment
    ``t // N_l`` while t < k*N_l and the slot p*k + t//N_l holds a real
    local crop; -1 otherwise (row-tail pads and the ragged last row's
    empty segments). Attention masks on per-row segment equality, so
    global-row 0s never meet packed-row ids.
    """
    N, N_l, k = layout.seq_global, layout.seq_local, layout.k
    t = np.arange(N)
    base = np.where(t < k * N_l, t // N_l, -1)
    pidx = np.arange(layout.n_packed_rows)[:, None]
    slot = pidx * k + base[None, :]
    seg_p = np.where((base[None, :] >= 0) & (slot < layout.n_local),
                     base[None, :], -1)
    seg_g = np.zeros((layout.n_global_rows, N), np.int64)
    plain = np.concatenate([seg_g, seg_p], axis=0).astype(np.int32)
    return interleave_rows(plain, layout)
