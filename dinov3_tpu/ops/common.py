"""Shared precision policy, initializers, and partitioning helpers.

The framework-wide mixed-precision contract:
- parameters are stored in ``param_dtype`` (fp32 master copies),
- matmuls/activations run in ``compute_dtype`` (bf16 on TPU, MXU-native),
- softmax / norm statistics / loss reductions accumulate in ``reduce_dtype``
  (fp32) — replacing the reference's ad-hoc per-layer casts
  (reference: dinov3_jax/layers/rms_norm.py:21, fp32 accumulation).

Parameters carry *logical* axis names via flax's logical partitioning; the
``parallel`` package maps logical names onto the physical
``(data, fsdp, tensor, seq)`` mesh (see dinov3_tpu/parallel/mesh.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

DTYPE_MAP = {
    "fp32": jnp.float32, "float32": jnp.float32, "f32": jnp.float32,
    "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
    "fp16": jnp.float16, "float16": jnp.float16,
    "fp64": jnp.float64, "float64": jnp.float64,
}


def canonical_dtype(name: str | jnp.dtype | None) -> Any:
    if name is None or not isinstance(name, str):
        return name
    try:
        return DTYPE_MAP[name.lower()]
    except KeyError as e:
        raise ValueError(f"unknown dtype name {name!r}") from e


@dataclasses.dataclass(frozen=True)
class Policy:
    """Mixed precision policy threaded through every module."""

    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    reduce_dtype: Any = jnp.float32
    probs_dtype: Any = None  # attention-probability storage; None = reduce
    # Teacher-target storage (sinkhorn/softmax-centered [*, K] probability
    # buffers over the 65k-262k prototype heads). None = reduce_dtype
    # (fp32, the reference numerics). bf16 halves the HBM traffic of the
    # largest loss-side tensors; every reduction over them still
    # accumulates in fp32 (r5 profile: these fp32 passes were 10.2% of
    # device step time, PROFILE_r05.json).
    target_dtype: Any = None

    @classmethod
    def from_cfg(cls, precision_cfg) -> "Policy":
        probs = precision_cfg.get("probs_dtype")
        target = precision_cfg.get("target_dtype")
        return cls(
            param_dtype=canonical_dtype(precision_cfg.get("param_dtype", "fp32")),
            compute_dtype=canonical_dtype(precision_cfg.get("compute_dtype", "bf16")),
            reduce_dtype=canonical_dtype(precision_cfg.get("reduce_dtype", "fp32")),
            probs_dtype=canonical_dtype(probs) if probs else None,
            target_dtype=canonical_dtype(target) if target else None,
        )


# DINOv3 init: truncated normal std=0.02 clipped at +-1 in unscaled units
# (reference: dinov3_jax/layers/dino_head.py:25-29).
def trunc_normal_init(stddev: float = 0.02) -> Callable:
    import jax

    return jax.nn.initializers.truncated_normal(
        stddev=stddev, lower=-1.0 / max(stddev, 1e-8), upper=1.0 / max(stddev, 1e-8)
    )


def l2_normalize(x: jnp.ndarray, axis: int = -1, eps: float = 1e-12) -> jnp.ndarray:
    """L2 normalize with a gradient that is finite at x == 0.

    ``x / (||x|| + eps)`` has a well-defined value at zero but d||x||/dx is
    0/0 there, so the backward pass produces NaN the moment any normalized
    vector is exactly zero (e.g. a fully-dropped-path sample whose masked
    tokens are the zero-init mask_token fed through zero-init biases).
    Putting eps inside the sqrt keeps value AND gradient finite.
    """
    import jax

    sq = jnp.sum(jnp.square(x), axis=axis, keepdims=True)
    return x * jax.lax.rsqrt(sq + eps * eps)


def part(init: Callable, names: Sequence[str | None]) -> Callable:
    """Attach logical partition names to a param initializer."""
    return nn.with_logical_partitioning(init, tuple(names))


# ---------------- fp8 matmul path ----------------
#
# (reference config surface: ssl_default_config.yaml:121-122
# ``student.fp8_enabled`` / ``student.fp8_filter`` — "Convert Linear layers
# to operate in fp8 precision". The reference never implemented it; here it
# is a current-scaling fp8 forward: per-tensor amax scales both operands
# into the float8_e4m3 range, the dot runs in f8 with fp32 accumulation,
# and the product of scales is applied to the output. Scales carry
# stop_gradient (straight-through), so the backward pass is the usual
# bf16/fp32 path. On fp8-capable TPUs XLA lowers the f8 dot natively; on
# older MXUs it upconverts — a capability knob, not a universal speedup.)

_F8_MAX = 448.0  # float8_e4m3 finite max


def fp8_dot_general(lhs, rhs, dimension_numbers, precision=None,
                    preferred_element_type=None):
    """Drop-in ``dot_general`` that quantizes both operands to f8e4m3."""
    import jax

    f8 = jnp.float8_e4m3fn
    out_dtype = preferred_element_type or lhs.dtype

    def quantize(t):
        tf = t.astype(jnp.float32)
        amax = jnp.max(jnp.abs(tf))
        scale = jax.lax.stop_gradient(jnp.maximum(amax, 1e-12) / _F8_MAX)
        return (tf / scale).astype(f8), scale

    ql, sl = quantize(lhs)
    qr, sr = quantize(rhs)
    out = jax.lax.dot_general(
        ql, qr, dimension_numbers, preferred_element_type=jnp.float32
    )
    return (out * (sl * sr)).astype(out_dtype)


def fp8_matmul(x: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """``x @ w`` through the fp8 path (last dim of x contracts with dim 0
    of w)."""
    return fp8_dot_general(
        x, w, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=x.dtype,
    )


def constrain(x: jnp.ndarray, names: Sequence[str | None]) -> jnp.ndarray:
    """Logical sharding constraint on an activation (no-op outside a mesh)."""
    return nn.with_logical_constraint(x, tuple(names))
