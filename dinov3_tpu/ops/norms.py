"""Normalization layers with fp32 statistic accumulation.

(reference: dinov3_jax/layers/rms_norm.py — which accumulated in fp32 but had
a ``jnp.float`` typo; and plain ``nn.LayerNorm`` used throughout the ViT.)
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from dinov3_tpu.ops.common import part


class LayerNorm(nn.Module):
    """LayerNorm: fp32 stats, params in param_dtype, output in input dtype.

    On TPU the forward/backward run as the fused Pallas kernel
    (ops/fused_norm.py) — one read, in-register fp32 statistics, one write —
    when the width is lane-aligned; elsewhere the identical math goes
    through plain XLA ops."""

    epsilon: float = 1e-6
    param_dtype: Any = jnp.float32
    reduce_dtype: Any = jnp.float32
    fused: bool = True

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        dim = x.shape[-1]
        scale = self.param("scale", part(nn.initializers.ones, ("embed",)), (dim,),
                           self.param_dtype)
        bias = self.param("bias", part(nn.initializers.zeros, ("embed",)), (dim,),
                          self.param_dtype)
        if self.fused and self.reduce_dtype == jnp.float32:
            from dinov3_tpu.ops.fused_norm import fused_layernorm

            return fused_layernorm(x, scale, bias, self.epsilon)
        xf = x.astype(self.reduce_dtype)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + self.epsilon)
        y = y * scale.astype(self.reduce_dtype) + bias.astype(self.reduce_dtype)
        return y.astype(x.dtype)


class RMSNorm(nn.Module):
    """RMSNorm: fp32 mean-square, learned scale."""

    epsilon: float = 1e-6
    param_dtype: Any = jnp.float32
    reduce_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        dim = x.shape[-1]
        scale = self.param("scale", part(nn.initializers.ones, ("embed",)), (dim,),
                           self.param_dtype)
        xf = x.astype(self.reduce_dtype)
        ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        y = xf * jax.lax.rsqrt(ms + self.epsilon)
        y = y * scale.astype(self.reduce_dtype)
        return y.astype(x.dtype)


def make_norm_layer(kind: str, **kwargs) -> nn.Module:
    # "layernormbf16" (7B recipes) selected a bf16-computed LN in the
    # PyTorch original; statistics stay fp32 here — strictly more accurate
    # and free on TPU (the VPU upcasts anyway).
    if kind in ("layernorm", "layer_norm", "ln", "layernormbf16"):
        return LayerNorm(**kwargs)
    if kind in ("rmsnorm", "rms_norm", "rms"):
        return RMSNorm(**kwargs)
    raise ValueError(f"unknown norm layer {kind!r}")
