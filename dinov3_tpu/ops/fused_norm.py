"""Fused LayerNorm as a Pallas TPU kernel with a custom VJP.

Why: the round-1 profile of the ViT-L fused train step showed an ~18 ms
fp32 elementwise tail dominated by layernorm statistics (of a 136 ms step)
— XLA lowers the norm to separate reduce + apply fusions, reading the
activation twice in fp32 per norm and more in the backward. This kernel
reads the bf16 activation once, keeps mean/rstd in registers (fp32), and
writes the normalized output once; the backward recomputes the statistics
in-register instead of saving them, and accumulates dscale/dbias across
row-blocks in VMEM.

(reference: the PyTorch original uses torch.nn.LayerNorm = cuDNN fused
kernels; the JAX port used plain ``nn.LayerNorm``/fp32 math with no fusion
control — dinov3_jax/layers/rms_norm.py and nn.LayerNorm call sites.)

Dispatch contract (``fused_layernorm``):
- Pallas kernel on a TPU backend when the trailing dim is lane-aligned
  (D % 128 == 0);
- under a multi-device mesh the kernel runs inside a ``shard_map`` island
  over the row-sharded activation: LayerNorm is row-local (statistics
  reduce over D only, which is never sharded — parallel/sharding.py maps
  ``embed_act`` to None), so each device normalizes its own rows and no
  collective is needed. Without the island an opaque custom call inside a
  GSPMD program would force replication;
- identical fp32 math through plain XLA ops otherwise (CPU test meshes,
  odd widths, row counts not divisible by the mesh's data axes) — same
  values, same gradients.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu imports fine on CPU builds; guard anyway
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _VMEM = None

_BLOCK_ROWS = 256


def _vmem_spec(block_shape=None, index_map=None):
    if _VMEM is None:  # pure-CPU jaxlib
        return pl.BlockSpec(block_shape, index_map)
    return pl.BlockSpec(block_shape, index_map, memory_space=_VMEM)


def _stats(x, eps):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    return xc, jax.lax.rsqrt(var + eps)


def _mask_rows(t, i, br, n_valid):
    """Zero rows beyond n_valid so garbage in the padded tail of the last
    block cannot reach the stats or the dscale/dbias accumulators."""
    row = i * br + jax.lax.broadcasted_iota(jnp.int32, t.shape, 0)
    return jnp.where(row < n_valid, t, 0.0)


def _fwd_kernel(x_ref, s_ref, b_ref, y_ref, *, eps, n_valid, br):
    x = x_ref[...].astype(jnp.float32)
    if n_valid % br:
        x = _mask_rows(x, pl.program_id(0), br, n_valid)
    xc, rstd = _stats(x, eps)
    s = s_ref[...].astype(jnp.float32)
    b = b_ref[...].astype(jnp.float32)
    y_ref[...] = (xc * rstd * s + b).astype(y_ref.dtype)


def _bwd_kernel(x_ref, s_ref, g_ref, dx_ref, ds_ref, db_ref,
                *, eps, n_valid, br):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        ds_ref[...] = jnp.zeros_like(ds_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    x = x_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    if n_valid % br:
        x = _mask_rows(x, i, br, n_valid)
        g = _mask_rows(g, i, br, n_valid)
    xc, rstd = _stats(x, eps)
    xhat = xc * rstd
    gs = g * s_ref[...].astype(jnp.float32)
    c1 = jnp.mean(gs, axis=-1, keepdims=True)
    c2 = jnp.mean(gs * xhat, axis=-1, keepdims=True)
    dx_ref[...] = (rstd * (gs - c1 - xhat * c2)).astype(dx_ref.dtype)
    ds_ref[...] += jnp.sum(g * xhat, axis=0, keepdims=True)
    db_ref[...] += jnp.sum(g, axis=0, keepdims=True)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ln_2d(x, scale, bias, eps, interpret):
    y, _ = _ln_2d_fwd(x, scale, bias, eps, interpret)
    return y


def _pallas_shapes(R: int):
    br = min(_BLOCK_ROWS, _round_up(R, 16))
    return br, pl.cdiv(R, br)


def _ln_2d_fwd(x, scale, bias, eps, interpret):
    R, D = x.shape
    br, grid = _pallas_shapes(R)
    y = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps, n_valid=R, br=br),
        grid=(grid,),
        in_specs=[
            _vmem_spec((br, D), lambda i: (i, 0)),
            _vmem_spec((1, D), lambda i: (0, 0)),
            _vmem_spec((1, D), lambda i: (0, 0)),
        ],
        out_specs=_vmem_spec((br, D), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((R, D), x.dtype),
        interpret=interpret,
    )(x, scale, bias)
    return y, (x, scale)


def _ln_2d_bwd(eps, interpret, res, g):
    x, scale = res
    R, D = x.shape
    br, grid = _pallas_shapes(R)
    dx, ds, db = pl.pallas_call(
        functools.partial(_bwd_kernel, eps=eps, n_valid=R, br=br),
        grid=(grid,),
        in_specs=[
            _vmem_spec((br, D), lambda i: (i, 0)),
            _vmem_spec((1, D), lambda i: (0, 0)),
            _vmem_spec((br, D), lambda i: (i, 0)),
        ],
        out_specs=[
            _vmem_spec((br, D), lambda i: (i, 0)),
            _vmem_spec((1, D), lambda i: (0, 0)),
            _vmem_spec((1, D), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, D), x.dtype),
            jax.ShapeDtypeStruct((1, D), jnp.float32),
            jax.ShapeDtypeStruct((1, D), jnp.float32),
        ],
        interpret=interpret,
    )(x, scale, g)
    return dx, ds.astype(scale.dtype), db.astype(scale.dtype)


_ln_2d.defvjp(_ln_2d_fwd, _ln_2d_bwd)


def _xla_layernorm(x, scale, bias, eps, reduce_dtype=jnp.float32):
    xf = x.astype(reduce_dtype)
    xc, rstd = _stats(xf, eps)
    y = xc * rstd * scale.astype(reduce_dtype) + bias.astype(reduce_dtype)
    return y.astype(x.dtype)


def use_pallas_layernorm(D: int) -> bool:
    """Opt-in (DINOV3_FUSED_LN=1): measured on v5e, the ViT-L train step is
    *faster without* this kernel — XLA fuses the LN statistics directly
    into the preceding matmul fusions (the round-2 profile's
    convert_reduce_fusions run at ~86% MXU), and an opaque custom call
    breaks those fusions and adds ~240 kernel launches per step (measured
    53.7 vs 58.9 img/s). Kept for workloads where the norm is NOT adjacent
    to a matmul."""
    import os

    if os.environ.get("DINOV3_FUSED_LN", "0") != "1":
        return False
    return jax.default_backend() == "tpu" and D % 128 == 0


def _island_specs(mesh, shape):
    """PartitionSpecs for running the row-local kernel per-shard under a
    multi-device mesh: rows (dim 0) over the data axes, tokens (dim 1 of
    rank-3 activations) over ``seq``, D unsharded. Returns None when the
    shape does not divide the mesh, or under pipeline parallelism — there
    the norms run inside the stage-vmapped pipeline body whose buffers are
    sharded over ``pipe``, a layout these specs cannot express (caller
    falls back to XLA)."""
    from jax.sharding import PartitionSpec as P

    from dinov3_tpu.parallel.mesh import data_axes, data_parallel_size

    if int(mesh.shape.get("pipe", 1)) > 1:
        return None
    if shape[0] % data_parallel_size(mesh) != 0:
        return None
    mid = [None] * (len(shape) - 2)
    if len(shape) >= 3 and int(mesh.shape.get("seq", 1)) > 1:
        if shape[1] % int(mesh.shape["seq"]) != 0:
            return None
        mid[0] = "seq"
    return P(data_axes(mesh), *mid, None)


def fused_layernorm(
    x: jnp.ndarray,
    scale: jnp.ndarray,
    bias: jnp.ndarray,
    eps: float = 1e-6,
    interpret: bool | None = None,
    force: bool | None = None,
) -> jnp.ndarray:
    """LayerNorm over the trailing dim: fp32 stats, output in ``x.dtype``.

    ``force=True`` runs the Pallas kernel regardless of backend (tests use
    it with ``interpret=True`` on CPU); ``force=False`` forces the XLA path.
    """
    D = x.shape[-1]
    use = use_pallas_layernorm(D) if force is None else force
    if not use:
        return _xla_layernorm(x, scale.reshape(D), bias.reshape(D), eps)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    from dinov3_tpu.parallel.context import get_current_mesh

    mesh = get_current_mesh()
    if mesh is not None and mesh.size > 1:
        from jax.sharding import PartitionSpec as P

        spec = _island_specs(mesh, x.shape)
        if spec is None:
            return _xla_layernorm(x, scale.reshape(D), bias.reshape(D), eps)

        def _local(xs, s, b):
            return _ln_nd(xs, s, b, float(eps), interpret)

        from dinov3_tpu.parallel.context import shard_map_compat

        return shard_map_compat(
            _local, mesh=mesh,
            in_specs=(spec, P(None), P(None)),
            out_specs=spec,
            # no collectives in the island (row-local math); pallas_call's
            # out_shape carries no vma so the varying-axes check must be off
            check_vma=False,
        )(x, scale.reshape(D), bias.reshape(D))

    return _ln_nd(x, scale, bias, float(eps), interpret)


def _ln_nd(x, scale, bias, eps, interpret):
    """Flatten leading dims, run the 2-D kernel, restore the shape."""
    D = x.shape[-1]
    lead = x.shape[:-1]
    R = 1
    for s in lead:
        R *= s
    y = _ln_2d(
        x.reshape(R, D), scale.reshape(1, D), bias.reshape(1, D),
        eps, interpret,
    )
    return y.reshape(*lead, D)
