"""Feed-forward layers: standard ViT MLP and SwiGLU.

(reference: dinov3_jax/layers/ffn_layers.py. The reference's ``Mlp`` applied
activation+dropout after the *second* Dense too — a deviation from the
standard ViT MLP and from Meta's PyTorch DINOv3; we use the standard form,
SURVEY.md §2.3. SwiGLU hidden sizing matches: ``int(2/3 * hidden)`` rounded
up to ``align_to``.)
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp

from dinov3_tpu.ops.common import fp8_dot_general, part, trunc_normal_init


def _dense_kwargs(fp8: bool) -> dict:
    return {"dot_general": fp8_dot_general} if fp8 else {}


class Mlp(nn.Module):
    hidden_dim: int
    out_dim: int | None = None
    act: Callable = nn.gelu
    use_bias: bool = True
    dropout_rate: float = 0.0
    fp8: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        out_dim = self.out_dim or x.shape[-1]
        x = nn.Dense(
            self.hidden_dim, use_bias=self.use_bias, dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=part(trunc_normal_init(), ("embed", "mlp")),
            bias_init=part(nn.initializers.zeros, ("mlp",)),
            name="fc1", **_dense_kwargs(self.fp8),
        )(x)
        x = self.act(x)
        x = nn.Dropout(self.dropout_rate)(x, deterministic=deterministic)
        x = nn.Dense(
            out_dim, use_bias=self.use_bias, dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=part(trunc_normal_init(), ("mlp", "embed")),
            bias_init=part(nn.initializers.zeros, ("embed",)),
            name="fc2", **_dense_kwargs(self.fp8),
        )(x)
        x = nn.Dropout(self.dropout_rate)(x, deterministic=deterministic)
        return x


def swiglu_hidden_dim(hidden_dim: int, align_to: int = 8) -> int:
    """2/3 rule rounded up to a lane-friendly multiple."""
    d = int(hidden_dim * 2 / 3)
    return (d + align_to - 1) // align_to * align_to


class SwiGLUFFN(nn.Module):
    hidden_dim: int
    out_dim: int | None = None
    use_bias: bool = True
    align_to: int = 64  # keep the hidden dim MXU/lane aligned on TPU
    fp8: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        out_dim = self.out_dim or x.shape[-1]
        d = swiglu_hidden_dim(self.hidden_dim, self.align_to)
        # fused [gate | value] projection: one big MXU matmul
        w12 = nn.Dense(
            2 * d, use_bias=self.use_bias, dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=part(trunc_normal_init(), ("embed", "mlp")),
            bias_init=part(nn.initializers.zeros, ("mlp",)),
            name="w12", **_dense_kwargs(self.fp8),
        )(x)
        gate, value = jnp.split(w12, 2, axis=-1)
        x = nn.silu(gate) * value
        return nn.Dense(
            out_dim, use_bias=self.use_bias, dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=part(trunc_normal_init(), ("mlp", "embed")),
            bias_init=part(nn.initializers.zeros, ("embed",)),
            name="w3", **_dense_kwargs(self.fp8),
        )(x)


def make_ffn_layer(kind: str, hidden_dim: int, **kwargs) -> nn.Module:
    if kind == "mlp":
        return Mlp(hidden_dim=hidden_dim, **kwargs)
    if kind in ("swiglu", "swiglu64", "swiglu128"):
        align = {"swiglu": 8, "swiglu64": 64, "swiglu128": 128}[kind]
        return SwiGLUFFN(hidden_dim=hidden_dim, align_to=align, **kwargs)
    raise ValueError(f"unknown ffn layer {kind!r}")
