"""Feed-forward layers: standard ViT MLP and SwiGLU.

(reference: dinov3_jax/layers/ffn_layers.py. The reference's ``Mlp`` applied
activation+dropout after the *second* Dense too — a deviation from the
standard ViT MLP and from Meta's PyTorch DINOv3; we use the standard form,
SURVEY.md §2.3. SwiGLU hidden sizing matches: ``int(2/3 * hidden)`` rounded
up to ``align_to``.)
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp

from dinov3_tpu.ops.common import fp8_dot_general, part, trunc_normal_init


def _dense_kwargs(fp8: bool) -> dict:
    return {"dot_general": fp8_dot_general} if fp8 else {}


def _lowp_dense_kwargs(module: nn.Module, kernel: str) -> dict:
    """Per-Dense ``dot_general`` override for a fp8/int8
    ``train.low_precision`` arm: the OWNING module reads the kernel's
    delayed scale from the read-only ``"lowp"`` collection (a Dense
    submodule cannot see sibling collections — scales live at the FFN
    module as ``fc1_kernel``-style names, ops/lowp.py
    ``lowp_scale_site``) and closes it over ``lowp_matmul``. Falls back
    to the legacy fp8 hook / plain dot when the arm is bf16 or no scale
    collection rode this apply (init, eval, the gram teacher)."""
    arm = getattr(module, "lowp_arm", "bf16")
    if arm == "bf16" or not module.has_variable("lowp", kernel):
        return _dense_kwargs(module.fp8)
    from dinov3_tpu.ops.lowp import make_lowp_dot_general

    return {"dot_general": make_lowp_dot_general(
        module.get_variable("lowp", kernel), arm)}


def exact_gelu(x):
    """erf-based GELU — what torch ``nn.GELU()`` (and hence Meta's DINOv3)
    computes; flax's ``nn.gelu`` defaults to the tanh approximation, which
    diverges from the released weights' semantics by up to ~1e-3."""
    import jax

    return jax.nn.gelu(x, approximate=False)


class Mlp(nn.Module):
    hidden_dim: int
    out_dim: int | None = None
    act: Callable = exact_gelu
    use_bias: bool = True
    dropout_rate: float = 0.0
    fp8: bool = False
    lowp_arm: str = "bf16"  # train.low_precision.arm (ops/lowp.py)
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        out_dim = self.out_dim or x.shape[-1]
        x = nn.Dense(
            self.hidden_dim, use_bias=self.use_bias, dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=part(trunc_normal_init(), ("embed", "mlp")),
            bias_init=part(nn.initializers.zeros, ("mlp",)),
            name="fc1", **_lowp_dense_kwargs(self, "fc1_kernel"),
        )(x)
        x = self.act(x)
        x = nn.Dropout(self.dropout_rate)(x, deterministic=deterministic)
        x = nn.Dense(
            out_dim, use_bias=self.use_bias, dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=part(trunc_normal_init(), ("mlp", "embed")),
            bias_init=part(nn.initializers.zeros, ("embed",)),
            name="fc2", **_lowp_dense_kwargs(self, "fc2_kernel"),
        )(x)
        x = nn.Dropout(self.dropout_rate)(x, deterministic=deterministic)
        return x


def swiglu_hidden_dim(hidden_dim: int, align_to: int = 8) -> int:
    """2/3 rule rounded up to a lane-friendly multiple."""
    d = int(hidden_dim * 2 / 3)
    return (d + align_to - 1) // align_to * align_to


class SwiGLUFFN(nn.Module):
    hidden_dim: int
    out_dim: int | None = None
    use_bias: bool = True
    align_to: int = 64  # keep the hidden dim MXU/lane aligned on TPU
    fp8: bool = False
    lowp_arm: str = "bf16"  # train.low_precision.arm (ops/lowp.py)
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        out_dim = self.out_dim or x.shape[-1]
        d = swiglu_hidden_dim(self.hidden_dim, self.align_to)
        # fused [gate | value] projection: one big MXU matmul
        w12 = nn.Dense(
            2 * d, use_bias=self.use_bias, dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=part(trunc_normal_init(), ("embed", "mlp")),
            bias_init=part(nn.initializers.zeros, ("mlp",)),
            name="w12", **_lowp_dense_kwargs(self, "w12_kernel"),
        )(x)
        gate, value = jnp.split(w12, 2, axis=-1)
        x = nn.silu(gate) * value
        return nn.Dense(
            out_dim, use_bias=self.use_bias, dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=part(trunc_normal_init(), ("mlp", "embed")),
            bias_init=part(nn.initializers.zeros, ("embed",)),
            name="w3", **_lowp_dense_kwargs(self, "w3_kernel"),
        )(x)


class MoEFFN(nn.Module):
    """Mixture-of-experts FFN with expert parallelism (beyond the
    reference, which has no MoE — SURVEY.md §2.5 "EP — absent").

    Dense (dropless) formulation: a linear router picks top-k experts per
    token; every expert computes every token and outputs combine weighted
    by the (renormalized) router probabilities, zero for non-selected
    experts. FLOPs are ``num_experts`` times a dense MLP of the same
    hidden size (``num_experts/top_k`` times a sparse top-k dispatch) —
    the right trade below ~16 experts, where the alternative
    (gather/scatter token dispatch) costs an all-to-all and ragged matmuls
    that XLA cannot tile well. Expert params are stacked [E, ...] with the "experts" logical
    axis -> ``expert`` mesh axis: each expert-parallel device computes its
    own experts and XLA inserts one activation-sized all-reduce for the
    combine.

    An auxiliary load-balancing loss (Switch-style: E * sum_e f_e * p_e)
    is stored in the "losses" collection under "moe_aux_loss".
    """

    hidden_dim: int
    num_experts: int = 8
    top_k: int = 2
    out_dim: int | None = None
    act: Callable = exact_gelu
    use_bias: bool = True
    fp8: bool = False  # accepted for make_ffn_layer symmetry; dense path only
    lowp_arm: str = "bf16"  # symmetry only (setup raises on lowp + moe)
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        import jax

        D = x.shape[-1]
        out_dim = self.out_dim or D
        E, H, K = self.num_experts, self.hidden_dim, self.top_k
        if not 1 <= K <= E:
            raise ValueError(f"top_k={K} must be in [1, {E}]")

        router = nn.Dense(
            E, use_bias=False, dtype=jnp.float32,
            param_dtype=self.param_dtype,
            kernel_init=part(trunc_normal_init(), ("embed", None)),
            name="router",
        )
        w1 = self.param(
            "w1", part(trunc_normal_init(), ("experts", "embed", "mlp")),
            (E, D, H), self.param_dtype,
        )
        w2 = self.param(
            "w2", part(trunc_normal_init(), ("experts", "mlp", None)),
            (E, H, out_dim), self.param_dtype,
        )
        b1 = b2 = None
        if self.use_bias:
            b1 = self.param("b1", part(nn.initializers.zeros, ("experts", "mlp")),
                            (E, H), self.param_dtype)
            b2 = self.param("b2", part(nn.initializers.zeros, ("experts", None)),
                            (E, out_dim), self.param_dtype)

        probs = jax.nn.softmax(router(x.astype(jnp.float32)), axis=-1)  # [..., E]
        top_p, top_idx = jax.lax.top_k(probs, K)
        # renormalize over the selected experts; scatter back to dense [E]
        top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
        gate = jnp.sum(
            jax.nn.one_hot(top_idx, E, dtype=probs.dtype) * top_p[..., None],
            axis=-2,
        )  # [..., E], zero for unselected experts

        # Switch-style load-balance aux loss over all tokens in the batch
        flat_gate = gate.reshape(-1, E)
        frac_tokens = jnp.mean((flat_gate > 0).astype(jnp.float32), axis=0)
        frac_probs = jnp.mean(probs.reshape(-1, E), axis=0)
        self.sow("losses", "moe_aux_loss",
                 E * jnp.sum(frac_tokens * frac_probs))

        xc = x.astype(self.dtype)
        h = jnp.einsum("...d,edh->e...h", xc, w1.astype(self.dtype))
        if b1 is not None:
            h = h + b1.astype(self.dtype).reshape((E,) + (1,) * (x.ndim - 1) + (H,))
        h = self.act(h)
        y = jnp.einsum("e...h,eho->e...o", h, w2.astype(self.dtype))
        if b2 is not None:
            y = y + b2.astype(self.dtype).reshape((E,) + (1,) * (x.ndim - 1) + (out_dim,))
        # combine: weighted sum over experts (all-reduce over the expert
        # mesh axis under GSPMD)
        gate_e = jnp.moveaxis(gate, -1, 0).astype(self.dtype)  # [E, ...]
        return jnp.sum(y * gate_e[..., None], axis=0)


def make_ffn_layer(kind: str, hidden_dim: int, *, moe_num_experts: int = 8,
                   moe_top_k: int = 2, **kwargs) -> nn.Module:
    if kind == "mlp":
        return Mlp(hidden_dim=hidden_dim, **kwargs)
    if kind in ("swiglu", "swiglu64", "swiglu128"):
        align = {"swiglu": 8, "swiglu64": 64, "swiglu128": 128}[kind]
        return SwiGLUFFN(hidden_dim=hidden_dim, align_to=align, **kwargs)
    if kind == "moe":
        return MoEFFN(hidden_dim=hidden_dim, num_experts=moe_num_experts,
                      top_k=moe_top_k, **kwargs)
    raise ValueError(f"unknown ffn layer {kind!r}")
