"""Blockwise flash attention as Pallas TPU kernels, with a custom VJP.

(reference: dinov3_jax/layers/attention.py:116 used
``flax.linen.dot_product_attention`` — a dense [N, N] softmax with O(N^2)
memory and no kernel fusion; SURVEY.md §5.7 calls out the absence of any
flash/blockwise path as the gap for high-res (518-768 px) and ViT-7B runs.)

Design
------
- Non-causal bidirectional attention (ViT), shapes [B, N, heads, d].
- Forward: one Pallas kernel per (batch, head, q-block); keys/values for
  the whole row live in VMEM (N <= ~2.4k tokens for DINOv3's largest crop,
  so K+V fit comfortably); online softmax with running max/normalizer in
  fp32, matmuls on the MXU via ``preferred_element_type=float32``.
- Backward: standard two-kernel FlashAttention-2 scheme — ``delta =
  rowsum(dO * O)`` precomputed, then a dq kernel (loop over k-blocks) and a
  dk/dv kernel (loop over q-blocks), both recomputing probabilities from
  the saved logsumexp instead of materializing [N, N].
- Sequence padding: N is static under jit, so q/k/v are zero-padded to a
  lane-aligned Np and the pad columns are masked with -inf at trace time
  only when padding exists.
- Segment masking (crop packing, ops/packing.py): an optional [B, N]
  int32 segment-id array turns every kernel block-diagonal — token q
  attends token k iff their ids match, exactly the ``-inf``-style
  masking the pad columns already use. Ids are threaded twice, as
  [BH, Np, 1] rows (q side) and [BH, 1, Np] cols (k side), so neither
  kernel needs an in-VMEM transpose. Pad positions from the lane
  alignment get id -2: distinct from the packer's -1 pads, though the
  existing n_valid masking already covers them.

All kernels run in interpret mode off-TPU so the CPU test mesh exercises
the exact same code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds too; guard anyway
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _VMEM = None

NEG_INF = -1e30


def _pick(n_padded: int, cap: int) -> int:
    for c in (512, 256, 128):
        if c <= cap and n_padded % c == 0:
            return c
    raise ValueError(f"n_padded={n_padded} is not a multiple of 128")


def _block_sizes(n_padded: int, block_q: int = 512,
                 block_kv: int = 512) -> tuple[int, int]:
    """Concrete q/kv block sizes: the largest 128-multiple divisor of
    n_padded within the configured caps (``kernels.flash_block_q/kv``)."""
    return (_pick(n_padded, max(128, int(block_q))),
            _pick(n_padded, max(128, int(block_kv))))


def _vmem_spec(block_shape=None, index_map=None):
    if _VMEM is None:  # pure-CPU jaxlib
        return pl.BlockSpec(block_shape, index_map)
    return pl.BlockSpec(block_shape, index_map, memory_space=_VMEM)


# ---------------------------------------------------------------- forward


def _fwd_kernel(*refs, scale, n_valid, bk, has_seg):
    # q_ref: [bq, d]; k_ref/v_ref: [Np, d]; o_ref: [bq, d]; lse_ref: [bq, 1]
    # with has_seg: + sq_ref [bq, 1], sk_ref [1, Np] (row/col segment ids)
    if has_seg:
        q_ref, k_ref, v_ref, sq_ref, sk_ref, o_ref, lse_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref = refs
        sq_ref = sk_ref = None
    bq, d = q_ref.shape
    n_padded = k_ref.shape[0]
    q = q_ref[...].astype(jnp.float32) * scale
    sq = sq_ref[...] if has_seg else None  # [bq, 1]

    m = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(j * bk, bk), :]
        v = v_ref[pl.ds(j * bk, bk), :]
        s = jax.lax.dot_general(
            q, k.astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        if has_seg:
            sk = sk_ref[:, pl.ds(j * bk, bk)]  # [1, bk]
            s = jnp.where(sq == sk, s, NEG_INF)
        if n_padded != n_valid:
            col = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(col < n_valid, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v.astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, n_padded // bk, body, (m, l, acc))
    o_ref[...] = (acc / l).astype(o_ref.dtype)
    lse_ref[...] = m + jnp.log(l)


def _flash_fwd(q, k, v, seg_rows=None, seg_cols=None, *, n_valid,
               interpret, caps=(512, 512)):
    """q, k, v: [BH, Np, d] fp32/bf16; returns (o, lse)."""
    bh, n_padded, d = q.shape
    bq, bk = _block_sizes(n_padded, *caps)
    scale = d ** -0.5
    has_seg = seg_rows is not None
    kernel = functools.partial(
        _fwd_kernel, scale=scale, n_valid=n_valid, bk=bk, has_seg=has_seg
    )
    grid = (bh, n_padded // bq)
    in_specs = [
        _vmem_spec((None, bq, d), lambda b, i: (b, i, 0)),
        _vmem_spec((None, n_padded, d), lambda b, i: (b, 0, 0)),
        _vmem_spec((None, n_padded, d), lambda b, i: (b, 0, 0)),
    ]
    args = [q, k, v]
    if has_seg:
        in_specs += [
            _vmem_spec((None, bq, 1), lambda b, i: (b, i, 0)),
            _vmem_spec((None, 1, n_padded), lambda b, i: (b, 0, 0)),
        ]
        args += [seg_rows, seg_cols]
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            _vmem_spec((None, bq, d), lambda b, i: (b, i, 0)),
            _vmem_spec((None, bq, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, n_padded, d), q.dtype),
            jax.ShapeDtypeStruct((bh, n_padded, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return o, lse


# ---------------------------------------------------------------- backward


def _dq_kernel(*refs, scale, n_valid, bk, has_seg):
    if has_seg:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         sq_ref, sk_ref, dq_ref) = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref = refs
        sq_ref = sk_ref = None
    bq, d = q_ref.shape
    n_padded = k_ref.shape[0]
    q = q_ref[...].astype(jnp.float32)
    do = do_ref[...].astype(jnp.float32)
    lse = lse_ref[...]      # [bq, 1]
    delta = delta_ref[...]  # [bq, 1]
    sq = sq_ref[...] if has_seg else None  # [bq, 1]
    dq = jnp.zeros((bq, d), jnp.float32)

    def body(j, dq):
        k = k_ref[pl.ds(j * bk, bk), :].astype(jnp.float32)
        v = v_ref[pl.ds(j * bk, bk), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if has_seg:
            sk = sk_ref[:, pl.ds(j * bk, bk)]  # [1, bk]
            s = jnp.where(sq == sk, s, NEG_INF)
        if n_padded != n_valid:
            col = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(col < n_valid, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq = jax.lax.fori_loop(0, n_padded // bk, body, dq)
    dq_ref[...] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(*refs, scale, n_valid, bq, has_seg):
    if has_seg:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         sq_ref, sk_ref, dk_ref, dv_ref) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref) = refs
        sq_ref = sk_ref = None
    bk, d = k_ref.shape
    n_padded = q_ref.shape[0]
    k = k_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    sk = sk_ref[...] if has_seg else None  # [1, bk]
    dk = jnp.zeros((bk, d), jnp.float32)
    dv = jnp.zeros((bk, d), jnp.float32)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[pl.ds(i * bq, bq), :].astype(jnp.float32)
        do = do_ref[pl.ds(i * bq, bq), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(i * bq, bq), :]      # [bq, 1]
        delta = delta_ref[pl.ds(i * bq, bq), :]  # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]
        if has_seg:
            sq = sq_ref[pl.ds(i * bq, bq), :]  # [bq, 1]
            s = jnp.where(sq == sk, s, NEG_INF)
        if n_padded != n_valid:
            # pad q rows: their lse is 0 -> exp(s) could blow up; mask rows
            row = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            s = jnp.where(row < n_valid, s, NEG_INF)
        p = jnp.exp(s - lse)  # [bq, bk]
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk, dv

    dk, dv = jax.lax.fori_loop(0, n_padded // bq, body, (dk, dv))
    dk_ref[...] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


# ------------------------------------------------------------ public entry


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_bhnd(q, k, v, interpret, caps):
    o, _ = _fwd_pallas(q, k, v, None, interpret, caps)
    return o


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash_bhnd_seg(q, k, v, seg, interpret, caps):
    o, _ = _fwd_pallas(q, k, v, seg, interpret, caps)
    return o


def _fwd_pallas(q, k, v, seg, interpret, caps=(512, 512)):
    n_valid = q.shape[1]
    n_padded = _round_up(n_valid, 128)
    pad = n_padded - n_valid
    if pad:
        padcfg = ((0, 0), (0, pad), (0, 0))
        q = jnp.pad(q, padcfg)
        k = jnp.pad(k, padcfg)
        v = jnp.pad(v, padcfg)
    seg_rows = seg_cols = None
    if seg is not None:
        if pad:
            seg = jnp.pad(seg, ((0, 0), (0, pad)), constant_values=-2)
        seg_rows = seg[:, :, None]
        seg_cols = seg[:, None, :]
    o, lse = _flash_fwd(q, k, v, seg_rows, seg_cols, n_valid=n_valid,
                        interpret=interpret, caps=caps)
    return o[:, :n_valid], (q, k, v, o, lse, seg, n_valid)


def _flash_bhnd_fwd(q, k, v, interpret, caps):
    o, res = _fwd_pallas(q, k, v, None, interpret, caps)
    return o, res


def _flash_bhnd_seg_fwd(q, k, v, seg, interpret, caps):
    o, res = _fwd_pallas(q, k, v, seg, interpret, caps)
    return o, res


def _bwd_pallas(interpret, caps, res, do):
    q, k, v, o, lse, seg, n_valid = res  # padded to Np
    bh, n_padded, d = q.shape
    pad = n_padded - n_valid
    if pad:
        do = jnp.pad(do, ((0, 0), (0, pad), (0, 0)))
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)
    bq, bk = _block_sizes(n_padded, *caps)
    scale = d ** -0.5
    has_seg = seg is not None
    seg_args, dq_seg_specs, dkv_seg_specs = [], [], []
    if has_seg:
        seg_args = [seg[:, :, None], seg[:, None, :]]
        dq_seg_specs = [
            _vmem_spec((None, bq, 1), lambda b, i: (b, i, 0)),
            _vmem_spec((None, 1, n_padded), lambda b, i: (b, 0, 0)),
        ]
        dkv_seg_specs = [
            _vmem_spec((None, n_padded, 1), lambda b, j: (b, 0, 0)),
            _vmem_spec((None, 1, bk), lambda b, j: (b, 0, j)),
        ]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, n_valid=n_valid, bk=bk,
                          has_seg=has_seg),
        grid=(bh, n_padded // bq),
        in_specs=[
            _vmem_spec((None, bq, d), lambda b, i: (b, i, 0)),
            _vmem_spec((None, n_padded, d), lambda b, i: (b, 0, 0)),
            _vmem_spec((None, n_padded, d), lambda b, i: (b, 0, 0)),
            _vmem_spec((None, bq, d), lambda b, i: (b, i, 0)),
            _vmem_spec((None, bq, 1), lambda b, i: (b, i, 0)),
            _vmem_spec((None, bq, 1), lambda b, i: (b, i, 0)),
        ] + dq_seg_specs,
        out_specs=_vmem_spec((None, bq, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, n_padded, d), q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta, *seg_args)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, n_valid=n_valid, bq=bq,
                          has_seg=has_seg),
        grid=(bh, n_padded // bk),
        in_specs=[
            _vmem_spec((None, n_padded, d), lambda b, j: (b, 0, 0)),
            _vmem_spec((None, bk, d), lambda b, j: (b, j, 0)),
            _vmem_spec((None, bk, d), lambda b, j: (b, j, 0)),
            _vmem_spec((None, n_padded, d), lambda b, j: (b, 0, 0)),
            _vmem_spec((None, n_padded, 1), lambda b, j: (b, 0, 0)),
            _vmem_spec((None, n_padded, 1), lambda b, j: (b, 0, 0)),
        ] + dkv_seg_specs,
        out_specs=[
            _vmem_spec((None, bk, d), lambda b, j: (b, j, 0)),
            _vmem_spec((None, bk, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, n_padded, d), k.dtype),
            jax.ShapeDtypeStruct((bh, n_padded, d), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta, *seg_args)

    if pad:
        dq, dk, dv = (t[:, :n_valid] for t in (dq, dk, dv))
    return dq, dk, dv


def _flash_bhnd_bwd(interpret, caps, res, do):
    return _bwd_pallas(interpret, caps, res, do)


def _flash_bhnd_seg_bwd(interpret, caps, res, do):
    dq, dk, dv = _bwd_pallas(interpret, caps, res, do)
    seg, n_valid = res[5], res[6]
    # integer segment ids have no tangent space; float0 is the formal
    # zero cotangent custom_vjp requires for them (shape of the UNPADDED
    # primal input)
    dseg = np.zeros((seg.shape[0], n_valid), dtype=jax.dtypes.float0)
    return dq, dk, dv, dseg


_flash_bhnd.defvjp(_flash_bhnd_fwd, _flash_bhnd_bwd)
_flash_bhnd_seg.defvjp(_flash_bhnd_seg_fwd, _flash_bhnd_seg_bwd)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    interpret: bool | None = None,
    block_q: int = 512,
    block_kv: int = 512,
    seg: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Fused attention. q, k, v: [B, N, heads, d] -> [B, N, heads, d].

    Softmax statistics accumulate in fp32 regardless of input dtype.
    ``interpret`` defaults to True off-TPU so CPU tests run the same code.
    ``block_q``/``block_kv`` cap the kernel block sizes
    (``kernels.flash_block_q/kv``; actual = largest divisor within cap).
    ``seg``: optional [B, N] int32 segment ids — block-diagonal attention
    for the crop-packed batch (ops/packing.py); same ``-inf`` masking
    class the kernels already apply to pad columns.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, N, h, d = q.shape
    to_bhnd = lambda t: t.transpose(0, 2, 1, 3).reshape(B * h, N, d)
    caps = (int(block_q), int(block_kv))
    if seg is None:
        o = _flash_bhnd(to_bhnd(q), to_bhnd(k), to_bhnd(v), interpret, caps)
    else:
        seg_bh = jnp.broadcast_to(
            seg.astype(jnp.int32)[:, None, :], (B, h, N)).reshape(B * h, N)
        o = _flash_bhnd_seg(to_bhnd(q), to_bhnd(k), to_bhnd(v), seg_bh,
                            interpret, caps)
    return o.reshape(B, h, N, d).transpose(0, 2, 1, 3)
