"""Patch embedding as an explicit unfold + matmul.

The reference used a strided conv (dinov3_jax/layers/patch_embed.py:38-42).
On TPU a stride==kernel "conv" is exactly a reshape + one large [B*T, p*p*C]
x [p*p*C, D] matmul, which maps straight onto the MXU with no conv layout
heuristics; the weight is kept in conv layout [p, p, C, D] so torch/reference
checkpoints port unchanged.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from dinov3_tpu.ops.common import part, trunc_normal_init


class PatchEmbed(nn.Module):
    embed_dim: int
    patch_size: int = 16
    in_chans: int = 3
    use_bias: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        """[B, H, W, C] (NHWC) -> [B, H/p * W/p, D]."""
        B, H, W, C = x.shape
        p = self.patch_size
        if H % p or W % p:
            raise ValueError(f"image size {(H, W)} not divisible by patch {p}")
        kernel = self.param(
            "kernel",
            part(trunc_normal_init(), (None, None, None, "embed")),
            (p, p, C, self.embed_dim),
            self.param_dtype,
        )
        h, w = H // p, W // p
        x = x.reshape(B, h, p, w, p, C).transpose(0, 1, 3, 2, 4, 5)
        x = x.reshape(B, h * w, p * p * C).astype(self.dtype)
        w_mat = kernel.reshape(p * p * C, self.embed_dim).astype(self.dtype)
        y = x @ w_mat
        if self.use_bias:
            bias = self.param(
                "bias", part(nn.initializers.zeros, ("embed",)),
                (self.embed_dim,), self.param_dtype,
            )
            y = y + bias.astype(self.dtype)
        return y
