from dinov3_tpu.ops.attention import (
    CausalSelfAttention,
    SelfAttention,
    dispatch_attention,
    xla_attention,
)
from dinov3_tpu.ops.block import CausalSelfAttentionBlock, SelfAttentionBlock
from dinov3_tpu.ops.common import Policy, canonical_dtype, constrain, part, trunc_normal_init
from dinov3_tpu.ops.dino_head import DINOHead
from dinov3_tpu.ops.drop_path import DropPath
from dinov3_tpu.ops.ffn import Mlp, SwiGLUFFN, make_ffn_layer, swiglu_hidden_dim
from dinov3_tpu.ops.layer_scale import LayerScale
from dinov3_tpu.ops.norms import LayerNorm, RMSNorm, make_norm_layer
from dinov3_tpu.ops.patch_embed import PatchEmbed
from dinov3_tpu.ops.rope import (
    patch_coords,
    rope_apply,
    rope_apply_with_prefix,
    rope_periods,
    rope_rotate_half,
    rope_sincos,
)

__all__ = [
    "SelfAttention", "CausalSelfAttention", "dispatch_attention",
    "xla_attention",
    "SelfAttentionBlock", "CausalSelfAttentionBlock",
    "Policy", "canonical_dtype", "constrain", "part",
    "trunc_normal_init", "DINOHead", "DropPath", "Mlp", "SwiGLUFFN",
    "make_ffn_layer", "swiglu_hidden_dim", "LayerScale", "LayerNorm",
    "RMSNorm", "make_norm_layer", "PatchEmbed", "patch_coords", "rope_apply",
    "rope_apply_with_prefix", "rope_periods", "rope_rotate_half", "rope_sincos",
]
