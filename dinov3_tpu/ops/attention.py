"""Multi-head self-attention with RoPE and pluggable kernels.

(reference: dinov3_jax/layers/attention.py — which used
``flax.linen.dot_product_attention`` with no fused kernel and a NaN-filled
"bias mask" for ``mask_k_bias``, SURVEY.md §2.9.)

TPU-first choices:
- one fused qkv matmul, head reshape after (single MXU call);
- softmax logits accumulate in ``reduce_dtype`` (fp32);
- ``mask_k_bias`` zeroes the k third of the qkv bias with a *constant* 0/1
  mask (softmax is shift-invariant in k-bias, so zeroing it is the intended
  semantic; the reference multiplied by NaNs);
- kernel dispatch: "pallas" selects the flash-attention kernel
  (dinov3_tpu/ops/flash_attention.py) on TPU, "xla" the unfused einsum
  path; "auto" picks per-backend.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from dinov3_tpu.ops.common import (
    constrain,
    fp8_matmul,
    part,
    trunc_normal_init,
)
from dinov3_tpu.ops.rope import rope_apply_full, rope_apply_with_prefix


import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _softmax_lowp(logits, out_dtype):
    """Softmax with fp32 statistics but low-precision output AND residual.

    Autodiff of a plain ``softmax(logits).astype(bf16)`` saves the fp32
    probabilities for the backward — at ViT-L's 224px global crops that
    is a [16, 16, 201, 201] fp32 array per layer whose save/transpose
    copies are pure HBM traffic. Storing the residual in ``out_dtype``
    (bf16) halves that traffic; the backward (dL = p * (g - sum(g*p)))
    accumulates in fp32. Committed A/B on the fp32-master program:
    47.58 -> 48.07 img/s/chip (BENCH_r03_phases.jsonl, bf16 vs fp32
    probs storage); the r5 on-chip profile (PROFILE_r05.json) confirms
    the residual copies survive as the f32 `[11,16,201,201]` copy ops
    (~1% of step) — the bf16 residual is what keeps them there and not
    at 2x that.
    """
    return jax.nn.softmax(logits, axis=-1).astype(out_dtype)


def _softmax_lowp_fwd(logits, out_dtype):
    p = _softmax_lowp(logits, out_dtype)
    return p, p


def _softmax_lowp_bwd(out_dtype, p, g):
    pf = p.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    s = jnp.sum(gf * pf, axis=-1, keepdims=True)
    return (pf * (gf - s),)


_softmax_lowp.defvjp(_softmax_lowp_fwd, _softmax_lowp_bwd)


def xla_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    reduce_dtype=jnp.float32,
    causal: bool = False,
    probs_dtype=None,
    seg: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Unfused attention: [B, N, h, d] inputs, softmax in reduce_dtype.

    ``probs_dtype``: storage dtype of the probabilities (fp32 statistics
    either way). bf16 halves the [B, h, N, N] HBM traffic — the recipe
    default via ``compute_precision.probs_dtype`` — while ``None`` keeps
    full-precision residuals (module default; bitwise-stable tests).

    ``seg``: optional [B, N] int32 segment ids (crop packing,
    ops/packing.py): token q attends token k iff seg[b,q] == seg[b,k] —
    block-diagonal attention, so packed crops never see each other.
    Masked logits get a large finite negative (the flash kernel's
    NEG_INF convention): their exp underflows to exactly 0 after the
    row-max shift (every token matches itself, so the max is always a
    real logit), which keeps packed-vs-unpacked softmax sums bitwise
    clean and — unlike -inf — cannot produce NaN for any row."""
    d = q.shape[-1]
    scale = d ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=reduce_dtype)
    logits = (logits * scale).astype(reduce_dtype)
    if seg is not None:
        same = seg[:, None, :, None] == seg[:, None, None, :]
        logits = jnp.where(same, logits, jnp.asarray(-1e30, logits.dtype))
    if causal:
        N = q.shape[1]
        row = jax.lax.broadcasted_iota(jnp.int32, (1, 1, N, N), 2)
        col = jax.lax.broadcasted_iota(jnp.int32, (1, 1, N, N), 3)
        logits = jnp.where(col <= row, logits, jnp.asarray(-jnp.inf, logits.dtype))
    if probs_dtype is not None and probs_dtype != logits.dtype:
        import os

        if os.environ.get("DINOV3_PLAIN_LOWP_SOFTMAX") == "1":
            # bisect switch (BENCH_r02 compile-hang postmortem): same
            # bf16 probability storage but plain autodiff — isolates the
            # custom_vjp as the variable if the axon compile helper stalls
            probs = jax.nn.softmax(logits, axis=-1).astype(probs_dtype)
        else:
            probs = _softmax_lowp(logits, probs_dtype)
    else:
        probs = jax.nn.softmax(logits, axis=-1)
    # named for the "attn" remat policy (ops/block.py remat_block_cls):
    # the [B, h, N, N] softmax state dominates saved activations at
    # long N; recomputing it in the backward trades cheap FLOPs for HBM
    from jax.ad_checkpoint import checkpoint_name

    probs = checkpoint_name(probs, "attn_probs")
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


def _flash_available() -> bool:
    try:
        from dinov3_tpu.ops import flash_attention  # noqa: F401

        return True
    except ImportError:
        return False


# Below this many tokens the dense-softmax XLA path wins on TPU: the whole
# [N, N] fits in VMEM, XLA fuses RoPE/scale/softmax into the matmuls, and
# the flash kernel's custom_vjp would block those fusions. Measured
# full-train-step evidence (v5e): dense wins at N=201 (~1.45x, r1) AND at
# N=1029 — the 512px ViT-L step runs 9.99 img/s dense vs 7.65 flash
# (MEASUREMENTS_r5.md phF rows), so the old 1024 threshold flipped to the
# slower path at its first live decision point. 2048 keeps every measured
# regime on dense while leaving flash reachable where its O(N) memory is
# the point (768px -> 2309 tokens, ViT-7B long-context).
#
# The SOURCE OF TRUTH for module-built models is the config knob
# ``kernels.flash_min_seq`` (ssl_default_config.yaml, default "auto") —
# "auto" resolves against the committed op-level crossover artifact
# CROSSOVER_r19.json via scripts/crossover_attention.py's
# ``recommended_flash_min_seq`` (configs/config.py
# ``resolve_flash_min_seq``; the artifact-pin test is
# tests/test_crossover_attention.py). Re-derive the threshold by
# re-running the crossover harness on TPU and committing the artifact,
# not by editing this file. This constant is only the fallback for
# direct dispatch_attention calls that pass flash_min_seq=0.
FLASH_MIN_SEQ = 2048

# Below this many tokens ring attention is not worth the rotation: the
# point of the ring is sharding the O(N) K/V state and the O(N^2)
# logits-block traffic over the seq axis, and at short N (the 98-201
# token local crops) the whole dense call is cheaper than size-1 chunks
# ppermuting around the mesh. Dispatch is per-PASS (q.shape[1]): under
# one dp x seq mesh the 1029-token 512px globals ring while the locals
# run dense with seq-replicated activations — the crossover is a memory
# argument (O(N/s) per device vs O(N)), unlike flash_min_seq's measured
# time crossover. Config knob: ``kernels.ring_min_seq`` (0 = this
# fallback); in-step ring tests override it to 1.
RING_MIN_SEQ = 1024


def dispatch_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    impl: str = "auto", reduce_dtype=jnp.float32,
    flash_block_q: int = 512, flash_block_kv: int = 512,
    probs_dtype=None, flash_min_seq: int = 0,
    seg: jnp.ndarray | None = None,
) -> jnp.ndarray:
    if impl == "auto":
        # 0/None = built-in default, matching kernels.flash_min_seq's
        # documented sentinel (one convention for module and direct calls)
        min_seq = flash_min_seq or FLASH_MIN_SEQ
        impl = (
            "pallas"
            if (
                jax.default_backend() == "tpu"
                and q.shape[1] >= min_seq
                and _flash_available()
            )
            else "xla"
        )
    if impl in ("xla", "reference"):
        return xla_attention(q, k, v, reduce_dtype, probs_dtype=probs_dtype,
                             seg=seg)
    if impl == "pallas":
        from dinov3_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, block_q=flash_block_q,
                               block_kv=flash_block_kv, seg=seg)
    raise ValueError(f"unknown attention impl {impl!r}")


class SelfAttention(nn.Module):
    dim: int
    num_heads: int = 8
    qkv_bias: bool = True
    proj_bias: bool = True
    proj_drop: float = 0.0
    mask_k_bias: bool = False
    attn_impl: str = "auto"
    seq_parallel: bool = False
    fp8: bool = False  # current-scaling fp8 projections (ops/common.py)
    # train.low_precision.arm: delayed-scaling fp8/int8 matmuls
    # (ops/lowp.py) — engaged only when the "lowp" scale collection is
    # present (training applies), so init/eval stay on the bf16 path
    lowp_arm: str = "bf16"
    causal: bool = False  # triangular mask (dense XLA path only)
    flash_block_q: int = 512   # kernels.flash_block_q/kv caps
    flash_block_kv: int = 512
    flash_min_seq: int = 0     # kernels.flash_min_seq; 0 = FLASH_MIN_SEQ
    ring_min_seq: int = 0      # kernels.ring_min_seq; 0 = RING_MIN_SEQ
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    reduce_dtype: Any = jnp.float32
    probs_dtype: Any = None  # probability storage; None = reduce_dtype

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        rope: tuple[jnp.ndarray, jnp.ndarray] | None = None,
        deterministic: bool = True,
        seg: jnp.ndarray | None = None,
    ) -> jnp.ndarray:
        """``seg``: optional [B, N] segment ids for block-diagonal
        (crop-packed) attention; ``rope`` tables may then be per-row
        [B, N, head_dim] (global vs packed coordinate grids)."""
        B, N, _ = x.shape
        h, d = self.num_heads, self.dim // self.num_heads

        qkv_kernel = self.param(
            "qkv_kernel", part(trunc_normal_init(), ("embed", "heads")),
            (self.dim, 3 * self.dim), self.param_dtype,
        )
        mm = fp8_matmul if self.fp8 else (lambda a, b: a @ b)

        def lowp_mm(name):
            """Quantized-arm matmul for the kernel whose delayed scale
            is at ``("lowp", name)`` — falls back to ``mm`` when the
            arm is bf16 or no scale collection rode this apply (init,
            eval, the gram teacher)."""
            if self.lowp_arm == "bf16" or not self.has_variable("lowp", name):
                return mm
            from dinov3_tpu.ops.lowp import lowp_matmul

            scale = self.get_variable("lowp", name)
            return lambda a, b: lowp_matmul(self.lowp_arm, a, b, scale)

        qkv = lowp_mm("qkv_kernel")(
            x.astype(self.dtype), qkv_kernel.astype(self.dtype))
        if self.qkv_bias:
            qkv_b = self.param(
                "qkv_bias", part(nn.initializers.zeros, ("heads",)),
                (3 * self.dim,), self.param_dtype,
            )
            if self.mask_k_bias:
                # zero the k third: softmax(q.(k+b)) is invariant to a shared
                # k shift only for the rotary-free part, so DINOv3 masks it
                # outright (reference: LinearKMaskedBias, attention.py:23-46).
                mask = jnp.concatenate([
                    jnp.ones((self.dim,), self.param_dtype),
                    jnp.zeros((self.dim,), self.param_dtype),
                    jnp.ones((self.dim,), self.param_dtype),
                ])
                qkv_b = qkv_b * mask
            qkv = qkv + qkv_b.astype(self.dtype)

        # contiguous last-dim thirds (same column order as
        # reshape(B,N,3,h,d) + moveaxis, which forced a full strided copy
        # of qkv — round-2 profile: ~6 ms/step on the moveaxis alone)
        q = qkv[..., : self.dim].reshape(B, N, h, d)
        k = qkv[..., self.dim: 2 * self.dim].reshape(B, N, h, d)
        v = qkv[..., 2 * self.dim:].reshape(B, N, h, d)
        if rope is not None:
            sin, cos = rope
            if sin.shape[-2] == N:
                # full-length table (identity prefix rows): fused fma path
                q, k = rope_apply_full(q, k, sin, cos)
            else:
                q, k = rope_apply_with_prefix(
                    q, k, sin, cos, dtype=self.reduce_dtype
                )

        out = None
        out_token_axis = None  # "seq_tokens" when the ring path engages
        if self.causal:
            # causal runs the dense path (ViT's SSL path never uses it;
            # reference kept a CausalSelfAttention for generative probes)
            out = xla_attention(q, k, v, self.reduce_dtype, causal=True,
                                probs_dtype=self.probs_dtype)
        if out is None and self.seq_parallel \
                and N >= (self.ring_min_seq or RING_MIN_SEQ):
            # per-pass dispatch: only passes long enough to pay for the
            # rotation ring (RING_MIN_SEQ) — under one dp x seq mesh the
            # high-res globals ring while short local crops run dense
            # with seq-replicated activations. Crop-packed rows ride
            # along: the segment ids thread through the rotating chunks
            # (parallel/ring_attention.py), same block-diagonal
            # semantics as the dense/flash seg mask.
            from dinov3_tpu.parallel.context import get_current_mesh

            mesh = get_current_mesh()
            if mesh is not None and int(mesh.shape.get("seq", 1)) > 1:
                from dinov3_tpu.parallel.ring_attention import ring_attention

                out = ring_attention(q, k, v, mesh, seg=seg,
                                     reduce_dtype=self.reduce_dtype)
                # keep the ring's output seq-sharded ("seq_tokens" rule,
                # parallel/sharding.py) so the MLP half of the block runs
                # on N/s tokens per device instead of re-gathering N
                out_token_axis = "seq_tokens"
        if out is None:
            out = dispatch_attention(
                q, k, v, self.attn_impl, self.reduce_dtype,
                flash_block_q=self.flash_block_q,
                flash_block_kv=self.flash_block_kv,
                probs_dtype=self.probs_dtype,
                flash_min_seq=self.flash_min_seq,
                seg=seg,
            )
        out = constrain(out.reshape(B, N, self.dim),
                        ("batch", out_token_axis, "embed_act"))

        proj_kernel = self.param(
            "proj_kernel", part(trunc_normal_init(), ("heads", "embed")),
            (self.dim, self.dim), self.param_dtype,
        )
        y = lowp_mm("proj_kernel")(
            out.astype(self.dtype), proj_kernel.astype(self.dtype))
        if self.proj_bias:
            proj_b = self.param(
                "proj_bias", part(nn.initializers.zeros, ("embed",)),
                (self.dim,), self.param_dtype,
            )
            y = y + proj_b.astype(self.dtype)
        if self.proj_drop > 0.0:
            y = nn.Dropout(self.proj_drop)(y, deterministic=deterministic)
        return y


class CausalSelfAttention(SelfAttention):
    """Causally-masked variant (reference: dinov3_jax/layers/attention.py
    CausalSelfAttention:135 — present in the reference inventory but unused
    by the ViT SSL path; kept for generative/probing heads)."""

    causal: bool = True
