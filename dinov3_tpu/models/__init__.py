"""Model factories from config (reference: dinov3_jax/models/__init__.py).

``build_backbone`` maps the ``student``/``teacher`` config sections onto
``DinoVisionTransformer`` kwargs; the teacher variant drops stochastic depth
(reference:41-49). ConvNeXt lives in ``dinov3_tpu/models/convnext.py``.
"""

from __future__ import annotations

from dinov3_tpu.configs import ConfigNode
from dinov3_tpu.models.convnext import (
    CONVNEXT_SIZES,
    ConvNeXt,
    get_convnext_arch,
)
from dinov3_tpu.models.vision_transformer import (
    ARCHS,
    DinoVisionTransformer,
    vit_7b,
    vit_base,
    vit_giant2,
    vit_huge2,
    vit_large,
    vit_small,
    vit_so400m,
    vit_test,
)
from dinov3_tpu.ops.common import Policy


def _validated_drop_path_mode(s) -> str:
    mode = str(s.get("drop_path_mode", "subset") or "subset")
    if mode not in ("subset", "mask"):
        raise ValueError(
            f"student.drop_path_mode={mode!r}: expected subset|mask"
        )
    return mode


def backbone_kwargs_from_cfg(cfg: ConfigNode, *, teacher: bool = False) -> dict:
    s = cfg.student
    kw = dict(
        patch_size=s.patch_size,
        drop_path_rate=0.0 if teacher else s.drop_path_rate,
        drop_path_mode=_validated_drop_path_mode(s),
        layerscale_init=s.layerscale,
        ffn_layer=s.ffn_layer,
        moe_num_experts=int(s.get("moe_num_experts", 8) or 8),
        moe_top_k=int(s.get("moe_top_k", 2) or 2),
        ffn_ratio=s.ffn_ratio,
        qkv_bias=s.qkv_bias,
        proj_bias=s.proj_bias,
        ffn_bias=s.ffn_bias,
        norm_layer=s.norm_layer,
        n_storage_tokens=s.n_storage_tokens,
        mask_k_bias=s.mask_k_bias,
        untie_cls_and_patch_norms=s.untie_cls_and_patch_norms,
        untie_global_and_local_cls_norm=s.untie_global_and_local_cls_norm,
        in_chans=s.in_chans,
        pos_embed_type=s.pos_embed_type,
        pos_embed_rope_base=s.pos_embed_rope_base,
        pos_embed_rope_min_period=s.pos_embed_rope_min_period,
        pos_embed_rope_max_period=s.pos_embed_rope_max_period,
        pos_embed_rope_normalize_coords=s.pos_embed_rope_normalize_coords,
        pos_embed_rope_shift_coords=None if teacher else s.pos_embed_rope_shift_coords,
        pos_embed_rope_jitter_coords=None if teacher else s.pos_embed_rope_jitter_coords,
        pos_embed_rope_rescale_coords=None if teacher else s.pos_embed_rope_rescale_coords,
        pos_embed_rope_dtype=s.pos_embed_rope_dtype,
    )
    # execution options
    train = cfg.train
    kw["remat"] = {False: "none", True: "blocks"}.get(train.get("checkpointing", False), "none")
    if train.get("checkpointing_full", False):
        kw["remat"] = "full"
    # parallel.remat: non-none values override the train.checkpointing
    # mapping (the merged config cannot distinguish an explicit "none"
    # from the schema default)
    pr = str((cfg.get("parallel") or {}).get("remat", "none") or "none")
    if pr not in ("none", "attn", "blocks", "full"):
        raise ValueError(
            f"parallel.remat={pr!r}: expected none|attn|blocks|full"
        )
    if pr != "none":
        kw["remat"] = pr
    kernels = cfg.get("kernels") or {}
    kw["attn_impl"] = kernels.get("flash_attention", "auto")
    kw["flash_block_q"] = int(kernels.get("flash_block_q", 512) or 512)
    kw["flash_block_kv"] = int(kernels.get("flash_block_kv", 512) or 512)
    from dinov3_tpu.configs.config import (
        live_tuned_fingerprint,
        resolve_flash_min_seq,
        resolve_ring_min_seq,
    )

    kw["flash_min_seq"] = resolve_flash_min_seq(
        kernels.get("flash_min_seq", "auto")
    )
    kw["ring_min_seq"] = resolve_ring_min_seq(
        kernels.get("ring_min_seq", 0),
        live=live_tuned_fingerprint(cfg),
    )
    parallel = cfg.get("parallel") or {}
    kw["seq_parallel"] = int(parallel.get("seq", 1) or 1) > 1
    if kw["remat"] == "attn" and kw["seq_parallel"]:
        import logging

        logging.getLogger("dinov3").warning(
            "remat=attn has no effect under seq parallelism: ring "
            "attention never materializes the [N, N] softmax state "
            "(same for the pallas flash kernel at >=%d tokens)",
            1024,
        )
    kw["pipeline_stages"] = int(parallel.get("pipe", 1) or 1)
    kw["pipeline_microbatches"] = int(parallel.get("pipe_microbatches", 0) or 0)
    kw["scan_layers"] = bool(train.get("scan_layers", False))
    # ZeRO-3 per-block weight stream (ops/block.py): gather each block's
    # sharded weights inside the block stack under the ``zero3_stream``
    # named scope, the matmul weights cast to compute dtype BEFORE the
    # gather (halves the streamed bytes; bitwise-identical because the
    # modules cast at use anyway). Engages only for model-parallel-free
    # zero3 configs (the materialization constraint would undo a
    # tensor/expert split), and never pre-casts under fp8 (the fp8
    # quantizer must see the original fp32 weights).
    from dinov3_tpu.configs.config import zero3_stream_wished

    kw["zero3_stream"] = zero3_stream_wished(cfg)
    # train.low_precision: fp8/int8 delayed-scaling block matmuls
    # (ops/lowp.py). BOTH student and teacher forward through the
    # quantized matmuls (the EMA STORAGE stays fp32 — only the teacher's
    # forward compute is quantized, the same way it already runs bf16);
    # eval builds and the gram teacher never receive a scale collection,
    # so the attr is inert there (the has_variable guard).
    from dinov3_tpu.configs.config import lowp_cfg

    kw["lowp_arm"] = lowp_cfg(cfg)["arm"]
    # fp8 projections inside blocks when the filter regex matches "blocks"
    # (reference config surface: student.fp8_enabled / fp8_filter,
    # ssl_default_config.yaml:121-122). Student only: the EMA teacher's
    # distillation targets stay full precision, like the other
    # student-only training knobs above (drop path, rope augmentation).
    if bool(s.get("fp8_enabled", False)) and not teacher:
        import re

        filt = str(s.get("fp8_filter", "blocks") or "")
        kw["fp8"] = bool(re.search(filt, "blocks")) if filt else True
        if not kw["fp8"]:
            import logging

            logging.getLogger("dinov3").warning(
                "student.fp8_enabled=true but fp8_filter=%r does not match "
                "'blocks' (the supported granularity is the whole block "
                "stack) — fp8 is OFF", filt,
            )

    policy = Policy.from_cfg(cfg.compute_precision)
    kw["dtype"] = policy.compute_dtype
    kw["param_dtype"] = policy.param_dtype
    kw["reduce_dtype"] = policy.reduce_dtype
    kw["probs_dtype"] = policy.probs_dtype
    return kw


def build_backbone(cfg: ConfigNode, *, teacher: bool = False,
                   param_dtype=None):
    """``param_dtype`` overrides the config policy's parameter dtype —
    the training path passes fp32 so masters (and initializer samples)
    never round through bf16 (ssl_meta_arch.py), while eval builds keep
    the recipe's storage dtype."""
    arch = cfg.student.arch
    if arch.startswith("convnext"):
        from dinov3_tpu.configs.config import lowp_cfg

        if lowp_cfg(cfg)["arm"] != "bf16":
            raise ValueError(
                f"train.low_precision.arm={lowp_cfg(cfg)['arm']!r} requires "
                "a ViT backbone (the quantized matmuls live in the "
                "attn/mlp block kernels); student.arch=" + arch)
        from dinov3_tpu.models.convnext import (
            convnext_kwargs_from_cfg,
            get_convnext_arch,
        )

        kw = convnext_kwargs_from_cfg(cfg, teacher=teacher)
        if param_dtype is not None:
            kw["param_dtype"] = param_dtype
        return get_convnext_arch(arch)(**kw)
    if arch not in ARCHS:
        raise ValueError(f"unknown arch {arch!r}; available: {sorted(ARCHS)}")
    kw = backbone_kwargs_from_cfg(cfg, teacher=teacher)
    if param_dtype is not None:
        kw["param_dtype"] = param_dtype
    return ARCHS[arch](**kw)


def build_model_from_cfg(cfg: ConfigNode, only_teacher: bool = False):
    """(student, teacher, embed_dim) — mirrors reference build_model_from_cfg."""
    teacher_model = build_backbone(cfg, teacher=True)
    if only_teacher:
        return teacher_model, teacher_model.embed_dim
    student_model = build_backbone(cfg, teacher=False)
    return student_model, teacher_model, student_model.embed_dim


def build_model_for_eval(cfg: ConfigNode, ckpt_dir: str | None = None):
    """(model, params) for feature extraction / evals.

    Loads the EMA teacher's backbone from a framework checkpoint directory
    (the reference's equivalent imported nonexistent ``dinov3.*`` modules,
    models/__init__.py:81-93 — SURVEY.md §2.2).
    """
    import jax
    import jax.numpy as jnp

    model = build_backbone(cfg, teacher=True)
    S = cfg.crops.global_crops_size
    if isinstance(S, (list, tuple)):
        S = int(S[0])
    example = jnp.zeros((1, S, S, cfg.student.in_chans), jnp.float32)
    import flax.linen as nn

    params = nn.meta.unbox(
        jax.jit(model.init)(jax.random.key(0), example)
    )["params"]
    if ckpt_dir:
        import orbax.checkpoint as ocp

        from ..checkpoint import pytree_restore_args

        with ocp.CheckpointManager(ckpt_dir) as manager:
            step = manager.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoint under {ckpt_dir}")
            abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, params)
            restored = manager.restore(
                step,
                args=ocp.args.Composite(
                    state=pytree_restore_args(
                        {"params": {"teacher": {"backbone": abstract}}}
                    )
                ),
            )
        params = restored["state"]["params"]["teacher"]["backbone"]
    return model, params


__all__ = [
    "ARCHS", "DinoVisionTransformer", "backbone_kwargs_from_cfg",
    "build_backbone", "build_model_from_cfg", "vit_small", "vit_base",
    "vit_large", "vit_so400m", "vit_huge2", "vit_giant2", "vit_7b", "vit_test",
]
