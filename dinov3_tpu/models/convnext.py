"""ConvNeXt backbone, NHWC / bf16 / MXU-friendly.

(reference: dinov3_jax/models/convnext.py — dead code in the reference
tree: never imported by its factory (models/__init__.py:12), a syntax
error in ``forward_features_list`` (:227) and a hard ``raise`` in
``Block.__call__`` (:83) (SURVEY.md §2.2). Re-implemented here as a live
backbone with the same architecture table (tiny/small/base/large,
:303-321) and the same DINO adaptations: mean-pool pseudo-CLS token, a
shared final norm over [cls | patches], and a ``patch_size`` option that
bilinearly resizes the stage-4 feature map onto a ViT-p patch grid so
ConvNeXt students can sit in the same SSL meta-arch (:210-235).

TPU-first choices: channels-last everywhere (stem + downsample convs lower
to MXU matmuls), depthwise 7x7 stays a VPU-friendly ``feature_group_count``
conv, LayerNorm statistics in fp32, stochastic depth as per-sample masks.)
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from dinov3_tpu.ops.common import Policy, part, trunc_normal_init
from dinov3_tpu.ops.drop_path import DropPath
from dinov3_tpu.ops.norms import LayerNorm


class ConvNeXtBlock(nn.Module):
    dim: int
    drop_path_rate: float = 0.0
    layer_scale_init: float | None = 1e-6
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    reduce_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True):
        # x: [B, H, W, C]
        residual = x
        x = nn.Conv(
            self.dim, kernel_size=(7, 7), padding="SAME",
            feature_group_count=self.dim, dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=part(trunc_normal_init(), (None, None, None, "embed")),
            name="dwconv",
        )(x.astype(self.dtype))
        x = LayerNorm(
            param_dtype=self.param_dtype, reduce_dtype=self.reduce_dtype,
            name="norm",
        )(x)
        x = nn.Dense(
            4 * self.dim, dtype=self.dtype, param_dtype=self.param_dtype,
            kernel_init=part(trunc_normal_init(), ("embed", "mlp")),
            name="pwconv1",
        )(x.astype(self.dtype))
        x = nn.gelu(x)
        x = nn.Dense(
            self.dim, dtype=self.dtype, param_dtype=self.param_dtype,
            kernel_init=part(trunc_normal_init(), ("mlp", "embed")),
            name="pwconv2",
        )(x)
        if self.layer_scale_init is not None:
            gamma = self.param(
                "gamma", part(nn.initializers.constant(self.layer_scale_init),
                              ("embed",)),
                (self.dim,), self.param_dtype,
            )
            x = x * gamma.astype(x.dtype)
        x = DropPath(self.drop_path_rate)(x, deterministic=deterministic)
        return residual + x


class ConvNeXt(nn.Module):
    depths: Sequence[int] = (3, 3, 9, 3)
    dims: Sequence[int] = (96, 192, 384, 768)
    drop_path_rate: float = 0.0
    layer_scale_init: float | None = 1e-6
    in_chans: int = 3
    # DINO adaptation: resize final features onto a ViT-style patch grid
    patch_size: int | None = None
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    reduce_dtype: Any = jnp.float32

    @property
    def embed_dim(self) -> int:
        return self.dims[-1]

    @property
    def n_storage_tokens(self) -> int:
        return 0

    def _downsample(self, x, i: int):
        norm_kw = dict(param_dtype=self.param_dtype,
                       reduce_dtype=self.reduce_dtype)
        if i == 0:
            x = nn.Conv(
                self.dims[0], kernel_size=(4, 4), strides=(4, 4),
                dtype=self.dtype, param_dtype=self.param_dtype,
                kernel_init=part(trunc_normal_init(),
                                 (None, None, None, "embed")),
                name="stem_conv",
            )(x.astype(self.dtype))
            return LayerNorm(name="stem_norm", **norm_kw)(x)
        x = LayerNorm(name=f"down{i}_norm", **norm_kw)(x)
        return nn.Conv(
            self.dims[i], kernel_size=(2, 2), strides=(2, 2),
            dtype=self.dtype, param_dtype=self.param_dtype,
            kernel_init=part(trunc_normal_init(), (None, None, None, "embed")),
            name=f"down{i}_conv",
        )(x.astype(self.dtype))

    def _stage(self, x, i: int, dp_rates, deterministic):
        start = sum(self.depths[:i])
        for j in range(self.depths[i]):
            x = ConvNeXtBlock(
                dim=self.dims[i],
                drop_path_rate=float(dp_rates[start + j]),
                layer_scale_init=self.layer_scale_init,
                dtype=self.dtype, param_dtype=self.param_dtype,
                reduce_dtype=self.reduce_dtype,
                name=f"stage{i}_block{j}",
            )(x, deterministic=deterministic)
        return x

    def _dp_rates(self):
        total = sum(self.depths)
        if total <= 1 or self.drop_path_rate == 0.0:
            return [0.0] * total
        return [self.drop_path_rate * k / (total - 1) for k in range(total)]

    def _features(self, x, deterministic, collect: Sequence[int] = ()):
        dp_rates = self._dp_rates()
        collected = {}
        for i in range(4):
            x = self._downsample(x, i)
            x = self._stage(x, i, dp_rates, deterministic)
            if i in collect:
                collected[i] = x
        return x, collected

    def _pseudo_patch_grid(self, feats, h, w):
        """Resize [B, H/32, W/32, C] onto the ViT patch grid H/p x W/p
        (reference convnext.py:253-259)."""
        if self.patch_size is None:
            return feats
        hp, wp = h // self.patch_size, w // self.patch_size
        if feats.shape[1:3] == (hp, wp):
            return feats
        return jax.image.resize(
            feats, (feats.shape[0], hp, wp, feats.shape[-1]),
            method="bilinear",
        ).astype(feats.dtype)

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        masks: jnp.ndarray | None = None,
        *,
        crop_kind: str = "global",
        deterministic: bool = True,
    ) -> dict:
        """Same output contract as DinoVisionTransformer. ``masks`` is
        carried through for API parity; a convnet cannot mask tokens
        mid-stage (iBOT applies to ViT students only, as in the original
        DINOv3)."""
        B, H, W, _ = x.shape
        feats, _ = self._features(x, deterministic)
        feats = self._pseudo_patch_grid(feats, H, W)
        pooled = feats.mean(axis=(1, 2))  # [B, C] pseudo-CLS
        tokens = feats.reshape(B, -1, feats.shape[-1])
        norm = LayerNorm(
            param_dtype=self.param_dtype, reduce_dtype=self.reduce_dtype,
            name="norm",
        )
        x_norm = norm(jnp.concatenate([pooled[:, None, :], tokens], axis=1))
        return {
            "x_norm_clstoken": x_norm[:, 0],
            "x_storage_tokens": x_norm[:, 1:1],
            "x_norm_patchtokens": x_norm[:, 1:],
            "x_prenorm": tokens,
            "masks": masks,
        }

    @nn.compact
    def get_intermediate_layers(
        self,
        x: jnp.ndarray,
        n: int | Sequence[int] = 1,
        reshape: bool = False,
        return_class_token: bool = False,
        norm: bool = True,
    ):
        """(reference convnext.py:269-301; only the final stage has a
        trained norm — earlier stages return raw features, as there.)"""
        B, H, W, _ = x.shape
        take = (
            list(range(4 - n, 4)) if isinstance(n, int) else [int(i) for i in n]
        )
        _, collected = self._features(x, True, collect=take)
        outputs = []
        for i in take:
            feats = collected[i]
            if i == 3:
                feats = self._pseudo_patch_grid(feats, H, W)
            pooled = feats.mean(axis=(1, 2))
            tokens = feats.reshape(B, -1, feats.shape[-1])
            if norm and i == 3:
                normed = LayerNorm(
                    param_dtype=self.param_dtype,
                    reduce_dtype=self.reduce_dtype, name="norm",
                )(jnp.concatenate([pooled[:, None, :], tokens], axis=1))
                pooled, tokens = normed[:, 0], normed[:, 1:]
            if reshape:
                hh, ww = feats.shape[1:3]
                tokens = tokens.reshape(B, hh, ww, -1)
            outputs.append(
                (tokens, pooled) if return_class_token else tokens
            )
        return tuple(outputs)


# architecture table (reference convnext.py:303-321)
CONVNEXT_SIZES = {
    "tiny": dict(depths=(3, 3, 9, 3), dims=(96, 192, 384, 768)),
    "small": dict(depths=(3, 3, 27, 3), dims=(96, 192, 384, 768)),
    "base": dict(depths=(3, 3, 27, 3), dims=(128, 256, 512, 1024)),
    "large": dict(depths=(3, 3, 27, 3), dims=(192, 384, 768, 1536)),
    "test": dict(depths=(1, 1, 2, 1), dims=(8, 16, 32, 64)),
}


def get_convnext_arch(arch_name: str):
    """"convnext_tiny" -> constructor (reference convnext.py:324-334)."""
    size = arch_name.split("_", 1)[1]
    if size not in CONVNEXT_SIZES:
        raise ValueError(
            f"unknown convnext size {size!r} (have {sorted(CONVNEXT_SIZES)})"
        )
    table = CONVNEXT_SIZES[size]

    def ctor(**kwargs):
        args = dict(table)
        args.update(kwargs)
        return ConvNeXt(**args)

    return ctor


def convnext_kwargs_from_cfg(cfg, *, teacher: bool = False) -> dict:
    s = cfg.student
    policy = Policy.from_cfg(cfg.compute_precision)
    return dict(
        drop_path_rate=0.0 if teacher else s.drop_path_rate,
        layer_scale_init=s.layerscale,
        in_chans=s.in_chans,
        patch_size=s.patch_size,
        dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype,
        reduce_dtype=policy.reduce_dtype,
    )
