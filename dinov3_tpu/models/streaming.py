"""Explicit double-buffered ZeRO-3 weight stream over a block stack —
the census/schedule twin of the GSPMD streaming engine.

The default engine (parallel.zero3, train/setup.py) expresses weight
streaming through sharding annotations: the scanned block stack enters
``nn.scan`` sharded over the data axes and each block's weights are
all-gathered inside the compiled while body at use (ops/block.py
``_zero3_stream_trans_in``). WHERE the partitioner places those gathers
relative to the consuming block's compute — and whether the gather of
block i+1 overlaps block i — is then the backend scheduler's decision,
invisible in the annotation-level program.

``streamed_block_scan`` below is the same schedule written EXPLICITLY,
the convention ``make_sharded_update_schedule`` established for the
sharded update engine: a ``lax.scan`` whose carry holds the NEXT block's
already-gathered weights — iteration i issues the gather of block i+1
(named scope ``zero3_prefetch``) before running block i's compute on the
weights gathered one iteration earlier, so the compiled HLO contains the
literal double-buffered gather schedule: every in-loop all-gather except
the priming one is issued a full block of compute ahead of its consumer.
scripts/cost_zero3.py compiles this program for the committed
prefetch-overlap census (the ``prefetch_overlap`` columns of
``utils.hlo_collective_census``), and the stack it streams is the bf16
pre-cast form (``cast_stream_leaves``), so the census prices the bf16
stream the engine asks for rather than whatever dtype placement the
backend's simplifier chose. tests/test_zero3.py pins both its numerics
(bitwise vs a per-block oracle loop) and its census shape.

Liveness is the double-buffer invariant: exactly TWO gathered block
weight sets exist at any point of the forward (current + prefetched),
1/dp of everything else — the "free after use" half of the SimpleFSDP
pattern falls out of the scan carry being overwritten each iteration.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from dinov3_tpu.ops.block import stream_castable_path


def cast_stream_leaves(stack_params: Any, dtype) -> Any:
    """Cast the bf16-streamable leaves (attn/mlp matmul weights — the
    shared ``stream_castable_path`` rule) of a stacked block-param tree
    to the stream dtype, leaving fp32-consumed leaves (norm scales,
    layerscale, MoE router) untouched. Shard-local and elementwise:
    applied BEFORE the scan so the loop constant — and therefore every
    in-loop gather — is in the stream dtype by construction."""
    import jax.tree_util as jtu

    def leaf(path, p):
        if (hasattr(p, "dtype") and stream_castable_path(path)
                and jnp.issubdtype(p.dtype, jnp.floating)):
            if isinstance(p, jax.ShapeDtypeStruct):
                # abstract (compile-only accounting) form
                return jax.ShapeDtypeStruct(p.shape, dtype)
            return p.astype(dtype)
        return p

    return jtu.tree_map_with_path(leaf, stack_params)


def streamed_block_scan(
    block_apply: Callable,
    stack_params: Any,
    x: jnp.ndarray,
    n_blocks: int,
    mesh=None,
    prefetch: bool = True,
):
    """Run ``n_blocks`` blocks over ``x`` with an explicit double-
    buffered weight stream.

    ``block_apply(block_params, x) -> x``: one block's pure apply (e.g.
    a bound ``SelfAttentionBlock.apply``). ``stack_params``: pytree of
    ``[n_blocks, ...]`` leaves, sharded over the data axes on non-layer
    dims (the zero3 layout — the per-block slice is then shard-local
    and only the materialization moves bytes). ``prefetch=True`` is the
    double-buffered schedule (gather i+1 under block i's compute, scope
    ``zero3_prefetch``); ``prefetch=False`` gathers each block at use
    (scope ``zero3_stream``) — the A/B control for the overlap census.
    """
    if mesh is None:
        from dinov3_tpu.parallel.context import get_current_mesh

        mesh = get_current_mesh()
    from dinov3_tpu.parallel.sharding import constrain_replicated

    def gather_block(i, scope):
        def leaf(p):
            s = jax.lax.dynamic_index_in_dim(p, i, 0, keepdims=False)
            return constrain_replicated(s, mesh) if mesh is not None else s

        with jax.named_scope(scope):
            return jax.tree.map(leaf, stack_params)

    if not prefetch:
        def body_at_use(x, i):
            return block_apply(gather_block(i, "zero3_stream"), x), None

        x, _ = jax.lax.scan(body_at_use, x, jnp.arange(n_blocks))
        return x

    # prime the buffer: block 0's weights gathered before the loop
    w0 = gather_block(jnp.asarray(0), "zero3_gather")

    def body(carry, i):
        x, w = carry
        # issue block i+1's gather BEFORE block i's compute — no data
        # dependency between them, so the scheduler can run the gather
        # under the compute (the last iteration re-gathers the final
        # block into a dead carry slot: one wasted gather per pass, the
        # price of a static-shape double buffer)
        w_next = gather_block(
            jnp.minimum(i + 1, n_blocks - 1), "zero3_prefetch")
        x = block_apply(w, x)
        return (x, w_next), None

    (x, _), _ = jax.lax.scan(body, (x, w0), jnp.arange(n_blocks))
    return x


def make_block_apply(block_kwargs: dict, rope=None, seg=None) -> Callable:
    """A deterministic single-block apply for the streamed scan:
    ``apply(block_params, x)`` binds ``SelfAttentionBlock`` with the
    model's own kwargs (pass-granularity convention of the cost
    scripts: eval-mode, no drop-path randomness)."""
    from dinov3_tpu.ops.block import SelfAttentionBlock

    block = SelfAttentionBlock(**block_kwargs)

    def apply(block_params, x):
        return block.apply(
            {"params": block_params}, x, rope, True, None, seg)

    return apply
