"""Explicit double-buffered ZeRO-3 weight stream over a block stack —
the census/schedule twin of the GSPMD streaming engine.

The default engine (parallel.zero3, train/setup.py) expresses weight
streaming through sharding annotations: the scanned block stack enters
``nn.scan`` sharded over the data axes and each block's weights are
all-gathered inside the compiled while body at use (ops/block.py
``_zero3_stream_trans_in``). WHERE the partitioner places those gathers
relative to the consuming block's compute — and whether the gather of
block i+1 overlaps block i — is then the backend scheduler's decision,
invisible in the annotation-level program.

``streamed_block_scan`` below is the same schedule written EXPLICITLY,
the convention ``make_sharded_update_schedule`` established for the
sharded update engine: a ``lax.scan`` whose carry holds the NEXT block's
already-gathered weights — iteration i issues the gather of block i+1
(named scope ``zero3_prefetch``) before running block i's compute on the
weights gathered one iteration earlier, so the compiled HLO contains the
literal double-buffered gather schedule: every in-loop all-gather except
the priming one is issued a full block of compute ahead of its consumer.
scripts/cost_zero3.py compiles this program for the committed
prefetch-overlap census (the ``prefetch_overlap`` columns of
``utils.hlo_collective_census``), and the stack it streams is the bf16
pre-cast form (``cast_stream_leaves``), so the census prices the bf16
stream the engine asks for rather than whatever dtype placement the
backend's simplifier chose. tests/test_zero3.py pins both its numerics
(bitwise vs a per-block oracle loop) and its census shape.

Liveness is the double-buffer invariant: exactly TWO gathered block
weight sets exist at any point of the forward (current + prefetched),
1/dp of everything else — the "free after use" half of the SimpleFSDP
pattern falls out of the scan carry being overwritten each iteration.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from dinov3_tpu.ops.block import stream_castable_path


def cast_stream_leaves(stack_params: Any, dtype) -> Any:
    """Cast the bf16-streamable leaves (attn/mlp matmul weights — the
    shared ``stream_castable_path`` rule) of a stacked block-param tree
    to the stream dtype, leaving fp32-consumed leaves (norm scales,
    layerscale, MoE router) untouched. Shard-local and elementwise:
    applied BEFORE the scan so the loop constant — and therefore every
    in-loop gather — is in the stream dtype by construction."""
    import jax.tree_util as jtu

    def leaf(path, p):
        if (hasattr(p, "dtype") and stream_castable_path(path)
                and jnp.issubdtype(p.dtype, jnp.floating)):
            if isinstance(p, jax.ShapeDtypeStruct):
                # abstract (compile-only accounting) form
                return jax.ShapeDtypeStruct(p.shape, dtype)
            return p.astype(dtype)
        return p

    return jtu.tree_map_with_path(leaf, stack_params)


def prefetch_depth(prefetch: bool | int) -> int:
    """Normalize the stream-prefetch knob to an integer lookahead
    depth: ``False``/0 = gather at use, ``True``/1 = the classic
    double buffer (gather i+1 under block i's compute), ``d >= 2`` = a
    ``d``-deep gather pipeline (the carry holds ``d`` gathered sets —
    liveness grows one block's weights per extra depth). Booleans map
    to 0/1 so every pre-tuner call site keeps its exact schedule; the
    integer form is the tuner's candidate axis
    (``optim.stream_prefetch``, resolve_stream_prefetch)."""
    depth = int(prefetch)
    if depth < 0:
        raise ValueError(f"prefetch depth must be >= 0, got {depth}")
    return depth


def streamed_block_scan(
    block_apply: Callable,
    stack_params: Any,
    x: jnp.ndarray,
    n_blocks: int,
    mesh=None,
    prefetch: bool | int = True,
):
    """Run ``n_blocks`` blocks over ``x`` with an explicit
    ``prefetch``-deep buffered weight stream.

    ``block_apply(block_params, x) -> x``: one block's pure apply (e.g.
    a bound ``SelfAttentionBlock.apply``). ``stack_params``: pytree of
    ``[n_blocks, ...]`` leaves, sharded over the data axes on non-layer
    dims (the zero3 layout — the per-block slice is then shard-local
    and only the materialization moves bytes). ``prefetch`` is the
    integer lookahead depth (``prefetch_depth``): depth 1 (= the old
    ``True``) is the double-buffered schedule — gather i+1 under block
    i's compute, scope ``zero3_prefetch``; depth ``d`` issues block
    i+d's gather there, giving the scheduler ``d`` blocks of compute to
    hide each gather under at the price of ``d`` live gathered weight
    sets. Depth 0 (= the old ``False``) gathers each block at use
    (scope ``zero3_stream``) — the A/B control for the overlap census.
    The gathers are pure movement, so every depth is bitwise-identical
    in values; only the wire schedule changes.
    """
    if mesh is None:
        from dinov3_tpu.parallel.context import get_current_mesh

        mesh = get_current_mesh()
    from dinov3_tpu.parallel.sharding import constrain_replicated

    depth = prefetch_depth(prefetch)

    def gather_block(i, scope):
        def leaf(p):
            s = jax.lax.dynamic_index_in_dim(p, i, 0, keepdims=False)
            return constrain_replicated(s, mesh) if mesh is not None else s

        with jax.named_scope(scope):
            return jax.tree.map(leaf, stack_params)

    if depth == 0:
        def body_at_use(x, i):
            return block_apply(gather_block(i, "zero3_stream"), x), None

        x, _ = jax.lax.scan(body_at_use, x, jnp.arange(n_blocks))
        return x

    # prime the buffer: blocks [0, depth) gathered before the loop
    buf0 = tuple(
        gather_block(jnp.asarray(min(j, n_blocks - 1)), "zero3_gather")
        for j in range(depth))

    def body(carry, i):
        x, buf = carry
        # issue block i+depth's gather BEFORE block i's compute — no
        # data dependency between them, so the scheduler can run the
        # gather under the next ``depth`` blocks of compute (the tail
        # iterations re-gather the final block into dead carry slots:
        # ``depth`` wasted gathers per pass, the price of a
        # static-shape buffer)
        w_next = gather_block(
            jnp.minimum(i + depth, n_blocks - 1), "zero3_prefetch")
        x = block_apply(buf[0], x)
        return (x, buf[1:] + (w_next,)), None

    (x, _), _ = jax.lax.scan(body, (x, buf0), jnp.arange(n_blocks))
    return x


def make_block_apply(block_kwargs: dict, rope=None, seg=None) -> Callable:
    """A deterministic single-block apply for the streamed scan:
    ``apply(block_params, x)`` binds ``SelfAttentionBlock`` with the
    model's own kwargs (pass-granularity convention of the cost
    scripts: eval-mode, no drop-path randomness)."""
    from dinov3_tpu.ops.block import SelfAttentionBlock

    block = SelfAttentionBlock(**block_kwargs)

    def apply(block_params, x):
        return block.apply(
            {"params": block_params}, x, rope, True, None, seg)

    return apply


def pack_stream_buckets(stack_params: Any, n_buckets: int, dp: int):
    """Coalesce the streamable block weights into ``n_buckets``
    equal-sized flat buckets aligned to the block-scan structure.

    ``stack_params``: pytree of stacked ``[n_blocks, ...]`` leaves (pass
    it through ``cast_stream_leaves`` first so the buckets carry the
    bf16 stream form). Bucket ``b`` holds blocks ``[b*g, (b+1)*g)``
    (``g = n_blocks / n_buckets``, which must divide): the streamable
    leaves (``ops/block.py stream_bucket_leaves`` — the same selection
    the per-block ZeRO-3 stream gathers) of those blocks, flattened and
    concatenated in tree order, zero-padded to a multiple of ``dp``.
    Every bucket is the same size (each leaf contributes ``g`` equal
    block slices), so the bucket axis scans — the double-buffer
    convention of ``streamed_block_scan`` lifts from per-block gathers
    to per-bucket gathers unchanged. Returns ``[n_buckets, S_pb]``.
    """
    from dinov3_tpu.ops.block import stream_bucket_leaves

    leaves = stream_bucket_leaves(stack_params)
    if not leaves:
        raise ValueError("stack has no streamable (attn/mlp) leaves")
    n_blocks = leaves[0][1].shape[0]
    if n_blocks % n_buckets:
        raise ValueError(
            f"n_buckets={n_buckets} must divide n_blocks={n_blocks} "
            f"(equal buckets are what makes the bucket axis scannable)"
        )
    g = n_blocks // n_buckets
    dtype = leaves[0][1].dtype
    rows = []
    for b in range(n_buckets):
        flat = jnp.concatenate([
            leaf[b * g:(b + 1) * g].reshape(-1).astype(dtype)
            for _, leaf in leaves
        ])
        rows.append(jnp.pad(flat, (0, (-flat.size) % max(1, dp))))
    return jnp.stack(rows)


def bucketed_stream_scan(
    bucket_shards: jnp.ndarray,
    x: jnp.ndarray,
    mesh=None,
    prefetch: bool | int = True,
    consume_fn: Callable | None = None,
    hierarchical: bool = False,
    staging_order: str = "inter_intra",
):
    """The BUCKETED forward weight-gather schedule, written explicitly —
    ``streamed_block_scan``'s double-buffer convention lifted from
    per-block gathers to per-bucket gathers, as a shard_map island so
    the compiled HLO contains the literal per-bucket ``all_gather``
    (and, under ``jax.grad``, its transpose ``psum_scatter`` inside the
    BACKWARD while loop — the overlap-placement evidence
    ``utils.hlo_collective_placement`` classifies and
    scripts/cost_buckets.py censuses: param gathers ride the forward
    loop, the coalesced grad reduce-scatter of bucket *i* is issued as
    backward leaves bucket *i*'s consume, under bucket *i-1*'s backward
    compute).

    ``bucket_shards``: ``[n_buckets, S_pb]`` from ``pack_stream_buckets``
    (dim 1 sharded over the data axes by the in_spec). ``prefetch`` is
    the integer lookahead depth (``prefetch_depth``; booleans map to
    0/1): depth ``d >= 1`` gathers bucket i+d under bucket i's consume
    (scope ``bucket_prefetch``, priming gathers ``bucket_gather``);
    depth 0 gathers at use (scope ``bucket_stream``) — the A/B
    control. ``consume_fn(w_full, x) -> x`` consumes one gathered
    bucket; the default is a cheap reduction coupling every weight
    element into ``x`` (pass-granularity convention of the cost
    scripts — the census prices the collective schedule, not the block
    math).

    ``hierarchical=True`` replaces each flat all-gather with the
    unified engine's STAGED schedule on a dp×fsdp mesh, the tiers
    released per ``staging_order``'s AG half (parallel/sharding.py
    ``split_staging_order``; the default moves 1/dp shards over the
    slow inter links first, then intra, scopes ``bucket_ag_inter``/
    ``bucket_ag_intra`` — the RS half rides the autodiff transpose
    here), followed by an index-order-restoring reshape so the
    consumed vector is BITWISE the flat gather's device-order concat:
    the options change the wire schedule, never the numerics. With one
    present mesh tier it degrades to the flat gather unchanged.
    """
    if mesh is None:
        from dinov3_tpu.parallel.context import get_current_mesh

        mesh = get_current_mesh()
    from jax.sharding import PartitionSpec as P

    from dinov3_tpu.parallel.context import shard_map_compat
    from dinov3_tpu.parallel.sharding import (
        UPDATE_SHARD_AXES,
        hierarchy_axes,
        split_staging_order,
    )

    axes = tuple(a for a in UPDATE_SHARD_AXES if a in mesh.shape)
    inter, intra = hierarchy_axes(mesh)
    staged = bool(hierarchical and inter and intra)
    ag_first, _ = split_staging_order(staging_order)
    depth = prefetch_depth(prefetch)
    n_buckets = int(bucket_shards.shape[0])
    if consume_fn is None:
        def consume_fn(w, x):
            return x + jnp.mean(w).astype(x.dtype) * x

    def body(shards, x):
        def gather(i, scope):
            s = jax.lax.dynamic_index_in_dim(shards, i, 0, keepdims=False)
            if staged:
                # staged gather, then restore flat device order: the
                # flat tiled gather concats inter-major (device-id
                # order), i.e. a [n_inter, n_intra, cols] raveling —
                # inter-first stacks [n_intra, n_inter, cols] and
                # swaps; intra-first lands inter-major directly
                if ag_first == "inter":
                    with jax.named_scope("bucket_ag_inter"):
                        g = jax.lax.all_gather(s, inter, tiled=False)
                    with jax.named_scope("bucket_ag_intra"):
                        g = jax.lax.all_gather(g, intra, tiled=False)
                    with jax.named_scope(scope):
                        return jnp.swapaxes(g, 0, 1).reshape(-1)
                with jax.named_scope("bucket_ag_intra"):
                    g = jax.lax.all_gather(s, intra, tiled=False)
                with jax.named_scope("bucket_ag_inter"):
                    g = jax.lax.all_gather(g, inter, tiled=False)
                with jax.named_scope(scope):
                    return g.reshape(-1)
            with jax.named_scope(scope):
                return jax.lax.all_gather(s, axes, tiled=True)

        if depth == 0:
            def at_use(x, i):
                return consume_fn(gather(i, "bucket_stream"), x), None

            x, _ = jax.lax.scan(at_use, x, jnp.arange(n_buckets))
            return x

        # prime the buffer: buckets [0, depth) gathered before the loop
        buf0 = tuple(
            gather(jnp.asarray(min(j, n_buckets - 1)), "bucket_gather")
            for j in range(depth))

        def step(carry, i):
            x, buf = carry
            # issue bucket i+depth's gather BEFORE consuming bucket i —
            # the streamed_block_scan lookahead, per bucket
            w_next = gather(
                jnp.minimum(i + depth, n_buckets - 1), "bucket_prefetch")
            x = consume_fn(buf[0], x)
            return (x, buf[1:] + (w_next,)), None

        (x, _), _ = jax.lax.scan(step, (x, buf0), jnp.arange(n_buckets))
        return x

    return shard_map_compat(
        body, mesh=mesh,
        in_specs=(P(None, axes), P()),
        out_specs=P(),
        check_vma=False,
    )(bucket_shards, x)
