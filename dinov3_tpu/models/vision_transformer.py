"""DINOv3 Vision Transformer, TPU-first.

Capabilities match the reference model
(dinov3_jax/models/vision_transformer.py:56-408): patch embed -> [CLS +
storage/register tokens + patches] -> N RoPE-attention blocks -> norm(s),
with masked-token replacement, untied CLS/patch and global/local-CLS norms,
intermediate-layer extraction, and the vit_small..vit_7b size ladder.

Redesigned rather than ported:
- crops are *batched per resolution* ([n_crops*B, H, W, 3]) instead of
  python lists of arrays, so one jitted forward per resolution serves any
  number of crops (the reference's list-forward could not jit across shapes,
  SURVEY.md §7.3);
- one RoPE table per forward, shared by all blocks (the reference recomputed
  it per block per crop, reference:212-217);
- optional ``nn.scan`` over the layer stack for O(1) compile time at depth
  40, and ``nn.remat`` for activation rematerialization;
- stochastic depth keeps the reference's batch-subset semantics (dropped
  samples skip branch compute) via a static keep count — see
  ops/drop_path.py; a per-sample mask variant remains as
  ``drop_path_mode="mask"``.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from dinov3_tpu.ops.block import (
    ScanBlockAdapter,
    SelfAttentionBlock,
    remat_block_cls,
)
from dinov3_tpu.ops.common import canonical_dtype, part
from dinov3_tpu.ops.norms import make_norm_layer
from dinov3_tpu.ops.patch_embed import PatchEmbed
from dinov3_tpu.ops.rope import (
    rope_periods,
    rope_sincos,
    rope_with_identity_prefix,
)


class _CollectScanBlock(nn.Module):
    """Scan adapter that also fills a [K, B, N, D] buffer with the outputs
    of the requested layers (carry = (x, buffer); ``i`` is the layer index
    scanned over). Only K requested layers are kept — stacking all L
    outputs as scan ys would cost L/K more activation memory at eval time.
    Param path matches ScanBlockAdapter ("blocks"/"block"), so the same
    trained params serve both applies. ``dp_plan`` as in
    ScanBlockAdapter (None on the collect path, which is eval-only)."""

    block_kwargs: dict
    collect_idx: tuple  # static, sorted
    remat: str = "none"
    zero3_stream: bool = False
    stream_dtype: Any = None

    @nn.compact
    def __call__(self, carry, i, dp_plan, rope, deterministic: bool):
        x, buf = carry
        x = remat_block_cls(
            self.remat, self.zero3_stream, self.stream_dtype,
            stream_init=self.is_initializing(),
            lowp_arm=self.block_kwargs.get("lowp_arm", "bf16"),
        )(
            **self.block_kwargs, name="block"
        )(x, rope, deterministic, dp_plan)
        hit = (jnp.asarray(self.collect_idx) == i)[:, None, None, None]
        buf = jnp.where(hit, x[None].astype(buf.dtype), buf)
        return (x, buf), None


class DinoVisionTransformer(nn.Module):
    patch_size: int = 16
    in_chans: int = 3
    embed_dim: int = 768
    n_blocks: int = 12
    num_heads: int = 12
    ffn_ratio: float = 4.0
    qkv_bias: bool = True
    proj_bias: bool = True
    ffn_bias: bool = True
    drop_path_rate: float = 0.0
    drop_path_mode: str = "subset"  # subset (reference semantics) | mask
    layerscale_init: float | None = None
    norm_layer: str = "layernorm"
    ffn_layer: str = "mlp"
    n_storage_tokens: int = 0
    mask_k_bias: bool = False
    untie_cls_and_patch_norms: bool = False
    untie_global_and_local_cls_norm: bool = False
    # RoPE
    pos_embed_type: str = "rope"
    pos_embed_rope_base: float | None = 100.0
    pos_embed_rope_min_period: float | None = None
    pos_embed_rope_max_period: float | None = None
    pos_embed_rope_normalize_coords: str = "separate"
    pos_embed_rope_shift_coords: float | None = None
    pos_embed_rope_jitter_coords: float | None = None
    pos_embed_rope_rescale_coords: float | None = None
    pos_embed_rope_dtype: str = "fp32"
    # execution
    attn_impl: str = "auto"
    flash_block_q: int = 512   # kernels.flash_block_q/kv caps
    flash_block_kv: int = 512
    flash_min_seq: int = 0     # kernels.flash_min_seq; 0 = ops default
    ring_min_seq: int = 0      # kernels.ring_min_seq; 0 = ops default
    seq_parallel: bool = False
    scan_layers: bool = False
    pipeline_stages: int = 1       # >1: GPipe pipeline over the pipe axis
    pipeline_microbatches: int = 0  # 0 = pipeline_stages
    fp8: bool = False              # fp8 projections inside blocks
    moe_num_experts: int = 8       # only used when ffn_layer == "moe"
    moe_top_k: int = 2
    # ZeRO-3 per-block weight stream (ops/block.py remat_block_cls):
    # materialize each block's sharded weights inside the block stack —
    # under nn.scan the all-gather sits inside the compiled while body,
    # matmul weights cast to compute dtype BEFORE the gather. Set from
    # parallel.zero3 by build_backbone (models/__init__.py); inert
    # without a sharded mesh.
    zero3_stream: bool = False
    # train.low_precision.arm: fp8/int8 delayed-scaling block matmuls
    # (ops/lowp.py); scales arrive as the read-only "lowp" variable
    # collection and the bf16 arm is today's bitwise-unchanged path
    lowp_arm: str = "bf16"
    remat: str = "none"  # none | blocks | full
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    reduce_dtype: Any = jnp.float32
    probs_dtype: Any = None  # attention-probability storage (None = fp32)

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    # ---------------- token preparation ----------------

    def _token_embedder(self):
        """Create the patch-embed module + token params ONCE per apply —
        the packed forward embeds the global and local crops with the
        same instances (a second creation would collide on names)."""
        patch_embed = PatchEmbed(
            embed_dim=self.embed_dim, patch_size=self.patch_size,
            in_chans=self.in_chans, dtype=self.dtype,
            param_dtype=self.param_dtype, name="patch_embed",
        )
        mask_token = self.param(
            "mask_token", part(nn.initializers.zeros, ("embed",)),
            (self.embed_dim,), self.param_dtype,
        )
        cls_token = self.param(
            "cls_token", part(nn.initializers.normal(0.02), (None, None, "embed")),
            (1, 1, self.embed_dim), self.param_dtype,
        )
        storage = None
        if self.n_storage_tokens > 0:
            storage = self.param(
                "storage_tokens",
                part(nn.initializers.normal(0.02), (None, None, "embed")),
                (1, self.n_storage_tokens, self.embed_dim), self.param_dtype,
            )
        return patch_embed, mask_token, cls_token, storage

    def _embed_tokens(self, embedder, x, masks):
        """[B, H, W, C] -> ([B, 1+S+T, D], (h, w)). masks: [B, T] bool."""
        patch_embed, mask_token, cls_token, storage = embedder
        B = x.shape[0]
        h, w = x.shape[1] // self.patch_size, x.shape[2] // self.patch_size
        tokens = patch_embed(x)
        if masks is not None:
            tokens = jnp.where(
                masks[..., None], mask_token.astype(tokens.dtype), tokens
            )
        parts = [jnp.broadcast_to(cls_token.astype(tokens.dtype),
                                  (B, 1, self.embed_dim))]
        if storage is not None:
            parts.append(jnp.broadcast_to(storage.astype(tokens.dtype),
                                          (B, self.n_storage_tokens, self.embed_dim)))
        parts.append(tokens)
        return jnp.concatenate(parts, axis=1), (h, w)

    def _prepare_tokens(self, x, masks):
        return self._embed_tokens(self._token_embedder(), x, masks)

    def _rope_table(self, h: int, w: int, deterministic: bool,
                    aug: dict | None = None):
        if self.pos_embed_type != "rope":
            return None
        periods = rope_periods(
            self.head_dim,
            base=self.pos_embed_rope_base,
            min_period=self.pos_embed_rope_min_period,
            max_period=self.pos_embed_rope_max_period,
        )
        rng = None
        augmenting = any(
            a is not None for a in (
                self.pos_embed_rope_shift_coords,
                self.pos_embed_rope_jitter_coords,
                self.pos_embed_rope_rescale_coords,
            )
        )
        if not deterministic and augmenting and aug is None:
            rng = self.make_rng("rope")
        sin, cos = rope_sincos(
            h, w, periods,
            normalize=self.pos_embed_rope_normalize_coords,
            rng=rng,
            shift=self.pos_embed_rope_shift_coords,
            jitter=self.pos_embed_rope_jitter_coords,
            rescale=self.pos_embed_rope_rescale_coords,
            dtype=canonical_dtype(self.pos_embed_rope_dtype),
            aug=aug if not deterministic else None,
        )
        # full-length table (identity rows for CLS/storage tokens): the
        # per-block apply becomes one fused fma, no token slice/concat
        return rope_with_identity_prefix(sin, cos, 1 + self.n_storage_tokens)

    # ---------------- layer stack ----------------

    def _block_kwargs(self):
        return dict(
            dim=self.embed_dim, num_heads=self.num_heads,
            ffn_ratio=self.ffn_ratio, ffn_layer=self.ffn_layer,
            norm_layer=self.norm_layer, qkv_bias=self.qkv_bias,
            proj_bias=self.proj_bias, ffn_bias=self.ffn_bias,
            drop_path_rate=self.drop_path_rate,
            drop_path_mode=self.drop_path_mode,
            layerscale_init=self.layerscale_init,
            mask_k_bias=self.mask_k_bias, attn_impl=self.attn_impl,
            flash_block_q=self.flash_block_q,
            flash_block_kv=self.flash_block_kv,
            flash_min_seq=self.flash_min_seq,
            ring_min_seq=self.ring_min_seq,
            seq_parallel=self.seq_parallel, fp8=self.fp8,
            lowp_arm=self.lowp_arm,
            moe_num_experts=self.moe_num_experts, moe_top_k=self.moe_top_k,
            dtype=self.dtype, param_dtype=self.param_dtype,
            reduce_dtype=self.reduce_dtype, probs_dtype=self.probs_dtype,
        )

    def _run_blocks(self, x, rope, deterministic, collect: Sequence[int] = (),
                    plan: dict | None = None, seg=None):
        """Run the stack; optionally collect outputs of the listed layers.

        Every path composes with every other feature: MoE aux losses ride
        the "losses" collection through scan/vmap (``variable_axes``), and
        the pipeline collects intermediate layers through per-stage
        buffers (parallel/pipeline.py).

        ``plan``: the pass's stacked drop-path plan ({"idx": [L, 2, keep]}
        or {"keep": [L, 2, B]}, rng/plan.py). The scanned stack consumes
        it as per-layer scan inputs (``in_axes=0`` — a dynamic-slice of
        the carried stack, not a folded key); the unrolled stack as
        static slices. The pipeline path keeps the legacy per-stage rng
        threading (the meta-arch never hands it a plan).

        ``seg``: [B, N] segment ids of the crop-packed batch — broadcast
        to every block like rope (not supported on the pipeline path;
        the meta arch falls back to two passes there)."""
        collected = {}
        # ZeRO-3 stream: bf16 pre-cast for the matmul weights, unless
        # fp8 owns the cast point (the quantizer reads the fp32 masters)
        stream_dtype = None if self.fp8 else self.dtype
        if self.pipeline_stages > 1:
            from dinov3_tpu.parallel.pipeline import PipelinedBlocks

            if self.lowp_arm != "bf16":
                raise ValueError(
                    "train.low_precision is not supported under pipeline "
                    "parallelism (per-stage scale plumbing is not wired); "
                    "set train.low_precision.arm=bf16")
            if seg is not None:
                raise ValueError(
                    "crop packing is not supported under pipeline "
                    "parallelism (the meta arch falls back to the "
                    "two-pass student forward there)")
            x, collected = PipelinedBlocks(
                block_kwargs=self._block_kwargs(),
                n_blocks=self.n_blocks,
                n_stages=self.pipeline_stages,
                n_microbatches=self.pipeline_microbatches,
                remat=self.remat,
                name="pipeline",
            )(x, rope, deterministic, collect=tuple(sorted(collect)))
        elif self.scan_layers and not collect:
            scanned = nn.scan(
                ScanBlockAdapter,
                # "lowp": per-layer delayed scales ([L] per kernel) ride
                # the scan like the stacked params — each iteration sees
                # its own layer's scalar scale (ops/lowp.py)
                variable_axes={"params": 0, "losses": 0, "lowp": 0},
                split_rngs={"params": True, "drop_path": True, "dropout": True},
                in_axes=(0 if plan is not None else nn.broadcast,
                         nn.broadcast, nn.broadcast, nn.broadcast),
                length=self.n_blocks,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(block_kwargs=self._block_kwargs(), remat=self.remat,
              zero3_stream=self.zero3_stream, stream_dtype=stream_dtype,
              name="blocks")
            x, _ = scanned(x, plan, rope, deterministic, seg)
        elif self.scan_layers:
            take = tuple(sorted(collect))
            scanned = nn.scan(
                _CollectScanBlock,
                variable_axes={"params": 0, "losses": 0, "lowp": 0},
                split_rngs={"params": True, "drop_path": True, "dropout": True},
                in_axes=(0, 0 if plan is not None else nn.broadcast,
                         nn.broadcast, nn.broadcast),
                length=self.n_blocks,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(block_kwargs=self._block_kwargs(), collect_idx=take,
              remat=self.remat, zero3_stream=self.zero3_stream,
              stream_dtype=stream_dtype, name="blocks")
            buf0 = jnp.zeros((len(take),) + x.shape, x.dtype)
            (x, buf), _ = scanned(
                (x, buf0), jnp.arange(self.n_blocks), plan, rope,
                deterministic
            )
            collected = {i: buf[k] for k, i in enumerate(take)}
        else:
            from dinov3_tpu.rng.plan import plan_layer_slice

            for i in range(self.n_blocks):
                x = remat_block_cls(
                    self.remat, self.zero3_stream, stream_dtype,
                    stream_init=self.is_initializing(),
                    lowp_arm=self.lowp_arm,
                )(
                    **self._block_kwargs(), name=f"blocks_{i}"
                )(x, rope, deterministic, plan_layer_slice(plan, i), seg)
                if i in collect:
                    collected[i] = x
        return x, collected

    # ---------------- heads/norms ----------------

    def _make_norms(self):
        """Create final-norm modules once; during init, touch the untied ones
        on a dummy so their params exist for later train-mode applies."""
        norm_kw = dict(param_dtype=self.param_dtype, reduce_dtype=self.reduce_dtype)
        norms = {"norm": make_norm_layer(self.norm_layer, name="norm", **norm_kw)}
        if self.untie_cls_and_patch_norms:
            norms["cls_norm"] = make_norm_layer(
                self.norm_layer, name="cls_norm", **norm_kw
            )
        if self.untie_global_and_local_cls_norm:
            norms["local_cls_norm"] = make_norm_layer(
                self.norm_layer, name="local_cls_norm", **norm_kw
            )
        if self.is_initializing():
            dummy = jnp.zeros((1, 1, self.embed_dim), self.dtype)
            for n in norms.values():
                n(dummy)
        return norms

    def _final_norms(self, x, norms, *, crop_kind: str, deterministic: bool):
        n_prefix = 1 + self.n_storage_tokens
        norm = norms["norm"]
        if self.untie_cls_and_patch_norms or self.untie_global_and_local_cls_norm:
            if (
                self.untie_global_and_local_cls_norm
                and not deterministic
                and crop_kind == "local"
            ):
                cls_norm = norms["local_cls_norm"]
            elif self.untie_cls_and_patch_norms:
                cls_norm = norms["cls_norm"]
            else:
                cls_norm = norm
            x_cls_reg = cls_norm(x[:, :n_prefix])
            x_patch = norm(x[:, n_prefix:])
        else:
            xn = norm(x)
            x_cls_reg, x_patch = xn[:, :n_prefix], xn[:, n_prefix:]
        return x_cls_reg, x_patch

    # ---------------- public API ----------------

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        masks: jnp.ndarray | None = None,
        *,
        crop_kind: str = "global",
        deterministic: bool = True,
        rng_plan: dict | None = None,
        local_crops: jnp.ndarray | None = None,
    ) -> dict:
        """Forward a batch of same-resolution crops.

        x: [B, H, W, C]; masks: optional [B, T] bool (T = H*W/p^2).
        ``rng_plan``: this pass's precomputed randomness
        ({"drop_path": ..., "rope": ...}, rng/plan.py) — when given, the
        forward consumes plan slices and never calls ``make_rng``.
        Returns the reference's feature dict (vision_transformer.py:236-243):
        x_norm_clstoken [B, D], x_storage_tokens [B, S, D],
        x_norm_patchtokens [B, T, D], x_prenorm, masks.

        ``local_crops``: optional [n_l*B, h, w, C] — the crop-packed
        single-pass engine (ops/packing.py, model.crop_packing): local
        sequences are packed k-per-row into global-length rows and run
        through ONE block stack with the globals, under segment-masked
        attention and per-segment RoPE. The returned dict then also
        carries "local_cls" [n_l*B, D] (and "local_storage_tokens");
        ``rng_plan["rope"]`` is the nested {"global": ..., "local": ...}
        per-table form there.
        """
        rng_plan = rng_plan or {}
        norms = self._make_norms()
        if local_crops is not None:
            return self._packed_forward(
                x, masks, local_crops, norms, deterministic, rng_plan)
        tokens, (h, w) = self._prepare_tokens(x, masks)
        rope = self._rope_table(h, w, deterministic,
                                aug=rng_plan.get("rope"))
        out, _ = self._run_blocks(tokens, rope, deterministic,
                                  plan=rng_plan.get("drop_path"))
        x_cls_reg, x_patch = self._final_norms(
            out, norms, crop_kind=crop_kind, deterministic=deterministic
        )
        return {
            "x_norm_clstoken": x_cls_reg[:, 0],
            "x_storage_tokens": x_cls_reg[:, 1:],
            "x_norm_patchtokens": x_patch,
            "x_prenorm": out,
            "masks": masks,
        }

    def _packed_forward(self, x, masks, local_crops, norms, deterministic,
                        rng_plan):
        """Crop-packed single-pass student forward (ops/packing.py).

        One block scan over [2B + P, N_g] rows — the ViT-L weight stack
        streams from HBM once per direction instead of twice, and the
        ~37-token local rows disappear into well-tiled global-length
        rows (the ISSUE-4 engine; oracle = the two-pass path behind
        ``model.crop_packing=false``). Per-token math is identical to
        the two-pass oracle: packing only changes which rows share an
        attention call, and segments are attention-isolated, so
        packed-vs-oracle equivalence holds to float reassociation
        (pinned in tests/test_crop_packing.py).
        """
        from dinov3_tpu.ops.packing import (
            assemble_packed_batch,
            make_packed_layout,
            pack_local_rows,
            packed_segment_ids,
            split_packed_output,
        )
        from dinov3_tpu.parallel.sharding import (
            constrain_packed_rows,
            packed_row_groups,
        )

        embedder = self._token_embedder()
        g_tokens, (hg, wg) = self._embed_tokens(embedder, x, masks)
        l_tokens, (hl, wl) = self._embed_tokens(embedder, local_crops, None)
        n_prefix = 1 + self.n_storage_tokens
        layout = make_packed_layout(
            n_global_rows=g_tokens.shape[0], n_local=l_tokens.shape[0],
            seq_global=g_tokens.shape[1], seq_local=l_tokens.shape[1],
            n_prefix=n_prefix, groups=packed_row_groups(),
        )
        if layout.k < 2:
            raise ValueError(
                f"crop packing needs k >= 2 local sequences per global "
                f"row (N_g={layout.seq_global}, N_l={layout.seq_local}); "
                "the meta arch guards this and falls back to two passes")
        with jax.named_scope("crop_pack"):
            packed = pack_local_rows(l_tokens, layout)
            tokens = constrain_packed_rows(
                assemble_packed_batch(g_tokens, packed, layout))
        seg = jnp.asarray(packed_segment_ids(layout))
        rope = self._packed_rope(layout, (hg, wg), (hl, wl), deterministic,
                                 rng_plan.get("rope"))
        out, _ = self._run_blocks(tokens, rope, deterministic,
                                  plan=rng_plan.get("drop_path"), seg=seg)
        with jax.named_scope("crop_unpack"):
            g_rows, p_rows = split_packed_output(out, layout)
            l_tok = p_rows[:, : layout.k * layout.seq_local, :]
            l_prefix = l_tok.reshape(
                layout.n_packed_rows * layout.k, layout.seq_local, -1
            )[: layout.n_local, :n_prefix]
        x_cls_reg, x_patch = self._final_norms(
            g_rows, norms, crop_kind="global", deterministic=deterministic
        )
        # the local-CLS norm choice _final_norms would make for
        # crop_kind="local" (norms are per-token, so norm-after-extract
        # == the oracle's extract-after-norm)
        if self.untie_global_and_local_cls_norm and not deterministic:
            local_norm = norms["local_cls_norm"]
        elif self.untie_cls_and_patch_norms:
            local_norm = norms["cls_norm"]
        else:
            local_norm = norms["norm"]
        l_cls_reg = local_norm(l_prefix)
        return {
            "x_norm_clstoken": x_cls_reg[:, 0],
            "x_storage_tokens": x_cls_reg[:, 1:],
            "x_norm_patchtokens": x_patch,
            "x_prenorm": out,
            "masks": masks,
            "local_cls": l_cls_reg[:, 0],
            "local_storage_tokens": l_cls_reg[:, 1:],
        }

    def _packed_rope(self, layout, global_hw, local_hw, deterministic,
                     rope_plan):
        """Per-row (sin, cos) tables for the packed batch, or None.

        ``rope_plan``: the packed pass's nested aug-factor dict
        ({"global": ..., "local": ...}, rng/plan.py) — each sub-table
        consumes its own lane, bitwise-identical to the factors the
        two-pass oracle's global/local passes would consume. On the
        legacy rng path each ``_rope_table`` call draws its own
        ``make_rng`` fold, mirroring the oracle's two per-pass draws.
        """
        if self.pos_embed_type != "rope":
            return None
        rope_plan = rope_plan or {}
        from dinov3_tpu.ops.rope import rope_packed_rows

        g_table = self._rope_table(*global_hw, deterministic,
                                   aug=rope_plan.get("global"))
        l_table = self._rope_table(*local_hw, deterministic,
                                   aug=rope_plan.get("local"))
        return rope_packed_rows(g_table, l_table, layout)

    @nn.compact
    def packed_feature_forward(self, patches, coords, prefix_idx, seg):
        """Serving-time forward over host-packed multi-image planes.

        The continuous-packing serve engine (serve/engine.py) admits
        variable-resolution images into fixed token-budget rows on the
        host; this method is the ONE fixed-shape device program those
        rows run through — deterministic (no student rng plan, no
        drop-path, no RoPE augmentation), segment-masked like the
        crop-packed trainer (``_packed_forward``), with per-TOKEN RoPE
        computed in-program from a host coordinate plane because packed
        segments carry arbitrary (h, w) patch grids rather than the
        trainer's two static crop resolutions.

        patches: [R, N, p, p, C] host-patchified pixels (zeros at
          prefix/pad slots) — each [p, p, C] patch keeps PatchEmbed's
          row-major inner layout, so embedding them as R*N single-patch
          images through the SAME PatchEmbed module reproduces the
          full-image unfold+matmul bitwise (ops/patch_embed.py).
        coords: [R, N, 2] f32 patch-center coordinates in [-1, 1]
          (ops/rope.py patch_coords math per segment); zeros at
          prefix/pad slots — angle 0 is sin 0 / cos 1, the identity
          rotation ``rope_with_identity_prefix`` gives prefix tokens.
        prefix_idx: [R, N] int32 — 0 = the slot holds the CLS token,
          s in [1, S] = storage token s-1, -1 = patch or pad slot.
        seg: [R, N] int32 segment ids, -1 = pad (ops/packing.py
          conventions: pads attend only among themselves).

        Returns {"cls_rows": [R, N, D], "patch_rows": [R, N, D]} — the
        block-stack output normed with the CLS norm and the patch norm
        respectively (the ``_final_norms`` crop_kind="global"
        deterministic selection; norms are per-token, so norming the
        full plane and extracting per segment afterwards equals the
        oracle's extract-then-norm). Per-segment CLS/pooled-patch
        extraction happens engine-side (serve_extract named scope).
        """
        patch_embed, _, cls_token, storage = self._token_embedder()
        norms = self._make_norms()
        R, N = seg.shape
        p, C = self.patch_size, self.in_chans
        with jax.named_scope("serve_pack"):
            tok = patch_embed(patches.reshape(R * N, p, p, C))
            tok = tok.reshape(R, N, self.embed_dim)
            # zero the pad slots (PatchEmbed of a zero patch is the
            # bias vector, not zero) and inject the prefix params
            is_prefix = prefix_idx >= 0
            tok = jnp.where((seg >= 0)[..., None] & ~is_prefix[..., None],
                            tok, jnp.zeros((), tok.dtype))
            table = cls_token[0]
            if storage is not None:
                table = jnp.concatenate([table, storage[0]], axis=0)
            pre = jnp.take(table.astype(tok.dtype),
                           jnp.clip(prefix_idx, 0, table.shape[0] - 1),
                           axis=0)
            tok = jnp.where(is_prefix[..., None], pre, tok)
        rope = self._serve_rope(coords)
        out, _ = self._run_blocks(tok, rope, True, seg=seg)
        cls_norm = (norms["cls_norm"] if self.untie_cls_and_patch_norms
                    else norms["norm"])
        return {"cls_rows": cls_norm(out), "patch_rows": norms["norm"](out)}

    def _serve_rope(self, coords):
        """Per-token (sin, cos) tables ([R, N, head_dim] x2) from a host
        coordinate plane — the same angle math as ``rope_sincos``
        (elementwise over the same f32 values, so real patch
        coordinates reproduce the oracle's table bitwise and zero
        coordinates reproduce the identity prefix rows bitwise),
        consumed by ``rope_apply_full``'s 3-D per-row path."""
        if self.pos_embed_type != "rope":
            return None
        import math

        periods = rope_periods(
            self.head_dim,
            base=self.pos_embed_rope_base,
            min_period=self.pos_embed_rope_min_period,
            max_period=self.pos_embed_rope_max_period,
        )
        angles = (2.0 * math.pi * coords[..., None]
                  / periods[None, None, None, :])
        angles = angles.reshape(*coords.shape[:2], -1)
        angles = jnp.concatenate([angles, angles], axis=-1)
        dtype = canonical_dtype(self.pos_embed_rope_dtype)
        return jnp.sin(angles).astype(dtype), jnp.cos(angles).astype(dtype)

    @nn.compact
    def get_intermediate_layers(
        self,
        x: jnp.ndarray,
        n: int | Sequence[int] = 1,
        *,
        reshape: bool = False,
        return_class_token: bool = False,
        return_extra_tokens: bool = False,
        norm: bool = True,
    ):
        """Eval-time feature extraction (reference:280-312, with its reshape
        and index typos fixed). Works on every block-stack layout,
        including the pipelined one (stage-owned collect buffers,
        parallel/pipeline.py)."""
        tokens, (h, w) = self._prepare_tokens(x, None)
        rope = self._rope_table(h, w, True)
        take = (
            list(range(self.n_blocks - n, self.n_blocks))
            if isinstance(n, int) else list(n)
        )
        bad = [i for i in take if not 0 <= i < self.n_blocks]
        if bad:
            raise ValueError(
                f"layer indices {bad} out of range for {self.n_blocks} "
                "blocks"
            )
        _, collected = self._run_blocks(tokens, rope, True, collect=take)
        outputs = [collected[i] for i in take]
        n_prefix = 1 + self.n_storage_tokens
        if norm:
            normed = []
            norm_kw = dict(param_dtype=self.param_dtype, reduce_dtype=self.reduce_dtype)
            norm_l = make_norm_layer(self.norm_layer, name="norm", **norm_kw)
            cls_l = (
                make_norm_layer(self.norm_layer, name="cls_norm", **norm_kw)
                if self.untie_cls_and_patch_norms else None
            )
            for out in outputs:
                if cls_l is not None:
                    normed.append(jnp.concatenate(
                        [cls_l(out[:, :n_prefix]), norm_l(out[:, n_prefix:])], axis=1
                    ))
                else:
                    normed.append(norm_l(out))
            outputs = normed
        class_tokens = [o[:, 0] for o in outputs]
        extra = [o[:, 1:n_prefix] for o in outputs]
        patches = [o[:, n_prefix:] for o in outputs]
        if reshape:
            B = x.shape[0]
            patches = [
                p.reshape(B, h, w, -1).transpose(0, 3, 1, 2) for p in patches
            ]
        if not return_class_token and not return_extra_tokens:
            return tuple(patches)
        if return_class_token and not return_extra_tokens:
            return tuple(zip(patches, class_tokens))
        if return_extra_tokens and not return_class_token:
            return tuple(zip(patches, extra))
        return tuple(zip(patches, class_tokens, extra))


# ---------------- size ladder (reference:325-408) ----------------

def _ctor(embed_dim, n_blocks, num_heads, ffn_ratio):
    def build(patch_size: int = 16, **kwargs) -> DinoVisionTransformer:
        if kwargs.get("ffn_ratio") is None:  # None defers to the ladder ratio
            kwargs.pop("ffn_ratio", None)
        args = dict(
            patch_size=patch_size, embed_dim=embed_dim, n_blocks=n_blocks,
            num_heads=num_heads, ffn_ratio=ffn_ratio,
        )
        args.update(kwargs)
        return DinoVisionTransformer(**args)

    return build


vit_small = _ctor(384, 12, 6, 4.0)
vit_base = _ctor(768, 12, 12, 4.0)
vit_large = _ctor(1024, 24, 16, 4.0)
vit_so400m = _ctor(1152, 27, 18, 3.777777778)
vit_huge2 = _ctor(1280, 32, 20, 4.0)
vit_giant2 = _ctor(1536, 40, 24, 4.0)
vit_7b = _ctor(4096, 40, 32, 3.0)
# tiny configs for tests/smoke runs (not in the reference ladder);
# vit_test_big is a distinct-width "teacher" for distillation tests,
# vit_test4 a 4-block stack for 4-stage pipeline validation,
# vit_test40 the 7B *shape* skeleton (40 blocks, ffn_ratio 3.0 — same
# depth/topology as vit_7b at test width) for stress dryruns
vit_test = _ctor(64, 2, 2, 2.0)
vit_test_big = _ctor(96, 3, 2, 2.0)
vit_test4 = _ctor(64, 4, 2, 2.0)
# same depth as vit_test4 at 2x width / 4 heads: the capacity axis of
# the loss-factorial ablations with depth held fixed
vit_test_wide = _ctor(128, 4, 4, 2.0)
vit_test40 = _ctor(64, 40, 2, 3.0)

ARCHS = {
    "vit_small": vit_small, "vit_base": vit_base, "vit_large": vit_large,
    "vit_so400m": vit_so400m, "vit_huge2": vit_huge2,
    "vit_giant2": vit_giant2, "vit_7b": vit_7b, "vit_test": vit_test,
    "vit_test_big": vit_test_big, "vit_test4": vit_test4,
    "vit_test_wide": vit_test_wide, "vit_test40": vit_test40,
}
