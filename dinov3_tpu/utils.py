"""Small shared utilities: parameter counting, loss recording/comparison,
weight dumps.

(reference: dinov3_jax/utils/utils.py ``count_parameters`` — which
contained a live ``IPython.embed()`` (SURVEY.md §2.9) — and the trainer's
declared-but-unwired verification flags ``--record-ref-losses`` /
``--ref-losses-path`` / ``--dump-fsdp-weights``
(dinov3_jax/train/train.py:63-69, never referenced again). Here they all
function; the loss recorder/comparator is the numerical-parity workflow
the reference intended: record per-iteration losses from a trusted run,
then compare a refactored run against them within a tolerance.)
"""

from __future__ import annotations

import json
import logging
from typing import Mapping

import jax
import numpy as np

logger = logging.getLogger("dinov3")


def count_parameters(params, by_top_level: bool = True) -> dict:
    """{submodule: parameter count} plus a ``total`` entry."""
    out: dict = {}
    if by_top_level and isinstance(params, Mapping):
        for key, sub in params.items():
            out[key] = sum(int(np.prod(x.shape))
                           for x in jax.tree.leaves(sub))
    out["total"] = sum(v for k, v in out.items()) if out else sum(
        int(np.prod(x.shape)) for x in jax.tree.leaves(params)
    )
    return out


def format_parameter_counts(counts: dict) -> str:
    width = max(len(k) for k in counts)
    lines = [f"{k:<{width}}  {v / 1e6:10.2f} M" for k, v in counts.items()]
    return "\n".join(lines)


class LossRecorder:
    """Append per-iteration scalar dicts; written as JSON lines."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "w")

    def record(self, iteration: int, metrics: Mapping[str, float]) -> None:
        row = {"iteration": int(iteration)}
        row.update({k: float(v) for k, v in metrics.items()})
        self._f.write(json.dumps(row) + "\n")
        self._f.flush()

    def close(self) -> None:
        self._f.close()


class LossComparator:
    """Compare a run's losses against a recorded file, iteration by
    iteration. ``check`` logs each divergence and returns whether the
    iteration matched; ``summary`` reports the worst deviation."""

    def __init__(self, path: str, rtol: float = 1e-3, atol: float = 1e-4):
        self.rows = {}
        with open(path) as f:
            for line in f:
                row = json.loads(line)
                self.rows[int(row.pop("iteration"))] = row
        self.rtol, self.atol = rtol, atol
        self.worst: tuple = (0.0, None, -1)  # (abs err, key, iteration)
        self.n_checked = 0
        self.n_diverged = 0

    def check(self, iteration: int, metrics: Mapping[str, float]) -> bool:
        ref = self.rows.get(int(iteration))
        if ref is None:
            return True
        self.n_checked += 1
        ok = True
        for key, want in ref.items():
            got = metrics.get(key)
            if got is None:
                continue
            got = float(got)
            err = abs(got - want)
            if err > self.atol + self.rtol * abs(want):
                ok = False
                logger.warning(
                    "loss divergence at iter %d: %s = %.6g, recorded %.6g",
                    iteration, key, got, want,
                )
            if err > self.worst[0]:
                self.worst = (err, key, iteration)
        self.n_diverged += not ok
        return ok

    def summary(self) -> str:
        err, key, it = self.worst
        head = (f"compared {self.n_checked} iterations, "
                f"{self.n_diverged} diverged")
        if key is None:
            return head + "; exact match"
        return head + f"; worst |err| {err:.3g} on {key!r} at iter {it}"


def dump_weights(path: str, params) -> None:
    """Flat ``.npz`` dump of a parameter tree ('/'-joined keys) for offline
    inspection or cross-framework diffing.

    Call from EVERY process of a multi-host run: gathering shards that
    live on other hosts is a collective (all hosts must participate);
    only process 0 writes the file."""
    flat = {}
    for keypath, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        name = "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in keypath
        )
        if hasattr(leaf, "is_fully_addressable") and not leaf.is_fully_addressable:
            from jax.experimental import multihost_utils

            leaf = multihost_utils.process_allgather(leaf, tiled=True)
        # non-writer hosts only participate in the collective; holding a
        # full unsharded copy of every param would OOM memory-tight hosts
        if jax.process_index() == 0:
            flat[name] = np.asarray(leaf)
    if jax.process_index() == 0:
        np.savez(path, **flat)
        logger.info("dumped %d arrays to %s", len(flat), path)


def donation_safe_argnums(argnums: tuple) -> tuple:
    """Gate buffer donation on backends where it is provably unsafe.

    jaxlib <= 0.4.36 XLA:CPU drops the input-output aliasing table when an
    executable is DESERIALIZED from the persistent compilation cache: a
    cache-hit jitted step whose state is donated returns the donated
    inputs' stale buffers as outputs — params/teacher/opt-state come back
    bit-identical to their inputs while non-aliased outputs (metrics, the
    step counter) are correct. Measured in this repo: the self-check's
    "student_updates"/"teacher_ema_moves" probes fail on the second
    same-process build (warm cache) and pass on the first (cold cache);
    dropping donation restores correctness on the warm path.

    Donation on CPU is a memory hint with no semantic value for the test
    suite, so on the affected backend (cpu + persistent cache enabled +
    old jaxlib) this returns ``()``; everywhere else the argnums pass
    through and the TPU step keeps its in-place buffer reuse. Compile-only
    users (cost accounting, HLO census) are unaffected — the bug is in
    execution after deserialization, not in lowering — and may keep
    explicit donation.
    """
    import jaxlib

    try:
        version = tuple(int(x) for x in jaxlib.__version__.split(".")[:3])
    except ValueError:
        return argnums
    if version >= (0, 5, 0):
        return argnums
    if jax.default_backend() != "cpu":
        return argnums
    cache_dir = jax.config.jax_compilation_cache_dir
    if not cache_dir:
        return argnums
    return ()


def respect_jax_platforms_env() -> None:
    """Make ``JAX_PLATFORMS`` authoritative over sitecustomize config pins.

    The axon image's sitecustomize pins ``jax_platforms="axon,cpu"`` via
    ``jax.config``, which outranks the environment variable — so a
    ``JAX_PLATFORMS=cpu`` run of any CLI entry point would still attempt
    (and, when the TPU tunnel is down, hang in) axon backend init. Call
    before first device use from every entry point."""
    import os

    env_plat = os.environ.get("JAX_PLATFORMS", "")
    if env_plat and "axon" not in env_plat:
        try:
            jax.config.update("jax_platforms", env_plat)
        except RuntimeError:
            pass  # backend already initialized; too late to change
