"""Small shared utilities: parameter counting, loss recording/comparison,
weight dumps.

(reference: dinov3_jax/utils/utils.py ``count_parameters`` — which
contained a live ``IPython.embed()`` (SURVEY.md §2.9) — and the trainer's
declared-but-unwired verification flags ``--record-ref-losses`` /
``--ref-losses-path`` / ``--dump-fsdp-weights``
(dinov3_jax/train/train.py:63-69, never referenced again). Here they all
function; the loss recorder/comparator is the numerical-parity workflow
the reference intended: record per-iteration losses from a trusted run,
then compare a refactored run against them within a tolerance.)
"""

from __future__ import annotations

import json
import logging
from typing import Mapping

import jax
import numpy as np

logger = logging.getLogger("dinov3")


def count_parameters(params, by_top_level: bool = True) -> dict:
    """{submodule: parameter count} plus a ``total`` entry."""
    out: dict = {}
    if by_top_level and isinstance(params, Mapping):
        for key, sub in params.items():
            out[key] = sum(int(np.prod(x.shape))
                           for x in jax.tree.leaves(sub))
    out["total"] = sum(v for k, v in out.items()) if out else sum(
        int(np.prod(x.shape)) for x in jax.tree.leaves(params)
    )
    return out


def format_parameter_counts(counts: dict) -> str:
    width = max(len(k) for k in counts)
    lines = [f"{k:<{width}}  {v / 1e6:10.2f} M" for k, v in counts.items()]
    return "\n".join(lines)


class LossRecorder:
    """Append per-iteration scalar dicts; written as JSON lines."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "w")

    def record(self, iteration: int, metrics: Mapping[str, float]) -> None:
        row = {"iteration": int(iteration)}
        row.update({k: float(v) for k, v in metrics.items()})
        self._f.write(json.dumps(row) + "\n")
        self._f.flush()

    def record_batch(self, iterations, names, rows) -> None:
        """Record one flushed telemetry batch: ``rows[j]`` is the
        ``[M]`` metric vector of ``iterations[j]`` with ``names`` as
        column order (telemetry/ring.py RingReader.flush) — the exact
        per-step values the ring stored, so ``--record-losses`` traces
        are identical under async metrics and the per-step oracle."""
        for it, row in zip(iterations, rows):
            self.record(int(it), dict(zip(names, row)))

    def close(self) -> None:
        self._f.close()


class LossComparator:
    """Compare a run's losses against a recorded file, iteration by
    iteration. ``check`` logs each divergence and returns whether the
    iteration matched; ``summary`` reports the worst deviation."""

    def __init__(self, path: str, rtol: float = 1e-3, atol: float = 1e-4):
        self.rows = {}
        with open(path) as f:
            for line in f:
                row = json.loads(line)
                self.rows[int(row.pop("iteration"))] = row
        self.rtol, self.atol = rtol, atol
        self.worst: tuple = (0.0, None, -1)  # (abs err, key, iteration)
        self.n_checked = 0
        self.n_diverged = 0

    def check(self, iteration: int, metrics: Mapping[str, float]) -> bool:
        ref = self.rows.get(int(iteration))
        if ref is None:
            return True
        self.n_checked += 1
        ok = True
        for key, want in ref.items():
            got = metrics.get(key)
            if got is None:
                continue
            got = float(got)
            err = abs(got - want)
            if err > self.atol + self.rtol * abs(want):
                ok = False
                logger.warning(
                    "loss divergence at iter %d: %s = %.6g, recorded %.6g",
                    iteration, key, got, want,
                )
            if err > self.worst[0]:
                self.worst = (err, key, iteration)
        self.n_diverged += not ok
        return ok

    def check_batch(self, iterations, names, rows) -> bool:
        """Check one flushed telemetry batch (see
        ``LossRecorder.record_batch``); returns whether EVERY row
        matched, logging divergences row by row as ``check`` does."""
        ok = True
        for it, row in zip(iterations, rows):
            ok = self.check(int(it), dict(zip(names, row))) and ok
        return ok

    def summary(self) -> str:
        err, key, it = self.worst
        head = (f"compared {self.n_checked} iterations, "
                f"{self.n_diverged} diverged")
        if key is None:
            return head + "; exact match"
        return head + f"; worst |err| {err:.3g} on {key!r} at iter {it}"


def dump_weights(path: str, params) -> None:
    """Flat ``.npz`` dump of a parameter tree ('/'-joined keys) for offline
    inspection or cross-framework diffing.

    Call from EVERY process of a multi-host run: gathering shards that
    live on other hosts is a collective (all hosts must participate);
    only process 0 writes the file."""
    flat = {}
    for keypath, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        name = "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in keypath
        )
        if hasattr(leaf, "is_fully_addressable") and not leaf.is_fully_addressable:
            from jax.experimental import multihost_utils

            leaf = multihost_utils.process_allgather(leaf, tiled=True)
        # non-writer hosts only participate in the collective; holding a
        # full unsharded copy of every param would OOM memory-tight hosts
        if jax.process_index() == 0:
            flat[name] = np.asarray(leaf)
    if jax.process_index() == 0:
        np.savez(path, **flat)
        logger.info("dumped %d arrays to %s", len(flat), path)


def donation_safe_argnums(argnums: tuple) -> tuple:
    """Gate buffer donation on backends where it is provably unsafe.

    jaxlib <= 0.4.36 XLA:CPU drops the input-output aliasing table when an
    executable is DESERIALIZED from the persistent compilation cache: a
    cache-hit jitted step whose state is donated returns the donated
    inputs' stale buffers as outputs — params/teacher/opt-state come back
    bit-identical to their inputs while non-aliased outputs (metrics, the
    step counter) are correct. Measured in this repo: the self-check's
    "student_updates"/"teacher_ema_moves" probes fail on the second
    same-process build (warm cache) and pass on the first (cold cache);
    dropping donation restores correctness on the warm path.

    Donation on CPU is a memory hint with no semantic value for the test
    suite, so on the affected backend (cpu + persistent cache enabled +
    old jaxlib) this returns ``()``; everywhere else the argnums pass
    through and the TPU step keeps its in-place buffer reuse. Compile-only
    users (cost accounting, HLO census) are unaffected — the bug is in
    execution after deserialization, not in lowering — and may keep
    explicit donation.
    """
    import jaxlib

    try:
        version = tuple(int(x) for x in jaxlib.__version__.split(".")[:3])
    except ValueError:
        return argnums
    if version >= (0, 5, 0):
        return argnums
    if jax.default_backend() != "cpu":
        return argnums
    cache_dir = jax.config.jax_compilation_cache_dir
    if not cache_dir:
        return argnums
    return ()


def respect_jax_platforms_env() -> None:
    """Make ``JAX_PLATFORMS`` authoritative over sitecustomize config pins.

    The axon image's sitecustomize pins ``jax_platforms="axon,cpu"`` via
    ``jax.config``, which outranks the environment variable — so a
    ``JAX_PLATFORMS=cpu`` run of any CLI entry point would still attempt
    (and, when the TPU tunnel is down, hang in) axon backend init. Call
    before first device use from every entry point."""
    import os

    env_plat = os.environ.get("JAX_PLATFORMS", "")
    if env_plat and "axon" not in env_plat:
        try:
            jax.config.update("jax_platforms", env_plat)
        except RuntimeError:
            pass  # backend already initialized; too late to change


# ---------------- compiled-HLO copy census (shared by
# scripts/cost_target_phase.py, scripts/cost_rng_copies.py and
# `bench.py --census`) ----------------

_HLO_COMP_HEADER = None  # compiled lazily (re module import kept local)

_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

HLO_COPY_OPS = ("copy", "copy-start", "copy-done", "dynamic-update-slice")


def hlo_non_fusion_lines(hlo_text: str):
    """Yield instruction lines outside fused-computation bodies.

    Instructions at the top level of any non-fusion computation (ENTRY,
    while bodies, conditionals) allocate real buffers; instructions
    inside a ``%fused_computation...`` body do not — the fusion emits
    only its root. This is the allocation-relevant line set for the copy
    census."""
    import re

    global _HLO_COMP_HEADER
    if _HLO_COMP_HEADER is None:
        _HLO_COMP_HEADER = re.compile(r"^(ENTRY\s+)?%?[\w.\-]+\s*\(.*\)\s*->.*\{")
    in_comp = None
    for line in hlo_text.splitlines():
        stripped = line.strip()
        if _HLO_COMP_HEADER.match(stripped):
            in_comp = stripped.split("(")[0].strip().lstrip("%")
            continue
        if stripped == "}":
            in_comp = None
            continue
        if in_comp is not None and "fused" not in in_comp:
            yield stripped


def _hlo_result_shape(line: str):
    """(dtype_str, elems, bytes) of an instruction's result, or None.

    Tuple-shaped results (async copy pairs) take their first leaf."""
    import re

    m = re.search(r"=\s*\(?([a-z]+\d*)\[([\d,]*)\]", line)
    if not m:
        return None
    dtype, dims = m.group(1), m.group(2)
    if dtype not in _HLO_DTYPE_BYTES:
        return None
    elems = 1
    for d in dims.split(","):
        if d:
            elems *= int(d)
    return dtype, elems, elems * _HLO_DTYPE_BYTES[dtype]


def classify_copy(line: str) -> str:
    """Attribution category for one copy-class HLO instruction.

    - "donation_async": ``copy-start``/``copy-done`` pairs — the async
      copies the runtime schedules around donated/aliased buffers and
      cross-memory DMA. (Heuristic by op kind: plain ``copy`` of a
      donated input exists too but is indistinguishable from a layout
      copy in HLO text.)
    - "gather_pack": copies whose op_name metadata places them inside
      the crop-packed engine's pack/unpack assembly (the
      ``crop_pack``/``crop_unpack`` named scopes in
      models/vision_transformer.py _packed_forward, and their
      transposed backward ops, which inherit the scope) — the
      pad/reshape/concat/slice traffic the packing engine introduces,
      attributed so the census ceiling names it instead of silently
      absorbing it.
    - "update_shard": copies inside the sharded update engine's
      flatten/pad/unflatten walk (the ``update_shard_pack``/
      ``update_shard_unpack`` named scopes in
      train/fused_update.py make_sharded_update) — the leaf-layout
      traffic the cross-replica sharding introduces, named for the same
      reason.
    - "telemetry": the async metrics ring's in-place row writes (the
      ``telemetry_ring`` named scope in telemetry/ring.py write_row —
      one [1, M] metrics-row and one [1] iteration-stamp
      dynamic-update-slice per step), attributed so the telemetry
      step's census ceiling names its own cost instead of absorbing it
      into "small" (tests/test_telemetry.py pins the ceiling).
    - "zero3": copies inside the ZeRO-3 engine's materialization sites
      (the ``zero3_gather``/``zero3_stream``/``zero3_prefetch`` named
      scopes — ssl_meta_arch._zero3_gather_params, ops/block.py
      _zero3_stream_trans_in, models/streaming.py) — the layout traffic
      weight streaming introduces, named so the census ceiling
      attributes it instead of absorbing it into "small"/"large".
    - "bucket": copies inside the bucketed collective engine's
      concat/slice walk (the ``bucket_pack``/``bucket_unpack`` named
      scopes in train/fused_update.py make_bucketed_update, and the
      ``bucket_gather``/``bucket_prefetch``/``bucket_stream`` scopes of
      the overlap twin in models/streaming.py) — the leaf→bucket
      assembly traffic coalescing introduces, named for the same reason
      as "update_shard".
    - "serve": copies inside the serve engine's plane assembly,
      per-segment extraction, and donated output ring (the
      ``serve_pack``/``serve_extract``/``serve_ring`` named scopes in
      models/vision_transformer.py packed_feature_forward and
      serve/engine.py make_serve_step, plus the ``serve_dequant``
      int8->bf16 weight expansion scope of quantized engines,
      serve/quant.py) — the token/feature-plane traffic continuous
      packing introduces, attributed so the serve step's census
      ceiling names it (scripts/bench_serve.py pins zero
      unattributed).
    - "rng": u32 results of <= 8 elements — threefry key/counter
      plumbing (keys are u32[2]/u32[4]; fold_in intermediates scalar).
    - "small": any other result of <= 1024 elements (scalar metrics,
      index vectors, centers).
    - "large": activation/weight-shaped copies (> 1024 elements) — a
      structural regression when a new class of these appears.
    """
    if "copy-start" in line or "copy-done" in line:
        return "donation_async"
    if "crop_pack" in line or "crop_unpack" in line:
        return "gather_pack"
    if "update_shard_pack" in line or "update_shard_unpack" in line:
        return "update_shard"
    if "telemetry_ring" in line:
        return "telemetry"
    if ("zero3_gather" in line or "zero3_stream" in line
            or "zero3_prefetch" in line):
        return "zero3"
    if ("bucket_pack" in line or "bucket_unpack" in line
            or "bucket_gather" in line or "bucket_prefetch" in line
            or "bucket_stream" in line):
        return "bucket"
    if ("serve_pack" in line or "serve_extract" in line
            or "serve_ring" in line or "serve_dequant" in line):
        return "serve"
    shp = _hlo_result_shape(line)
    if shp is None:
        return "small"
    dtype, elems, _ = shp
    if dtype == "u32" and elems <= 8:
        return "rng"
    return "small" if elems <= 1024 else "large"


def hlo_copy_census(hlo_text: str) -> dict:
    """Copy-class op counts + bytes + per-category attribution for one
    compiled HLO module (non-fusion lines only — the buffer-allocating
    set). Categories: see ``classify_copy``."""
    import re

    counts = {op: 0 for op in HLO_COPY_OPS}
    by_cat: dict = {}
    bytes_total = 0
    for line in hlo_non_fusion_lines(hlo_text):
        for op in HLO_COPY_OPS:
            if re.search(r"=\s*\S+\s+" + re.escape(op) + r"\(", line):
                counts[op] += 1
                break
        else:
            continue
        cat = classify_copy(line)
        shp = _hlo_result_shape(line)
        nbytes = shp[2] if shp else 0
        ent = by_cat.setdefault(cat, {"ops": 0, "bytes": 0})
        ent["ops"] += 1
        ent["bytes"] += nbytes
        bytes_total += nbytes
    return {
        "hlo_copy_ops": counts,
        "hlo_copy_total": sum(counts.values()),
        "hlo_copy_bytes": bytes_total,
        "by_category": by_cat,
    }


# ---------------- compiled-HLO collective census (shared by
# scripts/cost_sharded_update.py and `bench.py --census`) ----------------

# collective op kinds the census attributes; anything else that smells
# like a collective lands in "unattributed" — a structural regression
# when it appears (the sharded-update census pins it at 0)
HLO_COLLECTIVE_CLASSES = {
    "all-reduce": "all_reduce",
    "reduce-scatter": "reduce_scatter",
    "all-gather": "all_gather",
    "collective-permute": "ppermute",
    "all-to-all": "all_to_all",
}

# collective-looking op kinds OUTSIDE the attributed set: their
# appearance classifies as "unattributed" (a stray the ceiling names)
_HLO_COLLECTIVE_UNATTRIBUTED = ("collective-broadcast", "ragged-all-to-all")


def classify_collective(line: str) -> str | None:
    """Attribution class for one HLO instruction line, or None when the
    line is not a collective (or is the ``-done`` half of an async pair,
    which is counted at its ``-start``).

    Classes: "all_reduce" (the replicated engine's grad sync),
    "reduce_scatter" (the sharded engine's grad sync — each replica
    receives the summed 1/dp shard), "all_gather" (updated params back
    to every replica), "ppermute" (ring/pipeline transfers),
    "all_to_all" (resharding), "unattributed" (any other collective —
    a stray the census ceiling must name).

    Matching is by opcode token (the name followed by "(", preceded by
    whitespace or a closing bracket) rather than by result-type parsing,
    so tuple-typed async forms (``all-reduce-start`` et al.) classify on
    every backend's text format. Longest names are tested first so
    ``all-reduce`` can never claim a ``reduce-scatter`` line.
    """
    import re

    if "=" not in line:
        return None
    names = sorted(
        list(HLO_COLLECTIVE_CLASSES) + list(_HLO_COLLECTIVE_UNATTRIBUTED),
        key=len, reverse=True,
    )
    for base in names:
        esc = re.escape(base)
        if re.search(r"[\s)]" + esc + r"-done\(", line):
            return None  # async pair's -done half: counted at -start
        if re.search(r"[\s)]" + esc + r"(-start)?\(", line):
            return HLO_COLLECTIVE_CLASSES.get(base, "unattributed")
    return None


# named-scope markers -> attribution category for collectives: the
# engine scopes (zero3 weight streaming, the sharded update's flat
# pack, crop packing) wrap their materialization/collective sites, and
# the GSPMD-inserted collectives inherit the scope in their op_name
# metadata — so the census can say WHICH engine asked for each
# collective, not just its opcode class. Order matters: first match
# wins (prefetch before stream — the prefetch scope nests inside the
# stream program).
HLO_COLLECTIVE_SCOPES = (
    # the unified zero3 x bucketed engine's hierarchy-aware staged
    # schedule (parallel/sharding.py hier_gather_bucket): ag_inter =
    # the slow-tier shard gather, ag_intra = the fast-tier broadcast of
    # the assembled segments; rs_intra/rs_inter = the hand-written
    # custom_vjp backward (fast-tier volume reduction first, then the
    # shrunk cotangent over the slow links). Listed FIRST: these scopes
    # never nest under another engine scope, but a first-match table
    # must put the most specific markers before zero3_gather's
    ("bucket_ag_inter", "bucket_ag_inter"),
    ("bucket_ag_intra", "bucket_ag_intra"),
    ("bucket_rs_intra", "bucket_rs_intra"),
    ("bucket_rs_inter", "bucket_rs_inter"),
    ("zero3_prefetch", "zero3_prefetch"),
    ("zero3_stream", "zero3_stream"),
    ("zero3_gather", "zero3_gather"),
    # train.low_precision (ops/lowp.py): lowp_amax = the delayed-scaling
    # history advance + the activations' current-scale amax (under zero3
    # each is a tiny all-reduce-max over a sharded master); lowp_dequant
    # = the dequantize epilogue after each quantized matmul (normally
    # collective-free — listed so any reshard GSPMD hangs there is
    # attributed, not "other"). The quantized WEIGHT gathers themselves
    # ride the zero3_stream scope above on purpose: same collective
    # sites as the bf16 stream, 1-byte payloads.
    ("lowp_amax", "lowp_amax"),
    ("lowp_dequant", "lowp_dequant"),
    # the bucketed collective engine (train/fused_update.py
    # make_bucketed_update + the overlap twin in models/streaming.py):
    # pack = the coalesced grad reduce-scatter site, unpack = the
    # one-all-gather-per-bucket param/teacher re-materialization,
    # prefetch/gather/stream = the double-buffered bucket gather scan
    ("bucket_prefetch", "bucket_prefetch"),
    ("bucket_stream", "bucket_stream"),
    ("bucket_gather", "bucket_gather"),
    ("bucket_pack", "bucket_pack"),
    ("bucket_unpack", "bucket_unpack"),
    ("update_shard", "update_shard"),
    ("crop_pack", "gather_pack"),
    ("crop_unpack", "gather_pack"),
    # ring attention (parallel/ring_attention.py): ring_permute = the
    # rotating K/V(+segment) chunk ppermutes of the forward and of the
    # custom_vjp's second ring pass (where the dk/dv accumulators
    # co-rotate); ring_merge = the island boundary — any reshard GSPMD
    # inserts to feed the seq-sharded islands. ring_permute first: the
    # permute scope nests inside the boundary scope.
    ("ring_permute", "ring_permute"),
    ("ring_merge", "ring_merge"),
    # serve-backed distillation fan-out (serve/engine.py patch-plane
    # ring write; ssl_meta_arch.py get_teacher_output's precomputed
    # arm): the teacher_cls/teacher_patches batch planes enter the step
    # replicated-per-host and GSPMD reshards them onto the batch axes —
    # those copies/collectives belong to the fan-out, not "other"
    ("distill_fanout", "distill_fanout"),
    # the elastic-topology engine (parallel/reshard.py): one scope per
    # train-state leaf-group, wrapping the WHOLE per-group program —
    # the arm-layout conversion (flat <-> model <-> bucketed moment
    # reshapes) and the src->dst sharding constraint — so every
    # collective a live mesh/arm transition inserts is attributed to
    # the group that moved, and the zero-unattributed pin holds across
    # reshard censuses exactly as it does for train steps
    ("reshard_params", "reshard_params"),
    ("reshard_mu", "reshard_mu"),
    ("reshard_nu", "reshard_nu"),
    ("reshard_rest", "reshard_rest"),
    ("telemetry_ring", "telemetry"),
)


def classify_collective_scope(line: str) -> str:
    """Named-scope attribution category for one collective HLO line
    (``HLO_COLLECTIVE_SCOPES``), or "other" when no engine scope claims
    it (model-structure collectives: grad all-reduces, loss psums)."""
    for marker, cat in HLO_COLLECTIVE_SCOPES:
        if marker in line:
            return cat
    return "other"


def collective_size_bin(nbytes: int) -> tuple[int, str]:
    """Power-of-two message-size bin for one collective result.

    Returns ``(floor_bytes, label)``: the largest power of two
    <= ``nbytes`` and a human-readable half-open interval label
    ("[64MiB,128MiB)"; zero-byte results bin as ``(0, "0B")``). The
    census histograms collective traffic by these bins — the
    small-message latency-bound regime (hundreds of per-leaf
    collectives under 1 MiB) and the coalesced bucket regime (a few
    >= 64 MiB messages) then read directly off the bin keys.
    """
    n = int(nbytes)
    if n <= 0:
        return 0, "0B"
    floor = 1 << (n.bit_length() - 1)

    def fmt(v: int) -> str:
        for shift, unit in ((30, "GiB"), (20, "MiB"), (10, "KiB")):
            if v >= (1 << shift):
                scaled = v / (1 << shift)
                return (f"{int(scaled)}{unit}" if scaled == int(scaled)
                        else f"{scaled:g}{unit}")
        return f"{v}B"

    return floor, f"[{fmt(floor)},{fmt(floor * 2)})"


def hlo_collective_placement(line: str) -> str:
    """Issue-site placement of one collective HLO instruction, from its
    op_name metadata (the while-loop signal ``hlo_collective_in_loop``
    reads, split by pass direction):

    - "in-backward-loop": inside a compiled loop body AND on the
      transposed (backward) path — jax stamps backward-pass ops with a
      ``transpose(...)`` component in their op_name, which survives
      partitioning. A reduce-scatter here is a grad sync issued as the
      backward loop produces each bucket/block — overlappable with the
      remaining backward compute.
    - "in-forward-loop": inside a loop body on the forward path (the
      per-block / per-bucket weight-stream gathers).
    - "at-barrier": outside any loop — a whole-tree materialization or
      an update-phase collective issued after both passes complete
      (nothing left to overlap it with).
    """
    import re

    m = re.search(r'op_name="([^"]*)"', line)
    op = m.group(1) if m else ""
    if "while" in op:
        return "in-backward-loop" if "transpose" in op else "in-forward-loop"
    return "at-barrier"


def hlo_collective_in_loop(line: str) -> bool:
    """Whether a collective instruction executes inside a compiled loop
    body (the block scan / K-tile scan): jax stamps loop-body ops with a
    ``while`` component in their op_name metadata (``.../while/body/...``,
    ``jvp(while)``, ``transpose(jvp(while))``), which survives into the
    partitioned HLO — the placement signal behind the weight-stream and
    prefetch-overlap columns (an all-gather inside the block loop is a
    per-block stream gather; outside, a whole-tree materialization)."""
    import re

    m = re.search(r'op_name="([^"]*)"', line)
    return bool(m and "while" in m.group(1))


def hlo_collective_census(hlo_text: str) -> dict:
    """Collective op counts + result bytes per class for one compiled
    HLO module (non-fusion lines; ``-start``/plain forms counted once,
    ``-done`` halves skipped).

    Result bytes are the PER-DEVICE output of each collective — for an
    all-reduce that is the full buffer, for a reduce-scatter the 1/dp
    shard, for an all-gather the re-assembled full buffer — so the
    by-class byte totals read directly as the per-device collective
    traffic story of the module. Classes: see ``classify_collective``.

    Beyond ``by_class``, the census attributes every collective to the
    engine named scope that asked for it (``by_scope``,
    ``classify_collective_scope``) and records the weight-stream /
    prefetch-overlap story of the all-gathers (``prefetch_overlap``):
    how many gathers run inside loop bodies (the per-block stream),
    how many of those were issued AHEAD of their consuming block (the
    ``zero3_prefetch`` scope — the double-buffered schedule), and how
    many are issued at use (``zero3_stream``; overlap is then the async
    scheduler's job). The zero3 acceptance pins read these columns.

    Two further columns (the bucketed-collective acceptance pins,
    COST_BUCKET_r13.json):

    - ``size_histogram`` (top-level, and a per-class copy inside each
      ``by_class`` entry): count + bytes per power-of-two message-size
      bin (``collective_size_bin``). The per-leaf schedules show
      hundreds of sub-MiB entries; the bucketed engine a handful of
      >= 64 MiB ones — each bin entry carries its ``floor_bytes`` so
      pins read thresholds without parsing labels.
    - ``by_placement`` (top-level + per class): ops/bytes per issue
      site (``hlo_collective_placement``) — in-backward-loop /
      in-forward-loop / at-barrier. The overlap-scheduled engine's grad
      reduce-scatters attribute to the backward loop body; the per-leaf
      update-phase schedule is all at-barrier.
    """
    by_class: dict = {}
    by_scope: dict = {}
    by_placement: dict = {}
    size_histogram: dict = {}
    ag_in_loop_ops = ag_in_loop_bytes = 0
    ag_prefetch = ag_at_use = 0
    total_ops = 0
    total_bytes = 0

    def _bump_hist(hist: dict, nbytes: int) -> None:
        floor, label = collective_size_bin(nbytes)
        h = hist.setdefault(
            label, {"floor_bytes": floor, "ops": 0, "bytes": 0})
        h["ops"] += 1
        h["bytes"] += nbytes

    for line in hlo_non_fusion_lines(hlo_text):
        cat = classify_collective(line)
        if cat is None:
            continue
        shp = _hlo_result_shape(line)
        nbytes = shp[2] if shp else 0
        ent = by_class.setdefault(
            cat, {"ops": 0, "bytes": 0,
                  "size_histogram": {}, "by_placement": {}})
        ent["ops"] += 1
        ent["bytes"] += nbytes
        _bump_hist(ent["size_histogram"], nbytes)
        _bump_hist(size_histogram, nbytes)
        placement = hlo_collective_placement(line)
        for tbl in (ent["by_placement"], by_placement):
            p_ent = tbl.setdefault(placement, {"ops": 0, "bytes": 0})
            p_ent["ops"] += 1
            p_ent["bytes"] += nbytes
        scope = classify_collective_scope(line)
        s_ent = by_scope.setdefault(scope, {"ops": 0, "bytes": 0})
        s_ent["ops"] += 1
        s_ent["bytes"] += nbytes
        if cat == "all_gather":
            if hlo_collective_in_loop(line):
                ag_in_loop_ops += 1
                ag_in_loop_bytes += nbytes
            if scope == "zero3_prefetch":
                ag_prefetch += 1
            elif scope == "zero3_stream":
                ag_at_use += 1
        total_ops += 1
        total_bytes += nbytes
    return {
        "hlo_collective_total": total_ops,
        "hlo_collective_bytes": total_bytes,
        "by_class": by_class,
        "by_scope": by_scope,
        "by_placement": by_placement,
        "size_histogram": size_histogram,
        "prefetch_overlap": {
            "all_gather_in_loop_ops": ag_in_loop_ops,
            "all_gather_in_loop_bytes": ag_in_loop_bytes,
            "prefetch_scoped_ops": ag_prefetch,
            "at_use_scoped_ops": ag_at_use,
        },
        "unattributed": by_class.get("unattributed", {"ops": 0})["ops"],
    }
