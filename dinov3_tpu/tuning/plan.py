"""Tuned collective-schedule plan artifact: schema, deterministic
selection, and provenance (the measure->tune loop's committed half).

The tuner (scripts/tune_collectives.py) measures a few profiled steps
per candidate through the step-anatomy plane (telemetry/anatomy.py) and
writes ONE committed ``TUNED_*.json`` keyed by the setup fingerprint
(arch, device count, update-shard size, jax version). This module owns
everything about that artifact that is NOT measurement:

- ``select_best``: the deterministic argmin over a measurement trail —
  first candidate achieving the minimal objective wins, so ``chosen``
  is re-derivable from the committed trail by anyone (the
  tests/test_tuning.py pin, and the reason "auto" resolution is
  bitwise-deterministic: same artifact bytes -> same knob values).
- ``validate_plan`` / ``load_tuned_plan``: schema enforcement — every
  knob entry must carry its full per-candidate trail, its hand-set
  oracle value, and a ``chosen`` equal to ``select_best(trail)``.
- ``tuned_plan_provenance``: the per-knob resolution record bench.py
  embeds in every record (configured value, resolved value, and which
  path produced it: explicit / tuned / fallback), so a benched number
  can always be traced to the exact schedule that produced it.

Objective (telemetry/anatomy.py ``tuning_summary``):
``objective_ms = step_wall_ms.mean + exposed_comm_ms_per_step`` —
exposed collective time is paid once inside the wall and once more as
the penalty term, so two candidates with equal walls prefer the one
hiding more of its communication (the one with headroom on hardware
where compute and comm genuinely overlap; see docs/PERFORMANCE.md).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

from dinov3_tpu.configs.config import (
    TUNED_ARTIFACT,
    TUNED_FALLBACKS,
    tuned_fingerprint_mismatches,
)

TUNED_SCHEMA = "tuned-plan/v1"

# the full knob set a complete plan carries (the committed artifact is
# pinned to exactly this set; --smoke plans may carry a subset)
KNOBS = ("bucket_mb", "staging_order", "stream_prefetch", "ring_min_seq")

FINGERPRINT_KEYS = ("arch", "device_count", "update_shard_size", "jax")


def select_best(trail: list) -> Any:
    """Deterministic winner of a measurement trail: the FIRST candidate
    achieving the minimal ``objective_ms`` (strict-< scan, so ties go
    to the earlier row — candidate order is part of the artifact and
    the scan is reproducible from the committed floats alone)."""
    if not trail:
        raise ValueError("empty measurement trail")
    best = trail[0]
    for row in trail[1:]:
        if float(row["objective_ms"]) < float(best["objective_ms"]):
            best = row
    return best["value"]


def knob_entry(trail: list, knob: str, program: str,
               unit: str | None = None, extra: dict | None = None) -> dict:
    """Assemble one knob's artifact entry from its measurement trail.
    ``chosen`` is computed here, AFTER the caller rounded the trail
    (telemetry.anatomy.round_floats), so re-deriving it from the
    committed floats gives the same winner."""
    entry = {
        "chosen": select_best(trail),
        "handset": TUNED_FALLBACKS[knob],
        "program": program,
        "trail": trail,
    }
    if unit:
        entry["unit"] = unit
    if extra:
        entry.update(extra)
    return entry


def validate_plan(doc: dict) -> dict:
    """Raise ValueError on any schema violation; return the doc.

    Checks: schema tag, complete fingerprint, generated_by, and per
    knob — a known name, a non-empty trail whose rows carry
    ``value``/``objective_ms``, a ``handset`` equal to the hand-set
    oracle (configs/config.py TUNED_FALLBACKS), and ``chosen`` equal
    to ``select_best(trail)`` (the re-derivability pin)."""
    if doc.get("schema") != TUNED_SCHEMA:
        raise ValueError(
            f"schema {doc.get('schema')!r} != {TUNED_SCHEMA!r}")
    fp = doc.get("fingerprint") or {}
    missing = [k for k in FINGERPRINT_KEYS if k not in fp]
    if missing:
        raise ValueError(f"fingerprint missing {missing}")
    if not doc.get("generated_by"):
        raise ValueError("missing generated_by")
    knobs = doc.get("knobs") or {}
    if not knobs:
        raise ValueError("no knobs")
    for name, entry in knobs.items():
        if name not in KNOBS:
            raise ValueError(f"unknown knob {name!r}")
        trail = entry.get("trail") or []
        if not trail:
            raise ValueError(f"{name}: empty trail")
        for row in trail:
            if "value" not in row or "objective_ms" not in row:
                raise ValueError(f"{name}: trail row missing "
                                 f"value/objective_ms: {row}")
        if entry.get("handset") != TUNED_FALLBACKS[name]:
            raise ValueError(
                f"{name}: handset {entry.get('handset')!r} != oracle "
                f"{TUNED_FALLBACKS[name]!r}")
        if entry.get("chosen") != select_best(trail):
            raise ValueError(
                f"{name}: chosen {entry.get('chosen')!r} is not "
                f"select_best(trail) = {select_best(trail)!r} — the "
                f"committed winner must be re-derivable from the trail")
    return doc


def load_tuned_plan(path: Path | str | None = None) -> dict:
    """Read + validate a tuned plan artifact (default: the committed
    TUNED_ARTIFACT). Raises on unreadable/invalid — callers that want
    graceful degradation use the config resolvers instead."""
    p = Path(TUNED_ARTIFACT if path is None else path)
    with open(p) as f:
        return validate_plan(json.load(f))


def tuned_plan_provenance(
    cfg, artifact: Path | str | None = None, live: dict | None = None,
) -> dict:
    """Per-knob resolution record for bench/telemetry embedding:
    which value each schedule knob resolved to and WHY (the same
    decision procedure as the config resolvers, recorded instead of
    warned). ``source`` per knob is one of:

    - ``explicit``: the config hand-set the knob — the oracle;
    - ``tuned``: "auto" resolved from the artifact (fingerprint ok);
    - ``fallback_unreadable``: "auto" but no readable artifact;
    - ``fallback_stale``: "auto" but the artifact fingerprint
      mismatches the supplied live fingerprint.
    """
    import warnings

    from dinov3_tpu.configs.config import (
        resolve_bucket_mb,
        resolve_ring_min_seq,
        resolve_staging_order,
        resolve_stream_prefetch,
    )

    path = Path(TUNED_ARTIFACT if artifact is None else artifact)
    doc: dict | None
    try:
        with open(path) as f:
            doc = json.load(f)
    except Exception:  # noqa: BLE001 - recorded, not raised
        doc = None
    fp = (doc or {}).get("fingerprint") or {}
    stale = (tuned_fingerprint_mismatches(fp, live)
             if (doc is not None and live is not None) else [])

    optim = cfg.get("optim") or {}
    kernels = cfg.get("kernels") or {}
    configured = {
        "bucket_mb": optim.get("bucket_mb", "auto"),
        "staging_order": optim.get("staging_order", "auto"),
        "stream_prefetch": optim.get("stream_prefetch", "auto"),
        "ring_min_seq": kernels.get("ring_min_seq", "auto"),
    }
    resolvers = {
        "bucket_mb": resolve_bucket_mb,
        "staging_order": resolve_staging_order,
        "stream_prefetch": resolve_stream_prefetch,
        "ring_min_seq": resolve_ring_min_seq,
    }
    knobs = {}
    for name, raw in configured.items():
        auto = raw is None or raw == "" or raw == "auto"
        if not auto:
            source = "explicit"
        elif doc is None:
            source = "fallback_unreadable"
        elif stale:
            source = "fallback_stale"
        else:
            source = "tuned"
        with warnings.catch_warnings():
            # the provenance record replaces the warning here; the
            # loud path stays with the actual consumers
            warnings.simplefilter("ignore")
            resolved = resolvers[name](raw, artifact=path, live=live)
        knobs[name] = {"configured": raw, "resolved": resolved,
                       "source": source}
    return {
        "artifact": str(path),
        "artifact_readable": doc is not None,
        "fingerprint": fp or None,
        "fingerprint_live": live,
        "stale": stale,
        "knobs": knobs,
    }
