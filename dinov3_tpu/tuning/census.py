"""Knob census: every ``optim.*`` / ``kernels.*`` numeric constant
must be accounted for — tuned from the TUNED_* artifact, resolved
from a committed crossover measurement, or carrying a documented
justification — so no magic number rides in the schedule config
untracked (the tuner satellite's "no silent knobs" guarantee, pinned
by tests/test_tuning.py and run in CI via
``scripts/tune_collectives.py --census``).

Three kinds:

- ``tuned``: searched by scripts/tune_collectives.py against the
  step-anatomy objective; the default is "auto" and the numeric
  magic lives ONLY in configs/config.py TUNED_FALLBACKS (the
  hand-set oracle the resolver degrades to).
- ``crossover``: resolved from a dedicated committed measurement
  artifact (the resolve_flash_min_seq pattern).
- ``justified``: a training-recipe or kernel-shape constant that is
  NOT a latency knob — the entry documents why it is exempt from
  tuning.

The census walks the DEFAULT config (ssl_default_config.yaml): every
key under ``optim``/``kernels`` whose default is numeric (bools
excluded — they are mode switches, not magnitudes) must appear here;
an unregistered numeric key fails the census. Registered tuned/
crossover keys are checked even when their default is the "auto"
string (their magic number lives in the fallback).
"""

from __future__ import annotations

# section.key -> {kind, why, resolver?, artifact?}
KNOB_REGISTRY: dict = {
    # ---- tuned (TUNED_r20.json, scripts/tune_collectives.py) ----
    "optim.bucket_mb": {
        "kind": "tuned", "resolver": "resolve_bucket_mb",
        "artifact": "TUNED_r20.json",
        "why": "bucket payload target of the greedy leaf packing — "
               "swept against the measured step objective",
    },
    "optim.staging_order": {
        "kind": "tuned", "resolver": "resolve_staging_order",
        "artifact": "TUNED_r20.json",
        "why": "tier-release order of the hierarchy-aware staged "
               "gathers — all four orders swept",
    },
    "optim.stream_prefetch": {
        "kind": "tuned", "resolver": "resolve_stream_prefetch",
        "artifact": "TUNED_r20.json",
        "why": "gather-lookahead depth of the explicit weight "
               "streams — depths 0/1/2 swept",
    },
    "kernels.ring_min_seq": {
        "kind": "tuned", "resolver": "resolve_ring_min_seq",
        "artifact": "TUNED_r20.json",
        "why": "ring-dispatch token floor — derived from the measured "
               "ring-vs-dense workload table",
    },
    # ---- crossover (dedicated committed measurement) ----
    "kernels.flash_min_seq": {
        "kind": "crossover", "resolver": "resolve_flash_min_seq",
        "artifact": "CROSSOVER_r19.json",
        "why": "flash-vs-dense sequence crossover, measured by "
               "scripts/crossover_attention.py",
    },
    # ---- justified (documented non-latency constants) ----
    "optim.epochs": {
        "kind": "justified",
        "why": "training-recipe length (paper schedule), not a "
               "latency knob"},
    "optim.weight_decay": {
        "kind": "justified",
        "why": "cosine weight-decay start (reference recipe)"},
    "optim.weight_decay_end": {
        "kind": "justified",
        "why": "cosine weight-decay end (reference recipe)"},
    "optim.lr": {
        "kind": "justified",
        "why": "base learning rate before scaling_rule (reference "
               "recipe)"},
    "optim.warmup_epochs": {
        "kind": "justified",
        "why": "LR warmup length (reference recipe)"},
    "optim.min_lr": {
        "kind": "justified",
        "why": "cosine floor (reference recipe)"},
    "optim.schedule_trunc_extra": {
        "kind": "justified",
        "why": "schedule truncation margin (reference recipe)"},
    "optim.clip_grad": {
        "kind": "justified",
        "why": "global grad-norm clip (reference recipe; numerics, "
               "not latency)"},
    "optim.freeze_last_layer_epochs": {
        "kind": "justified",
        "why": "DINO last-layer freeze window (reference recipe)"},
    "optim.patch_embed_lr_mult": {
        "kind": "justified",
        "why": "per-group LR multiplier (reference recipe)"},
    "optim.dino_head_wd_multiplier": {
        "kind": "justified",
        "why": "per-group WD multiplier (reference recipe)"},
    "optim.layerwise_decay": {
        "kind": "justified",
        "why": "layerwise LR decay base (reference recipe)"},
    "optim.adamw_beta1": {
        "kind": "justified",
        "why": "AdamW moment coefficient (reference recipe)"},
    "optim.adamw_beta2": {
        "kind": "justified",
        "why": "AdamW moment coefficient (reference recipe)"},
    "optim.accum_steps": {
        "kind": "justified",
        "why": "gradient-accumulation factor — a memory/batch choice "
               "made by the launch config, not a tunable latency "
               "constant (its cost story is COST_UNIFIED_r18.json)"},
    "kernels.flash_block_q": {
        "kind": "justified",
        "why": "pallas flash kernel query-tile cap — hardware tile "
               "alignment (MXU/VMEM), changed only with the kernel"},
    "kernels.flash_block_kv": {
        "kind": "justified",
        "why": "pallas flash kernel key/value-tile cap — hardware "
               "tile alignment (MXU/VMEM), changed only with the "
               "kernel"},
    # ---- train.low_precision (ops/lowp.py, PR 17) ----
    "train.low_precision.arm": {
        "kind": "justified",
        "why": "precision-arm mode switch (bf16|fp8|int8), not a "
               "magnitude — its cost story is COST_LP_r21.json and "
               "the phQ on-chip A/B (scripts/r6_queue.sh)"},
    "train.low_precision.amax_history_len": {
        "kind": "justified",
        "why": "delayed-scaling amax ring length — the Transformer "
               "Engine default (16); a numerics-stability window, "
               "not a latency knob (the ring is a few f32 scalars "
               "per kernel)"},
    "train.low_precision.scale_margin": {
        "kind": "justified",
        "why": "headroom multiplier on the history amax — overflow "
               "insurance for between-step weight drift (numerics, "
               "not latency); 1.0 = trust the one-step-delayed amax"},
    "train.low_precision.divergence_tol": {
        "kind": "justified",
        "why": "warn_lowp_divergence gate on the setup drift probe — "
               "an alerting threshold (rel. Frobenius), not a "
               "schedule constant"},
}

# Dotted entries ("train.low_precision") walk nested config nodes — the
# census covers sub-blocks without sweeping every train.* key into it.
CENSUS_SECTIONS = ("optim", "kernels", "train.low_precision")


def _is_numeric(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def knob_census(cfg=None) -> dict:
    """Walk the default config's ``optim``/``kernels`` sections and
    classify every numeric constant against KNOB_REGISTRY. Returns
    ``{"ok": bool, "entries": [...], "unregistered": [...],
    "stale_registry": [...]}`` — ``unregistered`` are numeric keys
    with no registry entry (the failure the census exists to catch),
    ``stale_registry`` are registry entries whose key no longer
    exists in the config (a renamed/removed knob must leave the
    registry too)."""
    if cfg is None:
        from dinov3_tpu.configs import get_default_config

        cfg = get_default_config()
    entries = []
    unregistered = []
    seen = set()
    present_sections = []
    for section in CENSUS_SECTIONS:
        node = cfg
        for part in section.split("."):
            node = (node.get(part) or {}) if node else {}
        if node:
            present_sections.append(section)
        for key in node:
            value = node.get(key)
            name = f"{section}.{key}"
            reg = KNOB_REGISTRY.get(name)
            if reg is None:
                if _is_numeric(value):
                    unregistered.append({"knob": name, "default": value})
                continue
            seen.add(name)
            if not reg.get("why"):
                unregistered.append(
                    {"knob": name, "default": value,
                     "error": "registered without a justification"})
                continue
            entry = {"knob": name, "default": value,
                     "kind": reg["kind"], "why": reg["why"]}
            for opt in ("resolver", "artifact"):
                if opt in reg:
                    entry[opt] = reg[opt]
            entries.append(entry)
    # staleness is scoped to the sections the given config actually
    # carries: a partial/shadow config (tests census just optim+kernels)
    # must not read the other sections' registry entries as stale
    stale = sorted(
        name for name in set(KNOB_REGISTRY) - seen
        if any(name.startswith(s + ".") for s in present_sections))
    return {
        "ok": not unregistered and not stale,
        "n_knobs": len(entries),
        "by_kind": {
            kind: sorted(e["knob"] for e in entries if e["kind"] == kind)
            for kind in ("tuned", "crossover", "justified")
        },
        "entries": entries,
        "unregistered": unregistered,
        "stale_registry": stale,
    }
