"""Search driver for the collective auto-tuner: candidate spaces and
the generic measured-sweep loop (the measure->tune loop's search half).

The measurement functions themselves live in
scripts/tune_collectives.py (they own the harness: XLA device-count
flags before the jax import, profiled trace windows, the arm twins);
this module owns everything deterministic around them — WHICH values
to try and HOW a trail of ``tuning_summary`` measurements becomes a
committed trail (tuning/plan.py ``select_best`` then picks the
winner from the rounded floats).

Candidate spaces (each includes its hand-set oracle, so the sweep
always measures the status quo and ``tuned >= handset`` is checkable
per arm from the same trail):

- ``bucket_mb``: halving/doubling around the hand-set 128 MiB — the
  latency-vs-overlap-granularity trade of the greedy bucket packing
  (fewer+bigger buckets amortize collective latency, more+smaller
  ones pipeline deeper into the backward).
- ``staging_order``: all four "<ag>_<rs>" tier-release orders of the
  hierarchy-aware staged gathers (parallel/sharding.py
  STAGING_ORDERS) — which mesh tier each direction exercises first.
- ``stream_prefetch``: gather-lookahead depth of the explicit weight
  streams (0 = at-use, 1 = double buffer, 2 = deeper pipeline).
- ``ring_min_seq``: the ring-dispatch floor is NOT swept by
  recompiling the model per floor — ring-vs-dense is measured once
  per workload token count and every candidate floor's objective is
  derived deterministically from that committed table
  (``derive_ring_trail``), the crossover-artifact discipline of
  resolve_flash_min_seq applied to the ring path.
"""

from __future__ import annotations

from typing import Any, Callable

BUCKET_MB_CANDIDATES = (32, 64, 128, 256)
STREAM_PREFETCH_CANDIDATES = (0, 1, 2)
RING_MIN_SEQ_CANDIDATES = (256, 512, 1024, 2048)

TRAIL_FIELDS = ("objective_ms", "step_wall_ms_mean",
                "exposed_comm_ms_per_step", "exposed_comm_frac")


def staging_order_candidates() -> tuple:
    # lazy: parallel/sharding.py imports jax
    from dinov3_tpu.parallel.sharding import STAGING_ORDERS

    return STAGING_ORDERS


def trail_row(value: Any, tuning: dict, **extra) -> dict:
    """One trail row from a ``tuning_summary`` dict: the candidate
    value + the objective decomposition (committed so the winner is
    re-derivable and the loser margins are auditable)."""
    row = {"value": value}
    row.update({k: tuning[k] for k in TRAIL_FIELDS if k in tuning})
    row.update(extra)
    return row


def sweep_knob(
    knob: str,
    candidates,
    measure_fn: Callable[[Any], dict],
    log: Callable[[str], None] | None = None,
) -> list:
    """Measure every candidate through ``measure_fn`` (value ->
    ``tuning_summary`` dict) and return the full trail, in candidate
    order. No selection here — ``plan.select_best`` runs over the
    ROUNDED committed floats so artifact readers re-derive the same
    winner."""
    trail = []
    for value in candidates:
        tuning = measure_fn(value)
        row = trail_row(value, tuning)
        trail.append(row)
        if log:
            log(f"{knob}={value!r}: objective "
                f"{row['objective_ms']:.3f} ms (wall "
                f"{row['step_wall_ms_mean']:.3f} + exposed "
                f"{row['exposed_comm_ms_per_step']:.3f})")
    return trail


def derive_ring_trail(workloads: list, candidates=RING_MIN_SEQ_CANDIDATES,
                      ) -> list:
    """Per-floor objectives derived from the measured ring-vs-dense
    workload table: for floor F the dispatch (ops/attention.py) rings
    every pass with ``tokens >= F`` and runs the rest dense, so
    ``objective(F) = sum_w (ring if w.tokens >= F else dense)``.

    ``workloads``: ``[{"tokens": N, "ring_objective_ms": r,
    "dense_objective_ms": d}, ...]`` — measured once per N, floors
    cost nothing extra, and the derivation is exact arithmetic over
    committed floats (bitwise re-derivable)."""
    trail = []
    for floor in candidates:
        obj = 0.0
        split = []
        for w in workloads:
            rings = int(w["tokens"]) >= int(floor)
            obj += float(w["ring_objective_ms"] if rings
                         else w["dense_objective_ms"])
            split.append({"tokens": w["tokens"],
                          "impl": "ring" if rings else "dense"})
        trail.append({"value": floor, "objective_ms": obj,
                      "dispatch": split, "derived": True})
    return trail
