"""Anatomy-driven collective auto-tuner: the measure->tune loop.

``scripts/tune_collectives.py`` runs a few profiled steps per
candidate, reads the step-anatomy ledger's per-scope exposed/
overlapped columns (telemetry/anatomy.py), searches the schedule
knobs (optim.bucket_mb, optim.staging_order, optim.stream_prefetch,
kernels.ring_min_seq), and commits the winning plan + full
measurement trail as ``TUNED_r20.json``; "auto" on those knobs then
resolves from the artifact (configs/config.py resolve_* family) with
a fingerprint check and a loud hand-set fallback.

- ``plan``: artifact schema, ``select_best`` re-derivable selection,
  validation, and the per-knob provenance bench.py embeds.
- ``search``: candidate spaces + the generic sweep/derive drivers.
- ``census``: the no-silent-knobs registry over optim.*/kernels.*.
"""

from dinov3_tpu.tuning.census import (
    CENSUS_SECTIONS,
    KNOB_REGISTRY,
    knob_census,
)
from dinov3_tpu.tuning.plan import (
    FINGERPRINT_KEYS,
    KNOBS,
    TUNED_SCHEMA,
    knob_entry,
    load_tuned_plan,
    select_best,
    tuned_plan_provenance,
    validate_plan,
)
from dinov3_tpu.tuning.search import (
    BUCKET_MB_CANDIDATES,
    RING_MIN_SEQ_CANDIDATES,
    STREAM_PREFETCH_CANDIDATES,
    derive_ring_trail,
    staging_order_candidates,
    sweep_knob,
    trail_row,
)

__all__ = [
    "BUCKET_MB_CANDIDATES", "CENSUS_SECTIONS", "FINGERPRINT_KEYS",
    "KNOBS", "KNOB_REGISTRY", "RING_MIN_SEQ_CANDIDATES",
    "STREAM_PREFETCH_CANDIDATES", "TUNED_SCHEMA", "derive_ring_trail",
    "knob_census", "knob_entry", "load_tuned_plan", "select_best",
    "staging_order_candidates", "sweep_knob", "trail_row",
    "tuned_plan_provenance", "validate_plan",
]
