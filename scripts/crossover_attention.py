"""Op-level flash-vs-dense attention crossover: the EXECUTABLE
definition of the ``kernels.flash_min_seq`` dispatch threshold.

The full-step high-res benches compile for 20-40+ min through the axon
tunnel helper and have wedged it twice; this measures the SAME dispatch
decision (``dinov3_tpu/ops/attention.py``; config default
``kernels.flash_min_seq: auto`` resolves from THIS script's committed
artifact) with tiny fwd+bwd programs that compile in seconds, at the
token counts the recipes actually produce (224px->201, 512px->1029,
518px->1054, 768px->2309, plus 4096).

The threshold's definition is ``recommended_flash_min_seq``: the
smallest measured N at which the Pallas flash kernel beats dense XLA on
fwd+bwd wall time — dispatch flash for N >= that, dense below (None =
flash never won a measured point; keep dense everywhere). The committed
CROSSOVER_r19.json is this harness's verdict on the current platform
(``configs/config.py resolve_flash_min_seq`` reads it; on the CPU
harness interpret-mode Pallas never wins, so the verdict is null =
dense everywhere). Re-derive on-chip (r6 queue phH) and commit the new
artifact over it — never hand-edit the threshold.

Prints one JSON line per (N, impl) with ms/call, then a crossover
summary with the derived threshold. An out path ending in ``.json``
switches to committed-artifact mode (one JSON document). CPU tests
(tests/test_crossover_attention.py) keep the harness collectable, the
threshold definition pinned, and the committed artifact well-formed.

Usage: python scripts/crossover_attention.py [out.jsonl|out.json]
Env: XOVER_MAX_N (skip cases above N), XOVER_STEPS (20),
     XOVER_WARMUP (3; lower it on interpreted-Pallas CPU runs where a
     single flash call can take seconds),
     XOVER_CASES ("B1xN1,B2xN2,..." overrides the case ladder).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# ViT-L geometry: 16 heads x 64 head_dim; B chosen so B*N is roughly
# the 224px global-crop workload (16 seqs x 201 tokens) per call
HEADS, HEAD_DIM = 16, 64
CASES = [(16, 201), (4, 1029), (4, 1054), (2, 2309), (1, 4096)]


def parse_cases(s: str) -> list[tuple[int, int]]:
    """"16x201,4x1029" -> [(16, 201), (4, 1029)]."""
    out = []
    for part in s.split(","):
        b, n = part.lower().split("x")
        out.append((int(b), int(n)))
    return out


def measure_case(B: int, N: int, impl: str, steps: int, warmup: int,
                 heads: int = HEADS, head_dim: int = HEAD_DIM) -> dict:
    """One (B, N, impl) fwd+bwd timing record ({"error": ...} on
    failure — e.g. the Pallas kernel on a CPU backend)."""
    import jax
    import jax.numpy as jnp

    from dinov3_tpu.ops.attention import xla_attention

    q, k, v = (
        jax.random.normal(jax.random.key(i), (B, N, heads, head_dim),
                          jnp.bfloat16)
        for i in range(3)
    )
    if impl == "pallas":
        from dinov3_tpu.ops.flash_attention import flash_attention

        def fwd(q, k, v):
            return flash_attention(q, k, v)
    else:

        def fwd(q, k, v):
            return xla_attention(q, k, v, probs_dtype=jnp.bfloat16)

    # fwd+bwd like the train step sees it
    f = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(fwd(q, k, v).astype(jnp.float32)),
        argnums=(0, 1, 2),
    ))

    # Synchronize via a value fetch, NOT block_until_ready: the
    # tunneled-TPU transport can return from block_until_ready at
    # enqueue time (bench.py measure loop has the same note), which
    # made the r5 first-pass numbers ~70x faster than the chip's
    # bf16 peak. The fetched scalar forces the whole chain.
    def sync(g):
        return float(jnp.sum(g[0].astype(jnp.float32)))

    try:
        t0 = time.time()
        sync(f(q, k, v))
        compile_s = time.time() - t0
        g = None
        for _ in range(max(warmup, 0)):
            g = f(q, k, v)
        if g is not None:
            sync(g)
        t0 = time.perf_counter()
        for _ in range(steps):
            g = f(q, k, v)
        sync(g)
        ms = (time.perf_counter() - t0) / steps * 1e3
    except Exception as e:  # noqa: BLE001 - record and continue
        return {"B": B, "N": N, "impl": impl, "error": str(e)[:200]}
    return {"B": B, "N": N, "impl": impl, "ms": round(ms, 3),
            "compile_s": round(compile_s, 1)}


def measure_crossover(cases=None, steps: int = 20, warmup: int = 3,
                      emit=None) -> list[dict]:
    """All (case, impl) records; ``emit(rec)`` streams each as it lands
    (JSONL writers)."""
    records = []
    for B, N in (cases if cases is not None else CASES):
        for impl in ("xla", "pallas"):
            rec = measure_case(B, N, impl, steps, warmup)
            records.append(rec)
            if emit:
                emit(rec)
    return records


def crossover_summary(records: list[dict]) -> list[dict]:
    """Per-N xla-vs-flash pairs (cases where both impls measured)."""
    by_key = {(r["B"], r["N"], r["impl"]): r["ms"]
              for r in records if "ms" in r}
    seen, summary = set(), []
    for r in records:
        B, N = r["B"], r["N"]
        if (B, N) in seen:
            continue
        seen.add((B, N))
        a, b = by_key.get((B, N, "xla")), by_key.get((B, N, "pallas"))
        if a and b:
            summary.append({"N": N, "xla_ms": round(a, 3),
                            "flash_ms": round(b, 3),
                            "flash_speedup": round(a / b, 3)})
    return summary


def recommended_flash_min_seq(summary: list[dict]) -> int | None:
    """THE threshold definition: the smallest measured N where the flash
    kernel's fwd+bwd beats dense XLA (flash_speedup >= 1) — dispatch
    flash at N >= this. None = flash never won a measured point (keep
    dense everywhere, i.e. an effectively infinite flash_min_seq)."""
    wins = sorted(row["N"] for row in summary
                  if row["flash_speedup"] >= 1.0)
    return wins[0] if wins else None


def main():
    from dinov3_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()
    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")

    out_path = sys.argv[1] if len(sys.argv) > 1 else "/tmp/attn_crossover.jsonl"
    cases = CASES
    if os.environ.get("XOVER_CASES"):
        cases = parse_cases(os.environ["XOVER_CASES"])
    if os.environ.get("XOVER_MAX_N"):  # CPU smoke: skip the big cases
        cases = [c for c in cases if c[1] <= int(os.environ["XOVER_MAX_N"])]
    steps = int(os.environ.get("XOVER_STEPS", "20"))
    warmup = int(os.environ.get("XOVER_WARMUP", "3"))

    with open(out_path, "a") as out:
        def emit(rec):
            line = json.dumps(rec)
            print(line, flush=True)
            out.write(line + "\n")
            out.flush()

        records = measure_crossover(cases, steps=steps, warmup=warmup,
                                    emit=emit)
        summary = crossover_summary(records)
        line = json.dumps({
            "crossover": summary,
            "recommended_flash_min_seq": recommended_flash_min_seq(summary),
        })
        print(line, flush=True)
        out.write(line + "\n")

    if out_path.endswith(".json"):
        # committed-artifact mode (CROSSOVER_r19.json): one JSON document
        # the config resolver (configs/config.py resolve_flash_min_seq)
        # and the artifact-pin test read — overwrites the JSONL stream
        # written above with the final combined record.
        doc = {
            "generated_by": "scripts/crossover_attention.py",
            "platform": jax.devices()[0].platform,
            "jax": jax.__version__,
            "heads": HEADS, "head_dim": HEAD_DIM,
            "steps": steps,
            "records": records,
            "crossover": summary,
            "recommended_flash_min_seq": recommended_flash_min_seq(summary),
        }
        with open(out_path, "w") as out:
            json.dump(doc, out, indent=1)
            out.write("\n")


if __name__ == "__main__":
    main()
