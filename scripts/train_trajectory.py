"""Accuracy-trajectory run: SSL pretraining must make features BETTER.

Trains a miniature ViT with the full DINOv3 recipe on a real-file
(class-per-directory PNG folder) backend and runs the in-training eval
harness periodically; the committed artifact (TRAJECTORY_r0N.json) records
k-NN / linear-probe accuracy of the EMA teacher's features rising over
training — the first rung toward the reference's 83.3% IN1k target
(reference: dinov3_jax/configs/train/vitl_im1k_lin834.yaml:1-2, whose
`do_test` was a stub — train/train.py:315-316).

Data: scikit-learn's bundled handwritten digits (1797 real 8x8 images,
10 classes — the only real labeled image data reachable in a zero-egress
environment), upscaled and materialized as PNGs so the trainer exercises
the real folder pipeline (decode -> augment -> collate -> device).

Usage:  JAX_PLATFORMS=cpu python scripts/train_trajectory.py [out_dir]
Env: TRAJ_STEPS (default 600), TRAJ_EVAL_EVERY (default 100),
     TRAJ_ARCH (vit_test4), TRAJ_BATCH (48).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def materialize_digits(root: str, img_px: int = 64) -> tuple[str, str]:
    """Write sklearn digits as root/{train,val}/<class>/<i>.png."""
    import numpy as np
    from PIL import Image
    from sklearn.datasets import load_digits

    d = load_digits()
    n_train = 1500
    rng = np.random.default_rng(0)
    order = rng.permutation(len(d.images))
    for split, idxs in (("train", order[:n_train]),
                        ("val", order[n_train:])):
        for i in idxs:
            img = d.images[i]  # 8x8 float 0..16
            arr = np.clip(img * 15.9375, 0, 255).astype(np.uint8)
            pil = Image.fromarray(arr).convert("RGB").resize(
                (img_px, img_px), Image.BICUBIC
            )
            cls_dir = os.path.join(root, split, f"{d.target[i]:02d}")
            os.makedirs(cls_dir, exist_ok=True)
            pil.save(os.path.join(cls_dir, f"{i}.png"))
    return os.path.join(root, "train"), os.path.join(root, "val")


def main():
    from dinov3_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()

    out = sys.argv[1] if len(sys.argv) > 1 else "/tmp/trajectory_run"
    steps = int(os.environ.get("TRAJ_STEPS", "600"))
    eval_every = int(os.environ.get("TRAJ_EVAL_EVERY", "100"))
    arch = os.environ.get("TRAJ_ARCH", "vit_test4")
    batch = int(os.environ.get("TRAJ_BATCH", "48"))

    train_dir, val_dir = materialize_digits(os.path.join(out, "digits"))

    from dinov3_tpu.train.train import main as train_main

    epoch_len = eval_every
    epochs = steps // epoch_len
    result = train_main([
        "--output-dir", os.path.join(out, "run"), "--no-resume",
        f"student.arch={arch}", "student.patch_size=4",
        "student.drop_path_rate=0.1", "student.layerscale=1.0e-5",
        "crops.global_crops_size=32", "crops.local_crops_size=16",
        "crops.local_crops_number=6",
        "dino.head_n_prototypes=1024", "dino.head_hidden_dim=256",
        "dino.head_bottleneck_dim=64",
        "ibot.head_n_prototypes=1024", "ibot.head_hidden_dim=256",
        "ibot.head_bottleneck_dim=64",
        f"train.batch_size_per_device={batch}",
        f"train.OFFICIAL_EPOCH_LENGTH={epoch_len}",
        f"optim.epochs={epochs}",
        "optim.warmup_epochs=1", "optim.lr=0.001",
        "optim.scaling_rule=none",
        "teacher.warmup_teacher_temp_epochs=2",
        "train.num_workers=4",
        "data.backend=folder", f"data.root={train_dir}",
        "train.dataset_path=Folder:split=TRAIN",
        f"evaluation.eval_period_iterations={eval_every}",
        f"evaluation.train_dataset_path=Folder:root={train_dir}",
        f"evaluation.val_dataset_path=Folder:root={val_dir}",
    ])

    # one record per eval (the trainer writes evals.json exactly for
    # this; the meter JSONL smooths values into running medians)
    traj = []
    with open(os.path.join(out, "run", "evals.json")) as f:
        for line in f:
            traj.append(json.loads(line))
    artifact = {
        "dataset": "sklearn-digits (1500 train / 297 val PNGs, folder backend)",
        "arch": arch, "steps": steps, "batch": batch,
        "trajectory": traj,
        "final_loss": result.get("final_loss"),
    }
    print(json.dumps(artifact, indent=2))
    with open(os.path.join(out, "TRAJECTORY.json"), "w") as f:
        json.dump(artifact, f, indent=2)


if __name__ == "__main__":
    main()
