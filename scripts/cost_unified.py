"""Unified parallelism engine accounting: the committed evidence
behind COST_UNIFIED_r18.json (PR-1..6 discipline — compile the exact
shipped code paths, account from their compiled HLO).

The unified arm composes the PR-9 bucket layout with the PR-7 ZeRO-3
layout on a dp×fsdp mesh: the non-block zero3 gathers run as
hierarchy-aware flat buckets (one STAGED all-gather per bucket —
inter tier first, then intra — and one staged grad reduce-scatter per
bucket in the transpose) instead of one collective per leaf, and
``optim.accum_steps`` microbatches the fwd/bwd under a single bucketed
grad-RS per optimizer step. Three instruments, all on the 2×4
(data×fsdp) 8-simulated-device CPU mesh:

- **Gather-phase twins (compile-only)**: the per-leaf zero3 gather
  (one ``all_gather`` per shardable non-block leaf, one transposed
  ``psum_scatter`` per grad leaf — the ``=false`` oracle) vs the
  unified bucket schedule (``make_zero3_gather_schedule``: ONE staged
  AG/RS pair per bucket per tier, scopes ``bucket_ag_inter``/
  ``bucket_ag_intra``/``bucket_rs_intra``/``bucket_rs_inter``), both
  compiled as standalone ``jax.grad`` programs over the real
  non-block subtree so the grad sync is INSIDE the measured program.
- **In-step GSPMD census (honesty)**: the full shipped train step
  under ``build_train_setup`` with the unified arm engaged — the
  census must attribute staged gather collectives on BOTH mesh tiers
  with zero unattributed. This container's XLA:CPU lowers the
  engine's grad reduce-scatters in the pre-rewrite all-reduce+slice
  form (the slice carries the ``bucket_rs_*`` scope in its op_name);
  the schedule twin above is the committed proof of the post-rewrite
  collective set, exactly as for the flat bucketed engine
  (scripts/cost_buckets.py).
- **Accum sweep**: the same step at ``optim.accum_steps`` ∈ {1,2,4} —
  executed (loss trajectories recorded) and censused; the pin is that
  the bucket collective count DOES NOT grow with accum_steps (the
  gathers hoist outside the microbatch scan as scan constants, so the
  scan-constant transpose sums cotangents in-loop and the staged RS
  fires once per optimizer step).

One JSON record -> COST_UNIFIED_r18.json (argv[1], default
./COST_UNIFIED_r18.json); also printed to stdout. ``--smoke`` runs
the CI-sized variant (vit_test twins, accum {1,2}, same asserts, no
JSON write unless an out path is given explicitly).

Usage: JAX_PLATFORMS=cpu python scripts/cost_unified.py [out] [--smoke]
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMOKE = "--smoke" in sys.argv
_pos = [a for a in sys.argv[1:] if not a.startswith("--")]
OUT = _pos[0] if _pos else (None if SMOKE else "COST_UNIFIED_r18.json")
DATA, FSDP = 2, 4
DP = DATA * FSDP

os.environ.setdefault("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += f" --xla_force_host_platform_device_count={DP}"

# the SMOL dryrun shape (tests/test_zero3.py convention)
SMOL = [
    "student.arch=vit_test", "student.patch_size=4",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2", "train.batch_size_per_device=2",
    "optim.scaling_rule=none", "train.scan_layers=true",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
    "dino.head_bottleneck_dim=16",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
    "ibot.head_bottleneck_dim=16",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "optim.warmup_epochs=1",
    "telemetry.async_metrics=false",
]
MESH_OVR = ["parallel.data=2", "parallel.fsdp=4"]


def _log(msg):
    print(f"[cost_unified] {msg}", file=sys.stderr, flush=True)


def _prune_streamed(tree):
    """Drop the block-stack subtrees the in-scan weight stream owns
    (the ``zero3_streamed_path`` rule) from a nested param dict."""
    if not isinstance(tree, dict):
        return tree
    out = {}
    for k, v in tree.items():
        if k == "blocks" or k.startswith("blocks_") or k == "pipeline":
            continue
        out[k] = _prune_streamed(v)
    return out


def gather_phase_twins(cfg, mesh) -> dict:
    """Per-leaf vs unified-bucket gather schedules over the real
    non-block zero3 subtree: compile ``jax.grad`` of a sum-consume of
    each arm's gathered tree, so the forward gathers AND their
    transposed grad reduce-scatters are inside the measured program."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.parallel.sharding import zero3_leaf_spec
    from dinov3_tpu.train.fused_update import (
        make_zero3_bucket_plan,
        make_zero3_gather_schedule,
    )
    from dinov3_tpu.train.ssl_meta_arch import SSLMetaArch
    from dinov3_tpu.utils import hlo_collective_census

    meta = SSLMetaArch(cfg)
    batch = {k: jnp.asarray(v)
             for k, v in make_synthetic_batch(cfg, 1, seed=0).items()}
    student = jax.eval_shape(
        lambda r: meta.init_params(r, batch), jax.random.key(0)
    )["student"]
    subtree = _prune_streamed(student)
    from dinov3_tpu.configs.config import resolve_bucket_mb

    target_bytes = resolve_bucket_mb(
        cfg.optim.get("bucket_mb", "auto")) * 2 ** 20
    plan = make_zero3_bucket_plan(subtree, mesh, target_bytes=target_bytes)

    def shardings(tree):
        def leaf(l):
            spec = zero3_leaf_spec(l.shape, (None,) * l.ndim, mesh)
            return NamedSharding(mesh, spec if spec is not None else P())
        return jax.tree.map(leaf, tree)

    in_sh = shardings(subtree)

    def loss_of(gather):
        def loss(tree):
            full = gather(tree)
            # nonlinear consume: a plain sum of a gather reassociates
            # into local-sum + all-reduce under XLA's simplifier, which
            # would erase the very gathers being censused
            return sum(jnp.sum(jnp.sin(l.astype(jnp.float32)))
                       for l in jax.tree.leaves(full))
        return loss

    censuses = {}
    for arm, bucketed in (("per_leaf", False), ("unified", True)):
        g = make_zero3_gather_schedule(plan, mesh, bucketed=bucketed)
        _log(f"compiling {arm} gather twin...")
        with mesh:
            compiled = jax.jit(
                jax.grad(loss_of(g)), in_shardings=(in_sh,),
            ).lower(subtree).compile()
        censuses[arm] = hlo_collective_census(compiled.as_text())

    n_shardable = sum(len(b.members) for b in plan.buckets)
    return {
        "n_nonblock_leaves": plan.n_leaves,
        "n_shardable_leaves": n_shardable,
        "plan": {
            "n_buckets": len(plan.buckets),
            "n_inter": plan.n_inter,
            "n_intra": plan.n_intra,
            "target_bytes": plan.target_bytes,
            "buckets": plan.stats(),
        },
        "collective_census": censuses,
    }


def engine_step(cfg_overrides, accum_steps: int, n_steps: int = 3) -> dict:
    """Build the shipped train step (unified arm), census its compiled
    HLO, and run ``n_steps`` real steps recording the loss trajectory."""
    import jax
    import jax.numpy as jnp

    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup
    from dinov3_tpu.train.setup import put_batch
    from dinov3_tpu.utils import hlo_collective_census

    cfg = get_default_config()
    apply_dot_overrides(
        cfg, SMOL + MESH_OVR + [f"optim.accum_steps={accum_steps}"])
    batch = {k: jnp.asarray(v)
             for k, v in make_synthetic_batch(cfg, DP * 2, seed=0).items()}
    setup = build_train_setup(cfg, batch)
    assert setup.zero3 and setup.zero3_buckets, (
        setup.zero3, setup.zero3_buckets)
    assert setup.accum_steps == accum_steps, setup.accum_steps
    dbatch = put_batch(batch, setup.batch_shardings)
    _log(f"compiling unified step (accum_steps={accum_steps})...")
    compiled = setup.step_fn.lower(
        setup.state, dbatch, setup.scalars(0), jax.random.key(0)).compile()
    census = hlo_collective_census(compiled.as_text())
    # the backend lowers the engine's staged grad RS as
    # all-reduce+dynamic-slice; the slice op_name carries the scope, so
    # count scope-stamped grad-sync evidence lines for the record
    txt = compiled.as_text()
    rs_scope_lines = sum(
        txt.count(s) for s in ("bucket_rs_intra", "bucket_rs_inter"))
    losses = []
    state = setup.state
    for i in range(n_steps):
        state, metrics = setup.step_fn(
            state, dbatch, setup.scalars(i), jax.random.key(0))
        losses.append(float(metrics["total_loss"]))
    return {
        "accum_steps": accum_steps,
        "n_buckets": len(setup.zero3_bucket_plan.buckets),
        "loss_trajectory": losses,
        "collective_census": census,
        "grad_rs_scope_lines": rs_scope_lines,
    }


def main():
    from dinov3_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()
    import jax

    try:
        jax.config.update("jax_num_cpu_devices", DP)
    except AttributeError:
        pass
    import math

    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.parallel.context import set_current_mesh
    from dinov3_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(data=DATA, fsdp=FSDP))
    set_current_mesh(mesh)

    cfg = get_default_config()
    if SMOKE:
        apply_dot_overrides(cfg, SMOL + MESH_OVR)
    else:
        # twins at the real ViT-L tree (the cost_buckets.py convention);
        # the head/embed/norm tail is what the unified arm coalesces
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "bench", os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        apply_dot_overrides(cfg, bench.build_step_overrides("vit_large", 0))
        apply_dot_overrides(cfg, MESH_OVR)

    twins = gather_phase_twins(cfg, mesh)
    pl = twins["collective_census"]["per_leaf"]
    un = twins["collective_census"]["unified"]
    nb = twins["plan"]["n_buckets"]

    def scope_ops(c, s):
        return c["by_scope"].get(s, {"ops": 0})["ops"]

    def class_ops(c, k):
        return c["by_class"].get(k, {"ops": 0})["ops"]

    # ---- acceptance pins (ISSUE 14) ----
    assert pl["unattributed"] == 0 and un["unattributed"] == 0
    # coalesced collectives on BOTH mesh tiers, one per bucket per tier
    for s in ("bucket_ag_inter", "bucket_ag_intra",
              "bucket_rs_intra", "bucket_rs_inter"):
        assert scope_ops(un, s) == nb, (s, scope_ops(un, s), nb)
    rs_perleaf = class_ops(pl, "reduce_scatter")
    rs_unified = class_ops(un, "reduce_scatter")
    assert rs_perleaf == twins["n_shardable_leaves"], (
        rs_perleaf, twins["n_shardable_leaves"])
    # one staged RS per bucket per tier <= the per-leaf count collapsed
    assert rs_unified == 2 * nb and nb < twins["n_shardable_leaves"], (
        rs_unified, nb, twins["n_shardable_leaves"])

    accum_values = (1, 2) if SMOKE else (1, 2, 4)
    sweep = [engine_step(SMOL + MESH_OVR, a) for a in accum_values]
    base = sweep[0]["collective_census"]["by_scope"]
    for rec in sweep:
        c = rec["collective_census"]
        # BOTH tiers coalesced in the shipped step, zero unattributed
        assert c["unattributed"] == 0, rec["accum_steps"]
        assert scope_ops(c, "bucket_ag_inter") > 0, rec["accum_steps"]
        assert scope_ops(c, "bucket_ag_intra") > 0, rec["accum_steps"]
        # the bucket collective count does NOT grow with accum_steps
        for s in ("bucket_ag_inter", "bucket_ag_intra"):
            assert c["by_scope"][s]["ops"] == base[s]["ops"], (
                rec["accum_steps"], s)
        # grad-sync scope evidence present in the step program
        assert rec["grad_rs_scope_lines"] > 0, rec["accum_steps"]
        assert all(math.isfinite(v) for v in rec["loss_trajectory"])

    rec = {
        "what": ("unified parallelism engine: zero3 non-block gathers "
                 "as hierarchy-aware staged buckets + microbatched "
                 "gradient accumulation with one bucketed grad-RS per "
                 "optimizer step"),
        "arch": "vit_test" if SMOKE else "vit_large",
        "mesh": {"data": DATA, "fsdp": FSDP},
        "gather_phase": twins,
        "reduce_scatter_ops": {
            "per_leaf": rs_perleaf, "unified": rs_unified,
            "n_buckets": nb},
        "all_gather_ops": {
            "per_leaf": class_ops(pl, "all_gather"),
            "unified": class_ops(un, "all_gather")},
        "accum_sweep": sweep,
        "note": (
            "gather twins are the committed collective-set proof (this "
            "container's XLA:CPU lowers the in-step engine's staged "
            "grad reduce-scatters in the pre-rewrite all-reduce+slice "
            "form; the slice op_name carries the bucket_rs_* scope — "
            "counted under grad_rs_scope_lines); the in-step census "
            "pins both-tier coalesced gathers, zero unattributed, and "
            "accum-invariant bucket collective counts"
        ),
        "source": "hlo_census of the explicit gather schedule twins + "
                  "the shipped build_train_setup step at accum_steps "
                  f"{list(accum_values)} (2x4 data x fsdp simulated "
                  "CPU mesh, steps executed)",
    }
    if OUT:
        with open(OUT, "w") as f:
            json.dump(rec, f, indent=1)
        _log(f"wrote {OUT}")
    print(json.dumps({k: v for k, v in rec.items()
                      if k not in ("gather_phase", "accum_sweep")}))
    if SMOKE:
        _log("smoke OK: both-tier coalesced, zero unattributed, "
             "accum-invariant bucket collectives")


if __name__ == "__main__":
    main()
