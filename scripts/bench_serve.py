"""Serving A/B for the continuous-packing engine (serve/): the
committed evidence behind SERVE_r14.json.

Methodology (the PR-1..5 discipline — measure the exact shipped code
paths, stated precisely because this is the committed evidence in
docs/PERFORMANCE.md):

- **Three traffic mixes**, each a seeded draw of [H, W, 3] requests:
  ``uniform_224`` (every request the same square resolution — the mix
  rectangular batching is built for, kept as the oracle's home turf),
  ``mixed_ragged`` (H and W drawn INDEPENDENTLY on the 16px grid
  across banded 96..512px resolutions, small-skewed the way embedding
  traffic is — the shape space is hundreds of (H, W) pairs, so
  shape-polymorphic serving can never stay warm), and ``heavy_tail``
  (90% small 96..160px crops, 10% near-max 448..512px).
- **Three arms over identical traffic**: the packed engine
  (serve.continuous_packing, ONE ahead-of-time compile at build) and
  the two naive oracles (``oracle_rectangular``: group by exact shape,
  pad each group's batch to the next power of two; ``oracle_per_image``:
  one dispatch per request). All arms serve the SAME bf16 weight tree
  through the same admission/flush-deadline batcher policy.
- **Warmup protocol**: each arm first serves a DISJOINT warmup draw
  from the same mix distribution. That fully warms the packed arm (its
  one program is shape-independent) and warms the oracles exactly as
  much as a real deployment could (they cannot pre-trace traffic
  shapes they have not seen; the per-arm record reports how many
  measured shapes were novel after warmup). Oracle recompiles during
  measurement are part of the measured serving cost — that is the
  pathology under test — and are reported separately as
  ``compile_growth_during_measurement``.
- **Throughput (sustained drain)**: all measured requests arrive at
  t=0; img/s = N / wall-seconds of the drain. The stream is long
  enough (several full token budgets) that the packed arm's last
  partial pack amortizes.
- **Latency (virtual-clock rated replay)**: Poisson arrivals at 0.7x
  the PACKED arm's measured sustained rate — the same trace for every
  arm, so an arm slower than the offered rate visibly queues. The
  clock advances by each flush's measured wall time (plus waits to the
  next arrival/deadline), so percentiles don't require real sleeps;
  p50/p99 are over per-request ``done_s - arrival_s``.
- **Accounting**: per (arm, mix) record embeds bench.py's
  ``_serve_summary`` (token budget, measured pad waste, the
  blocking_fetch funnel counters) and re-fires the
  ``warn_serve_pad_waste`` guardrail against the MEASURED mix waste;
  the packed arm's one program carries the full copy + collective
  census (utils.hlo_copy_census / hlo_collective_census) with the
  serve-scoped traffic attributed and zero unattributed collectives
  pinned (tests/test_serve.py reads these from the committed record).

Layout for the full run: rows=4 x row_tokens=1025 (one max-envelope
image per row; dense segment-masked attention is O(row_tokens^2) per
row, so the smallest row that fits the 512px request minimizes the
fixed pack cost) and max_segments_per_row=28 (a row of 96px requests
holds 27 — anything lower slot-caps small traffic into pure padding).

Observability (ISSUE 11): every measured (arm, mix) window runs behind
a ``ServeObserver`` (telemetry/serve_obs.py) writing per-request phase
spans and per-SLO streaming latency histograms into one serve-role
span stream (``--obs-dir``); ``scripts/obs_report.py`` folds that
stream plus this record into the committed OBS artifact. Latency
percentiles go through the shared nearest-rank quantile helper
(telemetry/hist.py) — exact overall and per SLO class.

Writes one JSON document (default ./SERVE_r14.json) and prints it.

Usage: JAX_PLATFORMS=cpu python scripts/bench_serve.py \
           [--smoke] [--out SERVE_r14.json] [--seed 0] [--n N] \
           [--obs-dir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


# ---------------- traffic mixes ----------------
#
# Each mix is banded: (probability, (min_px, max_px)); H and W are
# drawn independently on the patch-size grid inside the band (square
# only when the band is a single value). Small-skewed bands reflect
# embedding-serving reality (thumbnails and crops dominate; full-res
# is the tail) — and raggedness is the point: the (H, W) space of the
# mixed bands is ~300 shapes, so per-shape jit caches never converge.

MIXES_FULL = {
    "uniform_224": [(1.0, (224, 224))],
    "mixed_ragged": [(0.70, (96, 256)), (0.20, (208, 320)),
                     (0.10, (336, 512))],
    "heavy_tail": [(0.90, (96, 160)), (0.10, (448, 512))],
}

MIXES_SMOKE = {
    "uniform_224": [(1.0, (16, 16))],
    "mixed_ragged": [(0.70, (8, 16)), (0.20, (20, 24)), (0.10, (28, 32))],
    "heavy_tail": [(0.90, (8, 12)), (0.10, (28, 32))],
}


def make_mix(rng: np.random.Generator, bands, n: int, grid: int) -> list:
    """n seeded [H, W, 3] float32 images from the banded distribution."""
    probs = np.array([p for p, _ in bands])
    out = []
    for b in rng.choice(len(bands), size=n, p=probs / probs.sum()):
        lo, hi = bands[int(b)][1]
        sizes = np.arange(lo, hi + 1, grid)
        h, w = rng.choice(sizes), rng.choice(sizes)
        out.append(rng.standard_normal((int(h), int(w), 3))
                   .astype(np.float32))
    return out


def slo_class(image, layout) -> str:
    """Deterministic SLO class per request: small crops (both sides at
    or below the envelope midpoint) are ``interactive`` — the
    thumbnail/crop traffic a frontend waits on — larger requests are
    ``batch``. Size-derived (not random) so every arm serves the same
    class per request and the per-class percentiles compare across
    arms."""
    cut = (layout.min_px + layout.max_px) / 2
    return ("interactive"
            if max(image.shape[0], image.shape[1]) <= cut else "batch")


# ---------------- replays ----------------


def drain_all(engine, images) -> tuple[float, list]:
    """All arrivals at t=0; wall-seconds and responses of the drain."""
    for i, im in enumerate(images):
        engine.submit(im, request_id=i, arrival_s=0.0,
                      slo=slo_class(im, engine.layout))
    t0 = time.perf_counter()
    responses = []
    while engine.queue_len:
        responses.extend(engine.flush())
    wall = time.perf_counter() - t0
    assert len(responses) == len(images)
    return wall, responses


def _lat_summary(latencies_s: list) -> dict:
    """Exact nearest-rank percentiles of a latency sample — the shared
    quantile helper (telemetry/hist.py), replacing the ad-hoc indexing
    this script used to hand-roll (p50 as ``lats[len//2]`` — the UPPER
    median on even n — and a hand-clamped p99 index)."""
    from dinov3_tpu.telemetry.hist import quantile_nearest_rank

    lats = sorted(latencies_s)
    return {
        "n": len(lats),
        "p50_ms": round(1e3 * quantile_nearest_rank(lats, 0.50), 3),
        "p99_ms": round(1e3 * quantile_nearest_rank(lats, 0.99), 3),
        "mean_ms": round(1e3 * sum(lats) / len(lats), 3),
    }


def rated_replay(engine, trace) -> dict:
    """Virtual-clock discrete-event replay of a timed arrival trace.

    ``trace``: [(arrival_s, image)] sorted by arrival. The clock
    advances by (a) jumps to the next arrival / flush deadline while
    idle and (b) each flush's MEASURED wall time while serving — so a
    too-slow arm accumulates queueing delay exactly as a real frontend
    would, without wall-clock sleeps between arrivals.
    """
    now, i = 0.0, 0
    responses = []
    obs = getattr(engine, "observer", None)
    while i < len(trace) or engine.queue_len:
        while i < len(trace) and trace[i][0] <= now:
            engine.submit(trace[i][1], request_id=i, arrival_s=trace[i][0],
                          slo=slo_class(trace[i][1], engine.layout))
            i += 1
        if engine.should_flush(now) or (i >= len(trace) and engine.queue_len):
            t0 = time.perf_counter()
            out = engine.flush()
            now += time.perf_counter() - t0
            for r in out:
                r.done_s = now
                if obs is not None:
                    # end-to-end latency on the replay's VIRTUAL clock,
                    # so the streaming histograms estimate the same
                    # quantity as the exact-sample percentiles below
                    obs.observe_latency(r.slo, r.latency_s, r.request_id)
            responses.extend(out)
            continue
        nxt = []
        if i < len(trace):
            nxt.append(trace[i][0])
        deadline = engine.flush_deadline()
        if deadline is not None:
            nxt.append(deadline)
        if not nxt:
            break
        # always advance: should_flush reuses flush_deadline's exact
        # arithmetic (serve/batcher.py) so jumping TO the deadline
        # fires it, but a stalled clock here would spin forever
        target = max(now, min(nxt))
        now = target if target > now else now + 1e-6
    out = _lat_summary([r.latency_s for r in responses])
    by_slo: dict = {}
    for r in responses:
        by_slo.setdefault(r.slo, []).append(r.latency_s)
    # exact per-class percentiles — the reference the streaming
    # histograms (serve.obs.slo in the same record) are judged against
    # in scripts/obs_report.py, one bucket width apart at most
    out["by_slo"] = {slo: _lat_summary(v)
                     for slo, v in sorted(by_slo.items())}
    return out


# ---------------- per-arm measurement ----------------


def measure_arm(engine, warm_images, meas_images, trace,
                serve_summary, warn_fn, observer=None) -> tuple[dict, list]:
    """Disjoint warmup draw, sustained drain, rated replay, summary.

    The observer attaches AFTER warmup, beside the host_sync reset, so
    its pack/request counters cover exactly the measured window — that
    alignment is what lets obs_report.py pin fetches-per-pack == 1
    (zero blocking syncs added by the observability plane)."""
    from dinov3_tpu.telemetry.host_sync import host_sync_stats

    drain_all(engine, warm_images)
    compiles_after_warmup = engine.compile_count

    host_sync_stats(reset=True)
    engine.reset_pad_stats()
    engine.observer = observer
    wall, responses = drain_all(engine, meas_images)
    lat = rated_replay(engine, trace)
    warm_shapes = {im.shape for im in warm_images}
    rec = {
        "throughput": {
            "images_per_s": round(len(meas_images) / wall, 3),
            "wall_s": round(wall, 4),
        },
        "latency": lat,
        "compile_count_after_warmup": compiles_after_warmup,
        "compile_growth_during_measurement": (
            engine.compile_count - compiles_after_warmup),
        "novel_shapes_after_warmup": len(
            {im.shape for im in meas_images} - warm_shapes),
        "serve": serve_summary(engine),
        "pad_waste_warning": warn_fn(engine.mean_pad_waste or 0.0),
    }
    engine.observer = None
    return rec, responses


def feature_agreement(a, b) -> dict:
    """Max |diff| between two arms' responses, matched by request id."""
    bb = {r.request_id: r for r in b}
    cls = max(float(np.abs(r.cls_feature - bb[r.request_id].cls_feature).max())
              for r in a)
    pooled = max(float(np.abs(r.pooled_patch_feature
                              - bb[r.request_id].pooled_patch_feature).max())
                 for r in a)
    return {"cls_max_abs_diff": cls, "pooled_max_abs_diff": pooled}


# ---------------- the fleet benchmark (SERVE_r16) ----------------
#
# ISSUE 12 acceptance: a multi-class rated replay (>= 2 SLO classes x
# >= 2 engines x cache hit-rate sweep {0, 0.5, 0.9}) with per-(engine,
# SLO) p50/p99, an int8-vs-bf16 single-engine A/B on the same mix
# (throughput + CLS drift under serve.quant.drift_tol), cache-hit
# responses bitwise-equal to their miss, and exactly n_engines total
# compiles across the whole replay. The fleet: an int8 fast lane whose
# envelope is DERIVED from the measured interactive mix
# (LiveMixTracker.recommended_serve_envelope — the PR-11 telemetry the
# admission layer was built for) next to the full bf16 row, with the
# content-addressed cache (serve/cache.py) in front.


def repeat_trace(rng, fresh_images, n_req, hit_rate):
    """A request sequence with repeated content at ~``hit_rate``: each
    position repeats a uniformly chosen EARLIER position's image object
    with probability hit_rate, else takes the next fresh image.
    Repeats reuse the same array object, so the content hash — and the
    route (same shape -> same engine) — are identical by construction.
    The measured hit rate trails the target slightly when a repeat
    lands while its original is still in flight (a miss that computes
    twice — reported honestly per sweep)."""
    seq = []
    fresh_i = 0
    for _ in range(int(n_req)):
        if seq and rng.random() < hit_rate:
            seq.append(seq[int(rng.integers(len(seq)))])
        else:
            seq.append(fresh_images[fresh_i % len(fresh_images)])
            fresh_i += 1
    return seq


def fleet_drain(router, images, layout) -> tuple[float, list]:
    """Sustained drain through the admission layer (all arrivals t=0)."""
    for i, im in enumerate(images):
        router.submit(im, request_id=i, arrival_s=0.0,
                      slo=slo_class(im, layout))
    t0 = time.perf_counter()
    responses = []
    while router.queue_len:
        responses.extend(router.flush())
    wall = time.perf_counter() - t0
    assert len(responses) == len(images)
    return wall, responses


def fleet_rated_replay(router, trace, layout) -> tuple[list, dict]:
    """The virtual-clock rated replay (see ``rated_replay``) through a
    ``FleetRouter``, auditing the cache as it goes: every hit response
    is compared BITWISE against the latest preceding computed (miss)
    response for the same image — the frozen-weights memoization claim,
    checked on the live replay rather than assumed. ``flush(now)``
    flushes only due engines mid-trace; the drain tail flushes all."""
    now, i = 0.0, 0
    responses: list = []
    obs = router.observer
    last_miss: dict = {}
    audit = {"hits": 0, "bitwise_failures": 0}
    while i < len(trace) or router.queue_len:
        while i < len(trace) and trace[i][0] <= now:
            router.submit(trace[i][1], request_id=i, arrival_s=trace[i][0],
                          slo=slo_class(trace[i][1], layout))
            i += 1
        if router.should_flush(now) or (i >= len(trace) and router.queue_len):
            t0 = time.perf_counter()
            out = router.flush(now if i < len(trace) else None)
            now += time.perf_counter() - t0
            for r in out:
                r.done_s = now
                img = trace[r.request_id][1]
                if r.cache_hit:
                    audit["hits"] += 1
                    ref = last_miss.get(id(img))
                    if ref is None or not (
                            np.array_equal(r.cls_feature, ref.cls_feature)
                            and np.array_equal(r.pooled_patch_feature,
                                               ref.pooled_patch_feature)):
                        audit["bitwise_failures"] += 1
                else:
                    last_miss[id(img)] = r
                if obs is not None:
                    # per-(engine, SLO) streaming histograms: the key
                    # the fleet's latency plane aggregates on
                    obs.observe_latency(f"{r.engine}/{r.slo}",
                                        r.latency_s, r.request_id)
            responses.extend(out)
            continue
        nxt = []
        if i < len(trace):
            nxt.append(trace[i][0])
        deadline = router.flush_deadline()
        if deadline is not None:
            nxt.append(deadline)
        if not nxt:
            break
        target = max(now, min(nxt))
        now = target if target > now else now + 1e-6
    return responses, audit


def run_fleet(args, cfg, mixes, tracer) -> dict:
    """The SERVE_r16 record: quant A/B + derived-envelope fleet +
    cache hit-rate sweep. Returns the record dict (main() writes it)."""
    import bench
    from dinov3_tpu.configs.config import (
        serve_obs_kwargs,
        warn_quant_drift,
    )
    from dinov3_tpu.serve import (
        PackedServeEngine,
        build_serve_fleet,
        load_serving_model,
        quant_feature_drift,
        quant_summary,
        quantize_serving_tree,
        serve_layout_from_cfg,
    )
    from dinov3_tpu.telemetry import LiveMixTracker, ServeObserver

    n = args.n or (12 if args.smoke else 64)
    qcfg = cfg.serve.get("quant") or {}
    tol = float(qcfg.get("drift_tol", 0.05) or 0.05)

    t0 = time.perf_counter()
    model, params = load_serving_model(cfg)
    layout = serve_layout_from_cfg(cfg)
    print(f"[bench_serve] fleet: {cfg.student.arch} base rows="
          f"{layout.rows}x{layout.row_tokens} envelope={layout.min_px}.."
          f"{layout.max_px}px build {time.perf_counter() - t0:.1f}s",
          flush=True)

    bands = mixes["mixed_ragged"]
    rng = np.random.default_rng(args.seed)
    warm_images = make_mix(rng, bands, n, layout.patch_size)
    meas_images = make_mix(rng, bands, n, layout.patch_size)

    # ---- (a) int8 quantization: drift probe + single-engine A/B ----
    qtree = quantize_serving_tree(params)
    probe_px = int(qcfg.get("probe_px", 0) or 0)
    if probe_px <= 0:
        p = layout.patch_size
        probe_px = max(p, (min(layout.max_px, 224) // p) * p)
    drift = quant_feature_drift(model, params, qtree, px=probe_px,
                                seed=args.seed)
    drift_warning = warn_quant_drift(
        drift["cls_max_abs_diff"], tol=tol,
        axis=f"int8 serving tree, {probe_px}px CLS probe")
    print(f"[bench_serve] quant drift: {drift} (tol {tol})", flush=True)

    eng = {"bf16": PackedServeEngine(model, params, layout, warn=False),
           "int8": PackedServeEngine(model, qtree, layout, warn=False)}
    for e in eng.values():
        drain_all(e, warm_images)
    reps = 2 if args.smoke else 3
    best = {}
    ab_responses = {}
    for _ in range(reps):
        # alternate arms within each rep so drift in machine load hits
        # both symmetrically; keep the best (least-perturbed) drain
        for name, e in eng.items():
            wall, rs = drain_all(e, meas_images)
            rate = len(meas_images) / wall
            if rate > best.get(name, 0.0):
                best[name] = rate
            ab_responses[name] = rs
    agreement = feature_agreement(ab_responses["bf16"],
                                  ab_responses["int8"])
    quant_rec = {
        "drift_probe": drift,
        "drift_tol": tol,
        "drift_warning": drift_warning,
        "summary": quant_summary(qtree),
        "throughput": {
            "reps_best_of": reps,
            "bf16_images_per_s": round(best["bf16"], 3),
            "int8_images_per_s": round(best["int8"], 3),
            "int8_over_bf16": round(best["int8"] / best["bf16"], 4),
        },
        "packed_feature_agreement": agreement,
    }
    print(f"[bench_serve] quant A/B: bf16 {best['bf16']:.3f} img/s, "
          f"int8 {best['int8']:.3f} img/s "
          f"(x{best['int8'] / best['bf16']:.3f})", flush=True)

    # ---- (b) the fleet: derived int8 fast lane + full bf16 row ----
    tracker = LiveMixTracker(layout)
    for im in warm_images:
        if slo_class(im, layout) == "interactive":
            tracker.observe_request(
                layout.seq_len(im.shape[0], im.shape[1]),
                im.shape[0], im.shape[1])
    tracker.roll()
    env = tracker.recommended_serve_envelope(threshold=0.15)
    assert env is not None, "no interactive traffic in the warm draw"
    cfg.serve.fleet.engines = [
        {"name": "fast_int8", "slo": "interactive", "quant": True,
         "rows": env["rows"], "row_tokens": env["row_tokens"],
         "max_segments_per_row": env["max_segments_per_row"],
         "min_px": env.get("min_px"), "max_px": env.get("max_px")},
        {"name": "full_bf16"},
    ]
    router = build_serve_fleet(cfg, params=params, warn=False)
    n_engines = len(router.specs)
    compiles_at_build = router.compile_count
    fleet_obs = ServeObserver(tracer, layout, slo_classes=(),
                              **serve_obs_kwargs(cfg))
    fleet_obs.set_labels(mix="fleet")
    router.observer = fleet_obs
    for spec in router.specs:
        o = ServeObserver(tracer, spec.engine.layout,
                          slo_classes=("interactive", "batch"),
                          **serve_obs_kwargs(cfg))
        o.set_labels(arm=spec.engine.arm, mix="fleet", engine=spec.name)
        spec.engine.observer = o
    print(f"[bench_serve] fleet engines: "
          + ", ".join(f"{s.name}({s.engine.arm} "
                      f"{s.engine.layout.rows}x{s.engine.layout.row_tokens})"
                      for s in router.specs)
          + f", {compiles_at_build} compiles", flush=True)

    # cold-cache sustained rate sets the offered rate for every sweep
    wall, _ = fleet_drain(router, warm_images, layout)
    rate = 0.7 * (n / wall)

    sweeps = {}
    for hit_rate in (0.0, 0.5, 0.9):
        router.cache.clear(reset_counters=True)
        seq = repeat_trace(rng, meas_images, n, hit_rate)
        arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n))
        trace = [(float(a), im) for a, im in zip(arrivals, seq)]
        responses, audit = fleet_rated_replay(router, trace, layout)
        assert len(responses) == n
        by_key: dict = {}
        by_slo: dict = {}
        for r in responses:
            by_key.setdefault(f"{r.engine}/{r.slo}", []).append(r.latency_s)
            by_slo.setdefault(r.slo, []).append(r.latency_s)
        stats = router.cache.stats()
        sweeps[f"hit_{hit_rate}"] = {
            "target_hit_rate": hit_rate,
            "measured_hit_rate": stats["hit_rate"],
            "n_responses": len(responses),
            "cache": stats,
            "cache_hits_bitwise_equal": audit["bitwise_failures"] == 0,
            "cache_hit_responses": audit["hits"],
            "latency": _lat_summary([r.latency_s for r in responses]),
            "by_engine_slo": {k: _lat_summary(v)
                              for k, v in sorted(by_key.items())},
            "by_slo": {k: _lat_summary(v)
                       for k, v in sorted(by_slo.items())},
            "compile_count": router.compile_count,
            "compile_growth": router.compile_count - compiles_at_build,
        }
        print(f"[bench_serve] fleet hit={hit_rate}: measured "
              f"{stats['hit_rate']} p99 "
              f"{sweeps[f'hit_{hit_rate}']['latency']['p99_ms']}ms "
              f"routes {dict(router.route_counts)}", flush=True)

    # forced hit: same image twice, back to back — the CI smoke's
    # bitwise claim in its smallest reproducible form
    probe_img = meas_images[0]
    router.cache.clear(reset_counters=True)
    router.submit(probe_img, request_id=900001, arrival_s=0.0,
                  slo=slo_class(probe_img, layout))
    miss = []
    while router.queue_len:
        miss.extend(router.flush())
    router.submit(probe_img, request_id=900002, arrival_s=0.0,
                  slo=slo_class(probe_img, layout))
    hit = []
    while router.queue_len:
        hit.extend(router.flush())
    forced_ok = (len(miss) == 1 and len(hit) == 1 and hit[0].cache_hit
                 and not miss[0].cache_hit
                 and np.array_equal(miss[0].cls_feature,
                                    hit[0].cls_feature)
                 and np.array_equal(miss[0].pooled_patch_feature,
                                    hit[0].pooled_patch_feature))

    fleet_rec = {
        "derived_fast_envelope": env,
        "offered_rate_images_per_s": round(rate, 3),
        "sweeps": sweeps,
        "forced_hit_bitwise": bool(forced_ok),
        "drift_check": router.check_drift(warn=False),
        "summary": bench._fleet_summary(router),
        "observer": fleet_obs.finalize(),
    }
    router.finalize()

    return {
        "what": ("quantized multi-tenant serving fleet: int8-vs-bf16 "
                 "single-engine A/B (drift probe + best-of-k sustained "
                 "drains on the same mixed-ragged draw), then a 2-engine "
                 "fleet — an int8 fast lane whose envelope is derived "
                 "from the measured interactive mix next to the full "
                 "bf16 row — behind one SLO/shape admission layer with "
                 "the content-addressed feature cache in front, rated-"
                 "replayed at cache hit rates {0, 0.5, 0.9} with "
                 "per-(engine, SLO) p50/p99, every cache hit audited "
                 "bitwise against its miss, and total compiles pinned "
                 "at n_engines"),
        "arch": cfg.student.arch,
        "smoke": bool(args.smoke),
        "seed": args.seed,
        "n_per_sweep": n,
        "backend": __import__("jax").default_backend(),
        "layout": {
            "rows": layout.rows, "row_tokens": layout.row_tokens,
            "token_budget": layout.token_budget,
            "n_prefix": layout.n_prefix,
            "patch_size": layout.patch_size,
            "min_px": layout.min_px, "max_px": layout.max_px,
            "max_segments_per_row": layout.max_segments_per_row,
        },
        "quant": quant_rec,
        "fleet": fleet_rec,
        "n_engines": n_engines,
        "compile_count_total": router.compile_count,
        "compile_growth_total": router.compile_count - compiles_at_build,
    }


# ---------------- main ----------------


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="vit_test + tiny envelope (CI tier-1 step)")
    ap.add_argument("--fleet", action="store_true",
                    help="the SERVE_r16 fleet benchmark: int8-vs-bf16 "
                         "A/B + 2-engine SLO-routed fleet + cache "
                         "hit-rate sweep (default --out SERVE_r16.json)")
    ap.add_argument("--out", default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--n", type=int, default=None,
                    help="images per mix (default: 64 full / 12 smoke)")
    ap.add_argument("--obs-dir", default=None,
                    help="output dir for the serve span stream "
                         "(telemetry/serve_obs.py; scripts/obs_report.py "
                         "folds it into the OBS artifact). Default: a "
                         "temp dir.")
    args = ap.parse_args()
    if args.out is None:
        args.out = "SERVE_r16.json" if args.fleet else "SERVE_r14.json"

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    import bench
    from dinov3_tpu.configs.config import (
        apply_dot_overrides,
        get_default_config,
        serve_obs_kwargs,
        serve_pad_waste_floor,
        warn_serve_pad_waste,
    )
    from dinov3_tpu.serve import (
        OracleServeEngine,
        PackedServeEngine,
        load_serving_model,
        serve_layout_from_cfg,
    )
    from dinov3_tpu.telemetry import ServeObserver, SpanTracer
    from dinov3_tpu.utils import hlo_collective_census, hlo_copy_census

    n = args.n or (12 if args.smoke else 64)
    cfg = get_default_config()
    if args.smoke:
        apply_dot_overrides(cfg, [
            "student.arch=vit_test", "student.patch_size=4",
            "serve.min_px=8", "serve.max_px=32", "serve.rows=4",
            "serve.row_tokens=65", "serve.max_segments_per_row=12",
            "train.scan_layers=true",
        ])
        mixes = MIXES_SMOKE
    else:
        apply_dot_overrides(cfg, [
            "student.arch=vit_small", "train.scan_layers=true",
            # one max-envelope image per row (min fixed pack cost: the
            # dense segment-masked attention is O(row_tokens^2)/row),
            # slots sized so a row of 96px requests (27 fit) isn't
            # slot-capped into padding
            "serve.rows=4", "serve.row_tokens=1025",
            "serve.max_segments_per_row=28",
        ])
        mixes = MIXES_FULL

    obs_dir = args.obs_dir
    if obs_dir is None:
        import tempfile

        obs_dir = tempfile.mkdtemp(prefix="bench_serve_obs_")
    # ONE serve-role tracer for the whole run: every (mix, arm)
    # observer writes into the same spans.serve.jsonl stream, labelled,
    # the way a deployment's engine pool would share one stream
    tracer = SpanTracer(obs_dir, role="serve")
    print(f"[bench_serve] serve span stream: {tracer.spans_path}",
          flush=True)

    if args.fleet:
        record = run_fleet(args, cfg, mixes, tracer)
        with open(args.out, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"[bench_serve] wrote {args.out}")
        return 0

    t0 = time.perf_counter()
    model, params = load_serving_model(cfg)
    layout = serve_layout_from_cfg(cfg)
    floor = serve_pad_waste_floor(
        layout.row_tokens, layout.patch_size, layout.n_prefix,
        layout.min_px, layout.max_px)
    print(f"[bench_serve] {cfg.student.arch} rows={layout.rows} "
          f"row_tokens={layout.row_tokens} budget={layout.token_budget} "
          f"envelope={layout.min_px}..{layout.max_px}px "
          f"floor(mean)={floor['mean_waste']:.3f} "
          f"build {time.perf_counter() - t0:.1f}s", flush=True)

    def build_engine(arm):
        if arm == "packed":
            return PackedServeEngine(model, params, layout, warn=False)
        return OracleServeEngine(model, params, layout,
                                 mode=arm.removeprefix("oracle_"))

    record = {
        "what": ("continuous-packing serve engine vs naive oracles: "
                 "sustained img/s + rated p50/p99 over three traffic "
                 "mixes, identical bf16 weights and batcher policy; "
                 "oracle arms warm on a disjoint draw, so their "
                 "recompiles on novel traffic shapes are measured "
                 "serving cost"),
        "arch": cfg.student.arch,
        "smoke": bool(args.smoke),
        "seed": args.seed,
        "n_per_mix": n,
        "backend": jax.default_backend(),
        "layout": {
            "rows": layout.rows, "row_tokens": layout.row_tokens,
            "token_budget": layout.token_budget,
            "n_prefix": layout.n_prefix,
            "patch_size": layout.patch_size,
            "min_px": layout.min_px, "max_px": layout.max_px,
            "max_segments_per_row": layout.max_segments_per_row,
        },
        "pad_waste_floor": {k: round(v, 4) if isinstance(v, float) else v
                            for k, v in floor.items()},
        "mixes": {},
    }

    arms = ("packed", "oracle_rectangular", "oracle_per_image")
    engines = {arm: build_engine(arm) for arm in arms}

    # the one packed program's census, from its optimized HLO
    hlo = engines["packed"].compiled_text()
    copies = hlo_copy_census(hlo)
    colls = hlo_collective_census(hlo)
    record["packed_census"] = {
        "compile_s": round(engines["packed"].compile_s, 3),
        "copy_total": copies["hlo_copy_total"],
        "copy_by_category": {k: v["ops"]
                             for k, v in copies["by_category"].items()},
        "collective_total": colls["hlo_collective_total"],
        "collective_unattributed": colls["unattributed"],
    }

    for mix_name, bands in mixes.items():
        rng = np.random.default_rng(args.seed)
        warm_images = make_mix(rng, bands, n, layout.patch_size)
        meas_images = make_mix(rng, bands, n, layout.patch_size)
        tokens = sum(layout.seq_len(im.shape[0], im.shape[1])
                     for im in meas_images)
        mix_rec = {
            "n": n,
            "measured_tokens": tokens,
            "distinct_shapes_measured": len(
                {im.shape for im in meas_images}),
        }
        responses = {}

        # packed first: its sustained rate sets the rated-replay
        # arrival trace every arm then replays
        trace = None
        for arm in arms:
            eng = engines[arm]
            print(f"[bench_serve] {mix_name}/{arm} ...", flush=True)
            if trace is None:
                # probe the packed sustained rate on the warmup draw
                # (its own warmup: the AOT program needs one execution
                # for allocator/runtime steady state)
                drain_all(eng, warm_images)
                wall, _ = drain_all(eng, warm_images)
                rate = 0.7 * (n / wall)
                arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n))
                trace = [(float(a), im)
                         for a, im in zip(arrivals, meas_images)]
                mix_rec["offered_rate_images_per_s"] = round(rate, 3)
            observer = ServeObserver(tracer, layout,
                                     slo_classes=("interactive", "batch"),
                                     **serve_obs_kwargs(cfg))
            observer.set_labels(arm=arm, mix=mix_name)
            arm_rec, resp = measure_arm(
                eng, warm_images, meas_images, trace,
                lambda e: bench._serve_summary(
                    e, copies if e.arm == "packed" else None),
                lambda w, a=arm: warn_serve_pad_waste(
                    w, stacklevel=3,
                    axis=f"measured {mix_name} mix, {a} arm"),
                observer=observer,
            )
            mix_rec[arm] = arm_rec
            responses[arm] = resp

        for arm in ("oracle_rectangular", "oracle_per_image"):
            mix_rec[f"features_vs_{arm}"] = feature_agreement(
                responses["packed"], responses[arm])
        mix_rec["speedup_vs_rectangular"] = round(
            mix_rec["packed"]["throughput"]["images_per_s"]
            / mix_rec["oracle_rectangular"]["throughput"]["images_per_s"], 3)
        mix_rec["speedup_vs_per_image"] = round(
            mix_rec["packed"]["throughput"]["images_per_s"]
            / mix_rec["oracle_per_image"]["throughput"]["images_per_s"], 3)
        record["mixes"][mix_name] = mix_rec
        print(f"[bench_serve] {mix_name}: packed "
              f"{mix_rec['packed']['throughput']['images_per_s']} img/s, "
              f"rect x{mix_rec['speedup_vs_rectangular']}, "
              f"per-image x{mix_rec['speedup_vs_per_image']}", flush=True)

    record["packed_compile_count"] = engines["packed"].compile_count
    tracer.close()
    from dinov3_tpu.telemetry.spans import SPAN_SCHEMA_V

    record["obs"] = {"spans_path": os.path.abspath(tracer.spans_path),
                     "schema_v": SPAN_SCHEMA_V}

    out = json.dumps(record, indent=1)
    with open(args.out, "w") as f:
        f.write(out + "\n")
    print(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
