"""Elastic topology engine: the chaos harness behind RESHARD_r23.json.

ONE training run is killed and resumed across THREE topologies on the
8-simulated-device CPU mesh — replicated@dp8 -> zero3@dp2xfsdp4 ->
zero3@dp8 — exercising BOTH elastic resume paths of the shipped trainer
(train/train.py do_train + train/setup.py elastic_resume):

- leg 0 -> leg 1 is an in-process resize WITHOUT preemption: the live
  ``TrainState`` is resharded in memory (``parallel/reshard.py``) onto
  the new mesh/arm, no disk round-trip (``--resume-topology memory``);
- leg 1 -> leg 2 is a real preemption: the programmatic
  ``PreemptionHandler.notice()`` kill path drives the final atomic save
  (write-then-finalize marker), the next incarnation restores the
  checkpoint ACROSS the topology change (``--resume-topology disk``).

Pins (asserted, then committed as the record):

- **bitwise loss trajectory**: the stitched 3-topology chaos run's
  per-iteration losses equal the unreshaped replicated@dp8 oracle's
  BITWISE, every iteration (under jax_default_matmul_precision=highest,
  the tests/conftest.py pin discipline). zero3 arms are bitwise vs the
  fused replicated update (tests/test_zero3.py); the bucketed arm is
  deliberately NOT a trajectory leg — its packed Adam update rounds
  last-ulp differently (measured here, reported in the record) — it
  rides the transition instrument below instead.
- **census honesty**: every in-memory transfer compiles to one program
  per leaf-group with EVERY collective attributed to its ``reshard_*``
  scope — zero unattributed, zero leakage into other scopes.
- **in-memory vs disk**: on the same transition, the in-memory
  transfer's execution beats the disk round-trip (atomic save +
  finalize + cross-arm restore) wall-clock; the one-time shape-keyed
  jit compile of the 4 group programs is reported alongside (at the
  vit_test probe size it rivals the tiny disk round-trip — at real
  state sizes the transfer scales with bytes while compile stays
  seconds, and repeats of the same resize pay it once).
- **preemption chain**: the span stream carries the full
  preempt_notice -> preempt_save -> resume_restore chain and the
  preemption-to-resume latency (``since_preempt_s``) for both resume
  paths; step-pitch / straggler z-scores (telemetry/anatomy.py
  fleet_report) are reported per leg, before/after each reshape.

``--smoke`` is the CI variant: oracle + two legs (memory-path resume
only), one A/B transition, same asserts.

Usage: JAX_PLATFORMS=cpu python scripts/cost_reshard.py [out] [--smoke]
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMOKE = "--smoke" in sys.argv
_pos = [a for a in sys.argv[1:] if not a.startswith("--")]
OUT = _pos[0] if _pos else (None if SMOKE else "RESHARD_r23.json")
N_DEV = 8

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += \
        f" --xla_force_host_platform_device_count={N_DEV}"

import jax  # noqa: E402
import numpy as np  # noqa: E402

# the bitwise-pin precision discipline (tests/conftest.py): reduction
# order differs across meshes; highest-precision matmuls make the
# cross-topology step bitwise-reproducible on CPU
jax.config.update("jax_default_matmul_precision", "highest")

from dinov3_tpu.configs import load_config  # noqa: E402
from dinov3_tpu.parallel.reshard import (  # noqa: E402
    describe_topology,
    reshard_state,
    topology_of,
)
from dinov3_tpu.telemetry.anatomy import fleet_report  # noqa: E402

# the SMOL dryrun shape (tests/test_zero3.py convention) + synthetic
# data so every incarnation sees the same stream at the same iteration
SMOL = [
    "student.arch=vit_test", "student.patch_size=4",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2", "train.batch_size_per_device=2",
    "optim.scaling_rule=none", "train.scan_layers=true",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
    "dino.head_bottleneck_dim=16",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
    "ibot.head_bottleneck_dim=16",
    "data.backend=synthetic", "optim.warmup_epochs=0",
    # only preemption/final saves: the chaos schedule owns the ckpt dir
    "checkpointing.period=1000",
    # losses recorded+compared on the fp32-probs program (main() pins
    # the same when --record-losses is given on the CLI)
    "compute_precision.probs_dtype=fp32",
]

TOPOLOGIES = {
    "replicated@dp8": ["parallel.data=8", "parallel.zero3=false",
                       "optim.sharded_update=false",
                       "optim.bucketed_collectives=false"],
    "zero3@2x4": ["parallel.data=2", "parallel.fsdp=4",
                  "parallel.zero3=true",
                  "optim.bucketed_collectives=false"],
    "zero3@dp8": ["parallel.data=8", "parallel.zero3=true",
                  "optim.bucketed_collectives=false"],
    "bucketed@dp8": ["parallel.data=8", "parallel.zero3=false",
                     "optim.bucketed_collectives=true"],
}

N_ITERS = 4 if SMOKE else 9
KILLS = [2] if SMOKE else [3, 6]  # iteration counts per killed leg
LEGS = (["replicated@dp8", "zero3@2x4"] if SMOKE
        else ["replicated@dp8", "zero3@2x4", "zero3@dp8"])
RESUME_PATHS = [None, "memory"] if SMOKE else [None, "memory", "disk"]


def build_cfg(topo: str, outdir: str):
    cfg = load_config(None, overrides=SMOL + TOPOLOGIES[topo] + [
        f"train.OFFICIAL_EPOCH_LENGTH={N_ITERS}", "optim.epochs=1"])
    cfg.train.output_dir = outdir
    return cfg


def build_args(outdir: str, losses: str, *, fresh: bool,
               resume_topology: str = "auto"):
    from dinov3_tpu.train.train import get_args_parser

    argv = ["--output-dir", outdir, "--record-losses", losses,
            "--resume-topology", resume_topology]
    if fresh:
        argv.append("--no-resume")
    args = get_args_parser().parse_args(argv)
    args.keep_state = True  # the supervisor handle (do_train result)
    return args


def install_chaos_handler():
    """Patch the trainer's PreemptionHandler with one whose stop-poll
    fires ``notice()`` after a set number of polled iterations — a
    deterministic in-process preemption with the REAL signal-path
    bookkeeping (first-notice clock, preempt span chain, atomic final
    save), minus the test-runner races of a delivered SIGTERM."""
    import dinov3_tpu.run.preemption as prmod

    base = prmod.PreemptionHandler

    class ChaosHandler(base):
        kill_after_steps = None  # set per leg by the harness

        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self._polls = 0

        def should_stop(self):
            if type(self).kill_after_steps is not None:
                self._polls += 1
                if self._polls >= type(self).kill_after_steps:
                    self.notice("chaos_kill")
            return super().should_stop()

    prmod.PreemptionHandler = ChaosHandler
    return ChaosHandler


def read_losses(path: str) -> dict:
    rows = {}
    with open(path) as f:
        for line in f:
            r = json.loads(line)
            rows[int(r["iteration"])] = float(r["total_loss"])
    return rows


def span_records(outdir: str) -> list:
    recs = []
    spans = os.path.join(outdir, "telemetry", "spans.jsonl")
    if os.path.exists(spans):
        with open(spans) as f:
            for line in f:
                try:
                    recs.append(json.loads(line))
                except ValueError:
                    continue  # torn trailing line of a killed writer
    return recs


def leg_fleet(recs: list, lo: int, hi: int) -> dict:
    """fleet_report over one leg's iteration window [lo, hi): the
    step-pitch distribution + straggler z-scores before/after each
    reshape (z == 0 on this single-host harness — the schema the
    multi-host fleet fills in)."""
    window = [r for r in recs
              if r.get("iteration") is not None
              and lo <= int(r["iteration"]) < hi]
    rep = fleet_report({"host0": window})
    host = rep["hosts"].get("host0", {})
    return {"step_ms": host.get("step_ms"),
            "straggler_z": host.get("straggler_z"),
            "stragglers": rep["stragglers"],
            "verdict": rep["verdict"]}


def summarize_reshard_report(rep: dict) -> dict:
    return {
        "src": rep["src"], "dst": rep["dst"],
        "same_devices": rep["same_devices"],
        "census_ok": rep["census_ok"],
        "total_wall_ms": rep["total_wall_ms"],
        "total_run_ms": rep["total_run_ms"],
        "total_bytes": rep["total_bytes"],
        "groups": {
            scope: {
                "mode": g["mode"],
                "collectives": {k: v["ops"] for k, v in
                                g["census"]["by_class"].items()},
                "by_scope": {k: v["ops"] for k, v in
                             g["census"]["by_scope"].items()},
                "unattributed": g["census"]["unattributed"],
                "compile_ms": g.get("compile_ms"),
                "run_ms": g.get("run_ms"),
                "bytes": g["bytes"],
            } for scope, g in rep["groups"].items()
        },
        "padding_warnings": rep["padding_warnings"],
    }


def chaos_run(workdir: str) -> dict:
    """The killed-and-resumed run: one loss stream stitched across the
    legs, the preempt span chain, per-leg fleet views."""
    from dinov3_tpu.train.train import do_train

    chaos = install_chaos_handler()
    out = os.path.join(workdir, "chaos")
    os.makedirs(out, exist_ok=True)
    bounds = [0] + KILLS + [N_ITERS]

    legs, live = [], None
    for i, topo in enumerate(LEGS):
        chaos.kill_after_steps = (KILLS[i] - bounds[i]
                                  if i < len(KILLS) else None)
        losses = os.path.join(out, f"losses_leg{i}.jsonl")
        path = RESUME_PATHS[i]
        args = build_args(out, losses, fresh=(i == 0),
                          resume_topology=path or "auto")
        kw = {}
        if path == "memory":
            kw = {"live_state": live["state"], "live_topology":
                  live["topology"]}
        t0 = time.perf_counter()
        res = do_train(build_cfg(topo, out), args, **kw)
        leg_s = time.perf_counter() - t0
        assert res["iterations"] == bounds[i + 1], (
            topo, res["iterations"], bounds[i + 1])
        live = {"state": res["state"], "topology": res["topology"]}
        legs.append({"topology": topo,
                     "desc": describe_topology(res["topology"]),
                     "iterations": [bounds[i], bounds[i + 1]],
                     "resume_path": path, "wall_s": round(leg_s, 3),
                     "losses": losses})
        print(f"[leg {i}] {topo}: iters {bounds[i]}..{bounds[i + 1]} "
              f"(resume={path}, {leg_s:.1f}s)", file=sys.stderr)
    chaos.kill_after_steps = None

    stitched = {}
    for leg in legs:
        stitched.update(read_losses(leg.pop("losses")))
    assert sorted(stitched) == list(range(N_ITERS)), sorted(stitched)

    recs = span_records(out)
    chain = {name: [r for r in recs if r.get("name") == name]
             for name in ("preempt_notice", "preempt_save",
                          "resume_restore")}
    n_kills = len(KILLS)
    assert len(chain["preempt_notice"]) == n_kills, chain
    assert len(chain["preempt_save"]) == n_kills, chain
    # every resumed leg emitted its restore record with the measured
    # preemption-to-resume latency and the path it took
    restores = chain["resume_restore"]
    assert len(restores) == len(LEGS) - 1, restores
    assert [r["path"] for r in restores] == RESUME_PATHS[1:], restores
    assert all("since_preempt_s" in r for r in restores), restores

    fleet = {f"leg{i}:{leg['topology']}":
             leg_fleet(recs, *leg["iterations"])
             for i, leg in enumerate(legs)}
    return {
        "legs": legs,
        "losses": stitched,
        "preempt_chain": {
            k: [{f: r.get(f) for f in
                 ("iteration", "step", "signal", "dur_ms", "path",
                  "since_preempt_s") if f in r} for r in v]
            for k, v in chain.items()},
        "preempt_to_resume_s": [r["since_preempt_s"] for r in restores],
        "fleet": fleet,
    }


def oracle_run(workdir: str) -> dict:
    from dinov3_tpu.train.train import do_train

    out = os.path.join(workdir, "oracle")
    os.makedirs(out, exist_ok=True)
    losses = os.path.join(out, "losses.jsonl")
    res = do_train(build_cfg(LEGS[0], out),
                   build_args(out, losses, fresh=True))
    assert res["iterations"] == N_ITERS
    return {"losses": read_losses(losses), "state": res["state"],
            "topology": res["topology"]}


def transition_ab(workdir: str, live, src_topo) -> list:
    """In-memory reshard vs disk round-trip on the SAME transitions the
    chaos run crossed (+ the bucketed arm conversion in full mode):
    wall clock, per-group censuses, and the value pin (the two paths
    land bitwise-identical states)."""
    from dinov3_tpu.checkpoint import Checkpointer
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup

    pairs = [("replicated@dp8", "zero3@2x4")] if SMOKE else [
        ("replicated@dp8", "zero3@2x4"),
        ("zero3@2x4", "zero3@dp8"),
        ("replicated@dp8", "bucketed@dp8"),
    ]
    rows = []
    for src_name, dst_name in pairs:
        cfg = build_cfg(dst_name, workdir)
        import jax.numpy as jnp

        batch = {k: jnp.asarray(v) for k, v in
                 make_synthetic_batch(cfg, 16, seed=0).items()}
        s_dst = build_train_setup(cfg, batch, init_state=True)
        src = live["topology"] if src_name == src_topo else None
        assert src is not None or not SMOKE
        if src is None:
            # chain from the previous row's resharded state
            src, state = prev_dst, prev_state  # noqa: F821
        else:
            state = live["state"]

        t0 = time.perf_counter()
        new_state, rep = reshard_state(state, src, topology_of(s_dst))
        jax.block_until_ready(new_state.params)
        mem_s = time.perf_counter() - t0

        ckdir = tempfile.mkdtemp(dir=workdir)
        ck = Checkpointer(ckdir, async_save=False,
                          bucket_plan=getattr(s_dst, "bucket_plan",
                                              None))
        t0 = time.perf_counter()
        ck.save(int(state.step), state)
        ck.wait_until_finished()
        save_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        disk_state = ck.restore(s_dst.state)
        jax.block_until_ready(disk_state.params)
        restore_s = time.perf_counter() - t0
        ck.close()
        shutil.rmtree(ckdir, ignore_errors=True)

        for (pa, a), (_, b) in zip(
                jax.tree_util.tree_flatten_with_path(new_state)[0],
                jax.tree_util.tree_flatten_with_path(disk_state)[0]):
            assert np.array_equal(np.asarray(a), np.asarray(b)), (
                f"{src_name}->{dst_name}: memory and disk paths "
                f"disagree at {jax.tree_util.keystr(pa)}")

        disk_s = save_s + restore_s
        mem_run_s = rep["total_run_ms"] / 1e3
        rows.append({
            "src": src_name, "dst": dst_name,
            "in_memory": summarize_reshard_report(rep),
            # wall includes the one-time jit compile of the 4 group
            # programs — shape-keyed, amortized across resizes; run is
            # the recurring transfer cost the disk path competes with
            "in_memory_wall_s": round(mem_s, 4),
            "in_memory_run_s": round(mem_run_s, 4),
            "disk": {"save_s": round(save_s, 4),
                     "restore_s": round(restore_s, 4),
                     "total_s": round(disk_s, 4)},
            "memory_vs_disk_speedup": round(disk_s / mem_run_s, 2),
            "paths_bitwise_equal": True,
        })
        print(f"[transition] {src_name} -> {dst_name}: memory "
              f"{mem_s:.2f}s vs disk {disk_s:.2f}s", file=sys.stderr)
        prev_dst, prev_state = topology_of(s_dst), new_state
    return rows


def bucketed_ulp_probe(workdir: str, live) -> dict:
    """Why the bucketed arm is not a bitwise trajectory leg: one step of
    the packed-bucket Adam update vs the replicated fused update from
    the same resharded state — the loss matches, the weights round a
    last-ulp apart (the packed reduction order)."""
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup, put_batch
    import jax.numpy as jnp

    cfg_b = build_cfg("bucketed@dp8", workdir)
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg_b, 16, seed=0).items()}
    s_b = build_train_setup(cfg_b, batch, init_state=True)
    cfg_r = build_cfg("replicated@dp8", workdir)
    s_r = build_train_setup(cfg_r, batch, init_state=True)

    st_b, rep = reshard_state(live["state"], live["topology"],
                              topology_of(s_b))
    assert rep["census_ok"]
    st_r, _ = reshard_state(live["state"], live["topology"],
                            topology_of(s_r))
    it = int(live["state"].step)
    d_b = put_batch(batch, s_b.batch_shardings)
    d_r = put_batch(batch, s_r.batch_shardings)
    st_b2, m_b = s_b.step_fn(st_b, d_b, s_b.scalars(it),
                             jax.random.key(0))
    st_r2, m_r = s_r.step_fn(st_r, d_r, s_r.scalars(it),
                             jax.random.key(0))
    worst, diff_leaves, n = 0.0, 0, 0
    for a, b in zip(jax.tree_util.tree_leaves(st_b2.params),
                    jax.tree_util.tree_leaves(st_r2.params)):
        n += 1
        a, b = np.asarray(a), np.asarray(b)
        if not np.array_equal(a, b):
            diff_leaves += 1
            worst = max(worst, float(np.max(np.abs(
                a.astype(np.float64) - b.astype(np.float64)))))
    return {
        "loss_bitwise": float(m_b["total_loss"]) ==
        float(m_r["total_loss"]),
        "param_leaves_differing": [diff_leaves, n],
        "worst_abs_diff": worst,
    }


def main():
    t_start = time.time()
    workdir = tempfile.mkdtemp(prefix="cost_reshard_")
    try:
        oracle = oracle_run(workdir)
        chaos = chaos_run(workdir)

        # THE pin: the killed-and-resumed run's trajectory is the
        # oracle's, bitwise, across both reshapes and both resume paths
        mismatches = [
            it for it in range(N_ITERS)
            if chaos["losses"][it] != oracle["losses"][it]]
        assert not mismatches, {
            it: (chaos["losses"][it], oracle["losses"][it])
            for it in mismatches}

        transitions = transition_ab(workdir, {
            "state": oracle["state"], "topology": oracle["topology"]},
            LEGS[0])
        for row in transitions:
            assert row["in_memory"]["census_ok"], row
            assert all(g["unattributed"] == 0 for g in
                       row["in_memory"]["groups"].values()), row
            assert row["in_memory_run_s"] < row["disk"]["total_s"], (
                row["src"], row["dst"], row["in_memory_run_s"],
                row["disk"])

        record = {
            "record": "reshard/r23",
            "host": "cpu-sim", "n_devices": N_DEV, "smoke": SMOKE,
            "precision": "highest",
            "topologies": {k: TOPOLOGIES[k] for k in TOPOLOGIES},
            "chaos": {
                "n_iterations": N_ITERS,
                "kills_at": KILLS,
                "legs": chaos["legs"],
                "loss_bitwise_vs_oracle": True,
                "losses": {str(k): repr(v) for k, v in
                           sorted(chaos["losses"].items())},
                "preempt_chain": chaos["preempt_chain"],
                "preempt_to_resume_s": chaos["preempt_to_resume_s"],
                "fleet": chaos["fleet"],
            },
            "transitions": transitions,
        }
        if not SMOKE:
            record["bucketed_ulp_probe"] = bucketed_ulp_probe(
                workdir, {"state": oracle["state"],
                          "topology": oracle["topology"]})
            # the probe is the documented reason bucketed@dp8 rides the
            # transition instrument, not the bitwise trajectory
            assert record["bucketed_ulp_probe"]["loss_bitwise"]
            assert record["bucketed_ulp_probe"]["worst_abs_diff"] < 1e-6
        record["wall_s"] = round(time.time() - t_start, 1)

        print(json.dumps(record, indent=1))
        if OUT:
            with open(OUT, "w") as f:
                json.dump(record, f, indent=1)
                f.write("\n")
            print(f"wrote {OUT}", file=sys.stderr)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    main()
