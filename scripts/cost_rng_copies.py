"""Copy-class HLO accounting for the step-wide RNG-plan engine
(rng/plan.py): op counts + bytes + per-category attribution, plan vs
the legacy fold_in oracle, at two pass granularities.

Methodology (the PR-1/PR-2 discipline, scripts/cost_update_phase.py /
cost_target_phase.py): compile the EXACT jitted programs on the host
backend and count copy-class HLO instructions
(``copy``/``copy-start``/``copy-done``/``dynamic-update-slice``)
outside fusion bodies — the buffer-allocating set — with the shared
category attribution (utils.classify_copy: "rng" = u32 key/counter
plumbing, "donation_async", "small", "large"). Two granularities:

- ``step``: the full fused train step (fwd+bwd+clip+AdamW+EMA, donated
  state) — what the copy-census CI ceiling pins
  (tests/test_streaming_targets.py);
- ``student_fwd``: the student forward alone (value_and_grad of the
  meta-arch loss), where every device-side RNG consumer lives — the
  granularity that isolates the plan's effect from update-phase and
  donation copies.

The r5 on-chip profile priced the copy/small-op bucket at 14.8% of step
time (21,384 copy-done + 35,400 slice-done trace ops,
PROFILE_r05.json), and the PR-2 census attributed ~98% of the 518
compiled-step copies to RNG-scalar plumbing. This script is the
committed host-side before/after for the engine that removes them; the
on-chip A/B is armed as scripts/r6_queue.sh phR.

One JSON line on stdout -> commit as COST_RNG_r08.json.

Usage: JAX_PLATFORMS=cpu python scripts/cost_rng_copies.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import importlib.util

_spec = importlib.util.spec_from_file_location(
    "cost_target_phase", os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "cost_target_phase.py")
)
ctp = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(ctp)


# the census arch (cost_target_phase.py convention): the copy structure
# under audit — per-layer rng threading, donation aliasing, crop-concat
# copies — is depth/width-independent at this granularity, and vit_test
# keeps the CPU compile seconds-long.
# model.crop_packing is pinned OFF: this artifact (COST_RNG_r08.json)
# is the rng-plan engine's before/after on the two-pass program it was
# committed against; the PR-4 crop-packed engine independently removes
# the two-pass crop-boundary copies from both arms (518 -> 190 legacy /
# 144 -> 96 plan on the packed default, tests/test_streaming_targets.py
# re-pins that ceiling) and would blur the attribution here.
CENSUS_OVERRIDES = [
    "model.crop_packing=false",
    "student.arch=vit_test", "student.patch_size=4",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
    "dino.head_bottleneck_dim=16",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
    "ibot.head_bottleneck_dim=16",
    "optim.scaling_rule=none",
]


def census_cfg(extra=()):
    from dinov3_tpu.configs import apply_dot_overrides, get_default_config

    cfg = get_default_config()
    apply_dot_overrides(cfg, CENSUS_OVERRIDES + list(extra))
    return cfg


def student_fwd_census(cfg, B: int = 4) -> dict:
    """Copy census of the student forward+backward alone (the pass that
    holds every device-side RNG consumer)."""
    import jax
    import jax.numpy as jnp

    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train.ssl_meta_arch import SSLMetaArch
    from dinov3_tpu.utils import hlo_copy_census

    meta = SSLMetaArch(cfg)
    batch = {k: jnp.asarray(v)
             for k, v in make_synthetic_batch(cfg, B, seed=0).items()}
    params_abs = jax.eval_shape(
        lambda r: meta.init_params(r, batch), jax.random.key(0))

    def loss(student, teacher, rng):
        rng_plan = rngs = None
        if meta.rng_plan:
            rng_plan = meta.build_rng_plan(rng, batch)
        else:
            rngs = {
                "drop_path": jax.random.fold_in(rng, 0),
                "rope": jax.random.fold_in(rng, 1),
                "dropout": jax.random.fold_in(rng, 2),
            }
        total, _ = meta.forward(
            student, {"teacher": teacher}, batch, teacher_temp=0.07,
            state=meta.init_state(), iteration=jnp.zeros((), jnp.int32),
            rngs=rngs, rng_plan=rng_plan,
        )
        return total

    compiled = jax.jit(jax.grad(loss)).lower(
        params_abs["student"], params_abs["teacher"],
        jax.eval_shape(lambda: jax.random.key(0)),
    ).compile()
    return hlo_copy_census(compiled.as_text())


def main():
    from dinov3_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()

    rec = {"arch": "vit_test", "granularity": {}}
    arms = {"plan_on": [], "plan_off": ["rng.plan=false"]}
    step = {t: ctp.copy_census(census_cfg(e), B=4) for t, e in arms.items()}
    fwd = {t: student_fwd_census(census_cfg(e), B=4)
           for t, e in arms.items()}
    rec["granularity"]["step"] = step
    rec["granularity"]["student_fwd"] = fwd
    rec["reduction_pct"] = {
        g: round(100.0 * (1.0 - d["plan_on"]["hlo_copy_total"]
                          / max(1, d["plan_off"]["hlo_copy_total"])), 1)
        for g, d in rec["granularity"].items()
    }
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
