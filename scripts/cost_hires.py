"""High-res gram-anchoring stage on sequence-sharded attention: the
committed evidence behind COST_HIRES_r19.json (PR-1..6 discipline —
compile the exact shipped code paths, account from their compiled HLO).

The paper's second training phase (512-768px multi-crop with gram
anchoring) is the regime ring attention was built for: at 768px the
2309-token global crops pad the [N, N] softmax state past what a
per-device dense pass wants to hold, and sequence parallelism shards
the K/V rotation O(N/s) per device. Two instruments, both on the
8-simulated-device CPU mesh:

- **Executed gram-stage arms (vit_test)**: the full shipped train step
  (``build_train_setup``) with the gram loss + gram-teacher refresh
  cadence on, at the same 16-row GLOBAL batch on three meshes —
  ``parallel.seq=1`` (dp=8, the oracle), dp=4 x seq=2, and
  dp=2 x fsdp=2 x seq=2. ``kernels.ring_min_seq=1`` so the tiny
  17-token passes actually ring (the per-pass dispatch would otherwise
  keep them dense, which is the SHIPPED default — the override is the
  test hook, not the recommendation). Pins: every arm's census has
  zero unattributed collectives; the seq arms attribute
  ``ring_permute``-scoped collectives; losses stay finite through a
  gram refresh; and the seq arms' loss trajectories match the seq=1
  oracle within tolerance (same global batch, same init, same rng).
- **ViT-L attention-memory twins (compile-only + one executed parity
  point)**: standalone fwd+bwd attention programs at ViT-L geometry
  (16 heads x 64 head_dim) and the real high-res token counts
  (512px -> 1029, 768px -> 2309), dense on dp=8 vs ring on
  dp=4 x seq=2, one row per data shard either way. The pin is the
  tentpole's memory claim: per-device temp bytes at seq=2 measurably
  below seq=1 (O(N/s) K/V rotation vs the dense [N, N] state), with
  the ring program's ppermutes scope-attributed and zero
  unattributed. A single executed point (N=1029, fp32) records
  ring-vs-dense max|diff| with and without segment ids.

CPU-harness honesty: nothing here times anything — XLA:CPU wall times
would say nothing about TPU. The committed numbers are structural
(collective censuses, compiled per-device memory stats, loss
trajectories); the on-chip A/B is armed as scripts/r6_queue.sh phH.

One JSON record -> COST_HIRES_r19.json (argv[1], default
./COST_HIRES_r19.json); also printed to stdout. ``--smoke`` runs the
executed vit_test arms only (same asserts, no JSON write unless an out
path is given explicitly).

Usage: JAX_PLATFORMS=cpu python scripts/cost_hires.py [out] [--smoke]
"""

from __future__ import annotations

import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMOKE = "--smoke" in sys.argv
_pos = [a for a in sys.argv[1:] if not a.startswith("--")]
OUT = _pos[0] if _pos else (None if SMOKE else "COST_HIRES_r19.json")
DP = 8
GLOBAL_ROWS = 16

os.environ.setdefault("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += f" --xla_force_host_platform_device_count={DP}"

# the SMOL dryrun shape (tests/test_zero3.py convention) + the gram
# stage of tests/test_gram_and_hrft.py; drop-path off so the three
# mesh arms consume identical randomness for the equivalence pin
SMOL = [
    "student.arch=vit_test", "student.patch_size=4",
    "student.drop_path_rate=0.0",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2",
    # scan_layers stays FALSE across every arm: the seq arms would be
    # force-unscanned anyway (setup.py's nn.scan x ring-custom_vjp
    # guard), and the oracle must share the seq arms' param-tree shape
    # (scanned stacks fold init RNG differently) for the loss
    # equivalence pin to compare like with like
    "optim.scaling_rule=none", "train.scan_layers=false",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
    "dino.head_bottleneck_dim=16",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
    "ibot.head_bottleneck_dim=16",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "optim.warmup_epochs=1",
    "telemetry.async_metrics=false",
]
GRAM = [
    "gram.use_loss=true", "gram.ema_teacher=false",
    "gram.rep_update=true", "gram.update_frequency=2",
    "gram.it_first_update=2", "gram.max_updates=2",
    "crops.gram_teacher_crops_size=16",
    "kernels.ring_min_seq=1",
]
# same 16-row global batch on every mesh: batch_size_per_device scales
# with the arm's data-parallel world so rows x world stays fixed
ARMS = [
    ("seq1_oracle", ["parallel.data=8",
                     "train.batch_size_per_device=2"]),
    ("dp_seq", ["parallel.data=4", "parallel.seq=2",
                "train.batch_size_per_device=4"]),
    ("dp_fsdp_seq", ["parallel.data=2", "parallel.fsdp=2",
                     "parallel.seq=2",
                     "train.batch_size_per_device=4"]),
]
N_STEPS = 3

# ViT-L geometry at the high-res token counts (1 CLS + 4 registers +
# (px/16)^2 patches — the vitl16 recipes)
VITL_HEADS, VITL_HEAD_DIM = 16, 64
VITL_CASES = [(512, 1029), (768, 2309)]


def _log(msg):
    print(f"[cost_hires] {msg}", file=sys.stderr, flush=True)


def scope_ops(census, scope):
    return census["by_scope"].get(scope, {"ops": 0})["ops"]


def gram_stage_arm(name, overrides) -> dict:
    """Build the shipped gram-stage step on one mesh, census its
    compiled HLO, execute N_STEPS steps with the gram-refresh cadence
    applied between them, and return the record."""
    import jax
    import jax.numpy as jnp

    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.parallel.context import set_current_mesh
    from dinov3_tpu.train import build_train_setup, put_batch
    from dinov3_tpu.train.gram_refresh import refresh_gram, should_refresh_gram
    from dinov3_tpu.utils import hlo_collective_census

    cfg = get_default_config()
    apply_dot_overrides(cfg, SMOL + GRAM + overrides)
    batch = {k: jnp.asarray(v)
             for k, v in make_synthetic_batch(cfg, GLOBAL_ROWS, seed=0).items()}
    try:
        setup = build_train_setup(cfg, batch)
        mesh_shape = {k: int(v) for k, v in setup.mesh.shape.items()
                      if int(v) > 1}
        dbatch = put_batch(batch, setup.batch_shardings)
        _log(f"compiling {name} step (mesh {mesh_shape})...")
        compiled = setup.step_fn.lower(
            setup.state, dbatch, setup.scalars(0),
            jax.random.key(0)).compile()
        census = hlo_collective_census(compiled.as_text())
        state, losses, refreshes = setup.state, [], 0
        for it in range(N_STEPS):
            state, metrics = setup.step_fn(
                state, dbatch, setup.scalars(it), jax.random.key(it))
            losses.append(float(metrics["total_loss"]))
            if should_refresh_gram(cfg, it, refreshes):
                state = refresh_gram(state)
                refreshes += 1
    finally:
        set_current_mesh(None)
    return {
        "arm": name,
        "mesh": mesh_shape,
        "seq": mesh_shape.get("seq", 1),
        "loss_trajectory": losses,
        "gram_refreshes": refreshes,
        "collective_census": census,
    }


def vitl_attention_twins() -> dict:
    """Dense-on-dp8 vs ring-on-dp4xseq2 fwd+bwd attention programs at
    ViT-L geometry: compiled per-device memory stats + collective
    census per arm, one executed fp32 parity point at N=1029."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dinov3_tpu.ops.attention import xla_attention
    from dinov3_tpu.parallel.mesh import MeshSpec, build_mesh
    from dinov3_tpu.parallel.ring_attention import ring_attention
    from dinov3_tpu.utils import hlo_collective_census

    h, d = VITL_HEADS, VITL_HEAD_DIM
    mesh_dense = build_mesh(MeshSpec(data=DP))
    mesh_ring = build_mesh(MeshSpec(data=DP // 2, seq=2))
    b_axes = ("dcn_data", "data", "fsdp")
    cases = []
    for px, N in VITL_CASES:
        row = {"px": px, "N": N, "arms": {}}
        for arm, mesh, B, spec, fn in (
            ("dense_seq1", mesh_dense, DP, P(b_axes, None, None, None),
             lambda q, k, v: xla_attention(q, k, v)),
            # ring-arm inputs are batch-sharded only: ViT token counts
            # (1029, 2309) are odd, so the seq split happens INSIDE
            # ring_attention (pad + constrain into the islands), exactly
            # like the train step hands it activations
            ("ring_seq2", mesh_ring, DP // 2, P(b_axes, None, None, None),
             lambda q, k, v, m=mesh_ring: ring_attention(q, k, v, m)),
        ):
            # one row per data shard in both arms, so per-device stats
            # isolate the attention state, not the batch split
            shapes = [jax.ShapeDtypeStruct((B, N, h, d), jnp.float32)] * 3
            sh = NamedSharding(mesh, spec)
            _log(f"compiling {arm} @ {px}px (N={N})...")
            with mesh:
                compiled = jax.jit(
                    jax.grad(lambda q, k, v: jnp.sum(fn(q, k, v)),
                             argnums=(0, 1, 2)),
                    in_shardings=(sh, sh, sh),
                ).lower(*shapes).compile()
            mem = compiled.memory_analysis()
            row["arms"][arm] = {
                "rows_per_device": 1,
                "temp_bytes_per_device": int(mem.temp_size_in_bytes),
                "argument_bytes_per_device": int(mem.argument_size_in_bytes),
                "output_bytes_per_device": int(mem.output_size_in_bytes),
                "collective_census": hlo_collective_census(
                    compiled.as_text()),
            }
        cases.append(row)

    # executed parity at the 512px count: ring (seq mesh) vs the plain
    # dense oracle, with and without crop-packed segment ids
    B, N = 2, VITL_CASES[0][1]
    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (B, N, h, d), jnp.float32)
               for kk in ks)
    seg = (jnp.arange(N)[None, :] >= N // 2).astype(jnp.int32).repeat(B, 0)
    ring = jax.jit(lambda q, k, v, s: ring_attention(
        q, k, v, mesh_ring, seg=s), static_argnums=())
    diff_plain = float(jnp.abs(
        jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh_ring))(q, k, v)
        - xla_attention(q, k, v)).max())
    diff_seg = float(jnp.abs(
        ring(q, k, v, seg) - xla_attention(q, k, v, seg=seg)).max())
    return {
        "cases": cases,
        "executed_parity": {
            "N": N, "dtype": "float32",
            "max_abs_diff_plain": diff_plain,
            "max_abs_diff_segmented": diff_seg,
        },
    }


def main():
    from dinov3_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()
    import jax

    try:
        jax.config.update("jax_num_cpu_devices", DP)
    except AttributeError:
        pass

    arms = [gram_stage_arm(name, ovr) for name, ovr in ARMS]

    # ---- acceptance pins (ISSUE 15) ----
    for rec in arms:
        c = rec["collective_census"]
        assert c["unattributed"] == 0, (rec["arm"], c["unattributed"])
        assert all(math.isfinite(v) for v in rec["loss_trajectory"]), rec
        assert rec["gram_refreshes"] >= 1, rec["arm"]
        if rec["seq"] > 1:
            # ring collectives present AND attributed to their scope
            assert scope_ops(c, "ring_permute") > 0, (
                rec["arm"], sorted(c["by_scope"]))
    oracle = arms[0]
    assert oracle["seq"] == 1
    equiv = {}
    for rec in arms[1:]:
        rel = [abs(a - b) / max(1.0, abs(a)) for a, b in
               zip(oracle["loss_trajectory"], rec["loss_trajectory"])]
        equiv[rec["arm"]] = {"rel_loss_diff": rel}
        # same global batch, same init, same rng: the seq split only
        # reorders reductions
        assert max(rel) < 5e-2, (rec["arm"], rel)

    out = {
        "what": ("high-res gram-anchoring stage on sequence-sharded, "
                 "segment-masked ring attention: executed gram-stage "
                 "arms on seq=1/dp x seq/dp x fsdp x seq meshes + "
                 "ViT-L attention-memory twins at 512/768px"),
        "global_batch_rows": GLOBAL_ROWS,
        "n_steps": N_STEPS,
        "hires_step": {"arms": arms, "oracle": "seq1_oracle",
                       "loss_equivalence": equiv},
        "unattributed_collective_ms": 0.0,
        "note": (
            "CPU harness: structural evidence only (censuses, compiled "
            "per-device memory stats, loss trajectories) — no wall "
            "times; on-chip A/B armed as scripts/r6_queue.sh phH. "
            "kernels.ring_min_seq=1 here is the test hook that makes "
            "17-token vit_test passes ring; shipped default 1024 keeps "
            "local crops dense"
        ),
        "source": ("hlo_census + memory_analysis of the shipped "
                   "build_train_setup step and standalone attention "
                   f"twins on {DP} simulated CPU devices, steps "
                   "executed"),
    }
    if not SMOKE:
        vitl = vitl_attention_twins()
        for row in vitl["cases"]:
            dense = row["arms"]["dense_seq1"]
            ring = row["arms"]["ring_seq2"]
            rc = ring["collective_census"]
            assert rc["unattributed"] == 0, (row["px"], rc["unattributed"])
            assert scope_ops(rc, "ring_permute") > 0, sorted(rc["by_scope"])
            assert dense["collective_census"]["unattributed"] == 0
            # THE memory pin: per-device attention state at seq=2
            # measurably below seq=1 (O(N/s) rotation vs dense [N, N])
            assert ring["temp_bytes_per_device"] \
                < dense["temp_bytes_per_device"], (
                row["px"], ring["temp_bytes_per_device"],
                dense["temp_bytes_per_device"])
        assert vitl["executed_parity"]["max_abs_diff_plain"] < 1e-4
        assert vitl["executed_parity"]["max_abs_diff_segmented"] < 1e-4
        out["vitl_attention"] = vitl

    if OUT:
        with open(OUT, "w") as f:
            json.dump(out, f, indent=1)
        _log(f"wrote {OUT}")
    print(json.dumps({k: v for k, v in out.items()
                      if k not in ("hires_step", "vitl_attention")}))
    if SMOKE:
        _log("smoke OK: ring collectives scope-attributed, zero "
             "unattributed, gram stage finite + refresh exercised, "
             "seq arms match the seq=1 oracle")


if __name__ == "__main__":
    main()
