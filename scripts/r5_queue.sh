#!/bin/bash
# Round-5 TPU measurement queue — successor of r4_queue.sh. The phase
# list is VERDICT r4's "next round" ladder, cheap/high-evidence first,
# wedge-prone giant compiles last (killing a hung 35-min remote compile
# wedges the tunnel for hours — see r3):
#   phA  default program (subset drop-path): the headline number
#        (VERDICT r4 missing #1/#2 — two rounds queued, zero measured)
#   phB  drop_path_mode=mask A/B — isolates the subset win
#   phC  batch sweep at B=10/B=12 (the FLOP cut may shift the peak)
#   phG  op-level flash-vs-dense attention crossover -> flash_min_seq
#   phD  profile of the default step program (committed artifact)
#   phH  fp32-master ViT-S/B ladder points (small, safe compiles)
#   phF  full-step high-res crossover (512/768px, scanned blocks)
#   phE  ViT-S accuracy rung on the texture dataset, full vs no_ibot
#        (does iBOT turn positive at real width? VERDICT r4 weak #3)
#
# Usage: bash scripts/r5_queue.sh   (env: RESULTS, QUEUE_LOG, DEADLINE_HOURS)

set -u
cd "$(dirname "$0")/.."
RESULTS="${RESULTS:-/tmp/r5_results.jsonl}"
LOG="${QUEUE_LOG:-/tmp/r5_queue.log}"
DEADLINE=$(( $(date +%s) + ${DEADLINE_HOURS:-10} * 3600 ))

note() { echo "[r5 $(date +%H:%M:%S)] $*" | tee -a "$LOG"; }

remaining() { echo $(( DEADLINE - $(date +%s) )); }

probe() {
    timeout 300 python - <<'EOF' >>"$LOG" 2>&1
import sys
sys.path.insert(0, ".")
from dinov3_tpu.utils import respect_jax_platforms_env
respect_jax_platforms_env()
import jax
assert jax.default_backend() != "cpu", "fell back to cpu"
print("PROBE-OK", jax.device_count())
EOF
}

wait_healthy() {
    while [ "$(remaining)" -gt 0 ]; do
        if probe; then note "probe healthy"; return 0; fi
        note "probe unhealthy; sleeping 240s ($(( $(remaining) / 60 )) min to deadline)"
        sleep 240
    done
    note "deadline reached while waiting for a healthy tunnel"
    return 1
}

# gate_phase <backstop_s> <tag>: true iff the deadline leaves room for
# the phase's worst case AND the tunnel is healthy
gate_phase() {
    local backstop="$1" tag="$2"
    if [ "$(remaining)" -le "$backstop" ]; then
        note "SKIP $tag: ${backstop}s backstop does not fit in $(remaining)s to deadline"
        return 1
    fi
    wait_healthy || return 1
    # wait_healthy may have slept for hours: re-check the fit so a
    # late-healthy tunnel cannot launch a phase past the deadline
    if [ "$(remaining)" -le "$backstop" ]; then
        note "SKIP $tag: deadline closed in while waiting for a healthy probe"
        return 1
    fi
    return 0
}

# run_bench <tag> <tmo> <pinned|ladder> [ENV=...]...
run_bench() {
    local tag="$1" tmo="$2" kind="$3"; shift 3
    local backstop budget
    if [ "$kind" = pinned ]; then
        budget=$tmo; backstop=$((tmo + 600))
    else
        budget=$((3 * tmo)); backstop=$((3 * tmo + 600))
    fi
    local try rc out
    for try in 1 2; do
        gate_phase "$backstop" "$tag" || return 1
        note "start $tag try=$try (tmo=${tmo}s budget=${budget}s) env: $*"
        out=$(env "$@" BENCH_ATTEMPT_TIMEOUT="$tmo" BENCH_TOTAL_BUDGET="$budget" \
              timeout "$backstop" python bench.py 2>>"$LOG")
        rc=$?
        if [ $rc -eq 0 ] && [ -n "$out" ]; then
            echo "{\"tag\": \"$tag\", \"rc\": 0, \"result\": $out}" >> "$RESULTS"
            note "done  $tag -> $out"
            return 0
        fi
        # keep the attributable skip record even on failure
        if [ -n "$out" ]; then
            echo "{\"tag\": \"$tag\", \"rc\": $rc, \"result\": $out}" >> "$RESULTS"
        else
            echo "{\"tag\": \"$tag\", \"rc\": $rc, \"result\": null}" >> "$RESULTS"
        fi
        if [ $rc -eq 3 ] && [ $try -eq 1 ]; then
            note "INFRA $tag rc=3 (tunnel died mid-run); re-gating on probe for one retry"
            continue
        fi
        note "FAIL  $tag rc=$rc"
        return $rc
    done
}

note "=== r5 queue starting; deadline $(date -d @$DEADLINE +%H:%M:%S) ==="

# phA: the headline — default program (subset drop-path, bf16 probs),
# unpinned so the driver-identical ladder defends it. A success also
# pre-seeds /tmp/jaxcache for the driver's end-of-round bench.
run_bench phA_subset_default 2100 ladder
# phB: mask A/B — pinned (a substituted program would break the A/B)
run_bench phB_mask_ab        2100 pinned BENCH_OVERRIDES=student.drop_path_mode=mask
# phC: batch sweep — pinned via a no-op BENCH_PROBS=bf16 (the default)
# so a ladder substitution can never mislabel a sweep point
run_bench phC_b10            2100 pinned BENCH_BATCH=10 BENCH_PROBS=bf16
run_bench phC_b12            2100 pinned BENCH_BATCH=12 BENCH_PROBS=bf16

gate_phase 2400 phG_attn_crossover && {
    note "start phG_attn_crossover"
    if timeout 2400 python scripts/bench_attention_crossover.py \
            /tmp/attn_crossover.jsonl >> "$LOG" 2>&1; then
        note "done  phG_attn_crossover -> /tmp/attn_crossover.jsonl"
    else
        note "FAIL  phG_attn_crossover rc=$?"
    fi
}

gate_phase 2400 phD_profile && {
    note "start phD_profile"
    if timeout 2400 python scripts/profile_step.py /tmp/prof_r5 \
            >> "$LOG" 2>&1; then
        note "done  phD_profile -> /tmp/prof_r5"
    else
        note "FAIL  phD_profile rc=$?"
    fi
}

# fp32-master ladder points for the README (small, safe compiles;
# BENCH_ARCH pins them to a single attempt)
run_bench phH_vit_small 1800 pinned BENCH_ARCH=vit_small BENCH_BATCH=32
run_bench phH_vit_base  1800 pinned BENCH_ARCH=vit_base  BENCH_BATCH=16

# wedge-prone giant compiles after everything cheap; scanned blocks on
# BOTH sides of the A/B keep the HLO ~24x smaller (the unscanned 512px
# flash compile exceeded 35 min and wedged the tunnel in r3)
run_bench phF_hr512_auto 3600 pinned BENCH_RES=512 BENCH_BATCH=2 \
    BENCH_OVERRIDES=train.scan_layers=true
run_bench phF_hr512_xla  3600 pinned BENCH_RES=512 BENCH_BATCH=2 \
    BENCH_OVERRIDES=kernels.flash_attention=xla,train.scan_layers=true
# B=2, not 1: KoLeo needs >=2 samples per group, so a B=1 program fails
# at build (found via the host-side FLOP count of the same program)
run_bench phF_hr768_auto 3900 pinned BENCH_RES=768 BENCH_BATCH=2 \
    BENCH_OVERRIDES=train.scan_layers=true
run_bench phF_hr768_xla  3900 pinned BENCH_RES=768 BENCH_BATCH=2 \
    BENCH_OVERRIDES=kernels.flash_attention=xla,train.scan_layers=true

# phE last: the ViT-S accuracy rung (hours of tunnel time, lowest
# marginal evidence per hour). Texture dataset, full recipe vs no_ibot
# at real width — the scale-dependence question from VERDICT r4 weak #3.
gate_phase 11400 phE_vits_textures && {
    note "start phE_vits_textures"
    if ABL_ARCH=vit_small ABL_ARMS=full,no_ibot \
            ABL_STEPS=3000 ABL_EVAL_EVERY=200 ABL_BATCH=48 \
            timeout 10800 python scripts/ablation_recipe.py /tmp/abl_vits \
            >> "$LOG" 2>&1; then
        note "done  phE_vits_textures -> /tmp/abl_vits/ABLATION.json"
    else
        note "FAIL  phE_vits_textures rc=$?"
    fi
}

note "=== r5 queue complete; results in $RESULTS ==="
