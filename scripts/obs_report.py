"""Fold a serve span stream + bench_serve record into the committed
OBS artifact (OBS_r15.json) — the observability plane's evidence.

Three claims, each checked here (violations raise, so the CI smoke
step fails loudly rather than committing a hollow artifact):

1. **Per-request phase breakdown**: every measured request has one
   ``serve_request`` record in the span stream carrying the six phase
   fields (``enqueue -> pack_placement -> dispatch -> device -> fetch
   -> extract``, telemetry/spans.py SERVE_PHASES), and every span
   record validates against the v1 schema (``"v": 1``, ``role``,
   ``name``). The artifact reports the per-(arm, mix) phase
   aggregates (mean + exact nearest-rank p50/p99 per phase).
2. **Histogram/exact agreement**: the streaming per-SLO log-bucketed
   histogram p50/p99 (telemetry/hist.py, carried in the record's
   ``serve.obs.slo`` blocks) sit within ONE bucket width
   (a ratio of 10^(1/bins_per_decade)) of the exact sorted-sample
   nearest-rank quantiles computed by bench_serve on the same rated
   Poisson replay (``latency.by_slo``), per (arm, mix, SLO class).
3. **Fetch-funnel census**: on the packed arm the ``blocking_fetch``
   count equals the observer's pack count (fetches_per_pack == 1.0) —
   the device-side stats rows rode the EXISTING ring fetch, zero
   blocking syncs added by the observability plane. The SERVE_r14
   reference fetch counts ride along for cross-PR comparison.

Usage: JAX_PLATFORMS=cpu python scripts/obs_report.py \
           --serve-json SERVE.json [--spans spans.serve.jsonl] \
           [--out OBS_r15.json] [--smoke]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dinov3_tpu.telemetry.hist import quantile_nearest_rank
from dinov3_tpu.telemetry.spans import SERVE_PHASES, SPAN_SCHEMA_V

# serve_request phase fields, in request order (the six SERVE_PHASES)
_PHASE_FIELDS = tuple(f"{p.removeprefix('serve_')}_ms"
                      for p in SERVE_PHASES)


def load_spans(path: str) -> tuple[list, dict]:
    """Parse + schema-validate the span stream; returns (records,
    census). Every line must be valid JSON with ``v == 1``, a ``role``
    and a ``name`` — the gate readers rely on instead of sniffing."""
    records = []
    census = {"lines": 0, "by_name": {}}
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("v") != SPAN_SCHEMA_V:
                raise ValueError(
                    f"{path}:{ln}: span schema v={rec.get('v')!r}, "
                    f"expected {SPAN_SCHEMA_V}")
            if "role" not in rec or "name" not in rec:
                raise ValueError(f"{path}:{ln}: span record missing "
                                 f"role/name: {sorted(rec)}")
            census["lines"] += 1
            census["by_name"][rec["name"]] = \
                census["by_name"].get(rec["name"], 0) + 1
            records.append(rec)
    return records, census


def phase_breakdown(requests: list) -> dict:
    """Aggregate serve_request records into per-phase latency stats:
    n present, mean, exact nearest-rank p50/p99 (ms)."""
    out = {"n_requests": len(requests)}
    for field in _PHASE_FIELDS:
        vals = sorted(r[field] for r in requests
                      if r.get(field) is not None)
        if not vals:
            out[field] = {"n": 0}
            continue
        out[field] = {
            "n": len(vals),
            "mean": round(sum(vals) / len(vals), 4),
            "p50": round(quantile_nearest_rank(vals, 0.50), 4),
            "p99": round(quantile_nearest_rank(vals, 0.99), 4),
        }
    return out


def check_requests(requests: list, expected_n: int, where: str) -> None:
    """Claim 1: a phase record for every measured request, each with
    every phase FIELD present (a value may be None — the oracle arms
    have no extract phase — but the key must exist)."""
    if len(requests) != expected_n:
        raise AssertionError(
            f"{where}: {len(requests)} serve_request records for "
            f"{expected_n} measured requests — per-request phase "
            f"breakdown is incomplete")
    for r in requests:
        missing = [f for f in _PHASE_FIELDS if f not in r]
        if missing:
            raise AssertionError(
                f"{where}: serve_request rid={r.get('rid')} missing "
                f"phase fields {missing}")


def hist_vs_exact(obs_slo: dict, exact_slo: dict, where: str) -> dict:
    """Claim 2: per SLO class, streaming-histogram p50/p99 within one
    log-bucket width (ratio <= width_factor) of the exact sample
    quantiles over the same rated replay."""
    rows = {}
    for slo, exact in exact_slo.items():
        h = obs_slo.get(slo)
        if h is None or not h.get("n"):
            raise AssertionError(
                f"{where}/{slo}: no streaming histogram for an SLO "
                f"class the exact sample saw")
        width = float(h["width_factor"])
        row = {"n_exact": exact["n"], "n_hist": h["n"],
               "width_factor": width}
        if h["n"] != exact["n"]:
            raise AssertionError(
                f"{where}/{slo}: histogram saw {h['n']} latencies, "
                f"exact sample has {exact['n']}")
        for q in ("p50", "p99"):
            est, ref = float(h[q]), float(exact[f"{q}_ms"])
            ratio = est / ref if ref else 1.0
            row[q] = {"hist_ms": round(est, 4), "exact_ms": ref,
                      "ratio": round(ratio, 4)}
            if not (1.0 / width <= ratio <= width):
                raise AssertionError(
                    f"{where}/{slo}: histogram {q} {est:.4f}ms vs "
                    f"exact {ref:.4f}ms — ratio {ratio:.4f} outside "
                    f"one bucket width ({width:.4f})")
        rows[slo] = row
    return rows


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--serve-json", required=True,
                    help="bench_serve.py output (SERVE record with the "
                         "per-arm serve.obs blocks)")
    ap.add_argument("--spans", default=None,
                    help="serve span stream; default: the record's "
                         "obs.spans_path")
    ap.add_argument("--out", default="OBS_r15.json")
    ap.add_argument("--reference", default=None,
                    help="prior SERVE record (SERVE_r14.json) whose "
                         "fetch counts ride along for comparison")
    ap.add_argument("--smoke", action="store_true",
                    help="label the artifact as a CI smoke run")
    args = ap.parse_args()

    with open(args.serve_json) as f:
        serve = json.load(f)
    spans_path = args.spans or serve.get("obs", {}).get("spans_path")
    if not spans_path or not os.path.exists(spans_path):
        raise FileNotFoundError(
            f"span stream not found (--spans / record obs.spans_path): "
            f"{spans_path!r}")
    records, span_census = load_spans(spans_path)

    out = {
        "what": ("serving observability plane: per-request phase "
                 "breakdown from the serve span stream, streaming-"
                 "histogram vs exact-sample latency quantiles on the "
                 "rated Poisson replay, and the blocking-fetch funnel "
                 "census pinning zero observability-added device "
                 "syncs"),
        "smoke": bool(args.smoke or serve.get("smoke")),
        "arch": serve.get("arch"),
        "seed": serve.get("seed"),
        "n_per_mix": serve.get("n_per_mix"),
        "span_schema_v": SPAN_SCHEMA_V,
        "span_census": span_census,
        "mixes": {},
    }

    arms = ("packed", "oracle_rectangular", "oracle_per_image")
    # a fleet record (bench_serve.py --fleet, SERVE_r16) has no 3-arm
    # "mixes" census — fold its pins through instead of KeyError-ing
    n = int(serve.get("n_per_mix") or serve.get("n_per_sweep") or 0)
    worst_ratio = 1.0
    if serve.get("fleet") is not None:
        fleet = serve["fleet"]
        cache_events: dict = {}
        for r in records:
            if r["name"] == "serve_cache":
                ev = r.get("event")
                cache_events[ev] = cache_events.get(ev, 0) + 1
        out["fleet"] = {
            "n_engines": serve.get("n_engines"),
            "compile_count_total": serve.get("compile_count_total"),
            "compile_growth_total": serve.get("compile_growth_total"),
            "forced_hit_bitwise": fleet.get("forced_hit_bitwise"),
            "route_counts": (fleet.get("summary") or {}).get(
                "route_counts"),
            "cache_span_events": cache_events,
            "sweeps": {
                k: {"measured_hit_rate": s.get("measured_hit_rate"),
                    "cache_hits_bitwise_equal":
                        s.get("cache_hits_bitwise_equal"),
                    "compile_growth": s.get("compile_growth")}
                for k, s in (fleet.get("sweeps") or {}).items()},
        }
        if serve.get("compile_growth_total"):
            raise AssertionError(
                "fleet record shows compile growth during replay — "
                "every engine must stay at its one AOT compile")
    for mix_name, mix_rec in (serve.get("mixes") or {}).items():
        mix_out = {}
        for arm in arms:
            arm_rec = mix_rec.get(arm)
            if arm_rec is None:
                continue
            where = f"{mix_name}/{arm}"
            reqs = [r for r in records
                    if r["name"] == "serve_request"
                    and r.get("arm") == arm and r.get("mix") == mix_name]
            # measured window = sustained drain (n) + rated replay (n)
            check_requests(reqs, 2 * n, where)
            obs = arm_rec["serve"].get("obs") or {}
            agreement = hist_vs_exact(
                obs.get("slo", {}), arm_rec["latency"]["by_slo"], where)
            for row in agreement.values():
                for q in ("p50", "p99"):
                    worst_ratio = max(worst_ratio, row[q]["ratio"],
                                      1.0 / row[q]["ratio"])
            arm_out = {
                "phase_breakdown": phase_breakdown(reqs),
                "hist_vs_exact": agreement,
                "packs": obs.get("packs"),
                "windows": obs.get("windows"),
                "stalls": obs.get("stalls"),
                "ewma_pad_waste": obs.get("ewma_pad_waste"),
                "recommended_envelope": obs.get("recommended_envelope"),
            }
            if arm == "packed":
                # claim 3: fetches == packs on the measured window
                fetches = arm_rec["serve"]["host_sync"]["fetches"]
                packs = obs.get("packs")
                fpp = fetches / packs if packs else None
                arm_out["fetch_funnel"] = {
                    "fetches": fetches, "packs": packs,
                    "fetches_per_pack": fpp,
                    "blocked_ms": arm_rec["serve"]["host_sync"].get(
                        "blocked_ms"),
                }
                if fpp != 1.0:
                    raise AssertionError(
                        f"{where}: {fetches} blocking fetches over "
                        f"{packs} packs — the stats plane must ride "
                        f"the existing ring fetch, not add syncs")
                # device stats rows rode that one fetch: census their
                # agreement with the host-side plan
                stats = [r for r in records
                         if r["name"] == "serve_pack_stats"
                         and r.get("arm") == arm
                         and r.get("mix") == mix_name]
                mismatch = sum(
                    1 for r in stats
                    if r.get("host_tokens_used") is not None
                    and int(r["tokens_used"]) != int(r["host_tokens_used"]))
                arm_out["device_stats"] = {
                    "rows": len(stats),
                    "host_token_mismatches": mismatch,
                }
                if stats and mismatch:
                    raise AssertionError(
                        f"{where}: {mismatch}/{len(stats)} device stats "
                        f"rows disagree with the host-side token plan")
            mix_out[arm] = arm_out
        out["mixes"][mix_name] = mix_out

    out["worst_hist_exact_ratio"] = round(worst_ratio, 4)
    if args.reference and os.path.exists(args.reference):
        with open(args.reference) as f:
            ref = json.load(f)
        out["reference_fetch_counts"] = {
            mix: {"fetches": rec["packed"]["serve"]["host_sync"]["fetches"],
                  "blocked_ms": rec["packed"]["serve"]["host_sync"].get(
                      "blocked_ms")}
            for mix, rec in ref.get("mixes", {}).items()
            if "packed" in rec}
        out["reference"] = os.path.basename(args.reference)

    doc = json.dumps(out, indent=1)
    with open(args.out, "w") as f:
        f.write(doc + "\n")
    print(doc)
    return 0


if __name__ == "__main__":
    sys.exit(main())
