"""Host-sync + memory accounting for the async telemetry engine
(telemetry/): the committed evidence behind COST_HSYNC_r11.json and
MEM_r11.json.

Methodology (the PR-1..5 discipline — measure the exact shipped code
paths, stated precisely because this is the committed evidence in
docs/PERFORMANCE.md):

- **Host-sync A/B (executed)**: the REAL hot loop
  (``train/train.py do_train`` via ``train_main``) runs twice on the
  8-simulated-device CPU mesh with a tiny vit_test program — once on
  the default async arm (metrics -> donated on-device ring, one flush
  per ``telemetry.flush_every`` steps) and once on the per-step-fetch
  oracle (``telemetry.async_metrics=false``). Every blocking
  device->host fetch either arm issues goes through the ONE counted
  funnel (telemetry/host_sync.py blocking_fetch), so
  ``fetches_per_step`` and ``host_blocked_ms_per_step`` are read
  straight off the instrument, not estimated. The claim under test:
  the async hot loop issues <= 1 blocking fetch per flush_every steps
  where the oracle issues 1 per step. Host-blocked ms is
  program-dependent (a tiny model on CPU); the FETCH COUNT is the
  structural, program-independent result. Both arms' span JSONL is
  summarized per phase (mean dispatch/data-wait/flush ms) as the
  phase-attribution record.
- **Memory accounting (ViT-L dp=8 dryrun, compile-only)**: the full
  telemetry step is built ABSTRACTLY (``build_train_setup(...,
  init_state=False)``) on 8 simulated devices — materializing 8
  replicated ViT-L trees in host RAM is exactly what the accounting
  exists to avoid — and per-device bytes-in-use are computed from the
  shardings the partitioner actually assigned (replicated leaves count
  fully per device; the ZeRO-1 sharded adam moments count 1/dp).
  ``compiled.memory_analysis()`` adds XLA's own temp/argument/output
  sizes where the backend exposes them (recorded with a source note
  either way); runtime ``device.memory_stats()`` samples from the
  executed tiny run ride along under ``runtime_samples`` (on this
  container's CPU backend they fall back to live-array walking,
  honestly labelled).

Writes MEM_r11.json (second argv, default ./MEM_r11.json) and prints
the COST_HSYNC record as one JSON line on stdout -> commit as
COST_HSYNC_r11.json.

Usage: JAX_PLATFORMS=cpu python scripts/cost_host_sync.py \
           [steps] [flush_every] [mem_out]   (defaults: 16 8 MEM_r11.json)
"""

from __future__ import annotations

import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DP = 8
# the simulated device count must be pinned before jax initializes
os.environ.setdefault("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += f" --xla_force_host_platform_device_count={DP}"

STEPS = int(sys.argv[1]) if len(sys.argv) > 1 else 16
FLUSH_EVERY = int(sys.argv[2]) if len(sys.argv) > 2 else 8
MEM_OUT = sys.argv[3] if len(sys.argv) > 3 else "MEM_r11.json"

TINY = [
    "student.arch=vit_test", "student.patch_size=4",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2", "train.batch_size_per_device=2",
    "optim.scaling_rule=none", "data.backend=synthetic",
    "optim.epochs=1", "optim.warmup_epochs=0",
    "checkpointing.period=1000000",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
    "dino.head_bottleneck_dim=16",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
    "ibot.head_bottleneck_dim=16",
]


def _span_summary(spans_path: str) -> dict:
    """Per-phase {count, mean_ms} over one run's span JSONL."""
    agg: dict = {}
    with open(spans_path) as f:
        for line in f:
            rec = json.loads(line)
            if "dur_ms" not in rec:
                continue
            ent = agg.setdefault(rec["name"], {"count": 0, "total_ms": 0.0})
            ent["count"] += 1
            ent["total_ms"] += rec["dur_ms"]
    return {
        name: {"count": ent["count"],
               "mean_ms": round(ent["total_ms"] / ent["count"], 4)}
        for name, ent in agg.items()
    }


def _memory_samples(spans_path: str) -> list:
    with open(spans_path) as f:
        return [json.loads(line) for line in f
                if '"name": "memory"' in line]


def run_hot_loop(async_metrics: bool, out_dir: str) -> dict:
    """One do_train run through the real trainer entry; returns the
    funnel's fetch/blocked-time stats over exactly the loop's fetches."""
    from dinov3_tpu.telemetry import host_sync_stats
    from dinov3_tpu.train.train import main as train_main

    host_sync_stats(reset=True)
    result = train_main([
        "--output-dir", out_dir, "--no-resume",
        "--max-iterations", str(STEPS),
    ] + TINY + [
        f"train.OFFICIAL_EPOCH_LENGTH={STEPS}",
        f"telemetry.flush_every={FLUSH_EVERY}",
        f"telemetry.async_metrics={'auto' if async_metrics else 'false'}",
    ])
    stats = host_sync_stats(reset=True)
    spans = os.path.join(out_dir, "telemetry", "spans.jsonl")
    return {
        "steps": STEPS,
        "flush_every": FLUSH_EVERY,
        "blocking_fetches": stats["fetches"],
        "fetches_per_step": round(stats["fetches"] / STEPS, 4),
        "host_blocked_ms": stats["blocked_ms"],
        "host_blocked_ms_per_step": round(stats["blocked_ms"] / STEPS, 4),
        "final_loss": result["final_loss"],
        "span_summary": _span_summary(spans),
        "_memory_samples": _memory_samples(spans),
    }


def measure_vitl_memory() -> dict:
    """ViT-L dp=8 compile-only memory accounting (see module doc)."""
    import importlib.util

    import jax

    _spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(_spec)
    _spec.loader.exec_module(bench)

    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.telemetry.ring import make_ring
    from dinov3_tpu.train import build_train_setup

    cfg = get_default_config()
    apply_dot_overrides(cfg, bench.build_step_overrides("vit_large", 0))
    B = 12 * DP
    batch_np = make_synthetic_batch(cfg, B, seed=0)
    # the setup traces need a subscriptable example (host numpy is fine
    # and never reaches a device); the lowering below uses the abstract
    # ShapeDtypeStruct form so no global batch is ever materialized
    # on the simulated mesh
    batch = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
             for k, v in batch_np.items()}
    setup = build_train_setup(cfg, batch_np, init_state=False)
    plan = setup.telemetry()
    ring_abs = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
        make_ring(len(plan.metric_names), plan.ring_len))

    def tree_bytes_per_device(tree, shardings) -> int:
        total = 0
        for leaf, sh in zip(jax.tree.leaves(tree),
                            jax.tree.leaves(shardings)):
            shard_shape = sh.shard_shape(leaf.shape)
            total += math.prod(shard_shape) * leaf.dtype.itemsize
        return total

    state_parts = {
        "params_student": tree_bytes_per_device(
            setup.state.params["student"],
            setup.state_shardings.params["student"]),
        "params_teacher": tree_bytes_per_device(
            setup.state.params["teacher"],
            setup.state_shardings.params["teacher"]),
        "opt_state": tree_bytes_per_device(
            setup.state.opt_state, setup.state_shardings.opt_state),
        "center_state": tree_bytes_per_device(
            setup.state.center_state, setup.state_shardings.center_state),
        "telemetry_ring": tree_bytes_per_device(
            ring_abs, plan.ring_shardings),
    }
    batch_bytes = tree_bytes_per_device(
        batch, setup.batch_shardings)
    state_bytes = sum(state_parts.values())

    scalars = {
        "teacher_temp": jax.ShapeDtypeStruct((), jax.numpy.float32),
        "momentum": jax.ShapeDtypeStruct((), jax.numpy.float32),
    }
    rng = jax.random.key(0)
    print(f"[cost_host_sync] compiling ViT-L dp={DP} telemetry step "
          "(compile-only dryrun)...", file=sys.stderr, flush=True)
    compiled = plan.step_fn.lower(
        setup.state, ring_abs, batch, scalars, rng).compile()
    mem_an = None
    source = "shardings"
    try:
        an = compiled.memory_analysis()
        if an is not None:
            mem_an = {
                k: int(getattr(an, k))
                for k in ("argument_size_in_bytes", "output_size_in_bytes",
                          "temp_size_in_bytes", "alias_size_in_bytes",
                          "generated_code_size_in_bytes")
                if hasattr(an, k)
            } or None
            if mem_an:
                source = "shardings+memory_analysis"
    except Exception as e:  # noqa: BLE001 - backend without the analysis
        mem_an = {"error": str(e)[:200]}
    temp = (mem_an or {}).get("temp_size_in_bytes")
    return {
        "arch": "vit_large", "dp": DP, "per_chip_batch": 12,
        "bytes_in_use_per_device": {
            **state_parts,
            "batch": batch_bytes,
            "state_total": state_bytes,
            "total": state_bytes + batch_bytes,
        },
        "peak_bytes_per_device": (
            None if temp is None
            else state_bytes + batch_bytes + int(temp)),
        "xla_memory_analysis": mem_an,
        "source": source,
        "note": (
            "compile-only dryrun on 8 simulated CPU devices: "
            "bytes-in-use from the NamedShardings the partitioner "
            "assigned (replicated leaves full-size per device, ZeRO-1 "
            "adam moments 1/dp); peak adds XLA's temp_size when the "
            "backend reports memory_analysis, else null. XLA:CPU's "
            "temp_size is an UNSCHEDULED upper bound (the TPU memory "
            "scheduler reuses buffers aggressively), so treat peak as "
            "the compile-level bound and re-measure on-chip via "
            "device.memory_stats() (the phO bench records embed it). "
            "Runtime sampling (telemetry/memory.py) is the on-chip "
            "instrument; its CPU fallback samples from the executed "
            "vit_test run are under runtime_samples. The bytes-in-use "
            "split is the ZeRO-3 before-picture: student+teacher fp32 "
            "masters fully replicated (2 x 1.40 GB/device at ViT-L), "
            "adam moments already 1/dp (ROADMAP item 1 shards the "
            "masters next)."
        ),
    }


def main():
    from dinov3_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()
    import tempfile

    import jax

    try:
        jax.config.update("jax_num_cpu_devices", DP)
    except AttributeError:
        pass  # XLA_FLAGS set above covers old jaxlibs

    with tempfile.TemporaryDirectory() as td:
        ring_arm = run_hot_loop(True, os.path.join(td, "ring"))
        oracle_arm = run_hot_loop(False, os.path.join(td, "oracle"))
    runtime_samples = ring_arm.pop("_memory_samples")
    oracle_arm.pop("_memory_samples")

    mem = measure_vitl_memory()
    mem["runtime_samples"] = {
        "program": "vit_test dp=8 executed hot loop (async arm)",
        "samples": runtime_samples,
    }
    with open(MEM_OUT, "w") as f:
        json.dump(mem, f, indent=1)
    print(f"[cost_host_sync] wrote {MEM_OUT}", file=sys.stderr)

    rec = {
        "program": "vit_test dp=8, real do_train hot loop, synthetic data",
        "steps_per_flush_claim": (
            "async arm issues <= 1 blocking device->host fetch per "
            "telemetry.flush_every steps; oracle issues 1 per step"),
        "ring": ring_arm,
        "oracle": oracle_arm,
        "fetch_reduction": (
            f"{oracle_arm['blocking_fetches']} -> "
            f"{ring_arm['blocking_fetches']} blocking fetches over "
            f"{STEPS} steps"),
        "mem_artifact": MEM_OUT,
    }
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
