"""Count step-program FLOPs with XLA cost analysis (committed artifact).

Round 4 committed `FLOPS_r04.json` from an ad-hoc console session; this
script makes the count reproducible and extends it to the arch ladder.
It compiles the EXACT bench step program (same override path bench.py
uses) on the host CPU backend and reads ``compiled.cost_analysis()``.

Caveats the artifact must carry (VERDICT r4 weak #4):
- ``cost_analysis`` counts a ``lax.scan`` body ONCE, so scanned-stack
  programs undercount by ~n_blocks; every point here compiles the
  UNROLLED stack (train.scan_layers=false) so numbers are comparable.
- These are executed-FLOP counts on a host compile — a compute ceiling,
  not a measurement; the measured img/s live in BENCH_* artifacts.

Usage: JAX_PLATFORMS=cpu python scripts/count_flops.py [out.json]
Env: FLOPS_POINTS — comma list of POINTS keys; the default is EVERY
     point, so running the script as documented regenerates the full
     committed artifact (compile_s and date vary; the persistent
     compile cache makes warm reruns fast).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# (arch, batch, res_override_px_or_0, drop_path_mode, extra overrides)
#
# The pre-PR-4 points pin ``model.crop_packing=false``: they were
# committed against the two-pass student program (FLOPS_r04/r05) and
# serve as the stable cross-check rungs; the crop-packed default
# program (one backbone scan, pad tokens priced in) gets its own
# standing ledger point so the pad-waste FLOPs sit next to the subset
# drop-path cut in the artifact.
_TWO_PASS = "model.crop_packing=false"
POINTS = {
    # the r4 pair, reproduced: the subset drop-path FLOP cut on the
    # default bench program (ViT-L/16, B=8, 224px + 8x96px)
    "vitl_mask": ("vit_large", 8, 0, "mask", [_TWO_PASS]),
    "vitl_subset": ("vit_large", 8, 0, "subset", [_TWO_PASS]),
    # the r5 default program: B=12, the on-chip sweep peak
    # (58.56 img/s/chip, MEASUREMENTS_r5.md phC row)
    "vitl_subset_b12": ("vit_large", 12, 0, "subset", [_TWO_PASS]),
    # the PR-4 default program: crop-packed single-pass student (44
    # packed rows instead of 120; attention runs over 197-token rows
    # for the locals too, so the pad/cross-segment waste shows up HERE
    # as extra counted FLOPs — the engine trades them for one weight
    # stream and clean tiling, COST_PACK_r09.json)
    "vitl_packed_b12": ("vit_large", 12, 0, "subset", []),
    # ladder points for the fp32-master BENCH_ARCH rungs (phH); the
    # _mask variants exist because the r1 bf16-master measurements ran
    # the mask program — utilization comparisons must divide them by
    # mask-program ceilings, not subset ones
    "vits": ("vit_small", 32, 0, "subset", [_TWO_PASS]),
    "vits_mask": ("vit_small", 32, 0, "mask", [_TWO_PASS]),
    "vitb": ("vit_base", 16, 0, "subset", [_TWO_PASS]),
    "vitb_mask": ("vit_base", 16, 0, "mask", [_TWO_PASS]),
    # high-res points (SLOW: the unrolled 512px host compile is ~4.5 min,
    # 768px substantially more) — request explicitly via FLOPS_POINTS
    "hr512": ("vit_large", 2, 512, "subset",
              ["kernels.flash_attention=xla", _TWO_PASS]),
    # B=2, not 1: KoLeo requires >=2 samples per group — a B=1 program
    # fails at build (this is also why the r5 queue's phF_hr768 is B=2)
    "hr768": ("vit_large", 2, 768, "subset",
              ["kernels.flash_attention=xla", _TWO_PASS]),
}


def count_point(arch: str, per_chip: int, res: int, mode: str,
                extra: list[str]) -> float:
    """TFLOP per step from a host compile of the bench program."""
    import jax
    import jax.numpy as jnp

    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup, put_batch

    # the override list comes from bench.py itself (single source of
    # truth), so these ceilings are always ceilings OF THE BENCHED
    # program — plus the unroll override: cost_analysis counts a scan
    # body once, so the stack must be unrolled on every point
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    cfg = get_default_config()
    apply_dot_overrides(cfg, bench.build_step_overrides(
        arch, res, drop_path_mode=mode,
        extra=["train.scan_layers=false"] + extra))
    batch = {k: jnp.asarray(v)
             for k, v in make_synthetic_batch(cfg, per_chip, seed=0).items()}
    setup = build_train_setup(cfg, batch, devices=jax.devices()[:1])
    dbatch = put_batch(batch, setup.batch_shardings)
    compiled = setup.step_fn.lower(
        setup.state, dbatch, setup.scalars(0), jax.random.key(0)
    ).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    return float(ca["flops"]) / 1e12


def main():
    import time

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get("BENCH_CACHE_DIR", "/tmp/jaxcache"),
    )

    out_path = sys.argv[1] if len(sys.argv) > 1 else "FLOPS.json"
    names = [p.strip() for p in os.environ.get(
        "FLOPS_POINTS", ",".join(POINTS)).split(",") if p.strip()]
    unknown = [n for n in names if n not in POINTS]
    if unknown:
        raise SystemExit(f"unknown FLOPS_POINTS {unknown}; "
                         f"known: {list(POINTS)}")

    rec = {
        "what": ("XLA cost_analysis of the exact bench step program "
                 "(fwd+bwd+opt, unrolled stack on every point for scan "
                 "comparability), host CPU compile — executed-FLOP "
                 "ceilings, not measurements"),
        "script": "scripts/count_flops.py",
        "date": time.strftime("%Y-%m-%d"),
        "cross_check": ("vitl_mask/vitl_subset/hr512 must reproduce "
                        "FLOPS_r04.json (13.680/10.083/9.344) — they pin "
                        "model.crop_packing=false, so any drift means "
                        "the two-pass program itself changed; the "
                        "crop-packed default program is the separate "
                        "vitl_packed_b12 point"),
        "points": {},
    }
    # incremental: each point is written as soon as it is counted, so a
    # killed later compile (the hr points are many-minute compiles)
    # still leaves a parseable artifact
    for name in names:
        arch, b, res, mode, extra = POINTS[name]
        t0 = time.perf_counter()
        tflop = count_point(arch, b, res, mode, extra)
        rec["points"][name] = {
            "arch": arch, "batch_per_chip": b,
            "global_crops_px": res or 224, "drop_path_mode": mode,
            "extra_overrides": extra,
            "tflop_per_step": round(tflop, 3),
            "tflop_per_img": round(tflop / b, 4),
            "compile_s": round(time.perf_counter() - t0, 1),
        }
        with open(out_path + ".tmp", "w") as f:
            json.dump(rec, f, indent=1)
        os.replace(out_path + ".tmp", out_path)
        print(f"[flops] {name}: {tflop:.3f} TFLOP/step "
              f"({time.perf_counter() - t0:.0f}s)", flush=True)
    print(json.dumps(rec["points"], indent=1))


if __name__ == "__main__":
    main()
