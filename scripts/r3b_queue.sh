#!/bin/bash
# Round-3b TPU measurement queue — probe-gated successor of r3_tpu_queue.sh.
#
# Lesson from the first r3 pass: killing a hung remote compile wedges the
# axon tunnel for a long time (every later backend init hangs in the
# probe). So this queue (a) waits for a HEALTHY probe before every phase
# rather than burning each phase's timeout against a dead tunnel, and
# (b) orders the wedge-prone giant compiles (high-res flash) last.
#
#   phA  default program — now includes reference-semantics subset
#        drop-path (student.drop_path_mode=subset): the headline number
#   phB  drop_path_mode=mask A/B — isolates the subset win
#   phC  batch sweep at B=10 and B=12 (the FLOP cut may shift the peak)
#   phG  op-level flash-vs-dense attention crossover (fast compiles)
#   phD  profile of the default step program (committed-evidence artifact)
#   phH  fp32-master ViT-S/B ladder points (small, safe compiles)
#   phF  full-step high-res crossover (512/768px) — wedge-prone giant
#        compiles, after everything cheap
#   phE  TPU accuracy trajectory (ViT-S, 3000 steps) — last, 2h
#
# Usage: bash scripts/r3b_queue.sh   (env: RESULTS, DEADLINE_HOURS)

set -u
cd "$(dirname "$0")/.."
RESULTS="${RESULTS:-/tmp/r3b_results.jsonl}"
LOG="${QUEUE_LOG:-/tmp/r3b_queue.log}"
DEADLINE=$(( $(date +%s) + ${DEADLINE_HOURS:-9} * 3600 ))

note() { echo "[r3b $(date +%H:%M:%S)] $*" | tee -a "$LOG"; }

probe() {
    timeout 300 python - <<'EOF' >>"$LOG" 2>&1
import sys
sys.path.insert(0, ".")
from dinov3_tpu.utils import respect_jax_platforms_env
respect_jax_platforms_env()
import jax
assert jax.default_backend() != "cpu", "fell back to cpu"
print("PROBE-OK", jax.device_count())
EOF
}

wait_healthy() {
    while [ "$(date +%s)" -lt "$DEADLINE" ]; do
        if probe; then note "probe healthy"; return 0; fi
        note "probe unhealthy; sleeping 240s"
        sleep 240
    done
    note "deadline reached while waiting for a healthy tunnel"
    return 1
}

run_bench() {
    local tag="$1" tmo="$2"; shift 2
    wait_healthy || return 1
    note "start $tag (attempt timeout ${tmo}s) env: $*"
    local out rc
    # 3*tmo: bench.py's supervisor walks up to a 3-rung fallback ladder
    # for unpinned runs; the backstop must outlast the whole ladder
    out=$(env "$@" BENCH_ATTEMPT_TIMEOUT="$tmo" \
          timeout $((3 * tmo + 600)) python bench.py 2>>"$LOG")
    rc=$?
    if [ $rc -eq 0 ] && [ -n "$out" ]; then
        echo "{\"tag\": \"$tag\", \"rc\": 0, \"result\": $out}" >> "$RESULTS"
        note "done  $tag -> $out"
    else
        echo "{\"tag\": \"$tag\", \"rc\": $rc, \"result\": null}" >> "$RESULTS"
        note "FAIL  $tag rc=$rc"
    fi
    return $rc
}

note "=== r3b queue starting; deadline $(date -d @$DEADLINE +%H:%M:%S) ==="

run_bench phA_subset_default 2100
run_bench phB_mask_ab        2100 BENCH_OVERRIDES=student.drop_path_mode=mask
run_bench phC_b10            2100 BENCH_BATCH=10
run_bench phC_b12            2100 BENCH_BATCH=12

wait_healthy && {
    note "start phG_attn_crossover"
    if timeout 2400 python scripts/bench_attention_crossover.py \
            /tmp/attn_crossover.jsonl >> "$LOG" 2>&1; then
        note "done  phG_attn_crossover -> /tmp/attn_crossover.jsonl"
    else
        note "FAIL  phG_attn_crossover rc=$?"
    fi
}

wait_healthy && {
    note "start phD_profile"
    if timeout 2400 python scripts/profile_step.py /tmp/prof_r3 \
            >> "$LOG" 2>&1; then
        note "done  phD_profile -> /tmp/prof_r3"
    else
        note "FAIL  phD_profile rc=$?"
    fi
}

# fp32-master ladder points for the README (small, safe compiles)
run_bench phH_vit_small 1800 BENCH_ARCH=vit_small BENCH_BATCH=32
run_bench phH_vit_base  1800 BENCH_ARCH=vit_base  BENCH_BATCH=16

# wedge-prone giant compiles after everything cheap (the 512px flash
# fwd+bwd compile exceeded 35 min through the tunnel helper; killing it
# wedges the tunnel) — only the 2h trajectory runs later, and it can
# survive on probe-waiting if a wedge clears
# scan_layers on BOTH sides of the A/B: one scanned block instead of 24
# unrolled ones cuts the HLO ~24x, which is what made the 512px flash
# compile exceed 35 min and wedge the tunnel; the flash-vs-xla
# comparison stays internally valid at fixed scan_layers
run_bench phF_hr512_auto 3600 BENCH_RES=512 BENCH_BATCH=2 \
    BENCH_OVERRIDES=train.scan_layers=true
run_bench phF_hr512_xla  3600 BENCH_RES=512 BENCH_BATCH=2 \
    BENCH_OVERRIDES=kernels.flash_attention=xla,train.scan_layers=true
run_bench phF_hr768_auto 3900 BENCH_RES=768 BENCH_BATCH=1 \
    BENCH_OVERRIDES=train.scan_layers=true
run_bench phF_hr768_xla  3900 BENCH_RES=768 BENCH_BATCH=1 \
    BENCH_OVERRIDES=kernels.flash_attention=xla,train.scan_layers=true

# trajectory last: 2h of tunnel time, lowest marginal evidence (the CPU
# trajectory + protocol eval already cover VERDICT r2 #4)
wait_healthy && {
    note "start phE_tpu_trajectory"
    if TRAJ_STEPS=3000 TRAJ_EVAL_EVERY=500 TRAJ_ARCH=vit_small TRAJ_BATCH=64 \
            timeout 7200 python scripts/train_trajectory.py /tmp/traj_tpu \
            >> "$LOG" 2>&1; then
        note "done  phE_tpu_trajectory -> /tmp/traj_tpu/TRAJECTORY.json"
    else
        note "FAIL  phE_tpu_trajectory rc=$?"
    fi
}

note "=== r3b queue complete; results in $RESULTS ==="
