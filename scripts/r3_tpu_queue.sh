#!/bin/bash
# Round-3 TPU measurement queue — one pass captures everything the round
# needs the moment the tunnel is healthy. Each phase is timeout-bounded
# and appends a tagged line to $RESULTS, so a hang in any phase is
# attributable (bench.py's stderr heartbeat names the stuck phase) and
# never blocks the rest.
#
#   ph1  probs=fp32      round-1-equivalent step program: validates the
#                        TPU path end-to-end and seeds the compile cache
#   ph2  default (bf16 probs, custom-VJP softmax) — the round-2 program
#                        the judge's bench run hung on
#   ph3  bf16 probs, plain autodiff (DINOV3_PLAIN_LOWP_SOFTMAX=1) —
#                        isolates the custom_vjp if ph2 stalls
#   ph4  fused Pallas LayerNorm on top of the ph1/ph2 winner
#   ph5  high-res flash-vs-XLA crossover (512px and 768px, auto vs xla)
#
# Usage: bash scripts/r3_tpu_queue.sh  (env: RESULTS, BENCH_* passthrough)

set -u
cd "$(dirname "$0")/.."
RESULTS="${RESULTS:-/tmp/r3_tpu_results.jsonl}"
LOG="${QUEUE_LOG:-/tmp/r3_tpu_queue.log}"

note() { echo "[queue $(date +%H:%M:%S)] $*" | tee -a "$LOG"; }

run_bench() {
    # The per-attempt bound lives in bench.py's supervisor (it kills the
    # whole child process GROUP — a shell `timeout` here would SIGTERM
    # only the supervisor and orphan a hung compile still holding the
    # tunnel). The outer timeout is a belt-and-braces backstop sized
    # above the supervisor's worst case (2 attempts x tmo).
    local tag="$1" tmo="$2"; shift 2
    note "start $tag (attempt timeout ${tmo}s) env: $*"
    local out rc
    out=$(env "$@" BENCH_ATTEMPT_TIMEOUT="$tmo" \
          timeout $((2 * tmo + 300)) python bench.py 2>>"$LOG")
    rc=$?
    if [ $rc -eq 0 ] && [ -n "$out" ]; then
        echo "{\"tag\": \"$tag\", \"rc\": 0, \"result\": $out}" >> "$RESULTS"
        note "done  $tag -> $out"
    else
        echo "{\"tag\": \"$tag\", \"rc\": $rc, \"result\": null}" >> "$RESULTS"
        note "FAIL  $tag rc=$rc (124=timeout: phase named in $LOG heartbeat)"
    fi
    return $rc
}

note "=== r3 TPU queue starting ==="

# ph1: round-1-equivalent program (fp32 probs). Long timeout: cold
# compile through the tunnel helper took 4-7 min in round 1. This is the
# end-to-end validation gate: if the known-good program cannot produce a
# number, the tunnel/helper is sick and the rest would only burn hours.
run_bench ph1_probs_fp32 1500 BENCH_PROBS=fp32
PH1=$?
if [ $PH1 -ne 0 ]; then
    note "ABORT: validation phase ph1 failed (rc=$PH1) — tunnel/helper unhealthy"
    exit 1
fi

# ph2: the round-2 default program (bf16 probs custom-VJP)
run_bench ph2_probs_bf16_customvjp 2100
PH2=$?

# ph3: only informative if ph2 stalled — bf16 storage, plain autodiff
if [ $PH2 -ne 0 ]; then
    run_bench ph3_probs_bf16_plain 2100 DINOV3_PLAIN_LOWP_SOFTMAX=1
fi

# ph4: fused Pallas LayerNorm on top of the best stable program
if [ $PH2 -eq 0 ]; then
    run_bench ph4_fused_ln 2100 DINOV3_FUSED_LN=1
else
    run_bench ph4_fused_ln_fp32probs 2100 DINOV3_FUSED_LN=1 BENCH_PROBS=fp32
fi

# ph5: high-res crossover table (flash auto vs dense xla)
run_bench ph5_hr512_auto 2100 BENCH_RES=512 BENCH_BATCH=2
run_bench ph5_hr512_xla  2100 BENCH_RES=512 BENCH_BATCH=2 \
    BENCH_OVERRIDES=kernels.flash_attention=xla
run_bench ph5_hr768_auto 2400 BENCH_RES=768 BENCH_BATCH=1
run_bench ph5_hr768_xla  2400 BENCH_RES=768 BENCH_BATCH=1 \
    BENCH_OVERRIDES=kernels.flash_attention=xla

# ph6: committed-evidence profile of the default step program (device
# time breakdown by op category; compile cache makes this cheap now)
note "start ph6_profile"
if timeout 1800 python scripts/profile_step.py /tmp/prof_r3 \
        >> "$LOG" 2>&1; then
    note "done  ph6_profile -> /tmp/prof_r3"
else
    note "FAIL  ph6_profile rc=$?"
fi

# ph7: ViT-S accuracy trajectory on the real chip (digits folder backend,
# a few thousand steps, evals every 500) — the VERDICT r2 #4 shape
note "start ph7_tpu_trajectory"
if TRAJ_STEPS=3000 TRAJ_EVAL_EVERY=500 TRAJ_ARCH=vit_small TRAJ_BATCH=64 \
        timeout 7200 python scripts/train_trajectory.py /tmp/traj_tpu \
        >> "$LOG" 2>&1; then
    note "done  ph7_tpu_trajectory -> /tmp/traj_tpu/TRAJECTORY.json"
else
    note "FAIL  ph7_tpu_trajectory rc=$?"
fi

note "=== r3 TPU queue complete; results in $RESULTS ==="
