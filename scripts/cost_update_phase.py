"""Bytes-accessed accounting for the weight-update phase: the four-pass
clip -> AdamW -> apply -> EMA chain vs the single-pass fused engine
(train/fused_update.py).

Methodology (stated precisely because it is the committed evidence in
docs/PERFORMANCE.md):

- The CHAIN is accounted at pass granularity: each of its four tree
  passes (per-submodel clip, optax.scale_by_adam + scheduled lr/wd,
  optax.apply_updates, teacher EMA) is compiled as its own XLA program
  and their ``cost_analysis()['bytes accessed']`` are summed. This is
  the granularity the r5 on-chip profile shows the TPU executing the
  phase at — distinct sequential weight-shaped elementwise fusion
  programs with materialized intermediates (``PROFILE_r05.json``
  ``multiply_add``/``multiply_multiply`` fusions inside the 28.5%
  norm/reduce bucket) — and it is what any pass-structured execution
  (separate jits, or a backend that does not fuse across the pass
  chain) pays.
- The FUSED engine is one program: clip norms as one up-front batched
  reduction, then a single tree.map emitting (new_param, new_mu,
  new_nu, new_teacher) per leaf.
- Caveat, measured and worth knowing: when the WHOLE chain is handed to
  XLA as one jit, CSE canonicalizes it to the same HLO as the fused
  engine (verified: identical op counts and bytes on the cpu backend).
  The engine's value is therefore structural — it guarantees the
  single-program form at the StableHLO level instead of relying on the
  backend seeing through four optax tree passes — and the on-chip A/B
  (scripts/r6_queue.sh phU) is the measurement that decides what the
  TPU scheduler actually does with each form.

Everything in these programs is weight-shaped (grads, masters, moments,
teacher and nothing else), so the totals ARE the weight-shaped
update-phase traffic. Host-side compile only (cpu backend fine; no
execution — abstract eval_shape + AOT lower/compile).

One JSON line on stdout:

    {"arch": ..., "n_params": ..., "bytes_chain_passes": {...},
     "bytes_chain_total": ..., "bytes_fused": ..., "reduction_pct": ...,
     "floor_bytes": ..., "fused_over_floor": ...}

``floor_bytes``: read g+p+mu+nu+t, write p+mu+nu+t = 9 fp32 passes over
the parameter count, plus the up-front clip-norm read of g = 10.

Since PR 5 the default update path at data-parallel size > 1 is the
CROSS-REPLICA SHARDED form of the fused engine (optim.sharded_update,
train/fused_update.py make_sharded_update). On the single simulated
device this script compiles with, the sharded engine auto-falls back to
the replicated fused form, so the chain-vs-fused numbers above remain
exactly reproducible (they ARE the dp=1 program). Pass a second ``dp``
argument > 1 to also compile the sharded arm over ``dp`` simulated
devices and record its per-device bytes next to the replicated ones
(``bytes_sharded_per_device`` / ``sharded_reduction_pct_vs_fused``);
the full collective story for that arm is
scripts/cost_sharded_update.py's COST_SHUP_r10.json.

Usage: JAX_PLATFORMS=cpu python scripts/cost_update_phase.py [arch] [dp]
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import importlib.util

_spec = importlib.util.spec_from_file_location(
    "bench", os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def _bytes_accessed(fn, args, donate=()) -> float:
    import jax

    compiled = jax.jit(fn, donate_argnums=donate).lower(*args).compile()
    analysis = compiled.cost_analysis()
    if isinstance(analysis, list):
        analysis = analysis[0]
    return float(analysis["bytes accessed"])


def measure(cfg, dp: int = 1) -> dict:
    import jax
    import jax.numpy as jnp
    import optax

    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import (
        build_fused_update,
        build_optimizer,
        build_schedules,
        clip_by_per_submodel_norm,
    )
    from dinov3_tpu.train.fused_update import ema_leaf
    from dinov3_tpu.train.ssl_meta_arch import SSLMetaArch

    meta = SSLMetaArch(cfg)
    batch = {k: jnp.asarray(v)
             for k, v in make_synthetic_batch(cfg, 1, seed=0).items()}
    abstract = jax.eval_shape(
        lambda r: meta.init_params(r, batch), jax.random.key(0)
    )
    student = abstract["student"]
    schedules = build_schedules(cfg)
    optimizer = build_optimizer(cfg, student, schedules)
    fused = build_fused_update(cfg, student, schedules, ema=True)
    opt_state = jax.eval_shape(optimizer.init, student)
    momentum = jax.ShapeDtypeStruct((), jnp.float32)
    clip = cfg.optim.clip_grad

    passes = {
        "clip": _bytes_accessed(
            lambda g: clip_by_per_submodel_norm(g, clip), (student,)),
        "adamw": _bytes_accessed(
            lambda g, s, p: optimizer.update(g, s, p),
            (student, opt_state, student), donate=(1,)),
        "apply": _bytes_accessed(
            optax.apply_updates, (student, student), donate=(0,)),
        "ema": _bytes_accessed(
            lambda t, s, m: jax.tree.map(
                lambda tt, ss: ema_leaf(tt, ss, m), t, s),
            (student, student, momentum), donate=(0,)),
    }
    bytes_fused = _bytes_accessed(
        lambda g, p, t, s, m: fused(g, p, t, s, m)[:3],
        (student, student, student, opt_state, momentum), donate=(1, 2, 3))

    n_params = sum(
        int(jnp.prod(jnp.asarray(l.shape)))
        for l in jax.tree.leaves(student)
    )
    total = sum(passes.values())
    floor = 10 * 4 * n_params
    rec = {
        "n_params": n_params,
        "bytes_chain_passes": passes,
        "bytes_chain_total": total,
        "bytes_fused": bytes_fused,
        "reduction_pct": round(100.0 * (1.0 - bytes_fused / total), 1),
        "floor_bytes": floor,
        "fused_over_floor": round(bytes_fused / floor, 3),
    }
    if dp > 1:
        # the sharded arm (the dp>1 default since PR 5): the GSPMD
        # engine's per-device update program over a dp-way data mesh
        import flax.linen as nn
        from jax.sharding import NamedSharding, PartitionSpec as P

        from dinov3_tpu.parallel.context import set_current_mesh
        from dinov3_tpu.parallel.mesh import MeshSpec, build_mesh
        from dinov3_tpu.parallel.sharding import UPDATE_SHARD_AXES
        from dinov3_tpu.train import make_sharded_update
        from dinov3_tpu.train.fused_update import sharded_adam_zeros
        from dinov3_tpu.train.optimizer import ScheduledAdamWState
        from dinov3_tpu.train.param_groups import build_multiplier_trees

        mesh = build_mesh(MeshSpec(data=dp))
        set_current_mesh(mesh)
        lm, wm, isll = build_multiplier_trees(
            student,
            layerwise_decay=cfg.optim.layerwise_decay,
            patch_embed_lr_mult=cfg.optim.patch_embed_lr_mult,
            dino_head_wd_multiplier=cfg.optim.dino_head_wd_multiplier,
        )
        sharded = make_sharded_update(
            schedules, lm, wm, isll, mesh,
            b1=cfg.optim.adamw_beta1, b2=cfg.optim.adamw_beta2,
            clip_grad=clip, ema=True)
        opt_sh = jax.eval_shape(
            lambda p: ScheduledAdamWState(
                jnp.zeros((), jnp.int32),
                optax.ScaleByAdamState(
                    jnp.zeros((), jnp.int32),
                    nn.meta.unbox(sharded_adam_zeros(p, dp)),
                    nn.meta.unbox(sharded_adam_zeros(p, dp)))),
            student)
        rep = NamedSharding(mesh, P())
        axes = tuple(a for a in UPDATE_SHARD_AXES if a in mesh.shape)
        shard = NamedSharding(mesh, P(axes))
        rep_tree = jax.tree.map(lambda _: rep, student)
        opt_sh_sh = ScheduledAdamWState(
            rep, optax.ScaleByAdamState(
                rep,
                jax.tree.map(lambda _: shard, opt_sh.adam.mu),
                jax.tree.map(lambda _: shard, opt_sh.adam.nu)))
        with mesh:
            compiled = jax.jit(
                lambda g, p, t, s, m: sharded(g, p, t, s, m)[:3],
                in_shardings=(rep_tree, rep_tree, rep_tree, opt_sh_sh, rep),
                out_shardings=(rep_tree, rep_tree, opt_sh_sh),
                donate_argnums=(1, 2, 3),
            ).lower(student, student, student, opt_sh, momentum).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, list):
            analysis = analysis[0]
        rec["sharded_dp"] = dp
        rec["bytes_sharded_per_device"] = float(analysis["bytes accessed"])
        rec["sharded_reduction_pct_vs_fused"] = round(
            100.0 * (1.0 - rec["bytes_sharded_per_device"] / bytes_fused), 1)
    return rec


def main():
    arch = sys.argv[1] if len(sys.argv) > 1 else "vit_large"
    dp = int(sys.argv[2]) if len(sys.argv) > 2 else 1
    if dp > 1 and "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={dp}").strip()
    from dinov3_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()
    if dp > 1:
        import jax

        try:
            jax.config.update("jax_num_cpu_devices", dp)
        except AttributeError:
            pass  # XLA_FLAGS above covers old jaxlibs
    from dinov3_tpu.configs import apply_dot_overrides, get_default_config

    cfg = get_default_config()
    apply_dot_overrides(cfg, bench.build_step_overrides(arch, 0))
    rec = {"arch": arch}
    rec.update(measure(cfg, dp))
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
