#!/bin/bash
# Round-2 TPU measurement queue (run after sweep1 frees the chip):
#  1. new default (bf16 probs + qkv slices) vs fp32-probs control
#  2. flash-vs-XLA crossover at the high-res regimes (VERDICT #7)
set -x
cd /root/repo

python scripts/bench_sweep.py \
    "probs16:" \
    "probs32:_overrides=compute_precision.probs_dtype=fp32" \
    2>&1

BENCH_RES=512 BENCH_BATCH=2 python scripts/bench_sweep.py \
    "hr512_auto:" \
    "hr512_xla:_overrides=kernels.flash_attention=xla" \
    2>&1

BENCH_RES=768 BENCH_BATCH=1 python scripts/bench_sweep.py \
    "hr768_auto:" \
    "hr768_xla:_overrides=kernels.flash_attention=xla" \
    2>&1
