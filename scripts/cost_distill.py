"""Serve-backed multi-student distillation: the committed evidence
behind COST_DISTILL_r22.json (ROADMAP item 2 — compute the 7B teacher
once, fan its features out to every student subgroup).

Under multidistillation every student subgroup used to forward the SAME
frozen teacher over the SAME images inside its own train step: k
subgroups x E epochs = k*E teacher evaluations per unique image. The
serve-backed arm moves the teacher to the host-shared packed AOT engine
(train/distillation.py TeacherServer) behind the content-addressed
feature cache (serve/cache.py), so every unique image is forwarded
EXACTLY ONCE per host — per step, per subgroup, per epoch — and the
train step consumes the precomputed ``teacher_cls``/``teacher_patches``
batch planes through ``get_teacher_output``'s serve arm.

Instruments (all on CPU, structural — no wall times):

- **fan-out dedup**: two student subgroups (vit_test + vit_test_big
  students, one shared vit_test_big teacher) replay a 2-epoch synthetic
  stream through ONE shared TeacherServer
  (multidistillation.shared_teacher_server). Pins: teacher forwards ==
  unique images (forwards per image == 1 regardless of k or epochs; the
  in-step arm pays k*E per image by construction), engine compile count
  == 1 across everything, and the measured cache hit rate equals the
  analytic 1 - 1/(k*E).
- **bitwise loss equivalence**: ``get_teacher_output`` fed precomputed
  planes holding the in-step oracle's OWN backbone features reproduces
  the oracle's teacher targets AND center state bitwise (shared
  ``teacher_targets_from_features`` tail; f32 planes round-trip bf16
  exactly). The serve ENGINE's features vs the in-step forward is a
  tolerance measurement, recorded as max|diff| over the executed step
  losses (bf16 packed program vs in-step program — the on-chip A/B is
  armed as scripts/r6_queue.sh phD).
- **cache hit == miss bitwise**: the replayed epoch's planes are
  array_equal to the first epoch's.
- **attribution**: the teacher-source=serve train step compiles with
  ZERO unattributed collectives (the ``distill_fanout`` scope is in
  utils.HLO_COLLECTIVE_SCOPES), and so does the packed teacher program.

One JSON record -> COST_DISTILL_r22.json (argv[1], default
./COST_DISTILL_r22.json); also printed to stdout. ``--smoke`` runs one
subgroup, one epoch (same pins that apply, no JSON write unless an out
path is given).

Usage: JAX_PLATFORMS=cpu python scripts/cost_distill.py [out] [--smoke]
"""

from __future__ import annotations

import json
import math
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMOKE = "--smoke" in sys.argv
_pos = [a for a in sys.argv[1:] if not a.startswith("--")]
OUT = _pos[0] if _pos else (None if SMOKE else "COST_DISTILL_r22.json")

N_STUDENTS = 1 if SMOKE else 2
N_EPOCHS = 1 if SMOKE else 2
BATCHES_PER_EPOCH = 2
ROWS_PER_BATCH = 4

SMOL = [
    "student.patch_size=4", "student.drop_path_rate=0.0",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
    "dino.head_bottleneck_dim=16",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
    "ibot.head_bottleneck_dim=16",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "optim.scaling_rule=none",
    "telemetry.async_metrics=false",
]

TEACHER_RECIPE = {
    "student": {"arch": "vit_test_big", "patch_size": 4,
                "drop_path_rate": 0.0},
    "dino": {"head_n_prototypes": 64, "head_hidden_dim": 48,
             "head_bottleneck_dim": 16},
    "ibot": {"head_n_prototypes": 64, "head_hidden_dim": 48,
             "head_bottleneck_dim": 16},
    "crops": {"global_crops_size": 16, "local_crops_size": 8,
              "local_crops_number": 2},
    "optim": {"scaling_rule": "none"},
}

# the k student subgroups (multidistillation spec: one arch each)
STUDENT_ARCHES = [
    ("vit_test", []),
    ("vit_test_big", ["dino.head_hidden_dim=48", "ibot.head_hidden_dim=48"]),
][:N_STUDENTS]


def _log(msg):
    print(f"[cost_distill] {msg}", file=sys.stderr, flush=True)


def _student_cfg(teacher_yaml, arch, extra, source="serve"):
    from dinov3_tpu.configs import apply_dot_overrides, get_default_config

    cfg = get_default_config()
    apply_dot_overrides(cfg, SMOL + [
        f"student.arch={arch}",
        "distillation.enabled=true",
        f"distillation.full_cfg_path={teacher_yaml}",
        f"distillation.teacher_source={source}",
    ] + list(extra))
    return cfg


def _epoch_batches(cfg):
    """The fixed synthetic 'dataset': every epoch replays the SAME
    BATCHES_PER_EPOCH batches (seeded), like a real epoch re-reads the
    same images."""
    from dinov3_tpu.data import make_synthetic_batch

    return [make_synthetic_batch(cfg, ROWS_PER_BATCH, seed=s)
            for s in range(BATCHES_PER_EPOCH)]


def fanout_dedup(teacher_yaml, tparams) -> dict:
    """k student subgroups x E epochs through ONE shared TeacherServer:
    the forwards-per-image and cache-hit-rate measurement."""
    import jax

    from dinov3_tpu.train.multidistillation import (
        _SHARED_TEACHERS,
        shared_teacher_server,
    )

    _SHARED_TEACHERS.clear()
    cfgs = [_student_cfg(teacher_yaml, arch, extra)
            for arch, extra in STUDENT_ARCHES]
    servers = [shared_teacher_server(c, teacher_params=tparams, warn=False)
               for c in cfgs]
    assert all(s is servers[0] for s in servers), "subgroups must share"
    srv = servers[0]

    batches = _epoch_batches(cfgs[0])
    # 2 global crops per image: the dedup unit is the CROP row (each
    # distinct crop is one teacher forward)
    unique = {srv.cache.key(np.asarray(b["global_crops"][i], np.float32),
                            srv.fingerprint)
              for b in batches
              for i in range(b["global_crops"].shape[0])}
    crop_rows = sum(b["global_crops"].shape[0] for b in batches)
    first_pass: dict = {}
    replay_bitwise = True
    for epoch in range(N_EPOCHS):
        for sub, _cfg in enumerate(cfgs):
            for bi, b in enumerate(batches):
                ann = srv.annotate(
                    {"global_crops": np.asarray(b["global_crops"],
                                                np.float32)})
                planes = (ann["teacher_cls"], ann["teacher_patches"])
                if bi in first_pass:
                    replay_bitwise &= all(
                        np.array_equal(x, y)
                        for x, y in zip(first_pass[bi], planes))
                else:
                    first_pass[bi] = planes
    stats = srv.stats()
    _SHARED_TEACHERS.clear()
    images_requested = N_STUDENTS * N_EPOCHS * crop_rows
    return {
        "students": N_STUDENTS,
        "epochs": N_EPOCHS,
        "unique_images": len(unique),
        "images_requested": images_requested,
        "teacher_forwards": stats["teacher_forwards"],
        "forwards_per_unique_image": (
            stats["teacher_forwards"] / len(unique)),
        "in_step_forwards_per_unique_image": N_STUDENTS * N_EPOCHS,
        "forward_reduction_x": N_STUDENTS * N_EPOCHS,
        "compile_count": stats["compile_count"],
        "cache": stats["cache"],
        "cache_hit_rate_analytic": 1.0 - 1.0 / (N_STUDENTS * N_EPOCHS),
        "replay_bitwise": bool(replay_bitwise),
        "engine_census_unattributed": __import__(
            "dinov3_tpu.utils", fromlist=["hlo_collective_census"]
        ).hlo_collective_census(srv.engine.compiled_text())["unattributed"],
    }


def loss_equivalence(teacher_yaml) -> dict:
    """The bitwise pin (oracle features through the serve arm) plus the
    executed-step tolerance measurement (engine features vs in-step)."""
    import jax
    import jax.numpy as jnp

    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.parallel.context import set_current_mesh
    from dinov3_tpu.train import build_train_setup, put_batch
    from dinov3_tpu.train.distillation import (
        TeacherServer,
        teacher_feature_example,
    )
    from dinov3_tpu.utils import hlo_collective_census

    arch, extra = STUDENT_ARCHES[0]
    rec = {}
    try:
        # ---- in-step oracle arm
        cfg_o = _student_cfg(teacher_yaml, arch, extra, source="in_step")
        batch = {k: jnp.asarray(v) for k, v in
                 make_synthetic_batch(cfg_o, ROWS_PER_BATCH, seed=0).items()}
        setup_o = build_train_setup(cfg_o, batch)
        meta = setup_o.meta
        frozen = setup_o.state.params["teacher"]
        state0 = meta.init_state()
        temp = 0.05
        oracle_out, oracle_state = meta.get_teacher_output(
            frozen, batch, temp, state0)

        # ---- serve arm fed the oracle's OWN features: bitwise
        cls, patches = meta.teacher_backbone_features(frozen, batch)
        sbatch = dict(batch)
        sbatch["teacher_cls"] = jnp.asarray(np.asarray(cls, np.float32))
        sbatch["teacher_patches"] = jnp.asarray(
            np.asarray(patches, np.float32))
        meta.teacher_source = "serve"
        serve_out, serve_state = meta.get_teacher_output(
            frozen, sbatch, temp, state0)
        meta.teacher_source = "in_step"
        bitwise = all(
            np.array_equal(np.asarray(x), np.asarray(y))
            for a, b in ((oracle_out, serve_out),
                         (oracle_state, serve_state))
            for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))

        # ---- executed steps: in-step program vs serve program whose
        # planes come from the PACKED ENGINE (bf16 serving tree) — the
        # tolerance measurement, not a bitwise claim
        # (snapshot the frozen teacher FIRST: the executed step donates
        # its state buffers, deleting the device tree)
        frozen_host = jax.device_get(frozen)
        dbatch_o = put_batch(batch, setup_o.batch_shardings)
        _log("executing in-step oracle step...")
        _, m_o = setup_o.step_fn(
            setup_o.state, dbatch_o, setup_o.scalars(0), jax.random.key(0))
        loss_o = float(m_o["total_loss"])
        set_current_mesh(None)

        cfg_s = _student_cfg(teacher_yaml, arch, extra, source="serve")
        srv = TeacherServer(
            cfg_s,
            teacher_params=frozen_host["backbone"], warn=False)
        ex = dict(batch)
        ex.update({k: jnp.asarray(v) for k, v in teacher_feature_example(
            cfg_s, ROWS_PER_BATCH * 2).items()})
        setup_s = build_train_setup(cfg_s, ex)
        # teacher init differs across setups; reuse the ORACLE's frozen
        # teacher tree in both programs so the arms compare like with like
        params_s = dict(setup_s.state.params)
        params_s["teacher"] = frozen_host
        state_s = setup_s.state.replace(params=params_s) \
            if hasattr(setup_s.state, "replace") \
            else setup_s.state._replace(params=params_s)
        ann = srv.annotate(
            {"global_crops": np.asarray(batch["global_crops"], np.float32)})
        sb = dict(batch)
        sb["teacher_cls"] = jnp.asarray(ann["teacher_cls"])
        sb["teacher_patches"] = jnp.asarray(ann["teacher_patches"])
        dbatch_s = put_batch(sb, setup_s.batch_shardings)
        _log("compiling + executing serve-arm step...")
        compiled = setup_s.step_fn.lower(
            state_s, dbatch_s, setup_s.scalars(0),
            jax.random.key(0)).compile()
        census = hlo_collective_census(compiled.as_text())
        _, m_s = compiled(
            state_s, dbatch_s, setup_s.scalars(0), jax.random.key(0))
        loss_s = float(m_s["total_loss"])
        rec = {
            "precomputed_vs_oracle_bitwise": bool(bitwise),
            "executed_step_loss_in_step": loss_o,
            "executed_step_loss_serve_engine": loss_s,
            "engine_vs_in_step_loss_diff": abs(loss_s - loss_o),
            "serve_step_census": census,
        }
    finally:
        set_current_mesh(None)
    return rec


def main():
    from dinov3_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import yaml

    from dinov3_tpu.models import build_backbone
    from dinov3_tpu.train.distillation import resolve_distillation_cfg

    tmp = tempfile.mkdtemp()
    teacher_yaml = os.path.join(tmp, "teacher.yaml")
    with open(teacher_yaml, "w") as f:
        yaml.safe_dump(TEACHER_RECIPE, f)

    # one frozen teacher weight tree shared by every arm
    any_cfg = _student_cfg(teacher_yaml, *STUDENT_ARCHES[0])
    teacher_cfg = resolve_distillation_cfg(any_cfg)
    tmodel = build_backbone(teacher_cfg, teacher=True)
    tparams = nn.meta.unbox(
        jax.jit(tmodel.init)(jax.random.key(1), jnp.zeros((1, 16, 16, 3)))
    )["params"]

    _log(f"fan-out dedup: {N_STUDENTS} subgroup(s) x {N_EPOCHS} epoch(s)")
    fanout = fanout_dedup(teacher_yaml, tparams)
    _log("loss equivalence arms...")
    equiv = loss_equivalence(teacher_yaml)

    # ---- acceptance pins (ISSUE 18) ----
    assert fanout["forwards_per_unique_image"] == 1.0, fanout
    assert fanout["compile_count"] == 1, fanout
    assert fanout["replay_bitwise"], "cache hit != miss"
    assert fanout["engine_census_unattributed"] == 0, fanout
    assert math.isclose(fanout["cache"]["hit_rate"],
                        fanout["cache_hit_rate_analytic"],
                        abs_tol=1e-9), fanout["cache"]
    assert equiv["precomputed_vs_oracle_bitwise"], equiv
    assert equiv["serve_step_census"]["unattributed"] == 0, \
        equiv["serve_step_census"]
    assert math.isfinite(equiv["executed_step_loss_serve_engine"]), equiv

    out = {
        "what": ("serve-backed multi-student distillation: ONE packed "
                 "AOT teacher forward per unique image fanned out to "
                 "every student subgroup through the content-addressed "
                 "cache, vs k-subgroups x E-epochs in-step forwards"),
        "fanout": fanout,
        "loss_equivalence": equiv,
        "unattributed_collective_ms": 0.0,
        "note": (
            "CPU harness: structural evidence only (forward/compile "
            "counters, censuses, bitwise comparisons) — no wall times. "
            "The bitwise pin feeds the in-step oracle's own features "
            "through the precomputed-targets arm (shared "
            "teacher_targets_from_features tail); the packed engine's "
            "bf16 features vs the in-step forward is the recorded "
            "loss-diff tolerance, priced on-chip by scripts/r6_queue.sh "
            "phD."),
        "source": ("TeacherServer/shared_teacher_server counters + "
                   "hlo_census of the teacher_source=serve train step "
                   "and the packed teacher program, steps executed"),
    }
    if OUT:
        with open(OUT, "w") as f:
            json.dump(out, f, indent=1)
        _log(f"wrote {OUT}")
    slim = dict(out)
    slim["loss_equivalence"] = {
        k: v for k, v in equiv.items() if k != "serve_step_census"}
    print(json.dumps(slim))
    if SMOKE:
        _log("smoke OK: forwards/unique image == 1, compile count == 1, "
             "replay bitwise, precomputed targets bitwise vs oracle, "
             "zero unattributed collectives")


if __name__ == "__main__":
    main()
