"""Recipe ablation: the DINOv3 loss set deleted one piece at a time.

VERDICT r3 #7: the digits trajectory proves the recipe *trains*, but
nothing showed the iBOT/KoLeo parts of the recipe *mattering*. This
harness trains loss-ablation arms on the procedural texture dataset
(dinov3_tpu/data/textures.py — class = spatial structure, color
decorrelated from label):

  full:       DINO + iBOT + KoLeo (the pretrain recipe defaults)
  dino_only:  ibot.loss_weight=0, dino.koleo_loss_weight=0
  no_koleo:   DINO + iBOT        (dino.koleo_loss_weight=0)
  no_ibot:    DINO + KoLeo       (ibot.loss_weight=0)

The default ABL_ARMS runs the headline pair (full vs dino_only); the
committed ABLATION_r04.json is the full 2x2 factorial, i.e. two more
invocations with ABL_ARMS=no_koleo and ABL_ARMS=no_ibot into the same
out_dir — out_dir/ABLATION.json merges arms by name across invocations
(a re-run arm replaces its previous record).

and records the held-out k-NN / linear-probe trajectory of each arm via
the in-training eval harness (reference's do_test slot —
dinov3_jax/train/train.py:315-316 was a stub). The committed artifact is
the side-by-side curve: the full recipe must beat DINO-only on held-out
k-NN for the extra losses to be pulling weight.

Usage:  JAX_PLATFORMS=cpu python scripts/ablation_recipe.py [out_dir]
Env: ABL_STEPS (default 1200), ABL_EVAL_EVERY (400), ABL_ARCH
     (vit_test4), ABL_BATCH (48), ABL_ARMS (comma list, default
     "full,dino_only"), ABL_TRAIN_PER_CLASS (150), ABL_VAL_PER_CLASS
     (30) — shrink the last two for smoke runs.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ARMS = {
    "full": [],
    "dino_only": ["ibot.loss_weight=0.0", "dino.koleo_loss_weight=0.0"],
    # single-loss deletions complete the factorial with dino_only/full:
    "no_koleo": ["dino.koleo_loss_weight=0.0"],
    "no_ibot": ["ibot.loss_weight=0.0"],
}

DEFAULT_ARCH = "vit_test4"


def record_name(name: str, arch: str) -> str:
    """Merge key for ABLATION.json: arm name, arch-suffixed when the
    arch is non-default so invocations at different widths never
    silently replace each other's records (ADVICE r4)."""
    return name if arch == DEFAULT_ARCH else f"{name}_{arch}"


def run_arm(name: str, out: str, train_dir: str, val_dir: str,
            steps: int, eval_every: int, arch: str, batch: int) -> dict:
    from dinov3_tpu.train.train import main as train_main

    epoch_len = eval_every
    run_dir = os.path.join(out, f"run_{record_name(name, arch)}")
    # train.py appends to <run_dir>/evals.json and --no-resume does not
    # clear the output dir, so a re-run arm would otherwise read the
    # stale previous invocation's eval lines concatenated with its own
    # (ADVICE r4): truncate before training.
    try:
        os.remove(os.path.join(run_dir, "evals.json"))
    except OSError:
        pass
    result = train_main([
        "--output-dir", run_dir, "--no-resume",
        f"student.arch={arch}", "student.patch_size=4",
        "student.drop_path_rate=0.1", "student.layerscale=1.0e-5",
        "crops.global_crops_size=32", "crops.local_crops_size=16",
        "crops.local_crops_number=6",
        "dino.head_n_prototypes=1024", "dino.head_hidden_dim=256",
        "dino.head_bottleneck_dim=64",
        "ibot.head_n_prototypes=1024", "ibot.head_hidden_dim=256",
        "ibot.head_bottleneck_dim=64",
        f"train.batch_size_per_device={batch}",
        f"train.OFFICIAL_EPOCH_LENGTH={epoch_len}",
        f"optim.epochs={steps // epoch_len}",
        "optim.warmup_epochs=1", "optim.lr=0.001",
        "optim.scaling_rule=none",
        "teacher.warmup_teacher_temp_epochs=2",
        "train.num_workers=4",
        "data.backend=folder", f"data.root={train_dir}",
        "train.dataset_path=Folder:split=TRAIN",
        f"evaluation.eval_period_iterations={eval_every}",
        f"evaluation.train_dataset_path=Folder:root={train_dir}",
        f"evaluation.val_dataset_path=Folder:root={val_dir}",
    ] + ARMS[name])
    traj = []
    with open(os.path.join(run_dir, "evals.json")) as f:
        for line in f:
            traj.append(json.loads(line))
    return {"arm": record_name(name, arch), "overrides": ARMS[name],
            "trajectory": traj,
            "final_loss": result.get("final_loss"),
            # per-arm metadata: merged artifacts can span invocations
            # with different settings, so each arm records its own
            "steps": steps, "arch": arch, "batch": batch}


def main():
    from dinov3_tpu.data.textures import materialize_textures
    from dinov3_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()

    out = sys.argv[1] if len(sys.argv) > 1 else "/tmp/ablation_run"
    steps = int(os.environ.get("ABL_STEPS", "1200"))
    eval_every = int(os.environ.get("ABL_EVAL_EVERY", "400"))
    arch = os.environ.get("ABL_ARCH", "vit_test4")
    batch = int(os.environ.get("ABL_BATCH", "48"))
    arms = [a.strip() for a in
            os.environ.get("ABL_ARMS", "full,dino_only").split(",")
            if a.strip()]
    unknown = [a for a in arms if a not in ARMS]
    if unknown:
        raise SystemExit(f"unknown ABL_ARMS {unknown}; known: {list(ARMS)}")
    if steps < eval_every or steps % eval_every:
        raise SystemExit(
            f"ABL_STEPS={steps} must be a positive multiple of "
            f"ABL_EVAL_EVERY={eval_every} (epochs are eval periods)")

    n_train = int(os.environ.get("ABL_TRAIN_PER_CLASS", "150"))
    n_val = int(os.environ.get("ABL_VAL_PER_CLASS", "30"))
    train_dir, val_dir = materialize_textures(
        os.path.join(out, "textures"),
        n_train_per_class=n_train, n_val_per_class=n_val,
    )

    art_path = os.path.join(out, "ABLATION.json")
    results = []
    if os.path.isfile(art_path):
        # merge across invocations by arm name (a re-run arm replaces
        # its old record), so the documented multi-invocation factorial
        # accumulates into ONE artifact instead of each run clobbering
        # the previous arms. A truncated artifact (killed mid-write of
        # a non-atomic writer from an older revision) must not brick
        # every later invocation — start fresh instead.
        replaced = {record_name(a, arch) for a in arms}

        def _stale(rec: dict) -> bool:
            # a record is replaced only when BOTH its arm key and its
            # recorded arch match this invocation's (arm, arch) cell:
            # the arch guard keeps a default-arch rerun from deleting an
            # old-format bare-name record that was written at a
            # DIFFERENT arch (a distinct cell). Also drop OLD-format
            # records from the pre-suffix revision (bare arm name at a
            # non-default arch) when their arch metadata matches.
            rec_arch = rec.get("arch", DEFAULT_ARCH)
            return ((rec["arm"] in replaced and rec_arch == arch)
                    or (rec["arm"] in arms and rec_arch == arch))

        try:
            with open(art_path) as f:
                results = [a for a in json.load(f).get("arms", [])
                           if not _stale(a)]
        except ValueError:
            print(f"[ablation] {art_path} unreadable; starting fresh",
                  flush=True)
    for arm in arms:
        print(f"[ablation] arm={arm} steps={steps}", flush=True)
        results.append(run_arm(arm, out, train_dir, val_dir, steps,
                               eval_every, arch, batch))
        # incremental + atomic: a killed later arm still leaves a
        # parseable artifact with every completed arm
        tmp_path = art_path + ".tmp"
        with open(tmp_path, "w") as f:
            json.dump({
                "dataset": "procedural textures, 12 classes = motif x "
                           "frequency-band, per-image palette "
                           f"({12 * n_train} train / {12 * n_val} val "
                           "PNGs, folder backend; eval batches are 64 "
                           "with drop_last, so metrics are over 320 of "
                           "the 360 val images)",
                # no top-level arch/steps/batch: the merged artifact can
                # span invocations with different settings — the per-arm
                # records are authoritative (r5 code review)
                "arms": results,
            }, f, indent=2)
        os.replace(tmp_path, art_path)
    print(json.dumps(results[-1]["trajectory"][-1:], indent=2))


if __name__ == "__main__":
    main()
