"""Host-side accounting for the crop-packed single-pass student engine
(ops/packing.py, model.crop_packing): student-phase weight-stream bytes,
row counts, and pad-waste fractions — packed vs the two-pass oracle, at
pass granularity.

Methodology (the PR-1/2/3 discipline, scripts/cost_update_phase.py /
cost_target_phase.py / cost_rng_copies.py): each student pass of the
ORACLE program is compiled as its own XLA fwd+bwd program — the
granularity at which the weight stack actually streams from HBM (one
read per forward, one per backward, per program) — and the PACKED
engine as one program. Three numbers per arm:

- ``weight_stream_bytes``: fp32 master bytes x the number of
  weight-stack streams (2 per program: fwd read + bwd read). This is
  STRUCTURAL: the two-pass oracle streams the ViT-L stack 4x per step
  (global fwd/bwd + local fwd/bwd), the packed engine 2x — the -50%
  that motivates the engine. No backend fusion can merge two separately
  dispatched backbone applications' weight reads.
- ``bytes_accessed``: the compiled programs'
  ``cost_analysis()['bytes accessed']`` summed per arm — the measured
  corroboration (includes activations, so the relative saving is
  smaller than the weight-stream number; stated, not hidden).
- row/pad geometry: 120 token-rows -> 44 at ViT-L B=12, the packed
  token pad-waste fraction, and the 128-lane pad factor of the
  37-token local rows the packing removes (the same padding-cliff
  class as the B=10 sublane guardrail).

Both arms are compiled DETERMINISTIC (no drop-path subsetting): the
subset engine is orthogonal and its cut is priced in the FLOP ledger
(scripts/count_flops.py vitl_subset vs vitl_mask); mixing the two
randomized gathers into this accounting would blur which engine owns
which bytes. The unrolled stack is compiled on every point (the scan
caveat from count_flops.py: cost_analysis counts a scan body once).

One JSON line on stdout -> commit as COST_PACK_r09.json. The on-chip
A/B that measures what the TPU scheduler does with each form is armed
as scripts/r6_queue.sh phP (both arms BENCH_PROBS=bf16 BENCH_CENSUS=1).

Usage: JAX_PLATFORMS=cpu python scripts/cost_pack_student.py
Env: COST_ARCH (default vit_large), COST_BATCH (default 12)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bytes_accessed(compiled) -> float:
    analysis = compiled.cost_analysis()
    if isinstance(analysis, list):
        analysis = analysis[0]
    return float(analysis["bytes accessed"])


def _lane_pad_factor(n: int, lane: int = 128) -> float:
    """Padded-lane fraction of an [., n] attention-score axis."""
    padded = -(-n // lane) * lane
    return (padded - n) / n


def main():
    from dinov3_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()
    import jax
    import jax.numpy as jnp

    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get("BENCH_CACHE_DIR", "/tmp/jaxcache"),
    )

    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.models import build_backbone
    from dinov3_tpu.ops.packing import layout_from_cfg

    arch = os.environ.get("COST_ARCH", "vit_large")
    B = int(os.environ.get("COST_BATCH", "12"))
    cfg = get_default_config()
    apply_dot_overrides(cfg, [
        f"student.arch={arch}", "train.scan_layers=false",
        "optim.scaling_rule=none",
    ])
    module = build_backbone(cfg, teacher=False, param_dtype=jnp.float32)
    S = int(cfg.crops.global_crops_size)
    s = int(cfg.crops.local_crops_size)
    n_l = int(cfg.crops.local_crops_number)
    g_abs = jax.ShapeDtypeStruct((2 * B, S, S, 3), jnp.float32)
    l_abs = jax.ShapeDtypeStruct((n_l * B, s, s, 3), jnp.float32)
    params_abs = jax.eval_shape(
        lambda r: module.init(r, jnp.zeros((1, S, S, 3)))["params"],
        jax.random.key(0))
    param_bytes = sum(
        leaf.size * 4 for leaf in jax.tree.leaves(params_abs))
    layout = layout_from_cfg(cfg, B)

    def out_sum(out):
        total = (jnp.sum(out["x_norm_clstoken"].astype(jnp.float32))
                 + jnp.sum(out["x_norm_patchtokens"].astype(jnp.float32)))
        if "local_cls" in out:
            total = total + jnp.sum(out["local_cls"].astype(jnp.float32))
        return total

    def g_pass(p, g):
        return out_sum(module.apply({"params": p}, g, None,
                                    crop_kind="global", deterministic=True))

    def l_pass(p, l):
        return out_sum(module.apply({"params": p}, l, None,
                                    crop_kind="local", deterministic=True))

    def packed_pass(p, g, l):
        return out_sum(module.apply({"params": p}, g, None,
                                    crop_kind="global", deterministic=True,
                                    local_crops=l))

    programs = {
        "oracle_global": (jax.grad(g_pass), (params_abs, g_abs)),
        "oracle_local": (jax.grad(l_pass), (params_abs, l_abs)),
        "packed": (jax.grad(packed_pass), (params_abs, g_abs, l_abs)),
    }
    measured = {}
    for name, (fn, args) in programs.items():
        t0 = time.perf_counter()
        compiled = jax.jit(fn).lower(*args).compile()
        measured[name] = {
            "bytes_accessed": _bytes_accessed(compiled),
            "compile_s": round(time.perf_counter() - t0, 1),
        }
        print(f"[pack] {name}: {measured[name]['bytes_accessed'] / 1e9:.2f} "
              f"GB accessed ({measured[name]['compile_s']}s compile)",
              file=sys.stderr, flush=True)

    oracle_bytes = (measured["oracle_global"]["bytes_accessed"]
                    + measured["oracle_local"]["bytes_accessed"])
    packed_bytes = measured["packed"]["bytes_accessed"]
    # weight-stream structure: fwd read + bwd read per compiled program
    streams_oracle, streams_packed = 2 * 2, 1 * 2
    rows_oracle = 2 * B + n_l * B
    rec = {
        "what": ("crop-packed single-pass student engine accounting: "
                 "fp32 weight-stream bytes (structural: streams x "
                 "param bytes, fwd+bwd per compiled program), measured "
                 "bytes accessed (cost_analysis, host compile, "
                 "deterministic passes, unrolled stack), row/pad "
                 "geometry"),
        "script": "scripts/cost_pack_student.py",
        "date": time.strftime("%Y-%m-%d"),
        "arch": arch, "batch_per_chip": B,
        "param_bytes_fp32": param_bytes,
        "weight_stream": {
            "oracle_streams": streams_oracle,
            "packed_streams": streams_packed,
            "oracle_bytes": streams_oracle * param_bytes,
            "packed_bytes": streams_packed * param_bytes,
            "reduction_pct": round(
                100.0 * (1.0 - streams_packed / streams_oracle), 1),
        },
        "bytes_accessed": {
            "oracle_pass_granularity": oracle_bytes,
            "packed": packed_bytes,
            "reduction_pct": round(
                100.0 * (1.0 - packed_bytes / oracle_bytes), 1),
            "per_program": measured,
        },
        "rows": {
            "oracle": rows_oracle,
            "packed": layout.rows_total,
            "k": layout.k,
            "packed_rows_local": layout.n_packed_rows,
            "seq_global": layout.seq_global,
            "seq_local": layout.seq_local,
        },
        "pad_waste": {
            "packed_token_fraction": round(layout.pad_waste, 4),
            "lane_pad_factor_local_rows": round(
                _lane_pad_factor(layout.seq_local), 3),
            "lane_pad_factor_packed_rows": round(
                _lane_pad_factor(layout.seq_global), 3),
        },
    }
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
