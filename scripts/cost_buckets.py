"""Bucketed collective engine accounting: the committed evidence
behind COST_BUCKET_r13.json (PR-1..6 discipline — compile the exact
shipped code paths, account from their compiled HLO).

Three instruments, all on the 8-simulated-device CPU mesh:

- **Update-phase twins (ViT-L, compile-only)**: the per-leaf schedule
  (``make_sharded_update_schedule`` — the bitwise oracle; one
  reduce-scatter per leaf, one all-gather per updated student/teacher
  leaf) vs the bucketed schedule (``make_bucketed_update_schedule`` —
  ONE reduce-scatter / all-gather per bucket), both compiled as
  standalone update-phase programs over [dp, *leaf] stacks of
  per-replica partial grads, so the grad sync is INSIDE the measured
  program. The in-step GSPMD-annotation engine
  (``make_bucketed_update``) is censused alongside for honesty
  (``engine_gspmd_census`` — this container's XLA:CPU lowers its
  reduce-scatters in the pre-rewrite all-reduce+slice form; the
  schedule twin is the committed proof of the post-rewrite collective
  set, and tests/test_buckets.py pins that both arms compute the
  BITWISE-identical update).
- **Message-size histogram**: ``utils.hlo_collective_census``'s
  power-of-two ``size_histogram`` of both twins — the per-leaf arm's
  hundreds of latency-bound sub-MiB messages vs the bucketed arm's
  handful of bandwidth-bound >= 64 MiB ones (>= 90% of collective
  bytes, pinned below).
- **Overlap placement**: ``jax.grad`` of the explicit overlap twin
  (``models/streaming.bucketed_stream_scan`` over a ViT-L-shaped bf16
  block stack in equal-sized bucket shards) — the census
  ``by_placement`` column must attribute the forward param all-gather
  to the forward loop body and its transposed grad reduce-scatter to
  the BACKWARD loop body (issued bucket-by-bucket as the backward
  produces each grad, overlappable with the remaining backward
  compute), with zero unattributed collectives.

One JSON record -> COST_BUCKET_r13.json (argv[1], default
./COST_BUCKET_r13.json); also printed to stdout.

Usage: JAX_PLATFORMS=cpu python scripts/cost_buckets.py [out] [dp]
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = sys.argv[1] if len(sys.argv) > 1 and not sys.argv[1].startswith(
    "--") else "COST_BUCKET_r13.json"
DP = int(sys.argv[2]) if len(sys.argv) > 2 else 8

os.environ.setdefault("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += f" --xla_force_host_platform_device_count={DP}"

BIG_BIN = 64 * 2 ** 20  # the coalesced-regime floor pinned below


def _log(msg):
    print(f"[cost_buckets] {msg}", file=sys.stderr, flush=True)


def _bench():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _compiled(fn, args, mesh, in_shardings, out_shardings=None, donate=()):
    import jax

    with mesh:
        return jax.jit(
            fn, in_shardings=in_shardings, out_shardings=out_shardings,
            donate_argnums=donate,
        ).lower(*args).compile()


def _big_bin_fraction(census) -> float:
    """Fraction of the module's collective bytes in >= BIG_BIN bins."""
    hist = census["size_histogram"]
    total = sum(h["bytes"] for h in hist.values())
    big = sum(h["bytes"] for h in hist.values()
              if h["floor_bytes"] >= BIG_BIN)
    return big / max(total, 1)


def update_phase_twins(cfg, dp: int) -> dict:
    """Per-leaf vs bucketed update schedules over the real ViT-L tree."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.parallel.context import set_current_mesh
    from dinov3_tpu.parallel.mesh import MeshSpec, build_mesh
    from dinov3_tpu.parallel.sharding import UPDATE_SHARD_AXES
    from dinov3_tpu.train import (
        build_multiplier_trees,
        build_schedules,
        make_bucket_plan,
        make_bucketed_update,
        make_bucketed_update_schedule,
        make_sharded_update_schedule,
    )
    from dinov3_tpu.train.fused_update import (
        bucketed_adam_zeros,
        sharded_adam_zeros,
    )
    from dinov3_tpu.train.optimizer import ScheduledAdamWState
    from dinov3_tpu.train.ssl_meta_arch import SSLMetaArch
    from dinov3_tpu.utils import hlo_collective_census

    mesh = build_mesh(MeshSpec(data=dp))
    set_current_mesh(mesh)
    meta = SSLMetaArch(cfg)
    batch = {k: jnp.asarray(v)
             for k, v in make_synthetic_batch(cfg, 1, seed=0).items()}
    student = jax.eval_shape(
        lambda r: meta.init_params(r, batch), jax.random.key(0)
    )["student"]
    schedules = build_schedules(cfg)
    lm, wm, isll = build_multiplier_trees(
        student,
        layerwise_decay=cfg.optim.layerwise_decay,
        patch_embed_lr_mult=cfg.optim.patch_embed_lr_mult,
        dino_head_wd_multiplier=cfg.optim.dino_head_wd_multiplier,
    )
    from dinov3_tpu.configs.config import resolve_bucket_mb

    target_bytes = resolve_bucket_mb(
        cfg.optim.get("bucket_mb", "auto")) * 2 ** 20
    plan = make_bucket_plan(student, dp, is_last_layer=isll,
                            target_bytes=target_bytes)
    kw = dict(b1=cfg.optim.adamw_beta1, b2=cfg.optim.adamw_beta2,
              clip_grad=cfg.optim.clip_grad, ema=True)
    perleaf = make_sharded_update_schedule(schedules, lm, wm, isll, mesh,
                                           **kw)
    bucketed = make_bucketed_update_schedule(schedules, lm, wm, isll, mesh,
                                             plan, **kw)
    engine = make_bucketed_update(schedules, lm, wm, isll, mesh, plan, **kw)

    rep = NamedSharding(mesh, P())
    axes = tuple(a for a in UPDATE_SHARD_AXES if a in mesh.shape)
    stacks = NamedSharding(mesh, P(axes))
    gstack = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct((dp,) + l.shape, l.dtype), student)
    opt_pl = jax.eval_shape(
        lambda p: ScheduledAdamWState(
            jnp.zeros((), jnp.int32),
            optax.ScaleByAdamState(
                jnp.zeros((), jnp.int32),
                nn.meta.unbox(sharded_adam_zeros(p, dp)),
                nn.meta.unbox(sharded_adam_zeros(p, dp)))),
        student)
    opt_bk = jax.eval_shape(
        lambda: ScheduledAdamWState(
            jnp.zeros((), jnp.int32),
            optax.ScaleByAdamState(
                jnp.zeros((), jnp.int32),
                nn.meta.unbox(bucketed_adam_zeros(plan)),
                nn.meta.unbox(bucketed_adam_zeros(plan)))))
    momentum = jax.ShapeDtypeStruct((), jnp.float32)
    rep_tree = jax.tree.map(lambda _: rep, student)
    stack_tree = jax.tree.map(lambda _: stacks, gstack)
    opt_pl_sh = ScheduledAdamWState(
        rep, optax.ScaleByAdamState(
            rep,
            jax.tree.map(lambda _: stacks, opt_pl.adam.mu),
            jax.tree.map(lambda _: stacks, opt_pl.adam.nu)))
    opt_bk_sh = ScheduledAdamWState(
        rep, optax.ScaleByAdamState(
            rep,
            jax.tree.map(lambda _: stacks, opt_bk.adam.mu),
            jax.tree.map(lambda _: stacks, opt_bk.adam.nu)))

    def perleaf_arm(gs, p, t, s, m):
        return perleaf(gs, p, t, s, m)[:3]

    def bucketed_arm(gs, p, t, s, m):
        return bucketed(gs, p, t, s, m)[:3]

    def engine_arm(gs, p, t, s, m):
        # the in-step GSPMD engine (what build_train_setup ships); its
        # grad input is the already-summed tree
        g = jax.tree.map(lambda x: jnp.sum(x, 0), gs)
        return engine(g, p, t, s, m)[:3]

    args_pl = (gstack, student, student, opt_pl, momentum)
    args_bk = (gstack, student, student, opt_bk, momentum)
    in_pl = (stack_tree, rep_tree, rep_tree, opt_pl_sh, rep)
    in_bk = (stack_tree, rep_tree, rep_tree, opt_bk_sh, rep)
    _log(f"compiling per-leaf update twin (dp={dp})...")
    c_pl = _compiled(perleaf_arm, args_pl, mesh, in_pl,
                     out_shardings=(rep_tree, rep_tree, opt_pl_sh),
                     donate=(1, 2, 3))
    _log("compiling bucketed update twin...")
    c_bk = _compiled(bucketed_arm, args_bk, mesh, in_bk,
                     out_shardings=(rep_tree, rep_tree, opt_bk_sh),
                     donate=(1, 2, 3))
    _log("compiling in-step GSPMD bucketed engine...")
    c_eng = _compiled(engine_arm, args_bk, mesh, in_bk,
                      out_shardings=(rep_tree, rep_tree, opt_bk_sh),
                      donate=(1, 2, 3))

    census_pl = hlo_collective_census(c_pl.as_text())
    census_bk = hlo_collective_census(c_bk.as_text())
    census_eng = hlo_collective_census(c_eng.as_text())

    rows = plan.padding_stats()
    payload = sum(r["bytes"] for r in rows)
    return {
        "n_param_leaves": len(jax.tree.leaves(student)),
        "plan": {
            "n_buckets": len(rows),
            "target_bytes": target_bytes,
            "payload_bytes": int(payload),
            "pad_fraction": round(
                sum(r["pad_elems"] for r in rows)
                / max(sum(r["elems"] for r in rows), 1), 6),
            "buckets": rows,
        },
        "collective_census": {
            "per_leaf": census_pl, "bucketed": census_bk},
        "engine_gspmd_census": census_eng,
        "big_bin_fraction": {
            "per_leaf": round(_big_bin_fraction(census_pl), 4),
            "bucketed": round(_big_bin_fraction(census_bk), 4),
        },
    }


def overlap_twin_census(cfg, dp: int, n_buckets: int = 4) -> dict:
    """``jax.grad`` of the explicit overlap twin at ViT-L block shapes:
    bf16 stack in equal bucket shards as a program input; the forward
    gathers ride the loop body one bucket ahead, their transposed grad
    reduce-scatters land in the backward loop body."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dinov3_tpu.models import build_backbone
    from dinov3_tpu.models.streaming import (
        bucketed_stream_scan,
        pack_stream_buckets,
    )
    from dinov3_tpu.ops.block import SelfAttentionBlock
    from dinov3_tpu.parallel.context import set_current_mesh
    from dinov3_tpu.parallel.mesh import MeshSpec, build_mesh
    from dinov3_tpu.parallel.sharding import UPDATE_SHARD_AXES
    from dinov3_tpu.utils import hlo_collective_census

    mesh = build_mesh(MeshSpec(data=dp))
    set_current_mesh(mesh)
    model = build_backbone(cfg)
    kwargs = model._block_kwargs()
    kwargs["drop_path_rate"] = 0.0
    L = model.n_blocks
    D = model.embed_dim
    N = 197

    block = SelfAttentionBlock(**kwargs)
    one_block = jax.eval_shape(
        lambda r: block.init(r, jnp.zeros((1, N, D), jnp.bfloat16)),
        jax.random.key(0))["params"]
    one_block = nn.meta.unbox(one_block)
    stack = jax.tree.map(
        lambda p: jax.ShapeDtypeStruct(
            (L,) + tuple(p.shape), jnp.bfloat16), one_block)
    shards = jax.eval_shape(
        lambda s: pack_stream_buckets(s, n_buckets, dp), stack)

    x_abs = jax.ShapeDtypeStruct((2 * dp, N, D), jnp.bfloat16)
    axes = tuple(a for a in UPDATE_SHARD_AXES if a in mesh.shape)

    def loss(bucket_shards, x):
        y = bucketed_stream_scan(bucket_shards, x, mesh=mesh, prefetch=True)
        return jnp.sum(y.astype(jnp.float32))

    _log("compiling grad of the bucketed overlap twin...")
    compiled = _compiled(
        jax.grad(loss), (shards, x_abs), mesh,
        (NamedSharding(mesh, P(None, axes)), NamedSharding(mesh, P(axes[0]))),
    )
    census = hlo_collective_census(compiled.as_text())
    return {
        "n_blocks": L,
        "n_buckets": n_buckets,
        "bucket_shard_shape": list(shards.shape),
        "collective_census": census,
        "note": (
            "explicit overlap twin (models/streaming.bucketed_stream_scan "
            "under jax.grad): the bf16 stack rides as [n_buckets, S/dp] "
            "equal bucket shards; the scan body all-gathers bucket i+1 "
            "under bucket_prefetch while consuming bucket i, and jax's "
            "transpose turns each in-loop gather into an in-loop "
            "reduce-scatter of that bucket's grads — the census "
            "by_placement column attributes it to the BACKWARD loop "
            "body (op_name carries transpose(...)), i.e. the grad sync "
            "is issued as the backward produces each bucket, "
            "overlappable with the remaining backward compute."
        ),
    }


def main():
    from dinov3_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()
    import jax

    try:
        jax.config.update("jax_num_cpu_devices", DP)
    except AttributeError:
        pass
    from dinov3_tpu.configs import apply_dot_overrides, get_default_config

    bench = _bench()
    cfg = get_default_config()
    # no scan_layers override: the per-leaf baseline counts (one RS per
    # of the 357 ViT-L leaves, one AG per updated student/teacher leaf)
    # are the unscanned tree's — the cost_sharded_update.py convention
    apply_dot_overrides(cfg, bench.build_step_overrides("vit_large", 0))

    upd = update_phase_twins(cfg, DP)
    pl = upd["collective_census"]["per_leaf"]["by_class"]
    bk = upd["collective_census"]["bucketed"]["by_class"]

    def ops(c, k):
        return c.get(k, {"ops": 0})["ops"]

    # ---- acceptance pins (ISSUE 9) ----
    assert upd["collective_census"]["per_leaf"]["unattributed"] == 0
    assert upd["collective_census"]["bucketed"]["unattributed"] == 0
    rs_before, rs_after = ops(pl, "reduce_scatter"), ops(bk, "reduce_scatter")
    ag_before, ag_after = ops(pl, "all_gather"), ops(bk, "all_gather")
    assert rs_after <= 16, (rs_before, rs_after)
    assert ag_after <= 32, (ag_before, ag_after)
    assert rs_before >= 300 and ag_before >= 600, (rs_before, ag_before)
    assert upd["big_bin_fraction"]["bucketed"] >= 0.90, upd[
        "big_bin_fraction"]

    overlap = overlap_twin_census(cfg, DP)
    oc = overlap["collective_census"]
    rs_pl = oc["by_class"]["reduce_scatter"]["by_placement"]
    ag_pl = oc["by_class"]["all_gather"]["by_placement"]
    assert oc["unattributed"] == 0
    assert rs_pl.get("in-backward-loop", {"ops": 0})["ops"] >= 1, rs_pl
    assert ag_pl.get("in-forward-loop", {"ops": 0})["ops"] >= 1, ag_pl

    rec = {
        "what": ("bucketed collective engine: coalesced update-phase "
                 "reduce-scatter/all-gather + overlap placement"),
        "arch": "vit_large",
        "dp": DP,
        "update_phase": upd,
        "reduce_scatter_ops": {"per_leaf": rs_before, "bucketed": rs_after},
        "all_gather_ops": {"per_leaf": ag_before, "bucketed": ag_after},
        "overlap_twin": overlap,
        "source": "hlo_census of the explicit schedule twins + grad of "
                  "the overlap twin (8 simulated CPU devices, "
                  "compile-only; PR-1..6 discipline)",
    }
    with open(OUT, "w") as f:
        json.dump(rec, f, indent=1)
    _log(f"wrote {OUT}")
    print(json.dumps({k: v for k, v in rec.items()
                      if k not in ("update_phase", "overlap_twin")}))


if __name__ == "__main__":
    main()
