"""Step-anatomy artifact: the committed evidence behind ANATOMY_r17.json
— MEASURED per-scope device time with the exposed/overlapped collective
split, for all four training arms, on the 8-simulated-device CPU mesh.

Where the COST_* artifacts census the compiled HLO (static placement:
"the RS sits inside the backward while-loop"), this one EXECUTES each
arm's program under the jax.profiler and parses the trace through the
shared anatomy plane (telemetry/trace.py + telemetry/anatomy.py):
device time by op category, collective time attributed to named scopes
via the compiled HLO's op_name metadata, measured exposed/overlapped
collective ms per scope, and the measured backward interval — the
dynamic twin of the ``by_placement`` census.

Programs (single-core honesty — this container has ONE CPU core, so a
full ViT-L train step cannot execute in budget; each arm is measured on
the executable program where the arms actually DIFFER, the same twin
discipline as COST_BUCKET_r13 / COST_Z3_r12, but executed, not just
compiled):

- **replicated**: ViT-L dp=8 update phase — stacked per-replica grads
  summed (the implicit grad all-reduce) + the fused replicated update.
- **flat (PR 5)**: ``make_sharded_update_schedule`` — one
  reduce-scatter per leaf, shard-local update, one all-gather per
  updated leaf (1074 collectives/step, all latency-bound).
- **bucketed (PR 9)**: ``make_bucketed_update_schedule`` — the same
  update through ~128 MB buckets (bucket_pack RS / bucket_unpack AG),
  PLUS the executed overlap twin (``jax.grad`` of
  ``bucketed_stream_scan`` at truncated depth): its ledger must show
  bucket-scoped reduce-scatter time INSIDE the measured backward
  interval — consistent with COST_BUCKET_r13.json's static
  ``in-backward-loop`` placement.
- **zero3 (PR 7)**: the executed double-buffered weight-stream twin
  (``jax.grad`` of ``streamed_block_scan``, zero3-sharded stack):
  zero3_prefetch gathers in the measured forward, their transposed
  reduce-scatters in the measured backward.

Plus a tiny end-to-end dryrun (vit_test dp=8) through the REAL trainer
with ``--profile-steps``, exercising the train-loop anatomy wiring
(anatomy.json + "anatomy" span), and the fleet report over its span
stream.

CPU-harness caveat (docs/OBSERVABILITY.md): XLA:CPU runs each simulated
device's thunks sequentially on one worker thread, so measured overlap
fractions here are structural LOWER bounds — the committed numbers pin
attribution, exposure ceilings, and backward-interval placement; the
TPU overlap fractions bank when scripts/r6_queue.sh phA runs.

Usage: JAX_PLATFORMS=cpu python scripts/anatomy_report.py [out] [--smoke]
--smoke: dryrun + schema/attribution checks only (the CI tier-1 step).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DP = 8
os.environ.setdefault("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += f" --xla_force_host_platform_device_count={DP}"

OUT = sys.argv[1] if len(sys.argv) > 1 and not sys.argv[1].startswith(
    "--") else "ANATOMY_r17.json"
SMOKE = "--smoke" in sys.argv

TRACED_STEPS = 2
# truncated stream-twin geometry (single-core budget): ViT-L width,
# fewer blocks/tokens — the comm *structure* (scopes, loop placement,
# double buffering) is depth-independent
TWIN_BLOCKS = 4
TWIN_TOKENS = 64
N_BUCKETS = 4

TINY = [
    "student.arch=vit_test", "student.patch_size=4",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2", "train.batch_size_per_device=2",
    "optim.scaling_rule=none", "data.backend=synthetic",
    "optim.epochs=1", "optim.warmup_epochs=0",
    "checkpointing.period=1000000",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
    "dino.head_bottleneck_dim=16",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
    "ibot.head_bottleneck_dim=16",
]


def _log(msg):
    print(f"[anatomy_report] {msg}", file=sys.stderr, flush=True)


def _bench():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _traced_summary(run_step, compiled, tag: str) -> dict:
    """Execute one warmup + TRACED_STEPS profiled steps of an arm's
    program and parse the window through the shared anatomy plane.
    ``run_step()`` executes ONE step and blocks on its outputs (the
    inter-step host sync is what gives the window its per-step gap
    structure — the same fetch-synced discipline bench.py uses)."""
    import jax

    from dinov3_tpu.telemetry import anatomy_ledger, ledger_summary
    from dinov3_tpu.telemetry.trace import find_trace_file, load_trace

    run_step()  # warmup: ensure no compile lands inside the window
    tdir = tempfile.mkdtemp(
        prefix=f"anatomy_{tag.replace('/', '_')}_", dir="/tmp")
    t0 = time.perf_counter()
    jax.profiler.start_trace(tdir)
    try:
        for _ in range(TRACED_STEPS):
            run_step()
    finally:
        jax.profiler.stop_trace()
    _log(f"{tag}: traced {TRACED_STEPS} steps in "
         f"{time.perf_counter() - t0:.1f}s")
    ledger = anatomy_ledger(
        load_trace(find_trace_file(tdir)),
        hlo_text=compiled.as_text(), n_steps=TRACED_STEPS)
    summary = ledger_summary(ledger)
    shutil.rmtree(tdir, ignore_errors=True)
    # ---- attribution pins, per arm ----
    assert summary["hlo_joined"], tag
    # >= DP, not ==: beyond the 8 tf_XLATfrtCpuClient device threads,
    # XLA:CPU's tf_XLAEigen intra-op pool carries op-annotated events on
    # larger programs (each pool thread spans every step, so per-timeline
    # step windows and attribution stay correct).
    assert summary["n_timelines"] >= DP, (tag, summary["n_timelines"])
    assert summary["unattributed_collective_ms"] == 0.0, (
        tag, summary["unattributed_collective_ms"])
    assert summary["collectives"], f"{tag}: no collective time measured"
    return summary


def _materialize(tree, shardings):
    import jax
    import jax.numpy as jnp

    return jax.tree.map(
        lambda l, s: jax.device_put(jnp.zeros(l.shape, l.dtype), s),
        tree, shardings)


def update_phase_arms(cfg, only: tuple | None = None) -> dict:
    """The three update-phase arms (replicated / flat / bucketed) over
    the real ViT-L tree, executed — same program construction as
    scripts/cost_buckets.py update_phase_twins, plus the replicated
    fused-update arm. ``only`` restricts to a subset of arm names (the
    tuner's per-candidate sweeps re-measure ONE arm per call,
    scripts/tune_collectives.py)."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.parallel.context import set_current_mesh
    from dinov3_tpu.parallel.mesh import MeshSpec, build_mesh
    from dinov3_tpu.parallel.sharding import UPDATE_SHARD_AXES
    from dinov3_tpu.train import (
        build_multiplier_trees,
        build_schedules,
        make_bucket_plan,
        make_bucketed_update_schedule,
        make_fused_update,
        make_sharded_update_schedule,
    )
    from dinov3_tpu.train.fused_update import (
        bucketed_adam_zeros,
        sharded_adam_zeros,
    )
    from dinov3_tpu.train.optimizer import ScheduledAdamWState
    from dinov3_tpu.train.ssl_meta_arch import SSLMetaArch

    mesh = build_mesh(MeshSpec(data=DP))
    set_current_mesh(mesh)
    meta = SSLMetaArch(cfg)
    batch = {k: jnp.asarray(v)
             for k, v in make_synthetic_batch(cfg, 1, seed=0).items()}
    student = jax.eval_shape(
        lambda r: meta.init_params(r, batch), jax.random.key(0)
    )["student"]
    schedules = build_schedules(cfg)
    lm, wm, isll = build_multiplier_trees(
        student,
        layerwise_decay=cfg.optim.layerwise_decay,
        patch_embed_lr_mult=cfg.optim.patch_embed_lr_mult,
        dino_head_wd_multiplier=cfg.optim.dino_head_wd_multiplier,
    )
    from dinov3_tpu.configs.config import resolve_bucket_mb

    target_bytes = resolve_bucket_mb(
        cfg.optim.get("bucket_mb", "auto")) * 2 ** 20
    plan = make_bucket_plan(student, DP, is_last_layer=isll,
                            target_bytes=target_bytes)
    kw = dict(b1=cfg.optim.adamw_beta1, b2=cfg.optim.adamw_beta2,
              clip_grad=cfg.optim.clip_grad, ema=True)

    rep = NamedSharding(mesh, P())
    axes = tuple(a for a in UPDATE_SHARD_AXES if a in mesh.shape)
    stacks = NamedSharding(mesh, P(axes))
    gstack_abs = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct((DP,) + l.shape, l.dtype), student)
    momentum = jnp.float32(0.999)
    rep_tree = jax.tree.map(lambda _: rep, student)
    stack_tree = jax.tree.map(lambda _: stacks, gstack_abs)

    def opt_sharding(opt):
        return ScheduledAdamWState(
            rep, optax.ScaleByAdamState(
                rep,
                jax.tree.map(lambda _: stacks, opt.adam.mu),
                jax.tree.map(lambda _: stacks, opt.adam.nu)))

    def opt_state_of(zeros_fn):
        return jax.eval_shape(
            lambda: ScheduledAdamWState(
                jnp.zeros((), jnp.int32),
                optax.ScaleByAdamState(
                    jnp.zeros((), jnp.int32),
                    nn.meta.unbox(zeros_fn()),
                    nn.meta.unbox(zeros_fn()))))

    fused = make_fused_update(schedules, lm, wm, isll, **kw)
    perleaf = make_sharded_update_schedule(schedules, lm, wm, isll, mesh,
                                           **kw)
    bucketed = make_bucketed_update_schedule(schedules, lm, wm, isll, mesh,
                                             plan, **kw)

    def repl_arm(gs, p, t, s, m):
        # the replicated arm's grad sync: per-replica partials summed
        # over the stacked (data-sharded) axis = the implicit all-reduce
        g = jax.tree.map(lambda x: jnp.sum(x, 0), gs)
        return fused(g, p, t, s, m)[:3]

    def perleaf_arm(gs, p, t, s, m):
        return perleaf(gs, p, t, s, m)[:3]

    def bucketed_arm(gs, p, t, s, m):
        return bucketed(gs, p, t, s, m)[:3]

    opt_rep = opt_state_of(lambda: jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), student))
    opt_rep_sh = ScheduledAdamWState(
        rep, optax.ScaleByAdamState(rep, rep_tree, rep_tree))
    opt_pl = opt_state_of(lambda: sharded_adam_zeros(student, DP))
    opt_bk = opt_state_of(lambda: bucketed_adam_zeros(plan))

    arms = {
        "replicated": (repl_arm, opt_rep, opt_rep_sh),
        "flat": (perleaf_arm, opt_pl, opt_sharding(opt_pl)),
        "bucketed": (bucketed_arm, opt_bk, opt_sharding(opt_bk)),
    }
    out = {}
    gstack = _materialize(gstack_abs, stack_tree)
    for name, (fn, opt_abs, opt_sh) in arms.items():
        if only is not None and name not in only:
            continue
        _log(f"compiling {name} update-phase arm (ViT-L dp={DP})...")
        with mesh:
            compiled = jax.jit(
                fn,
                in_shardings=(stack_tree, rep_tree, rep_tree, opt_sh, rep),
                out_shardings=(rep_tree, rep_tree, opt_sh),
                donate_argnums=(1, 2, 3),
            ).lower(gstack_abs, student, student, opt_abs,
                    jax.ShapeDtypeStruct((), jnp.float32)).compile()
        state = {
            "p": _materialize(student, rep_tree),
            "t": _materialize(student, rep_tree),
            "o": _materialize(opt_abs, opt_sh),
        }

        def run_step(state=state, compiled=compiled):
            p, t, o = compiled(gstack, state["p"], state["t"], state["o"],
                               momentum)
            jax.block_until_ready(p)
            state.update(p=p, t=t, o=o)

        summary = _traced_summary(run_step, compiled, f"update/{name}")
        out[name] = {
            "program": f"ViT-L dp={DP} update-phase twin, executed "
                       f"({TRACED_STEPS} fetch-synced traced steps)",
            "anatomy": summary,
        }
        del state, compiled
    del gstack
    return out


def stream_twin(cfg, which: str) -> dict:
    """Executed weight-stream twin at truncated ViT-L block geometry:
    ``jax.grad`` of the zero3 double-buffered scan (zero3 arm) or of the
    bucket-sharded scan (bucketed arm's overlap program)."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dinov3_tpu.models import build_backbone
    from dinov3_tpu.models.streaming import (
        bucketed_stream_scan,
        cast_stream_leaves,
        make_block_apply,
        pack_stream_buckets,
        streamed_block_scan,
    )
    from dinov3_tpu.ops.block import SelfAttentionBlock
    from dinov3_tpu.parallel.context import set_current_mesh
    from dinov3_tpu.parallel.mesh import MeshSpec, build_mesh
    from dinov3_tpu.parallel.sharding import UPDATE_SHARD_AXES, zero3_leaf_spec

    from dinov3_tpu.configs.config import (
        resolve_staging_order,
        resolve_stream_prefetch,
    )

    mesh = build_mesh(MeshSpec(data=DP))
    set_current_mesh(mesh)
    model = build_backbone(cfg)
    kwargs = model._block_kwargs()
    kwargs["drop_path_rate"] = 0.0
    L, D, N = TWIN_BLOCKS, model.embed_dim, TWIN_TOKENS
    depth = resolve_stream_prefetch(cfg.optim.get("stream_prefetch", "auto"))
    order = resolve_staging_order(cfg.optim.get("staging_order", "auto"))

    block = SelfAttentionBlock(**kwargs)
    one_block = nn.meta.unbox(jax.eval_shape(
        lambda r: block.init(r, jnp.zeros((1, N, D), jnp.bfloat16)),
        jax.random.key(0))["params"])
    stack_abs = cast_stream_leaves(jax.tree.map(
        lambda p: jax.ShapeDtypeStruct((L,) + tuple(p.shape), p.dtype),
        one_block), jnp.bfloat16)
    x_abs = jax.ShapeDtypeStruct((2 * DP, N, D), jnp.bfloat16)
    axes = tuple(a for a in UPDATE_SHARD_AXES if a in mesh.shape)
    x_sh = NamedSharding(mesh, P("data"))

    if which == "zero3":
        apply_fn = make_block_apply(kwargs, rope=None)

        def loss(stack_params, x):
            y = streamed_block_scan(apply_fn, stack_params, x, L, mesh,
                                    prefetch=depth)
            return jnp.sum(y.astype(jnp.float32))

        def stack_sharding(p):
            spec = zero3_leaf_spec(p.shape, ("layers",) + (None,) *
                                   (len(p.shape) - 1), mesh)
            return NamedSharding(mesh, spec if spec is not None else P())

        args_abs = (stack_abs, x_abs)
        in_sh = (jax.tree.map(stack_sharding, stack_abs), x_sh)
    else:  # bucketed overlap twin
        shards_abs = jax.eval_shape(
            lambda s: pack_stream_buckets(s, N_BUCKETS, DP), stack_abs)

        def loss(bucket_shards, x):
            y = bucketed_stream_scan(bucket_shards, x, mesh=mesh,
                                     prefetch=depth, staging_order=order)
            return jnp.sum(y.astype(jnp.float32))

        args_abs = (shards_abs, x_abs)
        # x rides data-sharded (unlike the census-only twin in
        # cost_buckets.py, this one EXECUTES, so x must match).
        in_sh = (NamedSharding(mesh, P(None, axes)), x_sh)

    _log(f"compiling executed {which} stream twin "
         f"(L={L}, N={N}, D={D})...")
    with mesh:
        compiled = jax.jit(jax.grad(loss), in_shardings=in_sh).lower(
            *args_abs).compile()
    args = (_materialize(args_abs[0], in_sh[0]),
            _materialize(x_abs, in_sh[1]))

    def run_step():
        import jax as _jax

        _jax.block_until_ready(compiled(*args))

    summary = _traced_summary(run_step, compiled, f"stream/{which}")
    return {
        "program": f"executed grad of the {which} stream twin "
                   f"(L={L} blocks, N={N} tokens, D={D} — ViT-L width, "
                   f"truncated depth for the single-core budget)",
        "anatomy": summary,
    }


def tiny_dryrun(steps: int = 8, window=(4, 6)) -> dict:
    """End-to-end wiring proof through the REAL trainer: vit_test dp=8,
    --profile-steps window -> the train loop's own emit_step_anatomy
    writes anatomy.json and the "anatomy" span; the fleet report reads
    the run's span stream."""
    from dinov3_tpu.telemetry import fleet_report
    from dinov3_tpu.train.train import main as train_main

    out_dir = tempfile.mkdtemp(prefix="anatomy_dryrun_", dir="/tmp")
    t0 = time.perf_counter()
    train_main([
        "--output-dir", out_dir, "--no-resume",
        "--max-iterations", str(steps),
        "--profile-steps", f"{window[0]},{window[1]}",
    ] + TINY + [f"train.OFFICIAL_EPOCH_LENGTH={steps}"])
    _log(f"dryrun: {steps} steps in {time.perf_counter() - t0:.1f}s")

    ledger_path = os.path.join(out_dir, "trace", "anatomy.json")
    assert os.path.exists(ledger_path), (
        "train-loop anatomy wiring did not write anatomy.json")
    with open(ledger_path) as f:
        ledger = json.load(f)
    assert ledger["schema"] == "anatomy/v1", ledger["schema"]
    assert ledger["n_steps"] == window[1] - window[0] + 1, ledger["n_steps"]
    assert ledger["hlo_joined"] is True
    assert ledger["unattributed_collective_ms"] == 0.0, (
        ledger["unattributed_collective_ms"])

    spans_path = os.path.join(out_dir, "telemetry", "spans.jsonl")
    anatomy_spans = []
    with open(spans_path) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("name") == "anatomy":
                anatomy_spans.append(rec)
    assert len(anatomy_spans) == 1, (
        f"expected exactly one anatomy span, got {len(anatomy_spans)}")
    summary = anatomy_spans[0]["summary"]

    fleet = fleet_report(out_dir, anatomy=summary)
    assert fleet["n_hosts"] == 1 and "rank0" in fleet["hosts"]
    assert fleet["hosts"]["rank0"]["straggler_z"] == 0.0  # single host
    assert fleet["verdict"] in ("input-bound", "comm-bound",
                                "compute-bound")
    shutil.rmtree(out_dir, ignore_errors=True)
    return {
        "program": f"vit_test dp={DP} real do_train, --profile-steps "
                   f"{window[0]},{window[1]} (the train-loop wiring path)",
        "anatomy": summary,
        "fleet": fleet,
    }


def main():
    from dinov3_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()
    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")
    from dinov3_tpu.telemetry.anatomy import round_floats

    dryrun = tiny_dryrun()
    if SMOKE:
        print(json.dumps(round_floats({
            "smoke": "ok",
            "verdict": dryrun["fleet"]["verdict"],
            "n_steps": dryrun["anatomy"]["n_steps"],
            "unattributed_collective_ms":
                dryrun["anatomy"]["unattributed_collective_ms"],
            "scopes": sorted(dryrun["anatomy"]["collectives"]),
        })))
        return

    bench = _bench()
    from dinov3_tpu.configs import apply_dot_overrides, get_default_config

    cfg = get_default_config()
    apply_dot_overrides(cfg, bench.build_step_overrides("vit_large", 0))

    arms = update_phase_arms(cfg)
    arms["zero3"] = stream_twin(cfg, "zero3")
    overlap = stream_twin(cfg, "bucketed")
    arms["bucketed"]["overlap_twin"] = overlap

    # ---- cross-arm acceptance pins (ISSUE 13) ----
    # flat arm: 3x the per-leaf collectives of the bucketed arm's
    # handful (the coalescing story, now in measured time)
    flat_n = sum(c["n_events"]
                 for c in arms["flat"]["anatomy"]["collectives"].values())
    bk_n = sum(c["n_events"]
               for c in arms["bucketed"]["anatomy"]["collectives"].values())
    assert flat_n > 3 * bk_n, (flat_n, bk_n)
    # bucketed update arm: collective time lands in the bucket_* scopes
    assert any(s.startswith("bucket")
               for s in arms["bucketed"]["anatomy"]["collectives"]), (
        arms["bucketed"]["anatomy"]["collectives"])
    # zero3 stream twin: the double-buffered gathers are
    # zero3_prefetch-scoped, and backward-time collective work exists
    z3 = arms["zero3"]["anatomy"]["collectives"]
    assert any(s.startswith("zero3") for s in z3), z3
    assert any(c["inside_backward_frac"] > 0
               for c in z3.values()), z3
    # bucketed overlap twin: measured bucket-scoped reduce-scatter time
    # INSIDE the backward interval — the dynamic twin of
    # COST_BUCKET_r13.json by_placement.in-backward-loop >= 1
    ov = overlap["anatomy"]["collectives"]
    rs_in_bwd = sum(c["ms_per_step"] * c["inside_backward_frac"]
                    for s, c in ov.items() if s.startswith("bucket"))
    assert rs_in_bwd > 0, ov
    with open("COST_BUCKET_r13.json") as f:
        r13 = json.load(f)
    static_bwd = r13["overlap_twin"]["collective_census"][
        "by_placement"].get("in-backward-loop", {"ops": 0})["ops"]
    assert static_bwd >= 1, static_bwd

    rec = round_floats({
        "what": ("step-anatomy ledger: measured per-scope device time, "
                 "exposed/overlapped collective ms, and backward-interval "
                 "placement for all four training arms"),
        "arch": "vit_large",
        "dp": DP,
        "traced_steps": TRACED_STEPS,
        "arms": arms,
        "dryrun": dryrun,
        "consistency": {
            "bucketed_rs_inside_backward_ms": rs_in_bwd,
            "cost_bucket_r13_in_backward_loop_ops": static_bwd,
            "note": ("measured bucket-scoped collective time inside the "
                     "measured backward interval > 0, consistent with "
                     "the static census placing >= 1 reduce-scatter "
                     "in-backward-loop (COST_BUCKET_r13.json)"),
        },
        "cpu_harness_caveat": (
            "XLA:CPU executes each simulated device's thunks "
            "sequentially on one worker thread: overlap fractions are "
            "structural lower bounds, exposed-comm is the conservative "
            "ceiling. Attribution, scope split, and backward-interval "
            "placement are exact. TPU overlap banks via r6_queue.sh phA."
        ),
        "source": ("executed arm twins + tiny real-trainer dryrun under "
                   "jax.profiler, parsed by telemetry/anatomy.py "
                   f"({DP} simulated CPU devices)"),
    })
    with open(OUT, "w") as f:
        json.dump(rec, f, indent=1)
    _log(f"wrote {OUT}")
    print(json.dumps({
        "arms": {k: {"step_wall_ms": v["anatomy"]["step_wall_ms"]["mean"],
                     "exposed_comm_frac": v["anatomy"]["exposed_comm_frac"],
                     "scopes": sorted(v["anatomy"]["collectives"])}
                 for k, v in arms.items()},
        "dryrun_verdict": dryrun["fleet"]["verdict"],
    }))


if __name__ == "__main__":
    main()
