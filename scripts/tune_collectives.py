"""Anatomy-driven collective auto-tuner: close the measure->tune loop.

Runs a few profiled steps per candidate through the step-anatomy plane
(telemetry/anatomy.py — the same executed-twin harness as
scripts/anatomy_report.py), searches the collective-schedule knobs, and
commits the winning plan + the FULL per-candidate measurement trail as
``TUNED_r20.json``. ``optim.bucket_mb: auto`` / ``optim.staging_order:
auto`` / ``optim.stream_prefetch: auto`` / ``kernels.ring_min_seq:
auto`` then resolve from the artifact (configs/config.py resolve_*
family) when the live fingerprint (arch, device count, update-shard
size, jax version) matches — and fall back loudly to the hand-set
oracle otherwise.

Objective (telemetry/anatomy.py ``tuning_summary``):
``objective_ms = step_wall_ms.mean + exposed_comm_ms_per_step`` —
exposed collective time counts double, so equal-wall candidates prefer
the schedule that hides more of its communication.

Search (every sweep measures the hand-set oracle too, so tuned-vs-
handset is checkable per arm from the same trail — the
``scripts/perf_gate.py --tuned-vs-handset`` gate):

- ``bucket_mb`` in {32, 64, 128, 256} MiB over the executed ViT-L
  dp=8 bucketed update-phase arm (make_bucket_plan granularity);
- ``staging_order`` over all four "<ag>_<rs>" tier orders of the
  executed unified staged-gather twin (2x4 data x fsdp mesh,
  make_zero3_gather_schedule — the grad RS rides in the transpose);
- ``stream_prefetch`` in {0, 1, 2} over the executed zero3 weight-
  stream twin (jax.grad of streamed_block_scan);
- ``ring_min_seq``: ring-vs-dense attention measured ONCE per
  workload token count (dense on dp=8, ring on dp=4 x seq=2 — same
  device budget, 1 row/device), then every candidate floor's
  objective derived deterministically from the committed table
  (tuning/search.py derive_ring_trail).

During measurement every tuned knob is HAND-SET explicitly — the
tuner never reads the artifact it is writing.

CPU-harness honesty (docs/OBSERVABILITY.md): XLA:CPU runs each
simulated device's thunks sequentially, so measured overlap is a
structural lower bound and exposed-comm a conservative ceiling — the
committed plan optimizes that conservative objective; on-chip
re-derivation is armed as scripts/r6_queue.sh phC_tune_collectives.

Usage:
  JAX_PLATFORMS=cpu python scripts/tune_collectives.py [out]
  ... --smoke    tiny-arch 2-candidate sweeps; asserts convergence,
                 artifact schema, and resolver round-trip (CI tier-1)
  ... --census   knob census only (tuning/census.py): rc=1 on any
                 untracked optim.*/kernels.* magic number
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DP = 8
os.environ.setdefault("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += f" --xla_force_host_platform_device_count={DP}"

SMOKE = "--smoke" in sys.argv
CENSUS = "--census" in sys.argv
_pos = [a for a in sys.argv[1:] if not a.startswith("--")]
OUT = _pos[0] if _pos else (None if (SMOKE or CENSUS) else "TUNED_r20.json")

# ring workload table: the token counts whose ring-vs-dense cost is
# measured (the candidate floors then partition them); ViT-L head
# geometry (16 heads x 64) — 256 ~ a 224px global crop's patch count,
# 1024 ~ a 448-512px high-res pass
RING_WORKLOADS = (256, 1024)
RING_HEADS, RING_HEAD_DIM = 16, 64

# measurement-time hand-set knobs (== configs/config.py
# TUNED_FALLBACKS): the tuner must never read the artifact it writes
HANDSET_OVR = [
    "optim.bucket_mb=128", "optim.staging_order=inter_intra",
    "optim.stream_prefetch=1", "kernels.ring_min_seq=1024",
]
MESH_OVR = ["parallel.data=2", "parallel.fsdp=4"]


def _log(msg):
    print(f"[tune_collectives] {msg}", file=sys.stderr, flush=True)


_SCRIPT_CACHE: dict = {}


def _load_script(name):
    if name in _SCRIPT_CACHE:
        return _SCRIPT_CACHE[name]
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        name, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           f"{name}.py")
        if name != "bench" else
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    _SCRIPT_CACHE[name] = mod
    return mod


def _slim(summary: dict, tuning: dict) -> dict:
    """The per-arm committed measurement: enough of the anatomy
    summary for the noise-calibrated perf gate (step_wall_ms stats,
    n_steps, exposed fraction) + the tuner's objective decomposition."""
    return {
        "step_wall_ms": summary["step_wall_ms"],
        "n_steps": summary["n_steps"],
        "exposed_comm_frac": summary["exposed_comm_frac"],
        "exposed_comm_ms_per_step": summary["exposed_comm_ms_per_step"],
        "objective_ms": tuning["objective_ms"],
        "top_exposed_scopes": tuning["top_exposed_scopes"],
    }


def _with_overrides(base_overrides: list, extra: list):
    from dinov3_tpu.configs import apply_dot_overrides, get_default_config

    cfg = get_default_config()
    apply_dot_overrides(cfg, base_overrides + HANDSET_OVR + extra)
    return cfg


def unified_gather_summary(cfg, mesh, order: str) -> dict:
    """Executed staged-bucket gather twin at one staging order: the
    grad of a sin-sum consume over ``make_zero3_gather_schedule``
    (bucketed) on the 2x4 data x fsdp mesh — forward staged AGs and
    their transposed staged grad RS inside the measured program (the
    executed twin of scripts/cost_unified.py gather_phase_twins)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.parallel.context import set_current_mesh
    from dinov3_tpu.parallel.sharding import zero3_leaf_spec
    from dinov3_tpu.train.fused_update import (
        make_zero3_bucket_plan,
        make_zero3_gather_schedule,
    )
    from dinov3_tpu.train.ssl_meta_arch import SSLMetaArch

    ar = _load_script("anatomy_report")
    set_current_mesh(mesh)
    meta = SSLMetaArch(cfg)
    batch = {k: jnp.asarray(v)
             for k, v in make_synthetic_batch(cfg, 1, seed=0).items()}
    student = jax.eval_shape(
        lambda r: meta.init_params(r, batch), jax.random.key(0)
    )["student"]
    subtree = _load_script("cost_unified")._prune_streamed(student)
    plan = make_zero3_bucket_plan(
        subtree, mesh, target_bytes=meta.zero3_bucket_bytes)

    def shardings(tree):
        def leaf(l):
            spec = zero3_leaf_spec(l.shape, (None,) * l.ndim, mesh)
            return NamedSharding(mesh, spec if spec is not None else P())
        return jax.tree.map(leaf, tree)

    in_sh = shardings(subtree)
    g = make_zero3_gather_schedule(plan, mesh, bucketed=True,
                                   staging_order=order)

    def loss(tree):
        full = g(tree)
        # nonlinear consume: a plain sum reassociates into
        # local-sum + all-reduce and erases the gathers being tuned
        return sum(jnp.sum(jnp.sin(l.astype(jnp.float32)))
                   for l in jax.tree.leaves(full))

    _log(f"compiling unified gather twin (staging_order={order})...")
    with mesh:
        compiled = jax.jit(
            jax.grad(loss), in_shardings=(in_sh,)).lower(subtree).compile()
    args = ar._materialize(subtree, in_sh)

    def run_step():
        jax.block_until_ready(compiled(args))

    return ar._traced_summary(run_step, compiled, f"unified/{order}")


def ring_workload_row(tokens: int) -> dict:
    """One workload row of the ring table: executed fwd+bwd attention
    at ViT-L head geometry — dense on the dp=8 mesh vs ring on the
    dp=4 x seq=2 mesh (same 8-device budget, 1 row per device; odd-N
    padding and the seq split happen INSIDE ring_attention, exactly
    like the train step hands it activations)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dinov3_tpu.ops.attention import xla_attention
    from dinov3_tpu.parallel.context import set_current_mesh
    from dinov3_tpu.parallel.mesh import MeshSpec, build_mesh
    from dinov3_tpu.parallel.ring_attention import ring_attention
    from dinov3_tpu.telemetry import tuning_summary

    ar = _load_script("anatomy_report")
    h, d = RING_HEADS, RING_HEAD_DIM
    row = {"tokens": tokens}
    for arm, mesh, B, fn in (
        ("dense", build_mesh(MeshSpec(data=DP)), DP,
         lambda q, k, v: xla_attention(q, k, v)),
        ("ring", build_mesh(MeshSpec(data=DP // 2, seq=2)), DP // 2,
         None),
    ):
        set_current_mesh(mesh)
        if fn is None:
            def fn(q, k, v, m=mesh):
                return ring_attention(q, k, v, m)
        sh = NamedSharding(mesh, P(("dcn_data", "data", "fsdp"),
                                   None, None, None))
        shapes = [jax.ShapeDtypeStruct((B, tokens, h, d), jnp.float32)] * 3
        _log(f"compiling ring workload {arm} @ N={tokens}...")
        with mesh:
            compiled = jax.jit(
                jax.grad(lambda q, k, v: jnp.sum(fn(q, k, v)),
                         argnums=(0, 1, 2)),
                in_shardings=(sh, sh, sh),
            ).lower(*shapes).compile()
        args = [ar._materialize(s, sh) for s in shapes]

        def run_step():
            jax.block_until_ready(compiled(*args))

        if arm == "ring":
            summary = ar._traced_summary(
                run_step, compiled, f"ring/N{tokens}")
        else:
            # the dense arm has NO collectives (batch-parallel only);
            # trace without the collective-presence assert
            import shutil
            import tempfile
            import time

            from dinov3_tpu.telemetry import anatomy_ledger, ledger_summary
            from dinov3_tpu.telemetry.trace import (
                find_trace_file,
                load_trace,
            )

            run_step()
            tdir = tempfile.mkdtemp(prefix=f"tune_dense_{tokens}_",
                                    dir="/tmp")
            t0 = time.perf_counter()
            jax.profiler.start_trace(tdir)
            try:
                for _ in range(ar.TRACED_STEPS):
                    run_step()
            finally:
                jax.profiler.stop_trace()
            _log(f"dense/N{tokens}: traced {ar.TRACED_STEPS} steps in "
                 f"{time.perf_counter() - t0:.1f}s")
            ledger = anatomy_ledger(
                load_trace(find_trace_file(tdir)),
                hlo_text=compiled.as_text(), n_steps=ar.TRACED_STEPS)
            summary = ledger_summary(ledger)
            shutil.rmtree(tdir, ignore_errors=True)
            assert summary["hlo_joined"]
            assert summary["unattributed_collective_ms"] == 0.0
        tuning = tuning_summary(summary)
        row[arm] = _slim(summary, tuning)
        row[f"{arm}_objective_ms"] = tuning["objective_ms"]
    return row


def measure_bucket_mb(vitl_overrides, mb: int) -> dict:
    ar = _load_script("anatomy_report")
    cfg = _with_overrides(vitl_overrides, [f"optim.bucket_mb={mb}"])
    out = ar.update_phase_arms(cfg, only=("bucketed",))
    return out["bucketed"]["anatomy"]


def measure_stream_prefetch(vitl_overrides, depth: int) -> dict:
    ar = _load_script("anatomy_report")
    cfg = _with_overrides(vitl_overrides,
                          [f"optim.stream_prefetch={depth}"])
    return ar.stream_twin(cfg, "zero3")["anatomy"]


def run_census() -> int:
    from dinov3_tpu.telemetry.anatomy import round_floats
    from dinov3_tpu.tuning import knob_census

    census = knob_census()
    print(json.dumps(round_floats(census), indent=1))
    if not census["ok"]:
        _log(f"census FAILED: unregistered={census['unregistered']} "
             f"stale={census['stale_registry']}")
        return 1
    _log(f"census ok: {census['n_knobs']} knobs accounted for "
         f"({ {k: len(v) for k, v in census['by_kind'].items()} })")
    return 0


def assemble_plan(fingerprint, knob_trails, arms, search_note) -> dict:
    """Round the trails, pick winners from the ROUNDED floats (so
    artifact readers re-derive identical choices), validate, return."""
    from dinov3_tpu.telemetry.anatomy import round_floats
    from dinov3_tpu.tuning import TUNED_SCHEMA, knob_entry, validate_plan

    knobs = {}
    for name, (trail, program, unit, extra) in knob_trails.items():
        knobs[name] = knob_entry(round_floats(trail), name, program,
                                 unit=unit, extra=round_floats(extra))
    doc = {
        "schema": TUNED_SCHEMA,
        "generated_by": "scripts/tune_collectives.py",
        "what": ("measured collective-schedule plan: anatomy-ledger "
                 "objective per candidate, winner re-derivable from "
                 "the committed trail (tuning/plan.py select_best)"),
        "objective": ("objective_ms = step_wall_ms.mean + "
                      "exposed_comm_ms_per_step "
                      "(telemetry/anatomy.py tuning_summary)"),
        "fingerprint": fingerprint,
        "search": search_note,
        "knobs": knobs,
        "arms": round_floats(arms),
        "cpu_harness_caveat": (
            "XLA:CPU executes each simulated device's thunks "
            "sequentially: overlap fractions are structural lower "
            "bounds, exposed-comm a conservative ceiling — the plan "
            "optimizes that conservative objective. Attribution and "
            "scope split are exact. On-chip re-derivation: "
            "scripts/r6_queue.sh phT2."),
    }
    return validate_plan(doc)


def smoke() -> None:
    """CI-sized tuner proof on the tiny arch: 2-candidate sweeps,
    schema + convergence + resolver round-trip asserts, artifact to a
    temp path (never the committed one)."""
    import tempfile
    import warnings

    from dinov3_tpu.configs.config import (
        TUNED_FALLBACKS,
        live_tuned_fingerprint,
        resolve_bucket_mb,
        resolve_stream_prefetch,
    )
    from dinov3_tpu.telemetry import tuning_summary
    from dinov3_tpu.tuning import select_best, sweep_knob, trail_row

    ar = _load_script("anatomy_report")
    tiny = list(ar.TINY)

    bucket_cands = (32, 128)
    pf_cands = (0, 1)
    bucket_sums = {}

    def meas_bucket(mb):
        s = measure_bucket_mb(tiny, mb)
        bucket_sums[mb] = s
        return tuning_summary(s)

    pf_sums = {}

    def meas_pf(depth):
        s = measure_stream_prefetch(tiny, depth)
        pf_sums[depth] = s
        return tuning_summary(s)

    bucket_trail = sweep_knob("bucket_mb", bucket_cands, meas_bucket,
                              log=_log)
    pf_trail = sweep_knob("stream_prefetch", pf_cands, meas_pf, log=_log)

    cfg = _with_overrides(tiny, [])
    fp = live_tuned_fingerprint(cfg)
    doc = assemble_plan(
        fp,
        {
            "bucket_mb": (bucket_trail,
                          "vit_test dp=8 bucketed update-phase arm",
                          "MiB", {}),
            "stream_prefetch": (pf_trail,
                                "vit_test zero3 stream twin", None, {}),
        },
        {
            "bucketed": {
                "handset": {"knobs": {"bucket_mb": 128},
                            "anatomy": _slim(
                                bucket_sums[128],
                                tuning_summary(bucket_sums[128]))},
                "tuned": {"knobs": {
                    "bucket_mb": select_best(bucket_trail)},
                    "anatomy": _slim(
                        bucket_sums[select_best(bucket_trail)],
                        tuning_summary(
                            bucket_sums[select_best(bucket_trail)]))},
            },
        },
        {"mode": "smoke", "traced_steps": ar.TRACED_STEPS,
         "candidates": {"bucket_mb": list(bucket_cands),
                        "stream_prefetch": list(pf_cands)}},
    )
    # ---- convergence: the winner is a measured candidate and is
    # re-derivable from the committed (rounded) trail ----
    chosen_mb = doc["knobs"]["bucket_mb"]["chosen"]
    assert chosen_mb in bucket_cands, chosen_mb
    assert chosen_mb == select_best(doc["knobs"]["bucket_mb"]["trail"])
    chosen_pf = doc["knobs"]["stream_prefetch"]["chosen"]
    assert chosen_pf in pf_cands, chosen_pf

    # ---- artifact schema + resolver round-trip ----
    tmp = os.path.join(tempfile.mkdtemp(prefix="tune_smoke_", dir="/tmp"),
                       "TUNED_smoke.json")
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    r1 = resolve_bucket_mb("auto", artifact=tmp, live=fp)
    r2 = resolve_bucket_mb("auto", artifact=tmp, live=fp)
    assert r1 == r2 == chosen_mb, (r1, r2, chosen_mb)
    assert resolve_stream_prefetch(
        "auto", artifact=tmp, live=fp) == chosen_pf
    # stale fingerprint -> loud hand-set fallback
    stale_live = dict(fp, arch="vit_large")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        fb = resolve_bucket_mb("auto", artifact=tmp, live=stale_live)
    assert fb == TUNED_FALLBACKS["bucket_mb"], fb
    assert any("tuned for a different setup" in str(w.message)
               for w in caught), [str(w.message) for w in caught]
    # explicit value stays the oracle
    assert resolve_bucket_mb(64, artifact=tmp, live=fp) == 64

    out = OUT or tmp
    if OUT:
        with open(OUT, "w") as f:
            json.dump(doc, f, indent=1)
    print(json.dumps({
        "smoke": "ok",
        "chosen": {"bucket_mb": chosen_mb, "stream_prefetch": chosen_pf},
        "resolver_round_trip": "bitwise",
        "stale_fallback": fb,
        "artifact": out,
    }))
    _log("smoke OK: convergence + schema + resolver round-trip")


def full() -> None:
    from dinov3_tpu.configs.config import live_tuned_fingerprint
    from dinov3_tpu.parallel.context import set_current_mesh
    from dinov3_tpu.parallel.mesh import MeshSpec, build_mesh
    from dinov3_tpu.telemetry import tuning_summary
    from dinov3_tpu.tuning import (
        BUCKET_MB_CANDIDATES,
        RING_MIN_SEQ_CANDIDATES,
        STREAM_PREFETCH_CANDIDATES,
        derive_ring_trail,
        select_best,
        staging_order_candidates,
        sweep_knob,
    )

    ar = _load_script("anatomy_report")
    bench = _load_script("bench")
    vitl = bench.build_step_overrides("vit_large", 0)
    cfg = _with_overrides(vitl, [])
    fp = live_tuned_fingerprint(cfg)
    _log(f"fingerprint: {fp}")

    # ---- plan-invariant arms (measured once; the schedule knobs do
    # not enter their programs) ----
    base_arms = ar.update_phase_arms(cfg, only=("replicated", "flat"))

    # ---- sweeps (each includes its hand-set oracle) ----
    bucket_sums = {}

    def meas_bucket(mb):
        s = measure_bucket_mb(vitl, mb)
        bucket_sums[mb] = s
        return tuning_summary(s)

    pf_sums = {}

    def meas_pf(depth):
        s = measure_stream_prefetch(vitl, depth)
        pf_sums[depth] = s
        return tuning_summary(s)

    bucket_trail = sweep_knob("bucket_mb", BUCKET_MB_CANDIDATES,
                              meas_bucket, log=_log)
    pf_trail = sweep_knob("stream_prefetch", STREAM_PREFETCH_CANDIDATES,
                          meas_pf, log=_log)

    mesh_u = build_mesh(MeshSpec(data=2, fsdp=4))
    st_sums = {}

    def meas_order(order):
        cfg_u = _with_overrides(vitl, MESH_OVR)
        s = unified_gather_summary(cfg_u, mesh_u, order)
        st_sums[order] = s
        return tuning_summary(s)

    st_trail = sweep_knob("staging_order", staging_order_candidates(),
                          meas_order, log=_log)
    set_current_mesh(None)

    # ---- ring workload table (measured once per N; floors derived) --
    ring_rows = [ring_workload_row(n) for n in RING_WORKLOADS]
    set_current_mesh(None)

    from dinov3_tpu.telemetry.anatomy import round_floats

    ring_rows_r = round_floats(ring_rows)
    ring_trail = derive_ring_trail(
        [{"tokens": r["tokens"],
          "ring_objective_ms": r["ring_objective_ms"],
          "dense_objective_ms": r["dense_objective_ms"]}
         for r in ring_rows_r],
        RING_MIN_SEQ_CANDIDATES)

    # ---- tuned-vs-handset arm rows, straight from the sweeps (the
    # handset candidate was measured in every sweep, so both sides of
    # the gate are real measurements of the same program family) ----
    def arm_row(sums, handset_value, chosen_value, knob):
        return {
            "handset": {"knobs": {knob: handset_value},
                        "anatomy": _slim(
                            sums[handset_value],
                            tuning_summary(sums[handset_value]))},
            "tuned": {"knobs": {knob: chosen_value},
                      "anatomy": _slim(
                          sums[chosen_value],
                          tuning_summary(sums[chosen_value]))},
            "same_program": handset_value == chosen_value,
        }

    chosen_mb = select_best(round_floats(bucket_trail))
    chosen_pf = select_best(round_floats(pf_trail))
    chosen_st = select_best(round_floats(st_trail))

    def invariant_arm(summary):
        t = tuning_summary(summary)
        return {"plan_invariant": True,
                "handset": {"knobs": {}, "anatomy": _slim(summary, t)},
                "tuned": {"knobs": {}, "anatomy": _slim(summary, t)}}

    arms = {
        "replicated": invariant_arm(base_arms["replicated"]["anatomy"]),
        "flat": invariant_arm(base_arms["flat"]["anatomy"]),
        "bucketed": arm_row(bucket_sums, 128, chosen_mb, "bucket_mb"),
        "zero3": arm_row(pf_sums, 1, chosen_pf, "stream_prefetch"),
        "unified": arm_row(st_sums, "inter_intra", chosen_st,
                           "staging_order"),
    }

    doc = assemble_plan(
        fp,
        {
            "bucket_mb": (
                bucket_trail,
                f"ViT-L dp={DP} bucketed update-phase arm "
                f"(make_bucket_plan target, executed "
                f"{ar.TRACED_STEPS} traced steps per candidate)",
                "MiB", {}),
            "stream_prefetch": (
                pf_trail,
                "ViT-L zero3 weight-stream twin (jax.grad of "
                "streamed_block_scan at lookahead depth d)",
                None, {}),
            "staging_order": (
                st_trail,
                "executed unified staged-gather twin, 2x4 data x fsdp "
                "mesh (make_zero3_gather_schedule '<ag>_<rs>' order)",
                None, {}),
            "ring_min_seq": (
                ring_trail,
                "derived from the measured ring-vs-dense workload "
                "table (dense dp=8 vs ring dp=4 x seq=2, ViT-L head "
                "geometry): objective(floor) = sum_w (ring if "
                "w.tokens >= floor else dense)",
                "tokens", {"workloads": ring_rows_r}),
        },
        arms,
        {"mode": "full", "traced_steps": ar.TRACED_STEPS,
         "candidates": {
             "bucket_mb": list(BUCKET_MB_CANDIDATES),
             "stream_prefetch": list(STREAM_PREFETCH_CANDIDATES),
             "staging_order": list(staging_order_candidates()),
             "ring_min_seq": list(RING_MIN_SEQ_CANDIDATES)},
         "ring_workload_tokens": list(RING_WORKLOADS)},
    )

    # ---- the acceptance property: tuned >= handset on every arm
    # under the noise-calibrated gate (scripts/perf_gate.py) ----
    pg = _load_script("perf_gate")
    gate = pg.tuned_vs_handset(doc)
    assert gate["passed"], json.dumps(gate, indent=1)

    if OUT:
        with open(OUT, "w") as f:
            json.dump(doc, f, indent=1)
        _log(f"wrote {OUT}")
    print(json.dumps({
        "chosen": {k: v["chosen"] for k, v in doc["knobs"].items()},
        "fingerprint": fp,
        "tuned_vs_handset": {"passed": gate["passed"],
                             "n_arms": gate["n_arms"]},
    }))


def main() -> int:
    if CENSUS:
        return run_census()
    from dinov3_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()
    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")
    if SMOKE:
        smoke()
    else:
        full()
    return 0


if __name__ == "__main__":
    sys.exit(main())
