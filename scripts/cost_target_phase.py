"""Bytes-accessed accounting for the teacher-target/CE ("target") phase:
materialized [*, K] teacher targets + CE reads vs the streaming
prototype-axis engine (losses/streaming.py) — plus a compiled-HLO copy
census of the full train step (donation/aliasing audit).

Methodology (PR-1 discipline, scripts/cost_update_phase.py): the
MATERIALIZED path is accounted at pass granularity — each pass is
compiled as its own XLA program and their ``cost_analysis()['bytes
accessed']`` summed:

- ``targets``: teacher logits -> materialized [*, K] probability buffers
  (softmax-center or the 3-iteration Sinkhorn), stored in
  ``compute_precision.target_dtype``;
- ``dino_ce``: student CLS logits x the materialized CLS targets ->
  both DINO losses (the logit-einsum CE);
- ``ibot_ce``: student masked-token logits x the materialized masked
  targets -> iBOT loss.

This is the granularity the r5 on-chip profile shows the TPU executing
the phase at (``PROFILE_r05.json``: 10.2% of step time in fp32
``convert_reduce``/``exponential_reduce`` passes over the [*, 65536]
buffers). The STREAMING engine is ONE program computing the same three
losses directly from the logits in a single K-tiled pass — the target
buffer never exists, so the saving is algorithmic, not a fusion
artifact: even a backend that fused the whole materialized phase into
one program would still write+read the [*, K] buffer unless it
re-derived the streaming algebra itself (the online-max rescaled
cross-term accumulation).

The copy census compiles the EXACT jitted train step (with state
donation, compile-only — the jaxlib<=0.4.36 cpu cache-staleness bug is
an execution-time bug, see utils.donation_safe_argnums) and counts HLO
``copy``/``copy-start``/``copy-done``/``dynamic-update-slice``
instructions outside fusion bodies plus any donation warnings, so
donation regressions and layout-churn copies fail CI
(tests/test_streaming_targets.py pins the ceiling).

One JSON line on stdout:

    {"arch": ..., "target_phase": {<centering>: {<target_dtype>: {...}}},
     "copy_census": {...}}

Usage: JAX_PLATFORMS=cpu python scripts/cost_target_phase.py [arch]
"""

from __future__ import annotations

import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import importlib.util

_spec = importlib.util.spec_from_file_location(
    "bench", os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def _bytes_accessed(fn, args) -> float:
    import jax

    compiled = jax.jit(fn).lower(*args).compile()
    analysis = compiled.cost_analysis()
    if isinstance(analysis, list):
        analysis = analysis[0]
    return float(analysis["bytes accessed"])


def measure_target_phase(cfg, centering: str, target_dtype) -> dict:
    """Pass-granularity bytes for materialized vs streaming, one
    centering mode and one target storage dtype."""
    import jax
    import jax.numpy as jnp

    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.losses import (
        ibot_loss_from_spec,
        ibot_patch_loss_masked,
        pair_ce_from_spec,
        pair_ce_to_loss,
        sinkhorn_knopp,
        softmax_center_teacher,
    )
    from dinov3_tpu.ops import Policy

    policy = Policy.from_cfg(cfg.compute_precision)
    comp = policy.compute_dtype
    B = int(os.environ.get("COST_BATCH", "12"))
    n_g, n_l = 2, cfg.crops.local_crops_number
    K = cfg.dino.head_n_prototypes
    K_i = cfg.ibot.head_n_prototypes
    M = make_synthetic_batch(cfg, 2, seed=0)["mask_indices"].shape[1]
    rows_m = 2 * B * M
    k_tile = int((cfg.get("loss") or {}).get("k_tile") or 8192)

    sd = jax.ShapeDtypeStruct
    cls_logits = sd((n_g * B, K), comp)
    masked_logits = sd((rows_m, K_i), comp)
    student_cat = sd((n_g + n_l, B, K), comp)
    student_masked = sd((rows_m, K_i), comp)
    center_d = sd((1, K), jnp.float32)
    center_i = sd((1, K_i), jnp.float32)
    valid = sd((rows_m,), jnp.float32)
    weights = sd((rows_m,), jnp.float32)
    temp = sd((), jnp.float32)

    def make_targets(cls_l, masked_l, v, c_d, c_i, t):
        if centering == "sinkhorn_knopp":
            q_c = sinkhorn_knopp(cls_l, t, storage_dtype=target_dtype)
            q_m = sinkhorn_knopp(masked_l, t, row_weights=v,
                                 storage_dtype=target_dtype)
        else:
            q_c = softmax_center_teacher(cls_l, c_d, t,
                                         storage_dtype=target_dtype)
            q_m = softmax_center_teacher(masked_l, c_i, t,
                                         storage_dtype=target_dtype)
            q_m = q_m * v[:, None].astype(q_m.dtype)
        return q_c, q_m

    q_c_abs, q_m_abs = jax.eval_shape(
        make_targets, cls_logits, masked_logits, valid, center_d,
        center_i, temp)

    def dino_ce(cat, q_c):
        pair = pair_ce_from_spec(
            cat, {"kind": "probs", "probs": q_c.reshape(n_g, B, K)})
        return (pair_ce_to_loss(pair[n_g:], B),
                pair_ce_to_loss(pair[:n_g], B, ignore_diagonal=True))

    def ibot_ce(sm, q_m, w):
        return ibot_patch_loss_masked(sm, q_m, w, n_images=n_g * B)

    def streaming(cat, sm, cls_l, masked_l, v, c_d, c_i, t, w):
        if centering == "sinkhorn_knopp":
            cspec = {"kind": "sinkhorn", "factors": sinkhorn_knopp(
                cls_l, t, storage_dtype=target_dtype, return_factors=True)}
            mspec = {"kind": "sinkhorn", "factors": sinkhorn_knopp(
                masked_l, t, row_weights=v, storage_dtype=target_dtype,
                return_factors=True)}
        else:
            cspec = {"kind": "softmax_center",
                     "logits": cls_l.reshape(n_g, B, K),
                     "center": c_d, "temp": t}
            mspec = {"kind": "softmax_center", "logits": masked_l,
                     "center": c_i, "temp": t}
        pair = pair_ce_from_spec(cat, cspec, k_tile=k_tile)
        ibot = ibot_loss_from_spec(sm, mspec, w, n_images=n_g * B,
                                   k_tile=k_tile)
        return (pair_ce_to_loss(pair[n_g:], B),
                pair_ce_to_loss(pair[:n_g], B, ignore_diagonal=True),
                ibot)

    passes = {
        "targets": _bytes_accessed(
            make_targets,
            (cls_logits, masked_logits, valid, center_d, center_i, temp)),
        "dino_ce": _bytes_accessed(dino_ce, (student_cat, q_c_abs)),
        "ibot_ce": _bytes_accessed(
            ibot_ce, (student_masked, q_m_abs, weights)),
    }
    bytes_streaming = _bytes_accessed(
        streaming,
        (student_cat, student_masked, cls_logits, masked_logits, valid,
         center_d, center_i, temp, weights))
    total = sum(passes.values())
    target_rows = n_g * B + rows_m
    return {
        "K": K, "rows_targets": target_rows, "k_tile": k_tile,
        "bytes_materialized_passes": passes,
        "bytes_materialized_total": total,
        "bytes_streaming": bytes_streaming,
        "reduction_pct": round(100.0 * (1.0 - bytes_streaming / total), 1),
    }


# ---------------- compiled-HLO helpers (copy census + target-buffer
# materialization check) ----------------


def non_fusion_lines(hlo_text: str):
    """Instruction lines outside fused-computation bodies — the
    allocation-relevant set for both the copy census and the [*, K]
    materialization check (shared impl: utils.hlo_non_fusion_lines)."""
    from dinov3_tpu.utils import hlo_non_fusion_lines

    return hlo_non_fusion_lines(hlo_text)


def count_materialized(hlo_text: str, dtype_str: str, last_dim: int,
                       rows: int, include_fusions: bool = False,
                       op_pattern: str | None = None) -> int:
    r"""Count instruction results of shape ``dtype[*, last_dim]`` whose
    leading dims multiply to ``rows`` — the teacher-target buffer
    signature.

    ``include_fusions=False`` counts only buffer-allocating (non-fusion-
    body) instructions. ``include_fusions=True`` scans every op,
    including fusion internals: a program in which NO op anywhere even
    produces a full [rows, K] value of the target dtype provably never
    materializes that buffer, regardless of how the backend fuses — the
    version-robust form of the streaming claim (a tiled engine's
    target-valued ops are all [rows, k_tile]-shaped).

    ``op_pattern`` restricts to specific op kinds, e.g.
    ``r"(exponential|divide)\("`` for target VALUES (softmax/sinkhorn
    probabilities). Distinguishing values matters because a backend may
    legally hoist a one-time fp32 convert of the loop-invariant LOGITS
    out of the K-tile loop (observed on XLA:CPU, which strips the
    optimization barriers guarding against it; the TPU pipeline honors
    them) — a bounded scheduling choice that the bytes-accessed
    accounting already reflects, distinct from materializing the
    targets."""
    pat = re.compile(r"=\s*" + re.escape(dtype_str) + r"\[([\d,]+)\]")
    lines = (hlo_text.splitlines() if include_fusions
             else non_fusion_lines(hlo_text))
    op_re = re.compile(op_pattern) if op_pattern else None
    n = 0
    for line in lines:
        m = pat.search(line)
        if not m:
            continue
        if op_re is not None and not op_re.search(line):
            continue
        dims = [int(d) for d in m.group(1).split(",")]
        if len(dims) >= 2 and dims[-1] == last_dim:
            lead = 1
            for d in dims[:-1]:
                lead *= d
            if lead == rows:
                n += 1
    return n


def copy_census(cfg, B: int = 4) -> dict:
    """Compile the exact jitted train step (donated state) on the host
    backend and count copy-class HLO ops + donation warnings."""
    import warnings

    import jax
    import jax.numpy as jnp

    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import (
        build_fused_update,
        build_optimizer,
        build_schedules,
    )
    from dinov3_tpu.train.ssl_meta_arch import SSLMetaArch
    from dinov3_tpu.train.train_step import TrainState, make_train_step

    meta = SSLMetaArch(cfg)
    batch = {k: jnp.asarray(v)
             for k, v in make_synthetic_batch(cfg, B, seed=0).items()}
    abstract_params = jax.eval_shape(
        lambda r: meta.init_params(r, batch), jax.random.key(0))
    schedules = build_schedules(cfg)
    optimizer = build_optimizer(cfg, abstract_params["student"], schedules)
    fused = build_fused_update(cfg, abstract_params["student"], schedules,
                               ema=not meta.distillation)
    step = make_train_step(meta, optimizer, clip_grad=cfg.optim.clip_grad,
                           fused_update=fused)
    state_abs = TrainState(
        params=abstract_params,
        opt_state=jax.eval_shape(optimizer.init, abstract_params["student"]),
        center_state=jax.eval_shape(meta.init_state),
        step=jax.ShapeDtypeStruct((), jnp.int32),
    )
    batch_abs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                 for k, v in batch.items()}
    scalars_abs = {"teacher_temp": jax.ShapeDtypeStruct((), jnp.float32),
                   "momentum": jax.ShapeDtypeStruct((), jnp.float32)}
    rng_abs = jax.eval_shape(lambda: jax.random.key(0))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        compiled = jax.jit(step, donate_argnums=(0,)).lower(
            state_abs, batch_abs, scalars_abs, rng_abs).compile()
    donation_warnings = [str(w.message) for w in caught
                         if "donat" in str(w.message).lower()]
    from dinov3_tpu.utils import hlo_copy_census

    # per-category attribution (rng / donation_async / small / large):
    # a future copy regression names its source instead of only moving
    # the total (utils.classify_copy documents the category heuristics)
    rec = hlo_copy_census(compiled.as_text())
    rec["donation_warnings"] = donation_warnings
    return rec


def main():
    from dinov3_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()
    import jax.numpy as jnp

    from dinov3_tpu.configs import apply_dot_overrides, get_default_config

    arch = sys.argv[1] if len(sys.argv) > 1 else "vit_large"
    cfg = get_default_config()
    apply_dot_overrides(cfg, bench.build_step_overrides(arch, 0))
    rec = {"arch": arch, "target_phase": {}}
    for centering in ("sinkhorn_knopp", "softmax_center"):
        rec["target_phase"][centering] = {
            "fp32": measure_target_phase(cfg, centering, None),
            "bf16": measure_target_phase(cfg, centering, jnp.bfloat16),
        }
    # the census compiles the full step: use the test arch so the CPU
    # compile stays seconds-long; the copy structure under audit
    # (donation aliasing, subset-gather copies, loss-phase copies) is
    # arch-independent at this granularity
    census_cfg = get_default_config()
    apply_dot_overrides(census_cfg, [
        "student.arch=vit_test", "student.patch_size=4",
        "crops.global_crops_size=16", "crops.local_crops_size=8",
        "crops.local_crops_number=2",
        "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
        "dino.head_bottleneck_dim=16",
        "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
        "ibot.head_bottleneck_dim=16",
        "optim.scaling_rule=none",
    ])
    rec["copy_census"] = {
        "arch": "vit_test",
        "streaming_on": copy_census(census_cfg),
    }
    apply_dot_overrides(census_cfg, ["loss.streaming_targets=false"])
    rec["copy_census"]["streaming_off"] = copy_census(census_cfg)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
