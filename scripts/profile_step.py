"""Capture a jax.profiler trace of the ViT-L fused train step and print a
per-op-category device-time breakdown (reads the trace.json.gz xplane dump).

Usage: python scripts/profile_step.py [outdir]
Env: BENCH_ARCH/BENCH_BATCH/BENCH_RES as in bench.py.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def categorize(name: str) -> str:
    n = name.lower()
    if "fusion" not in n and ("dot" in n or "conv" in n):
        return "matmul/conv"
    for key in ("all-gather", "all-reduce", "reduce-scatter", "collective",
                "psum", "permute"):
        if key in n:
            return "collective"
    if "softmax" in n or "exp" in n:
        return "softmax/exp"
    if "norm" in n or "rsqrt" in n or "reduce" in n:
        return "norm/reduce"
    if "copy" in n or "transpose" in n or "reshape" in n or "bitcast" in n:
        return "copy/layout"
    if "fusion" in n:
        return "fusion/elementwise"
    return "other"


def main():
    import jax
    import jax.numpy as jnp

    from dinov3_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()

    jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")

    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup, put_batch

    outdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/prof_r2"
    arch = os.environ.get("BENCH_ARCH", "vit_large")
    per_chip = int(os.environ.get("BENCH_BATCH", "12"))  # bench.py default
    res = int(os.environ.get("BENCH_RES", "0"))

    n = jax.device_count()
    cfg = get_default_config()
    apply_dot_overrides(cfg, [
        f"student.arch={arch}",
        "student.n_storage_tokens=4",
        "student.drop_path_rate=0.3",
        "optim.scaling_rule=none",
        "parallel.data=-1",
        "compute_precision.param_dtype=bf16",
    ] + ([f"crops.global_crops_size={res}",
          f"crops.local_crops_size={max(96, res // 4)}"] if res else []))
    B = per_chip * n
    batch_np = make_synthetic_batch(cfg, B, seed=0)
    batch = {k: jnp.asarray(v) for k, v in batch_np.items()}

    t0 = time.perf_counter()
    setup = build_train_setup(cfg, batch)
    dbatch = put_batch(batch, setup.batch_shardings)
    rng = jax.random.key(0)
    state = setup.state
    scalars = setup.scalars(0)
    print(f"setup {time.perf_counter() - t0:.1f}s", flush=True)

    t0 = time.perf_counter()
    for _ in range(3):
        state, metrics = setup.step_fn(state, dbatch, scalars, rng)
    float(metrics["total_loss"])
    print(f"warmup(3) {time.perf_counter() - t0:.1f}s", flush=True)

    steps = 6
    t0 = time.perf_counter()
    jax.profiler.start_trace(outdir)
    for _ in range(steps):
        state, metrics = setup.step_fn(state, dbatch, scalars, rng)
    float(metrics["total_loss"])
    jax.profiler.stop_trace()
    dt = (time.perf_counter() - t0) / steps
    print(f"step {dt * 1e3:.1f} ms  ->  {B / dt / n:.1f} img/s/chip", flush=True)

    # parse newest trace.json.gz
    paths = sorted(glob.glob(os.path.join(
        outdir, "**", "*.trace.json.gz"), recursive=True), key=os.path.getmtime)
    if not paths:
        print("no trace.json.gz found", flush=True)
        return
    with gzip.open(paths[-1], "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    # find TPU device pids (thread names like "XLA Op" under device pids)
    by_cat = defaultdict(float)
    by_name = defaultdict(float)
    total = 0.0
    pid_names = {e.get("pid"): e.get("args", {}).get("name", "")
                 for e in events if e.get("name") == "process_name"}
    dev_pids = {p for p, nm in pid_names.items()
                if nm and ("TPU" in nm or "/device:" in nm)}
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in dev_pids:
            continue
        name = e.get("name", "")
        dur = e.get("dur", 0) / 1e3  # us -> ms
        if not name or dur <= 0:
            continue
        by_cat[categorize(name)] += dur
        by_name[name] += dur
        total += dur
    per_step = total / steps
    print(f"\ndevice total {total:.1f} ms over {steps} steps "
          f"({per_step:.1f} ms/step)")
    print("\n== by category (ms/step) ==")
    for k, v in sorted(by_cat.items(), key=lambda kv: -kv[1]):
        print(f"  {k:24s} {v / steps:8.2f}  ({100 * v / total:5.1f}%)")
    print("\n== top 30 ops (ms/step) ==")
    for k, v in sorted(by_name.items(), key=lambda kv: -kv[1])[:30]:
        print(f"  {v / steps:8.3f}  {k[:120]}")


if __name__ == "__main__":
    main()
