"""Capture a jax.profiler trace of the ViT-L fused train step and print /
emit the per-op-category device-time breakdown — now riding the shared
step-anatomy parser (telemetry/trace.py + telemetry/anatomy.py) instead
of the ad-hoc flat classifier this script used to carry.

The old local ``categorize()`` undercounted matmul/conv (a fusion whose
kind-name carries a dot/conv token — ``convolution_add_fusion`` — was
binned "fusion/elementwise"; PROFILE_r05.json shows 46.3 ms/step of it)
and miscounted ``convert_element_type`` as a convolution (bare ``"conv"
in name`` substring). The shared ``telemetry.anatomy.categorize`` fixes
both; the historical r05 artifact is kept as-is for provenance (its
source trace was never committed — the r17 artifact pins the parser
against the committed ``docs/profiles/PROFILE_r17_trace.json.gz``
instead, tests/test_anatomy.py re-derives it byte-exactly).

Usage:
  python scripts/profile_step.py [outdir]          # capture + parse
  python scripts/profile_step.py --from-trace P    # parse an existing
                                                   # trace file/dir only
Flags: --steps N (traced/assumed step count), --out FILE (write the
machine-readable breakdown JSON), --hlo FILE (join against a compiled
HLO text for named-scope collective attribution).
Env: BENCH_ARCH/BENCH_BATCH/BENCH_RES as in bench.py.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _arg(flag: str, default=None):
    if flag in sys.argv:
        return sys.argv[sys.argv.index(flag) + 1]
    return default


def breakdown(trace_path: str, n_steps: int | None,
              hlo_text: str | None = None) -> dict:
    """One trace file/dir -> the machine-readable breakdown record
    (shared-parser ledger summary + the by-category and top-op views
    the old flat parser printed). Deterministic from the trace alone
    when ``hlo_text`` is None — the property the committed
    PROFILE_r17.json equivalence pin relies on."""
    from dinov3_tpu.telemetry import anatomy_ledger, ledger_summary
    from dinov3_tpu.telemetry.anatomy import round_floats
    from dinov3_tpu.telemetry.trace import find_trace_file, load_trace

    path = find_trace_file(trace_path)
    if path is None:
        raise FileNotFoundError(f"no *.trace.json.gz under {trace_path!r}")
    trace = load_trace(path)
    ledger = anatomy_ledger(trace, hlo_text=hlo_text, n_steps=n_steps)
    summary = ledger_summary(ledger)
    by_name: dict = {}
    for e in trace.op_events(module=ledger["module"]):
        by_name[e.name] = by_name.get(e.name, 0.0) + e.dur / 1e3
    n = max(1, ledger["n_steps"])
    return round_floats({
        "schema": "profile/v2",
        "trace": os.path.basename(path),
        "module": ledger["module"],
        "n_steps": ledger["n_steps"],
        "n_timelines": ledger["n_timelines"],
        "n_device_ops": len(by_name),
        "device_total_ms": summary["device_busy_ms_per_step"] * n,
        "by_category_ms_per_step": dict(sorted(
            summary["device_ms_per_step"].items(), key=lambda kv: -kv[1])),
        "summary": summary,
        "top_ops": [
            {"name": k[:120], "ms_per_step": v / n}
            for k, v in sorted(by_name.items(), key=lambda kv: -kv[1])[:30]
        ],
    })


def report(rec: dict) -> None:
    total = rec["device_total_ms"]
    n = max(1, rec["n_steps"])
    print(f"\ndevice total {total:.1f} ms over {n} steps "
          f"({total / n:.1f} ms/step)  [{rec['n_timelines']} timelines]")
    print("\n== by category (ms/step) ==")
    for k, v in rec["by_category_ms_per_step"].items():
        print(f"  {k:24s} {v:8.2f}  ({100 * v * n / max(total, 1e-9):5.1f}%)")
    colls = rec["summary"].get("collectives") or {}
    if colls:
        print("\n== collectives by scope (ms/step, exposed | overlap) ==")
        for k, v in sorted(colls.items(),
                           key=lambda kv: -kv[1]["ms_per_step"]):
            print(f"  {k:24s} {v['ms_per_step']:8.2f}  "
                  f"exposed {v['exposed_ms_per_step']:7.2f}  "
                  f"overlap {v['overlap_frac']:5.1%}")
    print("\n== top 30 ops (ms/step) ==")
    for row in rec["top_ops"]:
        print(f"  {row['ms_per_step']:8.3f}  {row['name']}")


def main():
    out = _arg("--out")
    from_trace = _arg("--from-trace")
    hlo_file = _arg("--hlo")
    hlo_text = open(hlo_file).read() if hlo_file else None
    if from_trace:
        rec = breakdown(from_trace, int(_arg("--steps", "0")) or None,
                        hlo_text)
        report(rec)
        if out:
            with open(out, "w") as f:
                json.dump(rec, f, indent=1)
            print(f"\nwrote {out}")
        return

    import jax
    import jax.numpy as jnp

    from dinov3_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()

    jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")

    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup, put_batch

    pos = [a for a in sys.argv[1:] if not a.startswith("--")
           and a not in (_arg("--out"), _arg("--steps"), _arg("--hlo"))]
    outdir = pos[0] if pos else "/tmp/prof_r2"
    arch = os.environ.get("BENCH_ARCH", "vit_large")
    per_chip = int(os.environ.get("BENCH_BATCH", "12"))  # bench.py default
    res = int(os.environ.get("BENCH_RES", "0"))

    n = jax.device_count()
    cfg = get_default_config()
    apply_dot_overrides(cfg, [
        f"student.arch={arch}",
        "student.n_storage_tokens=4",
        "student.drop_path_rate=0.3",
        "optim.scaling_rule=none",
        "parallel.data=-1",
        "compute_precision.param_dtype=bf16",
    ] + ([f"crops.global_crops_size={res}",
          f"crops.local_crops_size={max(96, res // 4)}"] if res else []))
    B = per_chip * n
    batch_np = make_synthetic_batch(cfg, B, seed=0)
    batch = {k: jnp.asarray(v) for k, v in batch_np.items()}

    t0 = time.perf_counter()
    setup = build_train_setup(cfg, batch)
    dbatch = put_batch(batch, setup.batch_shardings)
    rng = jax.random.key(0)
    state = setup.state
    scalars = setup.scalars(0)
    print(f"setup {time.perf_counter() - t0:.1f}s", flush=True)

    t0 = time.perf_counter()
    for _ in range(3):
        state, metrics = setup.step_fn(state, dbatch, scalars, rng)
    float(metrics["total_loss"])
    print(f"warmup(3) {time.perf_counter() - t0:.1f}s", flush=True)

    steps = int(_arg("--steps", "6"))
    t0 = time.perf_counter()
    jax.profiler.start_trace(outdir)
    for _ in range(steps):
        state, metrics = setup.step_fn(state, dbatch, scalars, rng)
    float(metrics["total_loss"])
    jax.profiler.stop_trace()
    dt = (time.perf_counter() - t0) / steps
    print(f"step {dt * 1e3:.1f} ms  ->  {B / dt / n:.1f} img/s/chip",
          flush=True)

    if hlo_text is None:
        # join against the exact program just traced, so collective
        # time lands in named scopes (bucket_*/zero3_*/update_shard)
        try:
            hlo_text = setup.step_fn.lower(
                state, dbatch, scalars, rng).compile().as_text()
        except Exception as e:  # noqa: BLE001 - report still useful bare
            print(f"hlo join skipped: {e}", flush=True)
    rec = breakdown(outdir, steps, hlo_text)
    report(rec)
    if out:
        with open(out, "w") as f:
            json.dump(rec, f, indent=1)
        print(f"\nwrote {out}")


if __name__ == "__main__":
    main()
