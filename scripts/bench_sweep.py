"""Timed A/B sweep of train-step variants on the real chip, with losses.

Each variant runs the full fused ViT-L train step (bench.py config) for a
few steps, printing step time, img/s/chip, and the loss trajectory so
numerics changes show up alongside the speed. Variants share one process
(compile cache reused).

Usage: python scripts/bench_sweep.py [variant ...]
Variants are "name:key=val,key=val" where keys are env knobs understood
below, e.g.  base:DINOV3_FUSED_LN=0  fused:DINOV3_FUSED_LN=1
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_variant(name: str, env: dict, steps=10, warmup=3):
    import jax
    import jax.numpy as jnp

    for k, v in env.items():
        os.environ[k] = v

    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup, put_batch

    arch = os.environ.get("BENCH_ARCH", "vit_large")
    per_chip = int(os.environ.get("BENCH_BATCH", "8"))
    n = jax.device_count()
    cfg = get_default_config()
    apply_dot_overrides(cfg, [
        f"student.arch={arch}",
        "student.n_storage_tokens=4",
        "student.drop_path_rate=0.3",
        "optim.scaling_rule=none",
        "parallel.data=-1",
        "compute_precision.param_dtype=bf16",
    ] + list(env.get("_overrides", "").split()))
    B = per_chip * n
    batch_np = make_synthetic_batch(cfg, B, seed=0)
    batch = {k: jnp.asarray(v) for k, v in batch_np.items()}
    t0 = time.perf_counter()
    setup = build_train_setup(cfg, batch)
    dbatch = put_batch(batch, setup.batch_shardings)
    rng = jax.random.key(0)
    state = setup.state
    scalars = setup.scalars(0)
    print(f"[{name}] setup {time.perf_counter() - t0:.1f}s", flush=True)

    losses = []
    t0 = time.perf_counter()
    for i in range(warmup):
        state, metrics = setup.step_fn(state, dbatch, scalars, rng)
        losses.append(float(metrics["total_loss"]))
    print(f"[{name}] warmup {time.perf_counter() - t0:.1f}s", flush=True)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = setup.step_fn(state, dbatch, scalars, rng)
    losses.append(float(metrics["total_loss"]))
    dt = (time.perf_counter() - t0) / steps
    print(f"[{name}] step {dt * 1e3:.2f} ms  {B / dt / n:.2f} img/s/chip  "
          f"losses {['%.4f' % l for l in losses]}", flush=True)
    return B / dt / n


def main():
    specs = sys.argv[1:] or ["fused:DINOV3_FUSED_LN=1", "base:DINOV3_FUSED_LN=0"]
    import jax

    from dinov3_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()
    jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")
    results = {}
    for spec in specs:
        name, _, kvs = spec.partition(":")
        env = {}
        for kv in kvs.split(","):
            if kv:
                k, _, v = kv.partition("=")
                env[k] = v
        results[name] = run_variant(name, env)
    print({k: round(v, 2) for k, v in results.items()}, flush=True)


if __name__ == "__main__":
    main()
