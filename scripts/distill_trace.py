"""Distill a jax.profiler trace (trace-viewer JSON) into a committed artifact.

Reads the ``vm.trace.json.gz`` that ``scripts/profile_step.py`` leaves under
``<outdir>/plugins/profile/<ts>/`` and writes one JSON document with:

- per-step device time (XLA Modules lane),
- op-kind buckets (uniquifying suffixes stripped) with time/count/share,
- the top-N exact op instances with their HLO result shapes, so "which
  tensor is this pass over" is answerable from the artifact alone.

Usage: python scripts/distill_trace.py <trace.json.gz> [out.json]
"""

from __future__ import annotations

import collections
import gzip
import json
import re
import sys


def distill(trace_path: str, top_n: int = 40) -> dict:
    with gzip.open(trace_path) as f:
        ev = json.load(f)["traceEvents"]
    # device lanes: pid of the process named /device:TPU:0
    dev_pids = {e["pid"] for e in ev
                if e.get("ph") == "M" and e.get("name") == "process_name"
                and "/device" in e.get("args", {}).get("name", "")}
    lanes = {}
    for e in ev:
        if (e.get("ph") == "M" and e.get("name") == "thread_name"
                and e.get("pid") in dev_pids):
            lanes[(e["pid"], e["tid"])] = e["args"]["name"]
    ops = [e for e in ev if e.get("ph") == "X"
           and lanes.get((e.get("pid"), e.get("tid"))) == "XLA Ops"]
    mods = [e for e in ev if e.get("ph") == "X"
            and lanes.get((e.get("pid"), e.get("tid"))) == "XLA Modules"]

    total_us = sum(e["dur"] for e in ops)
    buckets = collections.Counter()
    counts = collections.Counter()
    exact = collections.Counter()
    meta = {}
    for e in ops:
        kind = re.sub(r"[.\d]+$", "", e["name"])
        buckets[kind] += e["dur"]
        counts[kind] += 1
        exact[e["name"]] += e["dur"]
        if e["name"] not in meta:
            ln = e.get("args", {}).get("long_name", "")
            # keep just "%name = <result shape(s)>" — enough to identify
            # the tensor without embedding the whole HLO line
            meta[e["name"]] = ln.split(" fusion(")[0].split(" custom-call(")[0][:160]

    return {
        "trace": trace_path,
        "n_device_ops": len(ops),
        "steps": [{"name": m["name"].split("(")[0], "ms": round(m["dur"] / 1e3, 2)}
                  for m in mods],
        "device_total_ms": round(total_us / 1e3, 1),
        "buckets": [
            {"kind": k, "ms": round(v / 1e3, 1),
             "share": round(v / total_us, 4), "count": counts[k]}
            for k, v in buckets.most_common()
        ],
        "top_ops": [
            {"name": k, "ms": round(v / 1e3, 2),
             "share": round(v / total_us, 4), "hlo": meta[k]}
            for k, v in exact.most_common(top_n)
        ],
    }


def main():
    trace = sys.argv[1]
    out = sys.argv[2] if len(sys.argv) > 2 else "PROFILE.json"
    doc = distill(trace)
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
    b = doc["buckets"]
    print(f"device total {doc['device_total_ms']} ms over {len(doc['steps'])} modules")
    for row in b[:12]:
        print(f"{row['ms']:9.1f} ms {100 * row['share']:5.1f}% n={row['count']:6d} {row['kind']}")
    print("->", out)


if __name__ == "__main__":
    main()
