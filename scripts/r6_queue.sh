#!/bin/bash
# Round-6 queue: armed for the next healthy tunnel window. Cheapest /
# highest-evidence first:
#   phU   fused-update-engine A/B (the 28.5% norm/reduce attack,
#         train/fused_update.py): default program (fused on) vs
#         optim.fused_update=false control, same session, both arms
#         pinned BENCH_PROBS=bf16 at the B=12 default. The committed
#         host-side accounting (scripts/cost_update_phase.py,
#         docs/PERFORMANCE.md) shows -34.3% weight-shaped bytes at pass
#         granularity; this measures what the TPU scheduler actually
#         does with each form.
#   phT2  target_dtype=bf16 A/B (re-armed from r5b with BENCH_PROBS
#         pinned on BOTH arms)
#   phS   streaming-targets A/B (the 10.2% fp32 target-pass attack,
#         losses/streaming.py): default program (loss.streaming_targets
#         auto=on) vs =false materialized-oracle control, same session,
#         both arms BENCH_PROBS=bf16 at B=12. Host-side accounting
#         (scripts/cost_target_phase.py, COST_TARGET_r07.json): -69.5%
#         target-phase bytes at pass granularity for softmax-center,
#         -15.2% for the default sinkhorn (its iterate passes remain);
#         this measures what the TPU does with each form. A second pair
#         pins train.centering=softmax_center where the streaming win
#         is the large one.
#   phR   step-wide RNG-plan engine A/B (the 14.8% copy/small-op
#         attack, rng/plan.py): default program (rng.plan auto=on) vs
#         rng.plan=false legacy fold_in control, same session, both
#         arms pinned BENCH_PROBS=bf16 at B=12 and both carrying the
#         compiled-step copy census in their records (BENCH_CENSUS=1).
#         Host-side accounting (scripts/cost_rng_copies.py,
#         COST_RNG_r08.json): -72.2% copy-class HLO ops in the compiled
#         step (518 -> 144; the removed ops are the u32 RNG-key
#         plumbing, per-category attribution in the artifact); this
#         measures what the TPU scheduler does with each form.
#   phP   crop-packed single-pass student engine A/B (the two-pass
#         weight stream + 37-token tiling attack, ops/packing.py):
#         default program (model.crop_packing auto=on) vs
#         model.crop_packing=false two-pass control, same session,
#         both arms pinned BENCH_PROBS=bf16 AND BENCH_CENSUS=1 (the
#         r5b phT lesson: unpinned arms measured different programs).
#         Host-side accounting (scripts/cost_pack_student.py,
#         COST_PACK_r09.json): -50% student-phase weight-stream bytes
#         (4 -> 2 ViT-L stack streams per step), 120 -> 44 rows at
#         B=12; the packed attention's extra score bytes are the
#         documented trade — this measures which side the TPU
#         scheduler lands on.
#   phZ   cross-replica sharded update engine A/B (the dp-redundant
#         update-phase attack, train/fused_update.py
#         make_sharded_update): default program (optim.sharded_update
#         auto=on at dp>1) vs =false replicated-fused control, same
#         session, both arms pinned BENCH_PROBS=bf16 AND BENCH_CENSUS=1
#         so each record embeds the copy census AND the collective
#         census (utils.hlo_collective_census) — the grad-sync story
#         (all-reduce vs reduce-scatter+all-gather after the TPU
#         collective-optimizer rewrite) lands in the same JSONL row as
#         the throughput delta. Host-side accounting
#         (scripts/cost_sharded_update.py, COST_SHUP_r10.json): -80%
#         per-device update-phase weight-shaped bytes at dp=8 ViT-L,
#         RS+AG census with zero unattributed collectives; this
#         measures what the TPU scheduler does with each form.
#   phO   async telemetry engine A/B (the per-step host-sync attack,
#         telemetry/ring.py): default program (telemetry.async_metrics
#         auto=on — metrics row into a donated on-device ring, no
#         per-step device->host fetch) vs =false per-step-fetch oracle
#         control, same session, both arms pinned BENCH_PROBS=bf16 AND
#         BENCH_CENSUS=1 (the r5b phT pinned-arm lesson) so each
#         record embeds the copy census with the new "telemetry"
#         attribution category next to the throughput delta. Host-side
#         accounting (scripts/cost_host_sync.py, COST_HSYNC_r11.json):
#         the real hot loop issues 1 blocking fetch per
#         telemetry.flush_every steps vs 1 per step; every bench
#         record also embeds its own measure-loop fetch count +
#         host-blocked ms ("telemetry" field). This measures what the
#         TPU dispatch pipeline does with each form.
#   phB   bucketed overlap-scheduled collective engine A/B (the
#         per-leaf collective-launch attack, train/fused_update.py
#         make_bucketed_update): treatment pins the bucketed engine on
#         (optim.bucketed_collectives=true — 357 per-leaf grad
#         reduce-scatters coalesced into ~14 flat-bucket RS, 714
#         param/teacher all-gathers into ~28 bucket AG, shard-
#         interleaved layout so the reduction path stays bitwise);
#         control strips ONLY the engine (=false, the per-leaf PR-5
#         schedule), same scanned stack on both arms. Both arms carry
#         the copy + collective censuses (BENCH_CENSUS=1) so the
#         RS/AG op-count collapse and the size histogram (the >=64MB
#         big-bin fraction, COST_BUCKET_r13.json: 9% -> 90% of bytes)
#         land in the same JSONL row as the throughput delta — this
#         measures whether the TPU's collective scheduler actually
#         prices 25x fewer, 10x larger launches the way the host-side
#         accounting says it should.
#   phN   unified parallelism engine A/B (buckets x zero3 x grad
#         accumulation, PR 14: train/fused_update.py
#         gather_zero3_bucketed + make_zero3_bucket_plan): on the
#         dp x fsdp mesh, treatment runs the unified arm (non-block
#         zero3 gathers coalesced into hierarchy-aware staged buckets,
#         AG inter->intra / grad-RS intra->inter, 21 per-leaf -> 7
#         buckets at ViT-L 2x4, COST_UNIFIED_r18.json); control strips
#         ONLY the gather bucketing (optim.bucketed_collectives=false,
#         per-leaf zero3 gathers) on the identical mesh; a third arm
#         adds optim.accum_steps=2 on top of the treatment (the
#         microbatch scan with hoisted gathers — one bucketed RS per
#         optimizer step; per-microbatch throughput prices the scan
#         overhead). All arms carry BENCH_CENSUS=1 so the both-tier
#         scoped collective counts land next to the throughput delta —
#         whether staging over the real TPU hierarchy (ICI vs DCN)
#         pays is exactly the question the CPU artifact cannot answer.
#   phQ   low-precision training arm A/B (PR 17, ops/lowp.py):
#         treatment runs the fp8 arm (train.low_precision.arm=fp8 —
#         block matmul kernels quantized per-tensor with delayed
#         scaling, the zero3 stream gathering 1-byte codes instead of
#         bf16); control is the identical zero3 dp x fsdp mesh on the
#         default bf16 arm. Both arms carry BENCH_CENSUS=1 so the
#         streamed-gather scope counts + the record's "low_precision"
#         block (arm, setup drift probe, lowp_amax/lowp_dequant
#         scopes) land next to the throughput delta. Host-side
#         accounting (scripts/cost_lowp.py, COST_LP_r21.json):
#         >=1.8x fewer streamed kernel-gather bytes at identical
#         collective counts; XLA:CPU emulates fp8/int8 dots by
#         upconversion, so only this run prices the speed.
#   phD   serve-backed distillation teacher A/B (PR 18,
#         train/distillation.py TeacherServer): treatment runs the
#         real trainer with distillation.teacher_source=serve — the
#         frozen teacher forwards ONCE per unique image in the
#         host-shared packed AOT engine and the train step consumes
#         the precomputed teacher_cls/teacher_patches batch planes;
#         control is the identical run with teacher_source=in_step
#         (the teacher forward inside every compiled step — the
#         bitwise oracle). Same synthetic stream, same init, both
#         benchmark windows after warmup. CPU-side accounting
#         (scripts/cost_distill.py, COST_DISTILL_r22.json): k*E fewer
#         teacher forwards at 1 engine compile and bitwise
#         precomputed-vs-oracle targets; this measures whether the
#         host-side serve round-trip beats the in-step forward the
#         chip executes for free while the student waits.
#   phG2  fixed op-level flash-vs-dense attention crossover
#         (scripts/crossover_attention.py): the
#         kernels.flash_min_seq=2048 boundary is measured only at
#         N=201/1029 full-step points; 2048-2309 and the flash side are
#         unmeasured (ADVICE r5 low). Seconds-long compiles, banks the
#         crossover table + the executable recommended_flash_min_seq
#         the threshold cites.
#   phH   high-res gram-anchoring stage A/B (PR 15, sequence-sharded
#         segment-masked ring attention): treatment runs the 512px
#         gram stage on a dp x seq=2 mesh (ring path on the 1029-token
#         globals, per-pass kernels.ring_min_seq dispatch keeps locals
#         dense); control is the identical gram stage on the pure-dp
#         mesh (dense attention, seq=1). Both arms carry BENCH_CENSUS=1
#         so the ring_permute-scoped ppermute counts/bytes and the
#         seq_padding_warning land next to the throughput delta — the
#         CPU artifact (COST_HIRES_r19.json) prices the memory, only
#         the chip prices the rotation. Then re-derives the crossover
#         artifact on-chip (scripts/crossover_attention.py in
#         committed-JSON mode): CROSSOVER_r19.json's cpu verdict
#         (recommended_flash_min_seq=null, interpret-mode Pallas) is a
#         placeholder for exactly this run — commit the on-chip JSON
#         over it wholesale.
#   phE   continuous-packing serve engine A/B (the ragged-traffic
#         inference attack, dinov3_tpu/serve/): scripts/bench_serve.py
#         runs all three arms — packed (serve.continuous_packing
#         auto=on, ONE AOT fixed-shape compile) vs the rectangular-
#         batch and per-image shape-polymorphic oracles — over three
#         traffic mixes with disjoint warmup/measurement draws, and
#         embeds per-arm compile growth, pad waste, host-sync fetch
#         counts and the serve-category copy census in one record.
#         CPU-side accounting (SERVE_r14.json): packed >=2x the
#         rectangular oracle img/s on the mixed ragged band at
#         bf16-pinned feature equality, 1 compile after warmup; this
#         measures what TPU compile latency and HBM bandwidth do to
#         both sides of that ratio (oracle recompiles cost more
#         on-chip, but the packed row's O(row^2) dense attention
#         meets an 8x faster matmul unit).
#   phF   quantized serving fleet A/B (the int8-weights + SLO-pool +
#         feature-cache attack, dinov3_tpu/serve/{quant,fleet,cache}):
#         scripts/bench_serve.py --fleet runs the bf16-vs-int8
#         single-engine control (same layout, paired best-of-k drains,
#         CLS drift pinned under serve.quant.drift_tol) and the
#         2-engine SLO-routed fleet with the content-addressed cache
#         swept over hit rates {0, 0.5, 0.9}, every hit audited
#         bitwise against its miss and total compiles pinned at
#         n_engines. CPU-side accounting (SERVE_r16.json): int8 >=
#         bf16 img/s at ~1e-8 CLS drift, 0.56x weight bytes; this
#         measures what 8x-faster TPU matmul + HBM bandwidth do to
#         the dequant-fused row (the serve_dequant census category
#         rides in the record via BENCH_CENSUS=1).
#   phA   step-anatomy on-chip banking (telemetry/anatomy.py): re-runs
#         scripts/anatomy_report.py on the real TPU mesh, where each
#         device is its own trace pid and its streams genuinely run
#         concurrently — the committed CPU overlap fractions
#         (ANATOMY_r17.json) are structural lower bounds, and this run
#         banks the real ones: bucket/zero3 gathers overlapped under
#         forward compute, the coalesced grad-RS inside the measured
#         backward interval. perf_gate.py then compares the fresh
#         record against the CPU baseline (advisory across backends —
#         step times are not comparable; the TPU record lands in
#         RESULTS for the next session to commit as the on-chip
#         baseline).
#   phC   collective-schedule tuner on-chip re-derivation
#         (scripts/tune_collectives.py): the committed TUNED_r20.json
#         was searched on the CPU harness, whose sequential per-device
#         thunk execution makes exposed-comm a conservative ceiling —
#         this arm re-runs the full measure->tune loop where overlap
#         is real, gates tuned-vs-handset on the fresh artifact
#         (perf_gate.py --tuned-vs-handset), and banks the plan in
#         RESULTS for the next session to commit. ("phT2" in the
#         issue's wording; that tag already names the r5b target-bf16
#         A/B above, so the tuner runs as phC.)
# Every bench.py record now embeds the fixed calibration rung
# ("calib"), so these rows are comparable across sessions.
#
# Usage: bash scripts/r6_queue.sh  (env: RESULTS, QUEUE_LOG, DEADLINE_HOURS)

set -u
cd "$(dirname "$0")/.."
RESULTS="${RESULTS:-/tmp/r6_results.jsonl}"
LOG="${QUEUE_LOG:-/tmp/r6_queue.log}"
DEADLINE=$(( $(date +%s) + ${DEADLINE_HOURS:-10} * 3600 ))

note() { echo "[r6 $(date +%H:%M:%S)] $*" | tee -a "$LOG"; }

remaining() { echo $(( DEADLINE - $(date +%s) )); }

probe() {
    timeout 300 python - <<'EOF' >>"$LOG" 2>&1
import sys
sys.path.insert(0, ".")
from dinov3_tpu.utils import respect_jax_platforms_env
respect_jax_platforms_env()
import jax
assert jax.default_backend() != "cpu", "fell back to cpu"
print("PROBE-OK", jax.device_count())
EOF
}

wait_healthy() {
    while [ "$(remaining)" -gt 0 ]; do
        if probe; then note "probe healthy"; return 0; fi
        note "probe unhealthy; sleeping 240s ($(( $(remaining) / 60 )) min to deadline)"
        sleep 240
    done
    note "deadline reached while waiting for a healthy tunnel"
    return 1
}

gate_phase() {
    local backstop="$1" tag="$2"
    if [ "$(remaining)" -le "$backstop" ]; then
        note "SKIP $tag: ${backstop}s backstop does not fit in $(remaining)s to deadline"
        return 1
    fi
    wait_healthy || return 1
    if [ "$(remaining)" -le "$backstop" ]; then
        note "SKIP $tag: deadline closed in while waiting for a healthy probe"
        return 1
    fi
    return 0
}

run_bench() {
    local tag="$1" tmo="$2" kind="$3"; shift 3
    local backstop budget
    if [ "$kind" = pinned ]; then
        budget=$tmo; backstop=$((tmo + 600))
    else
        budget=$((3 * tmo)); backstop=$((3 * tmo + 600))
    fi
    local try rc out
    for try in 1 2; do
        gate_phase "$backstop" "$tag" || return 1
        note "start $tag try=$try (tmo=${tmo}s budget=${budget}s) env: $*"
        out=$(env "$@" BENCH_ATTEMPT_TIMEOUT="$tmo" BENCH_TOTAL_BUDGET="$budget" \
              timeout "$backstop" python bench.py 2>>"$LOG")
        rc=$?
        if [ $rc -eq 0 ] && [ -n "$out" ]; then
            echo "{\"tag\": \"$tag\", \"rc\": 0, \"result\": $out}" >> "$RESULTS"
            note "done  $tag -> $out"
            return 0
        fi
        if [ -n "$out" ]; then
            echo "{\"tag\": \"$tag\", \"rc\": $rc, \"result\": $out}" >> "$RESULTS"
        else
            echo "{\"tag\": \"$tag\", \"rc\": $rc, \"result\": null}" >> "$RESULTS"
        fi
        if [ $rc -eq 3 ] && [ $try -eq 1 ]; then
            note "INFRA $tag rc=3 (tunnel died mid-run); re-gating on probe for one retry"
            continue
        fi
        note "FAIL  $tag rc=$rc"
        return $rc
    done
}

note "=== r6 queue starting; deadline $(date -d @$DEADLINE +%H:%M:%S) ==="

# phU: fused update engine A/B. Treatment = committed default program
# (fused on); control strips ONLY the engine. Pinned (no ladder
# substitution) and same-session so the A/B is clean.
run_bench phU_fused_on 2100 pinned BENCH_PROBS=bf16
run_bench phU_fused_off_ctl 2100 pinned BENCH_PROBS=bf16 \
    BENCH_OVERRIDES=optim.fused_update=false

# phT2: teacher-target bf16 storage A/B, both arms sharing BENCH_PROBS
run_bench phT2_target_bf16 2100 pinned BENCH_PROBS=bf16 \
    BENCH_OVERRIDES=compute_precision.target_dtype=bf16
run_bench phT2_target_fp32_ctl 2100 pinned BENCH_PROBS=bf16

# phS: streaming prototype-axis target/CE engine A/B. Treatment = the
# committed default program (loss.streaming_targets auto = on); control
# strips ONLY the engine. Default sinkhorn centering first, then the
# softmax-center pair where the host-side accounting says the big win
# lives (-69.5% target-phase bytes, COST_TARGET_r07.json).
run_bench phS_stream_on 2100 pinned BENCH_PROBS=bf16
run_bench phS_stream_off_ctl 2100 pinned BENCH_PROBS=bf16 \
    BENCH_OVERRIDES=loss.streaming_targets=false
run_bench phS_sc_stream_on 2100 pinned BENCH_PROBS=bf16 \
    BENCH_OVERRIDES=train.centering=softmax_center
run_bench phS_sc_stream_off_ctl 2100 pinned BENCH_PROBS=bf16 \
    BENCH_OVERRIDES=train.centering=softmax_center,loss.streaming_targets=false

# phR: step-wide RNG-plan engine A/B. Treatment = the committed default
# program (rng.plan auto = on); control strips ONLY the engine (legacy
# fold_in chains). Both arms embed the compiled-step copy census in
# their records so the throughput delta and the copy-count delta land
# in the same JSONL row.
run_bench phR_rngplan_on 2100 pinned BENCH_PROBS=bf16 BENCH_CENSUS=1
run_bench phR_rngplan_off_ctl 2100 pinned BENCH_PROBS=bf16 BENCH_CENSUS=1 \
    BENCH_OVERRIDES=rng.plan=false

# phP: crop-packed student engine A/B. Treatment = the committed
# default program (model.crop_packing auto = on); control strips ONLY
# the engine (two-pass student forward). Both arms carry the compiled
# copy census so the pack/unpack attribution (utils.classify_copy
# "gather_pack") lands next to the throughput delta.
run_bench phP_packed_on 2100 pinned BENCH_PROBS=bf16 BENCH_CENSUS=1
run_bench phP_packed_off_ctl 2100 pinned BENCH_PROBS=bf16 BENCH_CENSUS=1 \
    BENCH_OVERRIDES=model.crop_packing=false

# phZ: cross-replica sharded update engine A/B. Treatment = the
# committed default program (optim.sharded_update auto = on at dp > 1);
# control strips ONLY the engine (replicated fused update). Both arms
# carry the copy + collective censuses of the exact benched program so
# the grad-sync collective story lands next to the throughput delta.
run_bench phZ_sharded_on 2100 pinned BENCH_PROBS=bf16 BENCH_CENSUS=1
run_bench phZ_sharded_off_ctl 2100 pinned BENCH_PROBS=bf16 BENCH_CENSUS=1 \
    BENCH_OVERRIDES=optim.sharded_update=false

# phO: async telemetry engine A/B. Treatment = the committed default
# program (telemetry.async_metrics auto = on; bench.py benches the
# telemetry step — ring write in-graph, one fetch per measure loop);
# control strips ONLY the engine (per-step-fetch-oracle program; note
# bench's measure loop itself never fetched per step, so the control
# isolates the ring write + donation cost while COST_HSYNC_r11.json
# carries the hot-loop fetch-count story). Both arms embed the copy
# census so the "telemetry" category lands next to the throughput.
run_bench phO_telemetry_on 2100 pinned BENCH_PROBS=bf16 BENCH_CENSUS=1
run_bench phO_telemetry_off_ctl 2100 pinned BENCH_PROBS=bf16 BENCH_CENSUS=1 \
    BENCH_OVERRIDES=telemetry.async_metrics=false

# phW: ZeRO-3 weight-streaming engine A/B. Treatment = the streamed
# program (parallel.zero3=true + scan_layers: masters/teacher/moments
# born sharded over the data axes, block weights gathered per block
# inside the scan); control strips ONLY the engine
# (parallel.zero3=false — replicated masters, same scanned stack).
# Both arms carry the censuses so the record pairs the throughput
# delta with the per-device state bytes (the "zero3" summary block),
# the scoped gather counts, and the REAL gather dtype — the CPU census
# float-normalizes bf16 collectives to f32, so the bf16-stream bytes
# claim is settled here, on chip.
run_bench phW_zero3_on 2100 pinned BENCH_PROBS=bf16 BENCH_CENSUS=1 \
    BENCH_OVERRIDES=parallel.zero3=true,train.scan_layers=true
run_bench phW_zero3_off_ctl 2100 pinned BENCH_PROBS=bf16 BENCH_CENSUS=1 \
    BENCH_OVERRIDES=parallel.zero3=false,train.scan_layers=true

# phB: bucketed overlap-scheduled collective engine A/B. Treatment
# pins the bucketed engine on (coalesced flat-bucket grad RS under
# backward + bucketed param/teacher AG — optim.bucketed_collectives
# auto-engages only on pure-dp meshes, so the pin keeps the arm honest
# whatever mesh the bench ladder lands on); control strips ONLY the
# engine (=false, the per-leaf PR-5 schedule), same scanned stack.
# Both arms carry the copy + collective censuses so the RS/AG launch
# collapse (357 -> 14 / 714 -> 28 at ViT-L dp=8, COST_BUCKET_r13.json)
# and the >=64MB big-bin bytes fraction land next to the throughput
# delta.
run_bench phB_bucketed_on 2100 pinned BENCH_PROBS=bf16 BENCH_CENSUS=1 \
    BENCH_OVERRIDES=optim.bucketed_collectives=true,train.scan_layers=true
run_bench phB_bucketed_off_ctl 2100 pinned BENCH_PROBS=bf16 BENCH_CENSUS=1 \
    BENCH_OVERRIDES=optim.bucketed_collectives=false,train.scan_layers=true

# phN: unified parallelism engine A/B (buckets x zero3 x accumulation,
# PR 14). All arms pin the SAME dp x fsdp=2 zero3 mesh so the only
# difference is the gather schedule (and, for the accum arm, the
# microbatch scan). Treatment = hierarchy-aware staged bucket gathers
# (optim.bucketed_collectives=true on the zero3 mesh — the unified
# arm); control = per-leaf zero3 gathers (=false) on the identical
# mesh; accum arm = treatment + optim.accum_steps=2 (one bucketed
# grad-RS per optimizer step, gathers hoisted out of the scan). The
# censuses carry the bucket_ag_inter/intra + bucket_rs_* scope counts
# so the staged-collective story lands next to the throughput delta.
run_bench phN_unified_on 2100 pinned BENCH_PROBS=bf16 BENCH_CENSUS=1 \
    BENCH_OVERRIDES=parallel.fsdp=2,parallel.zero3=true,optim.bucketed_collectives=true,train.scan_layers=true
run_bench phN_unified_perleaf_ctl 2100 pinned BENCH_PROBS=bf16 BENCH_CENSUS=1 \
    BENCH_OVERRIDES=parallel.fsdp=2,parallel.zero3=true,optim.bucketed_collectives=false,train.scan_layers=true
run_bench phN_unified_accum2 2100 pinned BENCH_PROBS=bf16 BENCH_CENSUS=1 \
    BENCH_OVERRIDES=parallel.fsdp=2,parallel.zero3=true,optim.bucketed_collectives=true,optim.accum_steps=2,train.scan_layers=true

# phQ: low-precision training arm A/B (PR 17). Both arms pin the SAME
# dp x fsdp=2 zero3 mesh + scanned stack so the only difference is the
# precision arm: treatment quantizes the block matmul kernels to fp8
# (delayed per-tensor scaling; the in-loop zero3 stream gathers 1-byte
# codes), control is the committed bf16 default (bitwise the PR-16
# program). The censuses carry the zero3_stream/lowp_* scope counts so
# the bytes-vs-counts story lands next to the throughput delta — the
# CPU artifact (COST_LP_r21.json) prices the bytes, only the chip's
# native fp8 matmul unit prices the speed.
run_bench phQ_lowp_fp8 2100 pinned BENCH_PROBS=bf16 BENCH_CENSUS=1 \
    BENCH_OVERRIDES=parallel.fsdp=2,parallel.zero3=true,train.scan_layers=true,train.low_precision.arm=fp8
run_bench phQ_lowp_bf16_ctl 2100 pinned BENCH_PROBS=bf16 BENCH_CENSUS=1 \
    BENCH_OVERRIDES=parallel.fsdp=2,parallel.zero3=true,train.scan_layers=true

# phD: serve-backed distillation teacher A/B (PR 18). Both arms run
# the REAL trainer (synthetic stream, default ViT-L distilling from
# its own recipe as the frozen teacher — weights are random either
# way, only the teacher-evaluation PATH differs) with a benchmark
# window: treatment = teacher_source=serve (one packed host-side
# teacher forward per unique image, planes ride the batch), control =
# teacher_source=in_step (the teacher forward inside every compiled
# step). The treatment result embeds the TeacherServer dedup/cache
# counters the CPU artifact pins.
if gate_phase 3000 phD_distill_serve; then
    note "start phD_distill_serve"
    printf '{}\n' > /tmp/phD_teacher.yaml
    for arm in serve in_step; do
        rm -rf "/tmp/phD_$arm"
        if timeout 3000 python - "$arm" > "/tmp/phD_$arm.json" 2>>"$LOG" <<'PY'
import json, sys
from dinov3_tpu.train.train import main

arm = sys.argv[1]
res = main([
    "--output-dir", f"/tmp/phD_{arm}", "--no-resume",
    "--max-iterations", "40", "--benchmark", "20",
    "data.backend=synthetic",
    "distillation.enabled=true",
    "distillation.full_cfg_path=/tmp/phD_teacher.yaml",
    f"distillation.teacher_source={arm}",
])
keep = ("img_per_sec", "final_loss", "iterations", "teacher_serve")
print(json.dumps({"arm": arm,
                  **{k: res[k] for k in keep if k in res}}))
PY
        then
            line=$(cat "/tmp/phD_$arm.json")
            note "done  phD_distill_serve/$arm -> $line"
            echo "{\"tag\": \"phD_distill_serve\", \"rc\": 0, \"result\": $line}" >> "$RESULTS"
        else
            note "FAIL  phD_distill_serve/$arm rc=$?"
            echo "{\"tag\": \"phD_distill_serve\", \"rc\": 1, \"result\": {\"arm\": \"$arm\"}}" >> "$RESULTS"
        fi
    done
fi

# phR: elastic-topology reshard A/B on chip (PR 19). The full chaos
# harness on the real mesh: one run killed/resumed across three
# topologies with the loss trajectory pinned bitwise vs the unreshaped
# oracle, plus the in-memory-vs-disk transition instrument — on chip
# the state is real-sized, so the memory-vs-disk gap (and whether the
# one-time program compile amortizes as predicted) is the banked
# number. Artifact rides RESULTS for the next session to commit as the
# on-chip RESHARD row.
if gate_phase 3000 phR_reshard_elastic; then
    note "start phR_reshard_elastic"
    rm -f /tmp/reshard_r6.json
    if timeout 3000 python scripts/cost_reshard.py /tmp/reshard_r6.json \
            >> "$LOG" 2>&1; then
        note "done  phR_reshard_elastic -> /tmp/reshard_r6.json"
        line=$(python -c "import json; print(json.dumps(json.load(open('/tmp/reshard_r6.json'))))")
        echo "{\"tag\": \"phR_reshard_elastic\", \"rc\": 0, \"result\": $line}" >> "$RESULTS"
    else
        note "FAIL  phR_reshard_elastic rc=$?"
        echo "{\"tag\": \"phR_reshard_elastic\", \"rc\": 1, \"result\": null}" >> "$RESULTS"
    fi
fi

# phG2: the fixed op-level flash-vs-dense crossover (compiles in
# seconds; measures the kernels.flash_min_seq=2048 boundary including
# the unmeasured 2048-2309 band and the flash side at N>=2309).
if gate_phase 2400 phG2_attn_crossover; then
    note "start phG2_attn_crossover"
    rm -f /tmp/attn_crossover_r6.jsonl
    if timeout 2400 python scripts/crossover_attention.py \
            /tmp/attn_crossover_r6.jsonl >> "$LOG" 2>&1; then
        note "done  phG2_attn_crossover -> /tmp/attn_crossover_r6.jsonl"
        while IFS= read -r line; do
            echo "{\"tag\": \"phG2_attn_crossover\", \"rc\": 0, \"result\": $line}" >> "$RESULTS"
        done < /tmp/attn_crossover_r6.jsonl
    else
        note "FAIL  phG2_attn_crossover rc=$?"
        echo "{\"tag\": \"phG2_attn_crossover\", \"rc\": 1, \"result\": null}" >> "$RESULTS"
    fi
fi

# phH: high-res gram-anchoring stage A/B (PR 15). Treatment = 512px
# gram stage on the dp x seq=2 mesh (ring attention on the 1029-token
# globals; kernels.ring_min_seq=1024 keeps the short local crops
# dense); control = the identical gram stage on the pure-dp mesh.
# scan_layers pinned OFF on both arms: seq>1 would force-disable it
# anyway (the nn.scan x custom_vjp tracer leak, train/setup.py) and
# the control must compile the same unscanned stack to be comparable.
run_bench phH_hires_ring_seq2 2700 pinned BENCH_PROBS=bf16 BENCH_CENSUS=1 \
    BENCH_OVERRIDES=crops.global_crops_size=512,crops.gram_teacher_crops_size=512,gram.use_loss=true,gram.ema_teacher=false,parallel.seq=2,train.scan_layers=false
run_bench phH_hires_dense_seq1_ctl 2700 pinned BENCH_PROBS=bf16 BENCH_CENSUS=1 \
    BENCH_OVERRIDES=crops.global_crops_size=512,crops.gram_teacher_crops_size=512,gram.use_loss=true,gram.ema_teacher=false,train.scan_layers=false

# ... and the committed-artifact crossover re-derivation: same harness
# as phG2 but in committed-JSON mode — the on-chip replacement for
# CROSSOVER_r19.json's cpu-verdict placeholder (flash_min_seq=auto
# resolves from this file; copy it over the repo root's and commit).
if gate_phase 2400 phH_crossover_artifact; then
    note "start phH_crossover_artifact"
    if timeout 2400 python scripts/crossover_attention.py \
            /tmp/CROSSOVER_r19_onchip.json >> "$LOG" 2>&1; then
        note "done  phH_crossover_artifact -> /tmp/CROSSOVER_r19_onchip.json"
        echo "{\"tag\": \"phH_crossover_artifact\", \"rc\": 0, \"result\": $(python -c 'import json,sys; d=json.load(open("/tmp/CROSSOVER_r19_onchip.json")); print(json.dumps({"platform": d["platform"], "recommended_flash_min_seq": d["recommended_flash_min_seq"], "crossover": d["crossover"]}))')}" >> "$RESULTS"
    else
        note "FAIL  phH_crossover_artifact rc=$?"
        echo "{\"tag\": \"phH_crossover_artifact\", \"rc\": 1, \"result\": null}" >> "$RESULTS"
    fi
fi

# phE: continuous-packing serve engine A/B. bench_serve.py runs the
# packed arm and both oracles in ONE process (same session, shared
# calib conditions by construction) over the three committed traffic
# mixes; the record already embeds per-arm compile growth, pad waste
# and the serve copy census, so the whole A/B is one JSON object.
if gate_phase 3000 phE_serve_packing; then
    note "start phE_serve_packing"
    rm -f /tmp/serve_r6.json
    if timeout 3000 python scripts/bench_serve.py \
            --out /tmp/serve_r6.json >> "$LOG" 2>&1; then
        note "done  phE_serve_packing -> /tmp/serve_r6.json"
        line=$(python -c "import json,sys; print(json.dumps(json.load(open('/tmp/serve_r6.json'))))")
        echo "{\"tag\": \"phE_serve_packing\", \"rc\": 0, \"result\": $line}" >> "$RESULTS"
    else
        note "FAIL  phE_serve_packing rc=$?"
        echo "{\"tag\": \"phE_serve_packing\", \"rc\": 1, \"result\": null}" >> "$RESULTS"
    fi
fi

# phF: quantized serving fleet A/B. One process runs the int8-vs-bf16
# single-engine control AND the 2-engine SLO-routed fleet + cache
# sweep (same session, shared model build); the record embeds the
# drift probe, per-(engine, SLO) p50/p99 and the compile pins, so the
# whole A/B is one JSON object.
if gate_phase 3000 phF_serve_fleet; then
    note "start phF_serve_fleet"
    rm -f /tmp/serve_fleet_r6.json
    if env BENCH_CENSUS=1 timeout 3000 python scripts/bench_serve.py \
            --fleet --out /tmp/serve_fleet_r6.json >> "$LOG" 2>&1; then
        note "done  phF_serve_fleet -> /tmp/serve_fleet_r6.json"
        line=$(python -c "import json,sys; print(json.dumps(json.load(open('/tmp/serve_fleet_r6.json'))))")
        echo "{\"tag\": \"phF_serve_fleet\", \"rc\": 0, \"result\": $line}" >> "$RESULTS"
    else
        note "FAIL  phF_serve_fleet rc=$?"
        echo "{\"tag\": \"phF_serve_fleet\", \"rc\": 1, \"result\": null}" >> "$RESULTS"
    fi
fi

# phA: step-anatomy on-chip banking. The full anatomy_report (executed
# update-phase arms, both stream twins, the real-trainer dryrun) on
# the TPU mesh; the measured-overlap column stops being a lower bound
# here. perf_gate.py runs advisory against the committed CPU baseline
# (attribution pins transfer; step times do not compare across
# backends), and the full record rides RESULTS so the next session can
# commit it as the on-chip baseline.
if gate_phase 3000 phA_step_anatomy; then
    note "start phA_step_anatomy"
    rm -f /tmp/anatomy_r6.json
    if timeout 3000 python scripts/anatomy_report.py /tmp/anatomy_r6.json >> "$LOG" 2>&1; then
        note "done  phA_step_anatomy -> /tmp/anatomy_r6.json"
        if python scripts/perf_gate.py --baseline ANATOMY_r17.json \
                --fresh /tmp/anatomy_r6.json >> "$LOG" 2>&1; then
            note "phA perf_gate: within tolerance of the CPU baseline"
        else
            note "phA perf_gate: drift vs the CPU baseline (expected across backends; see $LOG)"
        fi
        line=$(python -c "import json; print(json.dumps(json.load(open('/tmp/anatomy_r6.json'))))")
        echo "{\"tag\": \"phA_step_anatomy\", \"rc\": 0, \"result\": $line}" >> "$RESULTS"
    else
        note "FAIL  phA_step_anatomy rc=$?"
        echo "{\"tag\": \"phA_step_anatomy\", \"rc\": 1, \"result\": null}" >> "$RESULTS"
    fi
fi

# phC: collective-schedule tuner on-chip re-derivation. Full sweep on
# the real mesh (the CPU-derived plan optimized a sequential-thunk
# lower bound; this banks what the real overlap engine picks), then
# the tuned-vs-handset acceptance gate on the fresh artifact. The
# artifact rides RESULTS for the next session to commit — its
# fingerprint differs from the committed CPU one by design, so "auto"
# keeps falling back until it is committed alongside a matching setup.
if gate_phase 3600 phC_tune_collectives; then
    note "start phC_tune_collectives"
    rm -f /tmp/tuned_r6.json
    if timeout 3600 python scripts/tune_collectives.py /tmp/tuned_r6.json >> "$LOG" 2>&1; then
        note "done  phC_tune_collectives -> /tmp/tuned_r6.json"
        if python scripts/perf_gate.py --tuned-vs-handset \
                --baseline /tmp/tuned_r6.json >> "$LOG" 2>&1; then
            note "phC tuned_vs_handset: tuned plan >= hand-set on every arm"
        else
            note "phC tuned_vs_handset: FAIL on-chip (see $LOG)"
        fi
        line=$(python -c "import json; print(json.dumps(json.load(open('/tmp/tuned_r6.json'))))")
        echo "{\"tag\": \"phC_tune_collectives\", \"rc\": 0, \"result\": $line}" >> "$RESULTS"
    else
        note "FAIL  phC_tune_collectives rc=$?"
        echo "{\"tag\": \"phC_tune_collectives\", \"rc\": 1, \"result\": null}" >> "$RESULTS"
    fi
fi

note "=== r6 queue complete; results in $RESULTS ==="
