#!/bin/bash
# Round-5 follow-up queue, armed after the tunnel's 50-minute revival
# window (15:31-16:21 UTC Aug 2) banked phA/phB/phC/phD/phG/phH/phF and
# then died mid-phE. Phases here are what that window left, cheapest /
# highest-evidence first:
#   phG2  re-run op-level flash-vs-dense crossover with the FIXED
#         fetch-sync harness (the first pass measured enqueue only)
#   phT   target_dtype=bf16 A/B vs the committed B=12 default
#   phC16 B=16 sweep point (B=12 default beat B=8 by 7.5%)
#   phE2  ViT-S texture rung, full + no_ibot arms (arm 1 died at
#         iter ~1000/3000 when the tunnel went down)
#
# Usage: bash scripts/r5b_queue.sh  (env: RESULTS, QUEUE_LOG, DEADLINE_HOURS)

set -u
cd "$(dirname "$0")/.."
RESULTS="${RESULTS:-/tmp/r5b_results.jsonl}"
LOG="${QUEUE_LOG:-/tmp/r5b_queue.log}"
DEADLINE=$(( $(date +%s) + ${DEADLINE_HOURS:-10} * 3600 ))

note() { echo "[r5b $(date +%H:%M:%S)] $*" | tee -a "$LOG"; }

remaining() { echo $(( DEADLINE - $(date +%s) )); }

probe() {
    timeout 300 python - <<'EOF' >>"$LOG" 2>&1
import sys
sys.path.insert(0, ".")
from dinov3_tpu.utils import respect_jax_platforms_env
respect_jax_platforms_env()
import jax
assert jax.default_backend() != "cpu", "fell back to cpu"
print("PROBE-OK", jax.device_count())
EOF
}

wait_healthy() {
    while [ "$(remaining)" -gt 0 ]; do
        if probe; then note "probe healthy"; return 0; fi
        note "probe unhealthy; sleeping 240s ($(( $(remaining) / 60 )) min to deadline)"
        sleep 240
    done
    note "deadline reached while waiting for a healthy tunnel"
    return 1
}

gate_phase() {
    local backstop="$1" tag="$2"
    if [ "$(remaining)" -le "$backstop" ]; then
        note "SKIP $tag: ${backstop}s backstop does not fit in $(remaining)s to deadline"
        return 1
    fi
    wait_healthy || return 1
    if [ "$(remaining)" -le "$backstop" ]; then
        note "SKIP $tag: deadline closed in while waiting for a healthy probe"
        return 1
    fi
    return 0
}

run_bench() {
    local tag="$1" tmo="$2" kind="$3"; shift 3
    local backstop budget
    if [ "$kind" = pinned ]; then
        budget=$tmo; backstop=$((tmo + 600))
    else
        budget=$((3 * tmo)); backstop=$((3 * tmo + 600))
    fi
    local try rc out
    for try in 1 2; do
        gate_phase "$backstop" "$tag" || return 1
        note "start $tag try=$try (tmo=${tmo}s budget=${budget}s) env: $*"
        out=$(env "$@" BENCH_ATTEMPT_TIMEOUT="$tmo" BENCH_TOTAL_BUDGET="$budget" \
              timeout "$backstop" python bench.py 2>>"$LOG")
        rc=$?
        if [ $rc -eq 0 ] && [ -n "$out" ]; then
            echo "{\"tag\": \"$tag\", \"rc\": 0, \"result\": $out}" >> "$RESULTS"
            note "done  $tag -> $out"
            return 0
        fi
        if [ -n "$out" ]; then
            echo "{\"tag\": \"$tag\", \"rc\": $rc, \"result\": $out}" >> "$RESULTS"
        else
            echo "{\"tag\": \"$tag\", \"rc\": $rc, \"result\": null}" >> "$RESULTS"
        fi
        if [ $rc -eq 3 ] && [ $try -eq 1 ]; then
            note "INFRA $tag rc=3 (tunnel died mid-run); re-gating on probe for one retry"
            continue
        fi
        note "FAIL  $tag rc=$rc"
        return $rc
    done
}

note "=== r5b queue starting; deadline $(date -d @$DEADLINE +%H:%M:%S) ==="

# phG2: the fixed crossover (sync via value fetch). Minutes of chip time.
gate_phase 2400 phG2_attn_crossover && {
    note "start phG2_attn_crossover"
    rm -f /tmp/attn_crossover_fixed.jsonl
    if timeout 2400 python scripts/bench_attention_crossover.py \
            /tmp/attn_crossover_fixed.jsonl >> "$LOG" 2>&1; then
        note "done  phG2_attn_crossover -> /tmp/attn_crossover_fixed.jsonl"
    else
        note "FAIL  phG2_attn_crossover rc=$?"
    fi
}

# phT: teacher-target bf16 storage A/B against the committed B=12
# default (54.46->58.56 was the B sweep; this isolates target_dtype).
# Pinned: a ladder substitution would invalidate the A/B. BENCH_PROBS
# is pinned bf16 on BOTH arms (the control below already pins it) so
# the only delta between treatment and control is target_dtype.
run_bench phT_target_bf16 2100 pinned BENCH_PROBS=bf16 \
    BENCH_OVERRIDES=compute_precision.target_dtype=bf16
# control re-run in the same session so the A/B shares a host
run_bench phT_target_fp32_ctl 2100 pinned BENCH_PROBS=bf16

# phC16: the sweep's missing point above the new default
run_bench phC_b16 2100 pinned BENCH_BATCH=16 BENCH_PROBS=bf16

# phE2: the ViT-S accuracy rung (hours; lowest marginal evidence/hour).
gate_phase 11400 phE2_vits_textures && {
    note "start phE2_vits_textures"
    if ABL_ARCH=vit_small ABL_ARMS=full,no_ibot \
            ABL_STEPS=3000 ABL_EVAL_EVERY=200 ABL_BATCH=48 \
            timeout 10800 python scripts/ablation_recipe.py /tmp/abl_vits \
            >> "$LOG" 2>&1; then
        note "done  phE2_vits_textures -> /tmp/abl_vits/ABLATION.json"
    else
        note "FAIL  phE2_vits_textures rc=$?"
    fi
}

note "=== r5b queue complete; results in $RESULTS ==="
