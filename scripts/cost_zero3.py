"""ZeRO-3 weight-streaming accounting: the committed evidence behind
COST_Z3_r12.json and MEM_r12.json (PR-1..6 discipline — measure the
exact shipped code paths).

Three instruments, all on the 8-simulated-device CPU mesh:

- **Per-device state accounting (ViT-L, compile-only)**: both arms are
  built ABSTRACTLY (``build_train_setup(init_state=False)``) and
  per-device bytes come from the ``NamedSharding``s the setup assigned
  (``telemetry.memory.layout_split`` — replicated leaves count fully
  per device, sharded leaves 1/dp). Control strips ONLY the engine
  (``parallel.zero3=false`` — the pre-PR-7 default: replicated fp32
  masters + EMA teacher, ZeRO-1 flat adam moments); treatment is the
  zero3 arm (everything weight-shaped born sharded). Both arms
  ``train.scan_layers=true`` so the comparison isolates the layout, not
  the stack form. The ``layout_split`` replicated-fraction pin keeps
  the zero3 arm from silently reporting the replicated footprint.
- **Collective/weight-stream census**: the exact compiled default step
  of each arm (the telemetry step, as benched) through
  ``utils.hlo_collective_census`` — per-class ops/bytes, the named-scope
  attribution (every zero3 gather lands in ``zero3_stream``/
  ``zero3_gather``, never "unattributed"), and the in-loop all-gather
  story. The double-buffered prefetch schedule is censused on the
  EXPLICIT twin (``models/streaming.streamed_block_scan``, the
  ``make_sharded_update_schedule`` convention): a ViT-L block stack in
  the bf16 stream layout, compiled standalone, whose in-loop gathers
  are ``zero3_prefetch``-scoped — issued one full block of compute
  ahead of their consumer. The twin takes the bf16 stack as a program
  INPUT so the censused gather bytes are the stream dtype's by
  construction (inside the full step this backend's partitioner
  re-places the master->bf16 convert across the gather and moves fp32
  bytes; the TPU collective pipeline narrows them — the phW on-chip
  records carry the truth).
- **ViT-7B unlock dryrun**: ``configs/train/vit7b16_zero3.yaml``
  compiles end-to-end on the same 8 simulated devices
  (``build_train_setup(init_state=False)`` -> lower -> compile), with
  the per-device state accounting committed next to it. This is the
  deliverable of ROADMAP item 1: the state that CANNOT exist replicated
  (6.7B fp32 masters x2 = ~54 GB/device before moments) fits as
  ~1/8 shards.

Writes COST_Z3_r12.json (argv[1], default ./COST_Z3_r12.json) and
MEM_r12.json (argv[2], default ./MEM_r12.json); prints the COST record
to stdout.

Usage: JAX_PLATFORMS=cpu python scripts/cost_zero3.py \
           [cost_out] [mem_out] [--skip-7b]
"""

from __future__ import annotations

import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DP = 8
os.environ.setdefault("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += f" --xla_force_host_platform_device_count={DP}"

COST_OUT = sys.argv[1] if len(sys.argv) > 1 and not sys.argv[1].startswith(
    "--") else "COST_Z3_r12.json"
MEM_OUT = sys.argv[2] if len(sys.argv) > 2 and not sys.argv[2].startswith(
    "--") else "MEM_r12.json"
SKIP_7B = "--skip-7b" in sys.argv


def _log(msg):
    print(f"[cost_zero3] {msg}", file=sys.stderr, flush=True)


def _bench():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def tree_split(tree, shardings):
    from dinov3_tpu.telemetry.memory import layout_split

    return layout_split(tree, shardings)


def build_arm(zero3: bool):
    """ViT-L dp=8 abstract setup + compiled default (telemetry) step."""
    import jax

    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.telemetry.ring import make_ring
    from dinov3_tpu.train import build_train_setup

    bench = _bench()
    cfg = get_default_config()
    apply_dot_overrides(cfg, bench.build_step_overrides("vit_large", 0) + [
        "train.scan_layers=true",
        f"parallel.zero3={'true' if zero3 else 'false'}",
    ])
    B = 12 * DP
    batch_np = make_synthetic_batch(cfg, B, seed=0)
    batch = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
             for k, v in batch_np.items()}
    setup = build_train_setup(cfg, batch_np, init_state=False)
    assert setup.zero3 == zero3

    s = setup.state
    sh = setup.state_shardings
    split = {
        "params_student": tree_split(s.params["student"],
                                     sh.params["student"]),
        "params_teacher": tree_split(s.params["teacher"],
                                     sh.params["teacher"]),
        "opt_state": tree_split(s.opt_state, sh.opt_state),
        "center_state": tree_split(s.center_state, sh.center_state),
    }

    plan = setup.telemetry()
    ring_abs = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
        make_ring(len(plan.metric_names), plan.ring_len))
    scalars = {
        "teacher_temp": jax.ShapeDtypeStruct((), jax.numpy.float32),
        "momentum": jax.ShapeDtypeStruct((), jax.numpy.float32),
    }
    _log(f"compiling ViT-L dp={DP} default step (zero3={zero3})...")
    compiled = plan.step_fn.lower(
        s, ring_abs, batch, scalars, jax.random.key(0)).compile()
    return setup, split, compiled, batch, ring_abs


def twin_prefetch_census():
    """The explicit double-buffered stream twin at ViT-L block shapes:
    bf16 stack as a program input, compiled standalone; returns its
    collective census + per-pass stream-byte ledger."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.models import build_backbone
    from dinov3_tpu.models.streaming import (
        cast_stream_leaves,
        make_block_apply,
        streamed_block_scan,
    )
    from dinov3_tpu.ops.block import SelfAttentionBlock
    from dinov3_tpu.parallel.context import set_current_mesh
    from dinov3_tpu.parallel.mesh import MeshSpec, build_mesh
    from dinov3_tpu.parallel.sharding import zero3_leaf_spec
    from dinov3_tpu.utils import hlo_collective_census

    bench = _bench()
    cfg = get_default_config()
    apply_dot_overrides(cfg, bench.build_step_overrides("vit_large", 0))
    mesh = build_mesh(MeshSpec(data=DP))
    set_current_mesh(mesh)
    model = build_backbone(cfg)
    kwargs = model._block_kwargs()
    kwargs["drop_path_rate"] = 0.0  # pass-granularity eval-mode program
    L = model.n_blocks
    D = model.embed_dim
    N = 197  # 196 patch tokens + CLS at 224px/p16

    block = SelfAttentionBlock(**kwargs)
    x_abs = jax.ShapeDtypeStruct((2 * DP, N, D), jnp.bfloat16)
    one_block = jax.eval_shape(
        lambda r: block.init(r, jnp.zeros((1, N, D), jnp.bfloat16)),
        jax.random.key(0))["params"]
    import flax.linen as nn

    one_block = nn.meta.unbox(one_block)
    stack = jax.tree.map(
        lambda p: jax.ShapeDtypeStruct((L,) + tuple(p.shape), p.dtype),
        one_block)
    stack = cast_stream_leaves(stack, jnp.bfloat16)

    def stack_sharding(p):
        spec = zero3_leaf_spec(p.shape, ("layers",) + (None,) *
                               (len(p.shape) - 1), mesh)
        return NamedSharding(mesh, spec if spec is not None else P())

    stack_sh = jax.tree.map(stack_sharding, stack)
    rope = None  # block math w/o rope: the stream bytes are the subject
    apply_fn = make_block_apply(kwargs, rope=rope)

    def run(stack_params, x):
        return streamed_block_scan(apply_fn, stack_params, x, L, mesh)

    with mesh:
        _log("compiling explicit double-buffered stream twin...")
        compiled = jax.jit(
            run, in_shardings=(stack_sh, NamedSharding(mesh, P("data"))),
        ).lower(stack, x_abs).compile()
    census = hlo_collective_census(compiled.as_text())

    stream_bytes = sum(
        math.prod(p.shape) * p.dtype.itemsize
        for p in jax.tree.leaves(stack))
    n_leaves = len(jax.tree.leaves(stack))
    return {
        "collective_census": census,
        "stack_stream_bytes_per_fwd_pass": stream_bytes,
        "stack_param_leaves": n_leaves,
        "n_blocks": L,
        "note": (
            "explicit twin (models/streaming.py): bf16 stack is a "
            "program input sharded per zero3_leaf_spec; every in-loop "
            "all-gather is zero3_prefetch-scoped = issued one block of "
            "compute ahead of its consumer; the priming gather of "
            "block 0 is zero3_gather-scoped outside the loop. "
            "stack_stream_bytes_per_fwd_pass = full bf16 stack moved "
            "once per direction (the engine re-gathers in backward "
            "under remat)."
        ),
    }


def vit7b_dryrun():
    """Compile the ViT-7B zero3 recipe end-to-end on 8 simulated
    devices from the abstract state; commit the per-device accounting."""
    import jax

    from dinov3_tpu.configs import load_config
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = load_config(os.path.join(repo, "configs/train/vit7b16_zero3.yaml"))
    B = int(cfg.train.batch_size_per_device) * DP
    batch_np = make_synthetic_batch(cfg, B, seed=0)
    batch = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
             for k, v in batch_np.items()}
    _log("building ViT-7B abstract setup (zero3)...")
    setup = build_train_setup(cfg, batch_np, init_state=False)
    assert setup.zero3

    s, sh = setup.state, setup.state_shardings
    split = {
        "params_student": tree_split(s.params["student"],
                                     sh.params["student"]),
        "params_teacher": tree_split(s.params["teacher"],
                                     sh.params["teacher"]),
        "opt_state": tree_split(s.opt_state, sh.opt_state),
    }
    # the pin: a "zero3" 7B artifact whose masters report replicated is
    # an accounting bug, not a result
    for k in ("params_student", "params_teacher"):
        frac = split[k]["replicated_fraction"]
        assert frac < 0.05, f"7B {k} replicated_fraction={frac:.3f}"

    scalars = {
        "teacher_temp": jax.ShapeDtypeStruct((), jax.numpy.float32),
        "momentum": jax.ShapeDtypeStruct((), jax.numpy.float32),
    }
    _log("compiling ViT-7B dp=8 step (compile-only dryrun; this is the "
         "unlock deliverable)...")
    compiled = setup.step_fn.lower(
        s, batch, scalars, jax.random.key(0)).compile()
    mem_an = None
    try:
        an = compiled.memory_analysis()
        if an is not None:
            mem_an = {
                k: int(getattr(an, k))
                for k in ("argument_size_in_bytes", "output_size_in_bytes",
                          "temp_size_in_bytes", "alias_size_in_bytes")
                if hasattr(an, k)
            } or None
    except Exception as e:  # noqa: BLE001 - backend without the analysis
        mem_an = {"error": str(e)[:200]}
    n_params = sum(
        math.prod(l.shape)
        for l in jax.tree.leaves(s.params["student"]))
    return {
        "config": "configs/train/vit7b16_zero3.yaml",
        "arch": "vit_7b",
        "dp": DP,
        "n_student_params": n_params,
        "compiled": True,
        "per_device_state": split,
        "state_bytes_per_device_total": sum(
            v["per_device_bytes"] for v in split.values()),
        "replicated_equivalent_bytes_per_device": sum(
            v["full_bytes"] for v in split.values()),
        "xla_memory_analysis": mem_an,
    }


def main():
    from dinov3_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()
    from dinov3_tpu.utils import hlo_collective_census

    arms = {}
    mem_arms = {}
    for name, z in (("zero3", True), ("replicated", False)):
        setup, split, compiled, batch, ring_abs = build_arm(z)
        text = compiled.as_text()
        census = hlo_collective_census(text)
        masters = (split["params_student"]["per_device_bytes"]
                   + split["params_teacher"]["per_device_bytes"])
        arms[name] = {
            "per_device_state": split,
            "master_bytes_per_device": masters,
            "state_bytes_per_device_total": sum(
                v["per_device_bytes"] for v in split.values()),
            "collective_census": census,
        }
        mem_an = None
        try:
            an = compiled.memory_analysis()
            if an is not None:
                mem_an = {
                    k: int(getattr(an, k))
                    for k in ("argument_size_in_bytes",
                              "output_size_in_bytes", "temp_size_in_bytes",
                              "alias_size_in_bytes")
                    if hasattr(an, k)
                } or None
        except Exception as e:  # noqa: BLE001
            mem_an = {"error": str(e)[:200]}
        mem_arms[name] = {
            "bytes_in_use_per_device": {
                **{k: v["per_device_bytes"] for k, v in split.items()},
                "state_total": sum(
                    v["per_device_bytes"] for v in split.values()),
            },
            "replicated_fraction": {
                k: round(v["replicated_fraction"], 4)
                for k, v in split.items()},
            "xla_memory_analysis": mem_an,
        }
        del setup, compiled

    # the zero3 arm pin: masters must actually be sharded in the artifact
    for k in ("params_student", "params_teacher"):
        frac = arms["zero3"]["per_device_state"][k]["replicated_fraction"]
        assert frac < 0.05, f"zero3 {k} replicated_fraction={frac:.3f}"
    z3 = arms["zero3"]
    rep = arms["replicated"]
    # every all-gather of the zero3 step attributed (by class always;
    # the scope table must carry the engine categories)
    assert z3["collective_census"]["unattributed"] == 0
    master_red = 100.0 * (1 - z3["master_bytes_per_device"]
                          / rep["master_bytes_per_device"])

    twin = twin_prefetch_census()
    pf = twin["collective_census"]["prefetch_overlap"]
    assert pf["prefetch_scoped_ops"] >= twin["stack_param_leaves"], (
        "twin prefetch gathers missing from census", pf)

    rec = {
        "arch": "vit_large",
        "dp": DP,
        "per_chip_batch": 12,
        "arms": arms,
        "master_weight_state_reduction_pct": round(master_red, 1),
        "state_total_reduction_pct": round(
            100.0 * (1 - z3["state_bytes_per_device_total"]
                     / rep["state_bytes_per_device_total"]), 1),
        "prefetch_twin": twin,
        "source": "shardings+hlo_census (8 simulated CPU devices, "
                  "compile-only; PR-1..6 pass-granularity discipline)",
    }
    if not SKIP_7B:
        rec["vit7b_unlock"] = vit7b_dryrun()

    with open(COST_OUT, "w") as f:
        json.dump(rec, f, indent=1)
    _log(f"wrote {COST_OUT}")

    mem = {
        "arch": "vit_large",
        "dp": DP,
        "per_chip_batch": 12,
        "arms": mem_arms,
        "source": "shardings+memory_analysis",
        "note": (
            "compile-only dryrun on 8 simulated CPU devices "
            "(build_train_setup(init_state=False)), both arms "
            "train.scan_layers=true: bytes-in-use from the "
            "NamedShardings the setup assigned. The replicated arm is "
            "the MEM_r11 before-picture (student+teacher fp32 masters "
            "full-size per device, ZeRO-1 flat moments 1/dp); the "
            "zero3 arm is the after-picture — masters, EMA teacher and "
            "moments all ~1/dp per device, replicated_fraction pinned "
            "near 0 so this artifact cannot silently report the "
            "replicated footprint (telemetry/memory.layout_split). "
            "XLA:CPU temp_size stays an UNSCHEDULED upper bound; "
            "on-chip peaks come from device.memory_stats() via the phW "
            "bench records."
        ),
    }
    if "vit7b_unlock" in rec:
        mem["vit7b"] = rec["vit7b_unlock"]["per_device_state"]
    with open(MEM_OUT, "w") as f:
        json.dump(mem, f, indent=1)
    _log(f"wrote {MEM_OUT}")
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
