"""Compat wrapper: the op-level flash-vs-dense crossover harness moved
to scripts/crossover_attention.py (importable measurement functions +
the executable ``recommended_flash_min_seq`` threshold definition,
CPU-collectable test in tests/test_crossover_attention.py). This entry
point keeps older queue scripts working.

Usage: python scripts/bench_attention_crossover.py [out.jsonl]
"""

from __future__ import annotations

import importlib.util
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_spec = importlib.util.spec_from_file_location(
    "crossover_attention", os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "crossover_attention.py")
)
_mod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_mod)

main = _mod.main

if __name__ == "__main__":
    main()
