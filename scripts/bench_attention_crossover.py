"""Op-level flash-vs-dense attention crossover on the real chip.

The full-step high-res benches compile for 20-40+ min through the axon
tunnel helper and have wedged it twice; this measures the SAME dispatch
decision (``dinov3_tpu/ops/attention.py FLASH_MIN_SEQ``) with tiny
programs that compile in seconds: fwd+bwd of dense-XLA vs Pallas-flash
attention at the token counts the recipes actually produce
(224px->201, 512px->1029, 518px->1054, 768px->2309, plus 4096).

Prints one JSON line per (N, impl) with ms/call, and a final crossover
summary. Usage: python scripts/bench_attention_crossover.py [out.jsonl]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from dinov3_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")

    from dinov3_tpu.ops.attention import xla_attention

    out_path = sys.argv[1] if len(sys.argv) > 1 else "/tmp/attn_crossover.jsonl"
    out = open(out_path, "a")

    # ViT-L geometry: 16 heads x 64 head_dim; B chosen so B*N is roughly
    # the 224px global-crop workload (16 seqs x 201 tokens) per call
    H, D = 16, 64
    cases = [
        (16, 201), (4, 1029), (4, 1054), (2, 2309), (1, 4096),
    ]
    if os.environ.get("XOVER_MAX_N"):  # CPU smoke: skip the big cases
        cases = [c for c in cases if c[1] <= int(os.environ["XOVER_MAX_N"])]
    steps = int(os.environ.get("XOVER_STEPS", "20"))
    warmup = 3
    results = {}
    for B, N in cases:
        q, k, v = (
            jax.random.normal(jax.random.key(i), (B, N, H, D), jnp.bfloat16)
            for i in range(3)
        )
        for impl in ("xla", "pallas"):
            if impl == "pallas":
                try:
                    from dinov3_tpu.ops.flash_attention import flash_attention
                except ImportError:
                    continue

                def fwd(q, k, v):
                    return flash_attention(q, k, v)
            else:

                def fwd(q, k, v):
                    return xla_attention(q, k, v, probs_dtype=jnp.bfloat16)

            # fwd+bwd like the train step sees it
            f = jax.jit(jax.grad(
                lambda q, k, v: jnp.sum(fwd(q, k, v).astype(jnp.float32)),
                argnums=(0, 1, 2),
            ))

            # Synchronize via a value fetch, NOT block_until_ready: the
            # tunneled-TPU transport can return from block_until_ready at
            # enqueue time (bench.py measure loop has the same note), which
            # made the r5 first-pass numbers ~70x faster than the chip's
            # bf16 peak. The fetched scalar forces the whole chain.
            def sync(g):
                return float(jnp.sum(g[0].astype(jnp.float32)))

            try:
                t0 = time.time()
                sync(f(q, k, v))
                compile_s = time.time() - t0
                for _ in range(warmup):
                    g = f(q, k, v)
                sync(g)
                t0 = time.perf_counter()
                for _ in range(steps):
                    g = f(q, k, v)
                sync(g)
                ms = (time.perf_counter() - t0) / steps * 1e3
            except Exception as e:  # noqa: BLE001 - record and continue
                rec = {"B": B, "N": N, "impl": impl, "error": str(e)[:200]}
                print(json.dumps(rec)); out.write(json.dumps(rec) + "\n")
                continue
            rec = {"B": B, "N": N, "impl": impl, "ms": round(ms, 3),
                   "compile_s": round(compile_s, 1)}
            results[(B, N, impl)] = ms
            print(json.dumps(rec), flush=True)
            out.write(json.dumps(rec) + "\n"); out.flush()

    summary = []
    for B, N in cases:
        a, b = results.get((B, N, "xla")), results.get((B, N, "pallas"))
        if a and b:
            summary.append({"N": N, "xla_ms": round(a, 3),
                            "flash_ms": round(b, 3),
                            "flash_speedup": round(a / b, 3)})
    line = json.dumps({"crossover": summary})
    print(line, flush=True)
    out.write(line + "\n")


if __name__ == "__main__":
    main()
