"""Per-device byte + collective accounting for the cross-replica
sharded update engine (train/fused_update.py make_sharded_update) vs
the replicated fused oracle, on a SIMULATED multi-device mesh.

Methodology (the PR-1/2/3/4 discipline — compile the exact programs on
the host backend, account from their compiled HLO; stated precisely
because this is the committed evidence in docs/PERFORMANCE.md):

- Both arms are compiled at PASS GRANULARITY as standalone update-phase
  programs over ``dp`` simulated CPU devices, taking [dp, *leaf] STACKS
  of per-replica partial gradients (dim 0 sharded over the data axis —
  exactly what the data-parallel backward holds before any grad sync),
  so the grad synchronization collective is INSIDE the measured program
  for both arms instead of hiding in a backward pass this script does
  not compile.
- The REPLICATED arm sums the partials (GSPMD lowers it as the grad
  all-reduce) and runs the fused single-pass engine over the complete
  master/moment/teacher trees on every replica — the pre-PR-5 default.
- The SHARDED arm is ``make_sharded_update_schedule``: the same
  schedule with its collectives spelled out — psum_scatter
  (reduce-scatter) of each leaf's partials, shard-local single-pass
  clip+AdamW+EMA over 1/dp of every leaf (clip norms as shard-local
  partials + ONE small psum), all-gather of the updated student + EMA'd
  teacher. The in-step engine expresses the identical schedule through
  GSPMD "update_shard" annotations; this container's XLA:CPU lowers
  that form as all-reduce + fused dynamic-slice (recorded here under
  ``engine_gspmd_census`` for honesty — it is reduce-scatter's
  pre-rewrite form, which the TPU/GPU collective optimizer rewrites;
  the schedule program is the committed proof of the post-rewrite
  collective set, and tests/test_sharded_update.py pins that it
  computes the identical update).
- ``cost_analysis()['bytes accessed']`` of an SPMD-partitioned module
  is PER-DEVICE (the module is the per-device program).
  ``weight_shaped_bytes`` subtracts the collective result bytes
  (utils.hlo_collective_census) from that total, isolating the
  elementwise master/moment/teacher traffic each replica streams.
- The collective census must show: replicated arm = all_reduce only;
  sharded arm = reduce_scatter + all_gather + the small clip psum
  (all_reduce bytes ~scalar), and ZERO unattributed collectives.

One JSON line on stdout -> commit as COST_SHUP_r10.json.

Usage: JAX_PLATFORMS=cpu python scripts/cost_sharded_update.py \
           [arch] [dp]      (defaults: vit_large 8)
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DP = int(sys.argv[2]) if len(sys.argv) > 2 else 8

# the simulated device count must be pinned before jax initializes
os.environ.setdefault("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += f" --xla_force_host_platform_device_count={DP}"

import importlib.util

_spec = importlib.util.spec_from_file_location(
    "bench", os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def _compiled(fn, args, mesh, in_shardings, out_shardings=None, donate=()):
    import jax

    with mesh:
        return jax.jit(
            fn, in_shardings=in_shardings, out_shardings=out_shardings,
            donate_argnums=donate,
        ).lower(*args).compile()


def _bytes(compiled) -> float:
    analysis = compiled.cost_analysis()
    if isinstance(analysis, list):
        analysis = analysis[0]
    return float(analysis["bytes accessed"])


def measure(cfg, dp: int) -> dict:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dinov3_tpu.parallel.context import set_current_mesh
    from dinov3_tpu.parallel.mesh import MeshSpec, build_mesh
    from dinov3_tpu.parallel.sharding import UPDATE_SHARD_AXES
    from dinov3_tpu.train import (
        build_multiplier_trees,
        build_schedules,
        make_fused_update,
        make_sharded_update,
        make_sharded_update_schedule,
    )
    from dinov3_tpu.train.fused_update import (
        leaf_size,
        padded_flat_size,
        sharded_adam_zeros,
    )
    from dinov3_tpu.train.optimizer import ScheduledAdamWState
    from dinov3_tpu.train.ssl_meta_arch import SSLMetaArch
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.utils import hlo_collective_census

    import flax.linen as nn
    import optax

    mesh = build_mesh(MeshSpec(data=dp))
    set_current_mesh(mesh)
    meta = SSLMetaArch(cfg)
    batch = {k: jnp.asarray(v)
             for k, v in make_synthetic_batch(cfg, 1, seed=0).items()}
    student = jax.eval_shape(
        lambda r: meta.init_params(r, batch), jax.random.key(0)
    )["student"]
    schedules = build_schedules(cfg)
    lm, wm, isll = build_multiplier_trees(
        student,
        layerwise_decay=cfg.optim.layerwise_decay,
        patch_embed_lr_mult=cfg.optim.patch_embed_lr_mult,
        dino_head_wd_multiplier=cfg.optim.dino_head_wd_multiplier,
    )
    kw = dict(b1=cfg.optim.adamw_beta1, b2=cfg.optim.adamw_beta2,
              clip_grad=cfg.optim.clip_grad, ema=True)
    fused = make_fused_update(schedules, lm, wm, isll, **kw)
    sharded = make_sharded_update(schedules, lm, wm, isll, mesh, **kw)
    schedule = make_sharded_update_schedule(schedules, lm, wm, isll, mesh,
                                            **kw)

    rep = NamedSharding(mesh, P())
    axes = tuple(a for a in UPDATE_SHARD_AXES if a in mesh.shape)
    stacks = NamedSharding(mesh, P(axes))
    gstack = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct((dp,) + l.shape, l.dtype), student)
    opt_rep = jax.eval_shape(
        lambda p: ScheduledAdamWState(
            jnp.zeros((), jnp.int32),
            optax.ScaleByAdamState(jnp.zeros((), jnp.int32),
                                   jax.tree.map(jnp.zeros_like, p),
                                   jax.tree.map(jnp.zeros_like, p))),
        student)
    opt_sh = jax.eval_shape(
        lambda p: ScheduledAdamWState(
            jnp.zeros((), jnp.int32),
            optax.ScaleByAdamState(
                jnp.zeros((), jnp.int32),
                nn.meta.unbox(sharded_adam_zeros(p, dp)),
                nn.meta.unbox(sharded_adam_zeros(p, dp)))),
        student)
    momentum = jax.ShapeDtypeStruct((), jnp.float32)
    rep_tree = jax.tree.map(lambda _: rep, student)
    stack_tree = jax.tree.map(lambda _: stacks, gstack)
    opt_rep_sh = jax.tree.map(lambda _: rep, opt_rep)
    opt_sh_sh = ScheduledAdamWState(
        rep, optax.ScaleByAdamState(
            rep,
            jax.tree.map(lambda _: stacks, opt_sh.adam.mu),
            jax.tree.map(lambda _: stacks, opt_sh.adam.nu)))

    def replicated_arm(gs, p, t, s, m):
        g = jax.tree.map(lambda x: jnp.sum(x, 0), gs)  # the grad all-reduce
        return fused(g, p, t, s, m)[:3]

    def sharded_arm(gs, p, t, s, m):
        return schedule(gs, p, t, s, m)[:3]

    def engine_arm(gs, p, t, s, m):
        # the in-step GSPMD-annotation engine, for its structural census
        g = jax.tree.map(lambda x: jnp.sum(x, 0), gs)
        return sharded(g, p, t, s, m)[:3]

    args_rep = (gstack, student, student, opt_rep, momentum)
    args_sh = (gstack, student, student, opt_sh, momentum)
    in_rep = (stack_tree, rep_tree, rep_tree, opt_rep_sh, rep)
    in_sh = (stack_tree, rep_tree, rep_tree, opt_sh_sh, rep)
    c_rep = _compiled(replicated_arm, args_rep, mesh, in_rep,
                      out_shardings=(rep_tree, rep_tree, opt_rep_sh),
                      donate=(1, 2, 3))
    c_sh = _compiled(sharded_arm, args_sh, mesh, in_sh,
                     out_shardings=(rep_tree, rep_tree, opt_sh_sh),
                     donate=(1, 2, 3))
    c_eng = _compiled(engine_arm, args_sh, mesh, in_sh,
                      out_shardings=(rep_tree, rep_tree, opt_sh_sh),
                      donate=(1, 2, 3))

    census_rep = hlo_collective_census(c_rep.as_text())
    census_sh = hlo_collective_census(c_sh.as_text())
    census_eng = hlo_collective_census(c_eng.as_text())
    b_rep, b_sh = _bytes(c_rep), _bytes(c_sh)
    w_rep = b_rep - census_rep["hlo_collective_bytes"]
    w_sh = b_sh - census_sh["hlo_collective_bytes"]

    n_params = sum(leaf_size(l) for l in jax.tree.leaves(student))
    n_padded = sum(padded_flat_size(leaf_size(l), dp)
                   for l in jax.tree.leaves(student))
    return {
        "dp": dp,
        "n_params": n_params,
        "n_padded": n_padded,
        "pad_waste_pct": round(100.0 * (n_padded - n_params) / n_params, 4),
        "bytes_per_device": {"replicated": b_rep, "sharded": b_sh},
        "weight_shaped_bytes_per_device": {
            "replicated": w_rep, "sharded": w_sh},
        "weight_shaped_reduction_pct": round(100.0 * (1.0 - w_sh / w_rep), 1),
        "total_reduction_pct": round(100.0 * (1.0 - b_sh / b_rep), 1),
        "collective_census": {
            "replicated": census_rep, "sharded": census_sh},
        "engine_gspmd_census": census_eng,
    }


def main():
    from dinov3_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()
    import jax

    try:
        jax.config.update("jax_num_cpu_devices", DP)
    except AttributeError:
        pass  # XLA_FLAGS set above covers old jaxlibs
    from dinov3_tpu.configs import apply_dot_overrides, get_default_config

    arch = sys.argv[1] if len(sys.argv) > 1 else "vit_large"
    cfg = get_default_config()
    apply_dot_overrides(cfg, bench.build_step_overrides(arch, 0))
    rec = {"arch": arch}
    rec.update(measure(cfg, DP))
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
