"""Low-precision training-arm accounting: the committed evidence
behind COST_LP_r21.json (PR-1..6 discipline — compile the exact
shipped code paths, account from their compiled HLO, execute real
steps for the numerics story).

The fp8/int8 arms (train.low_precision, ops/lowp.py) quantize the
attn/mlp block matmul KERNELS per-tensor with delayed scaling and ride
the ZeRO-3 in-loop weight stream with 1-byte codes: under a lowp arm
the castable kernel leaves stay fsdp-sharded through the stream hook,
``lowp_matmul`` quantizes shard-local and gathers the code tensor
under the SAME ``zero3_stream`` named scope — identical collective
COUNTS, roughly half the streamed kernel BYTES vs the bf16 stream.
Masters, Adam moments, norms/biases and the EMA teacher storage stay
untouched; biases keep the plain bf16 stream.

Three instruments, all on the 2x4 (data x fsdp) 8-simulated-device
CPU mesh with the shipped ``build_train_setup`` step:

- **Streamed-collective census per arm**: compile the full train step
  on each arm and read the ``zero3_stream`` scope from
  ``hlo_collective_census`` — the pins are identical in-loop gather
  counts across arms, streamed bytes reduced >= 1.8x on the quantized
  arms, and zero unattributed collectives (the new ``lowp_amax`` /
  ``lowp_dequant`` scopes attribute their own collectives).
- **Executed loss trajectories per arm**: N real steps per arm from
  the same init seed; the quantized arms must track the bf16
  trajectory within the documented per-step relative tolerance, and
  the setup drift probe (``lowp_drift_probe``) must sit under
  ``train.low_precision.divergence_tol``.
- **bf16 bitwise control**: the default config (no low_precision
  overrides) and an explicit ``arm=bf16`` config (with a different
  amax_history_len, which the bf16 arm must ignore) must produce
  bitwise-identical loss trajectories — the default arm is the PR-16
  program, untouched.

Honesty caveat (docs/PERFORMANCE.md): XLA:CPU emulates fp8/int8 dot
products by upconversion, so this artifact prices BYTES and pins
NUMERICS; the speed story is the phQ on-chip A/B (scripts/r6_queue.sh).

One JSON record -> COST_LP_r21.json (argv[1], default
./COST_LP_r21.json); also printed to stdout. ``--smoke`` runs the
CI-sized variant (fewer steps, same asserts, no JSON write unless an
out path is given explicitly).

Usage: JAX_PLATFORMS=cpu python scripts/cost_lowp.py [out] [--smoke]
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMOKE = "--smoke" in sys.argv
_pos = [a for a in sys.argv[1:] if not a.startswith("--")]
OUT = _pos[0] if _pos else (None if SMOKE else "COST_LP_r21.json")
DATA, FSDP = 2, 4
DP = DATA * FSDP
N_STEPS = 3 if SMOKE else 8  # 8 clears the SMOL 4-step LR warmup
# per-step relative loss-trajectory tolerance of the quantized arms vs
# bf16 (tiny vit_test shapes quantize COARSER than ViT-L: per-tensor
# scales over 32-dim kernels; the committed artifact records the
# measured max next to this bound)
LOSS_RTOL = 0.10

os.environ.setdefault("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += f" --xla_force_host_platform_device_count={DP}"

# the SMOL dryrun shape (tests/test_zero3.py convention)
SMOL = [
    "student.arch=vit_test", "student.patch_size=4",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2", "train.batch_size_per_device=2",
    "optim.scaling_rule=none", "train.scan_layers=true",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
    "dino.head_bottleneck_dim=16",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
    "ibot.head_bottleneck_dim=16",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "optim.warmup_epochs=1",
    "telemetry.async_metrics=false",
]
MESH_OVR = ["parallel.data=2", "parallel.fsdp=4", "parallel.zero3=true"]


def _log(msg):
    print(f"[cost_lowp] {msg}", file=sys.stderr, flush=True)


def arm_step(arm_overrides, n_steps: int, trace: bool = False) -> dict:
    """Build the shipped train step under ``arm_overrides``, census its
    compiled HLO, and run ``n_steps`` real steps recording the loss
    trajectory (same synthetic batch + rng on every arm). With
    ``trace``, re-run two steps under the profiler and join the trace
    against the compiled HLO (telemetry/anatomy.py) — the
    ``unattributed_collective_ms`` pin reads from that ledger."""
    import jax
    import jax.numpy as jnp

    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup
    from dinov3_tpu.train.setup import put_batch
    from dinov3_tpu.utils import hlo_collective_census

    cfg = get_default_config()
    apply_dot_overrides(cfg, SMOL + MESH_OVR + list(arm_overrides))
    batch = {k: jnp.asarray(v)
             for k, v in make_synthetic_batch(cfg, DP * 2, seed=0).items()}
    setup = build_train_setup(cfg, batch)
    assert setup.zero3, "lowp arms ride the zero3 stream"
    dbatch = put_batch(batch, setup.batch_shardings)
    _log(f"compiling step for {list(arm_overrides) or ['<default>']}...")
    compiled = setup.step_fn.lower(
        setup.state, dbatch, setup.scalars(0), jax.random.key(0)).compile()
    txt = compiled.as_text()
    census = hlo_collective_census(txt)
    losses = []
    state = setup.state
    for i in range(n_steps):
        state, metrics = setup.step_fn(
            state, dbatch, setup.scalars(i), jax.random.key(0))
        losses.append(float(metrics["total_loss"]))
    anatomy = None
    if trace:
        import tempfile

        from dinov3_tpu.telemetry import (
            anatomy_ledger,
            find_trace_file,
            ledger_summary,
            load_trace,
        )

        tdir = tempfile.mkdtemp(prefix="cost_lp_trace_", dir="/tmp")
        n_trace = 2
        jax.profiler.start_trace(tdir)
        try:
            for i in range(n_trace):
                state, metrics = setup.step_fn(
                    state, dbatch, setup.scalars(i), jax.random.key(0))
            float(metrics["total_loss"])
        finally:
            jax.profiler.stop_trace()
        summ = ledger_summary(anatomy_ledger(
            load_trace(find_trace_file(tdir)), hlo_text=txt,
            n_steps=n_trace))
        anatomy = {
            "unattributed_collective_ms": summ["unattributed_collective_ms"],
            "collective_scopes": sorted(summ["collectives"]),
        }
    scope = census["by_scope"]
    return {
        "anatomy": anatomy,
        "arm": setup.lowp_arm,
        "drift_probe": setup.lowp_drift,
        "loss_trajectory": losses,
        "stream_scope": scope.get("zero3_stream", {"ops": 0, "bytes": 0}),
        "lowp_scopes": {k: scope[k] for k in ("lowp_amax", "lowp_dequant")
                        if k in scope},
        "unattributed": census["unattributed"],
        "collective_total": census["hlo_collective_total"],
        # engagement proof: the dequant epilogue's named scope stamped
        # into the compiled program's op_names — nonzero on the
        # quantized arms, exactly zero on the inert bf16 default
        "lowp_dequant_scope_lines": txt.count("lowp_dequant"),
        "collective_census": census,
    }


def main():
    from dinov3_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()
    import math

    from dinov3_tpu.configs import get_default_config
    from dinov3_tpu.configs.config import lowp_cfg

    tol = lowp_cfg(get_default_config())["divergence_tol"]

    # ---- the three precision arms + the bf16 bitwise control ----
    arms = {
        "bf16": arm_step([], N_STEPS),
        "fp8": arm_step(["train.low_precision.arm=fp8"], N_STEPS,
                        trace=True),
        "int8": arm_step(["train.low_precision.arm=int8"], N_STEPS,
                         trace=True),
    }
    # explicit arm=bf16 with a non-default ring length: the bf16 arm
    # must IGNORE the low_precision block entirely (no rings, no drift
    # probe, the PR-16 program bitwise)
    control = arm_step(
        ["train.low_precision.arm=bf16",
         "train.low_precision.amax_history_len=4"], N_STEPS)

    # ---- acceptance pins (ISSUE 17) ----
    bf16 = arms["bf16"]
    assert bf16["arm"] == "bf16" and bf16["drift_probe"] is None
    assert bf16["lowp_dequant_scope_lines"] == 0
    assert control["loss_trajectory"] == bf16["loss_trajectory"], (
        "bf16 arm is not bitwise-inert",
        control["loss_trajectory"], bf16["loss_trajectory"])
    trajectory_rel = {}
    for name in ("fp8", "int8"):
        rec = arms[name]
        assert rec["arm"] == name
        # zero unattributed collectives: every collective the lowp path
        # adds lands in a registered engine scope
        assert rec["unattributed"] == 0, (name, rec["unattributed"])
        assert bf16["unattributed"] == 0
        # quantized-matmul engagement: the dequant epilogue is IN the
        # compiled program (the has_variable guard makes a silently
        # inert arm a real failure mode — this pin catches it)
        assert rec["lowp_dequant_scope_lines"] > 0, name
        # measured-trace attribution: every collective event of the
        # quantized arm's executed steps joins an HLO op the ledger can
        # place — no unattributed collective time
        assert rec["anatomy"]["unattributed_collective_ms"] == 0, (
            name, rec["anatomy"])
        # identical streamed-gather COUNTS: the code gathers ride the
        # same zero3_stream schedule, one per kernel per use
        assert rec["stream_scope"]["ops"] == bf16["stream_scope"]["ops"], (
            name, rec["stream_scope"], bf16["stream_scope"])
        # streamed BYTES reduced >= 1.8x: 1-byte codes vs the bf16
        # stream on the kernel gathers (biases keep bf16, diluting the
        # ratio below the pure-kernel 2x)
        ratio = bf16["stream_scope"]["bytes"] / max(
            rec["stream_scope"]["bytes"], 1)
        rec["stream_bytes_ratio_vs_bf16"] = round(ratio, 4)
        assert ratio >= 1.8, (name, ratio)
        # the setup drift probe ran and sits under the guardrail gate
        assert rec["drift_probe"] is not None
        assert rec["drift_probe"]["max"] < tol, (name, rec["drift_probe"])
        # quantized loss trajectory tracks bf16 within the documented
        # per-step relative tolerance
        rel = [abs(a - b) / max(abs(b), 1e-9) for a, b in
               zip(rec["loss_trajectory"], bf16["loss_trajectory"])]
        assert all(math.isfinite(r) for r in rel)
        trajectory_rel[name] = float(f"{max(rel):.3g}")
        assert max(rel) < LOSS_RTOL, (name, rel)

    rec = {
        "what": ("fp8/int8 low-precision training arms: per-tensor "
                 "delayed-scaling block-matmul quantization riding the "
                 "zero3 weight stream with 1-byte code gathers"),
        "arch": "vit_test",
        "mesh": {"data": DATA, "fsdp": FSDP},
        "n_steps": N_STEPS,
        "loss_rtol_bound": LOSS_RTOL,
        "trajectory_rel_max": trajectory_rel,
        "divergence_tol": tol,
        "bf16_bitwise_control": True,
        "arms": {k: {kk: vv for kk, vv in v.items()
                     if kk != "collective_census"}
                 for k, v in arms.items()},
        "stream_bytes": {k: arms[k]["stream_scope"]["bytes"]
                         for k in arms},
        "stream_ops": {k: arms[k]["stream_scope"]["ops"] for k in arms},
        "note": (
            "XLA:CPU emulates fp8/int8 dot products by upconversion — "
            "this artifact prices the streamed-collective BYTES and "
            "pins the NUMERICS (trajectories, drift probe, bitwise "
            "bf16 control); the speed story is the phQ on-chip A/B "
            "(scripts/r6_queue.sh). This container's XLA:CPU also "
            "float-normalizes the bf16 stream's gathers to f32 (the "
            "phW caveat), so the int8 byte ratio here overstates the "
            "on-chip 2x while fp8 lands at ~2x either way; the "
            "identical-count pin and the >=1.8x floor are "
            "backend-independent"),
        "source": ("hlo_census + executed steps of the shipped "
                   "build_train_setup program per precision arm "
                   f"(2x4 data x fsdp simulated CPU mesh, {N_STEPS} "
                   "steps executed per arm)"),
    }
    if OUT:
        with open(OUT, "w") as f:
            json.dump(rec, f, indent=1)
        _log(f"wrote {OUT}")
    print(json.dumps({k: v for k, v in rec.items() if k != "arms"}))
    if SMOKE:
        _log("smoke OK: equal stream counts, >=1.8x streamed-byte "
             "reduction, zero unattributed, trajectories in tolerance, "
             "bf16 arm bitwise-inert")


if __name__ == "__main__":
    main()
