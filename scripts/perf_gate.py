"""Noise-aware perf-regression gate over the committed anatomy baseline.

Compares a fresh anatomy/bench record against a committed baseline
artifact (ANATOMY_r17.json by default) and FAILS (rc=1) on step-time or
exposed-comm regressions beyond a calibrated tolerance — the CI teeth
of the step-anatomy plane: a PR that silently de-overlaps a collective
schedule or bloats the step now trips a gate instead of a reviewer's
eyeball.

Tolerance calibration (noise-aware, not a bare percentage): the
baseline's own per-step wall-time spread sets the noise floor —
``tol_rel = clamp(K * cv / sqrt(n), TOL_FLOOR, TOL_CAP)`` where ``cv``
is the baseline window's coefficient of variation (std/mean over its
traced steps) and ``n`` its step count. A quiet baseline gates tightly
(floor 3%), a noisy one gates loosely but never beyond the 8% cap — the
cap guarantees the acceptance property that a 10% step-time regression
ALWAYS fails. Exposed-comm is gated on ABSOLUTE fraction drift
(``+EXPOSED_TOL`` over baseline, default 0.05): a schedule that stops
hiding its comm moves this number by tens of points, and an absolute
gate is immune to tiny-denominator blowups.

Record formats accepted on both sides (auto-detected):
- ANATOMY_r17.json (``arms.<arm>.anatomy`` summaries) — gates every
  arm present in BOTH records;
- a bare ``anatomy-summary/v1`` dict, or a bench.py --trace JSONL
  record carrying one under ``"anatomy"`` — gates as a single arm.

Usage:
  python scripts/perf_gate.py --baseline ANATOMY_r17.json --fresh X.json
  python scripts/perf_gate.py --self-check [--baseline ANATOMY_r17.json]
  python scripts/perf_gate.py --tuned-vs-handset [--baseline TUNED_r20.json]

--self-check (the CI invocation): gates the committed baseline against
ITSELF (must pass — same numbers, zero drift), then against synthetic
perturbations (x1.10 step time, +0.10 exposed fraction — both must
fail). rc=0 only when all three behave.

--tuned-vs-handset: reads a TUNED_*.json plan artifact
(scripts/tune_collectives.py) and gates every arm's TUNED measurement
against its HAND-SET measurement — step wall AND the tuner objective
(wall + exposed collective ms), each under the noise-calibrated
step-time tolerance. NOT the exposed-fraction check: across two
different schedules a smaller wall raises the fraction even when
exposed ms shrank too (see tuned_vs_handset). The acceptance property:
the resolved plan is never worse than the hand-set oracle on any arm
(replicated/flat/bucketed/zero3/unified). Plan-invariant arms (the
schedule knobs do not enter their programs) gate trivially by
construction and are reported as such.
"""

from __future__ import annotations

import copy
import json
import math
import sys

TOL_FLOOR = 0.03   # tightest step-time gate even on a silent baseline
TOL_CAP = 0.08     # loosest gate ever allowed — keeps 10% regressions failing
NOISE_K = 3.0      # z-like multiplier on the baseline's mean-level noise
EXPOSED_TOL = 0.05  # absolute exposed-comm-fraction drift allowed


def step_time_tolerance(summary: dict) -> float:
    """Relative step-time tolerance calibrated from the baseline
    window's own noise (see module doc)."""
    wall = summary.get("step_wall_ms") or {}
    mean = float(wall.get("mean", 0.0) or 0.0)
    std = float(wall.get("std", 0.0) or 0.0)
    n = max(1, int(summary.get("n_steps", 1) or 1))
    cv = std / mean if mean > 0 else 0.0
    return min(TOL_CAP, max(TOL_FLOOR, NOISE_K * cv / math.sqrt(n)))


def extract_summaries(rec: dict) -> dict:
    """{arm_name: anatomy summary} from any accepted record shape."""
    if "arms" in rec:
        return {arm: blk["anatomy"] for arm, blk in rec["arms"].items()
                if isinstance(blk, dict) and "anatomy" in blk}
    if "anatomy" in rec and isinstance(rec["anatomy"], dict):
        return {"bench": rec["anatomy"]}
    if rec.get("schema") == "anatomy-summary/v1" or "step_wall_ms" in rec:
        return {"record": rec}
    raise ValueError(
        "unrecognized record: expected an ANATOMY artifact ('arms'), a "
        "bench --trace record ('anatomy'), or a bare anatomy summary")


def gate(baseline: dict, fresh: dict) -> dict:
    """Compare two records; returns {passed, checks: [...]} with one
    check row per (arm, metric). Arms present in only one record are
    skipped (reported, not failed — program sets may legitimately
    differ across artifact revisions)."""
    base = extract_summaries(baseline)
    new = extract_summaries(fresh)
    checks = []
    for arm in sorted(base):
        if arm not in new:
            checks.append({"arm": arm, "metric": "presence",
                           "status": "skipped (absent in fresh record)"})
            continue
        b, f = base[arm], new[arm]
        b_ms = float(b["step_wall_ms"]["mean"])
        f_ms = float(f["step_wall_ms"]["mean"])
        tol = step_time_tolerance(b)
        ratio = f_ms / b_ms if b_ms > 0 else math.inf
        ok = ratio <= 1.0 + tol
        checks.append({
            "arm": arm, "metric": "step_wall_ms",
            "baseline": round(b_ms, 3), "fresh": round(f_ms, 3),
            "ratio": round(ratio, 4), "tol_rel": round(tol, 4),
            "status": "ok" if ok else
            f"FAIL: step time regressed {100 * (ratio - 1):.1f}% "
            f"(> {100 * tol:.1f}% noise-calibrated tolerance)",
        })
        b_ex = float(b.get("exposed_comm_frac", 0.0) or 0.0)
        f_ex = float(f.get("exposed_comm_frac", 0.0) or 0.0)
        ok_ex = f_ex <= b_ex + EXPOSED_TOL
        checks.append({
            "arm": arm, "metric": "exposed_comm_frac",
            "baseline": round(b_ex, 4), "fresh": round(f_ex, 4),
            "tol_abs": EXPOSED_TOL,
            "status": "ok" if ok_ex else
            f"FAIL: exposed-comm fraction grew "
            f"{f_ex - b_ex:+.3f} (> +{EXPOSED_TOL} absolute tolerance) — "
            f"the overlap schedule stopped hiding its communication",
        })
    return {
        "passed": all("FAIL" not in c["status"] for c in checks),
        "n_arms": sum(1 for c in checks if c["metric"] == "step_wall_ms"),
        "checks": checks,
    }


def tuned_vs_handset(doc: dict) -> dict:
    """Gate a TUNED_*.json plan's per-arm tuned measurements against
    their hand-set ones: neither the step wall nor the combined tuner
    objective (wall + exposed collective ms, the quantity the search
    minimized) may regress beyond the baseline-noise-calibrated
    tolerance.

    Deliberately NOT the cross-revision ``gate``'s exposed-FRACTION
    check: that gate compares two revisions of the SAME schedule,
    where a fraction jump means the program de-overlapped. Here the
    two sides are different schedules — a plan that halves the wall
    while also shrinking exposed ms RAISES the fraction (smaller
    denominator), and a fraction gate would fail exactly the win the
    tuner exists to find. The result carries a per-arm
    ``plan_invariant`` / ``same_program`` annotation so "passed
    trivially" is visible."""
    arms = doc.get("arms") or {}
    if not arms:
        raise ValueError("no 'arms' in the tuned plan artifact")
    checks = []
    for arm in sorted(arms):
        b = arms[arm]["handset"]["anatomy"]
        f = arms[arm]["tuned"]["anatomy"]
        tol = step_time_tolerance(b)
        for metric in ("step_wall_ms", "objective_ms"):
            if metric == "step_wall_ms":
                b_v = float(b["step_wall_ms"]["mean"])
                f_v = float(f["step_wall_ms"]["mean"])
            else:
                b_v = float(b.get("objective_ms", 0.0) or 0.0)
                f_v = float(f.get("objective_ms", 0.0) or 0.0)
            ratio = f_v / b_v if b_v > 0 else (math.inf if f_v else 1.0)
            ok = ratio <= 1.0 + tol
            checks.append({
                "arm": arm, "metric": metric,
                "baseline": round(b_v, 3), "fresh": round(f_v, 3),
                "ratio": round(ratio, 4), "tol_rel": round(tol, 4),
                "status": "ok" if ok else
                f"FAIL: tuned {metric} regressed {100 * (ratio - 1):.1f}% "
                f"vs hand-set (> {100 * tol:.1f}% noise-calibrated "
                f"tolerance) — prefer the hand-set schedule",
            })
    notes = {}
    for a, blk in arms.items():
        if blk.get("plan_invariant"):
            notes[a] = "plan-invariant (knobs do not enter this program)"
        elif blk.get("same_program"):
            notes[a] = "tuned == handset value (same program)"
    return {
        "passed": all("FAIL" not in c["status"] for c in checks),
        "n_arms": len(arms),
        "checks": checks,
        "arm_notes": notes,
    }


def _perturb(rec: dict, *, ms_scale: float = 1.0,
             exposed_add: float = 0.0) -> dict:
    out = copy.deepcopy(rec)
    for s in extract_summaries(out).values():
        s["step_wall_ms"]["mean"] = s["step_wall_ms"]["mean"] * ms_scale
        s["exposed_comm_frac"] = min(
            1.0, float(s.get("exposed_comm_frac", 0.0) or 0.0) + exposed_add)
    return out


def self_check(baseline: dict) -> int:
    """baseline-vs-itself must pass; +10% step time and +0.10 exposed
    fraction must each fail. The acceptance property of ISSUE 13."""
    rows = []
    r0 = gate(baseline, baseline)
    rows.append(("identity", r0["passed"], True))
    r1 = gate(baseline, _perturb(baseline, ms_scale=1.10))
    rows.append(("step_time_x1.10", r1["passed"], False))
    r2 = gate(baseline, _perturb(baseline, exposed_add=0.10))
    rows.append(("exposed_+0.10", r2["passed"], False))
    ok = all(got == want for _, got, want in rows)
    print(json.dumps({
        "self_check": "ok" if ok else "FAIL",
        "n_arms": r0["n_arms"],
        "cases": [{"case": name, "passed": got, "expected_passed": want}
                  for name, got, want in rows],
    }, indent=1))
    return 0 if ok else 1


def _arg(flag: str, default=None):
    if flag in sys.argv:
        return sys.argv[sys.argv.index(flag) + 1]
    return default


def _load(path: str) -> dict:
    with open(path) as f:
        text = f.read().strip()
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        # JSONL (bench output): gate the last record
        return json.loads(text.splitlines()[-1])


def main() -> int:
    if "--tuned-vs-handset" in sys.argv:
        doc = _load(_arg("--baseline", "TUNED_r20.json"))
        result = tuned_vs_handset(doc)
        print(json.dumps(result, indent=1))
        return 0 if result["passed"] else 1
    baseline = _load(_arg("--baseline", "ANATOMY_r17.json"))
    if "--self-check" in sys.argv:
        return self_check(baseline)
    fresh_path = _arg("--fresh")
    if not fresh_path:
        print("usage: perf_gate.py [--baseline B.json] "
              "(--fresh F.json | --self-check)", file=sys.stderr)
        return 2
    result = gate(baseline, _load(fresh_path))
    print(json.dumps(result, indent=1))
    return 0 if result["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
