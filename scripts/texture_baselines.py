"""Floor baselines for the texture ablation (context for ABLATION_r04).

Two floors show where the trained numbers stand:
  pixel k-NN        — k-NN on raw normalized 32px pixels: measures how
                      much of the class is readable without any
                      learning (the dataset was built so palette is
                      uninformative; this should sit near chance).
  random-init       — the in-training eval harness run on an UNTRAINED
                      vit_test4 backbone: the iteration-0 point of every
                      trajectory/ablation curve.

Usage: JAX_PLATFORMS=cpu python scripts/texture_baselines.py [out_dir]
(out_dir should be the ablation out_dir so the same texture tree is
reused; defaults to /tmp/abl_full.)
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from dinov3_tpu.utils import respect_jax_platforms_env

    respect_jax_platforms_env()

    import numpy as np
    from PIL import Image

    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.data.textures import materialize_textures
    from dinov3_tpu.evals.knn import knn_eval

    out = sys.argv[1] if len(sys.argv) > 1 else "/tmp/abl_full"
    tex_root = os.path.join(out, "textures")
    manifest_path = os.path.join(tex_root, "manifest.json")
    if not os.path.isfile(manifest_path):
        # NEVER generate here: the whole point of these floors is that
        # they are computed on the exact tree the ablation curves used —
        # fabricating a fresh default tree would silently decouple them
        raise SystemExit(
            f"no texture manifest under {tex_root}; run "
            "scripts/ablation_recipe.py into this out_dir first")
    with open(manifest_path) as f:
        m = json.load(f)
    train_dir, val_dir = materialize_textures(
        tex_root, n_train_per_class=m["n_train_per_class"],
        n_val_per_class=m["n_val_per_class"], px=m["px"],
        seed=m["seed"])

    def load_split(root, px=32):
        xs, ys = [], []
        classes = sorted(os.listdir(root))
        for ci, c in enumerate(classes):
            cdir = os.path.join(root, c)
            for f in sorted(os.listdir(cdir)):
                im = Image.open(os.path.join(cdir, f)).resize(
                    (px, px), Image.BICUBIC)
                xs.append(np.asarray(im, np.float32).reshape(-1) / 255.0)
                ys.append(ci)
        return np.stack(xs), np.asarray(ys)

    xtr, ytr = load_split(train_dir)
    xva, yva = load_split(val_dir)
    # population note (ADVICE r4): the eval harness's loaders shuffle
    # (seeded) BEFORE drop_last=True at batch 64, so the trajectory
    # numbers see a random subset with the tail dropped — NOT a prefix
    # in dataset order. Rather than replicate the loader's shuffle here,
    # the pixel floor is computed on ALL samples; the difference is the
    # dropped tail (< one batch per split, ~8 of 360 val samples) and is
    # negligible for a chance-floor calibration.
    pixel_knn = knn_eval(xtr, ytr, xva, yva, n_classes=12, k=10)

    # untrained backbone through the SAME eval harness the trajectories
    # use — the iteration-0 point of every committed curve. The shared
    # builder (random init when ckpt_dir is None) keeps the init path —
    # jit + unbox — identical to the certification CLI's.
    from dinov3_tpu.evals import do_eval
    from dinov3_tpu.models import build_model_for_eval

    cfg = get_default_config()
    apply_dot_overrides(cfg, [
        "student.arch=vit_test4", "student.patch_size=4",
        "crops.global_crops_size=32", "crops.local_crops_size=16",
        f"data.root={train_dir}", "data.backend=folder",
        f"evaluation.train_dataset_path=Folder:root={train_dir}",
        f"evaluation.val_dataset_path=Folder:root={val_dir}",
    ])
    model, params = build_model_for_eval(cfg, ckpt_dir=None)
    # default n_classes (1000-way probe) to match the in-training
    # do_eval call every committed trajectory point used
    rand = do_eval(cfg, model, params)

    rec = {
        "pixel_knn_top1": round(pixel_knn, 4),
        "random_init_knn_top1": round(rand["knn_top1"], 4),
        "random_init_linear_top1": round(rand["linear_top1"], 4),
        "chance": round(1 / 12, 4),
    }
    print(json.dumps(rec))
    with open(os.path.join(out, "BASELINES.json"), "w") as f:
        json.dump(rec, f, indent=2)


if __name__ == "__main__":
    main()
