"""Cold-compile wall-time table: scanned vs unrolled block stack.

Round-3 postmortem: the UNROLLED 512px flash fwd+bwd program compiled
>35 min through the axon tunnel, and killing the hung compile wedged the
tunnel for hours. Commit 4185e2e routed the high-res crossover phases
through ``train.scan_layers=true`` (one scanned block instead of 24
unrolled ones, ~24x smaller HLO) — this script VERIFIES that fix
(VERDICT r3 #6) by measuring cold build/lower/compile wall time of the
bench-identical step program on the host CPU backend (XLA compile time
is host-side; the structural scan-vs-unrolled effect is what made the
512px program wedge-unsafe. The TPU Mosaic kernel compile of the pallas
flash attention is NOT measurable off-tunnel — on cpu the dispatcher
falls back to xla attention, so the table captures the dominant,
structural term only).

Each variant runs in a fresh subprocess with a fresh, empty compilation
cache dir so every compile is cold.

Usage:  python scripts/measure_compile_time.py [out.jsonl]
        (env: CT_TIMEOUT per-variant seconds, default 3600)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VARIANTS = [
    # bench-identical high-res point (BENCH_RES=512 BENCH_BATCH=2, see
    # bench.py) — scanned is what r4_queue phF actually runs
    {"name": "hr512_scan", "res": 512, "batch": 2, "scan": True},
    {"name": "hr512_unrolled", "res": 512, "batch": 2, "scan": False},
    # the default 224px headline program for scale
    {"name": "base224_scan", "res": 0, "batch": 8, "scan": True},
    {"name": "base224_unrolled", "res": 0, "batch": 8, "scan": False},
]

_CHILD = r"""
import json, os, sys, time
sys.path.insert(0, sys.argv[1])
spec = json.loads(sys.argv[2])
import jax
# sitecustomize preimports jax before this code runs, so the env var is
# too late — force the platform through the config (the dead-tunnel axon
# plugin must never be touched by a host-side compile measurement)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", spec["cache_dir"])
import jax.numpy as jnp
from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.data import make_synthetic_batch
from dinov3_tpu.train import build_train_setup, put_batch

t0 = time.perf_counter()
cfg = get_default_config()
overrides = [
    "student.arch=vit_large", "student.n_storage_tokens=4",
    "student.drop_path_rate=0.3", "optim.scaling_rule=none",
    "parallel.data=-1", "compute_precision.param_dtype=bf16",
    f"train.scan_layers={str(spec['scan']).lower()}",
]
if spec["res"]:
    overrides += [f"crops.global_crops_size={spec['res']}",
                  f"crops.local_crops_size={max(96, spec['res'] // 4)}"]
apply_dot_overrides(cfg, overrides)
batch = {k: jnp.asarray(v)
         for k, v in make_synthetic_batch(cfg, spec["batch"], seed=0).items()}
setup = build_train_setup(cfg, batch)
dbatch = put_batch(batch, setup.batch_shardings)
t_build = time.perf_counter() - t0

t1 = time.perf_counter()
lowered = setup.step_fn.lower(setup.state, dbatch, setup.scalars(0),
                              jax.random.key(0))
t_lower = time.perf_counter() - t1

t2 = time.perf_counter()
lowered.compile()
t_compile = time.perf_counter() - t2
print(json.dumps({
    "name": spec["name"], "scan": spec["scan"], "res": spec["res"] or 224,
    "batch": spec["batch"], "build_s": round(t_build, 1),
    "lower_s": round(t_lower, 1), "compile_s": round(t_compile, 1),
    "total_s": round(time.perf_counter() - t0, 1),
}))
"""


def main() -> None:
    out_path = sys.argv[1] if len(sys.argv) > 1 else "/tmp/compile_times.jsonl"
    tmo = float(os.environ.get("CT_TIMEOUT", "3600"))
    for spec in VARIANTS:
        with tempfile.TemporaryDirectory(prefix="coldcache_") as cache:
            spec = dict(spec, cache_dir=cache)
            print(f"[compile-time] {spec['name']} (timeout {tmo:.0f}s)...",
                  flush=True)
            t0 = time.time()
            try:
                r = subprocess.run(
                    [sys.executable, "-c", _CHILD, REPO, json.dumps(spec)],
                    capture_output=True, text=True, timeout=tmo,
                )
                if r.returncode == 0 and r.stdout.strip():
                    rec = json.loads(r.stdout.strip().splitlines()[-1])
                else:
                    rec = {"name": spec["name"], "error":
                           f"rc={r.returncode}: "
                           + (r.stderr or "").strip().splitlines()[-1:]
                           .__str__()}
            except subprocess.TimeoutExpired:
                rec = {"name": spec["name"],
                       "error": f"cold compile exceeded {tmo:.0f}s",
                       "elapsed_s": round(time.time() - t0, 1)}
            rec["backend"] = "cpu-host"
            with open(out_path, "a") as f:
                f.write(json.dumps(rec) + "\n")
            print(f"[compile-time] -> {json.dumps(rec)}", flush=True)


if __name__ == "__main__":
    main()
