"""Gram-teacher refresh cadence + params-only (hrft) checkpoint restore."""

import jax
import jax.numpy as jnp
import numpy as np

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.data import make_synthetic_batch
from dinov3_tpu.train.gram_refresh import (
    gram_updates_before,
    refresh_gram,
    should_refresh_gram,
)

SMOL = [
    "student.arch=vit_test", "student.patch_size=4",
    "student.drop_path_rate=0.0",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
    "dino.head_bottleneck_dim=16",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
    "ibot.head_bottleneck_dim=16",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "optim.scaling_rule=none",
]


def _gram_cfg(extra=()):
    cfg = get_default_config()
    apply_dot_overrides(cfg, SMOL + [
        "gram.use_loss=true", "gram.ema_teacher=false",
        "gram.rep_update=true", "gram.update_frequency=2",
        "gram.it_first_update=2", "gram.max_updates=2",
        "crops.gram_teacher_crops_size=16",
    ] + list(extra))
    return cfg


def test_refresh_cadence():
    cfg = _gram_cfg()
    # first refresh after finishing iteration 1 (it+1 == 2 == first_update)
    assert not should_refresh_gram(cfg, 0, 0)
    assert should_refresh_gram(cfg, 1, 0)
    assert should_refresh_gram(cfg, 3, 1)
    assert not should_refresh_gram(cfg, 5, 2)  # max_updates reached
    assert gram_updates_before(cfg, 0) == 0
    assert gram_updates_before(cfg, 3) == 1
    assert gram_updates_before(cfg, 100) == 2  # clamped by max_updates


def test_refresh_copies_teacher_into_gram():
    cfg = _gram_cfg()
    from dinov3_tpu.train import build_train_setup, put_batch

    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, 4, seed=0).items()}
    setup = build_train_setup(cfg, batch)
    assert "gram" in setup.state.params
    state, _ = setup.step_fn(
        setup.state, put_batch(batch, setup.batch_shardings),
        setup.scalars(0), jax.random.key(0),
    )
    # after a step the teacher EMA moved away from the gram init
    t_leaf = jax.tree.leaves(state.params["teacher"]["backbone"])[1]
    g_leaf = jax.tree.leaves(state.params["gram"]["backbone"])[1]
    state2 = refresh_gram(state)
    g2 = jax.tree.leaves(state2.params["gram"]["backbone"])[1]
    assert np.allclose(np.asarray(g2), np.asarray(t_leaf))
    # and the copy is a new buffer, not an alias
    assert state2.params["gram"]["backbone"] is not \
        state2.params["teacher"]["backbone"]


def test_gram_stage_on_dp_seq_mesh():
    """Gram-anchored step dryrun on a dp x seq mesh: the ring path
    engages (kernels.ring_min_seq=1 makes even vit_test's 17-token
    passes ring), the gram loss lands finite in the metrics, and the
    refresh cadence still fires — the ISSUE-15 high-res stage in
    miniature."""
    from dinov3_tpu.parallel.context import set_current_mesh
    from dinov3_tpu.train import build_train_setup, put_batch

    cfg = _gram_cfg([
        "parallel.data=4", "parallel.seq=2", "parallel.zero3=false",
        "kernels.ring_min_seq=1",
    ])
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, 4, seed=0).items()}
    try:
        setup = build_train_setup(cfg, batch)
        assert setup.mesh.shape["seq"] == 2
        assert "gram" in setup.state.params
        # ring engagement itself is pinned by the HLO-census tests
        # (test_ring_attention.py) and the committed COST_HIRES_r19.json;
        # here the point is the gram stage surviving the dp x seq mesh
        state, metrics = setup.step_fn(
            setup.state, put_batch(batch, setup.batch_shardings),
            setup.scalars(0), jax.random.key(0),
        )
        assert jnp.isfinite(metrics["total_loss"])
        assert jnp.isfinite(metrics["gram_loss"])
        # cadence unchanged by the mesh: first refresh after iteration 1
        assert not should_refresh_gram(cfg, 0, 0)
        assert should_refresh_gram(cfg, 1, 0)
        state2 = refresh_gram(state)
        g2 = jax.tree.leaves(state2.params["gram"]["backbone"])[1]
        t = jax.tree.leaves(state2.params["teacher"]["backbone"])[1]
        assert np.allclose(np.asarray(g2), np.asarray(t))
    finally:
        set_current_mesh(None)


def test_hrft_params_only_restore(tmp_path):
    from dinov3_tpu.checkpoint import Checkpointer
    from dinov3_tpu.train import build_train_setup, put_batch

    cfg = get_default_config()
    apply_dot_overrides(cfg, SMOL)
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, 4, seed=0).items()}
    setup = build_train_setup(cfg, batch)
    state, _ = setup.step_fn(
        setup.state, put_batch(batch, setup.batch_shardings),
        setup.scalars(0), jax.random.key(0),
    )
    ckpt = Checkpointer(str(tmp_path / "ckpt"), async_save=False)
    ckpt.save(1, state)
    ckpt.close()

    # fresh run restores params only: step resets, params match
    setup2 = build_train_setup(cfg, batch)
    ckpt2 = Checkpointer(str(tmp_path / "ckpt"))
    restored = ckpt2.restore_params_only(setup2.state)
    ckpt2.close()
    assert int(restored.step) == 0
    want = jax.tree.leaves(state.params["student"])
    got = jax.tree.leaves(restored.params["student"])
    for w, g in zip(want, got):
        assert np.allclose(np.asarray(w), np.asarray(g))


def test_load_gram_teacher_from_checkpoint(tmp_path):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dinov3_tpu.checkpoint import Checkpointer
    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup, put_batch
    from dinov3_tpu.train.gram_refresh import load_gram_teacher

    smol = [
        "student.arch=vit_test", "student.patch_size=4",
        "student.drop_path_rate=0.0",
        "crops.global_crops_size=16", "crops.local_crops_size=8",
        "crops.local_crops_number=2",
        "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
        "dino.head_bottleneck_dim=16",
        "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
        "ibot.head_bottleneck_dim=16",
        "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
        "optim.scaling_rule=none",
    ]
    # teacher pretraining run -> checkpoint
    cfg = get_default_config()
    apply_dot_overrides(cfg, smol)
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, 4, seed=0).items()}
    setup = build_train_setup(cfg, batch)
    dbatch = put_batch(batch, setup.batch_shardings)
    state, _ = setup.step_fn(setup.state, dbatch, setup.scalars(0),
                             jax.random.key(0))
    ckpt = Checkpointer(str(tmp_path / "ckpt"), max_to_keep=1)
    ckpt.save(1, state)
    ckpt.wait_until_finished()
    ckpt.close()
    teacher_leaf = np.asarray(
        jax.tree.leaves(state.params["teacher"]["backbone"])[0])

    # gram-anchor run: gram backbone loads the prior EMA teacher
    cfg2 = get_default_config()
    apply_dot_overrides(cfg2, smol + [
        "gram.use_loss=true", f"gram.ckpt={tmp_path / 'ckpt'}",
        "gram.it_load_ema_teacher=-1",
    ])
    batch2 = {k: jnp.asarray(v) for k, v in
              make_synthetic_batch(cfg2, 4, seed=1).items()}
    setup2 = build_train_setup(cfg2, batch2)
    assert "gram" in setup2.state.params
    state2 = load_gram_teacher(cfg2, setup2.state, setup2.state_shardings)
    got = np.asarray(jax.tree.leaves(state2.params["gram"]["backbone"])[0])
    np.testing.assert_allclose(got, teacher_leaf)
