"""Elastic topology engine (parallel/reshard.py) + topology-elastic
checkpoints (ISSUE 19).

Pins, on the 8-virtual-device CPU mesh:

- in-memory reshard round-trips BITWISE across meshes (dp=8 <->
  dp=2 x fsdp=4) and opt-state arms (replicated / zero3 / bucketed),
  every transfer one jitted program per leaf-group with every inserted
  collective attributed to its ``reshard_*`` scope (zero unattributed,
  zero "other" leakage);
- the in-memory path is bitwise-interchangeable with the disk path
  (checkpoint save + cross-arm restore) on the same transition, and one
  train step from either resumed state is bitwise-deterministic;
- a TRUE resize (8 -> 4 devices) takes the staged device_put transfer
  path — still in memory, still bitwise;
- the cross-topology checkpoint matrix: a state saved at each of
  {replicated, zero3, unified} x {dp=8, dp=2x4} restores at a different
  (arm, mesh) bitwise (satellite: the checkpoint generalization);
- atomic checkpoint finalization: an interrupted/truncated save is
  unreadable-as-latest in BOTH backends (write-then-finalize marker in
  the local-npz backend, structural readability probe over orbax step
  dirs), so resume picks the previous step;
- ``elastic_resume`` policy routing (auto/memory/disk) and the
  ``topology.json`` sidecar.
"""

import json
import os

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import pytest

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.parallel.reshard import (
    ARM_LAYOUT,
    RESHARD_SCOPES,
    arm_name,
    describe_topology,
    moments_convert_needed,
    reshard_state,
    topology_of,
)

SMOL = [
    "student.arch=vit_test", "student.patch_size=4",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2", "train.batch_size_per_device=2",
    "optim.scaling_rule=none", "train.scan_layers=true",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
    "dino.head_bottleneck_dim=16",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
    "ibot.head_bottleneck_dim=16",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "optim.warmup_epochs=1",
]

REP8 = ["parallel.data=8", "parallel.zero3=false",
        "optim.sharded_update=false", "optim.bucketed_collectives=false"]
Z24 = ["parallel.data=2", "parallel.fsdp=4", "parallel.zero3=true",
       "optim.bucketed_collectives=false"]
BUK8 = ["parallel.data=8", "parallel.zero3=false",
        "optim.bucketed_collectives=true"]
U24 = ["parallel.data=2", "parallel.fsdp=4", "parallel.zero3=true",
       "optim.bucketed_collectives=true"]
Z8 = ["parallel.data=8", "parallel.zero3=true",
      "optim.bucketed_collectives=false"]
REP24 = ["parallel.data=2", "parallel.fsdp=4", "parallel.zero3=false",
         "optim.sharded_update=false", "optim.bucketed_collectives=false"]


def _setup(extra, devices=None, init_state=True):
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup

    cfg = get_default_config()
    apply_dot_overrides(cfg, SMOL + list(extra))
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, 16, seed=0).items()}
    return build_train_setup(cfg, batch, devices=devices,
                             init_state=init_state), batch


def assert_bitwise(a, b, what):
    fa = jtu.tree_flatten_with_path(a)[0]
    fb = jtu.tree_flatten_with_path(b)[0]
    assert len(fa) == len(fb), (what, len(fa), len(fb))
    for (pa, la), (_, lb) in zip(fa, fb):
        assert np.array_equal(np.asarray(la), np.asarray(lb)), (
            f"{what}: {jtu.keystr(pa)} differs")


@pytest.fixture(scope="module")
def topo(eight_devices):
    """One stepped replicated dp=8 state + the zero3 dp=2x4 setup it
    reshards into (concrete: the disk path and the determinism step
    need real arrays there) + the bucketed dp=8 target (abstract)."""
    from dinov3_tpu.train import put_batch

    s_r, batch = _setup(REP8, devices=eight_devices)
    d_r = put_batch(batch, s_r.batch_shardings)
    state1, _ = s_r.step_fn(s_r.state, d_r, s_r.scalars(0),
                            jax.random.key(0))
    s_z, _ = _setup(Z24, devices=eight_devices)
    s_b, _ = _setup(BUK8, devices=eight_devices, init_state=False)
    return {"s_r": s_r, "s_z": s_z, "s_b": s_b, "batch": batch,
            "d_r": d_r, "state1": state1}


# ---------------- unit: vocabulary / descriptors ----------------

def test_reshard_scopes_registered():
    from dinov3_tpu.utils import (
        HLO_COLLECTIVE_SCOPES,
        classify_collective_scope,
    )

    markers = [m for m, _ in HLO_COLLECTIVE_SCOPES]
    for scope in RESHARD_SCOPES:
        assert scope in markers
        line = (f'  %all-to-all.1 = f32[8]{{0}} all-to-all(%x), '
                f'metadata={{op_name="jit(prog)/jit(main)/{scope}/'
                f'sharding_constraint"}}')
        assert classify_collective_scope(line) == scope


def test_arm_layout_table():
    assert set(ARM_LAYOUT) == {
        "replicated", "zero3", "unified", "flat", "bucketed"}
    assert ARM_LAYOUT["replicated"] == "model"
    assert ARM_LAYOUT["unified"] == "model"
    assert ARM_LAYOUT["flat"] == "flat"
    assert ARM_LAYOUT["bucketed"] == "bucket"


def test_arm_name_resolution(topo):
    assert arm_name(topo["s_r"]) == "replicated"
    assert arm_name(topo["s_z"]) == "zero3"
    assert arm_name(topo["s_b"]) == "bucketed"


def test_describe_topology(topo):
    d = describe_topology(topology_of(topo["s_z"]))
    assert d["arm"] == "zero3" and d["dp"] == 8
    assert d["mesh"] == {"data": 2, "fsdp": 4}
    json.dumps(d)  # must be a committable record


# ---------------- in-memory reshard: bitwise + census ----------------

def test_roundtrip_mesh_and_arm_bitwise(topo):
    """rep@dp8 -> zero3@2x4 -> rep@dp8: bitwise round-trip, every group
    one jitted program, every census clean, and the gather-back
    direction's collectives attributed to their reshard scopes."""
    src = topology_of(topo["s_r"])
    dst = topology_of(topo["s_z"])
    assert not moments_convert_needed(src, dst)  # model layout both ends

    st_z, rep = reshard_state(topo["state1"], src, dst)
    assert rep["census_ok"] and rep["same_devices"]
    assert set(rep["groups"]) == set(RESHARD_SCOPES)
    for scope, row in rep["groups"].items():
        assert row["mode"] == "jit"
        assert row["census"]["unattributed"] == 0
        assert set(row["census"]["by_scope"]) <= {scope}
    # placement actually changed: a zero3 leaf is sharded over ZERO3_AXES
    shardings = jtu.tree_flatten(
        topo["s_z"].state_shardings.params["student"])[0]
    assert any(any(p is not None for p in s.spec) for s in shardings)

    back, rep2 = reshard_state(st_z, dst, src)
    assert rep2["census_ok"]
    # zero3 -> replicated re-materializes shards: at least one group
    # really moved data through an attributed collective
    moved = [r for r in rep2["groups"].values()
             if r["census"]["by_scope"]]
    assert moved, rep2["groups"]
    assert_bitwise(topo["state1"], back, "mesh+arm roundtrip")


def test_arm_conversion_bucketed_roundtrip(topo):
    """replicated (model moments) -> bucketed (bucket-dict moments):
    the layout conversion rides INSIDE the scoped programs, the mu tree
    comes out keyed by the plan's buckets, and the round-trip is
    bitwise."""
    src = topology_of(topo["s_r"])
    dst = topology_of(topo["s_b"])
    assert moments_convert_needed(src, dst)

    st_b, rep = reshard_state(topo["state1"], src, dst)
    assert rep["census_ok"]
    mu = st_b.opt_state.adam.mu
    assert sorted(dict(mu)) == sorted(dst.bucket_plan.names)
    back, rep2 = reshard_state(st_b, dst, src)
    assert rep2["census_ok"]
    assert_bitwise(topo["state1"], back, "bucketed roundtrip")


def test_in_memory_matches_disk_and_resume_determinism(
        topo, tmp_path, eight_devices):
    """The tentpole interchange pin: the in-memory reshard of a live
    state equals the disk round-trip (save at rep@dp8, cross-arm
    restore at zero3@2x4) BITWISE — and one train step from either
    resumed state is bitwise-identical, so the two resume paths are
    interchangeable mid-run."""
    from dinov3_tpu.checkpoint import Checkpointer
    from dinov3_tpu.train import put_batch

    src = topology_of(topo["s_r"])
    dst = topology_of(topo["s_z"])
    mem_state, rep = reshard_state(topo["state1"], src, dst)
    assert rep["census_ok"]

    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    ck.save(1, topo["state1"], topology=describe_topology(src))
    ck.wait_until_finished()
    disk_state = ck.restore(topo["s_z"].state, 1)
    assert_bitwise(mem_state, disk_state, "memory vs disk reshard")

    side = ck.saved_topology()
    assert side["arm"] == "replicated" and side["step"] == 1

    d_z = put_batch(topo["batch"], topo["s_z"].batch_shardings)
    st_m, m_m = topo["s_z"].step_fn(mem_state, d_z,
                                    topo["s_z"].scalars(1),
                                    jax.random.key(0))
    st_d, m_d = topo["s_z"].step_fn(disk_state, d_z,
                                    topo["s_z"].scalars(1),
                                    jax.random.key(0))
    assert float(m_m["total_loss"]) == float(m_d["total_loss"])
    assert np.isfinite(float(m_m["total_loss"]))
    assert_bitwise(st_m.params, st_d.params, "resume determinism")


def test_true_resize_transfer_path(topo, eight_devices):
    """dp=8 -> dp=4 on HALF the devices: no single program spans two
    device sets, so every group ships via the staged device_put path —
    still in memory, values bitwise, placement on the 4-device mesh."""
    s_4, _ = _setup(["parallel.data=4", "parallel.zero3=false",
                     "optim.sharded_update=false",
                     "optim.bucketed_collectives=false"],
                    devices=eight_devices[:4], init_state=False)
    src = topology_of(topo["s_r"])
    dst = topology_of(s_4)
    assert src.device_ids() != dst.device_ids()

    st_4, rep = reshard_state(topo["state1"], src, dst)
    assert not rep["same_devices"]
    for row in rep["groups"].values():
        assert row["mode"] == "transfer"
    assert_bitwise(topo["state1"].params, st_4.params, "resize values")
    got = {d.id for d in
           jax.tree.leaves(st_4.params)[0].sharding.mesh.devices.flat}
    assert got == {d.id for d in eight_devices[:4]}


# ---------------- cross-topology checkpoint matrix ----------------

@pytest.mark.parametrize("cell_name,cell_over", [
    ("zero3@dp8", Z8),
    ("replicated@2x4", REP24),
    ("unified@2x4", U24),
])
def test_checkpoint_matrix_save_anywhere_restore_anywhere(
        topo, tmp_path, eight_devices, cell_name, cell_over):
    """A state carried to {zero3, replicated, unified} x {dp8, 2x4}
    cells by the in-memory engine, SAVED there, then restored at a
    DIFFERENT (arm, mesh) — both back at rep@dp8 and across to
    zero3@2x4 — bitwise against the original. With rep@dp8 -> zero3@2x4
    covered by the interchange test above, every matrix row saves and
    restores across topologies."""
    from dinov3_tpu.checkpoint import Checkpointer

    s_c, _ = _setup(cell_over, devices=eight_devices, init_state=False)
    src = topology_of(topo["s_r"])
    cell = topology_of(s_c)
    st_c, rep = reshard_state(topo["state1"], src, cell)
    assert rep["census_ok"], (cell_name, rep)

    ck = Checkpointer(str(tmp_path / "ck"), async_save=False,
                      bucket_plan=getattr(s_c, "bucket_plan", None))
    ck.save(1, st_c, topology=describe_topology(cell))
    ck.wait_until_finished()
    assert ck.saved_topology()["arm"] == cell.arm

    back_r = ck.restore(topo["s_r"].state, 1)
    assert_bitwise(topo["state1"], back_r,
                   f"{cell_name} -> replicated@dp8")
    back_z = ck.restore(topo["s_z"].state, 1)
    assert_bitwise(topo["state1"].params, back_z.params,
                   f"{cell_name} -> zero3@2x4 params")
    assert_bitwise(topo["state1"].opt_state, back_z.opt_state,
                   f"{cell_name} -> zero3@2x4 moments")
    ck.close()


# ---------------- elastic_resume policy routing ----------------

def test_elastic_resume_policies(topo, tmp_path):
    from dinov3_tpu.checkpoint import Checkpointer
    from dinov3_tpu.train import elastic_resume

    src = topology_of(topo["s_r"])
    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    ck.save(1, topo["state1"], topology=describe_topology(src))
    ck.wait_until_finished()

    # auto + live state whose mesh is reachable -> memory path
    st, info = elastic_resume(
        topo["s_z"], ck, live_state=topo["state1"], live_topology=src,
        policy="auto")
    assert info["path"] == "memory"
    assert info["report"]["census_ok"]
    assert_bitwise(topo["state1"].params, st.params, "memory resume")

    # no live state (a real preemption) -> disk path
    st_d, info_d = elastic_resume(topo["s_z"], ck, policy="auto")
    assert info_d["path"] == "disk"
    assert_bitwise(st.params, st_d.params, "disk resume")

    # forced disk ignores the live state
    _, info_f = elastic_resume(
        topo["s_z"], ck, live_state=topo["state1"], live_topology=src,
        policy="disk")
    assert info_f["path"] == "disk"

    with pytest.raises(ValueError, match="live state"):
        elastic_resume(topo["s_z"], ck, policy="memory")
    with pytest.raises(ValueError, match="policy"):
        elastic_resume(topo["s_z"], ck, policy="sideways")
    ck.close()


# ---------------- atomic finalization ----------------

def _abstract_like(state):
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(tuple(x.shape), x.dtype), state)


def test_local_backend_truncated_save_not_latest(topo, tmp_path):
    """Local-npz backend: a mid-flight save killed after the payload
    started but before finalization (no marker / torn npz) must be
    invisible to latest_step — resume picks the previous step."""
    from dinov3_tpu.checkpoint import Checkpointer

    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    ck._local, ck.manager = True, None  # force the npz backend
    ck._local_save(1, topo["state1"])
    ck._local_save(2, topo["state1"])
    assert ck.latest_step() == 2

    # simulate the kill: step 3's payload exists but truncated, marker
    # never written (the finalize order guarantees this state)
    d3 = tmp_path / "ck" / "3"
    os.makedirs(d3)
    with open(tmp_path / "ck" / "2" / "state.npz", "rb") as f:
        blob = f.read()
    with open(d3 / "state.npz", "wb") as f:
        f.write(blob[: len(blob) // 2])
    assert ck.latest_step() == 2

    # a save killed mid-payload (tmp dir never renamed) is invisible too
    os.makedirs(tmp_path / "ck" / "tmp.4")
    assert ck.latest_step() == 2

    # ...and the announced step actually restores
    restored = ck._local_restore(topo["state1"], 2)
    assert_bitwise(topo["state1"], restored, "restore at previous step")

    # a finalized dir whose marker was lost is equally unreadable:
    # marker-gated discovery, not mtime heuristics
    os.remove(tmp_path / "ck" / "2" / ck.FINALIZED)
    assert ck.latest_step() == 1


def test_orbax_backend_truncated_save_not_latest(topo, tmp_path):
    """Orbax backend: a digit-named step dir that lost its item payload
    (truncated transfer / kill during finalize) fails the structural
    readability probe, so latest_step falls back to the previous
    restorable step."""
    import shutil

    from dinov3_tpu.checkpoint import Checkpointer

    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    ck.save(1, topo["state1"])
    ck.save(2, topo["state1"])
    ck.wait_until_finished()
    assert ck.latest_step() == 2

    item = tmp_path / "ck" / "2" / "state"
    assert item.is_dir()
    shutil.rmtree(item)  # the payload vanished mid-flight
    assert ck.latest_step() == 1

    restored = ck.restore(topo["s_r"].state)  # step=None -> discovery
    assert int(restored.step) == int(topo["state1"].step)
    assert_bitwise(topo["state1"], restored, "restore previous step")
    ck.close()


def test_reshard_report_padding_warnings(topo, eight_devices):
    """A transition into a flat-layout arm records the re-padding
    guardrail outcome (ISSUE 19 satellite: captured into bench records
    like the PR-9 bucket guardrail). vit_test leaves divide dp=8
    cleanly, so the list is present and empty here."""
    s_f, _ = _setup(["parallel.data=8", "parallel.zero3=false",
                     "optim.bucketed_collectives=false"],
                    devices=eight_devices, init_state=False)
    assert arm_name(s_f) == "flat"
    _, rep = reshard_state(
        topo["state1"], topology_of(topo["s_r"]), topology_of(s_f))
    assert rep["padding_warnings"] == []
    assert rep["census_ok"]
