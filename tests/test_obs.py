"""Serving observability plane (telemetry/serve_obs.py, hist.py,
watchdog.py + serve engine threading) and the committed OBS_r15.json.

Pins:

- streaming histogram: nearest-rank quantile within ONE log-bucket
  width of the exact sorted-sample quantile on adversarial
  distributions (bimodal, heavy-tail, constant), merge associativity,
  fixed memory under 1e6 observations, dict round-trip;
- the shared exact-quantile helper (the bench_serve percentile fix:
  p50 was the upper median on even n, p99 hand-clamped);
- SpanTracer crash-safety: bounded auto-flush leaves all but the last
  N-1 spans readable without close(), schema version + role on every
  record, role-split span files;
- heartbeat namespacing (heartbeat.train / heartbeat.serve) with the
  legacy un-namespaced read fallback, the staleness scan, and the
  watchdog's flush-window stall spans;
- the live-mix envelope round-trip (ISSUE 11 acceptance): the
  SERVE_r14 measured mixes re-derive an envelope that keeps
  warn_serve_pad_waste SILENT on the same mix and FIRES it on a
  shifted (all-384px) mix;
- ServeObserver end-to-end on the real packed engine: per-request
  phase records for every request, device-side stats rows agreeing
  with the host plan, and the blocking_fetch funnel UNCHANGED
  (fetches == packs — stats ride the existing ring fetch);
- the committed OBS_r15.json: phase breakdown for every measured
  request, hist-vs-exact within one bucket width per (arm, mix, SLO
  class), fetches_per_pack == 1.0.
"""

import importlib.util
import json
import os
import time
import warnings

import numpy as np
import pytest

from dinov3_tpu.telemetry.hist import LogHistogram, quantile_nearest_rank
from dinov3_tpu.telemetry.serve_obs import (
    LiveMixTracker,
    ServeObserver,
    recommended_serve_envelope,
    simulated_ffd_waste,
)
from dinov3_tpu.telemetry.spans import SERVE_PHASES, SPAN_SCHEMA_V, SpanTracer
from dinov3_tpu.telemetry.watchdog import (
    Watchdog,
    heartbeat_path,
    legacy_heartbeat_path,
    read_heartbeat,
    scan_heartbeats,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------- exact quantile helper ----------------


def test_quantile_nearest_rank_semantics():
    # ceil(q*n)-th order statistic: on even n the p50 is the LOWER
    # median (ceil(0.5*4) = 2nd of 4) — the old bench_serve indexing
    # lats[len//2] returned the 3rd
    assert quantile_nearest_rank([1, 2, 3, 4], 0.5) == 2
    assert quantile_nearest_rank([1, 2, 3], 0.5) == 2
    assert quantile_nearest_rank([5], 0.99) == 5
    assert quantile_nearest_rank([1, 2], 0.0) == 1   # min
    assert quantile_nearest_rank([1, 2], 1.0) == 2   # max
    with pytest.raises(ValueError):
        quantile_nearest_rank([], 0.5)
    with pytest.raises(ValueError):
        quantile_nearest_rank([1], 1.5)


def test_quantile_nearest_rank_matches_numpy_inverted_cdf():
    rng = np.random.default_rng(7)
    xs = np.sort(rng.lognormal(1.0, 2.0, 997))
    for q in (0.01, 0.25, 0.5, 0.9, 0.99):
        assert quantile_nearest_rank(xs, q) == np.quantile(
            xs, q, method="inverted_cdf")


def test_bench_serve_lat_summary_uses_shared_helper():
    bs = _load_script("bench_serve")
    lats = [0.004, 0.001, 0.002, 0.003]          # even n: lower median
    s = bs._lat_summary(lats)
    assert s["p50_ms"] == 2.0 and s["p99_ms"] == 4.0 and s["n"] == 4
    # pinned against the exact sorted-sample quantiles on a big draw
    rng = np.random.default_rng(0)
    sample = list(rng.exponential(0.05, 1001))
    s = bs._lat_summary(sample)
    ex = sorted(sample)
    assert s["p50_ms"] == round(1e3 * quantile_nearest_rank(ex, 0.5), 3)
    assert s["p99_ms"] == round(1e3 * quantile_nearest_rank(ex, 0.99), 3)


# ---------------- streaming histogram ----------------


@pytest.mark.parametrize("name,xs", [
    ("bimodal", np.concatenate([
        np.random.default_rng(0).normal(2.0, 0.1, 5000),
        np.random.default_rng(1).normal(800.0, 40.0, 5000)])),
    ("heavy_tail", np.random.default_rng(2).pareto(1.1, 10000) + 0.5),
    ("constant", np.full(1000, 37.5)),
])
def test_hist_quantile_within_one_bucket_width(name, xs):
    xs = np.abs(xs)
    h = LogHistogram(1e-2, 1e5, bins_per_decade=16)
    h.observe_many(xs)
    ex = np.sort(xs)
    for q in (0.5, 0.9, 0.99):
        est = h.quantile(q)
        ref = float(quantile_nearest_rank(ex, q))
        ratio = est / ref
        assert 1.0 / h.width_factor <= ratio <= h.width_factor, \
            (name, q, est, ref)


def test_hist_merge_associative_and_pure():
    rng = np.random.default_rng(3)
    parts = [LogHistogram() for _ in range(3)]
    for h in parts:
        h.observe_many(rng.lognormal(1, 1, 500))
    a, b, c = parts
    ab_c = a.merge(b).merge(c)
    a_bc = a.merge(b.merge(c))
    assert np.array_equal(ab_c.counts, a_bc.counts)
    assert ab_c.total == a_bc.total == 1500
    assert ab_c.min == a_bc.min and ab_c.max == a_bc.max
    # pure: operands untouched
    assert a.total == 500
    with pytest.raises(ValueError, match="incompatible"):
        a.merge(LogHistogram(1e-1, 1e4, bins_per_decade=8))


def test_hist_fixed_memory_under_1e6_observations():
    h = LogHistogram()
    nbytes0 = h.counts.nbytes
    rng = np.random.default_rng(4)
    for _ in range(10):
        h.observe_many(rng.lognormal(2, 3, 100_000))
    assert h.total == 1_000_000
    assert h.counts.nbytes == nbytes0          # the one fixed array
    assert int(h.counts.sum()) == h.total
    assert h.quantile(0.99) > h.quantile(0.5) > 0


def test_hist_out_of_range_and_round_trip():
    h = LogHistogram(1.0, 1e3, bins_per_decade=4)
    h.observe_many([0.0, -5.0, 0.5, 2.0, 5e4])
    # underflow/overflow quantiles report the tracked exact extremes
    assert h.quantile(0.01) == -5.0
    assert h.quantile(0.999) == 5e4
    h2 = LogHistogram.from_dict(json.loads(json.dumps(h.to_dict())))
    assert np.array_equal(h2.counts, h.counts)
    assert (h2.total, h2.sum, h2.min, h2.max) == \
        (h.total, h.sum, h.min, h.max)
    assert h2.quantile(0.5) == h.quantile(0.5)


# ---------------- SpanTracer crash-safety + roles ----------------


def test_span_autoflush_leaves_tail_readable(tmp_path):
    tracer = SpanTracer(str(tmp_path), flush_every_emits=5)
    for i in range(12):
        tracer.emit({"name": "x", "i": i})
    # ABANDONED: no beat(), no close(). Two auto-flushes at 5 and 10
    # emits — at most flush_every_emits - 1 spans may be lost.
    lines = [json.loads(ln) for ln in
             open(tracer.spans_path).read().splitlines()]
    assert len(lines) >= 10
    for rec in lines:
        assert rec["v"] == SPAN_SCHEMA_V
        assert rec["role"] == "train"
    assert [r["i"] for r in lines] == list(range(len(lines)))
    tracer.close()


def test_span_role_splits_files_and_stamps_records(tmp_path):
    train = SpanTracer(str(tmp_path), role="train")
    serve = SpanTracer(str(tmp_path), role="serve")
    assert train.spans_path.endswith("spans.jsonl")
    assert serve.spans_path.endswith("spans.serve.jsonl")
    assert train.heartbeat_path.endswith("heartbeat.train")
    assert serve.heartbeat_path.endswith("heartbeat.serve")
    with serve.span("serve_dispatch", pack=3):
        pass
    serve.close()
    train.close()
    [rec] = [json.loads(ln) for ln in
             open(serve.spans_path).read().splitlines()]
    assert rec["role"] == "serve" and rec["pack"] == 3
    assert rec["name"] in SERVE_PHASES


# ---------------- watchdog: heartbeats + stall windows ----------------


def test_heartbeat_namespacing_and_legacy_fallback(tmp_path):
    out = str(tmp_path)
    tracer = SpanTracer(out, role="serve", heartbeat_every=1)
    tracer.beat(7)
    hb = read_heartbeat(out, role="serve")
    assert hb and not hb["legacy"] and hb["iteration"] == 7
    assert hb["path"] == heartbeat_path(out, "serve")
    # no train heartbeat yet: namespaced miss, no legacy either
    assert read_heartbeat(out, role="train") is None
    # a pre-PR-11 run left the un-namespaced file: legacy fallback
    with open(legacy_heartbeat_path(out), "w") as f:
        f.write(json.dumps({"iteration": 3, "t": 1.0}))
    hb = read_heartbeat(out, role="train")
    assert hb and hb["legacy"] and hb["iteration"] == 3
    tracer.close()


def test_scan_heartbeats_roles_ranks_and_staleness(tmp_path):
    out = str(tmp_path)
    tdir = tmp_path / "telemetry"
    tdir.mkdir()
    for name, it in (("heartbeat", 1), ("heartbeat.rank3", 2),
                     ("heartbeat.train", 5), ("heartbeat.serve", 9)):
        (tdir / name).write_text(json.dumps({"iteration": it, "t": 0.0}))
    rows = scan_heartbeats(out, stale_after_s=1e6)
    by = {(r["role"], r["rank"]): r for r in rows}
    # legacy "heartbeat.rank3" parses as (train, 3), NOT role "rank3"
    assert set(by) == {("train", 0), ("train", 3), ("serve", 0)}
    assert by[("train", 3)]["legacy"]
    # the namespaced train beat shadows the legacy un-namespaced one
    assert not by[("train", 0)]["legacy"]
    assert all(not r["stalled"] for r in rows)
    rows = scan_heartbeats(out, stale_after_s=1e-9,
                           now=time.time() + 10.0)
    assert all(r["stalled"] for r in rows)


def test_watchdog_window_stall_span(tmp_path):
    tracer = SpanTracer(str(tmp_path))
    wd = Watchdog(tracer, deadline_s=1e-4)
    with wd.window("metrics_flush", iteration=12):
        time.sleep(0.005)
    with wd.window("metrics_flush", deadline_s=60.0):
        pass                                    # within deadline
    with wd.window("metrics_flush", deadline_s=0.0):
        time.sleep(0.002)                       # 0 disables
    tracer.close()
    assert wd.stalls == 1
    stalls = [json.loads(ln) for ln in
              open(tracer.spans_path).read().splitlines()
              if json.loads(ln)["name"] == "stall"]
    assert len(stalls) == 1
    s = stalls[0]
    assert s["window"] == "metrics_flush" and s["iteration"] == 12
    assert s["dur_ms"] > s["deadline_ms"]


# ---------------- live-mix tracking + envelope round-trip ----------------


def _serve_r14_layout():
    from dinov3_tpu.serve import ServeLayout

    # the committed SERVE_r14.json full layout
    return ServeLayout(rows=4, row_tokens=1025, n_prefix=1,
                       max_segments_per_row=28, patch_size=16,
                       min_px=96, max_px=512)


def _drain_mix_through_batcher(images, layout):
    """FFD-pack a mix (host only, no model) and return (tracker fed
    the way the observer feeds it, measured drain waste). One window =
    the whole drain, so the tracker's EWMA equals the measured waste
    (per-pack windows would EWMA-overweight the trailing partial
    pack)."""
    from dinov3_tpu.serve import ContinuousBatcher, ServeRequest

    tracker = LiveMixTracker(layout, alpha=0.25)
    b = ContinuousBatcher(layout)
    for i, im in enumerate(images):
        b.admit(ServeRequest(request_id=i, image=im))
        tracker.observe_request(layout.seq_len(*im.shape[:2]),
                                im.shape[0], im.shape[1])
    used = total = 0
    while b.queue_len:
        plan = b.next_pack()
        tracker.observe_pack(plan.tokens_used, layout.token_budget)
        used += plan.tokens_used
        total += layout.token_budget
    tracker.roll()
    return tracker, 1.0 - used / total


def test_envelope_round_trip_serve_r14_mixes():
    """ISSUE 11 acceptance: SERVE_r14's measured mixes re-derive an
    envelope that keeps warn_serve_pad_waste SILENT on the same mix
    and FIRES it on a shifted mix."""
    from dinov3_tpu.configs.config import warn_serve_pad_waste
    from dinov3_tpu.serve import ServeLayout

    bs = _load_script("bench_serve")
    layout = _serve_r14_layout()
    r14 = json.load(open(os.path.join(REPO, "SERVE_r14.json")))
    rng = np.random.default_rng(int(r14["seed"]))
    # SERVE_r14's seed and mix bands; a longer stream (several full
    # token budgets) so the drain-tail partial pack amortizes, the
    # bench_serve methodology
    images = bs.make_mix(rng, bs.MIXES_FULL["mixed_ragged"], 256,
                         layout.patch_size)

    tracker, waste = _drain_mix_through_batcher(images, layout)
    assert tracker.ewma_pad_waste == pytest.approx(waste)
    env = tracker.recommended_serve_envelope(threshold=0.15)
    assert env["within_threshold"], env
    assert env["min_px"] == min(min(im.shape[:2]) for im in images)
    assert env["max_px"] == max(max(im.shape[:2]) for im in images)
    assert env["max_seq_len"] == max(
        layout.seq_len(*im.shape[:2]) for im in images)

    # SAME mix served under the re-derived envelope: waste within
    # threshold -> the guardrail stays silent
    env_layout = ServeLayout(
        rows=env["rows"], row_tokens=env["row_tokens"], n_prefix=1,
        max_segments_per_row=env["max_segments_per_row"], patch_size=16,
        min_px=env["min_px"], max_px=env["max_px"])
    tr_same, waste_same = _drain_mix_through_batcher(images, env_layout)
    assert waste_same <= 0.15, waste_same
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert warn_serve_pad_waste(waste_same) is None
        assert tr_same.check_drift(threshold=0.15) is None

    # SHIFTED mix (traffic drifts to all-384px squares) under the SAME
    # envelope: one 577-token image per 1025-token row wastes ~44% ->
    # the drift check re-fires the guardrail
    shifted = [np.zeros((384, 384, 3), np.float32) for _ in range(32)]
    tr_shift, waste_shift = _drain_mix_through_batcher(shifted, layout)
    assert waste_shift > 0.15
    with pytest.warns(UserWarning, match="live mix EWMA"):
        msg = tr_shift.check_drift(threshold=0.15)
    assert msg is not None and "pad-waste" in msg
    # and the re-derived envelope for the NEW traffic fixes it
    env2 = tr_shift.recommended_serve_envelope(threshold=0.15)
    assert env2["within_threshold"] and env2["row_tokens"] == 577


def test_simulated_ffd_waste_properties():
    # single resolution: matches the analytic floor exactly
    assert simulated_ffd_waste([577] * 8, 1025, 28) == pytest.approx(
        1.0 - 577 / 1025)
    # a mix packs BETTER than the averaged single-resolution floors
    lens = [601] * 4 + [101] * 24
    mix_waste = simulated_ffd_waste(lens, 1025, 28)
    avg_floor = 0.5 * (1 - (1025 // 601) * 601 / 1025) \
        + 0.5 * (1 - (1025 // 101) * 101 / 1025)
    assert mix_waste < avg_floor
    # inadmissible length under the candidate row -> total waste
    assert simulated_ffd_waste([2000], 1025, 28) == 1.0
    assert simulated_ffd_waste([], 1025, 28) == 0.0


def test_recommended_envelope_empty_and_ewma_weighting():
    layout = _serve_r14_layout()
    assert recommended_serve_envelope({}, layout) is None
    tr = LiveMixTracker(layout, alpha=0.5)
    assert tr.roll() is None                   # empty window
    tr.observe_request(577, 384, 384)
    tr.observe_pack(577, 1025)
    tr.roll()
    w0 = tr.ewma_pad_waste
    assert w0 == pytest.approx(1 - 577 / 1025)
    tr.observe_request(101, 96, 160)
    tr.observe_pack(1010, 1025)
    tr.roll()
    # alpha=0.5: halfway between the window wastes
    assert tr.ewma_pad_waste == pytest.approx(
        0.5 * w0 + 0.5 * (1 - 1010 / 1025))
    assert set(tr.ewma_lens) == {577, 101}
    assert sum(tr.ewma_lens.values()) == pytest.approx(1.0)
    with pytest.raises(ValueError, match="alpha"):
        LiveMixTracker(layout, alpha=0.0)


# ---------------- ServeObserver unit flow ----------------


def test_serve_observer_records_and_windows(tmp_path):
    from dinov3_tpu.serve import ServeLayout

    layout = ServeLayout(rows=2, row_tokens=20, n_prefix=1,
                         max_segments_per_row=3, patch_size=4)
    tracer = SpanTracer(str(tmp_path), role="serve")
    obs = ServeObserver(tracer, layout, slo_classes=("interactive",),
                        window_packs=2, warn=False)
    obs.set_labels(arm="packed", mix="unit")
    phases = {"placement": 0.5, "dispatch": 1.0, "device": 2.0,
              "fetch": 2.0, "extract": 0.1}
    for pack in range(4):
        for rid in (2 * pack, 2 * pack + 1):
            obs.on_admit(rid, "interactive", seq_len=5, h_px=8, w_px=8)
        obs.on_pack([(2 * pack, "interactive", 5),
                     (2 * pack + 1, "interactive", 5)],
                    phases, device_stats={"tokens_used": 10.0,
                                          "n_segments": 2.0,
                                          "pad_tokens": 30.0,
                                          "stamp": float(pack)},
                    tokens_used=10)
        for rid in (2 * pack, 2 * pack + 1):
            obs.observe_latency("interactive", 0.004, rid)
    summary = obs.finalize()
    tracer.close()
    assert summary["packs"] == 4 and summary["requests"] == 8
    assert summary["windows"] >= 2
    slo = summary["slo"]["interactive"]
    assert slo["n"] == 8
    # 4ms latencies: the histogram p50 within one bucket width
    assert 4.0 / slo["width_factor"] <= slo["p50"] \
        <= 4.0 * slo["width_factor"]
    assert summary["ewma_pad_waste"] == pytest.approx(0.75)
    env = summary["recommended_envelope"]
    assert env["max_seq_len"] == 5 and env["within_threshold"]

    recs = [json.loads(ln) for ln in
            open(tracer.spans_path).read().splitlines()]
    by_name = {}
    for r in recs:
        assert r["v"] == SPAN_SCHEMA_V and r["role"] == "serve"
        assert r["arm"] == "packed" and r["mix"] == "unit"
        by_name.setdefault(r["name"], []).append(r)
    assert len(by_name["serve_request"]) == 8
    for r in by_name["serve_request"]:
        assert r["slo"] == "interactive"
        assert r["enqueue_ms"] is not None
        for f in ("pack_placement_ms", "dispatch_ms", "device_ms",
                  "fetch_ms", "extract_ms"):
            assert r[f] is not None
    assert len(by_name["serve_pack_stats"]) == 4
    assert [r["stamp"] for r in by_name["serve_pack_stats"]] == \
        [0.0, 1.0, 2.0, 3.0]
    assert len(by_name["serve_hist"]) == 1
    h = LogHistogram.from_dict(by_name["serve_hist"][0]["hist"])
    assert h.total == 8
    assert by_name["serve_mix"][0]["recommended_envelope"] is not None
    assert len(by_name["serve_window"]) == summary["windows"]


# ---------------- obs_report helpers ----------------


def test_obs_report_schema_gate_and_hist_bound(tmp_path):
    obs_report = _load_script("obs_report")
    good = tmp_path / "spans.jsonl"
    good.write_text(json.dumps(
        {"v": 1, "role": "serve", "name": "serve_request"}) + "\n")
    records, census = obs_report.load_spans(str(good))
    assert census["lines"] == 1 and records[0]["name"] == "serve_request"
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps({"role": "serve", "name": "x"}) + "\n")
    with pytest.raises(ValueError, match="schema"):
        obs_report.load_spans(str(bad))

    ok = {"interactive": {"n": 4, "width_factor": 1.1548,
                          "p50": 4.1, "p99": 8.2}}
    exact = {"interactive": {"n": 4, "p50_ms": 4.0, "p99_ms": 8.0}}
    rows = obs_report.hist_vs_exact(ok, exact, "t")
    assert rows["interactive"]["p50"]["ratio"] == pytest.approx(
        4.1 / 4.0, abs=1e-4)
    drifted = {"interactive": {**ok["interactive"], "p50": 6.0}}
    with pytest.raises(AssertionError, match="bucket width"):
        obs_report.hist_vs_exact(drifted, exact, "t")
    with pytest.raises(AssertionError, match="no streaming histogram"):
        obs_report.hist_vs_exact({}, exact, "t")


# ---------------- real engine: one fetch, stats ride it ----------------


@pytest.fixture(scope="module")
def tiny_packed_engine():
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.models import build_backbone
    from dinov3_tpu.serve import (
        PackedServeEngine,
        cast_serving_tree,
        serve_layout_from_cfg,
    )

    cfg = get_default_config()
    apply_dot_overrides(cfg, [
        "student.arch=vit_test", "student.patch_size=4",
        "crops.global_crops_size=16", "crops.local_crops_size=8",
        "crops.local_crops_number=2", "train.batch_size_per_device=2",
        "optim.scaling_rule=none", "train.scan_layers=true",
        "serve.min_px=8", "serve.max_px=24", "serve.rows=3",
        "serve.row_tokens=40", "serve.max_segments_per_row=6",
    ])
    model = build_backbone(cfg, teacher=True)
    params = nn.meta.unbox(
        jax.jit(model.init)(jax.random.key(0), jnp.zeros((1, 16, 16, 3)))
    )["params"]
    params = cast_serving_tree(params)
    layout = serve_layout_from_cfg(cfg)
    return PackedServeEngine(model, params, layout, warn=False)


def test_packed_engine_stats_ride_the_one_fetch(tmp_path,
                                                tiny_packed_engine):
    from dinov3_tpu.telemetry.host_sync import host_sync_stats

    eng = tiny_packed_engine
    rng = np.random.default_rng(0)
    tracer = SpanTracer(str(tmp_path), role="serve")
    obs = ServeObserver(tracer, eng.layout, window_packs=2, warn=False)
    eng.observer = obs
    host_sync_stats(reset=True)
    sizes = [(8, 8), (8, 16), (16, 16), (8, 8), (24, 16), (8, 12)]
    for i, (h, w) in enumerate(sizes):
        eng.submit(rng.standard_normal((h, w, 3)).astype(np.float32),
                   request_id=i, slo="batch" if h >= 16 else "interactive")
    responses = []
    while eng.queue_len:
        responses.extend(eng.flush())
    stats = host_sync_stats(reset=True)
    eng.observer = None
    tracer.close()

    assert len(responses) == len(sizes)
    assert {r.slo for r in responses} == {"interactive", "batch"}
    # THE pin: one blocking fetch per pack, observer attached — the
    # stats plane rode the existing ring fetch, zero syncs added
    assert stats["fetches"] == obs.packs

    recs = [json.loads(ln) for ln in
            open(tracer.spans_path).read().splitlines()]
    reqs = [r for r in recs if r["name"] == "serve_request"]
    assert {r["rid"] for r in reqs} == set(range(len(sizes)))
    srows = [r for r in recs if r["name"] == "serve_pack_stats"]
    assert len(srows) == obs.packs
    for r in srows:
        # device-side stats row agrees with the host-side plan: the
        # device counted prefix+patch tokens and live segments from
        # the same seg plane the forward consumed
        assert int(r["tokens_used"]) == int(r["host_tokens_used"])
        assert int(r["n_segments"]) == int(r["host_segments"])
        assert int(r["pad_tokens"]) == \
            eng.layout.token_budget - int(r["tokens_used"])
    # stamps echo the engine's pack counter through the device
    assert [int(r["stamp"]) for r in srows] == sorted(
        int(r["stamp"]) for r in srows)


# ---------------- the committed OBS_r15.json ----------------


def test_obs_r15_acceptance():
    path = os.path.join(REPO, "OBS_r15.json")
    assert os.path.exists(path), "OBS_r15.json missing"
    r = json.load(open(path))
    assert r["smoke"] is False
    assert r["span_schema_v"] == SPAN_SCHEMA_V
    n = int(r["n_per_mix"])
    assert set(r["mixes"]) == {"uniform_224", "mixed_ragged",
                               "heavy_tail"}
    width = 10 ** (1 / 16)
    for mix, rec in r["mixes"].items():
        for arm in ("packed", "oracle_rectangular", "oracle_per_image"):
            a = rec[arm]
            # per-request phase breakdown present for EVERY measured
            # request (drain n + rated replay n)
            assert a["phase_breakdown"]["n_requests"] == 2 * n, (mix, arm)
            for slo, row in a["hist_vs_exact"].items():
                for q in ("p50", "p99"):
                    ratio = row[q]["ratio"]
                    assert 1 / width <= ratio <= width, \
                        (mix, arm, slo, q, ratio)
        # zero added blocking fetches: stats rode the existing ring
        # fetch on every pack
        f = rec["packed"]["fetch_funnel"]
        assert f["fetches_per_pack"] == 1.0, (mix, f)
        assert rec["packed"]["device_stats"]["host_token_mismatches"] == 0
        env = rec["packed"]["recommended_envelope"]
        assert env is not None and env["row_tokens"] > 0
    assert r["worst_hist_exact_ratio"] <= width
    # the SERVE_r14 reference fetch counts ride along for comparison
    assert "reference_fetch_counts" in r
    r14 = json.load(open(os.path.join(REPO, "SERVE_r14.json")))
    for mix, ref in r["reference_fetch_counts"].items():
        assert ref["fetches"] == \
            r14["mixes"][mix]["packed"]["serve"]["host_sync"]["fetches"]
