"""Fused single-pass clip+AdamW+EMA engine (train/fused_update.py) vs
the optax oracle chain.

The engine is the default update path (optim.fused_update); the optax
chain stays in the tree as the reference implementation. These tests pin:
- leaf-for-leaf multi-step equivalence (params, teacher, mu, nu, counts)
  with clip active and inactive, last-layer lr freeze, and wd/lr
  multiplier trees in play. Tolerances: rtol=1e-6, atol=1e-7 — on the
  cpu backend the two programs are in fact bitwise identical (XLA CSE
  canonicalizes them to the same HLO; see docs/PERFORMANCE.md), the
  tolerance budget only covers backend fusion reassociation elsewhere;
- the full train step producing the same state on both paths;
- the engine being the default in build_train_setup and compiling under
  the 8-device dryrun mesh programs (the sharded regression);
- the bytes-accessed reduction mechanism of scripts/cost_update_phase.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.train import (
    build_multiplier_trees,
    clip_by_per_submodel_norm,
    make_fused_update,
    scheduled_adamw,
)
from dinov3_tpu.train.fused_update import ema_leaf
from dinov3_tpu.train.schedules import Schedules

RTOL, ATOL = 1e-6, 1e-7

SMOL = [
    "student.arch=vit_test", "student.patch_size=4",
    "student.drop_path_rate=0.0", "student.layerscale=1.0e-5",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2",
    "dino.head_n_prototypes=32", "dino.head_hidden_dim=24",
    "dino.head_bottleneck_dim=8",
    "ibot.head_n_prototypes=32", "ibot.head_hidden_dim=24",
    "ibot.head_bottleneck_dim=8",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "optim.warmup_epochs=1", "optim.freeze_last_layer_epochs=1",
    "compute_precision.compute_dtype=fp32",
    "optim.scaling_rule=none",
]


def smol_cfg(extra=()):
    cfg = get_default_config()
    apply_dot_overrides(cfg, list(SMOL) + list(extra))
    return cfg


def make_sched(n=16):
    """Non-trivial schedules: varying lr/wd, last-layer frozen 3 steps."""
    lr = np.linspace(0.1, 0.01, n)
    ll = lr.copy()
    ll[:3] = 0.0
    return Schedules(
        lr=lr, weight_decay=np.linspace(0.04, 0.4, n),
        momentum=np.zeros(n), teacher_temp=np.zeros(n),
        last_layer_lr=ll, total_iters=n,
    )


def fake_params():
    """Two submodels (separate clip groups), prototypes (last-layer),
    biases/norms (wd=0), patch embed (lr mult)."""
    return {
        "backbone": {
            "patch_embed": {"kernel": jnp.full((4, 4), 0.5),
                            "bias": jnp.zeros((4,))},
            "blocks_0": {"attn": {"qkv_kernel": jnp.full((4, 12), 0.3)}},
            "norm": {"scale": jnp.ones((4,))},
        },
        "dino_head": {
            "mlp_0": {"kernel": jnp.full((4, 4), 0.2),
                      "bias": jnp.zeros((4,))},
            "prototypes": jnp.full((4, 8), 0.1),
        },
    }


def grads_like(params, key, scale=3.0):
    leaves, treedef = jax.tree.flatten(params)
    keys = jax.random.split(key, len(leaves))
    return jax.tree.unflatten(treedef, [
        jax.random.normal(k, l.shape, l.dtype) * scale
        for k, l in zip(keys, leaves)
    ])


def assert_trees_close(a, b, what):
    for (pa, la), (pb, lb) in zip(
        jax.tree_util.tree_flatten_with_path(a)[0][:64],
        jax.tree_util.tree_flatten_with_path(b)[0][:64],
    ):
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(lb), rtol=RTOL, atol=ATOL,
            err_msg=f"{what}: {jax.tree_util.keystr(pa)}",
        )


@pytest.mark.parametrize("clip", [3.0, 0.05, None])
def test_fused_matches_optax_chain_multistep(clip):
    """>=10 steps, leaf-for-leaf: params, teacher, mu, nu, both counts.

    clip=0.05 forces the clip scale active every step; clip=None takes
    the no-clip branch; clip=3.0 mixes (norm-dependent).
    """
    sched = make_sched()
    params = fake_params()
    lm, wm, ll = build_multiplier_trees(
        params, layerwise_decay=0.9, patch_embed_lr_mult=0.2,
        dino_head_wd_multiplier=0.5,
    )
    opt = scheduled_adamw(sched, lm, wm, ll)
    fused = make_fused_update(sched, lm, wm, ll, clip_grad=clip, ema=True)
    momentum = jnp.asarray(0.95, jnp.float32)

    @jax.jit
    def ref_step(p, t, s, g):
        if clip is not None and clip > 0:
            g, _ = clip_by_per_submodel_norm(g, clip)
        u, s = opt.update(g, s, p)
        p = optax.apply_updates(p, u)
        t = jax.tree.map(lambda tt, ss: ema_leaf(tt, ss, momentum), t, p)
        return p, t, s

    fused_step = jax.jit(
        lambda g, p, t, s: fused(g, p, t, s, momentum)[:3])

    teacher = jax.tree.map(jnp.copy, params)
    p_ref = p_f = params
    t_ref = t_f = teacher
    s_ref = s_f = opt.init(params)
    key = jax.random.key(0)
    for _ in range(10):
        key, k = jax.random.split(key)
        g = grads_like(params, k)
        p_ref, t_ref, s_ref = ref_step(p_ref, t_ref, s_ref, g)
        p_f, t_f, s_f = fused_step(g, p_f, t_f, s_f)

    assert_trees_close(p_ref, p_f, "params")
    assert_trees_close(t_ref, t_f, "teacher")
    assert_trees_close(s_ref.adam.mu, s_f.adam.mu, "mu")
    assert_trees_close(s_ref.adam.nu, s_f.adam.nu, "nu")
    assert int(s_f.count) == 10 and int(s_f.adam.count) == 10
    # the schedules moved and the updates were non-trivial
    assert not np.allclose(np.asarray(jax.tree.leaves(p_f)[0]),
                           np.asarray(jax.tree.leaves(params)[0]))
    # teacher is a blend, not a copy of the student
    assert not np.allclose(np.asarray(jax.tree.leaves(t_f)[0]),
                           np.asarray(jax.tree.leaves(p_f)[0]))


def test_last_layer_freeze_respected():
    """Prototype leaves (last-layer) must not move while last_layer_lr
    is 0, then move — through the fused engine."""
    sched = make_sched()
    params = fake_params()
    lm, wm, ll = build_multiplier_trees(params)
    assert jax.tree.leaves(ll).count(True) == 1  # prototypes flagged
    fused = make_fused_update(sched, lm, wm, ll, clip_grad=None, ema=True)
    momentum = jnp.asarray(0.9, jnp.float32)
    t = jax.tree.map(jnp.copy, params)
    from dinov3_tpu.train import build_optimizer  # noqa: F401 (oracle import)
    from dinov3_tpu.train.optimizer import scheduled_adamw as _sa

    s = _sa(sched, lm, wm, ll).init(params)
    p = params
    key = jax.random.key(1)
    for i in range(5):
        key, k = jax.random.split(key)
        p_new, t, s, _ = fused(grads_like(params, k), p, t, s, momentum)
        proto_moved = not np.allclose(
            np.asarray(p_new["dino_head"]["prototypes"]),
            np.asarray(p["dino_head"]["prototypes"]))
        assert proto_moved == (i >= 3), f"step {i}"
        p = p_new


def test_fused_distillation_passes_teacher_through():
    """ema=False (frozen pretrained distillation teacher): the teacher
    tree is returned untouched — and may have a different structure."""
    sched = make_sched()
    params = fake_params()
    lm, wm, ll = build_multiplier_trees(params)
    fused = make_fused_update(sched, lm, wm, ll, clip_grad=3.0, ema=False)
    teacher = {"other_arch": jnp.ones((3,))}
    from dinov3_tpu.train.optimizer import scheduled_adamw as _sa

    s = _sa(sched, lm, wm, ll).init(params)
    p, t, s, norms = fused(
        grads_like(params, jax.random.key(2)), params, teacher, s,
        jnp.asarray(0.9, jnp.float32))
    assert t is teacher
    assert set(norms) == {"backbone", "dino_head"}
    assert not np.allclose(np.asarray(jax.tree.leaves(p)[0]),
                           np.asarray(jax.tree.leaves(params)[0]))


def test_rejects_foreign_opt_state():
    sched = make_sched()
    params = fake_params()
    lm, wm, ll = build_multiplier_trees(params)
    fused = make_fused_update(sched, lm, wm, ll)
    with pytest.raises(TypeError, match="scheduled_adamw"):
        fused(params, params, params, optax.adam(1e-3).init(params),
              jnp.float32(0.9))


# ---------------- full step + setup integration ----------------

def test_full_train_step_paths_agree():
    """make_train_step with the fused engine == without, end to end
    (same forward/backward, same update math)."""
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_optimizer, build_schedules
    from dinov3_tpu.train.fused_update import build_fused_update
    from dinov3_tpu.train.ssl_meta_arch import SSLMetaArch
    from dinov3_tpu.train.train_step import TrainState, make_train_step

    cfg = smol_cfg()
    meta = SSLMetaArch(cfg)
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, 4, seed=0).items()}
    params = meta.init_params(jax.random.key(0), batch)
    sched = build_schedules(cfg)
    opt = build_optimizer(cfg, params["student"], sched)
    fused = build_fused_update(cfg, params["student"], sched, ema=True)

    states = {}
    for name, engine in (("oracle", None), ("fused", fused)):
        step = jax.jit(make_train_step(
            meta, opt, clip_grad=cfg.optim.clip_grad, fused_update=engine))
        state = TrainState(
            jax.tree.map(jnp.copy, params), opt.init(params["student"]),
            meta.init_state(), jnp.zeros((), jnp.int32))
        for i in range(3):
            scal = sched.at(i)
            scalars = {
                "teacher_temp": jnp.asarray(scal["teacher_temp"], jnp.float32),
                "momentum": jnp.asarray(scal["momentum"], jnp.float32),
            }
            state, metrics = step(state, batch, scalars, jax.random.key(7))
        states[name] = state
        assert np.isfinite(float(metrics["total_loss"]))

    assert_trees_close(states["oracle"].params, states["fused"].params,
                       "full-step params")
    assert_trees_close(states["oracle"].opt_state.adam.nu,
                       states["fused"].opt_state.adam.nu, "full-step nu")


def test_build_train_setup_defaults_to_fused(eight_devices):
    """optim.fused_update defaults on; =false falls back to the oracle
    chain. Also the sharded-compile regression: both programs compile
    and run under dryrun-style 8-device meshes (dp x fsdp x seq with
    subset drop-path, and dp x fsdp x tensor)."""
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup, put_batch

    for axes, extra in (
        (["parallel.data=-1", "parallel.fsdp=2", "parallel.seq=2",
          "parallel.zero3=false"],
         ["student.drop_path_rate=0.5", "student.drop_path_mode=subset"]),
        (["parallel.data=-1", "parallel.fsdp=2", "parallel.tensor=2",
          "parallel.zero3=false"],
         ["optim.fused_update=false"]),
    ):
        cfg = smol_cfg(axes + extra)
        B = 16 if "student.drop_path_rate=0.5" in extra else 8
        batch = {k: jnp.asarray(v) for k, v in
                 make_synthetic_batch(cfg, B, seed=0).items()}
        setup = build_train_setup(cfg, batch, devices=eight_devices)
        assert (setup.fused_update is not None) == bool(
            cfg.optim.fused_update)
        d = put_batch(batch, setup.batch_shardings)
        state, metrics = setup.step_fn(
            setup.state, d, setup.scalars(0), jax.random.key(0))
        assert np.isfinite(float(metrics["total_loss"]))
        assert int(state.step) == 1


def test_sharded_fused_matches_oracle(eight_devices):
    """Same mesh, same batch: the two update paths produce identical
    losses and parameters after 2 sharded steps."""
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup, put_batch

    results = {}
    for flag in ("true", "false"):
        cfg = smol_cfg(["parallel.data=-1", "parallel.fsdp=2",
                        "parallel.zero3=false",
                        f"optim.fused_update={flag}"])
        batch = {k: jnp.asarray(v) for k, v in
                 make_synthetic_batch(cfg, 8, seed=0).items()}
        setup = build_train_setup(cfg, batch, devices=eight_devices)
        d = put_batch(batch, setup.batch_shardings)
        state = setup.state
        for i in range(2):
            state, m = setup.step_fn(state, d, setup.scalars(i),
                                     jax.random.key(0))
        results[flag] = (state, float(m["total_loss"]))

    assert results["true"][1] == pytest.approx(results["false"][1], rel=1e-6)
    assert_trees_close(results["true"][0].params, results["false"][0].params,
                       "sharded params")


# ---------------- bytes-accessed mechanism ----------------

def test_cost_accounting_reduction():
    """scripts/cost_update_phase.py's accounting on the test arch: the
    fused single program accesses fewer bytes than the four-pass chain
    (the committed ViT-L numbers in docs/PERFORMANCE.md use the same
    code path; -34.3% there)."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "cost_update_phase",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "cost_update_phase.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rec = mod.measure(smol_cfg())
    assert rec["bytes_fused"] < rec["bytes_chain_total"]
    assert rec["reduction_pct"] >= 20.0
    assert rec["bytes_fused"] >= rec["floor_bytes"]
    assert set(rec["bytes_chain_passes"]) == {
        "clip", "adamw", "apply", "ema"}
