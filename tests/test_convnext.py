"""ConvNeXt backbone: live, ViT-contract-compatible (the reference's was
dead code with syntax errors, SURVEY.md §2.2)."""

import jax
import jax.numpy as jnp
import pytest

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.models import build_backbone
from dinov3_tpu.models.convnext import CONVNEXT_SIZES, get_convnext_arch


def _cfg(arch="convnext_test"):
    cfg = get_default_config()
    apply_dot_overrides(cfg, [
        f"student.arch={arch}", "student.patch_size=4",
        "crops.global_crops_size=32", "crops.local_crops_size=16",
        "crops.local_crops_number=2",
        "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
        "dino.head_bottleneck_dim=16",
        "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
        "ibot.head_bottleneck_dim=16",
        "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
        "optim.scaling_rule=none",
    ])
    return cfg


def test_forward_contract(rng):
    model = build_backbone(_cfg(), teacher=False)
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    params = model.init(rng, x)
    out = model.apply(params, x, crop_kind="global", deterministic=True)
    # pseudo patch grid: 32/4 = 8 -> 64 tokens at embed_dim 64
    assert out["x_norm_clstoken"].shape == (2, 64)
    assert out["x_norm_patchtokens"].shape == (2, 64, 64)
    assert jnp.isfinite(out["x_norm_clstoken"].astype(jnp.float32)).all()


def test_size_table_and_unknown():
    assert CONVNEXT_SIZES["large"]["dims"] == (192, 384, 768, 1536)
    ctor = get_convnext_arch("convnext_tiny")
    model = ctor()
    assert model.dims == (96, 192, 384, 768)
    with pytest.raises(ValueError, match="unknown convnext size"):
        get_convnext_arch("convnext_nope")


def test_get_intermediate_layers(rng):
    model = build_backbone(_cfg(), teacher=True)
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    params = model.init(rng, x)
    outs = model.apply(
        params, x, 2, method=model.get_intermediate_layers,
        return_class_token=True,
    )
    assert len(outs) == 2
    tokens, cls = outs[-1]
    assert cls.shape == (2, 64)
    assert tokens.shape[0] == 2 and tokens.shape[-1] == 64


@pytest.mark.slow
def test_convnext_ssl_train_step():
    """ConvNeXt student through the full fused SSL step (distillation-style:
    no iBOT token masking inside the convnet)."""
    import numpy as np

    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup, put_batch

    cfg = _cfg()
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, 4, seed=0).items()}
    setup = build_train_setup(cfg, batch)
    dbatch = put_batch(batch, setup.batch_shardings)
    state, metrics = setup.step_fn(
        setup.state, dbatch, setup.scalars(0), jax.random.key(0)
    )
    assert np.isfinite(float(metrics["total_loss"]))
    assert int(state.step) == 1
