"""Anatomy-driven collective auto-tuner (ISSUE 16): artifact pins,
resolver semantics, knob census, the tuned-vs-handset gate, and the
schedule-knob equivalences the tuner's candidate axes rely on.

Pinned here:

- the committed ``TUNED_r20.json`` plan validates, carries every knob
  with its full measurement trail, and every ``chosen`` (including the
  derived ring floor) is re-derivable from the committed floats alone
  (tuning/plan.py ``select_best`` / tuning/search.py
  ``derive_ring_trail``) — the artifact never asks to be trusted;
- the "auto" resolvers (configs/config.py resolve_bucket_mb /
  resolve_staging_order / resolve_stream_prefetch /
  resolve_ring_min_seq): explicit values pass through untouched (the
  hand-set oracle), "auto" reads the artifact bitwise-
  deterministically, and unreadable/stale artifacts warn loudly and
  fall back to the exact pre-tuner constants;
- ``warn_tuned_plan_stale``'s dual modes and the knob census's
  no-silent-knobs guarantee (tuning/census.py);
- ``perf_gate.tuned_vs_handset``: the committed plan is never worse
  than the hand-set schedule on any arm, and a perturbed plan fails;
- candidate-axis equivalences: every stream-prefetch depth and every
  staging order computes the SAME numbers (they are pure wire
  schedules), so the tuner is free to pick any of them on latency
  alone.
"""

import copy
import importlib.util
import json
import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.configs.config import (
    TUNED_ARTIFACT,
    TUNED_FALLBACKS,
    resolve_bucket_mb,
    resolve_ring_min_seq,
    resolve_staging_order,
    resolve_stream_prefetch,
    tuned_fingerprint_mismatches,
    warn_tuned_plan_stale,
)
from dinov3_tpu.parallel.mesh import MeshSpec, build_mesh
from dinov3_tpu.tuning import (
    KNOBS,
    TUNED_SCHEMA,
    derive_ring_trail,
    knob_census,
    load_tuned_plan,
    select_best,
    sweep_knob,
    trail_row,
    tuned_plan_provenance,
    validate_plan,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# a live fingerprint that MATCHES the fake artifact below (not the
# committed one — these tests never depend on the committed tuning)
def _fake_live():
    return {"arch": "vit_test", "device_count": 8,
            "update_shard_size": 8, "jax": jax.__version__}


def _fake_doc():
    return {
        "schema": TUNED_SCHEMA,
        "generated_by": "test",
        "fingerprint": _fake_live(),
        "knobs": {
            "bucket_mb": {
                "chosen": 64, "handset": TUNED_FALLBACKS["bucket_mb"],
                "program": "test",
                "trail": [{"value": 64, "objective_ms": 1.0},
                          {"value": 128, "objective_ms": 2.0}]},
            "staging_order": {
                "chosen": "intra_inter",
                "handset": TUNED_FALLBACKS["staging_order"],
                "program": "test",
                "trail": [{"value": "inter_intra", "objective_ms": 2.0},
                          {"value": "intra_inter", "objective_ms": 1.0}]},
            "stream_prefetch": {
                "chosen": 2, "handset": TUNED_FALLBACKS["stream_prefetch"],
                "program": "test",
                "trail": [{"value": 1, "objective_ms": 2.0},
                          {"value": 2, "objective_ms": 1.0}]},
            "ring_min_seq": {
                "chosen": 512, "handset": TUNED_FALLBACKS["ring_min_seq"],
                "program": "test",
                "trail": [{"value": 512, "objective_ms": 1.0},
                          {"value": 1024, "objective_ms": 2.0}]},
        },
    }


@pytest.fixture
def fake_artifact(tmp_path):
    p = tmp_path / "TUNED_fake.json"
    p.write_text(json.dumps(validate_plan(_fake_doc())))
    return p


# ---------------- pure selection / derivation ----------------

def test_select_best_first_minimal_ties_to_earlier():
    trail = [{"value": "a", "objective_ms": 2.0},
             {"value": "b", "objective_ms": 1.5},
             {"value": "c", "objective_ms": 1.5}]
    assert select_best(trail) == "b"  # strict-< scan: tie -> earlier
    with pytest.raises(ValueError):
        select_best([])


def test_sweep_knob_preserves_candidate_order_and_fields():
    calls = []

    def measure(v):
        calls.append(v)
        return {"objective_ms": float(10 - v),
                "step_wall_ms_mean": float(v),
                "exposed_comm_ms_per_step": 0.5,
                "exposed_comm_frac": 0.1}

    trail = sweep_knob("k", (1, 2, 3), measure)
    assert calls == [1, 2, 3]
    assert [r["value"] for r in trail] == [1, 2, 3]
    assert all("objective_ms" in r and "exposed_comm_frac" in r
               for r in trail)
    assert trail_row(7, {"objective_ms": 1.0}, derived=True) == {
        "value": 7, "objective_ms": 1.0, "derived": True}


def test_derive_ring_trail_is_exact_arithmetic():
    workloads = [
        {"tokens": 256, "ring_objective_ms": 5.0,
         "dense_objective_ms": 3.0},
        {"tokens": 1024, "ring_objective_ms": 7.0,
         "dense_objective_ms": 11.0},
    ]
    trail = derive_ring_trail(workloads, candidates=(256, 512, 2048))
    by_floor = {r["value"]: r for r in trail}
    # floor 256: both workloads ring -> 5 + 7
    assert by_floor[256]["objective_ms"] == 12.0
    # floor 512: 256 dense, 1024 rings -> 3 + 7 (the winner here)
    assert by_floor[512]["objective_ms"] == 10.0
    # floor 2048: everything dense -> 3 + 11
    assert by_floor[2048]["objective_ms"] == 14.0
    assert select_best(trail) == 512
    assert all(r["derived"] for r in trail)
    assert by_floor[512]["dispatch"] == [
        {"tokens": 256, "impl": "dense"}, {"tokens": 1024, "impl": "ring"}]


def test_validate_plan_catches_violations():
    validate_plan(_fake_doc())  # the well-formed baseline passes
    bad = _fake_doc()
    bad["schema"] = "nope/v0"
    with pytest.raises(ValueError, match="schema"):
        validate_plan(bad)
    bad = _fake_doc()
    del bad["fingerprint"]["arch"]
    with pytest.raises(ValueError, match="fingerprint"):
        validate_plan(bad)
    bad = _fake_doc()
    bad["knobs"]["bucket_mb"]["chosen"] = 128  # not select_best(trail)
    with pytest.raises(ValueError, match="re-derivable"):
        validate_plan(bad)
    bad = _fake_doc()
    bad["knobs"]["bucket_mb"]["handset"] = 999  # not the oracle
    with pytest.raises(ValueError, match="oracle"):
        validate_plan(bad)
    bad = _fake_doc()
    bad["knobs"]["mystery"] = bad["knobs"].pop("bucket_mb")
    with pytest.raises(ValueError, match="unknown knob"):
        validate_plan(bad)


# ---------------- the committed artifact ----------------

def test_committed_plan_valid_and_complete():
    """TUNED_r20.json: validates, carries the FULL knob set with
    measurement trails, and was tuned on the 8-device ViT-L setup the
    fingerprint claims."""
    doc = load_tuned_plan()  # validate_plan already ran
    assert set(doc["knobs"]) == set(KNOBS)
    fp = doc["fingerprint"]
    assert fp["arch"] == "vit_large"
    assert fp["device_count"] == 8
    assert doc["generated_by"] == "scripts/tune_collectives.py"
    # every trail row commits the objective decomposition (derived
    # ring rows commit the dispatch split instead)
    for name, entry in doc["knobs"].items():
        assert len(entry["trail"]) >= 2, f"{name}: no search happened"
        for row in entry["trail"]:
            assert "objective_ms" in row
            assert "step_wall_ms_mean" in row or row.get("derived"), (
                f"{name}: measured row missing its decomposition")


def test_committed_chosen_rederivable_from_trails():
    doc = load_tuned_plan()
    for name, entry in doc["knobs"].items():
        assert entry["chosen"] == select_best(entry["trail"]), name
        assert entry["handset"] == TUNED_FALLBACKS[name], name


def test_committed_ring_trail_rederivable_from_workloads():
    """The ring floor's whole trail is arithmetic over the committed
    ring-vs-dense workload table — re-derive it and compare."""
    from dinov3_tpu.telemetry.anatomy import round_floats

    doc = load_tuned_plan()
    entry = doc["knobs"]["ring_min_seq"]
    workloads = entry["workloads"]
    assert len(workloads) >= 2
    floors = tuple(r["value"] for r in entry["trail"])
    redone = round_floats(derive_ring_trail(
        [{"tokens": w["tokens"],
          "ring_objective_ms": w["ring_objective_ms"],
          "dense_objective_ms": w["dense_objective_ms"]}
         for w in workloads], candidates=floors))
    committed = [{"value": r["value"], "objective_ms": r["objective_ms"],
                  "dispatch": r["dispatch"], "derived": r["derived"]}
                 for r in entry["trail"]]
    assert redone == committed


def test_committed_plan_resolves_bitwise_deterministically():
    """Two resolutions of every auto knob from the committed artifact
    are identical — and equal to the committed chosen values (matching
    live fingerprint)."""
    doc = load_tuned_plan()
    live = dict(doc["fingerprint"])  # live == tuned -> no fallback
    resolvers = {
        "bucket_mb": resolve_bucket_mb,
        "staging_order": resolve_staging_order,
        "stream_prefetch": resolve_stream_prefetch,
        "ring_min_seq": resolve_ring_min_seq,
    }
    for name, resolve in resolvers.items():
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # no fallback warning allowed
            a = resolve("auto", live=live)
            b = resolve("auto", live=live)
        assert a == b == doc["knobs"][name]["chosen"], name


# ---------------- resolver semantics ----------------

def test_resolvers_explicit_passthrough_is_the_oracle():
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # explicit values never warn
        assert resolve_bucket_mb(64) == 64
        assert resolve_bucket_mb("96") == 96
        assert resolve_ring_min_seq(0) == 0  # the ops-layer sentinel
        assert resolve_staging_order("intra_inter") == "intra_inter"
        assert resolve_stream_prefetch(0) == 0
        assert resolve_stream_prefetch(2) == 2


def test_resolvers_auto_read_artifact(fake_artifact):
    live = _fake_live()
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert resolve_bucket_mb(
            "auto", artifact=fake_artifact, live=live) == 64
        assert resolve_staging_order(
            "auto", artifact=fake_artifact, live=live) == "intra_inter"
        assert resolve_stream_prefetch(
            "auto", artifact=fake_artifact, live=live) == 2
        assert resolve_ring_min_seq(
            "auto", artifact=fake_artifact, live=live) == 512
        # None/"" normalize to "auto" (yaml null, empty override)
        assert resolve_bucket_mb(
            None, artifact=fake_artifact, live=live) == 64


def test_resolvers_unreadable_artifact_falls_back_loudly(tmp_path):
    gone = tmp_path / "nope.json"
    with pytest.warns(UserWarning, match="unreadable"):
        assert resolve_bucket_mb("auto", artifact=gone) == 128
    with pytest.warns(UserWarning, match="unreadable"):
        assert resolve_ring_min_seq("auto", artifact=gone) == 1024
    with pytest.warns(UserWarning, match="unreadable"):
        assert resolve_staging_order(
            "auto", artifact=gone) == "inter_intra"
    with pytest.warns(UserWarning, match="unreadable"):
        assert resolve_stream_prefetch("auto", artifact=gone) == 1
    # a partial artifact (readable json, missing the knob) degrades the
    # same way — never a crash
    partial = tmp_path / "partial.json"
    partial.write_text(json.dumps({"knobs": {}}))
    with pytest.warns(UserWarning, match="unreadable"):
        assert resolve_bucket_mb("auto", artifact=partial) == 128


def test_resolvers_stale_fingerprint_falls_back_loudly(fake_artifact):
    live = _fake_live()
    live["arch"] = "vit_large"  # artifact was "tuned" for vit_test
    with pytest.warns(UserWarning, match="different setup"):
        assert resolve_bucket_mb(
            "auto", artifact=fake_artifact, live=live) == 128
    with pytest.warns(UserWarning, match="different setup"):
        assert resolve_stream_prefetch(
            "auto", artifact=fake_artifact, live=live) == 1
    # without a live fingerprint there is nothing to compare: the
    # artifact applies (the config-load path stays device-free)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert resolve_bucket_mb("auto", artifact=fake_artifact) == 64


def test_resolvers_reject_invalid_explicit_values():
    with pytest.raises(ValueError):
        resolve_staging_order("sideways_inter")
    with pytest.raises(ValueError):
        resolve_stream_prefetch(-1)


def test_fingerprint_mismatch_semantics():
    fp = _fake_live()
    assert tuned_fingerprint_mismatches(fp, dict(fp)) == []
    # jax compares at major.minor: a patch bump is not staleness
    live = dict(fp)
    live["jax"] = ".".join(jax.__version__.split(".")[:2]) + ".999"
    assert tuned_fingerprint_mismatches(fp, live) == []
    live = dict(fp, device_count=256)
    bad = tuned_fingerprint_mismatches(fp, live)
    assert len(bad) == 1 and "device_count" in bad[0]


# ---------------- warn_tuned_plan_stale ----------------

def _cfg_with(overrides):
    cfg = get_default_config()
    apply_dot_overrides(cfg, overrides)
    return cfg


def test_warn_stale_silent_when_all_knobs_handset(fake_artifact):
    cfg = _cfg_with([
        "optim.bucket_mb=128", "optim.staging_order=inter_intra",
        "optim.stream_prefetch=1", "kernels.ring_min_seq=1024"])
    live = {"arch": "other", "device_count": 1,
            "update_shard_size": 1, "jax": jax.__version__}
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert warn_tuned_plan_stale(
            cfg, live=live, artifact=fake_artifact) is None


def test_warn_stale_names_the_mismatched_axes(fake_artifact):
    cfg = get_default_config()  # schedule knobs default to "auto"
    live = _fake_live()
    live.update(arch="vit_large", device_count=256)
    with pytest.warns(UserWarning) as rec:
        msg = warn_tuned_plan_stale(cfg, live=live,
                                    artifact=fake_artifact)
    assert msg is not None and msg in str(rec[0].message)
    assert "arch" in msg and "device_count" in msg
    assert "bucket_mb" in msg  # names the auto knobs that fall back
    # matching live: silent
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert warn_tuned_plan_stale(
            cfg, live=_fake_live(), artifact=fake_artifact) is None


def test_warn_stale_without_live_checks_wellformedness(tmp_path,
                                                      fake_artifact):
    cfg = get_default_config()
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert warn_tuned_plan_stale(cfg, artifact=fake_artifact) is None
    maimed = tmp_path / "nofp.json"
    doc = _fake_doc()
    del doc["fingerprint"]["update_shard_size"]
    maimed.write_text(json.dumps(doc))
    with pytest.warns(UserWarning, match="update_shard_size"):
        assert warn_tuned_plan_stale(cfg, artifact=maimed) is not None


def test_committed_artifact_fingerprint_wellformed():
    cfg = get_default_config()
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert warn_tuned_plan_stale(cfg, artifact=TUNED_ARTIFACT) is None


# ---------------- knob census ----------------

def test_knob_census_green_on_default_config():
    res = knob_census()
    assert res["ok"], (res["unregistered"], res["stale_registry"])
    assert res["n_knobs"] >= 20
    assert set(res["by_kind"]["tuned"]) == {
        "optim.bucket_mb", "optim.staging_order",
        "optim.stream_prefetch", "kernels.ring_min_seq"}
    assert "kernels.flash_min_seq" in res["by_kind"]["crossover"]


def test_knob_census_catches_unregistered_magic_number():
    cfg = get_default_config()
    shadow = {
        "optim": {k: cfg.optim.get(k) for k in cfg.optim},
        "kernels": {k: cfg.kernels.get(k) for k in cfg.kernels},
    }
    shadow["optim"]["mystery_latency_knob"] = 7
    res = knob_census(shadow)
    assert not res["ok"]
    assert any(u["knob"] == "optim.mystery_latency_knob"
               for u in res["unregistered"])
    # bools are mode switches, not magnitudes: never censused
    shadow["optim"].pop("mystery_latency_knob")
    shadow["optim"]["mystery_toggle"] = True
    assert knob_census(shadow)["ok"]


def test_knob_census_catches_stale_registry_entry():
    cfg = get_default_config()
    shadow = {
        "optim": {k: cfg.optim.get(k) for k in cfg.optim
                  if k != "bucket_mb"},  # "renamed away" a tuned knob
        "kernels": {k: cfg.kernels.get(k) for k in cfg.kernels},
    }
    res = knob_census(shadow)
    assert not res["ok"]
    assert "optim.bucket_mb" in res["stale_registry"]


# ---------------- perf gate: tuned vs hand-set ----------------

def test_perf_gate_tuned_vs_handset_committed_plan_passes():
    pg = _load_script("perf_gate")
    doc = load_tuned_plan()
    res = pg.tuned_vs_handset(doc)
    assert res["passed"], json.dumps(res, indent=1)
    assert res["n_arms"] == len(doc["arms"])
    assert "plan-invariant" in res["arm_notes"].get("replicated", "")


def test_perf_gate_tuned_vs_handset_catches_regression():
    pg = _load_script("perf_gate")
    doc = copy.deepcopy(load_tuned_plan())
    # a "tuned" plan 50% slower than HAND-SET on one arm must fail —
    # anchor the synthetic regression to the handset measurement so the
    # test holds however wide the committed plan's tuned-vs-handset
    # margin happens to be
    anat = doc["arms"]["bucketed"]["tuned"]["anatomy"]
    hand = doc["arms"]["bucketed"]["handset"]["anatomy"]
    anat["step_wall_ms"]["mean"] = hand["step_wall_ms"]["mean"] * 1.5
    res = pg.tuned_vs_handset(doc)
    assert not res["passed"]
    assert any(c["arm"] == "bucketed" and "FAIL" in c["status"]
               for c in res["checks"])


def test_perf_gate_tuned_vs_handset_catches_objective_regression():
    pg = _load_script("perf_gate")
    doc = copy.deepcopy(load_tuned_plan())
    anat = doc["arms"]["bucketed"]["tuned"]["anatomy"]
    hand = doc["arms"]["bucketed"]["handset"]["anatomy"]
    anat["objective_ms"] = hand["objective_ms"] * 1.5
    res = pg.tuned_vs_handset(doc)
    assert not res["passed"]
    assert any(c["arm"] == "bucketed" and c["metric"] == "objective_ms"
               and "FAIL" in c["status"] for c in res["checks"])


def test_perf_gate_tuned_vs_handset_ignores_fraction_rise():
    # a schedule that halves the wall while shrinking exposed ms RAISES
    # exposed_comm_frac (smaller denominator) — the cross-revision
    # fraction gate would fail exactly this win; tuned-vs-handset must
    # pass it (step wall and objective both improved).
    pg = _load_script("perf_gate")
    doc = copy.deepcopy(load_tuned_plan())
    anat = doc["arms"]["bucketed"]["tuned"]["anatomy"]
    hand = doc["arms"]["bucketed"]["handset"]["anatomy"]
    anat["step_wall_ms"] = dict(hand["step_wall_ms"],
                                mean=hand["step_wall_ms"]["mean"] * 0.5)
    anat["exposed_comm_ms_per_step"] = (
        hand["exposed_comm_ms_per_step"] * 0.7)
    anat["objective_ms"] = (anat["step_wall_ms"]["mean"]
                            + anat["exposed_comm_ms_per_step"])
    anat["exposed_comm_frac"] = min(
        1.0, hand["exposed_comm_frac"] + 0.30)  # fraction jumps anyway
    res = pg.tuned_vs_handset(doc)
    assert all("FAIL" not in c["status"] for c in res["checks"]
               if c["arm"] == "bucketed"), json.dumps(res, indent=1)


# ---------------- provenance (the bench.py embedding) ----------------

def test_provenance_source_classification(fake_artifact, tmp_path):
    live = _fake_live()
    cfg = {"optim": {"bucket_mb": 96, "staging_order": "auto",
                     "stream_prefetch": "auto"},
           "kernels": {"ring_min_seq": "auto"}}
    prov = tuned_plan_provenance(cfg, artifact=fake_artifact, live=live)
    assert prov["artifact_readable"] and not prov["stale"]
    k = prov["knobs"]
    assert k["bucket_mb"] == {"configured": 96, "resolved": 96,
                              "source": "explicit"}
    assert k["staging_order"]["source"] == "tuned"
    assert k["staging_order"]["resolved"] == "intra_inter"
    assert k["ring_min_seq"] == {"configured": "auto", "resolved": 512,
                                 "source": "tuned"}
    # stale live: every auto knob falls back, labelled as such
    stale_live = dict(live, arch="vit_giant")
    prov = tuned_plan_provenance(cfg, artifact=fake_artifact,
                                 live=stale_live)
    assert prov["stale"]
    assert k_src(prov, "stream_prefetch") == "fallback_stale"
    assert prov["knobs"]["stream_prefetch"]["resolved"] == 1
    assert k_src(prov, "bucket_mb") == "explicit"  # explicit unaffected
    # unreadable artifact
    prov = tuned_plan_provenance(cfg, artifact=tmp_path / "gone.json",
                                 live=live)
    assert not prov["artifact_readable"]
    assert k_src(prov, "ring_min_seq") == "fallback_unreadable"
    assert prov["knobs"]["ring_min_seq"]["resolved"] == 1024


def k_src(prov, name):
    return prov["knobs"][name]["source"]


# ---------------- candidate-axis equivalences ----------------

def _stream_fixture():
    import flax.linen as nn

    from dinov3_tpu.models.streaming import (
        cast_stream_leaves,
        make_block_apply,
    )
    from dinov3_tpu.ops.block import SelfAttentionBlock
    from dinov3_tpu.parallel.context import set_current_mesh
    from dinov3_tpu.parallel.sharding import zero3_leaf_spec
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = build_mesh(MeshSpec(data=8), devices=jax.devices())
    set_current_mesh(mesh)
    kwargs = dict(dim=32, num_heads=2, ffn_ratio=2.0,
                  drop_path_rate=0.0, dtype=jnp.float32)
    L, N, D = 4, 9, 32
    block = SelfAttentionBlock(**kwargs)
    one = nn.meta.unbox(
        block.init(jax.random.key(0), jnp.zeros((1, N, D), jnp.float32))
    )["params"]
    stack = jax.tree.map(
        lambda p: jnp.stack([p + 0.01 * i for i in range(L)]), one)
    stack = cast_stream_leaves(stack, jnp.float32)

    def sh(p):
        spec = zero3_leaf_spec(
            p.shape, ("layers",) + (None,) * (p.ndim - 1), mesh)
        return NamedSharding(mesh, spec if spec is not None else P())

    stack_sh = jax.tree.map(sh, stack)
    x = jax.random.normal(jax.random.key(1), (16, N, D), jnp.float32)
    return (mesh, jax.device_put(stack, stack_sh), stack_sh,
            jax.device_put(x, NamedSharding(mesh, P("data"))),
            NamedSharding(mesh, P("data")), L, make_block_apply(kwargs))


def test_stream_prefetch_depths_bitwise_equivalent():
    """Every lookahead depth (and the legacy booleans) computes the
    SAME forward bitwise — depth is purely a gather schedule, which is
    exactly why the tuner may pick any of 0/1/2 on latency alone."""
    from dinov3_tpu.models.streaming import (
        prefetch_depth,
        streamed_block_scan,
    )

    assert (prefetch_depth(False), prefetch_depth(True)) == (0, 1)
    assert (prefetch_depth(0), prefetch_depth(1), prefetch_depth(3)) \
        == (0, 1, 3)

    mesh, stack, stack_sh, x, x_sh, L, apply_fn = _stream_fixture()
    outs = []
    with mesh:
        for depth in (False, 0, True, 1, 2, 3):
            outs.append(np.asarray(jax.jit(
                lambda s, xx, d=depth: streamed_block_scan(
                    apply_fn, s, xx, L, mesh, prefetch=d),
                in_shardings=(stack_sh, x_sh))(stack, x)))
    for o in outs[1:]:
        assert np.array_equal(outs[0], o)


def test_bucketed_stream_prefetch_and_orders_bitwise(eight_devices):
    """bucketed_stream_scan: every prefetch depth AND every staging
    order of the hierarchical gather path is bitwise the flat
    double-buffered baseline."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dinov3_tpu.models.streaming import bucketed_stream_scan
    from dinov3_tpu.parallel.sharding import STAGING_ORDERS

    mesh = build_mesh(MeshSpec(data=2, fsdp=4), devices=eight_devices)
    shards = jnp.arange(4 * 64, dtype=jnp.float32).reshape(4, 64) * 0.01
    x = jnp.ones((8, 16), jnp.bfloat16)
    sh = jax.device_put(
        shards, NamedSharding(mesh, P(None, ("data", "fsdp"))))
    xx = jax.device_put(x, NamedSharding(mesh, P("data")))

    ref = np.asarray(jax.jit(lambda s, v: bucketed_stream_scan(
        s, v, mesh=mesh))(sh, xx))
    for depth in (0, 1, 2):
        got = jax.jit(lambda s, v, d=depth: bucketed_stream_scan(
            s, v, mesh=mesh, prefetch=d))(sh, xx)
        assert np.array_equal(ref, np.asarray(got)), f"depth {depth}"
    for order in STAGING_ORDERS:
        got = jax.jit(lambda s, v, o=order: bucketed_stream_scan(
            s, v, mesh=mesh, prefetch=1, hierarchical=True,
            staging_order=o))(sh, xx)
        assert np.array_equal(ref, np.asarray(got)), order


def test_staging_orders_equivalent_through_gather_schedule(
        eight_devices):
    """make_zero3_gather_schedule under all four staging orders:
    forward bitwise identical (pure wire schedule), grads equal at
    float tolerance (the RS transpose only reorders the reduction)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dinov3_tpu.parallel.sharding import (
        STAGING_ORDERS,
        split_staging_order,
        zero3_leaf_spec,
    )
    from dinov3_tpu.train.fused_update import (
        make_zero3_bucket_plan,
        make_zero3_gather_schedule,
    )

    assert STAGING_ORDERS == (
        "inter_intra", "intra_inter", "inter_inter", "intra_intra")
    assert split_staging_order("intra_inter") == ("intra", "inter")

    mesh = build_mesh(MeshSpec(data=2, fsdp=4), devices=eight_devices)
    rng = np.random.default_rng(0)
    tree_np = {"w": rng.normal(size=(64, 8)).astype(np.float32),
               "b": rng.normal(size=(48,)).astype(np.float32)}

    def put(x):
        spec = zero3_leaf_spec(x.shape, (None,) * x.ndim, mesh)
        return jax.device_put(jnp.asarray(x), NamedSharding(
            mesh, spec if spec else P()))

    tree = jax.tree.map(put, tree_np)
    plan = make_zero3_bucket_plan(tree, mesh, target_bytes=2 ** 9)

    def loss_of(g):
        def loss(t):
            return sum(jnp.sum(jnp.sin(le.astype(jnp.float32)))
                       for le in jax.tree.leaves(g(t)))
        return loss

    outs, grads = {}, {}
    for order in STAGING_ORDERS:
        g = make_zero3_gather_schedule(plan, mesh, bucketed=True,
                                       staging_order=order)
        outs[order] = jax.jit(g)(tree)
        grads[order] = jax.jit(jax.grad(loss_of(g)))(tree)
    ref = outs["inter_intra"]
    for order in STAGING_ORDERS[1:]:
        for a, b in zip(jax.tree.leaves(ref),
                        jax.tree.leaves(outs[order])):
            assert np.array_equal(np.asarray(a), np.asarray(b)), order
        for a, b in zip(jax.tree.leaves(grads["inter_intra"]),
                        jax.tree.leaves(grads[order])):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6,
                err_msg=order)
