import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dinov3_tpu.configs import get_default_config, apply_dot_overrides
from dinov3_tpu.train import (
    build_multiplier_trees,
    build_optimizer,
    build_schedules,
    clip_by_per_submodel_norm,
    cosine_schedule,
    linear_warmup_cosine_decay,
    scheduled_adamw,
)
from dinov3_tpu.train.schedules import Schedules


# ---------------- schedules ----------------

def test_cosine_schedule_shape_and_endpoints():
    s = cosine_schedule(1.0, 0.1, 100, warmup_iters=10, freeze_iters=5)
    assert len(s) == 100
    np.testing.assert_allclose(s[:5], 0.0)
    np.testing.assert_allclose(s[5], 0.0)  # warmup starts at 0
    np.testing.assert_allclose(s[14], 1.0, atol=0.12)  # warmup tops at base
    np.testing.assert_allclose(s[15], 1.0, atol=1e-9)  # cos starts at base
    assert s[-1] < 0.11  # decays toward final


def test_cosine_trunc_extra_ends_at_final():
    s = cosine_schedule(1.0, 0.01, 100, trunc_extra=0.25)
    assert len(s) == 100
    np.testing.assert_allclose(s[0], 1.0, atol=1e-9)
    np.testing.assert_allclose(s[-1], 0.01, atol=1e-9)
    assert np.all(np.diff(s) <= 1e-12)  # monotone decay


def test_linear_warmup_cosine_decay_segments():
    s = linear_warmup_cosine_decay(0.0, 1.0, 0.1, 10, 50, cosine_iterations=20)
    assert len(s) == 50
    assert s[9] < 1.0  # endpoint=False: warmup never hits peak early
    np.testing.assert_allclose(s[10], 1.0, atol=1e-9)
    np.testing.assert_allclose(s[30:], 0.1, atol=1e-9)  # constant tail


def test_build_schedules_v1():
    cfg = get_default_config()
    apply_dot_overrides(cfg, [
        "train.OFFICIAL_EPOCH_LENGTH=10", "optim.epochs=10",
        "optim.warmup_epochs=2", "optim.freeze_last_layer_epochs=1",
        "teacher.warmup_teacher_temp_epochs=3", "optim.lr=0.002",
    ])
    s = build_schedules(cfg)
    assert s.total_iters == 100
    np.testing.assert_allclose(s.last_layer_lr[:10], 0.0)
    assert s.last_layer_lr[15] == s.lr[15]
    np.testing.assert_allclose(s.teacher_temp[0], 0.04, atol=1e-9)
    np.testing.assert_allclose(s.teacher_temp[40:], 0.07, atol=1e-9)
    np.testing.assert_allclose(s.momentum[0], 0.992, atol=1e-9)
    np.testing.assert_allclose(s.momentum[-1], 1.0, atol=1e-3)
    # .at clamps beyond the end
    assert s.at(10**9)["lr"] == s.lr[-1]


def test_build_schedules_v2():
    cfg = get_default_config()
    apply_dot_overrides(cfg, [
        "train.OFFICIAL_EPOCH_LENGTH=10", "optim.epochs=10",
    ])
    cfg["schedules"] = {
        "lr": {"start": 0.0, "peak": 1e-3, "end": 1e-6, "warmup_epochs": 2,
               "freeze_last_layer_epochs": 1},
        "weight_decay": {"start": 0.04, "peak": 0.04, "end": 0.4,
                         "warmup_epochs": 0},
        "momentum": {"start": 0.992, "peak": 0.992, "end": 1.0,
                     "warmup_epochs": 0},
        "teacher_temp": {"start": 0.04, "peak": 0.07, "end": 0.07,
                         "warmup_epochs": 3},
    }
    s = build_schedules(cfg)
    np.testing.assert_allclose(s.last_layer_lr[:10], 0.0)
    np.testing.assert_allclose(s.lr[20], 1e-3, rtol=1e-6)
    np.testing.assert_allclose(s.weight_decay[-1], 0.4, rtol=1e-6)


# ---------------- param groups ----------------

def fake_params(n_blocks=3):
    p = {
        "backbone": {
            "patch_embed": {"kernel": jnp.ones((2, 2)), "bias": jnp.ones((2,))},
            "cls_token": jnp.ones((1, 1, 2)),
            "norm": {"scale": jnp.ones((2,)), "bias": jnp.ones((2,))},
        },
        "dino_head": {
            "mlp_0": {"kernel": jnp.ones((2, 2)), "bias": jnp.ones((2,))},
            "prototypes": jnp.ones((2, 8)),
        },
    }
    for i in range(n_blocks):
        p["backbone"][f"blocks_{i}"] = {
            "attn": {"qkv_kernel": jnp.ones((2, 6))},
            "ls1": {"gamma": jnp.ones((2,))},
        }
    return p


def test_multiplier_trees_semantics():
    params = fake_params()
    lr, wd, ll = build_multiplier_trees(
        params, layerwise_decay=0.9, patch_embed_lr_mult=0.2,
        dino_head_wd_multiplier=0.5,
    )
    d = 0.9
    # patch embed: layer 0 decay * 0.2 mult
    np.testing.assert_allclose(lr["backbone"]["patch_embed"]["kernel"],
                               d ** 4 * 0.2, rtol=1e-6)
    np.testing.assert_allclose(lr["backbone"]["cls_token"], d ** 4, rtol=1e-6)
    # block i -> decay^(L+1-(i+1))
    np.testing.assert_allclose(
        lr["backbone"]["blocks_1"]["attn"]["qkv_kernel"], d ** 2, rtol=1e-6)
    # head gets no layerwise decay (layer L+1 -> mult 1)
    np.testing.assert_allclose(lr["dino_head"]["mlp_0"]["kernel"], 1.0)
    # wd: biases/norms/gammas zero, head multiplied
    assert wd["backbone"]["patch_embed"]["bias"] == 0.0
    assert wd["backbone"]["norm"]["scale"] == 0.0
    assert wd["backbone"]["blocks_0"]["ls1"]["gamma"] == 0.0
    assert wd["dino_head"]["mlp_0"]["kernel"] == 0.5
    assert wd["dino_head"]["mlp_0"]["bias"] == 0.0
    assert wd["backbone"]["blocks_0"]["attn"]["qkv_kernel"] == 1.0
    # last layer flag
    assert ll["dino_head"]["prototypes"] is True
    assert ll["dino_head"]["mlp_0"]["kernel"] is False


def test_multiplier_trees_scanned_stack():
    params = {"backbone": {"blocks": {"block": {
        "attn": {"qkv_kernel": jnp.ones((4, 2, 6))}}},
        "patch_embed": {"kernel": jnp.ones((2, 2))}}}
    lr, _, _ = build_multiplier_trees(params, layerwise_decay=0.5)
    stacked = lr["backbone"]["blocks"]["block"]["attn"]["qkv_kernel"]
    assert stacked.shape == (4, 1, 1)
    np.testing.assert_allclose(
        np.asarray(stacked).ravel(), [0.5 ** 4, 0.5 ** 3, 0.5 ** 2, 0.5],
        rtol=1e-6)


# ---------------- optimizer ----------------

def make_sched(n=10, lr=0.1, wd=0.0):
    z = np.zeros(n)
    return Schedules(np.full(n, lr), np.full(n, wd), z, z, np.zeros(n), n)


def test_scheduled_adamw_matches_optax_adamw():
    """With all multipliers 1 and constant schedules, our chain must equal
    optax.adamw exactly."""
    params = {"w": jnp.ones((3, 3)), "b": jnp.zeros((3,))}
    grads = {"w": jnp.full((3, 3), 0.5), "b": jnp.ones((3,))}
    sched = make_sched(lr=0.1, wd=0.04)
    ones = jax.tree.map(lambda _: 1.0, params)
    falses = jax.tree.map(lambda _: False, params)
    opt = scheduled_adamw(sched, ones, ones, falses)
    ref = optax.adamw(0.1, weight_decay=0.04)
    s1, s2 = opt.init(params), ref.init(params)
    p1, p2 = params, params
    for _ in range(3):
        g = grads
        u1, s1 = opt.update(g, s1, p1)
        p1 = optax.apply_updates(p1, u1)
        u2, s2 = ref.update(g, s2, p2)
        p2 = optax.apply_updates(p2, u2)
    for k in params:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                   atol=1e-6)


def test_last_layer_freeze_and_multipliers():
    params = {"proto": jnp.ones((2, 2)), "w": jnp.ones((2, 2))}
    sched = Schedules(
        lr=np.array([0.1, 0.1]), weight_decay=np.zeros(2),
        momentum=np.zeros(2), teacher_temp=np.zeros(2),
        last_layer_lr=np.array([0.0, 0.1]), total_iters=2,
    )
    lr_mult = {"proto": 1.0, "w": 0.5}
    wd_mult = {"proto": 1.0, "w": 1.0}
    is_ll = {"proto": True, "w": False}
    opt = scheduled_adamw(sched, lr_mult, wd_mult, is_ll)
    state = opt.init(params)
    g = jax.tree.map(jnp.ones_like, params)
    u, state = opt.update(g, state, params)
    np.testing.assert_allclose(np.asarray(u["proto"]), 0.0)  # frozen step 0
    assert np.abs(np.asarray(u["w"])).max() > 0
    u2, state = opt.update(g, state, params)
    assert np.abs(np.asarray(u2["proto"])).max() > 0  # unfrozen step 1
    # lr_mult halves w's step relative to proto's
    np.testing.assert_allclose(np.asarray(u2["w"]) * 2, np.asarray(u2["proto"]),
                               atol=1e-7)


def test_build_optimizer_from_cfg_runs():
    cfg = get_default_config()
    apply_dot_overrides(cfg, ["train.OFFICIAL_EPOCH_LENGTH=5", "optim.epochs=2"])
    params = fake_params()
    sched = build_schedules(cfg)
    opt = build_optimizer(cfg, params, sched)
    state = opt.init(params)
    g = jax.tree.map(jnp.ones_like, params)
    u, _ = opt.update(g, state, params)
    assert jax.tree.structure(u) == jax.tree.structure(params)


def test_clip_per_submodel():
    grads = {
        "backbone": {"w": jnp.full((2, 2), 100.0)},
        "dino_head": {"w": jnp.full((2,), 1e-4)},
    }
    clipped, norms = clip_by_per_submodel_norm(grads, max_norm=3.0)
    bb_norm = float(jnp.sqrt(jnp.sum(clipped["backbone"]["w"] ** 2)))
    np.testing.assert_allclose(bb_norm, 3.0, rtol=1e-5)
    # small grads untouched
    np.testing.assert_allclose(np.asarray(clipped["dino_head"]["w"]), 1e-4)
    assert float(norms["backbone"]) > 3.0
