"""Streaming prototype-axis target/CE engine (losses/streaming.py) vs
the materialized oracle, plus the compiled-HLO guarantees.

Pinned here:
- loss-value AND student-gradient equivalence of the streaming engine
  against the materialized path (dino pairwise + ibot rows), for both
  centering modes (softmax-center, Sinkhorn) and both target storage
  dtypes (fp32, bf16);
- the full meta-arch forward agreeing between ``loss.streaming_targets``
  on and off, both centerings, including the center-EMA state;
- sharded-prototype correctness: the streaming step under a
  tensor-parallel (prototype-sharded "vocab") mesh matches the
  materialized step;
- the compiled-HLO claim: with streaming on, NO [*, K] fp32
  teacher-target buffer is materialized (softmax-center), and the
  Sinkhorn path materializes fewer [rows, K] buffers than the oracle
  (q eliminated, only the xs iterate remains);
- the copy census of the exact jitted train step does not regress
  (ceiling on copy-class HLO ops outside fusions; zero donation
  warnings);
- the jaxlib<=0.4.36 cpu donation/persistent-cache staleness workaround
  (utils.donation_safe_argnums) is active exactly where it must be.
"""

import importlib.util
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.losses import (
    choose_k_tile,
    dino_loss,
    ibot_loss_from_spec,
    ibot_patch_loss_masked,
    pair_ce_from_spec,
    pair_ce_to_loss,
    sinkhorn_knopp,
    softmax_center_teacher,
)

_CTP_PATH = os.path.join(os.path.dirname(__file__), "..", "scripts",
                         "cost_target_phase.py")


def _load_cost_script():
    spec = importlib.util.spec_from_file_location(
        "cost_target_phase", _CTP_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


SMOL = [
    "student.arch=vit_test", "student.patch_size=4",
    "student.drop_path_rate=0.0", "student.layerscale=1.0e-5",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=24",
    "dino.head_bottleneck_dim=8",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=24",
    "ibot.head_bottleneck_dim=8",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "optim.warmup_epochs=1", "optim.freeze_last_layer_epochs=1",
    "compute_precision.compute_dtype=fp32",
    "optim.scaling_rule=none",
]


def smol_cfg(extra=()):
    cfg = get_default_config()
    apply_dot_overrides(cfg, list(SMOL) + list(extra))
    return cfg


# ---------------- unit equivalence: engine vs oracle ----------------


def _pair_data(K=256, S=4, T=2, B=6, scale=3.0):
    key = jax.random.key(0)
    sl = jax.random.normal(key, (S, B, K)) * 2
    tl = jax.random.normal(jax.random.fold_in(key, 1), (T, B, K)) * scale
    center = jax.random.normal(jax.random.fold_in(key, 2), (1, K)) * 0.5
    return sl, tl, center


@pytest.mark.parametrize("tgt", [None, jnp.bfloat16])
def test_streaming_softmax_pairwise_matches_oracle(tgt):
    sl, tl, center = _pair_data()
    T, B, K = tl.shape
    temp = 0.07
    probs = softmax_center_teacher(
        tl.reshape(T * B, K), center, temp, storage_dtype=tgt
    ).reshape(T, B, K)
    oracle = dino_loss(sl, probs)
    spec = {"kind": "softmax_center", "logits": tl, "center": center,
            "temp": temp}
    stream = pair_ce_to_loss(pair_ce_from_spec(sl, spec, k_tile=64), B)
    # the streaming engine computes q in fp32 regardless of target
    # storage: vs a bf16-stored oracle the tolerance covers the oracle's
    # own bf16 target rounding
    rtol = 1e-5 if tgt is None else 5e-3
    np.testing.assert_allclose(float(stream), float(oracle), rtol=rtol)
    # ignore_diagonal normalization shared through pair_ce_to_loss
    oracle_d = dino_loss(sl[:T], probs, ignore_diagonal=True)
    stream_d = pair_ce_to_loss(
        pair_ce_from_spec(sl[:T], spec, k_tile=64), B,
        ignore_diagonal=True)
    np.testing.assert_allclose(float(stream_d), float(oracle_d), rtol=rtol)


@pytest.mark.parametrize("tgt", [None, jnp.bfloat16])
def test_streaming_sinkhorn_pairwise_matches_oracle(tgt):
    sl, tl, center = _pair_data()
    T, B, K = tl.shape
    temp = 0.07
    q = sinkhorn_knopp(tl.reshape(T * B, K), temp,
                       storage_dtype=tgt).reshape(T, B, K)
    oracle = dino_loss(sl, q)
    f = sinkhorn_knopp(tl.reshape(T * B, K), temp, storage_dtype=tgt,
                       return_factors=True)
    stream = pair_ce_to_loss(
        pair_ce_from_spec(sl, {"kind": "sinkhorn", "factors": f},
                          k_tile=64), B)
    # both paths share the storage-typed xs iterate; only the q
    # reconstruction differs (oracle stores q in tgt, streaming keeps it
    # fp32 in-register)
    rtol = 1e-5 if tgt is None else 5e-3
    np.testing.assert_allclose(float(stream), float(oracle), rtol=rtol)


@pytest.mark.parametrize("centering", ["softmax_center", "sinkhorn_knopp"])
def test_streaming_ibot_rows_match_oracle_with_padding(centering):
    K, M = 192, 12
    key = jax.random.key(3)
    sm = jax.random.normal(key, (M, K))
    tm = jax.random.normal(jax.random.fold_in(key, 1), (M, K)) * 2
    center = jax.random.normal(jax.random.fold_in(key, 2), (1, K)) * 0.3
    valid = jnp.array([1.0] * 8 + [0.0] * 4)
    w = jnp.where(valid > 0, 1 / 8.0, 0.0)
    temp = 0.07
    if centering == "softmax_center":
        probs = softmax_center_teacher(tm, center, temp) * valid[:, None]
        spec = {"kind": "softmax_center", "logits": tm, "center": center,
                "temp": temp}
    else:
        probs = sinkhorn_knopp(tm, temp, row_weights=valid)
        spec = {"kind": "sinkhorn", "factors": sinkhorn_knopp(
            tm, temp, row_weights=valid, return_factors=True)}
    oracle = ibot_patch_loss_masked(sm, probs, w, n_images=2)
    stream = ibot_loss_from_spec(sm, spec, w, 2, k_tile=64)
    np.testing.assert_allclose(float(stream), float(oracle), rtol=1e-5)


def test_streaming_gradients_match_oracle():
    """Student-logit gradients through the checkpointed scan == oracle
    gradients, softmax-center and sinkhorn."""
    sl, tl, center = _pair_data(K=128)
    T, B, K = tl.shape
    temp = 0.05
    probs = softmax_center_teacher(tl.reshape(T * B, K), center,
                                   temp).reshape(T, B, K)
    spec = {"kind": "softmax_center", "logits": tl, "center": center,
            "temp": temp}
    g_o = jax.grad(lambda s: dino_loss(s, probs))(sl)
    g_s = jax.grad(lambda s: pair_ce_to_loss(
        pair_ce_from_spec(s, spec, k_tile=32), B))(sl)
    np.testing.assert_allclose(np.asarray(g_s), np.asarray(g_o),
                               rtol=1e-4, atol=1e-6)
    q = sinkhorn_knopp(tl.reshape(T * B, K), temp).reshape(T, B, K)
    f = sinkhorn_knopp(tl.reshape(T * B, K), temp, return_factors=True)
    g_o = jax.grad(lambda s: dino_loss(s, q))(sl)
    g_s = jax.grad(lambda s: pair_ce_to_loss(pair_ce_from_spec(
        s, {"kind": "sinkhorn", "factors": f}, k_tile=32), B))(sl)
    np.testing.assert_allclose(np.asarray(g_s), np.asarray(g_o),
                               rtol=1e-4, atol=1e-6)


def test_choose_k_tile():
    assert choose_k_tile(65536, 8192) == 8192
    assert choose_k_tile(65536, 8000) == 4096  # largest divisor <= cap
    assert choose_k_tile(300, 128) == 100
    assert choose_k_tile(64, 8192) == 64       # cap above K: one tile
    assert choose_k_tile(64, 0) == 64          # 0 = unset


# ---------------- meta-arch integration ----------------


@pytest.mark.parametrize("centering", ["sinkhorn_knopp", "softmax_center"])
def test_meta_arch_streaming_matches_materialized(centering):
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train.ssl_meta_arch import SSLMetaArch

    results = {}
    for flag in ("true", "false"):
        cfg = smol_cfg([f"train.centering={centering}",
                        f"loss.streaming_targets={flag}",
                        "loss.k_tile=16"])
        meta = SSLMetaArch(cfg)
        assert meta.streaming_targets == (flag == "true")
        batch = {k: jnp.asarray(v) for k, v in
                 make_synthetic_batch(cfg, 4, seed=0).items()}
        params = meta.init_params(jax.random.key(0), batch)
        rngs = {"drop_path": jax.random.key(1), "rope": jax.random.key(2),
                "dropout": jax.random.key(3)}
        total, (loss_dict, state) = meta.forward(
            params["student"], {"teacher": params["teacher"]}, batch,
            teacher_temp=0.07, state=meta.init_state(), iteration=0,
            rngs=rngs,
        )
        results[flag] = (float(total),
                         {k: float(v) for k, v in loss_dict.items()},
                         state)
    t_on, d_on, s_on = results["true"]
    t_off, d_off, s_off = results["false"]
    np.testing.assert_allclose(t_on, t_off, rtol=1e-5)
    for k in d_off:
        np.testing.assert_allclose(d_on[k], d_off[k], rtol=2e-5,
                                   err_msg=k)
    # center EMA state is computed from the raw logits on both paths:
    # bit-identical fp32 accumulation
    for k in s_off:
        np.testing.assert_array_equal(np.asarray(s_on[k]),
                                      np.asarray(s_off[k]))


def test_streaming_auto_defaults_on():
    from dinov3_tpu.train.ssl_meta_arch import SSLMetaArch

    assert SSLMetaArch(smol_cfg()).streaming_targets is True
    assert SSLMetaArch(
        smol_cfg(["loss.streaming_targets=false"])).streaming_targets is False
    with pytest.raises(ValueError, match="streaming_targets"):
        SSLMetaArch(smol_cfg(["loss.streaming_targets=sometimes"]))


def test_sharded_prototypes_streaming_matches_materialized(eight_devices):
    """Tensor-axis ("vocab") sharded prototype heads: the streaming step
    under dp x tensor == the materialized step, same batch (the 8-device
    dryrun regression the ISSUE requires)."""
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup, put_batch

    losses = {}
    for flag in ("true", "false"):
        cfg = smol_cfg(["parallel.data=-1", "parallel.tensor=2",
                        f"loss.streaming_targets={flag}", "loss.k_tile=16"])
        batch = {k: jnp.asarray(v) for k, v in
                 make_synthetic_batch(cfg, 8, seed=0).items()}
        setup = build_train_setup(cfg, batch, devices=eight_devices)
        d = put_batch(batch, setup.batch_shardings)
        state, m = setup.step_fn(setup.state, d, setup.scalars(0),
                                 jax.random.key(0))
        assert np.isfinite(float(m["total_loss"]))
        losses[flag] = float(m["total_loss"])
    np.testing.assert_allclose(losses["true"], losses["false"], rtol=2e-5)


# ---------------- compiled-HLO guarantees ----------------

_K, _TILE, _T, _B, _S = 512, 64, 2, 4, 4


def _phase_programs(centering, target_dtype):
    """Compile the DINO target/CE phase two ways on abstract bf16 logits
    and return {"streaming": hlo, "materialized": hlo} plus row count."""
    sd = jax.ShapeDtypeStruct
    student = sd((_S, _B, _K), jnp.bfloat16)
    t_logits = sd((_T * _B, _K), jnp.bfloat16)
    center = sd((1, _K), jnp.float32)
    temp = sd((), jnp.float32)

    def streaming(s, tl, c, t):
        if centering == "softmax_center":
            spec = {"kind": "softmax_center",
                    "logits": tl.reshape(_T, _B, _K), "center": c,
                    "temp": t}
        else:
            spec = {"kind": "sinkhorn", "factors": sinkhorn_knopp(
                tl, t, storage_dtype=target_dtype, return_factors=True)}
        return pair_ce_to_loss(
            pair_ce_from_spec(s, spec, k_tile=_TILE), _B)

    def materialized(s, tl, c, t):
        if centering == "softmax_center":
            q = softmax_center_teacher(tl, c, t, storage_dtype=target_dtype)
        else:
            q = sinkhorn_knopp(tl, t, storage_dtype=target_dtype)
        return pair_ce_to_loss(pair_ce_from_spec(
            s, {"kind": "probs", "probs": q.reshape(_T, _B, _K)}), _B)

    texts = {}
    for name, fn in (("streaming", streaming),
                     ("materialized", materialized)):
        texts[name] = jax.jit(jax.value_and_grad(fn)).lower(
            student, t_logits, center, temp).compile().as_text()
    return texts


_TARGET_OPS = r"(exponential|divide|multiply)\("


def test_hlo_no_fp32_target_values_when_streaming():
    """The acceptance claim, in its version-robust form: in the compiled
    streaming program (softmax-center, bf16 logits) NO op — fusion
    internals included — produces a full [T*B, K] fp32 TARGET value
    (exp/divide/multiply of the softmax chain), so the fp32 teacher-
    target buffer provably never exists however the backend fuses; the
    materialized oracle program does produce them, which also validates
    the detector. (A backend may still hoist a one-time fp32 convert of
    the loop-invariant logits — XLA:CPU does, and strips the
    optimization barriers guarding against it; that scheduling choice is
    visible in, and already paid by, the pass-granularity bytes numbers
    in COST_TARGET_r07.json, which show streaming -69.5% anyway.)"""
    ctp = _load_cost_script()
    texts = _phase_programs("softmax_center", None)
    rows = _T * _B

    def full_target_values(text):
        return (ctp.count_materialized(text, "f32", _K, rows,
                                       include_fusions=True,
                                       op_pattern=_TARGET_OPS)
                + ctp.count_materialized(text, "f32", _K, _T * _B * _S,
                                         include_fusions=True,
                                         op_pattern=_TARGET_OPS))

    assert full_target_values(texts["streaming"]) == 0
    assert full_target_values(texts["materialized"]) > 0


def test_hlo_sinkhorn_streaming_drops_q_values():
    """Sinkhorn's ITERATIONS exp at full width inside their logsumexp
    reductions on both paths (algorithmically required — the iterate is
    what Sinkhorn is), but the q reconstruction stays K-tiled under
    streaming: strictly fewer full-[rows, K] exp/divide values than the
    materialized program, which reconstructs q at full width on top of
    the iterations."""
    ctp = _load_cost_script()
    texts = _phase_programs("sinkhorn_knopp", jnp.bfloat16)
    rows = _T * _B
    counts = {
        name: sum(
            ctp.count_materialized(t, dt, _K, rows,
                                   include_fusions=True,
                                   op_pattern=r"(exponential|divide)\(")
            for dt in ("f32", "bf16"))
        for name, t in texts.items()
    }
    assert counts["streaming"] < counts["materialized"], counts


def test_cost_target_reduction_mechanism():
    """scripts/cost_target_phase.py's pass-granularity accounting on a
    small config: streaming accesses >=30% fewer bytes than the
    materialized passes on the softmax-center path (the committed ViT-L
    K=65536 numbers in COST_TARGET_r07.json use the same code path;
    -69.5% there)."""
    ctp = _load_cost_script()
    cfg = smol_cfg(["dino.head_n_prototypes=2048",
                    "ibot.head_n_prototypes=2048", "loss.k_tile=256"])
    rec = ctp.measure_target_phase(cfg, "softmax_center", None)
    assert rec["bytes_streaming"] < rec["bytes_materialized_total"]
    assert rec["reduction_pct"] >= 30.0, rec
    assert set(rec["bytes_materialized_passes"]) == {
        "targets", "dino_ce", "ibot_ce"}


# ---------------- copy census + donation ----------------


def test_copy_census_does_not_regress():
    """Compile the exact jitted train step on CPU; the copy-class HLO op
    count outside fusions must stay at/below the audited ceiling and
    donation must produce zero warnings.

    Audited at PR-2 commit time on the drop-path-active census program
    (COST_TARGET_r07.json): 518 copies, ~98% of them scalar/u32[4]
    RNG-key plumbing (threefry fold_ins). PR-3's step-wide RNG-plan
    engine (rng/plan.py, default on) removes that plumbing: the same
    program now measures 144 copies (COST_RNG_r08.json, -72.2%; the
    legacy rng.plan=false oracle still measures 518). The ceiling is
    tightened from the old 700 to 200 — headroom for jax-version layout
    variation, not for structural regressions (a new weight-shaped copy
    pass is O(params) copies and a reintroduced per-layer key chain is
    O(layers); either blows straight through).

    The per-category attribution (utils.classify_copy) must also be
    present so a future regression names its source (RNG plumbing vs
    donation/async vs pack/unpack vs activation-sized copies).

    Re-pinned for PR-4 (crop packing, default on): the packed
    single-pass program measures 96 copies — packing REMOVED the old
    two-pass crop-boundary copies on top of the RNG-plan's cut — and
    its pack/unpack assembly lowers to slice/bitcast on this backend
    (zero copy-class ops; the "gather_pack" census category attributes
    them wherever a backend does materialize them, so the ceiling names
    a packing regression instead of silently absorbing it). The ceiling
    drops 200 -> 150 for the packed default; the two-pass oracle
    program keeps the prior 200 ceiling.
    """
    ctp = _load_cost_script()
    # the RNG-heavy program: drop-path active (the smol default of 0.0
    # has no device-side draws and measures ~11 copies on both paths)
    cfg = smol_cfg(["student.drop_path_rate=0.3"])
    rec = ctp.copy_census(cfg, B=4)
    assert rec["donation_warnings"] == []
    assert rec["hlo_copy_total"] <= 150, rec["hlo_copy_ops"]
    cats = {"rng", "donation_async", "small", "large", "gather_pack"}
    assert set(rec["by_category"]) <= cats
    assert rec["by_category"].get("gather_pack", {}).get("ops", 0) <= 40, rec
    assert rec["hlo_copy_bytes"] >= sum(
        c["bytes"] for c in rec["by_category"].values()) >= 0
    # the two-pass oracle program keeps its pre-packing ceiling
    rec_oracle = ctp.copy_census(
        smol_cfg(["student.drop_path_rate=0.3",
                  "model.crop_packing=false"]), B=4)
    assert rec_oracle["donation_warnings"] == []
    assert rec_oracle["hlo_copy_total"] <= 200, rec_oracle["hlo_copy_ops"]


def test_donation_safe_argnums_gating():
    """The workaround drops donation exactly on the affected
    configuration (cpu backend + persistent cache + jaxlib < 0.5)."""
    import jaxlib

    from dinov3_tpu.utils import donation_safe_argnums

    old = tuple(int(x) for x in jaxlib.__version__.split(".")[:3]) < (0, 5, 0)
    cache_on = bool(jax.config.jax_compilation_cache_dir)
    expected = () if (old and cache_on
                      and jax.default_backend() == "cpu") else (0,)
    assert donation_safe_argnums((0,)) == expected
    # with the cache off the argnums always pass through
    prev = jax.config.jax_compilation_cache_dir
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        assert donation_safe_argnums((0,)) == (0,)
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
