"""Flash-attention kernel vs the unfused XLA path (interpret mode on the
CPU test mesh; the identical kernels compile on TPU)."""

import jax
import jax.numpy as jnp
import pytest

from dinov3_tpu.ops.attention import xla_attention
from dinov3_tpu.ops.flash_attention import flash_attention


def _rand_qkv(rng, B, N, h, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(rng, 3)
    shape = (B, N, h, d)
    return (
        jax.random.normal(kq, shape, dtype),
        jax.random.normal(kk, shape, dtype),
        jax.random.normal(kv, shape, dtype),
    )


@pytest.mark.parametrize(
    "B,N,h,d",
    [
        (2, 128, 2, 64),    # aligned
        (1, 201, 3, 64),    # ViT-S/16 global crop: 196 patches + cls + 4 reg
        (2, 41, 2, 32),     # local crop, N << lane width
        (1, 640, 2, 64),    # multiple k blocks after padding
    ],
)
def test_forward_matches_xla(rng, B, N, h, d):
    q, k, v = _rand_qkv(rng, B, N, h, d)
    out = flash_attention(q, k, v, interpret=True)
    ref = xla_attention(q, k, v)
    assert out.shape == (B, N, h, d)
    assert jnp.allclose(out, ref, atol=2e-5, rtol=2e-5), (
        jnp.abs(out - ref).max()
    )


def test_gradients_match_xla(rng):
    B, N, h, d = 2, 137, 2, 32
    q, k, v = _rand_qkv(rng, B, N, h, d)
    tangent = jax.random.normal(jax.random.fold_in(rng, 7), (B, N, h, d))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, interpret=True) * tangent)

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v) * tangent)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        err = jnp.abs(gf - gr).max()
        assert jnp.allclose(gf, gr, atol=5e-5, rtol=5e-5), (name, err)


def test_bf16_inputs_fp32_softmax(rng):
    B, N, h, d = 1, 130, 2, 64
    q, k, v = _rand_qkv(rng, B, N, h, d, jnp.bfloat16)
    out = flash_attention(q, k, v, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = xla_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                        v.astype(jnp.float32))
    assert jnp.allclose(out.astype(jnp.float32), ref, atol=3e-2), (
        jnp.abs(out.astype(jnp.float32) - ref).max()
    )


def test_jit_and_vit_shapes(rng):
    # jit-compiles once per static shape, runs under value_and_grad
    q, k, v = _rand_qkv(rng, 2, 261, 4, 64)

    @jax.jit
    def f(q, k, v):
        return flash_attention(q, k, v, interpret=True).sum()

    assert jnp.isfinite(f(q, k, v))


def test_flash_block_caps_honored():
    """kernels.flash_block_q/kv cap the kernel block sizes (they were
    previously declared in the schema but never consumed)."""
    from dinov3_tpu.ops.flash_attention import _block_sizes

    assert _block_sizes(1024, 128, 256) == (128, 256)
    assert _block_sizes(1024) == (512, 512)
    assert _block_sizes(1152) == (128, 128)  # 1152 = 9*128

    # and the caps thread from config to the attention module
    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.models import backbone_kwargs_from_cfg

    cfg = get_default_config()
    apply_dot_overrides(cfg, ["student.arch=vit_test",
                              "kernels.flash_block_q=256"])
    kw = backbone_kwargs_from_cfg(cfg)
    assert kw["flash_block_q"] == 256 and kw["flash_block_kv"] == 512


def test_auto_dispatch_threshold(monkeypatch):
    """The auto dispatch keeps every *measured* regime on dense XLA.

    Full-step evidence (MEASUREMENTS_r5.md phF rows): dense beats flash at
    N=201 (224px) and N=1029 (512px, 9.99 vs 7.65 img/s/chip), so auto
    must choose xla there; flash stays reachable at 2309+ (768px) where
    its O(N) memory is the point. Backend/kernel availability are
    monkeypatched — this pins the threshold logic, not the TPU.
    """
    from dinov3_tpu.ops import attention as att

    chosen = {}

    def fake_xla(q, k, v, *a, **kw):
        chosen["impl"] = "xla"
        return q

    def fake_flash(q, k, v, **kw):
        chosen["impl"] = "pallas"
        return q

    monkeypatch.setattr(att, "xla_attention", fake_xla)
    monkeypatch.setattr(att.jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(att, "_flash_available", lambda: True)
    import dinov3_tpu.ops.flash_attention as fa

    monkeypatch.setattr(fa, "flash_attention", fake_flash)

    for N, want in [(201, "xla"), (1029, "xla"), (1054, "xla"),
                    (2309, "pallas"), (4096, "pallas")]:
        q = jnp.zeros((1, N, 2, 32), jnp.bfloat16)
        att.dispatch_attention(q, q, q, impl="auto")
        assert chosen["impl"] == want, (N, chosen["impl"], want)

    # kernels.flash_min_seq override still wins over the builtin
    q = jnp.zeros((1, 1029, 2, 32), jnp.bfloat16)
    att.dispatch_attention(q, q, q, impl="auto", flash_min_seq=512)
    assert chosen["impl"] == "pallas"
