"""Tests for the quantized multi-tenant serving fleet (ISSUE 12).

Three layers on the PR-10/11 serving plane, each pinned here:

- ``serve/quant.py``: per-channel symmetric int8 over the attn/mlp
  matmul weights (exactly the ``stream_castable_path`` set), host-side
  deterministic quantization, in-graph dequant under ``serve_dequant``.
- ``serve/cache.py``: content-addressed feature memoization keyed on
  (image bytes, weights fingerprint) with a bounded LRU — sound only
  because serving weights are frozen, so identity of key implies
  identity of features.
- ``serve/fleet.py``: N AOT engines behind one shape+SLO admission
  layer; a single-engine quant-off cache-off fleet must reproduce the
  bare ``PackedServeEngine`` bitwise (the PR-10 oracle), and the
  committed SERVE_r16.json pins the full-size claims.
"""

from __future__ import annotations

import json
import os
import warnings

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import pytest

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.configs.config import (
    warn_cache_memory,
    warn_quant_drift,
)
from dinov3_tpu.serve import (
    EngineSpec,
    FeatureCache,
    FleetRouter,
    PackedServeEngine,
    QuantLeaf,
    build_serve_fleet,
    cast_serving_tree,
    dequantize_tree,
    image_key,
    is_quantized_tree,
    layout_from_envelope,
    quant_feature_drift,
    quant_summary,
    quantizable_path,
    quantize_serving_tree,
    serve_layout_from_cfg,
    weights_fingerprint,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SERVE_SMOL = [
    "student.arch=vit_test", "student.patch_size=4",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2", "train.batch_size_per_device=2",
    "optim.scaling_rule=none", "train.scan_layers=true",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "serve.min_px=8", "serve.max_px=24", "serve.rows=3",
    "serve.row_tokens=40", "serve.max_segments_per_row=6",
]


def _smol_cfg(extra=()):
    cfg = get_default_config()
    apply_dot_overrides(cfg, SERVE_SMOL + list(extra))
    return cfg


@pytest.fixture(scope="module")
def tiny_serve():
    """One vit_test serving model + bf16 params + layout for the file."""
    import flax.linen as nn

    from dinov3_tpu.models import build_backbone

    cfg = _smol_cfg()
    model = build_backbone(cfg, teacher=True)
    params = nn.meta.unbox(
        jax.jit(model.init)(jax.random.key(0), jnp.zeros((1, 16, 16, 3)))
    )["params"]
    params = cast_serving_tree(params)
    return cfg, model, params, serve_layout_from_cfg(cfg)


def _img(rng, h, w):
    return rng.standard_normal((h, w, 3)).astype(np.float32)


# ---------------- quant: selection, roundtrip, determinism ----------------

def test_quantizable_path_is_the_stream_castable_kernel_set(tiny_serve):
    _, _, params, _ = tiny_serve
    from dinov3_tpu.ops.block import stream_castable_path

    qtree = quantize_serving_tree(params)
    leaves = jtu.tree_flatten_with_path(
        qtree, is_leaf=lambda x: isinstance(x, QuantLeaf))[0]
    n_q = 0
    for path, leaf in leaves:
        want = quantizable_path(path)
        assert isinstance(leaf, QuantLeaf) == want, jtu.keystr(path)
        if want:
            n_q += 1
            # quantizable implies stream-castable AND a matmul kernel
            assert stream_castable_path(path)
            assert "kernel" in jtu.keystr(path)
            assert leaf.q.dtype == jnp.int8
            assert leaf.scale.dtype == jnp.float32
            # per-OUTPUT-channel scales: reduction axis collapsed
            assert leaf.scale.shape[-2] == 1
            assert leaf.scale.shape[-1] == leaf.q.shape[-1]
    assert n_q > 0
    # norms, biases, patch embed, cls token stay bf16
    names = " ".join(jtu.keystr(p) for p, l in leaves
                     if not isinstance(l, QuantLeaf))
    assert "bias" in names and "patch_embed" in names
    assert "cls_token" in names and "norm" in names


def test_quant_roundtrip_error_bounded_by_half_scale():
    rng = np.random.default_rng(0)
    w = (rng.standard_normal((32, 16)) * rng.uniform(0.01, 4.0, 16)
         ).astype(np.float32)
    from dinov3_tpu.serve.quant import quantize_leaf

    leaf = quantize_leaf(w)
    back = np.asarray(leaf.q, np.float32) * np.asarray(leaf.scale)
    # symmetric round-to-nearest: |w - dq| <= scale/2 per channel
    assert np.all(np.abs(w - back) <= np.asarray(leaf.scale) / 2 + 1e-7)
    # full range used: amax column hits +-127
    assert np.abs(np.asarray(leaf.q)).max() == 127


def test_quantize_deterministic_and_idempotent(tiny_serve):
    _, _, params, _ = tiny_serve
    q1, q2 = quantize_serving_tree(params), quantize_serving_tree(params)
    f1 = jtu.tree_flatten_with_path(q1)[0]
    for (path, a), (_, b) in zip(f1, jtu.tree_flatten_with_path(q2)[0]):
        assert np.array_equal(np.asarray(a), np.asarray(b)), jtu.keystr(path)
    assert is_quantized_tree(q1) and not is_quantized_tree(params)
    # quantizing a quantized tree is a no-op, not double quantization
    q3 = quantize_serving_tree(q1)
    for (path, a), (_, b) in zip(f1, jtu.tree_flatten_with_path(q3)[0]):
        assert np.array_equal(np.asarray(a), np.asarray(b)), jtu.keystr(path)
    s = quant_summary(q1)
    assert s["quantized_kernels"] > 0
    assert s["bytes_ratio"] < 0.75  # int8+scale vs bf16


def test_dequantize_is_traceable_and_drift_small(tiny_serve):
    cfg, model, params, _ = tiny_serve
    qtree = quantize_serving_tree(params)

    @jax.jit
    def total(t):
        leaves = jtu.tree_leaves(dequantize_tree(t))
        return sum(jnp.sum(l.astype(jnp.float32)) for l in leaves)

    assert np.isfinite(float(total(qtree)))
    drift = quant_feature_drift(model, params, qtree, px=16)
    assert drift["probe_px"] == 16
    assert drift["cls_max_abs_diff"] <= 0.05
    assert drift["pooled_max_abs_diff"] <= 0.05


# ---------------- cache: content addressing + LRU ----------------

def test_image_key_is_content_addressed():
    rng = np.random.default_rng(1)
    a = _img(rng, 8, 8)
    assert image_key(a) == image_key(a.copy())       # same bytes
    assert image_key(a) != image_key(a + 1e-3)       # content
    assert image_key(a) != image_key(a.reshape(16, 4, 3))  # shape
    assert image_key(a) != image_key(a.astype(np.float64))  # dtype


def test_weights_fingerprint_invalidates_across_trees(tiny_serve):
    _, _, params, _ = tiny_serve
    qtree = quantize_serving_tree(params)
    f_bf16, f_int8 = weights_fingerprint(params), weights_fingerprint(qtree)
    assert f_bf16 != f_int8
    assert f_bf16 == weights_fingerprint(params)  # stable
    rng = np.random.default_rng(2)
    img = _img(rng, 8, 8)
    cache = FeatureCache(capacity=4)
    cache.put(cache.key(img, f_bf16), (np.zeros(4), np.zeros(4), 4))
    # same image under different weights is a MISS, not a stale hit
    assert cache.get(cache.key(img, f_int8)) is None
    assert cache.get(cache.key(img, f_bf16)) is not None


def test_cache_lru_eviction_and_counters():
    rng = np.random.default_rng(3)
    imgs = [_img(rng, 8, 8) for _ in range(3)]
    cache = FeatureCache(capacity=2)
    keys = [cache.key(im, "fp") for im in imgs]
    cls = [np.full(4, i, np.float32) for i in range(3)]
    assert not cache.put(keys[0], (cls[0], cls[0], 4))
    assert not cache.put(keys[1], (cls[1], cls[1], 4))
    # touch key0 so key1 is LRU, then overflow: key1 evicted, key0 kept
    assert cache.get(keys[0]) is not None
    assert cache.put(keys[2], (cls[2], cls[2], 4))  # True = evicted
    assert cache.get(keys[1]) is None
    hit = cache.get(keys[0])
    assert hit is not None and np.array_equal(hit[0], cls[0])
    # the stored array is returned as-is (hit == miss bitwise by
    # construction) and frozen against caller mutation
    assert not hit[0].flags.writeable
    s = cache.stats()
    assert s["entries"] == 2 and s["capacity"] == 2
    assert s["evictions"] == 1 and s["misses"] == 1 and s["hits"] == 2
    cache.clear(reset_counters=False)
    assert cache.stats()["entries"] == 0
    assert cache.stats()["evictions"] == 1
    cache.clear(reset_counters=True)
    assert cache.stats()["hits"] == 0 and cache.stats()["hit_rate"] is None


# ---------------- fleet: admission, routing, bitwise oracle ----------------

def test_layout_admits_shape_and_capacity(tiny_serve):
    _, _, _, layout = tiny_serve
    assert layout.admits(8, 8)
    assert not layout.admits(10, 8)       # not patch-divisible
    # row_tokens 40, patch 4: 24x24 -> 1+36 = 37 fits; 24x28 -> 43 no
    assert layout.admits(24, 24)
    assert not layout.admits(24, 28)


def test_router_routes_by_slo_then_capacity(tiny_serve):
    import dataclasses

    _, model, params, layout = tiny_serve
    small = dataclasses.replace(layout, rows=2, row_tokens=20,
                                max_segments_per_row=3, max_px=16)
    specs = [
        EngineSpec("fast", PackedServeEngine(model, params, small,
                                             warn=False),
                   slo_classes=("interactive",)),
        EngineSpec("full", PackedServeEngine(model, params, layout,
                                             warn=False)),
    ]
    router = FleetRouter(specs)
    assert router.compile_count == 2
    rng = np.random.default_rng(4)
    # small interactive -> fast (explicit SLO listing wins)
    assert router.route("interactive", 8, 8).name == "fast"
    # batch never enters the interactive-only lane
    assert router.route("batch", 8, 8).name == "full"
    # interactive but too big for the fast row -> overflow to full
    assert router.route("interactive", 24, 24).name == "full"
    with pytest.raises(ValueError, match="no engine admits"):
        router.route("interactive", 24, 44)  # over every row budget
    # traffic lands and is tagged with engine provenance
    router.submit(_img(rng, 8, 8), request_id=0, arrival_s=0.0,
                  slo="interactive")
    router.submit(_img(rng, 24, 24), request_id=1, arrival_s=0.0,
                  slo="batch")
    out = []
    while router.queue_len:
        out.extend(router.flush())
    assert {r.engine for r in out} == {"fast", "full"}
    assert router.route_counts == {("fast", "interactive"): 1,
                                   ("full", "batch"): 1}
    assert router.compile_count == 2  # unchanged by traffic


def test_single_engine_fleet_reproduces_bare_engine_bitwise(tiny_serve):
    """Quant off, cache off, one engine: the fleet IS PR-10's
    ``PackedServeEngine`` — identical responses bitwise on the same
    trace. The layers are composable opt-ins, not a new serving path."""
    _, model, params, layout = tiny_serve
    rng = np.random.default_rng(5)
    imgs = [_img(rng, 4 * int(rng.integers(2, 7)),
                 4 * int(rng.integers(2, 7))) for _ in range(8)]

    def drain(engine_like):
        for i, im in enumerate(imgs):
            engine_like.submit(im, request_id=i, arrival_s=0.0)
        out = []
        while engine_like.queue_len:
            out.extend(engine_like.flush())
        return {r.request_id: r for r in out}

    bare = drain(PackedServeEngine(model, params, layout, warn=False))
    spec = EngineSpec("solo", PackedServeEngine(model, params, layout,
                                                warn=False))
    fleet = drain(FleetRouter([spec]))
    assert set(bare) == set(fleet) == set(range(len(imgs)))
    for rid in bare:
        assert not fleet[rid].cache_hit
        assert np.array_equal(bare[rid].cls_feature,
                              fleet[rid].cls_feature), rid
        assert np.array_equal(bare[rid].pooled_patch_feature,
                              fleet[rid].pooled_patch_feature), rid


def test_fleet_cache_hit_bitwise_and_observed(tiny_serve):
    from dinov3_tpu.telemetry import ServeObserver

    _, model, params, layout = tiny_serve
    rng = np.random.default_rng(6)
    img = _img(rng, 12, 16)
    spec = EngineSpec("solo", PackedServeEngine(model, params, layout,
                                                warn=False))
    obs = ServeObserver(None, layout, slo_classes=("default",), warn=False)
    router = FleetRouter([spec], cache=FeatureCache(capacity=8),
                         observer=obs)

    def one(rid):
        router.submit(img, request_id=rid, arrival_s=0.0)
        out = []
        while router.queue_len:
            out.extend(router.flush())
        (r,) = out
        return r

    miss, hit = one(0), one(1)
    assert not miss.cache_hit and hit.cache_hit
    assert np.array_equal(miss.cls_feature, hit.cls_feature)
    assert np.array_equal(miss.pooled_patch_feature,
                          hit.pooled_patch_feature)
    stats = router.cache.stats()
    assert stats["hits"] == 1 and stats["misses"] == 1
    assert obs.cache_events == {"miss": 1, "insert": 1, "hit": 1}
    fin = router.finalize()
    assert fin["compile_count_total"] == 1
    assert fin["cache"]["hit_rate"] == 0.5


def test_build_serve_fleet_from_config_overlays(tiny_serve):
    cfg = _smol_cfg()
    _, _, params, _ = tiny_serve
    cfg.serve.fleet.engines = [
        {"name": "fast_int8", "slo": "interactive", "quant": True,
         "rows": 2, "row_tokens": 20, "max_segments_per_row": 3,
         "max_px": 16},
        {"name": "full_bf16"},
    ]
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        router = build_serve_fleet(cfg, params=params, warn=True)
    # pad-waste advisories may fire on the tiny envelope; the quant
    # drift and cache memory guardrails must NOT
    bad = [str(w.message) for w in caught
           if "quant drift axis" in str(w.message)
           or "cache memory axis" in str(w.message)]
    assert not bad, bad
    assert [s.name for s in router.specs] == ["fast_int8", "full_bf16"]
    assert router.compile_count == 2
    fast, full = router.specs
    assert fast.engine.weights_dtype == "int8"
    assert fast.engine.arm == "packed_int8"
    assert full.engine.weights_dtype == "bf16"
    assert fast.fingerprint != full.fingerprint
    assert fast.slo_classes == ("interactive",)
    assert full.slo_classes is None
    assert router.cache is not None  # cache defaults ON
    # build-time drift probe rode along and stayed under tol
    assert router.quant_drift is not None
    assert router.quant_drift["cls_max_abs_diff"] <= 0.05


def test_envelope_derivation_feeds_the_fast_lane(tiny_serve):
    """The PR-11 live-mix telemetry closes the loop: observe an
    interactive mix, take ``recommended_serve_envelope``, and the
    derived layout admits that whole mix in a tighter row."""
    from dinov3_tpu.telemetry import LiveMixTracker

    _, _, _, layout = tiny_serve
    tracker = LiveMixTracker(layout)
    rng = np.random.default_rng(7)
    sizes = [(4 * int(rng.integers(2, 5)), 4 * int(rng.integers(2, 5)))
             for _ in range(32)]
    for h, w in sizes:
        tracker.observe_request(layout.seq_len(h, w), h, w)
    tracker.roll()
    env = tracker.recommended_serve_envelope(threshold=0.15)
    assert env is not None
    fast = layout_from_envelope(layout, env)
    assert fast.row_tokens <= layout.row_tokens
    assert all(fast.admits(h, w) for h, w in sizes)


def test_quant_and_cache_guardrails():
    assert warn_quant_drift(0.01, tol=0.05) is None
    with pytest.warns(UserWarning, match="quant drift axis"):
        msg = warn_quant_drift(0.2, tol=0.05, axis="unit probe")
    assert "unit probe" in msg and "0.2" in msg
    assert warn_cache_memory(64, embed_dim=64, budget_mb=1024.0) is None
    with pytest.warns(UserWarning, match="cache memory axis"):
        msg = warn_cache_memory(1 << 22, embed_dim=4096,
                                budget_mb=1024.0)
    assert "capacity" in msg


# ---------------- committed artifact ----------------

def test_serve_r16_acceptance():
    """The committed SERVE_r16.json (vit_small, CPU): >= 2 engines x
    >= 2 SLO classes x cache hit-rate sweep {0, 0.5, 0.9} with
    per-(engine, SLO) p50/p99; int8 sustains >= bf16 at CLS drift
    under serve.quant.drift_tol; every cache hit audited bitwise-equal
    to its miss; exactly n_engines compiles across the whole replay."""
    rec = json.loads(open(os.path.join(REPO, "SERVE_r16.json")).read())
    assert not rec["smoke"]
    assert rec["n_engines"] >= 2
    assert rec["compile_count_total"] == rec["n_engines"]
    assert rec["compile_growth_total"] == 0

    q = rec["quant"]
    assert q["throughput"]["int8_over_bf16"] >= 1.0
    assert q["drift_probe"]["cls_max_abs_diff"] <= q["drift_tol"]
    assert q["drift_warning"] is None
    assert q["summary"]["bytes_ratio"] < 0.75
    assert q["packed_feature_agreement"]["cls_max_abs_diff"] <= 0.1

    fleet = rec["fleet"]
    assert fleet["forced_hit_bitwise"]
    sweeps = fleet["sweeps"]
    assert set(sweeps) == {"hit_0.0", "hit_0.5", "hit_0.9"}
    engines, slos = set(), set()
    for name, s in sweeps.items():
        assert s["cache_hits_bitwise_equal"], name
        assert s["compile_growth"] == 0, name
        lat = s["latency"]
        assert lat["p50_ms"] > 0 and lat["p99_ms"] >= lat["p50_ms"]
        for key, row in s["by_engine_slo"].items():
            en, slo = key.split("/")
            engines.add(en), slos.add(slo)
            assert row["p99_ms"] >= row["p50_ms"] > 0, key
    assert len(engines) >= 2 and len(slos) >= 2
    assert sweeps["hit_0.0"]["measured_hit_rate"] == 0.0
    assert (sweeps["hit_0.9"]["measured_hit_rate"]
            > sweeps["hit_0.0"]["measured_hit_rate"])
    # warm cache must not make the tail WORSE: p99 at 0.9 within 1.5x
    # of cold (CPU-noise slack; the claim is "no regression", the win
    # itself is machine-dependent)
    assert (sweeps["hit_0.9"]["latency"]["p99_ms"]
            <= 1.5 * sweeps["hit_0.0"]["latency"]["p99_ms"])
