"""Cross-replica sharded update engine (train/fused_update.py
make_sharded_update) vs the replicated fused oracle.

The sharded engine is the default update path at data-parallel size > 1
(``optim.sharded_update``); the replicated fused engine stays in the
tree as the oracle. These tests pin:
- leaf-for-leaf multi-step equivalence (params, teacher, mu, nu via the
  lossless flat round-trip, both counts) with clip engaged
  (clip=0.05), mixed (3.0) and off (None) — tolerances rtol=1e-6/
  atol=1e-7, the reduction-associativity budget of the flat clip norm;
- the explicit-collective schedule program
  (``make_sharded_update_schedule``, the program
  scripts/cost_sharded_update.py commits the census of) computing the
  identical update from stacked per-replica partial grads;
- padded-lane inertness (flat zero padding stays exactly 0 through the
  engine) and flatten/unflatten losslessness;
- build_train_setup wiring: auto-on at dp > 1, moments born flat-
  sharded over the data axes, =false oracle fallback, the
  fused_update=false conflict raising;
- full-step sharded-vs-replicated dryruns under data x fsdp and
  data x tensor meshes, plus the collective/copy census of the exact
  compiled sharded step (zero unattributed collectives);
- resume determinism across a sharded -> replicated checkpoint
  round-trip and back (bitwise moment round-trip, identical next step);
- the ``warn_update_shard_padding`` guardrail and the
  ``classify_collective`` attribution;
- the COST_SHUP_r10.json acceptance census: reduce-scatter + all-gather
  with zero unattributed collectives on the sharded arm, all-reduce
  only on the replicated arm.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.parallel.mesh import MeshSpec, build_mesh
from dinov3_tpu.parallel.sharding import UPDATE_SHARD_AXES
from dinov3_tpu.train import (
    build_multiplier_trees,
    make_fused_update,
    make_sharded_update,
    make_sharded_update_schedule,
)
from dinov3_tpu.train.fused_update import (
    flatten_update_leaf,
    padded_flat_size,
    sharded_adam_zeros,
    unflatten_update_leaf,
)
from dinov3_tpu.train.optimizer import scheduled_adamw
from test_fused_update import (
    SMOL,
    assert_trees_close,
    fake_params,
    grads_like,
    make_sched,
    smol_cfg,
)

RTOL, ATOL = 1e-6, 1e-7


@pytest.fixture(scope="module")
def mesh8(request):
    devs = jax.devices()
    assert len(devs) == 8
    return build_mesh(MeshSpec(data=8), devices=devs)


def sharded_opt_init(params, sched, lm, wm, ll, dp=8):
    """Oracle-chain init with the mu/nu swapped into the flat sharded
    layout — what build_train_setup's boxed init produces."""
    import flax.linen as nn

    s = scheduled_adamw(sched, lm, wm, ll).init(params)
    return s._replace(adam=s.adam._replace(
        mu=nn.meta.unbox(sharded_adam_zeros(params, dp)),
        nu=nn.meta.unbox(sharded_adam_zeros(params, dp)),
    ))


# ---------------- engine equivalence ----------------

@pytest.mark.parametrize("clip", [0.05, 3.0, None])
def test_sharded_matches_fused_multistep(mesh8, clip):
    """10 steps, leaf-for-leaf: params, teacher, mu/nu (through the flat
    round-trip), both counts. clip=0.05 engages the clip every step,
    None takes the no-clip branch, 3.0 mixes."""
    sched = make_sched()
    params = fake_params()
    lm, wm, ll = build_multiplier_trees(
        params, layerwise_decay=0.9, patch_embed_lr_mult=0.2,
        dino_head_wd_multiplier=0.5,
    )
    fused = make_fused_update(sched, lm, wm, ll, clip_grad=clip, ema=True)
    sharded = make_sharded_update(sched, lm, wm, ll, mesh8,
                                  clip_grad=clip, ema=True)
    momentum = jnp.asarray(0.95, jnp.float32)
    teacher = jax.tree.map(jnp.copy, params)
    s_f = scheduled_adamw(sched, lm, wm, ll).init(params)
    s_s = sharded_opt_init(params, sched, lm, wm, ll)

    with mesh8:
        f_step = jax.jit(lambda g, p, t, s: fused(g, p, t, s, momentum)[:3])
        s_step = jax.jit(lambda g, p, t, s: sharded(g, p, t, s, momentum)[:3])
        p_f = p_s = params
        t_f = t_s = teacher
        key = jax.random.key(0)
        for _ in range(10):
            key, k = jax.random.split(key)
            g = grads_like(params, k)
            p_f, t_f, s_f = f_step(g, p_f, t_f, s_f)
            p_s, t_s, s_s = s_step(g, p_s, t_s, s_s)

    assert_trees_close(p_f, p_s, "params")
    assert_trees_close(t_f, t_s, "teacher")
    mu_back = jax.tree.map(unflatten_update_leaf, s_s.adam.mu, params)
    nu_back = jax.tree.map(unflatten_update_leaf, s_s.adam.nu, params)
    assert_trees_close(s_f.adam.mu, mu_back, "mu")
    assert_trees_close(s_f.adam.nu, nu_back, "nu")
    assert int(s_s.count) == 10 and int(s_s.adam.count) == 10
    # the updates were non-trivial
    assert not np.allclose(np.asarray(jax.tree.leaves(p_s)[0]),
                           np.asarray(jax.tree.leaves(params)[0]))


def test_schedule_program_matches_fused(mesh8):
    """The explicit-collective schedule (psum_scatter/all_gather under
    shard_map — the program COST_SHUP_r10.json accounts) computes the
    identical update from [dp, *leaf] stacks of per-replica partials."""
    sched = make_sched()
    params = fake_params()
    lm, wm, ll = build_multiplier_trees(params, layerwise_decay=0.9)
    clip = 0.05  # engaged every step: the RS'd norms must match too
    fused = make_fused_update(sched, lm, wm, ll, clip_grad=clip, ema=True)
    schedule = make_sharded_update_schedule(sched, lm, wm, ll, mesh8,
                                            clip_grad=clip, ema=True)
    momentum = jnp.asarray(0.9, jnp.float32)
    teacher = jax.tree.map(jnp.copy, params)
    s_f = scheduled_adamw(sched, lm, wm, ll).init(params)
    s_s = sharded_opt_init(params, sched, lm, wm, ll)

    with mesh8:
        f_step = jax.jit(lambda g, p, t, s: fused(g, p, t, s, momentum))
        c_step = jax.jit(lambda gp, p, t, s: schedule(gp, p, t, s, momentum))
        p_f = p_c = params
        t_f = t_c = teacher
        key = jax.random.key(3)
        for _ in range(3):
            key, k1, k2 = jax.random.split(key, 3)
            # random per-replica partials; the oracle consumes their sum
            # computed the same way the schedule's reduce-scatter does
            parts = jax.tree.map(
                lambda l: jax.random.normal(
                    jax.random.fold_in(k1, l.size), (8,) + l.shape, l.dtype),
                params)
            g = jax.tree.map(lambda s_: jnp.sum(s_, 0), parts)
            p_f, t_f, s_f, norms_f = f_step(g, p_f, t_f, s_f)
            p_c, t_c, s_s, norms_c = c_step(parts, p_c, t_c, s_s)

    assert_trees_close(p_f, p_c, "schedule params")
    assert_trees_close(t_f, t_c, "schedule teacher")
    for k in norms_f:
        np.testing.assert_allclose(
            float(norms_f[k]), float(norms_c[k]), rtol=1e-5,
            err_msg=f"clip norm {k}")
    mu_back = jax.tree.map(unflatten_update_leaf, s_s.adam.mu, params)
    assert_trees_close(s_f.adam.mu, mu_back, "schedule mu")


def test_padded_lanes_inert_and_lossless(mesh8):
    """flatten/unflatten round-trips bitwise; the zero padding stays
    exactly 0 through 5 engine steps (so flat -> full -> flat checkpoint
    conversions are lossless in both directions)."""
    x = jnp.arange(13.0)
    flat = flatten_update_leaf(x.reshape(13), 8)
    assert flat.shape == (16,)
    assert np.array_equal(np.asarray(unflatten_update_leaf(flat, x)), x)
    assert padded_flat_size(13, 8) == 16

    sched = make_sched()
    params = fake_params()  # has a (5,)-bias: pads 5 -> 8
    lm, wm, ll = build_multiplier_trees(params)
    sharded = make_sharded_update(sched, lm, wm, ll, mesh8,
                                  clip_grad=3.0, ema=True)
    momentum = jnp.asarray(0.9, jnp.float32)
    s = sharded_opt_init(params, sched, lm, wm, ll)
    p, t = params, jax.tree.map(jnp.copy, params)
    with mesh8:
        step = jax.jit(lambda g, p, t, s: sharded(g, p, t, s, momentum)[:3])
        key = jax.random.key(1)
        for _ in range(5):
            key, k = jax.random.split(key)
            p, t, s = step(grads_like(params, k), p, t, s)
    for (path, mu), (_, like) in zip(
        jax.tree_util.tree_flatten_with_path(s.adam.mu)[0],
        jax.tree_util.tree_flatten_with_path(params)[0],
    ):
        n = like.size
        pad = np.asarray(mu)[n:]
        assert pad.size == mu.shape[0] - n
        assert np.all(pad == 0.0), f"padding moved: {path}"


# ---------------- setup wiring + dryruns ----------------

def _setup(extra, batch_size, eight_devices):
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup

    # pin the PR-5 flat engine arms: zero3 (PR 7) otherwise auto-takes
    # the fsdp>1 meshes, and the bucketed engine (PR 9) otherwise
    # auto-supersedes the per-leaf schedule this file pins
    cfg = smol_cfg(["parallel.zero3=false",
                    "optim.bucketed_collectives=false"] + list(extra))
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, batch_size, seed=0).items()}
    return build_train_setup(cfg, batch, devices=eight_devices), batch


def test_setup_born_sharded_and_toggles(eight_devices):
    """auto-on at dp > 1: moments born flat over the data axes; =false
    selects the replicated oracle; sharded+unfused conflict raises."""
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup, put_batch

    setup, batch = _setup(["parallel.data=-1", "parallel.fsdp=2"], 8,
                          eight_devices)
    assert setup.sharded_update and setup.fused_update is not None
    mu_leaves = jax.tree.leaves(setup.state.opt_state.adam.mu)
    assert all(l.ndim == 1 for l in mu_leaves)
    specs = [l.sharding.spec for l in mu_leaves]
    assert all(s[0] == UPDATE_SHARD_AXES for s in specs), specs[:2]
    d = put_batch(batch, setup.batch_shardings)
    state, metrics = setup.step_fn(setup.state, d, setup.scalars(0),
                                   jax.random.key(0))
    assert np.isfinite(float(metrics["total_loss"]))
    assert int(state.step) == 1

    setup_off, _ = _setup(["parallel.data=-1", "parallel.fsdp=2",
                           "optim.sharded_update=false"], 8, eight_devices)
    assert not setup_off.sharded_update
    assert all(l.ndim > 0 and l.shape == p.shape for l, p in zip(
        jax.tree.leaves(setup_off.state.opt_state.adam.mu),
        jax.tree.leaves(setup_off.state.params["student"])))

    # auto quietly falls back when the fused engine is off...
    setup_oracle, _ = _setup(["parallel.data=-1",
                              "optim.fused_update=false"], 8, eight_devices)
    assert not setup_oracle.sharded_update
    assert setup_oracle.fused_update is None
    # ...but an EXPLICIT sharded_update=true with fused off is a
    # misconfiguration, not a silent fallback
    with pytest.raises(ValueError, match="sharded_update"):
        _setup(["parallel.data=-1", "optim.fused_update=false",
                "optim.sharded_update=true"], 8, eight_devices)


@pytest.mark.parametrize("axes", [
    ["parallel.data=-1", "parallel.fsdp=2"],
    ["parallel.data=-1", "parallel.tensor=2"],
])
def test_full_step_sharded_vs_replicated(axes, eight_devices):
    """Dryruns under data x fsdp and data x tensor: 2 full steps, the
    sharded arm matches the replicated oracle's losses and params."""
    from dinov3_tpu.train import put_batch

    results = {}
    for flag in ("auto", "false"):
        setup, batch = _setup(axes + [f"optim.sharded_update={flag}"], 8,
                              eight_devices)
        assert setup.sharded_update == (flag == "auto")
        d = put_batch(batch, setup.batch_shardings)
        state = setup.state
        for i in range(2):
            state, m = setup.step_fn(state, d, setup.scalars(i),
                                     jax.random.key(0))
        results[flag] = (state, float(m["total_loss"]))

    assert results["auto"][1] == pytest.approx(results["false"][1], rel=1e-5)
    for (pa, la), (_, lb) in zip(
        jax.tree_util.tree_flatten_with_path(
            results["auto"][0].params)[0][:64],
        jax.tree_util.tree_flatten_with_path(
            results["false"][0].params)[0][:64],
    ):
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(lb), rtol=5e-6, atol=1e-6,
            err_msg=f"dryrun params {jax.tree_util.keystr(pa)}")


def test_sharded_step_census(eight_devices):
    """Collective + copy census of the EXACT compiled sharded step: no
    unattributed collectives, and the engine's pack/unpack copies carry
    the "update_shard" attribution instead of inflating "large"."""
    from dinov3_tpu.train import put_batch
    from dinov3_tpu.utils import hlo_collective_census, hlo_copy_census

    setup, batch = _setup(["parallel.data=-1"], 8, eight_devices)
    assert setup.sharded_update
    d = put_batch(batch, setup.batch_shardings)
    compiled = setup.step_fn.lower(
        setup.state, d, setup.scalars(0), jax.random.key(0)).compile()
    text = compiled.as_text()
    coll = hlo_collective_census(text)
    assert coll["unattributed"] == 0
    # the sharded update's param re-gather is in the program (this
    # backend spells reduce-scatter as all-reduce + fused slice, so
    # all_gather is the structural signature to pin here)
    assert coll["by_class"].get("all_gather", {"ops": 0})["ops"] >= 1
    copies = hlo_copy_census(text)
    # ceiling with headroom over the measured smol program; the census
    # categories must stay attributed (no new unexplained "large" class)
    assert copies["hlo_copy_total"] <= 400, copies


# ---------------- checkpoint round-trip + resume determinism ----------------

def test_checkpoint_cross_arm_roundtrip(tmp_path, eight_devices):
    """sharded -> replicated -> sharded checkpoint round-trip: the
    moments survive bitwise (flat padding is lossless both directions)
    and the resumed run is deterministic — the next sharded step from
    the round-tripped state equals the next step from the original."""
    from dinov3_tpu.checkpoint import Checkpointer
    from dinov3_tpu.train import put_batch

    setup_sh, batch = _setup(["parallel.data=-1", "parallel.fsdp=2"], 8,
                             eight_devices)
    assert setup_sh.sharded_update
    d = put_batch(batch, setup_sh.batch_shardings)
    state1, _ = setup_sh.step_fn(setup_sh.state, d, setup_sh.scalars(0),
                                 jax.random.key(0))

    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    ck.save(1, state1)
    ck.wait_until_finished()

    # restore into the replicated arm: moments become param-shaped
    setup_rep, _ = _setup(["parallel.data=-1", "parallel.fsdp=2",
                           "optim.sharded_update=false"], 8, eight_devices)
    rep_state = ck.restore(setup_rep.state, 1)
    assert all(l.shape == p.shape for l, p in zip(
        jax.tree.leaves(rep_state.opt_state.adam.mu),
        jax.tree.leaves(rep_state.params["student"])))
    # ... and back: bitwise identical to the original sharded state
    ck.save(2, rep_state)
    ck.wait_until_finished()
    back = ck.restore(setup_sh.state, 2)
    for (path, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(state1.opt_state)[0],
        jax.tree_util.tree_flatten_with_path(back.opt_state)[0],
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b)), (
            f"round-trip changed {jax.tree_util.keystr(path)}")

    # resume determinism: the next step from the round-tripped state is
    # the next step from the original state
    s_orig, m_orig = setup_sh.step_fn(state1, d, setup_sh.scalars(1),
                                      jax.random.key(0))
    s_back, m_back = setup_sh.step_fn(back, d, setup_sh.scalars(1),
                                      jax.random.key(0))
    assert float(m_orig["total_loss"]) == float(m_back["total_loss"])
    for (path, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(s_orig.params)[0][:32],
        jax.tree_util.tree_flatten_with_path(s_back.params)[0][:32],
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b)), (
            f"resume diverged at {jax.tree_util.keystr(path)}")

    # the replicated arm also RUNS from the adapted state (clip + update
    # consume the converted moments)
    d_rep = put_batch(batch, setup_rep.batch_shardings)
    s_rep, m_rep = setup_rep.step_fn(rep_state, d_rep, setup_rep.scalars(1),
                                     jax.random.key(0))
    assert np.isfinite(float(m_rep["total_loss"]))
    assert int(s_rep.step) == 2


# ---------------- guardrail ----------------

def test_update_shard_padding_guardrail(recwarn):
    from dinov3_tpu.configs.config import (
        update_shard_padding_waste,
        warn_update_shard_padding,
    )

    # well-divisible leaves: zero waste, no warning
    assert update_shard_padding_waste([64, 128, 1024], 8) == 0.0
    assert warn_update_shard_padding([64, 128, 1024], 8) is None
    # tiny-leaf pathology: [3, 5, 7] at dp=8 pads 15 -> 24 (60%)
    waste = update_shard_padding_waste([3, 5, 7], 8)
    assert waste > 0.5
    msg = warn_update_shard_padding([3, 5, 7], 8)
    assert msg is not None and "sharded-update flat master axis" in msg
    assert "dp=8" in msg
    w = [x for x in recwarn.list
         if "sharded-update flat master axis" in str(x.message)]
    assert len(w) == 1
    # threshold respected: 1 padded element in 1e6 is silent
    assert warn_update_shard_padding([10 ** 6 - 1], 8) is None


# ---------------- collective census ----------------

def test_classify_collective_attribution():
    from dinov3_tpu.utils import classify_collective

    ent = "ENTRY %main.1 (p0: f32[8]) -> f32[8] {\n"
    cases = {
        "  %ar = f32[128]{0} all-reduce(f32[128]{0} %x), replica_groups={}":
            "all_reduce",
        "  %ars = (f32[128], f32[128]) all-reduce-start(f32[128] %x)":
            "all_reduce",
        "  %rs = f32[16]{0} reduce-scatter(f32[128]{0} %x), dimensions={0}":
            "reduce_scatter",
        "  %ag = f32[128]{0} all-gather(f32[16]{0} %x), dimensions={0}":
            "all_gather",
        "  %cp = f32[16]{0} collective-permute(f32[16]{0} %x)": "ppermute",
        "  %aa = f32[16]{0} all-to-all(f32[16]{0} %x)": "all_to_all",
        "  %cb = f32[16]{0} collective-broadcast(f32[16]{0} %x)":
            "unattributed",
        # -done halves and non-collectives don't count
        "  %ard = f32[128]{0} all-reduce-done((f32[128], f32[128]) %ars)":
            None,
        "  %f = f32[128]{0} fusion(f32[128]{0} %x), kind=kLoop": None,
        "  %red = f32[] reduce(f32[128]{0} %x, f32[] %c)": None,
    }
    for line, want in cases.items():
        assert classify_collective(line) == want, line
    # whole-module census over the same lines
    from dinov3_tpu.utils import hlo_collective_census

    census = hlo_collective_census(ent + "\n".join(cases) + "\n}")
    assert census["by_class"]["all_reduce"]["ops"] == 2
    assert census["by_class"]["reduce_scatter"]["ops"] == 1
    assert census["by_class"]["reduce_scatter"]["bytes"] == 16 * 4
    assert census["unattributed"] == 1


def test_cost_script_census_acceptance(mesh8):
    """The COST_SHUP acceptance pins, on the test-scale trees: the
    schedule program's census is reduce-scatter + all-gather + the one
    small clip psum with ZERO unattributed collectives (one RS per leaf,
    two AG per leaf — student and teacher); the replicated arm is
    all-reduce only, with no RS/AG."""
    from dinov3_tpu.utils import hlo_collective_census

    sched = make_sched()
    params = fake_params()
    n_leaves = len(jax.tree.leaves(params))
    lm, wm, ll = build_multiplier_trees(params)
    fused = make_fused_update(sched, lm, wm, ll, clip_grad=3.0, ema=True)
    schedule = make_sharded_update_schedule(sched, lm, wm, ll, mesh8,
                                            clip_grad=3.0, ema=True)
    momentum = jnp.asarray(0.9, jnp.float32)
    s_sh = sharded_opt_init(params, sched, lm, wm, ll)
    s_rep = scheduled_adamw(sched, lm, wm, ll).init(params)
    gstack = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct((8,) + l.shape, l.dtype), params)

    with mesh8:
        from jax.sharding import NamedSharding, PartitionSpec as P

        axes = tuple(a for a in UPDATE_SHARD_AXES if a in mesh8.shape)
        stacks = jax.tree.map(lambda _: NamedSharding(mesh8, P(axes)),
                              gstack)
        c_sh = jax.jit(
            lambda gp, p, t, s: schedule(gp, p, t, s, momentum),
            in_shardings=(stacks, None, None, None),
        ).lower(gstack, params, params, s_sh).compile()
        c_rep = jax.jit(
            lambda gp, p, t, s: fused(
                jax.tree.map(lambda x: jnp.sum(x, 0), gp), p, t, s,
                momentum),
            in_shardings=(stacks, None, None, None),
        ).lower(gstack, params, params, s_rep).compile()

    sh = hlo_collective_census(c_sh.as_text())
    assert sh["unattributed"] == 0
    assert sh["by_class"]["reduce_scatter"]["ops"] == n_leaves
    assert sh["by_class"]["all_gather"]["ops"] == 2 * n_leaves
    # the only all-reduce is the small clip-norm psum (scalar bytes)
    ar = sh["by_class"].get("all_reduce", {"ops": 0, "bytes": 0})
    assert ar["bytes"] <= 64

    rep = hlo_collective_census(c_rep.as_text())
    assert rep["unattributed"] == 0
    assert rep["by_class"].get("reduce_scatter", {"ops": 0})["ops"] == 0
    assert rep["by_class"].get("all_gather", {"ops": 0})["ops"] == 0
    assert rep["by_class"]["all_reduce"]["ops"] >= 1
    # the committed ViT-L artifact tells the same story at scale
    import json
    import os

    art = os.path.join(os.path.dirname(__file__), "..", "COST_SHUP_r10.json")
    with open(art) as f:
        rec = json.load(f)
    assert rec["weight_shaped_reduction_pct"] >= 60.0
    assert rec["collective_census"]["sharded"]["unattributed"] == 0
    assert rec["collective_census"]["replicated"]["by_class"].keys() == {
        "all_reduce"}
    assert "reduce_scatter" in rec["collective_census"]["sharded"]["by_class"]
    assert "all_gather" in rec["collective_census"]["sharded"]["by_class"]
