"""Step-anatomy trace plane (telemetry/trace.py + telemetry/anatomy.py):
the measured-overlap ledger, per-scope device-time attribution, fleet
straggler report, and the perf-regression gate (scripts/perf_gate.py).

Layers under test:

- ``categorize`` — the shared op classifier, including the two bugs the
  old scripts/profile_step.py classifier carried (fusion-absorbs-matmul
  undercount; ``convert_element_type`` miscounted as a convolution);
- interval arithmetic + step-window splitting, exact on synthetic data;
- a synthetic Chrome-trace ledger whose exposed/overlapped collective
  milliseconds are computed by hand;
- ``build_op_index`` round-trips on REAL compiled programs of the
  bucketed and zero3 stream twins (named scopes + backward stamps);
- the bucketed twin executed under the profiler: trace -> ledger with
  the compiled HLO joined, zero unattributed collective time;
- fleet straggler math and the bound-verdict policy on synthetic spans;
- the ``warn_exposed_comm`` guardrail (fire/no-fire/tolerance checks);
- scripts/perf_gate.py: identity pass, synthetic step-time and
  exposed-comm regressions fail, noise-aware tolerance clamps;
- committed-artifact pins: ANATOMY_r17.json acceptance (all four arms,
  zero unattributed, measured in-backward bucket-RS time) and the
  PROFILE_r17.json equivalence pin re-derived from the committed trace.
"""

import glob
import gzip
import importlib.util
import json
import os
import tempfile

import jax
import jax.numpy as jnp
import pytest

from dinov3_tpu.telemetry.anatomy import (
    CATEGORIES,
    anatomy_ledger,
    build_op_index,
    categorize,
    emit_step_anatomy,
    fleet_report,
    intersect_length,
    ledger_summary,
    load_span_streams,
    merge_intervals,
    round_floats,
    step_windows,
)
from dinov3_tpu.telemetry.trace import (
    Trace,
    TraceEvent,
    find_trace_file,
    load_trace,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------- categorize ----------------


def test_categorize_buckets():
    assert categorize("all-reduce.17") == "collective"
    assert categorize("reduce-scatter.3") == "collective"
    assert categorize("collective-permute.1") == "collective"
    assert categorize("dot.42") == "matmul/conv"
    assert categorize("loop_convolution_fusion.2") == "matmul/conv"
    assert categorize("softmax_fusion") == "softmax/exp"
    assert categorize("exponential.1") == "softmax/exp"
    assert categorize("layer_norm_fusion") == "norm/reduce"
    assert categorize("multiply_reduce_fusion") == "norm/reduce"
    assert categorize("copy.1") == "copy/layout"
    assert categorize("transpose.9") == "copy/layout"
    assert categorize("loop_add_fusion.3") == "fusion/elementwise"
    assert categorize("custom-call.3") == "other"
    for name in ("dot.1", "fusion.2", "all-reduce.1", "whatever"):
        assert categorize(name) in CATEGORIES


def test_categorize_fixes_old_profile_step_bugs():
    # bug 1 (undercount): a fusion kind-name carrying a dot/conv token
    # was binned fusion/elementwise by the old flat classifier
    assert categorize("convolution_add_fusion.1") == "matmul/conv"
    # ...and a fusion whose BODY contains a dot (kind-name hides it)
    # is forced to matmul/conv via the HLO op index's fusion_dotty
    assert categorize("loop_add_fusion.1", fusion_dotty=True) \
        == "matmul/conv"
    # bug 2 (miscount): bare '"conv" in name' claimed every
    # convert_element_type as a convolution
    assert categorize("convert_element_type.5") == "copy/layout"
    assert categorize("convert.2") == "copy/layout"


# ---------------- interval arithmetic ----------------


def test_merge_intervals():
    assert merge_intervals([(5, 15), (0, 10), (20, 30), (30, 40),
                            (50, 50)]) == [(0, 15), (20, 40)]
    assert merge_intervals([]) == []
    assert merge_intervals([(3, 1)]) == []


def test_intersect_length_exact():
    merged = merge_intervals([(0, 15), (20, 40)])
    assert intersect_length(3, 25, merged) == (15 - 3) + (25 - 20)
    assert intersect_length(40, 60, merged) == 0.0
    assert intersect_length(-5, 0, merged) == 0.0
    assert intersect_length(0, 100, merged) == 15 + 20
    assert intersect_length(10, 10, merged) == 0.0


def _ev(name, ts, dur, pid=1, tid=0, **kw):
    return TraceEvent(name=name, pid=pid, tid=tid, ts=float(ts),
                      dur=float(dur), **kw)


def test_step_windows_largest_gaps():
    evs = [_ev("a", 0, 10), _ev("b", 12, 10), _ev("c", 1000, 10),
           _ev("d", 1015, 10), _ev("e", 2000, 10)]
    wins = step_windows(evs, 3)
    assert len(wins) == 3
    # each cluster lands whole in its own window
    for cluster, (w0, w1) in zip(([0, 12], [1000, 1015], [2000]), wins):
        for t in cluster:
            assert w0 <= t < w1
    # no n_steps, or too few events to split: one window
    assert len(step_windows(evs, None)) == 1
    assert len(step_windows(evs[:2], 3)) == 1
    assert step_windows([], 4) == []


# ---------------- synthetic-trace ledger: exact math ----------------


def _synthetic_trace():
    """One device pid, two steps. Step 0: a 100 ms collective
    (0..100 ms) half-covered by a 100 ms compute fusion (50..150 ms) ->
    50 ms overlapped, 50 ms exposed. Step 1 (after a long gap): a
    100 ms collective with no concurrent compute -> fully exposed."""
    events = [
        _ev("all-reduce.1", 0, 100_000),
        _ev("loop_add_fusion.1", 50_000, 100_000),
        _ev("all-reduce.2", 1_000_000, 100_000),
    ]
    return Trace(events=events, process_names={1: "/device:TPU:0"},
                 thread_names={}, path="synthetic")


def test_synthetic_ledger_exact_overlap_math():
    ledger = anatomy_ledger(_synthetic_trace(), n_steps=2)
    assert ledger["schema"] == "anatomy/v1"
    assert ledger["n_steps"] == 2 and ledger["n_timelines"] == 1
    assert ledger["hlo_joined"] is False
    s0, s1 = ledger["steps"]
    c0 = s0["collectives"]["unscoped"]  # no HLO index -> "unscoped"
    assert c0["ms"] == pytest.approx(100.0)
    assert c0["overlapped_ms"] == pytest.approx(50.0)
    assert c0["exposed_ms"] == pytest.approx(50.0)
    assert c0["overlap_frac"] == pytest.approx(0.5)
    assert s0["device_busy_ms"] == pytest.approx(200.0)
    assert s0["exposed_comm_frac"] == pytest.approx(50.0 / 200.0)
    assert s0["device_ms"]["fusion/elementwise"] == pytest.approx(100.0)
    c1 = s1["collectives"]["unscoped"]
    assert c1["exposed_ms"] == pytest.approx(100.0)
    assert c1["overlapped_ms"] == pytest.approx(0.0)
    assert s1["exposed_comm_frac"] == pytest.approx(1.0)
    # no index at all -> nothing can be "unattributed"
    assert ledger["unattributed_collective_ms"] == 0.0

    summary = ledger_summary(ledger)
    assert summary["schema"] == "anatomy-summary/v1"
    agg = summary["collectives"]["unscoped"]
    assert agg["ms_per_step"] == pytest.approx(100.0)
    assert agg["exposed_ms_per_step"] == pytest.approx(75.0)
    assert agg["overlap_frac"] == pytest.approx(50.0 / 200.0)
    assert summary["exposed_comm_frac"] == pytest.approx(150.0 / 300.0)
    assert summary["step_wall_ms"]["mean"] == pytest.approx(
        (150.0 + 100.0) / 2)


def test_trace_reader_roundtrip(tmp_path):
    """Write a Chrome-trace JSON the way jax lays it out; find + load
    it back; .pb paths raise the pointed no-TF-protos error."""
    raw = {"traceEvents": [
        {"name": "process_name", "ph": "M", "pid": 7,
         "args": {"name": "/host:CPU"}},
        {"name": "thread_name", "ph": "M", "pid": 7, "tid": 3,
         "args": {"name": "tf_XLATfrtCpuClient_0"}},
        {"name": "fusion.1", "ph": "X", "pid": 7, "tid": 3, "ts": 10.0,
         "dur": 5.0, "args": {"hlo_op": "fusion.1",
                              "hlo_module": "jit_step"}},
        {"name": "zero-dur", "ph": "X", "pid": 7, "tid": 3, "ts": 1.0,
         "dur": 0.0},
        {"name": "counter", "ph": "C", "pid": 7, "tid": 3, "ts": 2.0},
    ]}
    d = tmp_path / "trace" / "plugins" / "profile" / "2026_01_01"
    d.mkdir(parents=True)
    with gzip.open(d / "host.trace.json.gz", "wt") as f:
        json.dump(raw, f)
    found = find_trace_file(str(tmp_path / "trace"))
    assert found and found.endswith(".trace.json.gz")
    tr = load_trace(found)
    assert len(tr.events) == 1  # ph=="X" with dur>0 only
    assert tr.events[0].op_key == "fusion.1"
    assert tr.modules() == {"jit_step": 5.0}
    assert list(tr.timelines(tr.op_events())) \
        == ["/host:CPU/tf_XLATfrtCpuClient_03"]
    with pytest.raises(ValueError, match="xplane.pb"):
        load_trace("some/xplane.pb")


def test_emit_step_anatomy_writes_ledger_and_span(tmp_path):
    raw = {"traceEvents": [
        {"name": "process_name", "ph": "M", "pid": 1,
         "args": {"name": "/device:TPU:0"}},
        {"name": "all-reduce.1", "ph": "X", "pid": 1, "tid": 0,
         "ts": 0.0, "dur": 100.0},
        {"name": "dot.1", "ph": "X", "pid": 1, "tid": 0,
         "ts": 0.0, "dur": 100.0},
    ]}
    d = tmp_path / "plugins" / "profile" / "t0"
    d.mkdir(parents=True)
    with gzip.open(d / "vm.trace.json.gz", "wt") as f:
        json.dump(raw, f)

    emitted = []

    class FakeTracer:
        def emit(self, rec):
            emitted.append(rec)

    summary = emit_step_anatomy(str(tmp_path), n_steps=1,
                                tracer=FakeTracer(), iteration=12)
    assert summary is not None
    assert (tmp_path / "anatomy.json").exists()
    with open(tmp_path / "anatomy.json") as f:
        assert json.load(f)["schema"] == "anatomy/v1"
    assert len(emitted) == 1 and emitted[0]["name"] == "anatomy"
    assert emitted[0]["iteration"] == 12
    assert emitted[0]["summary"]["collectives"]
    # empty dir -> None, no artifacts
    assert emit_step_anatomy(str(tmp_path / "nothing")) is None


# ---------------- op-index round-trip on real compiled twins ----------


@pytest.fixture(scope="module")
def mesh8():
    from dinov3_tpu.parallel.context import (
        get_current_mesh,
        set_current_mesh,
    )
    from dinov3_tpu.parallel.mesh import MeshSpec, build_mesh

    prev = get_current_mesh()
    mesh = build_mesh(MeshSpec(data=8))
    set_current_mesh(mesh)
    yield mesh
    set_current_mesh(prev)


def _bucketed_twin_compiled(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dinov3_tpu.models.streaming import (
        bucketed_stream_scan,
        pack_stream_buckets,
    )
    from dinov3_tpu.parallel.sharding import UPDATE_SHARD_AXES

    n_blocks, n_buckets, dp = 8, 4, 8
    stack = {"attn": {"qkv": {"kernel": jnp.zeros(
        (n_blocks, 16, 48), jnp.bfloat16)}},
        "mlp": {"fc1": {"kernel": jnp.zeros(
            (n_blocks, 16, 64), jnp.bfloat16)}}}
    shards = jax.eval_shape(
        lambda s: pack_stream_buckets(s, n_buckets, dp), stack)
    x = jax.ShapeDtypeStruct((dp * 4,), jnp.float32)
    axes = tuple(a for a in UPDATE_SHARD_AXES if a in mesh.shape)

    def loss(shards, x):
        return jnp.sum(bucketed_stream_scan(
            shards, x, mesh=mesh, prefetch=True))

    with mesh:
        compiled = jax.jit(
            jax.grad(loss),
            in_shardings=(NamedSharding(mesh, P(None, axes)),
                          NamedSharding(mesh, P())),
            out_shardings=NamedSharding(mesh, P(None, axes)),
        ).lower(shards, x).compile()
    in_shardings = (NamedSharding(mesh, P(None, axes)),
                    NamedSharding(mesh, P()))
    args = (jax.device_put(jnp.zeros(shards.shape, shards.dtype),
                           in_shardings[0]),
            jax.device_put(jnp.zeros(x.shape, x.dtype), in_shardings[1]))
    return compiled, args


def test_op_index_roundtrip_bucketed_traced(mesh8):
    """The full dynamic round-trip on the bucketed overlap twin:
    execute the compiled grad under the profiler, join the ledger
    against the compiled HLO — every collective event must land in a
    named scope (zero unattributed), bucket scopes among them, and the
    measured backward interval must contain bucket-scoped collective
    time (the dynamic twin of COST_BUCKET_r13's in-backward-loop
    placement)."""
    compiled, args = _bucketed_twin_compiled(mesh8)
    hlo = compiled.as_text()

    idx = build_op_index(hlo)
    colls = {n: i for n, i in idx.items() if i["category"] == "collective"}
    assert colls, "compiled twin lost its collectives"
    assert any((i["scope"] or "").startswith("bucket")
               for i in colls.values()), sorted(
        {i["scope"] for i in colls.values()})
    assert any(i["backward"] for i in idx.values())

    jax.block_until_ready(compiled(*args))  # warmup outside the window
    tdir = tempfile.mkdtemp(prefix="anat_test_", dir="/tmp")
    jax.profiler.start_trace(tdir)
    for _ in range(2):
        jax.block_until_ready(compiled(*args))
    jax.profiler.stop_trace()

    ledger = anatomy_ledger(tdir, hlo_text=hlo, n_steps=2)
    assert ledger["hlo_joined"] is True
    assert ledger["n_steps"] == 2
    assert ledger["unattributed_collective_ms"] == 0.0
    summary = ledger_summary(ledger)
    scopes = set(summary["collectives"])
    assert any(s.startswith("bucket") for s in scopes), scopes
    total_coll = sum(c["ms_per_step"]
                     for c in summary["collectives"].values())
    assert total_coll > 0
    import shutil

    shutil.rmtree(tdir, ignore_errors=True)


def test_op_index_roundtrip_zero3_compiled(mesh8):
    """zero3 stream twin (streamed_block_scan grad): the double-buffer
    gathers index with zero3_* scopes; their transposed reduce-scatters
    carry the backward stamp."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dinov3_tpu.models.streaming import streamed_block_scan
    from dinov3_tpu.parallel.sharding import zero3_leaf_spec

    L, D = 4, 16
    stack = {"w": jax.ShapeDtypeStruct((L, D, D), jnp.bfloat16)}

    def apply(p, x):
        return x @ p["w"].astype(x.dtype)

    def loss(stack, x):
        y = streamed_block_scan(apply, stack, x, L, mesh8)
        return jnp.sum(y.astype(jnp.float32))

    def stack_sharding(p):
        spec = zero3_leaf_spec(
            p.shape, ("layers",) + (None,) * (len(p.shape) - 1), mesh8)
        return NamedSharding(mesh8, spec if spec is not None else P())

    x = jax.ShapeDtypeStruct((8, D), jnp.bfloat16)
    with mesh8:
        compiled = jax.jit(
            jax.grad(loss),
            in_shardings=(jax.tree.map(stack_sharding, stack),
                          NamedSharding(mesh8, P("data"))),
        ).lower(stack, x).compile()
    idx = build_op_index(compiled.as_text())
    colls = {n: i for n, i in idx.items() if i["category"] == "collective"}
    assert colls
    scopes = {i["scope"] for i in colls.values()}
    assert any((s or "").startswith("zero3") for s in scopes), scopes
    assert any(i["backward"] for i in colls.values()), colls


# ---------------- fleet report ----------------


def _dispatch_stream(step_s, n=6, t0=0.0):
    return [{"name": "dispatch", "iteration": i, "t": t0 + i * step_s}
            for i in range(n)]


def test_fleet_straggler_math():
    streams = {f"rank{i}": _dispatch_stream(0.100) for i in range(5)}
    streams["rank5"] = _dispatch_stream(0.400)  # the straggler
    rep = fleet_report(streams)
    assert rep["schema"] == "fleet/v1" and rep["n_hosts"] == 6
    assert rep["hosts"]["rank0"]["step_ms"]["mean"] == pytest.approx(100.0)
    assert rep["hosts"]["rank5"]["step_ms"]["mean"] == pytest.approx(400.0)
    # 5 hosts at 100 ms + 1 at 400: mean 150, std sqrt(12500) -> z 2.236
    assert rep["fleet_step_ms"]["mean"] == pytest.approx(150.0)
    assert rep["hosts"]["rank5"]["straggler_z"] == pytest.approx(
        2.2360679, rel=1e-5)
    assert rep["stragglers"] == ["rank5"]
    assert all(rep["hosts"][f"rank{i}"]["straggler_z"] < 0
               for i in range(5))


def test_fleet_single_host_z_and_verdicts():
    one = {"rank0": _dispatch_stream(0.100)}
    rep = fleet_report(one)
    assert rep["hosts"]["rank0"]["straggler_z"] == 0.0
    assert rep["verdict"] == "compute-bound"
    # measured exposed comm above tolerance -> comm-bound
    rep = fleet_report(one, anatomy={"exposed_comm_frac": 0.6})
    assert rep["verdict"] == "comm-bound"
    # data-wait dominating the pitch wins over comm: input-bound
    hungry = {"rank0": _dispatch_stream(0.100)
              + [{"name": "data_wait", "dur_ms": 60.0}] * 5}
    rep = fleet_report(hungry, anatomy={"exposed_comm_frac": 0.6})
    assert rep["verdict"] == "input-bound"
    assert rep["max_data_wait_frac"] == pytest.approx(0.6)


def test_load_span_streams_ranks_roles_torn_lines(tmp_path):
    tdir = tmp_path / "telemetry"
    tdir.mkdir()
    with open(tdir / "spans.jsonl", "w") as f:
        f.write(json.dumps({"v": 1, "name": "dispatch", "iteration": 0,
                            "t": 0.0}) + "\n")
        f.write(json.dumps({"v": 2, "name": "dispatch"}) + "\n")  # wrong v
        f.write('{"torn": ')  # live-writer tail
    with open(tdir / "spans.rank1.jsonl", "w") as f:
        f.write(json.dumps({"v": 1, "name": "dispatch", "iteration": 0,
                            "t": 0.0, "role": "train"}) + "\n")
        f.write(json.dumps({"v": 1, "name": "dispatch", "iteration": 1,
                            "t": 0.1, "role": "serve"}) + "\n")
    streams = load_span_streams(str(tmp_path))
    assert sorted(streams) == ["rank0", "rank1"]
    assert len(streams["rank0"]) == 1
    assert len(streams["rank1"]) == 1  # serve-role record filtered


# ---------------- warn_exposed_comm guardrail ----------------


def test_warn_exposed_comm_fire_and_quiet(recwarn):
    from dinov3_tpu.configs import get_default_config
    from dinov3_tpu.configs.config import warn_exposed_comm

    cfg = get_default_config()  # exposed_comm_tol: 0.25
    summary = {
        "exposed_comm_frac": 0.60,
        "collectives": {
            "bucket_pack": {"exposed_ms_per_step": 9.0, "overlap_frac": 0.1},
            "other": {"exposed_ms_per_step": 2.0, "overlap_frac": 0.0},
        },
    }
    msg = warn_exposed_comm(cfg, summary)
    assert msg and "bucket_pack" in msg and "0.25" in msg
    assert any("exposed comm" in str(w.message) for w in recwarn.list)
    # within tolerance: silent
    assert warn_exposed_comm(cfg, {"exposed_comm_frac": 0.1,
                                   "collectives": {}}) is None
    # anatomy plane off: never fires, even over tolerance
    cfg.telemetry.anatomy = False
    assert warn_exposed_comm(cfg, summary) is None


def test_warn_exposed_comm_tol_validation(recwarn):
    from dinov3_tpu.configs import get_default_config
    from dinov3_tpu.configs.config import warn_exposed_comm

    cfg = get_default_config()
    assert warn_exposed_comm(cfg) is None  # default tol is sane
    cfg.telemetry.exposed_comm_tol = 1.5
    msg = warn_exposed_comm(cfg)
    assert msg and "exposed_comm_tol" in msg


# ---------------- perf gate ----------------


def _gate_baseline(mean=100.0, std=1.0, n=4, exposed=0.2):
    return {"arms": {"a": {"anatomy": {
        "schema": "anatomy-summary/v1", "n_steps": n,
        "step_wall_ms": {"mean": mean, "std": std},
        "exposed_comm_frac": exposed}}}}


def test_perf_gate_pass_and_regressions():
    pg = _load_script("perf_gate")
    base = _gate_baseline()
    assert pg.gate(base, base)["passed"] is True
    # within the 3% floor: passes
    assert pg.gate(base, _gate_baseline(mean=102.0))["passed"] is True
    # a 10% step-time regression ALWAYS fails (tolerance cap 8%)
    r = pg.gate(base, _gate_baseline(mean=110.0))
    assert r["passed"] is False
    assert any("step time regressed" in c["status"] for c in r["checks"])
    # exposed-comm drift beyond the absolute tolerance fails
    r = pg.gate(base, _gate_baseline(exposed=0.2 + 0.10))
    assert r["passed"] is False
    assert any("exposed-comm" in c["status"] for c in r["checks"])
    # ...but small drift within it passes
    assert pg.gate(base, _gate_baseline(exposed=0.24))["passed"] is True
    # an arm missing from the fresh record is skipped, not failed
    r = pg.gate(base, {"arms": {}})
    assert r["passed"] is True and "skipped" in r["checks"][0]["status"]


def test_perf_gate_noise_aware_tolerance():
    pg = _load_script("perf_gate")
    quiet = {"n_steps": 4, "step_wall_ms": {"mean": 100.0, "std": 0.0}}
    assert pg.step_time_tolerance(quiet) == pytest.approx(0.03)
    noisy = {"n_steps": 4, "step_wall_ms": {"mean": 100.0, "std": 40.0}}
    assert pg.step_time_tolerance(noisy) == pytest.approx(0.08)  # capped
    mid = {"n_steps": 4, "step_wall_ms": {"mean": 100.0, "std": 4.0}}
    # 3 * 0.04 / sqrt(4) = 0.06: between floor and cap
    assert pg.step_time_tolerance(mid) == pytest.approx(0.06)


def test_perf_gate_self_check_on_committed_baseline(capsys):
    pg = _load_script("perf_gate")
    with open(os.path.join(REPO, "ANATOMY_r17.json")) as f:
        baseline = json.load(f)
    assert pg.self_check(baseline) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["self_check"] == "ok" and out["n_arms"] >= 4


# ---------------- committed-artifact pins ----------------


def test_anatomy_r17_acceptance():
    """ANATOMY_r17.json: measured ledgers for all four arms, every
    collective attributed to a scope (zero unattributed ms), and the
    bucketed overlap twin's reduce-scatter time measured INSIDE the
    backward interval — consistent with the static COST_BUCKET_r13
    census."""
    with open(os.path.join(REPO, "ANATOMY_r17.json")) as f:
        rec = json.load(f)
    arms = rec["arms"]
    assert set(arms) >= {"replicated", "flat", "bucketed", "zero3"}
    for name, arm in arms.items():
        a = arm["anatomy"]
        assert a["schema"] == "anatomy-summary/v1", name
        assert a["hlo_joined"] is True, name
        assert a["unattributed_collective_ms"] == 0.0, name
        assert a["collectives"], name
        assert a["step_wall_ms"]["mean"] > 0, name
    # coalescing story in measured events: per-leaf arm carries far
    # more collective launches than the bucketed arm
    flat_n = sum(c["n_events"]
                 for c in arms["flat"]["anatomy"]["collectives"].values())
    bk_n = sum(c["n_events"]
               for c in arms["bucketed"]["anatomy"]["collectives"].values())
    assert flat_n > 3 * bk_n, (flat_n, bk_n)
    assert any(s.startswith("bucket")
               for s in arms["bucketed"]["anatomy"]["collectives"])
    assert any(s.startswith("zero3")
               for s in arms["zero3"]["anatomy"]["collectives"])
    # the measured-overlap column: bucket-scoped RS inside the measured
    # backward interval, matching the static in-backward-loop placement
    cons = rec["consistency"]
    assert cons["bucketed_rs_inside_backward_ms"] > 0
    assert cons["cost_bucket_r13_in_backward_loop_ops"] >= 1
    # the real-trainer dryrun wiring banked too
    assert rec["dryrun"]["anatomy"]["n_steps"] == 3
    assert rec["dryrun"]["fleet"]["verdict"] in (
        "input-bound", "comm-bound", "compute-bound")


def test_profile_r17_equivalence_pin():
    """The committed PROFILE_r17.json re-derives byte-identically from
    the committed trace through the shared parser (name-only path: no
    HLO join, so the derivation depends on nothing but the trace and
    the parser) — the pin that freezes parser semantics."""
    ps = _load_script("profile_step")
    trace = os.path.join(REPO, "docs", "profiles",
                         "PROFILE_r17_trace.json.gz")
    rec = ps.breakdown(trace, 3, None)
    with open(os.path.join(REPO, "PROFILE_r17.json")) as f:
        committed = json.load(f)
    assert rec == committed
    assert committed["schema"] == "profile/v2"
    assert committed["n_steps"] == 3
    # the trace is a real vit_test dp=8 train window: it must carry
    # collective + matmul device time
    cats = committed["by_category_ms_per_step"]
    assert cats.get("collective", 0) > 0
    assert cats.get("matmul/conv", 0) > 0


def test_round_floats():
    assert round_floats({"a": [1.23456789, {"b": (2.0000001,)}],
                         "c": "s", "d": 3}) \
        == {"a": [1.2346, {"b": [2.0]}], "c": "s", "d": 3}
