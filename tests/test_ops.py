import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinov3_tpu.ops import (
    DINOHead,
    LayerNorm,
    LayerScale,
    Mlp,
    PatchEmbed,
    RMSNorm,
    SelfAttention,
    SelfAttentionBlock,
    SwiGLUFFN,
    rope_apply_with_prefix,
    rope_periods,
    rope_sincos,
    swiglu_hidden_dim,
    xla_attention,
)

F32 = dict(dtype=jnp.float32, param_dtype=jnp.float32)


# ---------------- RoPE ----------------

def test_rope_periods_base_spectrum():
    p = rope_periods(head_dim=16, base=100.0)
    assert p.shape == (4,)
    # base ** (2j / (D/2)) for j in 0..3, D=16
    expect = 100.0 ** (2 * np.arange(4) / 8.0)
    np.testing.assert_allclose(np.asarray(p), expect, rtol=1e-5)


def test_rope_periods_minmax_range():
    p = np.asarray(rope_periods(head_dim=16, base=None, min_period=0.5, max_period=8.0))
    assert abs(p[0] - 0.5) < 1e-5 and abs(p[-1] - 8.0) < 1e-4
    assert np.all(np.diff(p) > 0)


def test_rope_sincos_shapes_and_identity():
    sin, cos = rope_sincos(4, 6, rope_periods(32))
    assert sin.shape == (24, 32) and cos.shape == (24, 32)
    np.testing.assert_allclose(np.asarray(sin**2 + cos**2), 1.0, atol=1e-5)


def test_rope_rotation_preserves_norm_and_prefix():
    rng = jax.random.key(0)
    B, N, h, d, P = 2, 10, 3, 16, 8
    q = jax.random.normal(rng, (B, N, h, d))
    k = jax.random.normal(jax.random.key(1), (B, N, h, d))
    sin, cos = rope_sincos(2, 4, rope_periods(d))
    q2, k2 = rope_apply_with_prefix(q, k, sin, cos)
    # prefix tokens (first N-P) untouched
    np.testing.assert_allclose(np.asarray(q2[:, : N - P]), np.asarray(q[:, : N - P]))
    # rotation preserves per-pair norms => full vector norm
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(q2), axis=-1),
        np.linalg.norm(np.asarray(q), axis=-1),
        rtol=1e-5,
    )
    # and actually rotates
    assert not np.allclose(np.asarray(q2[:, -1]), np.asarray(q[:, -1]))


def test_rope_augmentation_changes_tables():
    p = rope_periods(16)
    s1, _ = rope_sincos(4, 4, p, rng=jax.random.key(0), shift=0.5)
    s2, _ = rope_sincos(4, 4, p, rng=jax.random.key(1), shift=0.5)
    s3, _ = rope_sincos(4, 4, p)
    assert not np.allclose(np.asarray(s1), np.asarray(s2))
    assert not np.allclose(np.asarray(s1), np.asarray(s3))


# ---------------- attention ----------------

def test_xla_attention_matches_flax():
    rng = jax.random.key(0)
    B, N, h, d = 2, 9, 4, 8
    q, k, v = jax.random.normal(rng, (3, B, N, h, d))
    ours = xla_attention(q, k, v)
    ref = nn.dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(ours), np.asarray(ref), atol=2e-5)


def test_self_attention_forward_and_k_bias_invariance():
    B, N, D = 2, 12, 32
    x = jax.random.normal(jax.random.key(0), (B, N, D))
    attn = SelfAttention(dim=D, num_heads=4, mask_k_bias=True, attn_impl="xla", **F32)
    params = nn.meta.unbox(attn.init(jax.random.key(1), x))
    y0 = attn.apply(params, x)
    assert y0.shape == (B, N, D)
    # poke the k third of the qkv bias: masked -> output must not change
    import flax

    flat = flax.traverse_util.flatten_dict(params)
    key = [k_ for k_ in flat if k_[-1] == "qkv_bias"][0]
    b = flat[key]
    poked = b.at[D : 2 * D].set(77.0)
    flat[key] = poked
    params2 = flax.traverse_util.unflatten_dict(flat)
    y1 = attn.apply(params2, x)
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y1), atol=1e-6)
    # q third is NOT masked
    flat[key] = b.at[:D].set(7.0)
    y2 = attn.apply(flax.traverse_util.unflatten_dict(flat), x)
    assert not np.allclose(np.asarray(y0), np.asarray(y2))


def test_self_attention_with_rope_runs():
    B, N, D, h = 2, 4 + 2, 32, 4  # 2 prefix + 2x2 patches
    x = jax.random.normal(jax.random.key(0), (B, N, D))
    rope = rope_sincos(2, 2, rope_periods(D // h))
    attn = SelfAttention(dim=D, num_heads=h, attn_impl="xla", **F32)
    params = attn.init(jax.random.key(1), x, rope=rope)
    y = attn.apply(params, x, rope=rope)
    assert y.shape == (B, N, D)


# ---------------- ffn / norms / misc ----------------

def test_swiglu_hidden_rule():
    assert swiglu_hidden_dim(4096, 64) == 2752  # ceil(2731/64)*64
    assert swiglu_hidden_dim(12, 8) == 8


def test_mlp_and_swiglu_shapes():
    x = jax.random.normal(jax.random.key(0), (2, 5, 24))
    mlp = Mlp(hidden_dim=96, **F32)
    p = mlp.init(jax.random.key(1), x)
    assert mlp.apply(p, x).shape == x.shape
    sw = SwiGLUFFN(hidden_dim=96, align_to=8, **F32)
    p = sw.init(jax.random.key(1), x)
    assert sw.apply(p, x).shape == x.shape


def test_layernorm_matches_flax():
    x = jax.random.normal(jax.random.key(0), (4, 7, 16))
    ours = LayerNorm()
    p = ours.init(jax.random.key(1), x)
    ref = nn.LayerNorm(epsilon=1e-6)
    pr = ref.init(jax.random.key(1), x)
    np.testing.assert_allclose(
        np.asarray(ours.apply(p, x)), np.asarray(ref.apply(pr, x)), atol=1e-5
    )


def test_rmsnorm_formula():
    x = jax.random.normal(jax.random.key(0), (3, 8))
    m = RMSNorm(epsilon=1e-6)
    p = m.init(jax.random.key(1), x)
    got = np.asarray(m.apply(p, x))
    xn = np.asarray(x)
    expect = xn / np.sqrt((xn**2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(got, expect, atol=1e-5)


def test_patch_embed_matches_conv():
    B, H, W, C, D, ps = 2, 8, 8, 3, 16, 4
    x = jax.random.normal(jax.random.key(0), (B, H, W, C))
    pe = PatchEmbed(embed_dim=D, patch_size=ps, **F32)
    params = nn.meta.unbox(pe.init(jax.random.key(1), x))
    y = pe.apply(params, x)
    assert y.shape == (B, 4, D)
    kernel = params["params"]["kernel"]
    bias = params["params"]["bias"]
    ref = jax.lax.conv_general_dilated(
        x, kernel, window_strides=(ps, ps), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    ).reshape(B, 4, D) + bias
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-4)


def test_dino_head_bottleneck_unit_norm_and_shapes():
    x = jax.random.normal(jax.random.key(0), (6, 32))
    head = DINOHead(out_dim=64, hidden_dim=48, bottleneck_dim=16, **F32)
    p = head.init(jax.random.key(1), x)
    z = head.apply(p, x, skip_last_layer=True)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(z), axis=-1), 1.0, atol=1e-4)
    logits = head.apply(p, x)
    assert logits.shape == (6, 64)
    # only_last_layer consumes bottleneck input
    out = head.apply(p, z, only_last_layer=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(logits), atol=1e-5)


def test_dino_head_weight_norm():
    x = jax.random.normal(jax.random.key(0), (4, 32))
    head = DINOHead(out_dim=16, hidden_dim=48, bottleneck_dim=8,
                    norm_last_layer=True, **F32)
    p = head.init(jax.random.key(1), x)
    z = head.apply(p, x, skip_last_layer=True)
    logits = head.apply(p, z, only_last_layer=True)
    # |logit_k| <= |z| * |w_k| = 1 (both unit-norm) by Cauchy-Schwarz
    assert np.abs(np.asarray(logits)).max() <= 1.0 + 1e-5


def test_layer_scale_init_value():
    x = jnp.ones((2, 3, 8))
    m = LayerScale(init_value=1e-5)
    p = m.init(jax.random.key(0), x)
    np.testing.assert_allclose(np.asarray(m.apply(p, x)), 1e-5, rtol=1e-6)


# ---------------- block ----------------

@pytest.mark.parametrize("mode", ["mask", "subset"])
def test_block_forward_and_drop_path(mode):
    B, N, D = 4, 6, 32
    x = jax.random.normal(jax.random.key(0), (B, N, D))
    blk = SelfAttentionBlock(dim=D, num_heads=4, drop_path_rate=0.5,
                             drop_path_mode=mode, attn_impl="xla", **F32)
    params = blk.init(jax.random.key(1), x)
    y = blk.apply(params, x)  # deterministic: no drop_path rng needed
    assert y.shape == x.shape
    # train mode: per-sample drop — outputs differ across rng
    y1 = blk.apply(params, x, deterministic=False,
                   rngs={"drop_path": jax.random.key(2)})
    y2 = blk.apply(params, x, deterministic=False,
                   rngs={"drop_path": jax.random.key(3)})
    assert not np.allclose(np.asarray(y1), np.asarray(y2))


def test_subset_residual_reference_semantics():
    """subset drop-path = reference batch subsetting (block.py:94-117):
    exactly floor(B*(1-rate)) rows get the B/keep-scaled residual, the
    rest pass through untouched."""
    from dinov3_tpu.ops.drop_path import subset_keep_count, subset_residual

    B, N, D = 8, 5, 16
    rate = 0.3
    keep = subset_keep_count(B, rate)
    assert keep == 5  # floor(8 * 0.7)
    x = jax.random.normal(jax.random.key(0), (B, N, D))
    y = jax.jit(
        lambda x, r: subset_residual(x, lambda s: jnp.ones_like(s), r, rate)
    )(x, jax.random.key(1))
    delta = np.asarray(y - x)
    changed = np.nonzero(np.abs(delta).sum(axis=(1, 2)) > 1e-6)[0]
    assert len(changed) == keep
    np.testing.assert_allclose(delta[changed], B / keep, rtol=1e-5)
    # the subset is rng-dependent
    y2 = subset_residual(x, lambda s: jnp.ones_like(s), jax.random.key(7), rate)
    assert not np.allclose(np.asarray(y2), np.asarray(y))
    # keep >= B degenerates to a plain residual
    y3 = subset_residual(x, lambda s: jnp.ones_like(s), jax.random.key(1), 0.0)
    np.testing.assert_allclose(np.asarray(y3 - x), 1.0, rtol=1e-6)


def test_subset_residual_grads_skip_dropped_rows():
    """The defining property of subset mode: dropped rows receive NO
    branch gradient (their compute was skipped), kept rows receive the
    scaled branch gradient on top of the residual identity."""
    from dinov3_tpu.ops.drop_path import subset_keep_count, subset_residual

    B, N, D = 8, 3, 4
    rate = 0.3
    keep = subset_keep_count(B, rate)
    x = jax.random.normal(jax.random.key(0), (B, N, D))
    rng = jax.random.key(1)

    g = jax.grad(
        lambda x: jnp.sum(subset_residual(x, lambda s: 2.0 * s, rng, rate))
    )(x)
    # identity path gives 1 everywhere; kept rows add 2 * (B/keep)
    per_row = np.asarray(g)[:, 0, 0]
    kept = np.nonzero(np.abs(per_row - 1.0) > 1e-6)[0]
    assert len(kept) == keep
    np.testing.assert_allclose(per_row[kept], 1.0 + 2.0 * B / keep, rtol=1e-5)
    # B=1 cannot express any subset (keep=max(1,0)=1): plain residual
    y = subset_residual(x[:1], lambda s: jnp.ones_like(s), rng, 0.5)
    np.testing.assert_allclose(np.asarray(y - x[:1]), 1.0, rtol=1e-6)


def test_subset_residual_stratified_groups():
    """groups=G samples floor((B/G)*(1-rate)) rows inside each contiguous
    span — per-shard-balanced, matching torch's per-rank subsetting."""
    from dinov3_tpu.ops.drop_path import subset_keep_count, subset_residual

    B, G, rate = 16, 4, 0.5
    keep_g = subset_keep_count(B // G, rate)
    x = jnp.zeros((B, 2, 2))
    y = subset_residual(x, lambda s: jnp.ones_like(s), jax.random.key(3),
                        rate, groups=G)
    changed = np.nonzero(np.abs(np.asarray(y)).sum(axis=(1, 2)) > 1e-6)[0]
    assert len(changed) == G * keep_g
    spans = changed // (B // G)
    counts = {int(s): int((spans == s).sum()) for s in np.unique(spans)}
    assert counts == {g: keep_g for g in range(G)}, counts
    np.testing.assert_allclose(
        np.asarray(y)[changed], (B // G) / keep_g, rtol=1e-5
    )


def test_subset_drop_path_block_grads_flow():
    """Grads flow through the gather/scatter of the block's subset path."""
    B, N, D = 4, 6, 32
    x = jax.random.normal(jax.random.key(0), (B, N, D))
    blk = SelfAttentionBlock(dim=D, num_heads=4, drop_path_rate=0.5,
                             drop_path_mode="subset", attn_impl="xla", **F32)
    params = blk.init(jax.random.key(1), x)

    def loss(p):
        y = blk.apply(p, x, deterministic=False,
                      rngs={"drop_path": jax.random.key(2)})
        return jnp.sum(y ** 2)

    g = jax.grad(loss)(params)
    gflat = jax.tree.leaves(g)
    assert all(np.all(np.isfinite(np.asarray(t))) for t in gflat)
    assert any(np.abs(np.asarray(t)).sum() > 0 for t in gflat)


def test_subset_drop_path_tiny_batch_falls_back_to_mask():
    """B=1 cannot express a subset at any rate: the block must keep
    stochastic depth alive via the per-sample mask instead of silently
    disabling it (pipeline single-row microbatch case)."""
    N, D = 6, 32
    x = jax.random.normal(jax.random.key(0), (1, N, D))
    blk = SelfAttentionBlock(dim=D, num_heads=4, drop_path_rate=0.5,
                             drop_path_mode="subset", attn_impl="xla", **F32)
    params = blk.init(jax.random.key(1), x)
    ys = [
        np.asarray(blk.apply(params, x, deterministic=False,
                             rngs={"drop_path": jax.random.key(k)}))
        for k in range(8)
    ]
    # with mask-mode fallback some draws drop the residual entirely:
    # outputs must differ across rngs (subset mode would be constant)
    assert any(not np.allclose(ys[0], y) for y in ys[1:])


def test_subset_drop_path_indivisible_batch_falls_back_to_mask():
    """Under a >1-shard data axis with B % shards != 0, an ungrouped
    subset gather would cross shard spans (GSPMD partition failure or
    heavy resharding — ADVICE r3): the block must fall back to mask
    semantics and say so once."""
    import warnings as _warnings

    from dinov3_tpu.ops import block as block_mod
    from dinov3_tpu.parallel.context import get_current_mesh, set_current_mesh
    from dinov3_tpu.parallel.mesh import MeshSpec, build_mesh

    N, D = 6, 32
    x = jax.random.normal(jax.random.key(0), (3, N, D))  # 3 % 2 != 0
    blk = SelfAttentionBlock(dim=D, num_heads=4, drop_path_rate=0.3,
                             drop_path_mode="subset", attn_impl="xla", **F32)
    params = blk.init(jax.random.key(1), x)
    prev = get_current_mesh()
    mesh = build_mesh(MeshSpec(data=2), devices=jax.devices()[:2])
    block_mod._SUBSET_FALLBACK_WARNED.clear()
    set_current_mesh(mesh)
    try:
        with _warnings.catch_warnings(record=True) as caught:
            _warnings.simplefilter("always")
            y = blk.apply(params, x, deterministic=False,
                          rngs={"drop_path": jax.random.key(2)})
        assert y.shape == x.shape
        msgs = [str(w.message) for w in caught]
        assert any("not divisible by data-shard count 2" in m for m in msgs)
        # divisible B on the same mesh: subset must NOT degrade
        x4 = jax.random.normal(jax.random.key(3), (4, N, D))
        with _warnings.catch_warnings(record=True) as caught2:
            _warnings.simplefilter("always")
            blk.apply(params, x4, deterministic=False,
                      rngs={"drop_path": jax.random.key(4)})
        assert not any("not divisible" in str(w.message) for w in caught2)
    finally:
        set_current_mesh(prev)


def test_block_swiglu_rmsnorm_variant():
    B, N, D = 2, 6, 32
    x = jax.random.normal(jax.random.key(0), (B, N, D))
    blk = SelfAttentionBlock(dim=D, num_heads=4, ffn_layer="swiglu",
                             norm_layer="rmsnorm", attn_impl="xla", **F32)
    params = blk.init(jax.random.key(1), x)
    assert blk.apply(params, x).shape == x.shape


def test_block_grads_flow():
    B, N, D = 2, 6, 32
    x = jax.random.normal(jax.random.key(0), (B, N, D))
    blk = SelfAttentionBlock(dim=D, num_heads=4, attn_impl="xla", **F32)
    params = blk.init(jax.random.key(1), x)

    def loss(p):
        return jnp.sum(blk.apply(p, x) ** 2)

    g = jax.grad(loss)(params)
    leaves = jax.tree.leaves(g)
    assert all(np.isfinite(np.asarray(l)).all() for l in leaves)
    assert any(np.abs(np.asarray(l)).max() > 0 for l in leaves)


# ---------------- fp8 matmul path ----------------

def test_fp8_dot_close_to_dense():
    """Current-scaling fp8 matmul approximates the bf16/fp32 product within
    e4m3 quantization error, and its gradients are finite."""
    from dinov3_tpu.ops.common import fp8_matmul

    k = jax.random.key(0)
    x = jax.random.normal(k, (32, 64), jnp.float32)
    w = jax.random.normal(jax.random.fold_in(k, 1), (64, 48), jnp.float32) * 0.05
    ref = x @ w
    out = fp8_matmul(x, w)
    err = float(jnp.abs(out - ref).max() / (jnp.abs(ref).max() + 1e-9))
    assert err < 0.08, err  # e4m3 has ~2 decimal digits

    g = jax.grad(lambda w: jnp.sum(fp8_matmul(x, w) ** 2))(w)
    assert bool(jnp.isfinite(g).all())


def test_fp8_block_forward_and_grads():
    """A transformer block with fp8 projections stays close to the exact
    block and yields finite grads (reference config surface:
    student.fp8_enabled, ssl_default_config.yaml:121-122)."""
    from dinov3_tpu.ops.block import SelfAttentionBlock

    kw = dict(dim=64, num_heads=2, ffn_ratio=2.0, drop_path_rate=0.0,
              layerscale_init=1e-5, dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (2, 10, 64), jnp.float32)
    exact = SelfAttentionBlock(**kw)
    quant = SelfAttentionBlock(fp8=True, **kw)
    params = exact.init(jax.random.key(1), x)
    y_exact = exact.apply(params, x)
    y_quant = quant.apply(params, x)  # same param structure
    rel = float(jnp.abs(y_quant - y_exact).max() /
                (jnp.abs(y_exact).max() + 1e-9))
    assert rel < 0.05, rel

    g = jax.grad(
        lambda p: jnp.sum(quant.apply(p, x) ** 2)
    )(params)
    assert all(bool(jnp.isfinite(l).all()) for l in jax.tree.leaves(g))


def test_fp8_flag_threads_from_config():
    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.models import backbone_kwargs_from_cfg

    cfg = get_default_config()
    apply_dot_overrides(cfg, ["student.arch=vit_test",
                              "student.fp8_enabled=true"])
    kw = backbone_kwargs_from_cfg(cfg)
    assert kw.get("fp8") is True
    apply_dot_overrides(cfg, ["student.fp8_filter=nothing_matches"])
    kw = backbone_kwargs_from_cfg(cfg)
    assert not kw.get("fp8")


def test_remat_attn_config_path():
    """parallel.remat=attn must thread through backbone_kwargs_from_cfg
    (regression: the seq-parallel warning read kw['seq_parallel'] before
    assignment -> KeyError)."""
    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.models import backbone_kwargs_from_cfg

    cfg = get_default_config()
    apply_dot_overrides(cfg, ["student.arch=vit_test", "parallel.remat=attn"])
    kw = backbone_kwargs_from_cfg(cfg)
    assert kw["remat"] == "attn"
    # and with seq parallelism on (the warning path itself)
    apply_dot_overrides(cfg, ["parallel.seq=2"])
    kw = backbone_kwargs_from_cfg(cfg)
    assert kw["remat"] == "attn" and kw["seq_parallel"]


def test_remat_attn_matches_none():
    """remat='attn' (recompute softmax state in backward) must be exact —
    same outputs and same grads as no remat."""
    from dinov3_tpu.ops.block import SelfAttentionBlock, remat_block_cls

    kw = dict(dim=32, num_heads=2, ffn_ratio=2.0, drop_path_rate=0.0,
              layerscale_init=1e-5, dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (2, 9, 32), jnp.float32)
    base = SelfAttentionBlock(**kw)
    params = base.init(jax.random.key(1), x)

    def loss(cls_fn, p):
        return jnp.sum(cls_fn(**kw).apply(p, x, None, True) ** 2)

    l0, g0 = jax.value_and_grad(lambda p: loss(SelfAttentionBlock, p))(params)
    l1, g1 = jax.value_and_grad(
        lambda p: loss(remat_block_cls("attn"), p)
    )(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-6)


# ---------------- causal attention ----------------

def test_causal_attention_masks_future():
    """Causal xla_attention: position i must be independent of keys > i,
    matching a manual masked-softmax reference."""
    from dinov3_tpu.ops.attention import xla_attention

    k = jax.random.key(0)
    B, N, h, d = 2, 7, 2, 8
    q, kk, v = (jax.random.normal(jax.random.fold_in(k, i), (B, N, h, d))
                for i in range(3))
    out = xla_attention(q, kk, v, causal=True)

    logits = np.einsum("bqhd,bkhd->bhqk", np.asarray(q), np.asarray(kk))
    logits = logits / np.sqrt(d)
    mask = np.tril(np.ones((N, N), bool))
    logits = np.where(mask, logits, -np.inf)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bkhd->bqhd", p, np.asarray(v))
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)

    # perturbing a future key must not change earlier outputs
    kk2 = np.asarray(kk).copy()
    kk2[:, -1] += 10.0
    out2 = xla_attention(q, jnp.asarray(kk2), v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out[:, :-1]), np.asarray(out2[:, :-1]), atol=1e-5
    )


def test_causal_block_runs():
    from dinov3_tpu.ops.block import CausalSelfAttentionBlock

    blk = CausalSelfAttentionBlock(dim=32, num_heads=2, drop_path_rate=0.0,
                                   layerscale_init=1e-5, dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (2, 5, 32))
    params = blk.init(jax.random.key(1), x)
    y = blk.apply(params, x)
    assert y.shape == x.shape and bool(jnp.isfinite(y).all())


def test_xla_attention_bf16_probs_parity():
    """compute_precision.probs_dtype=bf16: same attention within bf16
    tolerance, fwd and grads (fp32 statistics both ways)."""
    from dinov3_tpu.ops.attention import xla_attention

    k1, k2, k3, k4 = jax.random.split(jax.random.key(7), 4)
    q = jax.random.normal(k1, (2, 33, 4, 16), jnp.float32)
    k = jax.random.normal(k2, (2, 33, 4, 16), jnp.float32)
    v = jax.random.normal(k3, (2, 33, 4, 16), jnp.float32)
    ct = jax.random.normal(k4, (2, 33, 4, 16), jnp.float32)

    def loss(probs_dtype):
        return lambda q, k, v: jnp.sum(
            xla_attention(q, k, v, probs_dtype=probs_dtype) * ct)

    o32 = xla_attention(q, k, v)
    o16 = xla_attention(q, k, v, probs_dtype=jnp.bfloat16)
    assert jnp.abs(o16 - o32).max() < 2e-2
    g32 = jax.grad(loss(None), argnums=(0, 1, 2))(q, k, v)
    g16 = jax.grad(loss(jnp.bfloat16), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g16, g32):
        assert jnp.abs(a - b).max() < 3e-2


def test_probs_dtype_threads_from_config():
    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.models import backbone_kwargs_from_cfg

    cfg = get_default_config()
    apply_dot_overrides(cfg, ["student.arch=vit_test"])
    kw = backbone_kwargs_from_cfg(cfg)
    assert kw["probs_dtype"] == jnp.bfloat16
    apply_dot_overrides(cfg, ["compute_precision.probs_dtype=fp32"])
    kw = backbone_kwargs_from_cfg(cfg)
    assert kw["probs_dtype"] == jnp.float32
