"""ADE20K and COCO-Captions dataset I/O on tiny on-disk fixtures.

The reference stubbed both to random arrays (SURVEY.md §2.6:
data/datasets/ade20k.py:56-60); these tests pin the real file layouts.
"""

import json
import os

import numpy as np
from PIL import Image

from dinov3_tpu.data.datasets.ade20k import ADE20K
from dinov3_tpu.data.datasets.coco_captions import CocoCaptions


def _write_img(path, size=(16, 12), value=128):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    Image.new("RGB", size, (value, value // 2, 20)).save(path)


def test_ade20k_reads_images_and_segmaps(tmp_path):
    root = str(tmp_path)
    for i in range(3):
        _write_img(f"{root}/images/validation/img_{i}.jpg", value=50 + i)
        seg = Image.fromarray(
            np.full((12, 16), i, np.uint8), mode="L"
        )
        os.makedirs(f"{root}/annotations/validation", exist_ok=True)
        seg.save(f"{root}/annotations/validation/img_{i}.png")

    ds = ADE20K(root=root, split="VAL")
    assert len(ds) == 3
    image, seg = ds[1]
    assert image.size == (16, 12)
    assert seg.shape == (12, 16) and int(seg.max()) == 1

    # missing annotation -> image still served, target None
    _write_img(f"{root}/images/validation/img_9.jpg")
    ds = ADE20K(root=root, split="VAL")
    image, seg = ds[len(ds) - 1]
    assert seg is None


def test_ade20k_missing_root_raises(tmp_path):
    import pytest

    with pytest.raises(FileNotFoundError):
        ADE20K(root=str(tmp_path / "nope"), split="VAL")


def test_coco_captions_groups_by_image(tmp_path):
    root = str(tmp_path)
    for i in range(2):
        _write_img(f"{root}/img_{i}.jpg")
    meta = {
        "images": [
            {"id": 7, "file_name": "img_0.jpg"},
            {"id": 3, "file_name": "img_1.jpg"},
        ],
        "annotations": [
            {"image_id": 7, "caption": "a red square"},
            {"image_id": 7, "caption": "still a red square"},
            {"image_id": 3, "caption": "another image"},
        ],
    }
    ann = str(tmp_path / "captions.json")
    with open(ann, "w") as f:
        json.dump(meta, f)

    ds = CocoCaptions(root=root, annotations=ann)
    assert len(ds) == 2
    # ids sorted: index 0 -> id 3, index 1 -> id 7
    img, caps = ds[0]
    assert caps == ["another image"]
    img, caps = ds[1]
    assert sorted(caps) == ["a red square", "still a red square"]
    assert img.size == (16, 12)
