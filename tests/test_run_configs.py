"""Every shipped run recipe loads, schedules build, and the meta-arch
initializes abstractly (zero FLOPs) with the recipe's model settings."""

import glob
import os

import jax
import jax.numpy as jnp
import pytest

from dinov3_tpu.configs import apply_dot_overrides, load_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RECIPES = sorted(glob.glob(os.path.join(REPO, "configs/train/*.yaml")))


def test_recipes_exist():
    names = {os.path.basename(p) for p in RECIPES}
    assert {
        "vitl16_im1k.yaml", "vitl16_im1k_smol.yaml", "vit7b16_pretrain.yaml",
        "vit7b16_gram_anchor.yaml", "vit7b16_high_res_adapt.yaml",
        "vitl16_distilled.yaml",
    } <= names


@pytest.mark.parametrize(
    "path", RECIPES, ids=[os.path.basename(p) for p in RECIPES]
)
def test_recipe_abstract_build(path):
    cfg = load_config(path)
    if cfg.distillation.enabled:
        pytest.skip("needs a teacher checkpoint; covered in test_distillation")
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train.schedules import build_schedules
    from dinov3_tpu.train.ssl_meta_arch import SSLMetaArch

    # shrink the compute-heavy dials but KEEP the recipe's structure
    # (arch, ffn kind, norms, rope flags, gram, schedules)
    small_arch = {
        "vit_7b": "vit_test", "vit_giant2": "vit_test",
        "vit_large": "vit_test", "vit_base": "vit_test",
        "vit_small": "vit_test",
    }.get(cfg.student.arch)
    overrides = [
        "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
        "dino.head_bottleneck_dim=16",
        "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
        "ibot.head_bottleneck_dim=16",
        "train.OFFICIAL_EPOCH_LENGTH=2",
    ]
    if small_arch:
        overrides.append(f"student.arch={small_arch}")
    apply_dot_overrides(cfg, overrides)
    if isinstance(cfg.crops.global_crops_size, list):
        cfg.crops.global_crops_size = 32
        cfg.crops.local_crops_size = 16
        cfg.crops.gram_teacher_crops_size = 48
    else:
        cfg.crops.global_crops_size = 32
        cfg.crops.local_crops_size = 16
        if cfg.crops.get("gram_teacher_crops_size"):
            cfg.crops.gram_teacher_crops_size = 48
    cfg.student.patch_size = 4

    schedules = build_schedules(cfg)
    assert schedules.at(0)["lr"] >= 0.0

    meta = SSLMetaArch(cfg)
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, 2, seed=0).items()}
    abstract = jax.eval_shape(lambda r: meta.init_params(r, batch),
                              jax.random.key(0))
    assert "student" in abstract and "teacher" in abstract
    if cfg.gram.use_loss and not cfg.gram.ema_teacher:
        assert "gram" in abstract


def test_multires_recipe_combines_loaders():
    cfg = load_config(
        os.path.join(REPO, "configs/train/vit7b16_high_res_adapt.yaml"))
    assert isinstance(cfg.crops.global_crops_size, list)
    assert len(cfg.crops.global_crops_size) == 5
    apply_dot_overrides(cfg, [
        "student.arch=vit_test", "student.patch_size=4",
        "train.dataset_path=Synthetic:size=32:image_size=48",
        "train.num_workers=2", "data.backend=folder",
    ])
    cfg.crops.global_crops_size = [16, 24]
    cfg.crops.local_crops_size = [8, 8]
    cfg.crops.gram_teacher_crops_size = [24, 32]
    cfg.crops.global_local_crop_pairs_ratios = [0.5, 0.5]
    from dinov3_tpu.data.pipeline import make_multires_train_pipeline

    it = make_multires_train_pipeline(cfg, global_batch_size=2)
    seen = set()
    for _ in range(6):
        b = next(it)
        seen.add(b["global_crops"].shape[1])
        assert b["gram_teacher_crops"].shape[1] in (24, 32)
    assert seen <= {16, 24} and len(seen) == 2
