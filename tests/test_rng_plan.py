"""Step-wide RNG-plan engine (rng/plan.py) vs the legacy fold_in
oracle.

Pinned here:
- plan structure, determinism, and the subset-index invariants (sorted,
  unique, exact per-group keep counts, span-local under grouping);
- draw-for-draw DISTRIBUTIONAL equivalence against the legacy oracle's
  draws (subset inclusion frequency, mask keep rate, RoPE jitter
  log-uniform moments) — the plan derives from different key paths so
  realizations differ, distributions must not;
- bit-identical consumption: ``subset_residual_planned`` fed the same
  kept-index vector the in-place sampler derives == ``subset_residual``;
- the full meta-arch forward under the plan: deterministic, finite,
  iteration-dependent, all loss keys; the legacy path (rng.plan=false)
  intact; scan-over-blocks and 8-device sharded step paths compile;
- same-seed determinism + deterministic RESUME under BOTH rng paths:
  draws at iteration k are a pure function of (seed, k) — never of the
  execution history — and the host-side mask stream realigns with the
  sampler (data/pipeline.py ``_SeededCollate`` start_ordinal);
- the copy-census acceptance claim: the plan removes >= 60% of the
  compiled train step's copy-class HLO ops vs the legacy program.
"""

import importlib.util
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.ops.drop_path import (
    subset_keep_count,
    subset_residual,
    subset_residual_planned,
)
from dinov3_tpu.rng.plan import (
    PassPlanSpec,
    build_pass_plan,
    build_step_plan,
    mask_plan,
    subset_plan,
)

_CTP_PATH = os.path.join(os.path.dirname(__file__), "..", "scripts",
                         "cost_target_phase.py")

SMOL = [
    "student.arch=vit_test", "student.patch_size=4",
    "student.drop_path_rate=0.3", "student.layerscale=1.0e-5",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=24",
    "dino.head_bottleneck_dim=8",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=24",
    "ibot.head_bottleneck_dim=8",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "optim.warmup_epochs=1", "optim.freeze_last_layer_epochs=1",
    "compute_precision.compute_dtype=fp32",
    "optim.scaling_rule=none",
]


def smol_cfg(extra=()):
    cfg = get_default_config()
    apply_dot_overrides(cfg, list(SMOL) + list(extra))
    return cfg


def make_meta(extra=()):
    from dinov3_tpu.train.ssl_meta_arch import SSLMetaArch

    return SSLMetaArch(smol_cfg(extra))


# ---------------- plan construction invariants ----------------


def test_subset_plan_invariants():
    L, B, rate, G = 3, 16, 0.3, 2
    Bg = B // G
    keep_g = subset_keep_count(Bg, rate)
    idx = np.asarray(subset_plan(jax.random.key(0), L, B, rate, G))
    assert idx.shape == (L, 2, G * keep_g)
    assert idx.dtype == np.int32
    for l in range(L):
        for br in range(2):
            v = idx[l, br]
            # globally sorted + unique (the gather/scatter contract)
            assert (np.diff(v) > 0).all()
            # span-local: group g's entries live in [g*Bg, (g+1)*Bg)
            for g in range(G):
                span = v[g * keep_g:(g + 1) * keep_g]
                assert (span >= g * Bg).all() and (span < (g + 1) * Bg).all()
    # layers/branches draw differently (stacked, not broadcast)
    assert not np.array_equal(idx[0, 0], idx[0, 1])
    assert not np.array_equal(idx[0], idx[1])


def test_plan_determinism_and_key_sensitivity():
    spec = PassPlanSpec(batch=8, n_blocks=2, drop_path_rate=0.25,
                        rope_jitter=1.1)
    p1 = build_pass_plan(jax.random.key(3), spec)
    p2 = build_pass_plan(jax.random.key(3), spec)
    p3 = build_pass_plan(jax.random.key(4), spec)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), p1, p2)
    assert not np.array_equal(np.asarray(p1["drop_path"]["idx"]),
                              np.asarray(p3["drop_path"]["idx"]))
    assert set(p1) == {"drop_path", "rope"}
    assert set(p1["rope"]) == {"jitter"}


def test_mask_plan_mode_and_dropout_lane():
    # mask mode: bernoulli bits of the right shape
    spec = PassPlanSpec(batch=6, n_blocks=2, drop_path_rate=0.5,
                        drop_path_mode="mask")
    p = build_pass_plan(jax.random.key(0), spec)
    assert p["drop_path"]["keep"].shape == (2, 2, 6)
    assert p["drop_path"]["keep"].dtype == jnp.bool_
    # the dropout lane exists only when a nonzero rate is configured
    # (today's step program has no dropout consumer — rng/plan.py doc)
    spec_d = PassPlanSpec(batch=6, n_blocks=3, dropout_rate=0.1)
    p_d = build_pass_plan(jax.random.key(0), spec_d)
    assert p_d["dropout_keys"].shape == (3, 2)
    assert "dropout_keys" not in p


def test_step_plan_passes_and_purity():
    specs = {
        "global": PassPlanSpec(batch=8, n_blocks=2, drop_path_rate=0.3),
        "local": PassPlanSpec(batch=12, n_blocks=2, drop_path_rate=0.3),
    }
    plan = build_step_plan(jax.random.key(11), specs)
    assert set(plan) == {"global", "local"}
    # pass lanes draw independently
    assert plan["global"]["drop_path"]["idx"].shape[-1] != \
        plan["local"]["drop_path"]["idx"].shape[-1] or not np.array_equal(
            np.asarray(plan["global"]["drop_path"]["idx"]),
            np.asarray(plan["local"]["drop_path"]["idx"]))
    # purity: the same step key rebuilds the same plan after unrelated
    # draws (what checkpoint resume relies on)
    _ = build_step_plan(jax.random.key(5), specs)
    again = build_step_plan(jax.random.key(11), specs)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), plan, again)


# ---------------- distributional equivalence vs the legacy oracle ----


def test_subset_inclusion_frequency_matches_legacy():
    """Per-row inclusion frequency of the plan's kept indices == the
    legacy permutation draw's, both == keep/B (draw-for-draw
    distributional equivalence; realizations differ by construction)."""
    B, rate, trials = 8, 0.3, 400
    keep = subset_keep_count(B, rate)
    keys = jax.random.split(jax.random.key(0), trials)
    plan_idx = jax.vmap(lambda k: subset_plan(k, 1, B, rate, 1))(keys)
    plan_freq = np.zeros(B)
    for v in np.asarray(plan_idx).reshape(-1, keep):
        plan_freq[v] += 1
    plan_freq /= trials * 2  # 2 branches per layer
    legacy_idx = jax.vmap(
        lambda k: jnp.sort(jax.random.permutation(k, B)[:keep]))(
        jax.random.split(jax.random.key(1), trials))
    legacy_freq = np.bincount(
        np.asarray(legacy_idx).ravel(), minlength=B) / trials
    expected = keep / B
    np.testing.assert_allclose(plan_freq, expected, atol=0.09)
    np.testing.assert_allclose(legacy_freq, expected, atol=0.09)
    np.testing.assert_allclose(plan_freq, legacy_freq, atol=0.12)


def test_mask_keep_rate_matches_legacy():
    rate, trials, B = 0.4, 300, 10
    keys = jax.random.split(jax.random.key(2), trials)
    bits = jax.vmap(lambda k: mask_plan(k, 2, B, rate))(keys)
    freq = float(np.asarray(bits).mean())
    legacy = jax.vmap(
        lambda k: jax.random.bernoulli(k, 1 - rate, (2, 2, B)))(keys)
    legacy_freq = float(np.asarray(legacy).mean())
    assert abs(freq - (1 - rate)) < 0.03
    assert abs(freq - legacy_freq) < 0.04


def test_rope_aug_distribution_matches_legacy():
    from dinov3_tpu.ops.rope import augment_coords, rope_aug_values

    shift, jitter, rescale = 0.5, 1.4, 1.25
    trials = 600
    keys = jax.random.split(jax.random.key(7), trials)
    vals = jax.vmap(lambda k: rope_aug_values(
        jax.random.uniform(k, (5,)), shift, jitter, rescale))(keys)
    s = np.asarray(vals["shift"])          # U[-shift, shift]
    j = np.log(np.asarray(vals["jitter"]))   # U[-log j, log j]
    r = np.log(np.asarray(vals["rescale"]))  # U[-log r, log r]
    assert np.abs(s).max() <= shift and np.abs(s.mean()) < 0.06
    assert np.abs(j).max() <= np.log(jitter) + 1e-6
    assert np.abs(r).max() <= np.log(rescale) + 1e-6
    # legacy oracle: coords (1, 1) through augment_coords isolates the
    # product jitter*rescale; compare log-moments
    coords = jnp.ones((1, 2))
    legacy = jax.vmap(lambda k: augment_coords(
        coords, k, None, jitter, rescale))(keys)
    lg = np.log(np.asarray(legacy)).ravel()
    pl = (j + r).ravel()
    assert abs(lg.mean() - pl.mean()) < 0.03
    assert abs(lg.std() - pl.std()) < 0.03


# ---------------- consumption equivalence ----------------


def test_subset_residual_planned_matches_inplace_sampling():
    """Same kept rows -> bit-identical output: the planned consumer is
    the in-place sampler minus the draw."""
    B, D, rate = 8, 5, 0.4
    keep = subset_keep_count(B, rate)
    x = jax.random.normal(jax.random.key(0), (B, D))
    branch = lambda t: t * 2.0 + 1.0  # noqa: E731
    rng = jax.random.key(9)
    legacy = subset_residual(x, branch, rng, rate)
    # the in-place sampler's own index derivation (groups=1)
    idx = jnp.sort(jax.random.permutation(rng, B)[:keep])
    planned = subset_residual_planned(x, branch, idx)
    np.testing.assert_array_equal(np.asarray(legacy), np.asarray(planned))


def test_mask_residual_planned_matches_drop_path_expr():
    from dinov3_tpu.ops.drop_path import mask_residual_planned

    B, D, rate = 6, 4, 0.5
    x = jax.random.normal(jax.random.key(0), (B, D))
    y = jax.random.normal(jax.random.key(1), (B, D))
    bits = jax.random.bernoulli(jax.random.key(2), 1 - rate, (B,))
    out = mask_residual_planned(x, y, bits, rate)
    expect = x + jnp.where(bits[:, None], y / (1 - rate), 0.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=1e-6)


# ---------------- meta-arch integration ----------------


def _forward(meta, params, batch, it, rng, state=None):
    kw = {}
    if meta.rng_plan:
        kw["rng_plan"] = meta.build_rng_plan(
            jax.random.fold_in(rng, it), batch)
    else:
        r = jax.random.fold_in(rng, it)
        kw["rngs"] = {"drop_path": jax.random.fold_in(r, 0),
                      "rope": jax.random.fold_in(r, 1),
                      "dropout": jax.random.fold_in(r, 2)}
    return meta.forward(
        params["student"], {"teacher": params["teacher"]}, batch,
        teacher_temp=0.07, state=state or meta.init_state(), iteration=it,
        **kw)


@pytest.mark.parametrize("extra,expected", [
    ((), True),
    (("rng.plan=false",), False),
    (("train.scan_layers=true",), True),
    (("parallel.pipe=2",), False),       # pipeline falls back loudly
    (("student.pos_embed_rope_jitter_coords=1.05",), True),
])
def test_forward_runs_under_plan_variants(extra, expected):
    from dinov3_tpu.data import make_synthetic_batch

    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        meta = make_meta(extra)
    assert meta.rng_plan is expected
    cfg = meta.cfg
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, 4, seed=0).items()}
    params = meta.init_params(jax.random.key(0), batch)
    rng = jax.random.key(5)
    t1, (d1, _) = _forward(meta, params, batch, 0, rng)
    t2, _ = _forward(meta, params, batch, 0, rng)
    t3, _ = _forward(meta, params, batch, 1, rng)
    assert np.isfinite(float(t1))
    assert float(t1) == float(t2)            # same-seed determinism
    assert float(t1) != float(t3)            # draws move with iteration
    for k in ("dino_global_crops_loss", "dino_local_crops_loss",
              "ibot_loss", "koleo_loss", "total_loss"):
        assert k in d1


def test_bad_rng_plan_value_raises():
    with pytest.raises(ValueError, match="rng.plan"):
        make_meta(("rng.plan=sometimes",))


def test_sharded_step_under_plan(eight_devices):
    """The plan-on step compiles and runs on an 8-device data-parallel
    mesh: the stacked plan arrays are born sharded (constrain_batch_dim)
    and the grouped subset indices stay span-local per shard."""
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup, put_batch

    cfg = smol_cfg(["parallel.data=-1"])
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, 8, seed=0).items()}
    setup = build_train_setup(cfg, batch, devices=eight_devices)
    assert setup.meta.rng_plan
    d = put_batch(batch, setup.batch_shardings)
    state, m = setup.step_fn(setup.state, d, setup.scalars(0),
                             jax.random.key(0))
    assert np.isfinite(float(m["total_loss"]))


# ---------------- deterministic resume (both rng paths) ----------------


@pytest.mark.parametrize("flag", ["true", "false"])
def test_step_draws_resume_from_iteration_counter(flag):
    """Draws at iteration k are a pure function of (seed, k): stepping a
    captured state again reproduces the uninterrupted run's metrics
    bit-for-bit, and the plan built at k after unrelated work matches —
    the property checkpoint resume relies on, under BOTH rng paths."""
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup, put_batch

    cfg = smol_cfg([f"rng.plan={flag}"])
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, 4, seed=0).items()}
    setup = build_train_setup(cfg, batch)
    d = put_batch(batch, setup.batch_shardings)
    rng = jax.random.key(cfg.train.seed + 1)

    def snapshot(s):
        return jax.tree.map(jnp.copy, s)

    state = setup.state
    metrics = []
    saved = None
    for it in range(3):
        if it == 2:
            saved = snapshot(state)         # "checkpoint" before step 2
        state, m = setup.step_fn(snapshot(state), d, setup.scalars(it), rng)
        metrics.append({k: float(v) for k, v in m.items()})
    # "restart": a fresh step call from the saved state must reproduce
    # iteration 2 exactly (same draws, same metrics)
    _, m_resumed = setup.step_fn(saved, d, setup.scalars(2), rng)
    for k, v in metrics[2].items():
        assert float(m_resumed[k]) == v, (k, flag)


def test_plan_independent_of_history():
    from dinov3_tpu.data import make_synthetic_batch

    meta = make_meta()
    cfg = meta.cfg
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, 4, seed=0).items()}
    rng = jax.random.key(1)
    direct = meta.build_rng_plan(jax.random.fold_in(rng, 5), batch)
    for it in (0, 1, 2):                      # unrelated earlier draws
        meta.build_rng_plan(jax.random.fold_in(rng, it), batch)
    replay = meta.build_rng_plan(jax.random.fold_in(rng, 5), batch)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), direct, replay)


def test_collate_mask_stream_resumes_with_sampler():
    """Host-side counterpart: restarting the pipeline collate at batch
    ordinal k draws the SAME iBOT masks the uninterrupted stream drew
    for batch k (data/pipeline.py _SeededCollate start_ordinal)."""
    from dinov3_tpu.data.pipeline import _SeededCollate

    cfg = smol_cfg()
    rng_img = np.random.default_rng(0)

    def samples():
        # collate consumes (sample, target) pairs of augmentation output
        s = {
            "global_crops": [rng_img.standard_normal((16, 16, 3)).astype(
                np.float32) for _ in range(2)],
            "local_crops": [rng_img.standard_normal((8, 8, 3)).astype(
                np.float32) for _ in range(2)],
        }
        return [(s, None), (s, None)]

    batches = [samples() for _ in range(4)]
    full = _SeededCollate(cfg, seed=123)
    uninterrupted = [full(b) for b in batches]
    resumed = _SeededCollate(cfg, seed=123, start_ordinal=2)
    replay = resumed(batches[2])
    for k in ("masks", "mask_indices", "mask_weights", "mask_valid"):
        np.testing.assert_array_equal(uninterrupted[2][k], replay[k])
    # and the masks do differ across ordinals (the stream moves)
    assert not np.array_equal(uninterrupted[1]["masks"],
                              uninterrupted[2]["masks"])


# ---------------- the copy-census acceptance claim ----------------


def test_plan_removes_rng_copy_sink():
    """rng.plan=true removes >= 60% of the compiled train step's
    copy-class HLO ops vs the legacy program (acceptance criterion; the
    committed before/after is COST_RNG_r08.json: 518 -> 144, -72.2%),
    with zero donation warnings on both arms and the removed ops
    attributed to the 'rng' category."""
    spec = importlib.util.spec_from_file_location(
        "cost_target_phase", _CTP_PATH)
    ctp = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ctp)
    # pinned on the committed two-pass program (model.crop_packing=false):
    # the claim and its COST_RNG_r08.json artifact predate the crop-packed
    # engine, which independently removes the two-pass crop-boundary
    # copies from BOTH arms (518 -> 190 legacy / 144 -> 96 plan) and
    # would blur what this test isolates
    pin = ["model.crop_packing=false"]
    on = ctp.copy_census(smol_cfg(pin), B=4)
    off = ctp.copy_census(smol_cfg(pin + ["rng.plan=false"]), B=4)
    assert on["donation_warnings"] == [] and off["donation_warnings"] == []
    assert on["hlo_copy_total"] <= 0.4 * off["hlo_copy_total"], (on, off)
    removed_rng = (off["by_category"].get("rng", {}).get("ops", 0)
                   - on["by_category"].get("rng", {}).get("ops", 0))
    removed_total = off["hlo_copy_total"] - on["hlo_copy_total"]
    assert removed_rng >= 0.8 * removed_total, (on, off)
