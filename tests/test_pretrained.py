"""Warm-start from pretrained checkpoints (student.pretrained_weights /
student.resume_from_teacher_chkpt — keys the reference declared but never
wired)."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from dinov3_tpu.checkpoint import Checkpointer
from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.data import make_synthetic_batch
from dinov3_tpu.train import build_train_setup, put_batch
from dinov3_tpu.train.pretrained import load_pretrained_weights

SMOL = [
    "student.arch=vit_test", "student.patch_size=4",
    "student.drop_path_rate=0.0",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
    "dino.head_bottleneck_dim=16",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
    "ibot.head_bottleneck_dim=16",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "optim.scaling_rule=none",
]


def _pretrain_and_save(tmp_path, steps=2):
    cfg = get_default_config()
    apply_dot_overrides(cfg, SMOL)
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, 4, seed=0).items()}
    setup = build_train_setup(cfg, batch)
    dbatch = put_batch(batch, setup.batch_shardings)
    state = setup.state
    for _ in range(steps):
        state, _ = setup.step_fn(state, dbatch, setup.scalars(0),
                                 jax.random.key(0))
    ckpt = Checkpointer(str(tmp_path / "ckpt"), max_to_keep=1)
    ckpt.save(int(state.step), state)
    ckpt.wait_until_finished()
    ckpt.close()
    return cfg, state


def test_pretrained_weights_warm_start(tmp_path):
    cfg, trained = _pretrain_and_save(tmp_path)
    cfg2 = get_default_config()
    apply_dot_overrides(cfg2, SMOL + [
        f"student.pretrained_weights={tmp_path / 'ckpt'}",
    ])
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg2, 4, seed=1).items()}
    setup = build_train_setup(cfg2, batch)
    state = load_pretrained_weights(cfg2, setup.state, setup.state_shardings)

    want = np.asarray(jax.tree.leaves(trained.params["student"])[0])
    got = np.asarray(jax.tree.leaves(state.params["student"])[0])
    np.testing.assert_allclose(got, want)
    # fresh optimizer/step: warm start, not resume
    assert int(state.step) == 0
    # teacher mirrors the warm-started student
    t = np.asarray(jax.tree.leaves(state.params["teacher"]["backbone"])[0])
    s = np.asarray(jax.tree.leaves(state.params["student"]["backbone"])[0])
    np.testing.assert_allclose(t, s)


@pytest.mark.slow
def test_resume_from_teacher_chkpt_loads_ema_branch(tmp_path):
    cfg, trained = _pretrain_and_save(tmp_path)
    cfg2 = get_default_config()
    apply_dot_overrides(cfg2, SMOL + [
        f"student.resume_from_teacher_chkpt={tmp_path / 'ckpt'}",
    ])
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg2, 4, seed=1).items()}
    setup = build_train_setup(cfg2, batch)
    state = load_pretrained_weights(cfg2, setup.state, setup.state_shardings)

    want = np.asarray(
        jax.tree.leaves(trained.params["teacher"]["backbone"])[0])
    got = np.asarray(jax.tree.leaves(state.params["student"]["backbone"])[0])
    np.testing.assert_allclose(got, want)


def test_no_keys_is_identity(tmp_path):
    cfg = get_default_config()
    apply_dot_overrides(cfg, SMOL)
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, 4, seed=0).items()}
    setup = build_train_setup(cfg, batch)
    assert load_pretrained_weights(
        cfg, setup.state, setup.state_shardings) is setup.state


@pytest.mark.slow
def test_partial_warm_start_with_mismatched_heads(tmp_path):
    cfg, trained = _pretrain_and_save(tmp_path)
    cfg2 = get_default_config()
    apply_dot_overrides(cfg2, SMOL + [
        "dino.head_n_prototypes=128",  # differs from the checkpoint's 64
        "ibot.head_n_prototypes=128",
        f"student.pretrained_weights={tmp_path / 'ckpt'}",
    ])
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg2, 4, seed=1).items()}
    setup = build_train_setup(cfg2, batch)
    state = load_pretrained_weights(cfg2, setup.state, setup.state_shardings)

    # backbone matched -> loaded from the checkpoint
    want = np.asarray(
        jax.tree.leaves(trained.params["student"]["backbone"])[0])
    got = np.asarray(jax.tree.leaves(state.params["student"]["backbone"])[0])
    np.testing.assert_allclose(got, want)
    # mismatched head keeps its fresh shape
    last = state.params["student"]["dino_head"]
    dims = {np.asarray(x).shape[-1] for x in jax.tree.leaves(last)}
    assert 128 in dims
