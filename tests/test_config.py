import pytest
import yaml

from dinov3_tpu.configs import (
    apply_dot_overrides,
    get_default_config,
    load_config,
)


def test_default_schema_keys():
    cfg = get_default_config()
    # reference-compatible sections (dinov3_jax/configs/ssl_default_config.yaml)
    for section in [
        "dino", "ibot", "gram", "train", "student", "teacher",
        "distillation", "multidistillation", "hrft", "optim", "crops",
        "evaluation", "checkpointing", "compute_precision",
    ]:
        assert section in cfg, section
    assert cfg.dino.head_n_prototypes == 65536
    assert cfg.student.arch == "vit_large"
    assert cfg.ibot.mask_ratio_min_max == [0.1, 0.5]


def test_dot_overrides_typing():
    cfg = get_default_config()
    apply_dot_overrides(cfg, [
        "optim.lr=0.005",
        "student.arch=vit_small",
        "train.batch_size_per_device=4",
        "dino.koleo_loss_distributed=true",
        "crops.local_crops_number=2",
    ])
    assert cfg.optim.lr == 0.005
    assert cfg.student.arch == "vit_small"
    assert cfg.train.batch_size_per_device == 4
    assert cfg.dino.koleo_loss_distributed is True
    assert cfg.crops.local_crops_number == 2


def test_run_yaml_merge(tmp_path):
    run = {"student": {"arch": "vit_base"}, "optim": {"lr": 0.002}}
    p = tmp_path / "run.yaml"
    p.write_text(yaml.safe_dump(run))
    cfg = load_config(p, overrides=["optim.scaling_rule=none"])
    assert cfg.student.arch == "vit_base"
    assert cfg.optim.lr == 0.002
    # untouched default survives the merge
    assert cfg.ibot.separate_head is True


def test_sqrt_lr_scaling(tmp_path):
    import jax

    cfg = load_config(overrides=["train.batch_size_per_device=128",
                                 "optim.lr=0.004"])
    # reference formula: lr *= 4 * sqrt(B/1024)  (dinov3_jax/configs/config.py:54)
    B = 128 * jax.device_count()
    assert abs(cfg.optim.lr - 0.004 * 4.0 * (B / 1024.0) ** 0.5) < 1e-12
    # idempotent
    from dinov3_tpu.configs import apply_scaling_rules_to_cfg
    lr = cfg.optim.lr
    apply_scaling_rules_to_cfg(cfg)
    assert cfg.optim.lr == lr


def test_schedules_v2_skips_lr_scaling(tmp_path):
    import yaml as _yaml

    p = tmp_path / "run.yaml"
    p.write_text(_yaml.safe_dump(
        {"schedules": {"lr": {"start": 0.0, "peak": 1e-3, "end": 1e-6,
                              "warmup_epochs": 10}},
         "optim": {"lr": 0.004}}))
    cfg = load_config(p)
    assert cfg.optim.lr == 0.004  # untouched (reference config.py:45-46)


def test_batch_size_per_gpu_alias(tmp_path):
    import yaml as _yaml

    p = tmp_path / "run.yaml"
    p.write_text(_yaml.safe_dump({"train": {"batch_size_per_gpu": 32}}))
    cfg = load_config(p, overrides=["optim.scaling_rule=none"])
    assert cfg.train.batch_size_per_device == 32
    assert "batch_size_per_gpu" not in cfg.train


def test_list_index_override():
    cfg = get_default_config()
    apply_dot_overrides(cfg, ["ibot.mask_ratio_min_max.1=0.6"])
    assert cfg.ibot.mask_ratio_min_max == [0.1, 0.6]


def test_model_parallel_excluded_from_global_batch():
    from dinov3_tpu.configs import global_batch_size

    cfg = get_default_config()
    apply_dot_overrides(cfg, ["train.batch_size_per_device=4",
                              "parallel.tensor=8"])
    # 8 CPU devices / tensor=8 -> 1 data shard
    assert global_batch_size(cfg) == 4


def test_dot_overrides_reject_unknown_keys():
    """Typos cannot silently train with defaults (the reference's
    OmegaConf set_struct strictness, configs/config.py:84)."""
    from dinov3_tpu.configs import apply_dot_overrides, get_default_config

    cfg = get_default_config()
    with pytest.raises(KeyError, match="lrr"):
        apply_dot_overrides(cfg, ["optim.lrr=0.1"])
    with pytest.raises(KeyError, match="brandnew"):
        apply_dot_overrides(cfg, ["brandnew.section=1"])
    # '+' prefix opts in to genuinely new keys
    apply_dot_overrides(cfg, ["+extras.tag=v1"])
    assert cfg.extras.tag == "v1"
    # nested-but-existing sections still work, including null sections
    apply_dot_overrides(cfg, ["optim.lr=0.5"])
    assert cfg.optim.lr == 0.5


def test_dot_overrides_reject_scalar_to_section():
    """optim.lr.x=1 must not silently clobber the scalar optim.lr into a
    section (losing the configured value)."""
    from dinov3_tpu.configs import apply_dot_overrides, get_default_config

    cfg = get_default_config()
    apply_dot_overrides(cfg, ["optim.lr=0.5"])
    with pytest.raises(KeyError, match="value, not a section"):
        apply_dot_overrides(cfg, ["optim.lr.x=1"])
    assert cfg.optim.lr == 0.5
    # explicit opt-in with '+' still allows replacing it with a section
    apply_dot_overrides(cfg, ["+optim.lr.x=1"])
    assert cfg.optim.lr.x == 1
    # ... and the symmetric direction: a scalar must not wipe a section
    with pytest.raises(KeyError, match="section, not a value"):
        apply_dot_overrides(cfg, ["optim=5"])
    assert cfg.optim.lr.x == 1
    apply_dot_overrides(cfg, ["+optim=5"])
    assert cfg.optim == 5


# ---------------- batch-tiling guardrail ----------------

def test_sublane_padding_waste_model():
    from dinov3_tpu.configs.config import sublane_padding_waste

    # the measured triple (MEASUREMENTS_r5.md phC rows): B=10 pads to
    # 16, B=8 and B=12 (8+4) tile cleanly
    assert sublane_padding_waste(10) == pytest.approx(0.6)
    assert sublane_padding_waste(8) == 0.0
    assert sublane_padding_waste(12) == 0.0
    # small power-of-two batches (the 512px high-res configs) are fine
    assert sublane_padding_waste(2) == 0.0
    assert sublane_padding_waste(4) == 0.0


def test_batch_tiling_guardrail_fires_on_b10_only():
    import warnings

    from dinov3_tpu.configs.config import warn_bad_batch_tiling

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        msg = warn_bad_batch_tiling(10)
        assert msg is not None
        # cites the measurement and suggests the nearest good sizes
        assert "24.22" in msg and "58.56" in msg
        assert "8 or 12" in msg
        assert len(caught) == 1
        assert warn_bad_batch_tiling(8) is None
        assert warn_bad_batch_tiling(12) is None
        assert len(caught) == 1  # no extra warnings for good sizes


def test_batch_tiling_guardrail_at_config_build():
    import warnings

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        load_config(overrides=["train.batch_size_per_device=10",
                               "optim.scaling_rule=none"])
        assert any("sublane" in str(w.message) for w in caught)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        load_config(overrides=["train.batch_size_per_device=12",
                               "optim.scaling_rule=none"])
        assert not any("sublane" in str(w.message) for w in caught)


def test_reshard_guardrail_config_and_live_modes():
    """warn_reshard_padding (ISSUE 19): config mode rejects typo'd
    elastic-resume knobs at load; live mode prices the target
    topology's flat-shard re-padding on a reshape."""
    import warnings

    from dinov3_tpu.configs.config import warn_reshard_padding

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        load_config(overrides=["train.resume_topology=sideways",
                               "train.reshard_padding_tol=7",
                               "optim.scaling_rule=none"])
        text = " ".join(str(w.message) for w in caught)
        assert "resume_topology" in text and "reshard_padding_tol" in text
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        load_config(overrides=["train.resume_topology=memory",
                               "optim.scaling_rule=none"])
        assert not any("resume_topology" in str(w.message)
                       for w in caught)

    # live mode: 7 elements at dp=8 pad 1/8; clean at dp=7
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        msgs = warn_reshard_padding(leaf_sizes=[7], src_dp=7, dst_dp=8,
                                    threshold=0.05)
        assert len(msgs) == 1 and "dp=8" in msgs[0]
        assert any("re-padding" in str(w.message) for w in caught)
    assert warn_reshard_padding(leaf_sizes=[7], src_dp=8, dst_dp=7,
                                threshold=0.05) == []
