"""Eval harness: k-NN, linear probe, feature extraction, do_eval wiring."""

import numpy as np
import pytest

from dinov3_tpu.evals import knn_eval, linear_probe_eval


def _blobs(n_per_class, n_classes, d, seed, spread=0.15):
    # class centers are fixed (seed 42); `seed` only varies the noise
    centers = np.random.default_rng(42).standard_normal(
        (n_classes, d)).astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    rng = np.random.default_rng(seed)
    feats, labels = [], []
    for c in range(n_classes):
        feats.append(
            centers[c] + spread * rng.standard_normal(
                (n_per_class, d)).astype(np.float32)
        )
        labels.append(np.full(n_per_class, c, np.int64))
    return np.concatenate(feats), np.concatenate(labels)


def test_knn_separable_blobs():
    train_x, train_y = _blobs(50, 5, 16, seed=0)
    test_x, test_y = _blobs(20, 5, 16, seed=1)
    acc = knn_eval(train_x, train_y, test_x, test_y, n_classes=5, k=10)
    assert acc > 0.95


def test_knn_chance_on_noise():
    rng = np.random.default_rng(0)
    train_x = rng.standard_normal((200, 16)).astype(np.float32)
    train_y = rng.integers(0, 4, 200)
    test_x = rng.standard_normal((100, 16)).astype(np.float32)
    test_y = rng.integers(0, 4, 100)
    acc = knn_eval(train_x, train_y, test_x, test_y, n_classes=4, k=10)
    assert acc < 0.6  # ~chance


def test_linear_probe_separable_blobs():
    train_x, train_y = _blobs(50, 5, 16, seed=0)
    test_x, test_y = _blobs(20, 5, 16, seed=1)
    acc = linear_probe_eval(
        train_x, train_y, test_x, test_y, n_classes=5,
        epochs=20, batch_size=64, lr=0.5,
    )
    assert acc > 0.95


def test_do_eval_end_to_end():
    """Tiny backbone + synthetic dataset through the full harness."""
    import jax
    import jax.numpy as jnp

    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.evals import do_eval
    from dinov3_tpu.models import build_backbone

    cfg = get_default_config()
    apply_dot_overrides(cfg, [
        "student.arch=vit_test", "student.patch_size=4",
        "crops.global_crops_size=16",
        "train.dataset_path=Synthetic:size=64:image_size=24:n_classes=4",
        "train.num_workers=2", "optim.scaling_rule=none",
    ])
    model = build_backbone(cfg, teacher=True)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 16, 16, 3))
    )["params"]
    results = do_eval(
        cfg, model, params,
        n_classes=4, batch_size=8,
        max_train_samples=32, max_val_samples=16, probe_epochs=2,
    )
    assert 0.0 <= results["knn_top1"] <= 1.0
    assert 0.0 <= results["linear_top1"] <= 1.0
