"""Eval harness: k-NN, linear probe, feature extraction, do_eval wiring."""

import numpy as np
import pytest

from dinov3_tpu.evals import knn_eval, linear_probe_eval


def _blobs(n_per_class, n_classes, d, seed, spread=0.15):
    # class centers are fixed (seed 42); `seed` only varies the noise
    centers = np.random.default_rng(42).standard_normal(
        (n_classes, d)).astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    rng = np.random.default_rng(seed)
    feats, labels = [], []
    for c in range(n_classes):
        feats.append(
            centers[c] + spread * rng.standard_normal(
                (n_per_class, d)).astype(np.float32)
        )
        labels.append(np.full(n_per_class, c, np.int64))
    return np.concatenate(feats), np.concatenate(labels)


def test_knn_separable_blobs():
    train_x, train_y = _blobs(50, 5, 16, seed=0)
    test_x, test_y = _blobs(20, 5, 16, seed=1)
    acc = knn_eval(train_x, train_y, test_x, test_y, n_classes=5, k=10)
    assert acc > 0.95


def test_knn_chance_on_noise():
    rng = np.random.default_rng(0)
    train_x = rng.standard_normal((200, 16)).astype(np.float32)
    train_y = rng.integers(0, 4, 200)
    test_x = rng.standard_normal((100, 16)).astype(np.float32)
    test_y = rng.integers(0, 4, 100)
    acc = knn_eval(train_x, train_y, test_x, test_y, n_classes=4, k=10)
    assert acc < 0.6  # ~chance


def test_linear_probe_separable_blobs():
    train_x, train_y = _blobs(50, 5, 16, seed=0)
    test_x, test_y = _blobs(20, 5, 16, seed=1)
    acc = linear_probe_eval(
        train_x, train_y, test_x, test_y, n_classes=5,
        epochs=20, batch_size=64, lr=0.5,
    )
    assert acc > 0.95


def test_do_eval_end_to_end():
    """Tiny backbone + synthetic dataset through the full harness."""
    import jax
    import jax.numpy as jnp

    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.evals import do_eval
    from dinov3_tpu.models import build_backbone

    cfg = get_default_config()
    apply_dot_overrides(cfg, [
        "student.arch=vit_test", "student.patch_size=4",
        "crops.global_crops_size=16",
        "train.dataset_path=Synthetic:size=64:image_size=24:n_classes=4",
        "train.num_workers=2", "optim.scaling_rule=none",
    ])
    model = build_backbone(cfg, teacher=True)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 16, 16, 3))
    )["params"]
    results = do_eval(
        cfg, model, params,
        n_classes=4, batch_size=8,
        max_train_samples=32, max_val_samples=16, probe_epochs=2,
    )
    assert 0.0 <= results["knn_top1"] <= 1.0
    assert 0.0 <= results["linear_top1"] <= 1.0


def test_linear_probe_sweep_grid():
    """The vmapped lr x wd grid trains every probe jointly; the best one
    separates the blobs and the grid reports one acc per combo."""
    from dinov3_tpu.evals.linear import linear_probe_sweep

    train_x, train_y = _blobs(50, 5, 16, seed=0)
    test_x, test_y = _blobs(20, 5, 16, seed=1)
    best, grid = linear_probe_sweep(
        train_x, train_y, test_x, test_y, n_classes=5,
        lrs=(1e-3, 1e-1, 0.5), wds=(0.0, 1e-4), epochs=15, batch_size=64,
    )
    assert len(grid) == 6
    assert best == max(grid.values())
    assert best > 0.95


def test_knn_eval_multi_ks():
    from dinov3_tpu.evals.knn import knn_eval_multi

    train_x, train_y = _blobs(50, 5, 16, seed=0)
    test_x, test_y = _blobs(20, 5, 16, seed=1)
    res = knn_eval_multi(train_x, train_y, test_x, test_y, n_classes=5)
    assert set(res) == {"knn10_top1", "knn20_top1"}
    assert max(res.values()) > 0.9


@pytest.mark.slow
def test_standalone_eval_cli(tmp_path):
    """python -m dinov3_tpu.evals --ckpt ... runs the full protocol path
    (sweep + multi-k) against a trained checkpoint, standalone
    (VERDICT r1 next-round #6)."""
    import json

    from dinov3_tpu.evals.__main__ import main as eval_main
    from dinov3_tpu.train.train import main as train_main

    out = tmp_path / "run"
    common = [
        "student.arch=vit_test", "student.patch_size=4",
        "crops.global_crops_size=16", "crops.local_crops_size=8",
        "crops.local_crops_number=2",
        "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
        "dino.head_bottleneck_dim=16",
        "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
        "ibot.head_bottleneck_dim=16",
        "train.batch_size_per_device=2",
        "optim.scaling_rule=none",
    ]
    train_main(["--output-dir", str(out), "--no-resume"] + common + [
        "train.OFFICIAL_EPOCH_LENGTH=2", "optim.epochs=1",
        "optim.warmup_epochs=0", "data.backend=synthetic",
    ])
    results = eval_main([
        "--ckpt", str(out / "ckpt"),
        "--batch-size", "8",
        "--probe-epochs", "2",
        "--max-train-samples", "32",
        "--max-val-samples", "16",
        "--output", str(tmp_path / "eval.json"),
    ] + common + [
        "+evaluation.train_dataset_path="
        "Synthetic:split=TRAIN:size=64:image_size=24:n_classes=4",
        "+evaluation.val_dataset_path="
        "Synthetic:split=VAL:size=32:image_size=24:n_classes=4",
        "train.num_workers=2",
    ])
    assert "linear_sweep" in results and len(results["linear_sweep"]) >= 2
    assert {"knn10_top1", "knn20_top1", "knn_top1",
            "linear_top1"} <= set(results)
    on_disk = json.loads((tmp_path / "eval.json").read_text())
    assert on_disk["linear_top1"] == results["linear_top1"]
