"""Pin the subset drop-path FLOP cut with XLA cost analysis.

docs/PERFORMANCE.md's headline optimization claim — reference-semantics
batch-subset stochastic depth does ~24% less work at ViT-L/rate-0.3
(13.31 -> 10.08 TFLOP/step) — rests on compiling the exact step program
and reading ``cost_analysis()``. This test pins the mechanism at test
scale: at drop rate 0.5 the subset program must execute well under 3/4
of the mask program's FLOPs (measured ~0.61x at vit_test4 scale), and
the cut must come from the block branches alone (both programs share
everything else).

(reference: dinov3_jax/layers/block.py:94-117 — the reference's
batch-subset stochastic depth, the semantics ``drop_path_mode=subset``
restores with static shapes.)
"""

import jax
import jax.numpy as jnp
import pytest

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.data import make_synthetic_batch
from dinov3_tpu.train import build_train_setup, put_batch

pytestmark = pytest.mark.slow  # two full step compiles (~2 min)


def _step_flops(mode: str, rate: float) -> float:
    cfg = get_default_config()
    apply_dot_overrides(cfg, [
        "student.arch=vit_test4", "student.patch_size=4",
        f"student.drop_path_rate={rate}",
        f"student.drop_path_mode={mode}",
        "crops.global_crops_size=16", "crops.local_crops_size=8",
        "crops.local_crops_number=2",
        "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
        "dino.head_bottleneck_dim=16",
        "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
        "ibot.head_bottleneck_dim=16",
        "optim.scaling_rule=none", "parallel.data=-1",
    ])
    batch = {k: jnp.asarray(v)
             for k, v in make_synthetic_batch(cfg, 8, seed=0).items()}
    # single device on purpose: this pins the single-chip bench program
    # (groups=1). Under the 8-way test mesh the per-span batch is 2 and
    # XLA expands the tiny gather/scatter into one-hot contractions that
    # dwarf vit_test4's matmuls (~3x total flops at this toy scale) —
    # an artifact of test dims: at ViT-L dims the same expansion is
    # <0.1% of a block's FLOPs.
    setup = build_train_setup(cfg, batch, devices=jax.devices()[:1])
    dbatch = put_batch(batch, setup.batch_shardings)
    compiled = setup.step_fn.lower(
        setup.state, dbatch, setup.scalars(0), jax.random.key(0)
    ).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    return float(ca["flops"])


def test_subset_drop_path_cuts_step_flops():
    f_subset = _step_flops("subset", 0.5)
    f_mask = _step_flops("mask", 0.5)
    ratio = f_subset / f_mask
    # measured 0.606 on this program; anything approaching 1.0 means the
    # subset gather stopped skipping compute (the whole point)
    assert ratio < 0.75, (
        f"subset program executes {ratio:.2f}x the mask program's FLOPs "
        "— the compute cut regressed"
    )
    assert ratio > 0.35, (
        f"subset/mask FLOP ratio {ratio:.2f} is implausibly low — "
        "cost analysis or program construction changed"
    )
