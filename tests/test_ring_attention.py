"""Ring attention (sequence/context parallelism) vs dense attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dinov3_tpu.ops.attention import xla_attention
from dinov3_tpu.parallel.ring_attention import ring_attention


def _mesh(eight_devices, seq):
    rest = 8 // seq
    arr = np.array(eight_devices).reshape(1, rest, 1, 1, seq, 1, 1)
    return Mesh(arr, ("dcn_data", "data", "pipe", "fsdp", "seq", "tensor",
                      "expert"))


def _qkv(rng, B, N, h, d, dtype=jnp.float32):
    ks = jax.random.split(rng, 3)
    return tuple(jax.random.normal(k, (B, N, h, d), dtype) for k in ks)


@pytest.mark.parametrize("seq,N", [(4, 128), (4, 201), (8, 64), (2, 41)])
def test_ring_matches_dense(eight_devices, rng, seq, N):
    mesh = _mesh(eight_devices, seq)
    B, h, d = 2, 2, 16
    q, k, v = _qkv(rng, B, N, h, d)

    f = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))
    out = f(q, k, v)
    ref = xla_attention(q, k, v)
    assert out.shape == (B, N, h, d)
    err = jnp.abs(out - ref).max()
    assert jnp.allclose(out, ref, atol=1e-5, rtol=1e-5), err


def test_ring_gradients_match_dense(eight_devices, rng):
    mesh = _mesh(eight_devices, 4)
    B, N, h, d = 1, 50, 2, 8  # N=50 not divisible by 4 -> padded path
    q, k, v = _qkv(rng, B, N, h, d)
    tangent = jax.random.normal(jax.random.fold_in(rng, 3), (B, N, h, d))

    g_ring = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(ring_attention(q, k, v, mesh) * tangent),
        argnums=(0, 1, 2),
    ))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(xla_attention(q, k, v) * tangent),
        argnums=(0, 1, 2),
    )(q, k, v)
    for gr, gd, name in zip(g_ring, g_ref, "qkv"):
        err = jnp.abs(gr - gd).max()
        assert jnp.allclose(gr, gd, atol=2e-5, rtol=2e-5), (name, err)


def test_ring_with_sharded_inputs(eight_devices, rng):
    """Inputs already sharded over (data, seq) stay exact."""
    mesh = _mesh(eight_devices, 4)
    B, N, h, d = 4, 64, 2, 8
    q, k, v = _qkv(rng, B, N, h, d)
    sh = NamedSharding(mesh, P(("dcn_data", "data", "fsdp"), "seq", None, None))
    qs, ks, vs = (jax.device_put(t, sh) for t in (q, k, v))
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(qs, ks, vs)
    ref = xla_attention(q, k, v)
    assert jnp.allclose(out, ref, atol=1e-5, rtol=1e-5)


def _blocky_seg(B, N):
    """[B, N] int32 segment ids: a few contiguous blocks per row, with
    different block boundaries per batch row (crop-packing shape)."""
    rows = [jnp.arange(N) * (3 + b) // N for b in range(B)]
    return jnp.stack(rows).astype(jnp.int32)


@pytest.mark.parametrize("seq,N", [(4, 128), (4, 201), (2, 41)])
def test_ring_segment_mask_matches_dense(eight_devices, rng, seq, N):
    """Packed-crop block-diagonal masking: ring with rotating segment-id
    chunks must match the dense ``xla_attention(seg=...)`` oracle,
    including on the padded path (N not divisible by seq)."""
    mesh = _mesh(eight_devices, seq)
    B, h, d = 2, 2, 16
    q, k, v = _qkv(rng, B, N, h, d)
    seg = _blocky_seg(B, N)

    out = jax.jit(lambda q, k, v, s: ring_attention(q, k, v, mesh, seg=s))(
        q, k, v, seg)
    ref = xla_attention(q, k, v, seg=seg)
    err = jnp.abs(out - ref).max()
    assert jnp.allclose(out, ref, atol=1e-5, rtol=1e-5), err
    # the mask must actually bite: segmented != unsegmented
    assert not jnp.allclose(out, xla_attention(q, k, v), atol=1e-3)


def test_ring_segment_gradients_match_dense(eight_devices, rng):
    """custom_vjp backward with the segment ids co-rotating: dq/dk/dv
    match dense, and the integer seg input takes no cotangent."""
    mesh = _mesh(eight_devices, 4)
    B, N, h, d = 2, 50, 2, 8  # N=50 -> padded path with seg padding
    q, k, v = _qkv(rng, B, N, h, d)
    seg = _blocky_seg(B, N)
    tangent = jax.random.normal(jax.random.fold_in(rng, 3), (B, N, h, d))

    g_ring = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(
            ring_attention(q, k, v, mesh, seg=seg) * tangent),
        argnums=(0, 1, 2),
    ))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(xla_attention(q, k, v, seg=seg) * tangent),
        argnums=(0, 1, 2),
    )(q, k, v)
    for gr, gd, name in zip(g_ring, g_ref, "qkv"):
        err = jnp.abs(gr - gd).max()
        assert jnp.allclose(gr, gd, atol=2e-5, rtol=2e-5), (name, err)


def test_ring_collectives_scope_attributed(eight_devices, rng):
    """Anatomy-ledger census: every collective-permute the ring emits
    (fwd AND custom_vjp bwd) indexes under the ``ring_permute`` scope in
    the compiled HLO, and an executed profiler trace joins against it
    with zero unattributed collective time — the dp x seq twin of the
    bucketed-overlap round-trip in test_anatomy.py."""
    import shutil
    import tempfile

    from dinov3_tpu.telemetry.anatomy import (
        anatomy_ledger,
        build_op_index,
        ledger_summary,
    )

    mesh = _mesh(eight_devices, 4)
    B, N, h, d = 2, 64, 2, 8
    q, k, v = _qkv(rng, B, N, h, d)
    seg = _blocky_seg(B, N)
    tangent = jax.random.normal(jax.random.fold_in(rng, 3), (B, N, h, d))

    f = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(
            ring_attention(q, k, v, mesh, seg=seg) * tangent),
        argnums=(0, 1, 2),
    ))
    compiled = f.lower(q, k, v).compile()
    hlo = compiled.as_text()

    idx = build_op_index(hlo)
    colls = {n: i for n, i in idx.items() if i["category"] == "collective"}
    assert colls, "ring twin compiled away its collective-permutes"
    scopes = {i["scope"] for i in colls.values()}
    assert any((s or "").startswith("ring_permute") for s in scopes), scopes
    # no ring collective may index outside a ring_* scope
    stray = {n: i["scope"] for n, i in colls.items()
             if not (i["scope"] or "").startswith("ring_")}
    assert not stray, stray

    jax.block_until_ready(compiled(q, k, v))  # warmup outside the window
    tdir = tempfile.mkdtemp(prefix="ring_anat_", dir="/tmp")
    try:
        jax.profiler.start_trace(tdir)
        for _ in range(2):
            jax.block_until_ready(compiled(q, k, v))
        jax.profiler.stop_trace()

        ledger = anatomy_ledger(tdir, hlo_text=hlo, n_steps=2)
        assert ledger["hlo_joined"] is True
        assert ledger["unattributed_collective_ms"] == 0.0
        summary = ledger_summary(ledger)
        led_scopes = set(summary["collectives"])
        assert any(s.startswith("ring_") for s in led_scopes), led_scopes
    finally:
        shutil.rmtree(tdir, ignore_errors=True)


def test_ring_min_seq_dispatch_per_pass(eight_devices):
    """Per-pass dispatch inside SelfAttention: on a dp x seq mesh the
    long pass (N >= ring_min_seq) compiles to a ring program
    (collective-permutes present) while the short pass on the SAME
    module stays dense with seq-replicated activations (none)."""
    import flax.linen as nn

    from dinov3_tpu.ops.attention import SelfAttention
    from dinov3_tpu.parallel.context import set_current_mesh

    mesh = _mesh(eight_devices, 2)
    D, h = 32, 2
    attn = SelfAttention(
        dim=D, num_heads=h, seq_parallel=True, ring_min_seq=64,
        attn_impl="xla", dtype=jnp.float32, param_dtype=jnp.float32,
    )
    x_long = jax.random.normal(jax.random.key(0), (2, 64, D))
    x_short = jax.random.normal(jax.random.key(1), (2, 16, D))
    params = nn.meta.unbox(attn.init(jax.random.key(2), x_long))

    set_current_mesh(mesh)
    try:
        def hlo_for(x):
            return jax.jit(
                lambda p, x: attn.apply(p, x)
            ).lower(params, x).compile().as_text()

        assert "collective-permute" in hlo_for(x_long)
        assert "collective-permute" not in hlo_for(x_short)
    finally:
        set_current_mesh(None)


def test_seq_parallel_train_step(eight_devices):
    """Full fused train step on a dp2 x fsdp2 x seq2 mesh."""
    import jax.numpy as jnp

    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.parallel.context import set_current_mesh
    from dinov3_tpu.train import build_train_setup, put_batch

    cfg = get_default_config()
    apply_dot_overrides(cfg, [
        "student.arch=vit_test", "student.patch_size=4",
        "student.drop_path_rate=0.0",
        "crops.global_crops_size=16", "crops.local_crops_size=8",
        "crops.local_crops_number=2",
        "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
        "dino.head_bottleneck_dim=16",
        "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
        "ibot.head_bottleneck_dim=16",
        "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
        "optim.scaling_rule=none",
        "parallel.data=2", "parallel.fsdp=2", "parallel.seq=2",
        "parallel.zero3=false",
    ])
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, 4, seed=0).items()}
    try:
        setup = build_train_setup(cfg, batch)
        assert setup.mesh.shape["seq"] == 2
        dbatch = put_batch(batch, setup.batch_shardings)
        state, metrics = setup.step_fn(
            setup.state, dbatch, setup.scalars(0), jax.random.key(0)
        )
        assert jnp.isfinite(metrics["total_loss"])
        assert int(state.step) == 1
    finally:
        set_current_mesh(None)
