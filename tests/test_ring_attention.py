"""Ring attention (sequence/context parallelism) vs dense attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dinov3_tpu.ops.attention import xla_attention
from dinov3_tpu.parallel.ring_attention import ring_attention


def _mesh(eight_devices, seq):
    rest = 8 // seq
    arr = np.array(eight_devices).reshape(1, rest, 1, 1, seq, 1, 1)
    return Mesh(arr, ("dcn_data", "data", "pipe", "fsdp", "seq", "tensor",
                      "expert"))


def _qkv(rng, B, N, h, d, dtype=jnp.float32):
    ks = jax.random.split(rng, 3)
    return tuple(jax.random.normal(k, (B, N, h, d), dtype) for k in ks)


@pytest.mark.parametrize("seq,N", [(4, 128), (4, 201), (8, 64), (2, 41)])
def test_ring_matches_dense(eight_devices, rng, seq, N):
    mesh = _mesh(eight_devices, seq)
    B, h, d = 2, 2, 16
    q, k, v = _qkv(rng, B, N, h, d)

    f = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))
    out = f(q, k, v)
    ref = xla_attention(q, k, v)
    assert out.shape == (B, N, h, d)
    err = jnp.abs(out - ref).max()
    assert jnp.allclose(out, ref, atol=1e-5, rtol=1e-5), err


def test_ring_gradients_match_dense(eight_devices, rng):
    mesh = _mesh(eight_devices, 4)
    B, N, h, d = 1, 50, 2, 8  # N=50 not divisible by 4 -> padded path
    q, k, v = _qkv(rng, B, N, h, d)
    tangent = jax.random.normal(jax.random.fold_in(rng, 3), (B, N, h, d))

    g_ring = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(ring_attention(q, k, v, mesh) * tangent),
        argnums=(0, 1, 2),
    ))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(xla_attention(q, k, v) * tangent),
        argnums=(0, 1, 2),
    )(q, k, v)
    for gr, gd, name in zip(g_ring, g_ref, "qkv"):
        err = jnp.abs(gr - gd).max()
        assert jnp.allclose(gr, gd, atol=2e-5, rtol=2e-5), (name, err)


def test_ring_with_sharded_inputs(eight_devices, rng):
    """Inputs already sharded over (data, seq) stay exact."""
    mesh = _mesh(eight_devices, 4)
    B, N, h, d = 4, 64, 2, 8
    q, k, v = _qkv(rng, B, N, h, d)
    sh = NamedSharding(mesh, P(("dcn_data", "data", "fsdp"), "seq", None, None))
    qs, ks, vs = (jax.device_put(t, sh) for t in (q, k, v))
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(qs, ks, vs)
    ref = xla_attention(q, k, v)
    assert jnp.allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_seq_parallel_train_step(eight_devices):
    """Full fused train step on a dp2 x fsdp2 x seq2 mesh."""
    import jax.numpy as jnp

    from dinov3_tpu.configs import apply_dot_overrides, get_default_config
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.parallel.context import set_current_mesh
    from dinov3_tpu.train import build_train_setup, put_batch

    cfg = get_default_config()
    apply_dot_overrides(cfg, [
        "student.arch=vit_test", "student.patch_size=4",
        "student.drop_path_rate=0.0",
        "crops.global_crops_size=16", "crops.local_crops_size=8",
        "crops.local_crops_number=2",
        "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
        "dino.head_bottleneck_dim=16",
        "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
        "ibot.head_bottleneck_dim=16",
        "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
        "optim.scaling_rule=none",
        "parallel.data=2", "parallel.fsdp=2", "parallel.seq=2",
        "parallel.zero3=false",
    ])
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, 4, seed=0).items()}
    try:
        setup = build_train_setup(cfg, batch)
        assert setup.mesh.shape["seq"] == 2
        dbatch = put_batch(batch, setup.batch_shardings)
        state, metrics = setup.step_fn(
            setup.state, dbatch, setup.scalars(0), jax.random.key(0)
        )
        assert jnp.isfinite(metrics["total_loss"])
        assert int(state.step) == 1
    finally:
        set_current_mesh(None)
