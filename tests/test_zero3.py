"""ZeRO-3 weight-streaming engine (parallel.zero3: train/setup.py +
parallel/sharding.py zero3_* + ops/block.py stream wrapper +
models/streaming.py explicit twin) vs the replicated-masters oracle.

The zero3 engine is the default master layout at ``parallel.fsdp > 1``
(and at any data-axis product > 1 via ``parallel.zero3=true``); the
replicated layout stays in the tree as the oracle behind ``=false``.
These tests pin:

- leaf-for-leaf BITWISE equivalence of the two arms on the same mesh:
  every loss metric (values), the first-step adam mu (grads — mu is
  (1-b1)*g_clipped at step one), and the post-update masters/teacher/
  moments, over multiple steps;
- the weight-stream structure of the compiled step: all-gathers INSIDE
  the block scan's while body, attributed to the ``zero3_stream``/
  ``zero3_gather`` named scopes, zero unattributed collectives
  (``utils.hlo_collective_census`` by_scope / prefetch_overlap);
- the explicit double-buffered twin (``streamed_block_scan``): numerics
  bitwise against a per-block oracle loop and against its own at-use
  variant, and the prefetch-overlap census columns (every in-loop
  gather ``zero3_prefetch``-scoped = issued a block ahead of its
  consumer);
- dp-only and dp x fsdp dryruns, plus the unrolled (scan_layers=false)
  path;
- setup wiring: auto-on at fsdp > 1, model-SHAPED sharded moments (not
  the PR-5 flat layout), oracle fallback, the explicit
  sharded_update=true conflict raising;
- cross-arm checkpoints in all directions (replicated <-> zero3 as pure
  re-placements; PR-5 flat <-> zero3 through the _adapt_opt_leaf
  flat/full path), with bitwise round-trips and resume determinism;
- the layout guardrails (warn_zero3_padding / warn_zero3_no_stream) and
  the committed COST_Z3_r12.json / MEM_r12.json acceptance numbers
  (>= 70% master reduction, replicated-fraction pin, attributed
  gathers, populated prefetch column);
- the ViT-7B compile-only dryrun (slow) — the unlock deliverable.
"""

import json
import os

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import pytest

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.parallel.mesh import MeshSpec, build_mesh
from dinov3_tpu.parallel.sharding import (
    ZERO3_AXES,
    zero3_leaf_spec,
    zero3_replicated_waste,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SMOL = [
    "student.arch=vit_test", "student.patch_size=4",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2", "train.batch_size_per_device=2",
    "optim.scaling_rule=none", "train.scan_layers=true",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=32",
    "dino.head_bottleneck_dim=16",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=32",
    "ibot.head_bottleneck_dim=16",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "optim.warmup_epochs=1",
]


def _setup(extra, batch_size, devices):
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup

    cfg = get_default_config()
    # pin the bucketed engine (PR 9) off: this file pins the zero3-vs-
    # PR-5-flat arm topology, and bucketed otherwise auto-supersedes
    # the flat engine's slot on dp-only meshes
    apply_dot_overrides(
        cfg, SMOL + ["optim.bucketed_collectives=false"] + list(extra))
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, batch_size, seed=0).items()}
    return build_train_setup(cfg, batch, devices=devices), batch


def _flat_params(tree):
    return jtu.tree_flatten_with_path(tree)[0]


def assert_trees_bitwise(a, b, what, limit=None):
    fa, fb = _flat_params(a), _flat_params(b)
    assert len(fa) == len(fb)
    for (pa, la), (_, lb) in (zip(fa, fb) if limit is None
                              else zip(fa[:limit], fb[:limit])):
        assert np.array_equal(np.asarray(la), np.asarray(lb)), (
            f"{what}: {jtu.keystr(pa)} differs")


# ---------------- layout / spec unit tests ----------------

def test_zero3_leaf_spec_dim_choice(eight_devices):
    mesh = build_mesh(MeshSpec(data=8), devices=eight_devices)
    # largest dividing dim wins
    spec = zero3_leaf_spec((64, 192), ("embed", "heads"), mesh)
    assert spec[1] == ZERO3_AXES and spec[0] is None
    # stacked scan dim is never taken, even though it divides
    spec = zero3_leaf_spec((8, 64, 192), ("layers", "embed", "heads"), mesh)
    assert spec[0] is None and spec[2] == ZERO3_AXES
    # no dividing dim -> None (leaf stays on the logical-rules layout)
    assert zero3_leaf_spec((3, 5), (None, None), mesh) is None
    # scalars/empty shapes -> None
    assert zero3_leaf_spec((), (), mesh) is None
    # 1-device mesh -> None (nothing to shard)
    mesh1 = build_mesh(MeshSpec(data=1), devices=eight_devices[:1])
    assert zero3_leaf_spec((64,), ("embed",), mesh1) is None


def test_zero3_leaf_spec_respects_tensor_axes(eight_devices):
    mesh = build_mesh(MeshSpec(data=4, tensor=2), devices=eight_devices)
    # "heads" maps to the >1 tensor axis: kept, zero3 lands elsewhere
    spec = zero3_leaf_spec((64, 192), ("embed", "heads"), mesh)
    assert spec[1] == "tensor"
    assert spec[0] == ZERO3_AXES
    # both dims tensor-owned at tensor>1, none free -> None
    spec = zero3_leaf_spec((192,), ("heads",), mesh)
    assert spec is None


def test_zero3_replicated_waste():
    mesh = build_mesh(MeshSpec(data=8), devices=jax.devices())
    # everything shardable -> 0
    assert zero3_replicated_waste(
        [((64, 64), (None, None)), ((128,), (None,))], mesh) == 0.0
    # a stuck leaf contributes its element share
    waste = zero3_replicated_waste(
        [((64,), (None,)), ((3, 5), (None, None))], mesh)
    assert waste == pytest.approx(15 / 79)


# ---------------- guardrails ----------------

def test_zero3_guardrails(recwarn):
    from dinov3_tpu.configs.config import (
        warn_zero3_no_stream,
        warn_zero3_padding,
    )

    assert warn_zero3_padding(0.0, 8) is None
    msg = warn_zero3_padding(0.25, 8)
    assert msg is not None and "zero3 master layout" in msg
    assert "dp=8" in msg
    assert len([w for w in recwarn.list
                if "zero3 master layout" in str(w.message)]) == 1

    # no-stream warning: zero3 wished (fsdp>1) + scan_layers=false
    cfg = get_default_config()
    apply_dot_overrides(cfg, ["parallel.fsdp=2", "train.scan_layers=false"])
    msg = warn_zero3_no_stream(cfg)
    assert msg is not None and "scan_layers" in msg
    # scan on, or zero3 off: silent
    cfg2 = get_default_config()
    apply_dot_overrides(cfg2, ["parallel.fsdp=2", "train.scan_layers=true"])
    assert warn_zero3_no_stream(cfg2) is None
    cfg3 = get_default_config()
    assert warn_zero3_no_stream(cfg3) is None


# ---------------- setup wiring + toggles ----------------

def test_setup_wiring_and_toggles(eight_devices):
    # explicit true on a dp-only mesh: masters sharded, moments
    # model-SHAPED and sharded (not the PR-5 flat layout)
    setup, _ = _setup(["parallel.zero3=true"], 16, eight_devices)
    assert setup.zero3 and not setup.sharded_update
    for (path, leaf), (_, sh) in zip(
        _flat_params(setup.state.params["student"])[:16],
        _flat_params(setup.state_shardings.params["student"])[:16],
    ):
        if any(d % 8 == 0 for d in leaf.shape):
            assert any(s == ZERO3_AXES for s in sh.spec), (
                jtu.keystr(path), sh.spec)
    mu0 = jax.tree.leaves(setup.state.opt_state.adam.mu)[0]
    p0 = jax.tree.leaves(setup.state.params["student"])[0]
    assert mu0.shape == p0.shape  # model-shaped, not flat

    # auto: on at fsdp>1, off on a dp-only mesh
    s_fsdp, _ = _setup(["parallel.fsdp=2"], 16, eight_devices)
    assert s_fsdp.zero3 and not s_fsdp.sharded_update
    s_dp, _ = _setup([], 16, eight_devices)
    assert not s_dp.zero3 and s_dp.sharded_update  # PR-5 default intact

    # =false: replicated oracle (and the flat engine resumes its slot)
    s_off, _ = _setup(["parallel.fsdp=2", "parallel.zero3=false"], 16,
                      eight_devices)
    assert not s_off.zero3 and s_off.sharded_update

    # explicit flat engine + zero3 is a misconfiguration
    with pytest.raises(ValueError, match="zero3"):
        _setup(["parallel.zero3=true", "optim.sharded_update=true"], 16,
               eight_devices)


# ---------------- bitwise equivalence ----------------

@pytest.fixture(scope="module")
def arms_dp(eight_devices):
    """zero3 vs replicated arms on the dp-only 8-device mesh, with the
    replicated arm's flat update engine ALSO stripped so the comparison
    isolates the master layout (both arms run the fused update)."""
    from dinov3_tpu.train import put_batch

    s_z, batch = _setup(["parallel.zero3=true"], 16, eight_devices)
    s_r, _ = _setup(["parallel.zero3=false", "optim.sharded_update=false"],
                    16, eight_devices)
    d = put_batch(batch, s_z.batch_shardings)
    return s_z, s_r, d


def test_bitwise_equivalence_dp_only(arms_dp):
    """Values (every loss metric), grads (step-1 mu) and post-update
    masters/teacher/moments: BITWISE equal between the zero3 and
    replicated arms over 2 steps."""
    s_z, s_r, d = arms_dp
    st_z, st_r = s_z.state, s_r.state
    for i in range(2):
        st_z, m_z = s_z.step_fn(st_z, d, s_z.scalars(i), jax.random.key(0))
        st_r, m_r = s_r.step_fn(st_r, d, s_r.scalars(i), jax.random.key(0))
        for k in m_r:
            assert float(m_z[k]) == float(m_r[k]), (i, k)
        if i == 0:
            # step-1 mu is (1-b1) * clipped grad: grads bitwise
            assert_trees_bitwise(st_z.opt_state.adam.mu,
                                 st_r.opt_state.adam.mu, "grads (mu)")
    assert_trees_bitwise(st_z.params, st_r.params, "post-update masters")
    assert_trees_bitwise(st_z.opt_state.adam.nu, st_r.opt_state.adam.nu,
                         "nu")
    # the zero3 masters really are sharded (not silently replicated)
    from dinov3_tpu.telemetry.memory import layout_split

    split = layout_split(st_z.params, s_z.state_shardings.params)
    assert split["replicated_fraction"] < 0.05
    rep = layout_split(st_r.params, s_r.state_shardings.params)
    assert rep["replicated_fraction"] > 0.9


def test_dryrun_dp_fsdp(eight_devices):
    """dp x fsdp mesh: the zero3 arm (auto-on) runs 2 finite steps and
    matches the replicated arm at PR-5 dryrun tolerances. Both arms
    START FROM THE SAME STATE (zero3 keeps model shapes, so the zero3
    init re-places losslessly into the oracle arm's shardings): on this
    backend the init DRAWS themselves depend on the init program's
    shardings (the fsdp-mesh embed-sharded init already differs from
    the eager init on 10 leaves pre-PR-7), so per-arm inits would
    compare two different models. fp32 compute: the fsdp-mesh oracle
    partitions its matmuls over the embed axis where zero3 gathers the
    weights — in fp32 only reduction associativity separates the
    programs."""
    from dinov3_tpu.train import put_batch

    common = ["parallel.data=-1", "parallel.fsdp=2",
              "optim.sharded_update=false",
              "compute_precision.compute_dtype=fp32"]
    s_z, batch = _setup(common + ["parallel.zero3=auto"], 16,
                        eight_devices)
    s_r, _ = _setup(common + ["parallel.zero3=false"], 16, eight_devices)
    assert s_z.zero3 and not s_r.zero3
    state_r = jax.device_put(s_z.state, s_r.state_shardings)
    results = {}
    for name, setup, state in (("zero3", s_z, s_z.state),
                               ("oracle", s_r, state_r)):
        d = put_batch(batch, setup.batch_shardings)
        for i in range(2):
            state, m = setup.step_fn(state, d, setup.scalars(i),
                                     jax.random.key(0))
        results[name] = (state, float(m["total_loss"]))
    assert results["zero3"][1] == pytest.approx(results["oracle"][1],
                                                rel=1e-5)
    for (pa, la), (_, lb) in zip(
        _flat_params(results["zero3"][0].params)[:48],
        _flat_params(results["oracle"][0].params)[:48],
    ):
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(lb), rtol=5e-6, atol=1e-6,
            err_msg=f"dp x fsdp params {jtu.keystr(pa)}")


def test_dryrun_unrolled_blocks(eight_devices):
    """scan_layers=false + zero3: the unrolled path still runs (gathers
    in the flat graph, no stream loop) and stays bitwise with its own
    replicated arm."""
    from dinov3_tpu.train import put_batch

    s_z, batch = _setup(["parallel.zero3=true", "train.scan_layers=false"],
                        16, eight_devices)
    s_r, _ = _setup(["parallel.zero3=false", "optim.sharded_update=false",
                     "train.scan_layers=false"], 16, eight_devices)
    d = put_batch(batch, s_z.batch_shardings)
    st_z, m_z = s_z.step_fn(s_z.state, d, s_z.scalars(0), jax.random.key(0))
    st_r, m_r = s_r.step_fn(s_r.state, d, s_r.scalars(0), jax.random.key(0))
    assert float(m_z["total_loss"]) == float(m_r["total_loss"])
    assert_trees_bitwise(st_z.params, st_r.params, "unrolled masters",
                         limit=48)


# ---------------- weight-stream HLO structure ----------------

def test_stream_gathers_in_loop_and_scoped(arms_dp):
    """The compiled zero3 step's census: gathers inside the block scan's
    while body, zero3_stream/zero3_gather scope attribution present,
    zero unattributed collectives; the replicated arm has none of the
    zero3 scopes."""
    from dinov3_tpu.utils import hlo_collective_census, hlo_copy_census

    s_z, s_r, d = arms_dp
    comp = s_z.step_fn.lower(
        s_z.state, d, s_z.scalars(0), jax.random.key(0)).compile()
    text = comp.as_text()
    cen = hlo_collective_census(text)
    assert cen["unattributed"] == 0
    assert cen["by_scope"].get("zero3_stream", {"ops": 0})["ops"] > 0
    assert cen["by_scope"].get("zero3_gather", {"ops": 0})["ops"] > 0
    pf = cen["prefetch_overlap"]
    assert pf["all_gather_in_loop_ops"] > 0
    assert pf["at_use_scoped_ops"] > 0  # engine gathers at use in-loop
    # copy census: the zero3 scopes never surface as unexplained "large"
    copies = hlo_copy_census(text)
    assert copies["hlo_copy_total"] <= 400, copies

    comp_r = s_r.step_fn.lower(
        s_r.state, d, s_r.scalars(0), jax.random.key(0)).compile()
    cen_r = hlo_collective_census(comp_r.as_text())
    assert not any(k.startswith("zero3") for k in cen_r["by_scope"])


# ---------------- explicit double-buffered twin ----------------

def _twin_fixture(dtype):
    import flax.linen as nn

    from dinov3_tpu.models.streaming import (
        cast_stream_leaves,
        make_block_apply,
    )
    from dinov3_tpu.ops.block import SelfAttentionBlock
    from dinov3_tpu.parallel.context import set_current_mesh

    mesh = build_mesh(MeshSpec(data=8), devices=jax.devices())
    set_current_mesh(mesh)
    kwargs = dict(dim=64, num_heads=2, ffn_ratio=2.0, drop_path_rate=0.0,
                  dtype=dtype)
    L, N, D = 4, 17, 64
    block = SelfAttentionBlock(**kwargs)
    one = nn.meta.unbox(
        block.init(jax.random.key(0), jnp.zeros((1, N, D), dtype))
    )["params"]
    stack = jax.tree.map(
        lambda p: jnp.stack([p + 0.01 * i for i in range(L)]), one)
    stack = cast_stream_leaves(stack, dtype)
    x = jax.random.normal(jax.random.key(1), (16, N, D), dtype)
    return mesh, kwargs, stack, x, L, make_block_apply(kwargs)


def _twin_shardings(stack, mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    def sh(p):
        spec = zero3_leaf_spec(
            p.shape, ("layers",) + (None,) * (p.ndim - 1), mesh)
        return NamedSharding(mesh, spec if spec is not None else P())

    return jax.tree.map(sh, stack)


def test_streamed_twin_matches_oracle():
    """fp32 twin: double-buffered schedule bitwise == at-use schedule
    bitwise == the per-block oracle loop."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dinov3_tpu.models.streaming import streamed_block_scan

    mesh, kwargs, stack, x, L, apply_fn = _twin_fixture(jnp.float32)
    stack_sh = _twin_shardings(stack, mesh)
    stack_dev = jax.device_put(stack, stack_sh)
    x_sh = NamedSharding(mesh, P("data"))
    x_dev = jax.device_put(x, x_sh)

    def oracle(s, xx):
        for i in range(L):
            xx = apply_fn(jax.tree.map(lambda p: p[i], s), xx)
        return xx

    xo = jax.jit(oracle)(stack, x)
    with mesh:
        x_pf = jax.jit(
            lambda s, xx: streamed_block_scan(apply_fn, s, xx, L, mesh),
            in_shardings=(stack_sh, x_sh))(stack_dev, x_dev)
        x_au = jax.jit(
            lambda s, xx: streamed_block_scan(apply_fn, s, xx, L, mesh,
                                              prefetch=False),
            in_shardings=(stack_sh, x_sh))(stack_dev, x_dev)
    assert np.array_equal(np.asarray(x_pf), np.asarray(x_au))
    assert np.array_equal(np.asarray(x_pf), np.asarray(xo))


def test_twin_prefetch_overlap_census():
    """The prefetch-overlap HLO check: every in-loop gather of the
    double-buffered twin is zero3_prefetch-scoped (issued one block
    ahead of its consumer; the priming gather sits outside the loop
    under zero3_gather); the at-use variant flips the attribution."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dinov3_tpu.models.streaming import streamed_block_scan
    from dinov3_tpu.utils import hlo_collective_census

    mesh, kwargs, stack, x, L, apply_fn = _twin_fixture(jnp.float32)
    stack_sh = _twin_shardings(stack, mesh)
    x_sh = NamedSharding(mesh, P("data"))
    n_leaves = len(jax.tree.leaves(stack))

    with mesh:
        c_pf = jax.jit(
            lambda s, xx: streamed_block_scan(apply_fn, s, xx, L, mesh),
            in_shardings=(stack_sh, x_sh)).lower(stack, x).compile()
        c_au = jax.jit(
            lambda s, xx: streamed_block_scan(apply_fn, s, xx, L, mesh,
                                              prefetch=False),
            in_shardings=(stack_sh, x_sh)).lower(stack, x).compile()

    cen = hlo_collective_census(c_pf.as_text())
    pf = cen["prefetch_overlap"]
    assert pf["prefetch_scoped_ops"] == n_leaves
    assert pf["at_use_scoped_ops"] == 0
    assert pf["all_gather_in_loop_ops"] == n_leaves
    assert cen["by_scope"]["zero3_gather"]["ops"] == n_leaves  # priming
    assert cen["unattributed"] == 0

    cen_au = hlo_collective_census(c_au.as_text())
    pf_au = cen_au["prefetch_overlap"]
    assert pf_au["prefetch_scoped_ops"] == 0
    assert pf_au["at_use_scoped_ops"] == n_leaves


# ---------------- cross-arm checkpoints ----------------

def test_checkpoint_replicated_zero3_roundtrip(tmp_path, eight_devices):
    """zero3 -> replicated -> zero3: shapes never change (model layout
    both arms), values round-trip bitwise, and the resumed zero3 run is
    deterministic against the uninterrupted one."""
    from dinov3_tpu.checkpoint import Checkpointer
    from dinov3_tpu.train import put_batch

    s_z, batch = _setup(["parallel.zero3=true"], 16, eight_devices)
    s_r, _ = _setup(["parallel.zero3=false", "optim.sharded_update=false"],
                    16, eight_devices)
    d = put_batch(batch, s_z.batch_shardings)
    state1, _ = s_z.step_fn(s_z.state, d, s_z.scalars(0), jax.random.key(0))

    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    ck.save(1, state1)
    ck.wait_until_finished()

    rep_state = ck.restore(s_r.state, 1)
    assert_trees_bitwise(state1.params, rep_state.params,
                         "zero3 -> replicated params")
    # the replicated arm RUNS from it
    s_rep2, m_rep = s_r.step_fn(rep_state, d, s_r.scalars(1),
                                jax.random.key(0))
    assert np.isfinite(float(m_rep["total_loss"]))

    ck.save(2, rep_state)
    ck.wait_until_finished()
    back = ck.restore(s_z.state, 2)
    assert_trees_bitwise(state1.opt_state, back.opt_state,
                         "round-trip opt state")

    st_orig, m_orig = s_z.step_fn(state1, d, s_z.scalars(1),
                                  jax.random.key(0))
    st_back, m_back = s_z.step_fn(back, d, s_z.scalars(1),
                                  jax.random.key(0))
    assert float(m_orig["total_loss"]) == float(m_back["total_loss"])
    assert_trees_bitwise(st_orig.params, st_back.params,
                         "resume determinism", limit=32)


def test_checkpoint_flat_arm_to_zero3(tmp_path, eight_devices):
    """A PR-5 flat-sharded-update checkpoint (flat padded moments)
    restores into a zero3 run: the moments come back model-shaped
    through the _adapt_opt_leaf flat->full path, bitwise equal to the
    unpadded flat values, and the zero3 step runs from them."""
    from dinov3_tpu.checkpoint import Checkpointer
    from dinov3_tpu.train import put_batch
    from dinov3_tpu.train.fused_update import unflatten_update_leaf

    s_flat, batch = _setup(["parallel.zero3=false"], 16, eight_devices)
    assert s_flat.sharded_update  # the PR-5 arm (dp-only default)
    d = put_batch(batch, s_flat.batch_shardings)
    state1, _ = s_flat.step_fn(s_flat.state, d, s_flat.scalars(0),
                               jax.random.key(0))
    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    ck.save(1, state1)
    ck.wait_until_finished()

    s_z, _ = _setup(["parallel.zero3=true"], 16, eight_devices)
    restored = ck.restore(s_z.state, 1)
    for (path, flat), (_, full), (_, like) in zip(
        _flat_params(state1.opt_state.adam.mu),
        _flat_params(restored.opt_state.adam.mu),
        _flat_params(s_z.state.params["student"]),
    ):
        want = np.asarray(unflatten_update_leaf(flat, like))
        assert np.array_equal(want, np.asarray(full)), jtu.keystr(path)
        assert full.shape == like.shape
    st, m = s_z.step_fn(restored, d, s_z.scalars(1), jax.random.key(0))
    assert np.isfinite(float(m["total_loss"]))
    assert int(st.step) == 2


# ---------------- committed artifacts ----------------

def test_cost_artifact_acceptance():
    """COST_Z3_r12.json: >= 70% per-device master reduction at dp=8
    ViT-L, every gather attributed (zero unattributed), the
    prefetch-overlap column populated, masters' replicated fraction
    pinned ~0 on the zero3 arm (the MEM pin), and the 7B unlock section
    present with a compiling dryrun."""
    with open(os.path.join(REPO, "COST_Z3_r12.json")) as f:
        rec = json.load(f)
    assert rec["dp"] == 8 and rec["arch"] == "vit_large"
    assert rec["master_weight_state_reduction_pct"] >= 70.0
    z3 = rec["arms"]["zero3"]
    for k in ("params_student", "params_teacher"):
        assert z3["per_device_state"][k]["replicated_fraction"] < 0.05
    rep = rec["arms"]["replicated"]
    assert rep["per_device_state"]["params_student"][
        "replicated_fraction"] > 0.9
    cen = z3["collective_census"]
    assert cen["unattributed"] == 0
    assert cen["by_scope"].get("zero3_stream", {"ops": 0})["ops"] > 0
    twin = rec["prefetch_twin"]["collective_census"]
    assert twin["prefetch_overlap"]["prefetch_scoped_ops"] >= \
        rec["prefetch_twin"]["stack_param_leaves"]
    v7 = rec["vit7b_unlock"]
    assert v7["compiled"] and v7["dp"] == 8
    assert v7["n_student_params"] > 6e9
    # the unlock arithmetic: sharded state fits where replicated cannot
    assert (v7["state_bytes_per_device_total"]
            < 0.2 * v7["replicated_equivalent_bytes_per_device"])

    with open(os.path.join(REPO, "MEM_r12.json")) as f:
        mem = json.load(f)
    for k in ("params_student", "params_teacher"):
        assert mem["arms"]["zero3"]["replicated_fraction"][k] < 0.05
    z_mem = mem["arms"]["zero3"]["bytes_in_use_per_device"]
    r_mem = mem["arms"]["replicated"]["bytes_in_use_per_device"]
    # the headline: 2 x 1.40 GB replicated masters -> ~2 x 175 MB/device
    assert r_mem["params_student"] > 1.3e9
    assert z_mem["params_student"] < 0.3 * r_mem["params_student"]


# ---------------- the 7B unlock dryrun ----------------

@pytest.mark.slow
def test_vit7b_zero3_compile_dryrun(eight_devices):
    """The flagship unlock, end-to-end: the committed ViT-7B zero3
    recipe builds abstractly (init_state=False — nothing materializes)
    and its train step lowers AND compiles on the 8-simulated-device
    mesh, with the per-device accounting sharded (not replicated)."""
    from dinov3_tpu.configs import load_config
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.telemetry.memory import layout_split
    from dinov3_tpu.train import build_train_setup

    cfg = load_config(os.path.join(REPO, "configs/train/vit7b16_zero3.yaml"))
    B = int(cfg.train.batch_size_per_device) * 8
    batch_np = make_synthetic_batch(cfg, B, seed=0)
    setup = build_train_setup(cfg, batch_np, devices=eight_devices,
                              init_state=False)
    assert setup.zero3
    split = layout_split(setup.state.params, setup.state_shardings.params)
    assert split["replicated_fraction"] < 0.05
    batch = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
             for k, v in batch_np.items()}
    scalars = {"teacher_temp": jax.ShapeDtypeStruct((), jnp.float32),
               "momentum": jax.ShapeDtypeStruct((), jnp.float32)}
    compiled = setup.step_fn.lower(
        setup.state, batch, scalars, jax.random.key(0)).compile()
    assert compiled is not None