"""Golden forward parity: Meta-layout torch weights -> our ViT.

(VERDICT r1 "what's missing" #2: the reference's de-facto correctness
check was converting Meta's released ``dinov3_vits16`` torch weights and
running a forward — /root/reference/hubconf.py:40-80 — but no test ever
asserted output parity. Here the released checkpoint is stood in for by
``tests/torch_dinov3_oracle.py`` — an independent PyTorch implementation
with the release's exact state_dict naming — so the whole chain
[real layout -> interop converter -> our ViT forward] is asserted against
an independent forward at <=1e-3, offline. The same converter path serves
real released weights.)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from dinov3_tpu.interop.torch_convert import load_backbone_from_torch  # noqa: E402
from dinov3_tpu.models import vit_small  # noqa: E402
from torch_dinov3_oracle import TorchDinoViT  # noqa: E402


def _build_pair(depth=12, embed_dim=384, num_heads=6):
    torch.manual_seed(0)
    oracle = TorchDinoViT(embed_dim=embed_dim, depth=depth,
                          num_heads=num_heads, patch_size=16,
                          n_storage_tokens=4, ls_init=1e-5)
    # realistic weight scales (released weights are trained, not init-tiny)
    with torch.no_grad():
        for p in oracle.parameters():
            p.copy_(torch.randn_like(p) * 0.02)
    oracle.eval()

    model = vit_small(
        patch_size=16, n_storage_tokens=4, mask_k_bias=True,
        layerscale_init=1e-5, drop_path_rate=0.0,
        pos_embed_rope_base=100.0,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    if depth != 12:
        from dinov3_tpu.models import DinoVisionTransformer

        model = DinoVisionTransformer(
            patch_size=16, embed_dim=embed_dim, n_blocks=depth,
            num_heads=num_heads, ffn_ratio=4.0, n_storage_tokens=4,
            mask_k_bias=True, layerscale_init=1e-5, drop_path_rate=0.0,
            pos_embed_rope_base=100.0,
            dtype=jnp.float32, param_dtype=jnp.float32,
        )
    variables = load_backbone_from_torch(
        model, oracle.state_dict(), example_shape=(1, 112, 112, 3),
    )
    return oracle, model, variables


def test_state_dict_layout_is_meta_layout():
    """The oracle's key set is the released dinov3_vits16 layout the
    reference's hubconf remapped — pin the names our converter must eat."""
    oracle, _, _ = _build_pair(depth=1)
    keys = set(oracle.state_dict().keys())
    for expected in (
        "cls_token", "storage_tokens", "mask_token",
        "patch_embed.proj.weight", "patch_embed.proj.bias",
        "rope_embed.periods",
        "blocks.0.norm1.weight", "blocks.0.attn.qkv.weight",
        "blocks.0.attn.qkv.bias", "blocks.0.attn.qkv.bias_mask",
        "blocks.0.attn.proj.weight", "blocks.0.ls1.gamma",
        "blocks.0.norm2.weight", "blocks.0.mlp.fc1.weight",
        "blocks.0.mlp.fc2.weight", "blocks.0.ls2.gamma",
        "norm.weight", "norm.bias",
    ):
        assert expected in keys, expected


@pytest.mark.parametrize("res", [(112, 112), (96, 64)])
def test_forward_parity_vits16(res):
    """Full ViT-S/16: converted Meta-layout weights produce the same
    features as the independent torch forward (<=1e-3, fp32)."""
    oracle, model, variables = _build_pair()
    H, W = res
    rng = np.random.default_rng(1)
    x = rng.standard_normal((2, H, W, 3), dtype=np.float32)

    with torch.no_grad():
        want = oracle(torch.from_numpy(x))
    got = model.apply(variables, jnp.asarray(x), deterministic=True)

    for key in ("x_norm_clstoken", "x_storage_tokens", "x_norm_patchtokens"):
        w = want[key].numpy()
        g = np.asarray(got[key], np.float32)
        assert g.shape == w.shape, key
        diff = np.abs(g - w).max()
        scale = np.abs(w).max()
        assert diff <= 1e-3 * max(1.0, scale), (
            f"{key}: max abs diff {diff:.2e} (feature scale {scale:.2e})"
        )
