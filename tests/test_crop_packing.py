"""Crop-packed single-pass student engine (ops/packing.py,
models/vision_transformer.py _packed_forward) vs the two-pass oracle
(``model.crop_packing=false``).

Pinned here:
- layout math (k, P, the ragged last row, pad-waste fractions) and the
  segment-id invariants (self-match, pad isolation, ragged marking);
- segment-masked attention: cross-segment isolation, dense-vs-flash
  parity (values AND grads, interpret mode on CPU) including ragged
  rows where one row holds a single segment + pad;
- packed-vs-oracle meta-arch equivalence: values + student grads on
  BOTH rng paths (rng.plan true/false), and with stochastic-RoPE lanes
  active under the plan (the packed pass consumes bitwise the oracle's
  per-pass factors);
- drop-path on the packed layout: deterministic per (seed, iteration),
  iteration-sensitive, and subset indices at packed-row granularity;
- the compiled-HLO acceptance claim: the packed student forward
  contains exactly ONE block-scan loop (the two-pass oracle compiles
  two), and fwd+bwd exactly two (oracle four);
- 8-device dryruns: data-parallel step (shard-grouped packed rows) and
  the tensor-sharded packed-vs-oracle equivalence;
- the auto-on default, the oracle switch, the pipeline/k<2 fallback
  warnings (seq parallelism no longer falls back: ring attention
  carries the packed segment mask), and the satellite guardrail/census
  attribution.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.ops.packing import (
    assemble_packed_batch,
    interleave_rows,
    make_packed_layout,
    pack_local_rows,
    packed_segment_ids,
    split_packed_output,
)

SMOL = [
    "student.arch=vit_test", "student.patch_size=4",
    "student.drop_path_rate=0.0", "student.layerscale=1.0e-5",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2",
    "dino.head_n_prototypes=64", "dino.head_hidden_dim=24",
    "dino.head_bottleneck_dim=8",
    "ibot.head_n_prototypes=64", "ibot.head_hidden_dim=24",
    "ibot.head_bottleneck_dim=8",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "optim.warmup_epochs=1", "optim.freeze_last_layer_epochs=1",
    "compute_precision.compute_dtype=fp32",
    "optim.scaling_rule=none",
]


def smol_cfg(extra=()):
    cfg = get_default_config()
    apply_dot_overrides(cfg, list(SMOL) + list(extra))
    return cfg


def make_meta(extra=()):
    from dinov3_tpu.train.ssl_meta_arch import SSLMetaArch

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return SSLMetaArch(smol_cfg(extra))


def smol_batch(cfg, B=4, seed=0):
    from dinov3_tpu.data import make_synthetic_batch

    return {k: jnp.asarray(v)
            for k, v in make_synthetic_batch(cfg, B, seed=seed).items()}


# ---------------- layout math ----------------


def test_layout_vitl_b12_rows():
    """The ISSUE-4 acceptance shape: ViT-L/16 at B=12 packs 5x37-token
    locals into 197-token rows — 120 rows -> 44."""
    lay = make_packed_layout(n_global_rows=24, n_local=96,
                             seq_global=197, seq_local=37, n_prefix=1)
    assert lay.k == 5
    assert lay.n_packed_rows == 20          # 19 full + 1 ragged
    assert lay.rows_total == 44             # <= 48 acceptance bound
    assert lay.pad_segments == 4            # ragged row holds 1 local
    assert lay.pad_tokens_per_row == 197 - 5 * 37
    assert 0.0 < lay.pad_waste < 0.15


def test_layout_ragged_and_errors():
    lay = make_packed_layout(n_global_rows=8, n_local=8,
                             seq_global=17, seq_local=5, n_prefix=1)
    assert lay.k == 3 and lay.n_packed_rows == 3 and lay.pad_segments == 1
    with pytest.raises(ValueError, match="longer than global"):
        make_packed_layout(n_global_rows=2, n_local=2, seq_global=5,
                           seq_local=17, n_prefix=1)
    # indivisible row counts degrade the shard grouping to 1
    lay_g = make_packed_layout(n_global_rows=8, n_local=8, seq_global=17,
                               seq_local=5, n_prefix=1, groups=4)
    assert lay_g.groups == 1  # P=3 not divisible by 4
    lay_g2 = make_packed_layout(n_global_rows=8, n_local=12, seq_global=17,
                                seq_local=5, n_prefix=1, groups=2)
    assert lay_g2.groups == 2  # P=4, 8 both divide


def test_segment_ids_invariants():
    lay = make_packed_layout(n_global_rows=4, n_local=8,
                             seq_global=17, seq_local=5, n_prefix=1)
    seg = packed_segment_ids(lay)
    assert seg.shape == (lay.rows_total, 17)
    assert seg.dtype == np.int32
    # global rows: one segment
    assert (seg[:4] == 0).all()
    # full packed rows: segments 0..k-1 over k*N_l tokens, -1 tail
    row = seg[4]
    assert list(row[:15]) == [0] * 5 + [1] * 5 + [2] * 5
    assert list(row[15:]) == [-1, -1]
    # ragged last row: 8 locals = 2 full rows (3+3) + 1 row of 2 segments
    last = seg[-1]
    assert list(last[:10]) == [0] * 5 + [1] * 5
    assert (last[10:] == -1).all()
    # every token has a self-matching segment (no empty softmax rows)
    assert (seg == seg).all()


@pytest.mark.parametrize("n_local,groups", [(8, 1), (12, 2)])
def test_pack_roundtrip_and_grouped_order(n_local, groups):
    """Pack -> assemble -> split roundtrips, with a ragged last row
    (n_local=8: P=3, 1 empty segment) and with the shard-grouped row
    order (n_local=12: P=4, groups=2)."""
    lay = make_packed_layout(n_global_rows=4, n_local=n_local,
                             seq_global=17, seq_local=5, n_prefix=1,
                             groups=groups)
    assert lay.groups == groups
    D = 3
    g_tok = jnp.arange(4 * 17 * D, dtype=jnp.float32).reshape(4, 17, D)
    l_tok = 1000 + jnp.arange(n_local * 5 * D, dtype=jnp.float32).reshape(
        n_local, 5, D)
    packed = pack_local_rows(l_tok, lay)
    assert packed.shape == (lay.n_packed_rows, 17, D)
    batch = assemble_packed_batch(g_tok, packed, lay)
    g_back, p_back = split_packed_output(batch, lay)
    np.testing.assert_array_equal(np.asarray(g_back), np.asarray(g_tok))
    np.testing.assert_array_equal(np.asarray(p_back), np.asarray(packed))
    # local sequence s lives at packed row s//k, span (s%k)*N_l
    for s in range(n_local):
        span = np.asarray(packed)[s // lay.k,
                                  (s % lay.k) * 5:(s % lay.k + 1) * 5]
        np.testing.assert_array_equal(span, np.asarray(l_tok)[s])
    # interleave_rows matches assemble_packed_batch's row order
    plain = np.concatenate([np.asarray(g_tok), np.asarray(packed)])
    np.testing.assert_array_equal(interleave_rows(plain, lay),
                                  np.asarray(batch))


# ---------------- segment-masked attention ----------------


def _qkv(B, N, h, d, seed=0):
    key = jax.random.key(seed)
    return tuple(jax.random.normal(jax.random.fold_in(key, i), (B, N, h, d))
                 for i in range(3))


def test_segment_isolation_matches_per_segment_attention():
    """Dense seg-masked attention == running each segment separately
    (values and grads) — the packing correctness core."""
    from dinov3_tpu.ops.attention import xla_attention

    B, N, h, d = 1, 12, 2, 8
    q, k, v = _qkv(B, N, h, d)
    seg = jnp.asarray([[0] * 4 + [1] * 4 + [-1] * 4], jnp.int32)

    def masked(q, k, v):
        return xla_attention(q, k, v, seg=seg)

    out = masked(q, k, v)
    for lo, hi in ((0, 4), (4, 8)):
        ref = xla_attention(q[:, lo:hi], k[:, lo:hi], v[:, lo:hi])
        np.testing.assert_allclose(np.asarray(out[:, lo:hi]),
                                   np.asarray(ref), atol=1e-6)
    # grads: cross-segment cotangents must not leak
    def loss_seg0(q, k, v):
        return jnp.sum(masked(q, k, v)[:, :4] ** 2)

    gq, gk, gv = jax.grad(loss_seg0, argnums=(0, 1, 2))(q, k, v)
    for g in (gq, gk, gv):
        assert float(jnp.abs(g[:, 4:]).max()) == 0.0

    def loss_ref(q04, k04, v04):
        return jnp.sum(xla_attention(q04, k04, v04) ** 2)

    rq, rk, rv = jax.grad(loss_ref, argnums=(0, 1, 2))(
        q[:, :4], k[:, :4], v[:, :4])
    np.testing.assert_allclose(np.asarray(gq[:, :4]), np.asarray(rq),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(gk[:, :4]), np.asarray(rk),
                               atol=1e-6)


@pytest.mark.parametrize("N", [11, 37])
def test_dense_vs_flash_seg_parity_values_and_grads(N):
    """Pallas seg-masked kernels (interpret mode) == the dense path,
    on a batch with a ragged row (one segment + pad) and a pad-only
    tail — the ISSUE's ragged-last-row case."""
    from dinov3_tpu.ops.attention import xla_attention
    from dinov3_tpu.ops.flash_attention import flash_attention

    B, h, d = 3, 2, 8
    q, k, v = _qkv(B, N, h, d, seed=3)
    k3 = N // 3
    rows = [
        [0] * N,                                    # global-style row
        [0] * k3 + [1] * k3 + [-1] * (N - 2 * k3),  # two segments + pad
        [0] * k3 + [-1] * (N - k3),                 # ragged: one segment
    ]
    seg = jnp.asarray(rows, jnp.int32)
    dense = xla_attention(q, k, v, seg=seg)
    flash = flash_attention(q, k, v, seg=seg)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(flash),
                               atol=2e-6)

    def loss(fn):
        return lambda q, k, v: jnp.sum(jnp.sin(fn(q, k, v)))

    gd = jax.grad(loss(lambda *a: xla_attention(*a, seg=seg)),
                  argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss(lambda *a: flash_attention(*a, seg=seg)),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gd, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-6)


# ---------------- packed vs oracle (meta arch) ----------------


def _forward_with_grads(meta, params, batch, it=0, seed=5):
    rng = jax.random.key(seed)

    def loss(student):
        kw = {}
        if meta.rng_plan:
            kw["rng_plan"] = meta.build_rng_plan(
                jax.random.fold_in(rng, it), batch)
        else:
            r = jax.random.fold_in(rng, it)
            kw["rngs"] = {"drop_path": jax.random.fold_in(r, 0),
                          "rope": jax.random.fold_in(r, 1),
                          "dropout": jax.random.fold_in(r, 2)}
        total, (d, _) = meta.forward(
            student, {"teacher": params["teacher"]}, batch,
            teacher_temp=0.07, state=meta.init_state(),
            iteration=jnp.asarray(it, jnp.int32), **kw)
        return total, d

    (total, d), grads = jax.value_and_grad(loss, has_aux=True)(
        params["student"])
    return float(total), d, grads


@pytest.mark.parametrize("rng_flag", ["true", "false"])
def test_packed_matches_oracle_values_and_grads(rng_flag):
    """The acceptance equivalence: packed vs two-pass oracle, values +
    student grads, BOTH rng paths. With no active rng consumers the two
    programs compute identical per-token math (segments are attention-
    isolated), so losses match to float reassociation and grads
    tightly."""
    meta_p = make_meta([f"rng.plan={rng_flag}"])
    meta_o = make_meta([f"rng.plan={rng_flag}", "model.crop_packing=false"])
    assert meta_p.crop_packing and not meta_o.crop_packing
    batch = smol_batch(meta_p.cfg)
    params = meta_p.init_params(jax.random.key(0), batch)
    t_p, d_p, g_p = _forward_with_grads(meta_p, params, batch)
    t_o, d_o, g_o = _forward_with_grads(meta_o, params, batch)
    assert np.isfinite(t_p)
    np.testing.assert_allclose(t_p, t_o, rtol=1e-6)
    for k in ("dino_global_crops_loss", "dino_local_crops_loss",
              "ibot_loss", "koleo_loss", "total_loss"):
        np.testing.assert_allclose(float(d_p[k]), float(d_o[k]), rtol=1e-5,
                                   err_msg=k)
    err = jax.tree.reduce(max, jax.tree.map(
        lambda a, b: float(jnp.abs(a - b).max()), g_p, g_o))
    scale = jax.tree.reduce(max, jax.tree.map(
        lambda a: float(jnp.abs(a).max()), g_o))
    assert err <= 1e-4 * max(1.0, scale), (err, scale)


def test_packed_matches_oracle_with_rope_plan_lanes():
    """Stochastic RoPE under the plan: the packed pass consumes the
    SAME per-pass aug-factor lanes the oracle's global/local passes
    draw (rng/plan.packed_pass_plan), so equivalence stays tight with
    augmentation active."""
    aug = ["student.pos_embed_rope_jitter_coords=1.1",
           "student.pos_embed_rope_shift_coords=0.2"]
    meta_p = make_meta(aug)
    meta_o = make_meta(aug + ["model.crop_packing=false"])
    batch = smol_batch(meta_p.cfg)
    params = meta_p.init_params(jax.random.key(0), batch)
    t_p, _, g_p = _forward_with_grads(meta_p, params, batch)
    t_o, _, g_o = _forward_with_grads(meta_o, params, batch)
    np.testing.assert_allclose(t_p, t_o, rtol=1e-6)
    err = jax.tree.reduce(max, jax.tree.map(
        lambda a, b: float(jnp.abs(a - b).max()), g_p, g_o))
    assert err <= 1e-4, err


@pytest.mark.parametrize("rng_flag", ["true", "false"])
def test_packed_drop_path_deterministic_and_moving(rng_flag):
    """Drop path on the packed layout (packed-ROW granularity): the
    forward stays deterministic per (seed, iteration), draws move with
    the iteration, and losses stay finite — on both rng paths."""
    meta = make_meta([f"rng.plan={rng_flag}",
                      "student.drop_path_rate=0.3"])
    batch = smol_batch(meta.cfg)
    params = meta.init_params(jax.random.key(0), batch)
    t0, d0, _ = _forward_with_grads(meta, params, batch, it=0)
    t0b, _, _ = _forward_with_grads(meta, params, batch, it=0)
    t1, _, _ = _forward_with_grads(meta, params, batch, it=1)
    assert np.isfinite(t0)
    assert t0 == t0b
    assert t0 != t1
    for k in ("dino_global_crops_loss", "dino_local_crops_loss",
              "ibot_loss", "total_loss"):
        assert np.isfinite(float(d0[k]))


def test_packed_plan_has_row_granularity_drop_lane():
    """The packed plan's drop-path lane covers the mixed 2B + P row
    axis, and the rope lanes are bitwise the oracle step plan's."""
    meta_p = make_meta(["student.drop_path_rate=0.3",
                        "student.pos_embed_rope_jitter_coords=1.2"])
    meta_o = make_meta(["student.drop_path_rate=0.3",
                        "student.pos_embed_rope_jitter_coords=1.2",
                        "model.crop_packing=false"])
    batch = smol_batch(meta_p.cfg)
    rng = jax.random.key(3)
    plan_p = meta_p.build_rng_plan(rng, batch)
    plan_o = meta_o.build_rng_plan(rng, batch)
    assert set(plan_p) == {"global", "local", "packed"}
    layout = meta_p._packed_layout(batch)
    idx = plan_p["packed"]["drop_path"]["idx"]
    L = meta_p.student_backbone.n_blocks
    from dinov3_tpu.ops.drop_path import subset_keep_count

    assert idx.shape == (L, 2, subset_keep_count(layout.rows_total, 0.3))
    assert int(idx.max()) < layout.rows_total
    # rope lanes: bitwise the oracle's per-pass factors
    for name in ("global", "local"):
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            plan_p["packed"]["rope"][name], plan_o[name]["rope"])
    # the oracle lanes' rope draws were not perturbed by adding the
    # packed lane (key positions preserved)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        plan_p["global"]["rope"], plan_o["global"]["rope"])


# ---------------- compiled-HLO: one block scan ----------------


def _count_while(stablehlo_text: str) -> int:
    return stablehlo_text.count("stablehlo.while")


def test_packed_student_compiles_one_block_scan():
    """The acceptance HLO check (the streaming engine's no-target-buffer
    discipline): under scan_layers the packed student forward contains
    exactly ONE block-scan while loop where the two-pass oracle has two,
    and fwd+bwd exactly TWO (the scan's forward + its reverse) where the
    oracle has four. The config has no rng consumers, so every while in
    the program IS a block scan. Counted on the LOWERED program
    (StableHLO): the structural claim, independent of the backend's
    loop unrolling — XLA:CPU fully unrolls vit_test's 2-trip scans in
    its optimized HLO, while at ViT-L depth 24 they survive."""
    cfg = smol_cfg(["train.scan_layers=true"])
    from dinov3_tpu.train.ssl_meta_arch import SSLMetaArch

    meta = SSLMetaArch(cfg)
    batch = smol_batch(cfg)
    params = meta.init_params(jax.random.key(0), batch)
    g, l = batch["global_crops"], batch["local_crops"]
    module = meta.student_backbone
    bb = params["student"]["backbone"]

    def packed_fwd(p):
        out = module.apply({"params": p}, g, None, crop_kind="global",
                           deterministic=False, local_crops=l)
        return (jnp.sum(out["x_norm_clstoken"]) + jnp.sum(out["local_cls"])
                + jnp.sum(out["x_norm_patchtokens"]))

    def oracle_fwd(p):
        o1 = module.apply({"params": p}, g, None, crop_kind="global",
                          deterministic=False)
        o2 = module.apply({"params": p}, l, None, crop_kind="local",
                          deterministic=False)
        return (jnp.sum(o1["x_norm_clstoken"])
                + jnp.sum(o2["x_norm_clstoken"])
                + jnp.sum(o1["x_norm_patchtokens"]))

    def hlo(fn):
        return jax.jit(fn).lower(bb).as_text()

    assert _count_while(hlo(packed_fwd)) == 1
    assert _count_while(hlo(oracle_fwd)) == 2
    assert _count_while(hlo(jax.grad(packed_fwd))) == 2
    assert _count_while(hlo(jax.grad(oracle_fwd))) == 4


# ---------------- sharded dryruns ----------------


def test_sharded_step_packed(eight_devices):
    """8-way data-parallel packed step: the shard-grouped row order +
    constrain_packed_rows keep the pack shard-local; the step runs and
    the loss is finite."""
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup, put_batch

    cfg = smol_cfg(["parallel.data=-1"])
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, 8, seed=0).items()}
    setup = build_train_setup(cfg, batch, devices=eight_devices)
    assert setup.meta.crop_packing
    d = put_batch(batch, setup.batch_shardings)
    state, m = setup.step_fn(setup.state, d, setup.scalars(0),
                             jax.random.key(0))
    assert np.isfinite(float(m["total_loss"]))


def test_tensor_sharded_packed_matches_oracle(eight_devices):
    """The acceptance tensor-sharded dryrun: packed vs oracle step under
    dp x tensor=2, same batch.

    The CE/iBOT losses must match tightly. KoLeo gets its own loose
    bound: it is -mean(log(min pairwise distance)) over near-duplicate
    untrained test-scale CLS rows, so the different GSPMD partitionings
    (a [22, N] program vs [16, N]+[6, N] programs) turn ~1e-6 CLS
    reassociation noise into percent-level koleo shifts — the same
    amplification moves even the oracle across meshes (12.066 dp-only
    vs 12.058 dp x tensor). On the dp-only mesh packed == oracle
    EXACTLY (test_packed_matches_oracle_values_and_grads)."""
    from dinov3_tpu.data import make_synthetic_batch
    from dinov3_tpu.train import build_train_setup, put_batch

    metrics = {}
    for flag in ("auto", "false"):
        cfg = smol_cfg(["parallel.data=-1", "parallel.tensor=2",
                        f"model.crop_packing={flag}"])
        batch = {k: jnp.asarray(v) for k, v in
                 make_synthetic_batch(cfg, 8, seed=0).items()}
        setup = build_train_setup(cfg, batch, devices=eight_devices)
        d = put_batch(batch, setup.batch_shardings)
        _, m = setup.step_fn(setup.state, d, setup.scalars(0),
                             jax.random.key(0))
        assert np.isfinite(float(m["total_loss"]))
        metrics[flag] = {k: float(v) for k, v in m.items()}
    for k in ("dino_global_crops_loss", "dino_local_crops_loss",
              "ibot_loss"):
        np.testing.assert_allclose(metrics["auto"][k], metrics["false"][k],
                                   rtol=2e-5, err_msg=k)
    np.testing.assert_allclose(metrics["auto"]["koleo_loss"],
                               metrics["false"]["koleo_loss"], rtol=0.1)


# ---------------- config surface + fallbacks ----------------


def test_crop_packing_defaults_and_switch():
    assert make_meta().crop_packing is True
    assert make_meta(["model.crop_packing=false"]).crop_packing is False
    with pytest.raises(ValueError, match="crop_packing"):
        make_meta(["model.crop_packing=perhaps"])


def test_crop_packing_fallbacks_warn():
    from dinov3_tpu.train.ssl_meta_arch import SSLMetaArch

    with pytest.warns(UserWarning, match="pipeline"):
        meta = SSLMetaArch(smol_cfg(["parallel.pipe=2"]))
    assert meta.crop_packing is False
    # seq parallelism used to forfeit packing with a loud warning (the
    # pre-ring pin of this test); ring attention now threads the packed
    # segment ids through its rotating K/V chunks, so packing stays ON
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        meta = SSLMetaArch(smol_cfg(["parallel.seq=2"]))
    assert meta.crop_packing is True
    packing_warnings = [w for w in caught
                        if "crop_packing" in str(w.message)]
    assert not packing_warnings, [str(w.message) for w in packing_warnings]
    # local crops as big as globals: k == 1, nothing to pack
    with pytest.warns(UserWarning, match="do not pack"):
        meta = SSLMetaArch(smol_cfg(["crops.local_crops_size=16"]))
    assert meta.crop_packing is False


def test_forward_still_works_after_k1_fallback():
    meta = make_meta(["crops.local_crops_size=16"])
    assert not meta.crop_packing
    batch = smol_batch(meta.cfg)
    params = meta.init_params(jax.random.key(0), batch)
    t, _, _ = _forward_with_grads(meta, params, batch)
    assert np.isfinite(t)


# ---------------- satellites ----------------


def test_row_tiling_guardrail_checks_local_and_packed_axes():
    from dinov3_tpu.configs.config import warn_student_row_tiling

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        # packed program at B=12: 44 rows tile as 8n+4 -> clean
        assert warn_student_row_tiling(get_default_config(), 12) == []
        # two-pass program: the local-row axis is guarded; n_l*B = 8*21
        # = 168 tiles clean, but B such that n_l*B pads badly warns
        cfg_off = get_default_config()
        apply_dot_overrides(cfg_off, ["model.crop_packing=false",
                                      "crops.local_crops_number=9"])
        msgs = warn_student_row_tiling(cfg_off, 1)  # 9 rows -> pads to 16
        assert msgs and "local-crop row axis" in msgs[0]
        # packed program with a pathological packed row count warns
        cfg_on = get_default_config()
        apply_dot_overrides(cfg_on, ["crops.local_crops_number=6"])
        # B=3: 2B + ceil(18/5) = 6 + 4 = 10 -> pads 60%
        msgs = warn_student_row_tiling(cfg_on, 3)
        assert msgs and "packed student row count" in msgs[0]
    assert any("sublane" in str(w.message) for w in caught)


def test_classify_copy_gather_pack_category():
    from dinov3_tpu.utils import classify_copy, hlo_copy_census

    line = ('%copy.1 = f32[11,17,64]{2,1,0} copy(f32[11,17,64]{2,1,0} '
            '%concatenate.5), metadata={op_name="jit(loss)/jit(main)/'
            'crop_pack/concatenate" source_file="a.py"}')
    assert classify_copy(line) == "gather_pack"
    bwd = line.replace("crop_pack/concatenate",
                       "transpose(jvp(crop_unpack))/slice")
    assert classify_copy(bwd) == "gather_pack"
    plain = line.replace("crop_pack/", "")
    assert classify_copy(plain) == "large"
    # and the census aggregates the category
    hlo = "ENTRY %main (p: f32[4]) -> f32[4] {\n  " + line + "\n}"
    rec = hlo_copy_census(hlo)
    assert rec["by_category"]["gather_pack"]["ops"] == 1


def test_count_flops_has_packed_ledger_point():
    """scripts/count_flops.py carries the packed-student program as a
    standing FLOP-ledger point, and pins the legacy cross-check points
    to the two-pass oracle so they keep reproducing FLOPS_r04/r05."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "count_flops", os.path.join(os.path.dirname(__file__), "..",
                                    "scripts", "count_flops.py"))
    cf = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cf)
    assert "vitl_packed_b12" in cf.POINTS
    arch, b, res, mode, extra = cf.POINTS["vitl_packed_b12"]
    assert (arch, b, mode) == ("vit_large", 12, "subset")
    assert not any("crop_packing=false" in e for e in extra)
    for legacy in ("vitl_mask", "vitl_subset", "vitl_subset_b12", "hr512"):
        assert any("model.crop_packing=false" in e
                   for e in cf.POINTS[legacy][4]), legacy
