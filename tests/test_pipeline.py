"""Pipeline parallelism (parallel/pipeline.py) on the 8-device CPU mesh.

The reference has no PP (SURVEY.md §2.5 "PP — absent"); these tests pin the
TPU-native addition: a GPipe schedule over stage-stacked block params must
be numerically identical to running the same blocks sequentially, and the
full SSL train step must run under a (data, pipe, fsdp) mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinov3_tpu.configs import apply_dot_overrides, get_default_config
from dinov3_tpu.data import make_synthetic_batch
from dinov3_tpu.models import build_backbone
from dinov3_tpu.parallel import build_mesh, set_current_mesh
from dinov3_tpu.parallel.mesh import MeshSpec
from dinov3_tpu.train import build_train_setup, put_batch

SMOL = [
    "student.arch=vit_test", "student.patch_size=4",
    "student.drop_path_rate=0.0", "student.layerscale=1.0e-5",
    "crops.global_crops_size=16", "crops.local_crops_size=8",
    "crops.local_crops_number=2",
    "dino.head_n_prototypes=32", "dino.head_hidden_dim=24",
    "dino.head_bottleneck_dim=8",
    "ibot.head_n_prototypes=32", "ibot.head_hidden_dim=24",
    "ibot.head_bottleneck_dim=8",
    "train.OFFICIAL_EPOCH_LENGTH=4", "optim.epochs=4",
    "optim.warmup_epochs=1", "optim.freeze_last_layer_epochs=1",
    "compute_precision.compute_dtype=fp32",
    "optim.scaling_rule=none",
]


def _cfg(extra=()):
    cfg = get_default_config()
    apply_dot_overrides(cfg, list(SMOL) + list(extra))
    return cfg


@pytest.fixture(autouse=True)
def _clear_mesh():
    yield
    set_current_mesh(None)


def test_pipelined_forward_matches_sequential(eight_devices):
    """Same init seed => pipelined forward == plain per-block forward.

    vit_test has 2 blocks; run 2 stages x 2 microbatches on a pipe=2 mesh.
    The stacked [S, L/S, ...] params are reshaped from the sequential
    blocks' params so both models compute with identical weights.
    """
    mesh = build_mesh(MeshSpec(data=2, pipe=2, fsdp=2), devices=eight_devices)
    set_current_mesh(mesh)

    cfg = _cfg()
    seq_model = build_backbone(cfg, teacher=True)
    apply_dot_overrides(cfg, ["parallel.pipe=2"])
    pipe_model = build_backbone(cfg, teacher=True)
    assert pipe_model.pipeline_stages == 2

    import flax.linen as nn

    x = jax.random.normal(jax.random.key(1), (4, 16, 16, 3), jnp.float32)
    seq_params = nn.meta.unbox(seq_model.init(jax.random.key(0), x))["params"]
    pipe_params = nn.meta.unbox(pipe_model.init(jax.random.key(0), x))["params"]

    # graft the sequential blocks' weights into the stage-stacked layout:
    # blocks_{i} -> stage axis s = i // (L/S), within-stage scan axis i % (L/S)
    from flax.core import unfreeze

    pipe_params = unfreeze(pipe_params)
    grafted = jax.tree.map(
        lambda a, b: jnp.stack([a[None], b[None]]),  # [S=2, L/S=1, ...]
        seq_params["blocks_0"], seq_params["blocks_1"],
    )
    target = pipe_params["pipeline"]["tick"]["stages"]["blocks"]["block"]
    same = jax.tree.map(lambda a, b: a.shape == b.shape, grafted, target)
    assert all(jax.tree.leaves(same))
    pipe_params["pipeline"]["tick"]["stages"]["blocks"]["block"] = grafted
    for k, v in seq_params.items():
        if not k.startswith("blocks_"):
            pipe_params[k] = v

    out_seq = seq_model.apply({"params": seq_params}, x)
    with mesh:
        out_pipe = jax.jit(
            lambda p, x: pipe_model.apply({"params": p}, x)
        )(pipe_params, x)
    from conftest import legacy_tol

    # jaxlib < 0.5 XLA:CPU: measured 1.9e-3 rel skew on the pipelined
    # stage scan (documented in tests/conftest.py legacy_tol)
    tol = legacy_tol(2e-5, 6e-3)
    np.testing.assert_allclose(
        np.asarray(out_seq["x_norm_clstoken"], np.float32),
        np.asarray(out_pipe["x_norm_clstoken"], np.float32),
        rtol=tol, atol=tol,
    )
    np.testing.assert_allclose(
        np.asarray(out_seq["x_norm_patchtokens"], np.float32),
        np.asarray(out_pipe["x_norm_patchtokens"], np.float32),
        rtol=tol, atol=tol,
    )


def test_microbatch_counts(eight_devices):
    """M > S and M == B paths produce the same result."""
    mesh = build_mesh(MeshSpec(data=2, pipe=2, fsdp=2), devices=eight_devices)
    set_current_mesh(mesh)
    cfg = _cfg(["parallel.pipe=2"])
    x = jax.random.normal(jax.random.key(1), (4, 16, 16, 3), jnp.float32)

    outs = []
    for m in (2, 4):
        apply_dot_overrides(cfg, [f"parallel.pipe_microbatches={m}"])
        model = build_backbone(cfg, teacher=True)
        import flax.linen as nn

        params = nn.meta.unbox(model.init(jax.random.key(0), x))
        with mesh:
            out = jax.jit(lambda p, x: model.apply(p, x))(params, x)
        outs.append(np.asarray(out["x_norm_clstoken"], np.float32))
    np.testing.assert_allclose(outs[0], outs[1], rtol=2e-5, atol=2e-5)


def test_pipelined_train_step(eight_devices):
    """Full fused SSL step under (data=2, pipe=2, fsdp=2): finite loss over
    two steps (donation path) and stage-stacked params sharded over pipe."""
    cfg = _cfg(["parallel.data=2", "parallel.pipe=2", "parallel.fsdp=2",
                "parallel.zero3=false"])
    B = 8
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, B, seed=0).items()}
    setup = build_train_setup(cfg, batch, devices=eight_devices)
    assert setup.mesh.shape["pipe"] == 2

    # the stage axis of stacked block params must be sharded over pipe
    blk_sh = setup.state_shardings.params["student"]["backbone"]["pipeline"]
    def has_pipe(s):
        return any(
            "pipe" in (ax if isinstance(ax, tuple) else (ax,))
            for ax in s.spec if ax is not None
        )
    assert all(has_pipe(s) for s in jax.tree.leaves(blk_sh)), blk_sh
    blk = setup.state.params["student"]["backbone"]["pipeline"]["tick"]["stages"]
    leaf = jax.tree.leaves(blk)[0]
    assert leaf.shape[0] == 2  # n_stages leading axis

    dbatch = put_batch(batch, setup.batch_shardings)
    state, metrics = setup.step_fn(
        setup.state, dbatch, setup.scalars(0), jax.random.key(0)
    )
    assert np.isfinite(float(metrics["total_loss"]))
    assert int(state.step) == 1
    state, metrics2 = setup.step_fn(
        state, dbatch, setup.scalars(1), jax.random.key(0)
    )
    assert np.isfinite(float(metrics2["total_loss"]))


def test_pipeline_composes_with_ring_attention(eight_devices):
    """pipe=2 x seq=2 x data=2 in one program: GPipe stages whose attention
    runs ring attention over the seq axis (the pipeline's UNCONSTRAINED
    buffer dims must not force the token dim replicated)."""
    cfg = _cfg(["parallel.data=2", "parallel.pipe=2", "parallel.seq=2"])
    B = 8
    batch = {k: jnp.asarray(v) for k, v in
             make_synthetic_batch(cfg, B, seed=0).items()}
    setup = build_train_setup(cfg, batch, devices=eight_devices)
    assert setup.mesh.shape["pipe"] == 2 and setup.mesh.shape["seq"] == 2
    dbatch = put_batch(batch, setup.batch_shardings)
    state, metrics = setup.step_fn(
        setup.state, dbatch, setup.scalars(0), jax.random.key(0)
    )
    assert np.isfinite(float(metrics["total_loss"]))


def test_pipeline_get_intermediate_layers_matches_unrolled(eight_devices):
    """get_intermediate_layers on a pipelined model (stage-owned collect
    buffers) must match the unrolled model given the same weights, for a
    mid-stage layer AND a stage-boundary layer — VERDICT r2 #5 deleted the
    NotImplementedError guard."""
    import flax.linen as nn

    from dinov3_tpu.models.vision_transformer import DinoVisionTransformer
    from dinov3_tpu.parallel.pipeline import unstack_pipeline_params

    mesh = build_mesh(MeshSpec(data=2, pipe=2, fsdp=2), devices=eight_devices)
    set_current_mesh(mesh)

    cfg = _cfg(["student.arch=vit_test4", "parallel.pipe=2"])
    pipe_model = build_backbone(cfg, teacher=True)
    assert pipe_model.pipeline_stages == 2 and pipe_model.n_blocks == 4

    x = jax.random.normal(jax.random.key(2), (4, 16, 16, 3), jnp.float32)
    pipe_params = nn.meta.unbox(pipe_model.init(jax.random.key(0), x))["params"]

    cfg_seq = _cfg(["student.arch=vit_test4"])
    seq_model = build_backbone(cfg_seq, teacher=True)
    seq_params = unstack_pipeline_params(pipe_params, n_stages=2, n_blocks=4)
    assert "blocks_3" in seq_params and "pipeline" not in seq_params

    kw = dict(n=[1, 3], return_class_token=True,
              method=DinoVisionTransformer.get_intermediate_layers)
    with mesh:
        outs_pipe = jax.jit(
            lambda p, x: pipe_model.apply({"params": p}, x, **kw)
        )(pipe_params, x)
    outs_seq = seq_model.apply({"params": seq_params}, x, **kw)
    assert len(outs_pipe) == len(outs_seq) == 2
    from conftest import legacy_tol

    # jaxlib < 0.5 XLA:CPU: measured up to 1.5e-3 rel / 5e-3 abs skew on
    # the 4-block pipelined stack (tests/conftest.py legacy_tol)
    tol = legacy_tol(2e-5, 6e-3)
    for (pp, cp), (ps, cs) in zip(outs_pipe, outs_seq):
        np.testing.assert_allclose(np.asarray(pp), np.asarray(ps),
                                   rtol=tol, atol=tol)
        np.testing.assert_allclose(np.asarray(cp), np.asarray(cs),
                                   rtol=tol, atol=tol)


def test_pipeline_param_relayout_roundtrip(eight_devices):
    """stack_params_for_pipeline is the exact inverse of
    unstack_pipeline_params (warm-start path for pipelined runs)."""
    import flax.linen as nn

    from dinov3_tpu.parallel.pipeline import (
        stack_params_for_pipeline,
        unstack_pipeline_params,
    )

    mesh = build_mesh(MeshSpec(data=-1, pipe=2), devices=eight_devices)
    set_current_mesh(mesh)
    cfg = _cfg(["student.arch=vit_test4", "parallel.pipe=2"])
    model = build_backbone(cfg, teacher=True)
    x = jnp.zeros((2, 16, 16, 3), jnp.float32)
    params = nn.meta.unbox(model.init(jax.random.key(0), x))["params"]

    seq = unstack_pipeline_params(params, n_stages=2, n_blocks=4)
    back = stack_params_for_pipeline(seq, n_stages=2, n_blocks=4)
    orig_stack = params["pipeline"]["tick"]["stages"]["blocks"]["block"]
    back_stack = back["pipeline"]["tick"]["stages"]["blocks"]["block"]
    same = jax.tree.map(
        lambda a, b: bool(jnp.all(a == b)), orig_stack, back_stack
    )
    assert all(jax.tree.leaves(same))
