"""Byte-level BPE tokenizer (reference thirdparty/CLIP equivalent)."""

import numpy as np

from dinov3_tpu.data.tokenizer import BPETokenizer, train_bpe

CORPUS = [
    "a cat sitting on a mat",
    "the cat and the dog",
    "a dog running in the park",
    "two cats playing with a ball",
    "the quick brown fox jumps over the lazy dog",
] * 4


def test_roundtrip_without_merges():
    tok = BPETokenizer([])
    for text in ["hello world", "caption with 123 numbers!", "émojis ok"]:
        assert tok.decode(tok.encode(text)) == text.lower()


def test_train_reduces_sequence_length():
    merges = train_bpe(CORPUS, vocab_size=600)
    assert merges
    base = BPETokenizer([])
    trained = BPETokenizer(merges)
    text = "the cat and the dog"
    assert len(trained.encode(text)) < len(base.encode(text))
    assert trained.decode(trained.encode(text)) == text


def test_batched_fixed_shape_padding():
    tok = BPETokenizer.train(CORPUS, vocab_size=600)
    arr = tok(["a cat", "the quick brown fox jumps over the lazy dog"],
              context_length=16)
    assert arr.shape == (2, 16) and arr.dtype == np.int32
    assert arr[0, 0] == tok.SOT
    assert tok.EOT in arr[0]
    # padding is zeros after <end>
    end0 = list(arr[0]).index(tok.EOT)
    assert not arr[0, end0 + 1:].any()


def test_truncation_keeps_markers():
    tok = BPETokenizer([])
    arr = tok("word " * 100, context_length=8)
    assert arr.shape == (1, 8)
    assert arr[0, 0] == tok.SOT and arr[0, -1] == tok.EOT


def test_save_load(tmp_path):
    tok = BPETokenizer.train(CORPUS, vocab_size=560)
    path = str(tmp_path / "bpe.json")
    tok.save(path)
    tok2 = BPETokenizer.load(path)
    text = "cats and dogs"
    assert tok.encode(text) == tok2.encode(text)
    assert tok2.vocab_size == tok.vocab_size
