"""Fused Pallas LayerNorm vs the plain-XLA reference math.

Runs the kernel in interpret mode on the CPU mesh (the exact code path a
TPU backend compiles), asserting value and gradient parity against
``_xla_layernorm`` — the same fp32-statistics formulation the LayerNorm
module uses off-TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinov3_tpu.ops.fused_norm import (
    _xla_layernorm,
    fused_layernorm,
    use_pallas_layernorm,
)


def _pallas(x, s, b, eps=1e-6):
    return fused_layernorm(x, s, b, eps, interpret=True, force=True)


@pytest.mark.parametrize("shape", [
    (4, 256),          # single block
    (300, 128),        # row tail (300 % 256 != 0) exercises masking
    (2, 7, 384),       # leading dims flattened
    (513, 128),        # multi-block with tail
])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fused_layernorm_forward_matches_xla(shape, dtype):
    k1, k2, k3 = jax.random.split(jax.random.key(0), 3)
    D = shape[-1]
    x = jax.random.normal(k1, shape, dtype) * 3 + 1
    s = jax.random.normal(k2, (D,), jnp.float32) * 0.5 + 1
    b = jax.random.normal(k3, (D,), jnp.float32)
    got = _pallas(x, s, b)
    want = _xla_layernorm(x, s, b, 1e-6)
    assert got.dtype == x.dtype
    tol = 1e-6 if dtype == jnp.float32 else 2e-2
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=tol, atol=tol)


@pytest.mark.parametrize("shape", [(4, 256), (300, 128), (2, 7, 384)])
def test_fused_layernorm_grads_match_xla(shape):
    k1, k2, k3, k4 = jax.random.split(jax.random.key(1), 4)
    D = shape[-1]
    x = jax.random.normal(k1, shape, jnp.float32) * 2
    s = jax.random.normal(k2, (D,), jnp.float32) + 1
    b = jax.random.normal(k3, (D,), jnp.float32)
    ct = jax.random.normal(k4, shape, jnp.float32)

    def loss(fn):
        return lambda x, s, b: jnp.sum(fn(x, s, b) * ct)

    gx, gs, gb = jax.grad(loss(_pallas), argnums=(0, 1, 2))(x, s, b)
    wx, ws, wb = jax.grad(
        loss(lambda x, s, b: _xla_layernorm(x, s, b, 1e-6)),
        argnums=(0, 1, 2),
    )(x, s, b)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(wx),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(ws),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(wb),
                               rtol=2e-5, atol=2e-5)


def test_fused_layernorm_bf16_params_grad_dtypes():
    """param_dtype=bf16 recipes: cotangents must come back in param dtype."""
    x = jax.random.normal(jax.random.key(2), (32, 128), jnp.bfloat16)
    s = jnp.ones((128,), jnp.bfloat16)
    b = jnp.zeros((128,), jnp.bfloat16)
    gx, gs, gb = jax.grad(
        lambda x, s, b: jnp.sum(_pallas(x, s, b).astype(jnp.float32)),
        argnums=(0, 1, 2),
    )(x, s, b)
    assert gx.dtype == jnp.bfloat16
    assert gs.dtype == jnp.bfloat16 and gb.dtype == jnp.bfloat16


def test_layernorm_module_dispatch_off_tpu():
    """On the CPU test mesh the module must take the XLA path (the kernel
    would otherwise run interpreted everywhere = very slow)."""
    assert not use_pallas_layernorm(1024)


@pytest.mark.parametrize("axes,shape", [
    ({"data": -1, "fsdp": 2}, (8, 6, 256)),         # rows over data x fsdp
    ({"data": -1, "fsdp": 2, "seq": 2}, (4, 8, 256)),  # tokens over seq too
    ({"data": -1}, (16, 128)),                       # rank-2 (head MLP rows)
])
def test_fused_layernorm_multidevice_island_parity(eight_devices, axes, shape):
    """VERDICT r2 #2: the Pallas kernel must stay legal under a multi-device
    mesh — a shard_map island over the row-sharded activation, exact parity
    with the XLA lowering, forward and backward, under jit+GSPMD."""
    from dinov3_tpu.parallel import build_mesh
    from dinov3_tpu.parallel.context import get_current_mesh, set_current_mesh
    from dinov3_tpu.parallel.mesh import MeshSpec

    mesh = build_mesh(MeshSpec(**axes), devices=eight_devices)
    D = shape[-1]
    k1, k2, k3, k4 = jax.random.split(jax.random.key(5), 4)
    x = jax.random.normal(k1, shape, jnp.float32) * 2 + 0.5
    s = jax.random.normal(k2, (D,), jnp.float32) + 1
    b = jax.random.normal(k3, (D,), jnp.float32)
    ct = jax.random.normal(k4, shape, jnp.float32)

    prev = get_current_mesh()
    set_current_mesh(mesh)
    try:
        assert mesh.size > 1

        def loss(fn):
            return lambda x, s, b: jnp.sum(fn(x, s, b) * ct)

        fused = jax.jit(jax.value_and_grad(loss(_pallas), argnums=(0, 1, 2)))
        plain = jax.jit(jax.value_and_grad(
            loss(lambda x, s, b: _xla_layernorm(x, s, b, 1e-6)),
            argnums=(0, 1, 2),
        ))
        got_v, got_g = fused(x, s, b)
        want_v, want_g = plain(x, s, b)
        np.testing.assert_allclose(float(got_v), float(want_v),
                                   rtol=2e-5, atol=2e-5)
        for g, w in zip(got_g, want_g):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=2e-5, atol=2e-5)
    finally:
        set_current_mesh(prev)


def test_fused_layernorm_multidevice_indivisible_rows_falls_back(
    eight_devices,
):
    """Row counts that don't divide the data axes must fall back to XLA
    (not crash in shard_map)."""
    from dinov3_tpu.parallel import build_mesh
    from dinov3_tpu.parallel.context import get_current_mesh, set_current_mesh
    from dinov3_tpu.parallel.mesh import MeshSpec

    mesh = build_mesh(MeshSpec(data=-1), devices=eight_devices)
    prev = get_current_mesh()
    set_current_mesh(mesh)
    try:
        x = jax.random.normal(jax.random.key(6), (7, 128), jnp.float32)
        s = jnp.ones((128,), jnp.float32)
        b = jnp.zeros((128,), jnp.float32)
        got = _pallas(x, s, b)
        want = _xla_layernorm(x, s, b, 1e-6)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)
    finally:
        set_current_mesh(prev)


def test_layernorm_module_fused_flag_equivalence():
    from dinov3_tpu.ops.norms import LayerNorm

    x = jax.random.normal(jax.random.key(3), (2, 9, 256), jnp.bfloat16)
    m_fused = LayerNorm(fused=True)
    m_plain = LayerNorm(fused=False)
    p = m_fused.init(jax.random.key(4), x)
    np.testing.assert_allclose(
        np.asarray(m_fused.apply(p, x), np.float32),
        np.asarray(m_plain.apply(p, x), np.float32),
        rtol=1e-6, atol=1e-6,
    )
